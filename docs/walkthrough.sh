#!/usr/bin/env bash
# End-to-end walkthrough of sda-tpu's CLIs: one server, a recipient, three
# clerks, three participants, additive 3-way sharing of 10-dim mod-433
# vectors. Expected final reveal: 0 2 2 4 4 6 6 8 8 10
# (the reference walkthrough's config and output: SURVEY.md §6).
#
# Usage:  bash docs/walkthrough.sh   (from the repo root; needs libsodium)
set -euo pipefail

WORK=$(mktemp -d)
trap 'kill $SERVER_PID 2>/dev/null || true; rm -rf "$WORK"' EXIT
PORT=$(( (RANDOM % 10000) + 20000 ))
URL="http://127.0.0.1:$PORT"

echo "== starting sdad (sqlite store) on $URL"
# sdad's stdout goes to a log: its shutdown "drained" line must not race
# the reveal for the last line of the walkthrough's own output (ci.sh
# asserts on `tail -1`)
python -m sda_tpu.cli.serverd --sqlite "$WORK/server.db" httpd \
  --bind "127.0.0.1:$PORT" > "$WORK/sdad.log" 2>&1 &
SERVER_PID=$!
for _ in $(seq 50); do
  python -m sda_tpu.cli.main -s "$URL" -i "$WORK/probe" ping >/dev/null 2>&1 && break
  sleep 0.2
done

sda() { local who=$1; shift; python -m sda_tpu.cli.main -s "$URL" -i "$WORK/$who" "$@"; }

echo "== recipient + clerks register and publish encryption keys"
sda recipient agent create
sda recipient agent keys create
for c in clerk-1 clerk-2 clerk-3; do
  sda "$c" agent create
  sda "$c" agent keys create
done

echo "== recipient creates and opens the aggregation"
AGG=$(sda recipient aggregations create demo --dimension 10 --modulus 433 \
        --sharing add --shares 3)
sda recipient aggregations begin "$AGG"

echo "== three participants submit masked, shared inputs"
sda participant-1 agent create
sda participant-1 participate "$AGG" 0 0 0 1 1 1 2 2 2 3
sda participant-2 agent create
sda participant-2 participate "$AGG" 0 1 1 1 1 2 2 3 3 3
sda participant-3 agent create
sda participant-3 participate "$AGG" 0 1 1 2 2 3 2 3 3 4

echo "== recipient closes the round; committee members process their jobs"
sda recipient aggregations end "$AGG"
# the recipient owns a key too, so it may itself be elected to the committee
for c in clerk-1 clerk-2 clerk-3 recipient; do
  sda "$c" clerk --once
done

echo "== final reveal"
sda recipient aggregations reveal "$AGG"
