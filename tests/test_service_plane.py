"""The continuous multi-tenant service plane (``sda_tpu/service``).

Contracts under test (docs/service.md):

- **scheduler** — deterministic ``uuid5(schedule, epoch)`` ids; a tick
  installs epoch 0 then mints epoch R+1 WHILE closing epoch R (pipelined
  collection); the advance is a store-arbitrated single-winner CAS on
  all four backends (two racing handles mint exactly one epoch, the
  loser converges on the identical deterministic aggregation id); a
  worker that dies between CAS and mint is repaired by any peer's next
  reconcile; ``max_pipelined`` bounds non-terminal epochs in flight;
- **retention** — terminal rounds past their TTL transition to
  ``expired`` via the lifecycle CAS (exactly one sweeping worker wins)
  and are cascade-purged from every backend; a late clerk-result post
  racing the expiry can never resurrect the round;
- **delete cascade** — ``delete_aggregation`` removes EVERY artifact the
  round produced (aggregation, round doc, participations + owner
  markers, clerking jobs/leases/results, snapshot records/freezes/mask
  chunks) on memory, sqlite, jsonfs and (fake-)mongo — the leak-count
  tests measure actual store rows before/after;
- **tenant fairness** — the per-tenant admission budget sheds a hot
  tenant's 429 against its OWN bucket before the shared caps, and one
  tenant's exhaustion never throttles another;
- **/statusz rounds** — live rounds outrank terminal history in the
  bounded ``recent`` table, and the per-tenant rollup stays O(limit).
"""

import threading
import time

import pytest

from sda_tpu import chaos, obs
from sda_tpu.http.admission import AdmissionControl, TENANT_HEADER
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    ClerkingResult,
    Committee,
    NoMasking,
    NotFound,
    Participation,
    ParticipationId,
    Snapshot,
    SnapshotId,
    SodiumEncryption,
)
from sda_tpu.server import (
    new_jsonfs_server,
    new_memory_server,
    new_mongo_server,
    new_sqlite_server,
)
from sda_tpu.server import lifecycle
from sda_tpu.service import (
    RetentionPolicy,
    RoundScheduler,
    ScheduleSpec,
    epoch_aggregation_id,
    epoch_snapshot_id,
    schedules_report,
    sweep_retention,
)
from sda_tpu.service.retention import (
    jsonfs_file_counts,
    memory_row_counts,
    sqlite_row_counts,
)
from sda_tpu.utils import metrics

from util import mock_encryption, new_agent, new_full_agent

BACKENDS = ["memory", "sqlite", "jsonfs", "fakemongo"]


@pytest.fixture(autouse=True)
def _clean_registries():
    obs.reset_all()
    chaos.reset()
    yield
    chaos.reset()
    obs.reset_all()


def _two_handles(backend, tmp_path):
    """Two independent service handles over ONE shared backend — the
    fleet-arbitration fixture (same shape as test_round_lifecycle)."""
    if backend == "memory":
        from sda_tpu.server import SdaServerService
        from sda_tpu.server.core import SdaServer
        from sda_tpu.server.memory import (
            MemoryAggregationsStore,
            MemoryAgentsStore,
            MemoryAuthTokensStore,
            MemoryClerkingJobsStore,
        )

        stores = dict(
            agents_store=MemoryAgentsStore(),
            auth_tokens_store=MemoryAuthTokensStore(),
            aggregation_store=MemoryAggregationsStore(),
            clerking_job_store=MemoryClerkingJobsStore(),
        )
        return SdaServerService(SdaServer(**stores)), \
            SdaServerService(SdaServer(**stores))
    if backend == "sqlite":
        path = tmp_path / "shared.db"
        return new_sqlite_server(path), new_sqlite_server(path)
    if backend == "jsonfs":
        root = tmp_path / "shared-jfs"
        return new_jsonfs_server(root), new_jsonfs_server(root)
    from fake_mongo import FakeDatabase

    db = FakeDatabase()
    return new_mongo_server(db), new_mongo_server(db)


def _spec(recipient_id, key_id, committee_ids, name="sched-a",
          period_s=0.001, max_pipelined=2):
    template = Aggregation(
        id=AggregationId.random(), title="svc", vector_dimension=4,
        modulus=433, recipient=recipient_id, recipient_key=key_id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(3, 433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    ).to_obj()
    return ScheduleSpec(
        name=name, period_s=period_s, template=template,
        committee=[[str(a), str(k)] for a, k in committee_ids],
        max_pipelined=max_pipelined,
    )


def _service_world(service):
    """Recipient + 3-clerk committee policy on a live service handle."""
    recipient, rkey = new_full_agent(service)
    committee = [new_full_agent(service) for _ in range(3)]
    return recipient, rkey, [(a.id, k.body.id) for (a, k) in committee]


def _participate(service, agg_id, data=b"x"):
    agent = new_agent()
    service.create_agent(agent, agent)
    committee = service.get_committee(agent, agg_id)
    service.create_participation(agent, Participation(
        id=ParticipationId.random(), participant=agent.id,
        aggregation=agg_id, recipient_encryption=None,
        clerk_encryptions=[(a, mock_encryption(data))
                           for (a, _) in committee.clerks_and_keys],
    ))
    return agent


def _post_results(service, agg_id):
    committee = service.server.get_committee(agg_id)
    for clerk_id, _key in committee.clerks_and_keys:
        agent = service.server.get_agent(clerk_id)
        job = service.get_clerking_job(agent, clerk_id)
        if job is None:
            continue
        service.create_clerking_result(agent, ClerkingResult(
            job=job.id, clerk=clerk_id, encryption=mock_encryption(b"r")))


# ---------------------------------------------------------------------------
# ScheduleSpec + deterministic ids

def test_epoch_ids_deterministic_and_distinct():
    a0 = epoch_aggregation_id("s1", 0)
    assert a0 == epoch_aggregation_id("s1", 0)
    assert a0 != epoch_aggregation_id("s1", 1)
    assert a0 != epoch_aggregation_id("s2", 0)
    assert epoch_snapshot_id("s1", 0) == epoch_snapshot_id("s1", 0)
    assert str(epoch_snapshot_id("s1", 0)) != str(a0)


def test_schedule_spec_roundtrip_and_validation():
    service = new_memory_server()
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee)
    again = ScheduleSpec.from_obj(spec.to_obj())
    assert again.to_obj() == spec.to_obj()
    assert again.tenant == str(recipient.id)
    agg = again.aggregation_for_epoch(3)
    assert agg.id == epoch_aggregation_id(spec.name, 3)
    assert agg.title == f"{spec.name} epoch 3"
    with pytest.raises(ValueError):
        _spec(recipient.id, rkey.body.id, committee, name="bad name!")
    with pytest.raises(ValueError):
        _spec(recipient.id, rkey.body.id, committee, period_s=0)
    with pytest.raises(ValueError):
        ScheduleSpec(name="x", period_s=1.0, template=spec.template,
                     committee=[])


# ---------------------------------------------------------------------------
# scheduler: install, mint, close, pipeline gating

def test_first_tick_installs_epoch_zero():
    service = new_memory_server()
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee)
    scheduler = RoundScheduler(service.server, [spec])
    tick = scheduler.tick_once()
    kinds = [a["action"] for a in tick["actions"]]
    assert "installed" in kinds and "aggregation" in kinds
    agg0 = epoch_aggregation_id(spec.name, 0)
    assert service.server.get_aggregation(agg0) is not None
    assert service.server.get_committee(agg0) is not None
    assert service.server.get_round_status(agg0).state == "collecting"
    report = schedules_report(service.server)
    assert report["count"] == 1
    assert report["schedules"][0]["epoch"] == 0
    assert report["schedules"][0]["tenant"] == str(recipient.id)


def test_mint_closes_previous_epoch_and_pipelines():
    service = new_memory_server()
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee)
    scheduler = RoundScheduler(service.server, [spec])
    scheduler.tick_once()
    agg0 = epoch_aggregation_id(spec.name, 0)
    _participate(service, agg0)
    # past the period: the next tick mints epoch 1 AND closes epoch 0
    tick = scheduler.tick_once(now=time.time() + 10)
    kinds = [a["action"] for a in tick["actions"]]
    assert "minted" in kinds and "closed" in kinds
    agg1 = epoch_aggregation_id(spec.name, 1)
    # epoch 1 collects while epoch 0 clerks — pipelined by construction,
    # and the history stamps prove the order
    status0 = service.server.get_round_status(agg0)
    status1 = service.server.get_round_status(agg1)
    assert status0.state == "clerking"
    assert status0.snapshot == epoch_snapshot_id(spec.name, 0)
    assert status1.state == "collecting"
    stamps0 = dict(status0.history)
    stamps1 = dict(status1.history)
    assert stamps1["collecting"] <= stamps0["clerking"]
    # the frozen epoch-0 set has exactly its own participation
    assert service.server.aggregation_store.count_participations_snapshot(
        agg0, status0.snapshot) == 1


def test_max_pipelined_gates_minting():
    service = new_memory_server()
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee, max_pipelined=1)
    scheduler = RoundScheduler(service.server, [spec])
    scheduler.tick_once()
    before = metrics.counter_report().get(
        "service.schedule.pipeline_full", 0)
    tick = scheduler.tick_once(now=time.time() + 10)
    # epoch 0 is still live (collecting): with max_pipelined=1 nothing
    # may be minted — strictly sequential rounds
    assert "minted" not in [a["action"] for a in tick["actions"]]
    assert metrics.counter_report()["service.schedule.pipeline_full"] \
        == before + 1
    assert service.server.aggregation_store.get_schedule_state(
        spec.name)["epoch"] == 0


def test_crash_between_cas_and_mint_is_repaired_by_reconcile():
    service = new_memory_server()
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee)
    scheduler = RoundScheduler(service.server, [spec])
    scheduler.tick_once()
    # simulate the crash window: the CAS advanced but the winner died
    # before minting anything for epoch 1
    store = service.server.aggregation_store
    doc = store.get_schedule_state(spec.name)
    advanced = dict(doc, epoch=1, next_epoch_at=time.time() + 3600)
    assert store.transition_schedule_state(spec.name, 0, advanced)
    agg1 = epoch_aggregation_id(spec.name, 1)
    assert store.get_aggregation(agg1) is None
    # any peer's next tick reconciles: epoch 1 materializes, epoch 0 is
    # closed — without advancing the epoch again
    tick = scheduler.tick_once()
    kinds = [a["action"] for a in tick["actions"]]
    assert "aggregation" in kinds and "closed" in kinds
    assert "minted" not in kinds
    assert store.get_aggregation(agg1) is not None
    assert store.get_schedule_state(spec.name)["epoch"] == 1


@pytest.mark.parametrize("backend", BACKENDS)
def test_raced_mint_single_winner_identical_ids(backend, tmp_path):
    a, b = _two_handles(backend, tmp_path)
    recipient, rkey, committee = _service_world(a)
    # a long period: the install ticks must not themselves come due
    # before the RACED advance below (slow backends take real ms)
    spec = _spec(recipient.id, rkey.body.id, committee, period_s=3600.0)
    schedulers = [RoundScheduler(a.server, [spec]),
                  RoundScheduler(b.server, [spec])]
    # both handles install epoch 0 (single-winner create)
    for scheduler in schedulers:
        scheduler.tick_once()
    assert a.server.aggregation_store.get_schedule_state(
        spec.name)["epoch"] == 0
    # raced advance: exactly ONE handle mints epoch 1
    now = time.time() + 7200
    results = [None, None]

    def tick(ix):
        results[ix] = schedulers[ix].tick_once(now=now)

    threads = [threading.Thread(target=tick, args=(ix,)) for ix in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    minted = [action for r in results for action in r["actions"]
              if action["action"] == "minted"]
    assert len(minted) == 1, minted
    assert minted[0]["epoch"] == 1
    # both handles converge on the SAME deterministic aggregation id
    agg1 = epoch_aggregation_id(spec.name, 1)
    for handle in (a, b):
        assert handle.server.aggregation_store.get_schedule_state(
            spec.name)["epoch"] == 1
        assert handle.server.get_aggregation(agg1) is not None
        assert handle.server.get_aggregation(agg1).id == agg1
        status0 = handle.server.get_round_status(
            epoch_aggregation_id(spec.name, 0))
        assert status0.state == "clerking"


@pytest.mark.parametrize("backend", BACKENDS)
def test_schedule_install_cannot_reset_advanced_schedule(backend, tmp_path):
    a, b = _two_handles(backend, tmp_path)
    store_a = a.server.aggregation_store
    doc = {"schedule": "s", "tenant": "t", "epoch": 0,
           "next_epoch_at": 0.0, "updated_at": 0.0}
    assert store_a.create_schedule_state(doc) is True
    assert store_a.transition_schedule_state(
        "s", 0, dict(doc, epoch=5)) is True
    # a late booting scheduler's install loses: the advance survives
    assert b.server.aggregation_store.create_schedule_state(doc) is False
    assert b.server.aggregation_store.get_schedule_state("s")["epoch"] == 5
    # and a stale CAS (wrong FROM epoch) loses too
    assert b.server.aggregation_store.transition_schedule_state(
        "s", 0, dict(doc, epoch=1)) is False
    assert store_a.get_schedule_state("s")["epoch"] == 5


# ---------------------------------------------------------------------------
# delete_aggregation cascade: leak-count per backend

def _row_counts(backend, service, tmp_path):
    if backend == "memory":
        return memory_row_counts(service.server)
    if backend == "sqlite":
        return sqlite_row_counts(tmp_path / "shared.db")
    if backend == "jsonfs":
        return jsonfs_file_counts(tmp_path / "shared-jfs")
    db = service.server.aggregation_store.db
    return {name: len(collection._docs)
            for name, collection in db._collections.items()}


def _full_round(service, spec_name="cascade"):
    """One complete mock round: aggregation, committee, participations,
    snapshot (jobs + freeze), results, round doc."""
    recipient, rkey, committee = _service_world(service)
    agg = Aggregation(
        id=AggregationId.random(), title=spec_name, vector_dimension=4,
        modulus=433, recipient=recipient.id, recipient_key=rkey.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(3, 433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    service.create_committee(recipient, Committee(
        aggregation=agg.id, clerks_and_keys=committee))
    for i in range(3):
        _participate(service, agg.id, data=bytes([i]))
    snapshot = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snapshot)
    _post_results(service, agg.id)
    return recipient, agg, snapshot


@pytest.mark.parametrize("backend", BACKENDS)
def test_delete_aggregation_cascades_every_artifact(backend, tmp_path):
    a, _b = _two_handles(backend, tmp_path)
    # baseline BEFORE the round exists (agents/keys/tokens persist across
    # rounds by design — they are not round artifacts)
    recipient, agg, snapshot = _full_round(a)
    baseline = _row_counts(backend, a, tmp_path)
    # a second, unrelated round that must SURVIVE the delete untouched
    _other_recipient, other_agg, other_snapshot = _full_round(a, "other")
    a.delete_aggregation(recipient, agg.id)
    after = _row_counts(backend, a, tmp_path)
    store = a.server.aggregation_store
    jobs = a.server.clerking_job_store
    assert store.get_aggregation(agg.id) is None
    assert store.get_committee(agg.id) is None
    assert store.get_round_state(agg.id) is None
    assert store.list_snapshots(agg.id) == []
    assert store.count_participations(agg.id) == 0
    assert store.get_snapshot_mask(snapshot.id) in (None, [])
    assert jobs.list_snapshot_jobs(snapshot.id) == []
    assert jobs.list_results(snapshot.id) == []
    # the unrelated round is intact
    assert store.get_aggregation(other_agg.id) is not None
    assert len(jobs.list_results(other_snapshot.id)) == 3
    # leak count: both stores held exactly one full round's artifacts at
    # baseline and after the delete (round 1 then, round 2 now), so the
    # per-table totals must MATCH — any surplus is a leak. Agent/key/
    # token registrations are not round artifacts and survive deletes.
    agent_tables = {"agents", "auth_tokens", "enc_keys", "profiles",
                    "keys", "auths", "."}
    for table in set(baseline) | set(after):
        if any(key in str(table) for key in agent_tables):
            continue
        assert after.get(table, 0) == baseline.get(table, 0), (
            f"{table}: {baseline.get(table, 0)} -> {after.get(table, 0)} "
            f"(leak after delete_aggregation)")


@pytest.mark.parametrize("backend", BACKENDS)
def test_purge_snapshot_jobs_store_level(backend, tmp_path):
    a, b = _two_handles(backend, tmp_path)
    _recipient, agg, snapshot = _full_round(a)
    jobs = a.server.clerking_job_store
    assert len(jobs.list_snapshot_jobs(snapshot.id)) == 3
    removed = jobs.purge_snapshot_jobs(snapshot.id)
    assert removed >= 3
    assert jobs.list_snapshot_jobs(snapshot.id) == []
    assert jobs.list_results(snapshot.id) == []
    # idempotent, and visible through the peer handle
    assert jobs.purge_snapshot_jobs(snapshot.id) == 0
    assert b.server.clerking_job_store.list_results(snapshot.id) == []


# ---------------------------------------------------------------------------
# retention: TTL expiry + cascade purge, raced sweeps, no resurrection

def _revealed_round(service):
    recipient, agg, snapshot = _full_round(service)
    # the recipient-grade fetch flips the round to revealed
    result = service.get_snapshot_result(recipient, agg.id, snapshot.id)
    assert result is not None
    assert service.server.get_round_status(agg.id).state == "revealed"
    return recipient, agg, snapshot


def test_retention_expires_and_purges_revealed_round():
    service = new_memory_server()
    service.server.retention_policy = RetentionPolicy(revealed_ttl_s=60.0)
    _recipient, agg, snapshot = _revealed_round(service)
    # inside the TTL: nothing happens
    assert sweep_retention(service.server) == []
    assert service.server.get_round_status(agg.id).state == "revealed"
    # past the TTL: expire (CAS) + cascade purge
    actions = sweep_retention(service.server, now=time.time() + 61)
    assert [a["to"] for a in actions] == ["expired", "purged"]
    assert service.server.get_round_status(agg.id) is None
    assert service.server.get_aggregation(agg.id) is None
    assert service.server.clerking_job_store.list_results(snapshot.id) == []
    counters = metrics.counter_report()
    assert counters.get("server.round.retention_expired") == 1
    assert counters.get("server.round.purged") == 1


def test_retention_rides_the_sweeper():
    service = new_memory_server()
    service.server.retention_policy = RetentionPolicy(revealed_ttl_s=0.0)
    _recipient, agg, _snapshot = _revealed_round(service)
    sweeper = lifecycle.RoundSweeper(service.server)
    swept = sweeper.sweep_once()
    assert any(a.get("to") == "purged" for a in swept["actions"])
    assert service.server.get_round_status(agg.id) is None


def test_retention_failed_ttl_covers_failed_and_expired():
    service = new_memory_server()
    service.server.retention_policy = RetentionPolicy(failed_ttl_s=0.0)
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee)
    agg = spec.aggregation_for_epoch(0)
    service.server.create_aggregation(agg)
    assert lifecycle.transition(
        service.server.aggregation_store, agg.id, ("collecting",),
        "failed", reason="test")
    actions = sweep_retention(service.server, now=time.time() + 1)
    assert [a["to"] for a in actions] == ["expired", "purged"]
    assert service.server.aggregation_store.get_round_state(agg.id) is None
    # revealed rounds are NOT covered by failed_ttl_s
    _recipient2, agg2, _snap2 = _revealed_round(service)
    assert sweep_retention(service.server, now=time.time() + 1) == []
    assert service.server.get_round_status(agg2.id).state == "revealed"


def test_retention_never_purges_a_schedules_current_epoch():
    """Purging the CURRENT epoch would make the scheduler's reconcile
    re-mint its deterministic id as an empty zombie round (and a later
    close would fabricate an empty result under the original epoch id):
    retention must defer until the schedule advances past the epoch."""
    service = new_memory_server()
    service.server.retention_policy = RetentionPolicy(revealed_ttl_s=0.0)
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee, period_s=3600.0)
    scheduler = RoundScheduler(service.server, [spec])
    scheduler.tick_once()
    agg0 = epoch_aggregation_id(spec.name, 0)
    # drive epoch 0 terminal (revealed) while it is still the CURRENT
    # epoch — the long period means no advance has happened
    _participate(service, agg0)
    service.create_snapshot(recipient, Snapshot(
        id=epoch_snapshot_id(spec.name, 0), aggregation=agg0))
    _post_results(service, agg0)
    assert service.get_snapshot_result(
        recipient, agg0, epoch_snapshot_id(spec.name, 0)) is not None
    assert service.server.get_round_status(agg0).state == "revealed"
    # retention DEFERS: the round is terminal and past its 0s TTL, but
    # it is the schedule's current epoch
    assert sweep_retention(service.server, now=time.time() + 9999) == []
    assert service.server.get_aggregation(agg0) is not None
    assert metrics.counter_report()["server.round.retention_deferred"] >= 1
    # reconcile does NOT re-mint anything (the aggregation still exists)
    tick = scheduler.tick_once()
    assert "aggregation" not in [a["action"] for a in tick["actions"]]
    # once the schedule advances, epoch 0 becomes purgeable
    tick = scheduler.tick_once(now=time.time() + 7200)
    assert "minted" in [a["action"] for a in tick["actions"]]
    actions = sweep_retention(service.server, now=time.time() + 9999)
    assert [a["to"] for a in actions if a["aggregation"] == str(agg0)] \
        == ["expired", "purged"]
    assert service.server.get_aggregation(agg0) is None
    # and the scheduler never resurrects the purged past epoch
    tick = scheduler.tick_once()
    assert str(agg0) not in [a.get("aggregation") for a in tick["actions"]]
    assert service.server.get_aggregation(agg0) is None


@pytest.mark.parametrize("backend", BACKENDS)
def test_raced_retention_single_expiry_winner(backend, tmp_path):
    a, b = _two_handles(backend, tmp_path)
    for handle in (a, b):
        handle.server.retention_policy = RetentionPolicy(revealed_ttl_s=0.0)
    _recipient, agg, _snapshot = _revealed_round(a)
    now = time.time() + 1
    results = [None, None]

    def sweep(ix, handle):
        results[ix] = sweep_retention(handle.server, now=now)

    threads = [threading.Thread(target=sweep, args=(ix, handle))
               for ix, handle in enumerate((a, b))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    expired = [action for r in results for action in r
               if action["to"] == "expired"]
    assert len(expired) == 1, expired  # the CAS admits one winner
    for handle in (a, b):
        assert handle.server.aggregation_store.get_round_state(
            agg.id) is None
        assert handle.server.get_aggregation(agg.id) is None


def test_late_clerk_result_cannot_resurrect_expired_round():
    """The raced-expiry hazard: retention expires a round between a
    clerk's poll and its result post. The result may land in the job
    store (pre-purge) or 404 (post-purge) — the ROUND's terminal verdict
    must survive either way."""
    service2 = new_memory_server()
    recipient2, rkey2, committee2 = _service_world(service2)
    agg2 = Aggregation(
        id=AggregationId.random(), title="late", vector_dimension=4,
        modulus=433, recipient=recipient2.id, recipient_key=rkey2.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(3, 433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service2.create_aggregation(recipient2, agg2)
    service2.create_committee(recipient2, Committee(
        aggregation=agg2.id, clerks_and_keys=committee2))
    _participate(service2, agg2.id)
    snap2 = Snapshot(id=SnapshotId.random(), aggregation=agg2.id)
    service2.create_snapshot(recipient2, snap2)
    # the round is clerking; the sweep expires it (deadline semantics)
    assert lifecycle.transition(
        service2.server.aggregation_store, agg2.id, ("clerking",),
        "expired", reason="test expiry")
    # phase 1: expired but not yet purged — the late result is accepted
    # by the job store, but the round verdict is NOT resurrected
    clerk_id, _ = committee2[0]
    clerk_agent = service2.server.get_agent(clerk_id)
    job = service2.get_clerking_job(clerk_agent, clerk_id)
    assert job is not None
    service2.create_clerking_result(clerk_agent, ClerkingResult(
        job=job.id, clerk=clerk_id, encryption=mock_encryption(b"late")))
    assert service2.server.get_round_status(agg2.id).state == "expired"
    # phase 2: purged — a later clerk's post gets a clean NotFound
    service2.server.purge_aggregation(agg2.id)
    clerk_id2, _ = committee2[1]
    clerk_agent2 = service2.server.get_agent(clerk_id2)
    assert service2.get_clerking_job(clerk_agent2, clerk_id2) is None
    with pytest.raises(NotFound):
        service2.server.create_clerking_result(ClerkingResult(
            job=job.id, clerk=clerk_id2,
            encryption=mock_encryption(b"later")))
    assert service2.server.get_round_status(agg2.id) is None


# ---------------------------------------------------------------------------
# tenant fairness (http/admission.py)

def test_tenant_budget_sheds_before_shared_caps():
    admission = AdmissionControl(max_inflight=100, tenant_rate=1.0,
                                 tenant_burst=2.0)
    assert admission.enabled
    # burst of 2 admits twice, then sheds 429 against the TENANT budget
    assert admission.admit("agent-1", tenant_key="tenant-a") is None
    assert admission.admit("agent-2", tenant_key="tenant-a") is None
    shed = admission.admit("agent-3", tenant_key="tenant-a")
    assert shed is not None and shed.status == 429
    assert shed.reason == "per-tenant budget"
    assert shed.retry_after > 0
    # ANOTHER tenant is untouched by tenant-a's exhaustion
    assert admission.admit("agent-4", tenant_key="tenant-b") is None
    # and a request with no tenant header skips the tenant guard
    assert admission.admit("agent-5") is None
    report = admission.tenants_report()
    assert report["tenants"]["tenant-a"]["shed"] == 1
    assert report["tenants"]["tenant-a"]["admitted"] == 2
    assert report["tenants"]["tenant-b"]["shed"] == 0
    assert metrics.counter_report()["http.throttled.tenant"] == 1


def test_tenant_shed_does_not_consume_inflight():
    admission = AdmissionControl(max_inflight=1, tenant_rate=0.5,
                                 tenant_burst=1.0)
    assert admission.admit("a", tenant_key="t1") is None  # takes the slot
    # a hot tenant's overflow sheds 429 on ITS budget, not 503 on the
    # shared in-flight cap — the fairness ordering under test
    shed = admission.admit("b", tenant_key="t1")
    assert shed.status == 429 and shed.reason == "per-tenant budget"
    admission.release()


def test_tenant_header_flows_over_http():
    from sda_tpu.http import SdaHttpClient, SdaHttpServer

    service = new_memory_server()
    server = SdaHttpServer(service, bind="127.0.0.1:0",
                           tenant_rate=1.0, tenant_burst=1.0)
    server.start_background()
    try:
        proxy = SdaHttpClient(server.address, token="t",
                              max_retries=0, deadline=5.0)
        proxy.tenant = "11111111-2222-3333-4444-555555555555"
        assert proxy.ping().running  # burst of 1: admitted
        from sda_tpu.protocol import ServerError

        with pytest.raises(ServerError) as err:
            proxy.ping()  # same tenant, bucket empty: shed 429
        assert "429" in str(err.value)
        report = server.admission.tenants_report()
        assert report["tenants"][proxy.tenant]["shed"] >= 1
        # the statusz page surfaces the tenant table
        statusz = server.statusz()
        assert statusz["admission"]["tenants"][proxy.tenant]["shed"] >= 1
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# /statusz rounds table: live-priority, per-tenant rollup, O(limit)

def test_rounds_report_prefers_live_and_rolls_up_tenants():
    service = new_memory_server()
    store = service.server.aggregation_store
    now = time.time()
    # 40 terminal rounds (fresher updated_at!) + 3 live ones
    for i in range(40):
        store.put_round_state({
            "aggregation": str(AggregationId.random()),
            "tenant": f"tenant-{i % 4}", "state": "revealed",
            "updated_at": now + 100 + i,
        })
    live_ids = []
    for i in range(3):
        aggregation = str(AggregationId.random())
        live_ids.append(aggregation)
        store.put_round_state({
            "aggregation": aggregation, "tenant": "tenant-live",
            "state": "clerking", "updated_at": now + i,
        })
    report = lifecycle.rounds_report(service.server, limit=8)
    assert report["count"] == 43
    assert report["live"] == 3
    assert len(report["recent"]) == 8  # O(limit), not O(rounds)
    # every live round leads the table despite older updated_at stamps
    assert [r["aggregation"] for r in report["recent"][:3]] \
        == list(reversed(live_ids))
    assert all(r["state"] == "revealed" for r in report["recent"][3:])
    # per-tenant rollup with state counts
    assert report["by_tenant"]["tenant-live"] == {"clerking": 3}
    assert report["by_tenant"]["tenant-0"] == {"revealed": 10}
    assert report["tenants_omitted"] == 0
    # a tenant flood stays bounded
    tight = lifecycle.rounds_report(service.server, limit=2)
    assert len(tight["by_tenant"]) == 2
    assert tight["tenants_omitted"] == 3
    # round docs written by the service plane carry their tenant
    recipient, rkey, committee = _service_world(service)
    spec = _spec(recipient.id, rkey.body.id, committee, name="tenants")
    RoundScheduler(service.server, [spec]).tick_once()
    report = lifecycle.rounds_report(service.server, limit=50)
    assert str(recipient.id) in report["by_tenant"]


# ---------------------------------------------------------------------------
# the soak drill, smoke-sized (real crypto end to end)

def test_soak_smoke_memory():
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")
    from sda_tpu.service import SoakProfile, run_soak

    report = run_soak(SoakProfile(
        tenants=2, epochs=2, participants=4, dim=4, seed=11, churn=0.5))
    assert report["exact"] is True
    assert report["rounds_exact"] == 4
    # epoch e entered collecting before epoch e-1 revealed, per tenant
    assert report["pipelined"] is True
    assert report["pipelined_pairs"] == "2/2"
    # zero cross-epoch/cross-tenant contamination
    assert report["leaks"] == 0
    assert sum(report["replay_probes"].values()) == 2
    # retention kept the store flat: every revealed round purged
    assert report["retention"]["purged_rounds"] == 4
    assert report["retention"]["store_rows_flat"] is True
    # churned devices all resumed via their journals
    assert report["churn"]["participants_resumed"] \
        == report["churn"]["participants_churned"]
    assert report["value"] > 0  # rounds_per_hour headline
    assert report["unit"] == "rounds/hour"
