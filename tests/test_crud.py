"""Tier-2: CRUD + ACL tests against the service seam (reference: crud.rs).

Parametrized over memory and JSON-file backends — same tests, swapped
fixture, per the reference's feature-gated test design.
"""

import pytest

from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    EncryptionKeyId,
    NoMasking,
    NotFound,
    PermissionDenied,
    Profile,
    InvalidCredentials,
    SodiumEncryption,
)
from sda_tpu.server import (
    auth_token,
    new_jsonfs_server,
    new_memory_server,
    new_sqlite_server,
)

from util import (
    mongo_real_params,
    new_agent,
    new_full_agent,
    new_key_for_agent,
    new_mongo_real_service,
)


@pytest.fixture(
    params=["memory", "jsonfs", "sqlite", "mongo"] + mongo_real_params()
)
def service(request, tmp_path):
    if request.param == "memory":
        return new_memory_server()
    if request.param == "sqlite":
        return new_sqlite_server(tmp_path / "sda.db")
    if request.param == "mongo":
        from fake_mongo import FakeDatabase
        from sda_tpu.server import new_mongo_server

        return new_mongo_server(FakeDatabase())
    if request.param == "mongo-real":
        return new_mongo_real_service(request)
    return new_jsonfs_server(tmp_path)


def default_aggregation(recipient, key) -> Aggregation:
    return Aggregation(
        id=AggregationId.random(),
        title="foo",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.id,
        recipient_key=key.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )


def test_ping(service):
    assert service.ping().running


def test_agent_crud(service):
    alice = new_agent()
    service.create_agent(alice, alice)
    assert service.get_agent(alice, alice.id) == alice
    assert service.get_agent(alice, new_agent().id) is None


def test_agent_create_spoof_denied(service):
    alice, bob = new_agent(), new_agent()
    with pytest.raises(PermissionDenied):
        service.create_agent(alice, bob)


def test_profile_upsert_and_spoof(service):
    alice = new_agent()
    service.create_agent(alice, alice)
    profile = Profile(owner=alice.id, name="Alice")
    service.upsert_profile(alice, profile)
    assert service.get_profile(alice, alice.id).name == "Alice"
    # update
    service.upsert_profile(alice, Profile(owner=alice.id, name="Alice2"))
    assert service.get_profile(alice, alice.id).name == "Alice2"
    # spoof denied (crud.rs:63-81 semantics)
    mallory = new_agent()
    service.create_agent(mallory, mallory)
    with pytest.raises(PermissionDenied):
        service.upsert_profile(mallory, Profile(owner=alice.id, name="Evil"))


def test_encryption_key_crud_and_spoof(service):
    alice = new_agent()
    service.create_agent(alice, alice)
    key = new_key_for_agent(alice)
    service.create_encryption_key(alice, key)
    assert service.get_encryption_key(alice, key.body.id) == key
    assert service.get_encryption_key(alice, EncryptionKeyId.random()) is None
    mallory = new_agent()
    with pytest.raises(PermissionDenied):
        service.create_encryption_key(mallory, new_key_for_agent(alice))


def test_aggregation_lifecycle_and_filters(service):
    alice, alice_key = new_full_agent(service)
    bob, bob_key = new_full_agent(service)

    agg1 = default_aggregation(alice, alice_key).replace(title="apples and pears")
    agg2 = default_aggregation(alice, alice_key).replace(title="apples only")
    agg3 = default_aggregation(bob, bob_key).replace(title="only pears")
    for caller, agg in [(alice, agg1), (alice, agg2), (bob, agg3)]:
        service.create_aggregation(caller, agg)

    ids = lambda l: {str(i) for i in l}
    assert ids(service.list_aggregations(alice, filter="apples")) == ids([agg1.id, agg2.id])
    assert ids(service.list_aggregations(alice, filter="pears")) == ids([agg1.id, agg3.id])
    assert ids(service.list_aggregations(alice, recipient=bob.id)) == ids([agg3.id])
    assert ids(
        service.list_aggregations(alice, filter="pears", recipient=alice.id)
    ) == ids([agg1.id])

    # only the recipient can delete
    with pytest.raises(PermissionDenied):
        service.delete_aggregation(bob, agg1.id)
    service.delete_aggregation(alice, agg1.id)
    assert service.get_aggregation(alice, agg1.id) is None
    with pytest.raises(NotFound):
        service.delete_aggregation(alice, agg1.id)


def test_aggregation_create_spoof_denied(service):
    alice, alice_key = new_full_agent(service)
    mallory = new_agent()
    with pytest.raises(PermissionDenied):
        service.create_aggregation(mallory, default_aggregation(alice, alice_key))


def test_committee_size_validation(service):
    alice, alice_key = new_full_agent(service)
    agg = default_aggregation(alice, alice_key)  # share_count=3
    service.create_aggregation(alice, agg)
    from sda_tpu.protocol import Committee, InvalidRequest

    too_small = Committee(aggregation=agg.id, clerks_and_keys=[(alice.id, alice_key.body.id)])
    with pytest.raises(InvalidRequest):
        service.create_committee(alice, too_small)


def test_auth_token_lifecycle(service):
    server = service.server
    alice = new_agent()
    service.create_agent(alice, alice)
    token = auth_token(alice.id, "sekrit-token")
    server.upsert_auth_token(token)
    assert server.check_auth_token(token) == alice
    with pytest.raises(InvalidCredentials):
        server.check_auth_token(auth_token(alice.id, "wrong"))
    server.delete_auth_token(alice.id)
    with pytest.raises(InvalidCredentials):
        server.check_auth_token(token)


def test_status_requires_recipient(service):
    alice, alice_key = new_full_agent(service)
    bob, _ = new_full_agent(service)
    agg = default_aggregation(alice, alice_key)
    service.create_aggregation(alice, agg)
    with pytest.raises(PermissionDenied):
        service.get_aggregation_status(bob, agg.id)
    status = service.get_aggregation_status(alice, agg.id)
    assert status.number_of_participations == 0 and status.snapshots == []
