"""benchmarks/hw_check.py affine_fit_report — the timing_check v2 math.

The fit runs only inside scarce hardware windows, so its classification
logic is pinned here off-chip: a fit bug must not burn a TPU window (the
round-3 window shipped an unexplained ok:false exactly because the old
two-point probe had no model behind it).
"""

import importlib.util
import os

_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks", "hw_check.py")
_spec = importlib.util.spec_from_file_location("bench_hw_check", _PATH)
hw_check = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(hw_check)


def test_linear_scaling_classified_linear():
    # t = 1ms + 25ns/dim: tiny intercept, flat per-element cost
    pts = [(dd, 1e-3 + 25e-9 * dd)
           for dd in (250_000, 500_000, 750_000, 1_000_000)]
    r = hw_check.affine_fit_report(pts, participants=100)
    assert r["ok"] is True
    assert r["classification"] == "linear"
    assert abs(r["model"]["ns_per_dim"] - 25.0) < 0.1
    assert r["ratio_full_half"] is not None

def test_round3_superlinear_signature_detected():
    # the measured round-3 shape: per-element cost ~1.7x worse at full
    # width than at half (25.83ms@1M vs 7.67ms@0.5M), quadratic-ish tail
    pts = [(250_008, 3.2e-3), (499_992, 7.67e-3),
           (750_000, 15.0e-3), (999_999, 25.83e-3)]
    r = hw_check.affine_fit_report(pts, participants=100)
    assert r["classification"] == "superlinear"
    assert r["el_cost_ratio_last_vs_first"] > 1.25


def test_fixed_overhead_classified_affine_with_overhead():
    # t = 10ms + 10ns/dim: clean fit, large intercept (per-element cost
    # FALLS with dim — the opposite of superlinear)
    pts = [(250_000, 12.5e-3), (500_000, 15e-3),
           (750_000, 17.5e-3), (1_000_000, 20e-3)]
    r = hw_check.affine_fit_report(pts, participants=100)
    assert r["ok"] is True
    assert r["classification"] == "affine-with-overhead"


def test_noisy_measurements_classified_inconsistent():
    # no affine model fits these within 10%: the under-synchronized-chain
    # failure mode must be flagged, not averaged away
    pts = [(250_000, 20e-3), (500_000, 4e-3),
           (750_000, 30e-3), (1_000_000, 6e-3)]
    r = hw_check.affine_fit_report(pts, participants=100)
    assert r["ok"] is False
    assert r["classification"] == "inconsistent"


def test_three_point_fit_has_no_full_half_ratio():
    pts = [(333_336, 8e-3), (666_672, 16e-3), (999_999, 24e-3)]
    r = hw_check.affine_fit_report(pts, participants=100)
    assert r["ratio_full_half"] is None
    assert r["classification"] == "linear"
