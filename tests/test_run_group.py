"""The watch's process-group runner: timeout, stall culling, heartbeats.

A tunnel that dies mid-window leaves the hw_check child blocked forever
inside a device call; benchmarks/hw_check.py's ``_run_group`` must cull
such children on output/heartbeat starvation instead of waiting out the
multi-hour window timeout (round-4 03:45Z window postmortem). The runner
is pure host logic, so the contract is pinned off-chip.
"""

import os
import sys
import time

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

from hw_check import _heartbeat_mtime, _run_group  # noqa: E402


def _py(code: str) -> list:
    return [sys.executable, "-u", "-c", code]


def test_completed_child_passes_through_rc_and_output():
    out, rc, why = _run_group(
        _py("print('hello'); raise SystemExit(7)"), dict(os.environ), 30)
    assert rc == 7
    assert why is None
    assert "hello" in out


def test_stalled_child_is_killed_with_stall_reason():
    t0 = time.time()
    out, rc, why = _run_group(
        _py("print('started', flush=True)\nimport time; time.sleep(600)"),
        dict(os.environ), timeout_s=600, stall_timeout_s=8)
    assert rc is None
    assert why == "stall"
    assert "started" in out  # output up to the kill is preserved
    assert time.time() - t0 < 120  # culled promptly, not at timeout_s


def test_steady_output_is_progress():
    # the child OUTLIVES stall_timeout_s by 3x: only the line-by-line
    # progress tracking can keep it alive, so deleting that logic (e.g.
    # progress = start time) fails this test instead of a hardware window
    code = ("import time\n"
            "for i in range(8):\n"
            "    print(i, flush=True)\n"
            "    time.sleep(3)\n")
    out, rc, why = _run_group(
        _py(code), dict(os.environ), timeout_s=600, stall_timeout_s=8)
    assert rc == 0 and why is None
    assert "7" in out


def test_heartbeat_file_counts_as_progress(tmp_path):
    hb = tmp_path / "beat.txt"
    # silent child beating a file for 24s against an 8s stall timeout:
    # only _heartbeat_mtime progress can carry it to completion
    code = (f"import time, pathlib\n"
            f"p = pathlib.Path({str(hb)!r})\n"
            f"for i in range(8):\n"
            f"    p.write_text(str(i))\n"
            f"    time.sleep(3)\n")
    out, rc, why = _run_group(
        _py(code), dict(os.environ), timeout_s=600, stall_timeout_s=8,
        heartbeats=(str(tmp_path / "*.txt"),))
    assert rc == 0 and why is None


def test_timeout_still_kills():
    out, rc, why = _run_group(
        _py("import time\n"
            "while True:\n"
            "    print('x', flush=True)\n"
            "    time.sleep(1)\n"),
        dict(os.environ), timeout_s=8, stall_timeout_s=600)
    assert rc is None
    assert why == "timeout"


def test_heartbeat_mtime_globs(tmp_path):
    assert _heartbeat_mtime((str(tmp_path / "*.npz"),)) == 0.0
    f = tmp_path / "a.npz"
    f.write_bytes(b"x")
    got = _heartbeat_mtime((str(tmp_path / "*.npz"),))
    assert got == pytest.approx(os.path.getmtime(f))
