"""Phase timing and logging setup (reference gap: SURVEY §5.1/§5.5).

The reference ships no tracing/metrics at all; sda-tpu times every protocol
phase. These tests assert the registry fills during a real round and that
the stats are sane.
"""

import logging

import numpy as np
import pytest

from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.client import SdaClient
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    FullMasking,
    SodiumEncryption,
)
from sda_tpu.server import new_memory_server
from sda_tpu.utils import (
    configure_logging,
    count,
    counter_report,
    phase_report,
    reset_counters,
    reset_phase_report,
    timed_phase,
)


def test_counter_registry_basics():
    reset_counters()
    count("unit.a")
    count("unit.a", 2)
    count("unit.b")
    count("other.c")
    assert counter_report()["unit.a"] == 3
    assert counter_report("unit.") == {"unit.a": 3, "unit.b": 1}
    reset_counters()
    assert counter_report() == {}


def test_timed_phase_accumulates():
    reset_phase_report()
    for _ in range(3):
        with timed_phase("unit.test_phase"):
            pass
    report = phase_report()
    stat = report["unit.test_phase"]
    assert stat["count"] == 3
    assert stat["total_s"] >= 0.0
    assert stat["min_s"] <= stat["mean_s"] <= stat["max_s"]


def test_timed_phase_records_on_exception():
    reset_phase_report()
    with pytest.raises(RuntimeError):
        with timed_phase("unit.failing_phase"):
            raise RuntimeError("boom")
    assert phase_report()["unit.failing_phase"]["count"] == 1


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_full_round_populates_all_protocol_phases():
    reset_phase_report()
    reset_counters()
    service = new_memory_server()

    def new_client():
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        client = SdaClient(agent, keystore, service)
        client.upload_agent()
        return client

    recipient = new_client()
    recipient_key = recipient.new_encryption_key()
    recipient.upload_encryption_key(recipient_key)
    clerks = []
    for _ in range(3):
        clerk = new_client()
        clerk.upload_encryption_key(clerk.new_encryption_key())
        clerks.append(clerk)

    aggregation = Aggregation(
        id=AggregationId.random(), title="timing", vector_dimension=4, modulus=433,
        recipient=recipient.agent.id, recipient_key=recipient_key,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(aggregation)
    recipient.begin_aggregation(aggregation.id)
    for offset in range(2):
        new_client().participate([1 + offset, 2, 3, 4], aggregation.id)
    recipient.end_aggregation(aggregation.id)
    for clerk in clerks + [recipient]:
        clerk.run_chores(-1)
    output = recipient.reveal_aggregation(aggregation.id)
    np.testing.assert_array_equal(output.positive().values, [3, 4, 6, 8])

    report = phase_report()
    for phase in (
        "participant.mask", "participant.share", "participant.encrypt",
        "server.snapshot_freeze", "server.transpose", "server.enqueue_jobs",
        "clerk.decrypt", "clerk.combine", "clerk.encrypt",
        "recipient.combine_masks", "recipient.decrypt_results",
        "recipient.reconstruct", "recipient.unmask",
    ):
        assert phase in report, f"missing phase {phase}"
        assert report[phase]["count"] >= 1
    assert report["participant.share"]["count"] == 2  # one per participant
    assert report["clerk.combine"]["count"] == 3      # one per committee clerk

    counters = counter_report("server.")
    assert counters["server.participation.created"] == 2
    assert counters["server.snapshot.created"] == 1
    assert counters["server.clerking_result.created"] == 3
    assert counters["server.job.polled"] == 3
    assert counters["server.job.poll_empty"] >= 1  # run_chores drains to empty


def test_http_request_status_logging_and_counters():
    """Per-request status lines + status counters (reference analog:
    server-http/src/lib.rs:105-122 logs method/path/status per request)."""
    import io
    import urllib.request

    from sda_tpu.http.server import SdaHttpServer
    from sda_tpu.server import new_memory_server

    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    http_log = logging.getLogger("sda_tpu.http.server")
    http_log.addHandler(handler)
    old_level = http_log.level
    http_log.setLevel(logging.INFO)
    reset_counters()
    srv = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0").start_background()
    try:
        urllib.request.urlopen(srv.address + "/v1/ping").read()
        try:
            urllib.request.urlopen(srv.address + "/v1/nonexistent")
        except urllib.error.HTTPError:
            pass
        counts = srv.status_counts
        assert counts.get(200) == 1
        assert counts.get(401) == 1  # unknown route without auth -> 401
        globals_ = counter_report("http.")
        assert globals_["http.request"] == 2
        assert globals_["http.status.200"] == 1
        assert globals_["http.status.401"] == 1
        lines = buf.getvalue().strip().splitlines()
        assert any("GET /v1/ping -> 200" in l for l in lines)
        assert any("-> 401" in l for l in lines)
    finally:
        srv.shutdown()
        http_log.removeHandler(handler)
        http_log.setLevel(old_level)


def test_configure_logging_levels():
    configure_logging(0)
    assert logging.getLogger().level == logging.WARNING
    logging.getLogger().setLevel(logging.DEBUG)
    configure_logging(2)  # basicConfig won't reconfigure, but must not raise
    logging.getLogger().setLevel(logging.WARNING)
