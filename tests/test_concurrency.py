"""Thread-safety stress tests for the server stores.

The reference leans on Rust's ownership model (Arc + Send/Sync bounds,
SURVEY.md §5.2) and has no race tests at all. Here the broker is Python:
these tests hammer the mutable store paths from many threads — concurrent
participation uploads racing a snapshot, concurrent clerk result uploads,
concurrent agent registration — and assert the invariants the protocol
depends on: a snapshot freezes a consistent participation set, every job
is answered exactly once, nothing is lost or double-counted.
"""

import threading

import pytest

from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    NoMasking,
    Participation,
    ParticipationId,
    Snapshot,
    SnapshotId,
    SodiumEncryption,
)
from sda_tpu.server import new_memory_server, new_sqlite_server

from util import mock_encryption, new_agent, new_full_agent


@pytest.fixture(params=["memory", "sqlite"])
def service(request, tmp_path):
    if request.param == "memory":
        return new_memory_server()
    return new_sqlite_server(tmp_path / "sda.db")


def _world(service, clerks=3):
    recipient, recipient_key = new_full_agent(service)
    committee = [new_full_agent(service) for _ in range(clerks)]
    agg = Aggregation(
        id=AggregationId.random(), title="stress", vector_dimension=4, modulus=433,
        recipient=recipient.id, recipient_key=recipient_key.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=clerks, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    from sda_tpu.protocol import Committee

    service.create_committee(recipient, Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for (a, k) in committee],
    ))
    return recipient, committee, agg


def _participate(service, agg, clerks):
    agent = new_agent()
    service.create_agent(agent, agent)
    participation = Participation(
        id=ParticipationId.random(), participant=agent.id, aggregation=agg.id,
        recipient_encryption=None,
        clerk_encryptions=[(a.id, mock_encryption(b"x")) for (a, _) in clerks],
    )
    service.create_participation(agent, participation)


def test_concurrent_participations_race_snapshot(service):
    """60 participations from 6 threads racing one snapshot: the frozen set
    must be a consistent subset and the total count must end exact."""
    recipient, committee, agg = _world(service)
    clerks = [c for (c, _) in committee]
    errors = []

    def worker():
        try:
            for _ in range(10):
                _participate(service, agg, committee)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(6)]
    for t in threads:
        t.start()
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)
    for t in threads:
        t.join()
    assert not errors

    status = service.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 60

    # the frozen set: every clerk job carries exactly the same count, and
    # that count can't exceed the final total
    jobs = [service.get_clerking_job(clerk, clerk.id) for clerk in clerks]
    jobs = [j for j in jobs if j is not None]
    assert jobs, "snapshot must have enqueued clerk jobs"
    sizes = {len(j.encryptions) for j in jobs}
    assert len(sizes) == 1, f"clerks saw inconsistent frozen sets: {sizes}"
    assert 0 <= sizes.pop() <= 60


def test_concurrent_clerk_results_exactly_once(service):
    """All clerks upload concurrently (with duplicates): every job ends
    done exactly once and the snapshot's results are complete."""
    from sda_tpu.protocol import ClerkingResult

    recipient, committee, agg = _world(service, clerks=8)
    for _ in range(5):
        _participate(service, agg, committee)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)

    errors = []

    def clerk_worker(agent):
        try:
            job = service.get_clerking_job(agent, agent.id)
            result = ClerkingResult(
                job=job.id, clerk=agent.id, encryption=mock_encryption(b"sum")
            )
            service.create_clerking_result(agent, result)
            service.create_clerking_result(agent, result)  # duplicate upload
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=clerk_worker, args=(a,))
               for (a, _) in committee]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors

    status = service.get_aggregation_status(recipient, agg.id)
    assert status.snapshots[0].number_of_clerking_results == 8
    assert status.snapshots[0].result_ready
    for (agent, _) in committee:
        assert service.get_clerking_job(agent, agent.id) is None  # queue drained


def test_concurrent_agent_registration(service):
    agents = [new_agent() for _ in range(40)]
    errors = []

    def register(a):
        try:
            service.create_agent(a, a)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=register, args=(a,)) for a in agents]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for a in agents:
        assert service.get_agent(a, a.id) == a


def test_http_transport_concurrent_job_polling(tmp_path):
    """The REST seam under contention: get_clerking_job is a POLL (the job
    stays queued until its result lands — reference semantics,
    clerking_jobs.rs), so competing pollers per clerk must all see the
    same job, racing result uploads must settle exactly-once, and the
    queue must then read empty for everyone (ThreadingHTTPServer +
    per-thread client sessions; reference analog is rouille's thread
    pool, server-http/src/lib.rs)."""
    from sda_tpu.http import SdaHttpClient, SdaHttpServer
    from sda_tpu.protocol import ClerkingResult
    from sda_tpu.store import Filebased

    http_server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0").start_background()
    try:
        service = SdaHttpClient(http_server.address, store=Filebased(tmp_path / "tokens"))
        recipient, committee, agg = _world(service, clerks=4)
        for _ in range(12):
            _participate(service, agg, committee)
        snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
        service.create_snapshot(recipient, snap)

        polled, errors = [], []
        lock = threading.Lock()

        def clerk_worker(clerk):
            try:
                job = service.get_clerking_job(clerk, clerk.id)
                if job is not None:
                    with lock:
                        polled.append((clerk.id, job.id))
                    service.create_clerking_result(clerk, ClerkingResult(
                        job=job.id, clerk=clerk.id,
                        encryption=mock_encryption(b"sum"),
                    ))
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=clerk_worker, args=(clerk,))
            for (clerk, _) in committee
            for _ in range(3)          # 3 competing workers per clerk
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "worker hung"
        assert not errors
        # every competing poller of one clerk saw that clerk's single job
        jobs_by_clerk = {}
        for clerk_id, job_id in polled:
            jobs_by_clerk.setdefault(clerk_id, set()).add(job_id)
        assert all(len(v) == 1 for v in jobs_by_clerk.values()), jobs_by_clerk
        # duplicate racing results settled exactly-once: 4 results, ready
        status = service.get_aggregation_status(recipient, agg.id)
        assert status.snapshots[0].number_of_clerking_results == len(committee)
        assert status.snapshots[0].result_ready
        # and the queue reads empty over HTTP for every clerk
        for (clerk, _) in committee:
            assert service.get_clerking_job(clerk, clerk.id) is None
    finally:
        http_server.shutdown()
