"""Fused Pallas round kernel — interpret-mode exactness on CPU.

`external` randomness mode feeds deterministic bits so the kernel's uint32
Solinas arithmetic is checkable without TPU hardware: the full round must
equal the plain participant sum (masks and share randomness cancel), and
the kernel's combined shares must equal the XLA fast-path shares computed
from the same bits.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sda_tpu.fields import fastfield, numtheory
from sda_tpu.fields.pallas_round import (
    _uniform_from_bits,
    fused_mask_share_combine,
    single_chip_round_pallas,
)
from sda_tpu.fields.sharing import batch_columns
from sda_tpu.protocol import FullMasking, NoMasking, PackedShamirSharing


def fast_scheme():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    return PackedShamirSharing(3, 8, t, p, w2, w3)


from util import external_bits


@pytest.mark.parametrize("masking", ["none", "full"])
def test_pallas_round_equals_plain_sum(masking):
    s = fast_scheme()
    mask = FullMasking(s.prime_modulus) if masking == "full" else NoMasking()
    fn = single_chip_round_pallas(
        s, mask, tile=128, interpret=True, external_bits_fn=external_bits
    )
    rng = np.random.default_rng(21)
    inputs = rng.integers(0, 1 << 20, size=(5, 500))  # B=167 -> padded to 256
    out = np.asarray(fn(jnp.asarray(inputs), jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


def test_pallas_kernel_matches_xla_shares_same_bits():
    """Kernel combined-shares == XLA packed_share32 fed identical residues."""
    s = fast_scheme()
    sp = fastfield.SolinasPrime.try_from(s.prime_modulus)
    k, t, n = s.secret_count, s.privacy_threshold, s.share_count
    m_host = numtheory.packed_share_matrix(
        k, n, t, s.prime_modulus, s.omega_secrets, s.omega_shares
    )
    P, d, tile = 4, 384, 128
    B = d // k
    rng = np.random.default_rng(22)
    x = jnp.asarray(rng.integers(0, s.prime_modulus, size=(P, d)).astype(np.uint32))
    x_cols = batch_columns(x, k)
    bits = external_bits(jax.random.PRNGKey(30), P, k + t, B)

    shares, mask_tot = fused_mask_share_combine(
        x_cols, 0, sp, m_host, t, True,
        tile=tile, external_bits=bits, interpret=True,
    )

    # reference: same draws through the fastfield helpers
    mask = _uniform_from_bits(bits[:, 0:k, :], bits[:, k:2 * k, :], sp)
    rand = _uniform_from_bits(bits[:, 2 * k:2 * k + t, :],
                              bits[:, 2 * k + t:2 * (k + t), :], sp)
    masked_cols = fastfield.modadd32(x_cols, mask, sp)
    zeros = jnp.zeros((P, 1, B), jnp.uint32)
    values = jnp.concatenate([zeros, masked_cols, rand], axis=1)
    per_part = fastfield.modmatmul32(m_host, values, sp)        # [P, n, B]
    expected_shares = fastfield.modsum32(per_part, sp, axis=0)
    expected_mask_tot = fastfield.modsum32(mask, sp, axis=0)

    np.testing.assert_array_equal(np.asarray(shares), np.asarray(expected_shares))
    np.testing.assert_array_equal(np.asarray(mask_tot), np.asarray(expected_mask_tot))


def test_pallas_round_streams_participant_tiles():
    """P larger than one VMEM participant tile: the kernel's second grid
    axis must zero-init on the first visit and accumulate across revisits
    of the same output block (the lenet-60k VMEM-OOM regression: all P in
    one block). p_tile=32 with P=70 forces ceil(80/32)=3 grid-axis-1
    steps — the auto tile would fit all of P in one block at these
    shapes and never exercise the revisit path."""
    s = fast_scheme()
    fn = single_chip_round_pallas(
        s, FullMasking(s.prime_modulus),
        tile=128, interpret=True, external_bits_fn=external_bits,
        p_tile=32,
    )
    rng = np.random.default_rng(23)
    inputs = rng.integers(0, 1 << 20, size=(70, 500))
    out = np.asarray(fn(jnp.asarray(inputs), jax.random.PRNGKey(9)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


def test_pallas_combined_shares_equal_per_participant_sum():
    """Linearity fusion (Σp M@v_p == M@Σp v_p): kernel combined shares must
    equal folding per-participant packed_share32 rows from the same bits."""
    s = fast_scheme()
    sp = fastfield.SolinasPrime.try_from(s.prime_modulus)
    k, t = s.secret_count, s.privacy_threshold
    m_host = numtheory.packed_share_matrix(
        k, s.share_count, t, s.prime_modulus, s.omega_secrets, s.omega_shares
    )
    P, d = 6, 384
    B = d // k
    rng = np.random.default_rng(31)
    x = jnp.asarray(rng.integers(0, s.prime_modulus, size=(P, d)).astype(np.uint32))
    bits = external_bits(jax.random.PRNGKey(44), P, t, B)  # unmasked: t rows

    shares, _ = fused_mask_share_combine(
        batch_columns(x, k), 0, sp, m_host, t, False,
        tile=128, external_bits=bits, interpret=True, p_block=2,
    )
    # per-participant path from the identical bits
    rand = _uniform_from_bits(bits[:, 0:t, :], bits[:, t:2 * t, :], sp)
    per_part = fastfield.modmatmul32(
        m_host,
        jnp.concatenate(
            [jnp.zeros((P, 1, B), jnp.uint32), batch_columns(x, k), rand],
            axis=1,
        ),
        sp,
    )
    np.testing.assert_array_equal(
        np.asarray(shares), np.asarray(fastfield.modsum32(per_part, sp, axis=0))
    )


def test_pallas_round_rejects_generic_prime():
    s = PackedShamirSharing(3, 8, 4, 433, 354, 150)
    with pytest.raises(ValueError, match="Solinas"):
        single_chip_round_pallas(s)


@pytest.mark.parametrize("p_block", [50, 100])
def test_pallas_round_divisor_p_blocks(p_block):
    """p_block values dividing P exactly (the sweep's zero-padding points:
    at P=100, p_block 16/32/64 pad the participant axis to 112/128 rows
    while 50/100 pad none) stay exact."""
    s = fast_scheme()
    fn = single_chip_round_pallas(
        s, FullMasking(s.prime_modulus), p_block=p_block, tile=128,
        interpret=True, external_bits_fn=external_bits,
    )
    rng = np.random.default_rng(3)
    inputs = rng.integers(0, 1 << 20, size=(100, 3 * 128))
    out = np.asarray(fn(jnp.asarray(inputs), jax.random.PRNGKey(5)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


# -- tree fold: dense-sublane halving fold, bit-identical ------------------

@pytest.mark.parametrize("masking", ["none", "full"])
@pytest.mark.parametrize("p_block", [2, 4, 8])
def test_tree_fold_bit_identical_to_slice_fold(masking, p_block):
    """tree_fold=True must reproduce the slice fold bit-for-bit from the
    same external bits (mod-p sums are order-free; the canon cadence
    keeps raw partials inside uint32)."""
    s = fast_scheme()
    mask = FullMasking(s.prime_modulus) if masking == "full" else NoMasking()
    rng = np.random.default_rng(31)
    inputs = jnp.asarray(rng.integers(0, 1 << 20, size=(8, 504)))
    key = jax.random.PRNGKey(14)
    outs = {}
    for tree in (False, True):
        fn = single_chip_round_pallas(
            s, mask, tile=128, interpret=True,
            external_bits_fn=external_bits, p_block=p_block,
            tree_fold=tree,
        )
        outs[tree] = np.asarray(fn(inputs, key))
    np.testing.assert_array_equal(outs[True], outs[False])
    np.testing.assert_array_equal(
        outs[True], np.asarray(inputs).sum(axis=0) % s.prime_modulus)


def test_tree_fold_shares_match_slice_shares_same_bits():
    """At the kernel seam: combined shares and mask totals identical."""
    s = fast_scheme()
    sp = fastfield.SolinasPrime.try_from(s.prime_modulus)
    k, t = s.secret_count, s.privacy_threshold
    m_host = numtheory.packed_share_matrix(
        k, s.share_count, t, s.prime_modulus, s.omega_secrets,
        s.omega_shares)
    P, d, tile = 8, 384, 128
    B = d // k
    rng = np.random.default_rng(33)
    x = jnp.asarray(
        rng.integers(0, s.prime_modulus, size=(P, d)).astype(np.uint32))
    x_cols = batch_columns(x, k)
    bits = external_bits(jax.random.PRNGKey(34), P, k + t, B)
    got = {}
    for tree in (False, True):
        got[tree] = fused_mask_share_combine(
            x_cols, 0, sp, m_host, t, True, tile=tile, external_bits=bits,
            interpret=True, p_block=4, tree_fold=tree)
    np.testing.assert_array_equal(
        np.asarray(got[True][0]), np.asarray(got[False][0]))
    np.testing.assert_array_equal(
        np.asarray(got[True][1]), np.asarray(got[False][1]))


def test_tree_fold_non_pow2_p_block_falls_back():
    """A non-power-of-two effective p_block silently runs the slice fold
    (the knob is a no-op, never an error)."""
    s = fast_scheme()
    rng = np.random.default_rng(35)
    inputs = jnp.asarray(rng.integers(0, 1 << 20, size=(6, 336)))
    fn = single_chip_round_pallas(
        s, FullMasking(s.prime_modulus), tile=112, interpret=True,
        external_bits_fn=external_bits, p_block=3, tree_fold=True)
    out = np.asarray(fn(inputs, jax.random.PRNGKey(15)))
    np.testing.assert_array_equal(
        out, np.asarray(inputs).sum(axis=0) % s.prime_modulus)
