"""benchmarks/suite.py record merging — the BENCH_SUITE.json provenance
contract (round-3 verdict, weak #5).

The merge must (a) never let an error stub or a CPU rerun clobber committed
hardware evidence, and (b) stamp every record from an earlier window with an
explicit stale flag so a reader can tell fresh records from survivors
without diffing git history.
"""

import importlib.util
import json
import os
import sys

_SUITE_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks", "suite.py")
_spec = importlib.util.spec_from_file_location("bench_suite", _SUITE_PATH)
suite = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(suite)


def _read(path):
    with open(path) as f:
        return json.load(f)


def _merge(tmp_path, results, meta=None):
    out = os.path.join(tmp_path, "BENCH_SUITE.json")
    suite._write_merged(out, results, meta or {"platform": "cpu"})
    return _read(out)


def test_merge_stamps_missing_timestamp_as_stale(tmp_path):
    data = _merge(tmp_path, [
        {"config": "packed-1m", "value": 1.0, "platform": "tpu",
         "recorded_at": "2026-07-30T15:40:15+00:00"},
        {"config": "mobilenet-3.5m", "value": 2.0, "platform": "tpu"},
    ])
    by = {r["config"]: r for r in data["results"]}
    assert "stale" not in by["packed-1m"]
    assert by["mobilenet-3.5m"]["stale"] is True


def test_merge_stamps_earlier_window_stale_and_fresh_clears_it(tmp_path):
    old = {"config": "lora-13m", "value": 1.0, "platform": "tpu",
           "recorded_at": "2026-07-28T10:00:00+00:00"}
    new = {"config": "packed-1m", "value": 2.0, "platform": "tpu",
           "recorded_at": "2026-07-30T15:00:00+00:00"}
    data = _merge(tmp_path, [old, new])
    by = {r["config"]: r for r in data["results"]}
    assert by["lora-13m"]["stale"] is True
    assert "stale" not in by["packed-1m"]
    # a fresh re-record of the stale config clears the flag
    data = _merge(tmp_path, [
        {"config": "lora-13m", "value": 3.0, "platform": "tpu",
         "recorded_at": "2026-07-30T15:30:00+00:00"}])
    by = {r["config"]: r for r in data["results"]}
    assert "stale" not in by["lora-13m"]
    assert by["lora-13m"]["value"] == 3.0


def test_merge_same_window_records_not_stale(tmp_path):
    # two records an hour apart are the same window (span threshold 3h)
    data = _merge(tmp_path, [
        {"config": "packed-1m", "value": 1.0, "platform": "tpu",
         "recorded_at": "2026-07-30T14:45:00+00:00"},
        {"config": "lenet-60k", "value": 2.0, "platform": "tpu",
         "recorded_at": "2026-07-30T15:40:00+00:00"},
    ])
    assert all("stale" not in r for r in data["results"])


def test_merge_error_stub_never_replaces_good_record(tmp_path):
    good = {"config": "packed-1m", "value": 5.0, "platform": "tpu",
            "recorded_at": "2026-07-30T15:00:00+00:00"}
    _merge(tmp_path, [good])
    data = _merge(tmp_path, [
        {"config": "packed-1m", "error": "Boom", "platform": "cpu",
         "recorded_at": "2026-07-30T16:00:00+00:00"}])
    by = {r["config"]: r for r in data["results"]}
    assert by["packed-1m"]["value"] == 5.0
    assert "error" not in by["packed-1m"]


def test_merge_cpu_rerun_never_downgrades_tpu_record(tmp_path):
    tpu = {"config": "packed-1m", "value": 5.0, "platform": "tpu",
           "recorded_at": "2026-07-30T15:00:00+00:00"}
    _merge(tmp_path, [tpu])
    data = _merge(tmp_path, [
        {"config": "packed-1m", "value": 0.1, "platform": "cpu",
         "recorded_at": "2026-07-31T15:00:00+00:00"}])
    by = {r["config"]: r for r in data["results"]}
    assert by["packed-1m"]["platform"] == "tpu"
    assert by["packed-1m"]["value"] == 5.0
    # the rejected downgrade contributed no newer record, so the surviving
    # TPU evidence is still the newest window: not stale
    assert "stale" not in by["packed-1m"]
    # a NON-tpu record from a later time must not move the staleness
    # anchor (windows are TPU events; a CPU dev-box rerun of one config
    # must not relabel the whole file stale)
    data = _merge(tmp_path, [
        {"config": "paillier-premix", "value": 9.0, "platform": "cpu",
         "recorded_at": "2026-07-31T15:00:00+00:00"}])
    by = {r["config"]: r for r in data["results"]}
    assert "stale" not in by["packed-1m"]
    assert "stale" not in by["paillier-premix"]  # newer than the anchor
    # once another TPU record lands from a later window, the old TPU
    # record is visibly from an earlier one
    data = _merge(tmp_path, [
        {"config": "lenet-60k", "value": 9.0, "platform": "tpu",
         "recorded_at": "2026-07-31T15:00:00+00:00"}])
    by = {r["config"]: r for r in data["results"]}
    assert by["packed-1m"]["stale"] is True
    # the cpu record carries the same timestamp as the new anchor: fresh
    assert "stale" not in by["paillier-premix"]
    assert "stale" not in by["lenet-60k"]


def test_merge_tolerates_naive_timestamps(tmp_path):
    # a hand-edited record without a timezone must not crash the merge
    # (it runs after every config inside a scarce hardware window)
    data = _merge(tmp_path, [
        {"config": "packed-1m", "value": 1.0, "platform": "tpu",
         "recorded_at": "2026-07-28T10:00:00"},
        {"config": "lenet-60k", "value": 2.0, "platform": "tpu",
         "recorded_at": "2026-07-30T15:40:00+00:00"},
    ])
    by = {r["config"]: r for r in data["results"]}
    assert by["packed-1m"]["stale"] is True
    assert "stale" not in by["lenet-60k"]
