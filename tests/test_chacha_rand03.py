"""rand-0.3 ChaChaRng wire interop (CHACHA_PRG_RAND03).

The reference masks via rand 0.3's ``ChaChaRng::from_seed(&[u32])`` +
``gen_range(0_i64, modulus)`` (client/src/crypto/masking/chacha.rs:24-77).
Round-4's verdict flagged that sda-tpu's own CHACHA_PRG_V1 stream shared
the Rust scheme's wire *shape* while drawing a different stream — a mixed
Rust/sda-tpu round would silently reveal a wrong aggregate. These tests pin
the fix:

- a straight-line sequential transcription of the rand 0.3 algorithm
  (``Rand03ChaChaRng`` below — the fixture oracle, deliberately a separate
  code path from the vectorized implementations);
- RFC 8439 A.1 keystream vectors as external ground truth for the shared
  ChaCha20 block function (rand 0.3's 128-bit block counter coincides with
  the RFC layout at zero nonce for < 2^32 blocks);
- bit-identity of the numpy / native C++ / jax rand03 expansions against
  the oracle, including rejection-heavy and power-of-two moduli;
- the wire contract: a bare Rust-shaped scheme object means rand03, the V1
  stream is an explicit tag, unknown tags fail loudly at parse time.

No cargo exists in this image, so an executed-Rust capture is impossible;
the oracle transcription (cited to the crate's files) is the strongest
available fixture and is honestly labelled as such in the README.
"""

from __future__ import annotations

import numpy as np
import pytest

from sda_tpu.crypto import masking
from sda_tpu.fields import chacha, chacha_jax
from sda_tpu.protocol import (
    CHACHA_PRG_RAND03,
    CHACHA_PRG_V1,
    ChaChaMasking,
    LinearMaskingScheme,
)
from sda_tpu import native


# ---------------------------------------------------------------------------
# The fixture oracle: rand 0.3's ChaChaRng, transcribed line by line.

_M32 = 0xFFFFFFFF


class Rand03ChaChaRng:
    """Sequential transcription of rand 0.3's ``ChaChaRng`` (rand-0.3
    src/chacha.rs: ``init``/``update``/``next_u32``/``from_seed``), the
    default ``Rng::next_u64`` (first draw = high half), and the i64
    ``gen_range`` rejection sampler (src/distributions/range.rs:
    ``zone = u64::MAX - u64::MAX % range``, accept ``v < zone``)."""

    def __init__(self, seed_words):
        # from_seed: init with zero key, then copy seed into state[4..12]
        # (shorter seeds leave the remaining key words zero)
        self.state = [0x61707865, 0x3320646E, 0x79622D32, 0x6B206574] + [0] * 12
        for i, w in enumerate(list(seed_words)[:8]):
            self.state[4 + i] = int(w) & _M32
        self.buffer = [0] * 16
        self.index = 16  # STATE_WORDS: forces update() on the first draw

    def _update(self):
        x = list(self.state)

        def qr(a, b, c, d):
            x[a] = (x[a] + x[b]) & _M32
            x[d] ^= x[a]
            x[d] = ((x[d] << 16) | (x[d] >> 16)) & _M32
            x[c] = (x[c] + x[d]) & _M32
            x[b] ^= x[c]
            x[b] = ((x[b] << 12) | (x[b] >> 20)) & _M32
            x[a] = (x[a] + x[b]) & _M32
            x[d] ^= x[a]
            x[d] = ((x[d] << 8) | (x[d] >> 24)) & _M32
            x[c] = (x[c] + x[d]) & _M32
            x[b] ^= x[c]
            x[b] = ((x[b] << 7) | (x[b] >> 25)) & _M32

        for _ in range(10):  # CHACHA_ROUNDS / 2 double rounds
            qr(0, 4, 8, 12)
            qr(1, 5, 9, 13)
            qr(2, 6, 10, 14)
            qr(3, 7, 11, 15)
            qr(0, 5, 10, 15)
            qr(1, 6, 11, 12)
            qr(2, 7, 8, 13)
            qr(3, 4, 9, 14)
        self.buffer = [(xi + si) & _M32 for xi, si in zip(x, self.state)]
        self.index = 0
        # 128-bit block counter across words 12..15 (chacha.rs update())
        for w in range(12, 16):
            self.state[w] = (self.state[w] + 1) & _M32
            if self.state[w] != 0:
                break

    def next_u32(self) -> int:
        if self.index == 16:
            self._update()
        v = self.buffer[self.index]
        self.index += 1
        return v

    def next_u64(self) -> int:
        # Rng::next_u64 default: ((next_u32 as u64) << 32) | next_u32
        hi = self.next_u32()
        lo = self.next_u32()
        return (hi << 32) | lo

    def gen_range_i64(self, low: int, high: int) -> int:
        rng = high - low
        umax = (1 << 64) - 1
        zone = umax - umax % rng
        while True:
            v = self.next_u64()
            if v < zone:
                return low + v % rng

    def expand(self, dimension: int, modulus: int) -> np.ndarray:
        return np.array(
            [self.gen_range_i64(0, modulus) for _ in range(dimension)],
            dtype=np.int64,
        )


# ---------------------------------------------------------------------------
# External ground truth for the shared block function.

# RFC 8439 A.1 test vectors #1/#2: zero key, zero nonce, counters 0 and 1.
# With a zero nonce the RFC state layout equals rand 0.3's 128-bit-counter
# layout, so these pin the block function both PRGs share.
_RFC8439_BLOCK0 = bytes.fromhex(
    "76b8e0ada0f13d90405d6ae55386bd28"
    "bdd219b8a08ded1aa836efcc8b770dc7"
    "da41597c5157488d7724e03fb8d84a37"
    "6a43b8f41518a11cc387b669b2ee6586"
)
_RFC8439_BLOCK1 = bytes.fromhex(
    "9f07e7be5551387a98ba977c732d080d"
    "cb0f29a048e3656912c6533e32ee7aed"
    "29b721769ce64e43d57133b074d839d5"
    "31ed1f28510afb45ace10a1f4b794d6f"
)


def test_block_function_matches_rfc8439():
    words = chacha.chacha_block_words([], 0, 2)
    assert words[0].astype("<u4").tobytes() == _RFC8439_BLOCK0
    assert words[1].astype("<u4").tobytes() == _RFC8439_BLOCK1


def test_oracle_buffer_matches_rfc8439():
    """The oracle's own block output against the RFC — so a shared
    transcription error between oracle and implementation cannot hide."""
    rng = Rand03ChaChaRng([])
    stream = bytes()
    for _ in range(32):  # two blocks of u32 words, little-endian
        stream += rng.next_u32().to_bytes(4, "little")
    assert stream == _RFC8439_BLOCK0 + _RFC8439_BLOCK1


# ---------------------------------------------------------------------------
# Vectorized implementations == oracle.

_CASES = [
    # (seed, dimension, modulus)
    ([1, 2, 3, 4], 100, 433),
    ([0xDEADBEEF, 0x01234567, 0x89ABCDEF], 257, 536870233),
    ([7], 1, 2),
    ([0xFFFFFFFF] * 8, 65, 1024),  # power-of-two modulus: rand03 != V1 zone
    ([5, 6, 7, 8], 200, (1 << 61) + 1),  # ~12.5% rejection per draw
    ([9, 10, 11, 12, 13, 14, 15, 16], 1000, (1 << 62) - 57),
]


@pytest.mark.parametrize("seed,dim,modulus", _CASES)
def test_numpy_rand03_matches_oracle(seed, dim, modulus):
    got = chacha.expand_mask_rand03(seed, dim, modulus)
    exp = Rand03ChaChaRng(seed).expand(dim, modulus)
    np.testing.assert_array_equal(got, exp)


@pytest.mark.parametrize("seed,dim,modulus", _CASES)
def test_native_rand03_matches_oracle(seed, dim, modulus):
    if not native.available():
        pytest.skip("native library unavailable")
    got = native.chacha_expand_mask(seed, dim, modulus, prg=CHACHA_PRG_RAND03)
    exp = Rand03ChaChaRng(seed).expand(dim, modulus)
    np.testing.assert_array_equal(got, exp)


@pytest.mark.parametrize("seed,dim,modulus", _CASES)
def test_jax_rand03_matches_oracle(seed, dim, modulus):
    got = chacha_jax.expand_mask(seed, dim, modulus, prg=CHACHA_PRG_RAND03)
    exp = Rand03ChaChaRng(seed).expand(dim, modulus)
    np.testing.assert_array_equal(got, exp)


def test_combine_rand03_all_backends():
    seeds = np.array(
        [[1, 2, 3, 4], [5, 6, 7, 8], [0xFFFFFFFF, 0, 1, 2]], dtype=np.int64
    )
    dim, m = 150, 433
    exp = np.zeros(dim, dtype=np.int64)
    for s in seeds:
        exp = (exp + Rand03ChaChaRng(s).expand(dim, m)) % m
    np.testing.assert_array_equal(
        chacha_jax.combine_masks(
            [list(map(int, s)) for s in seeds], dim, m, prg=CHACHA_PRG_RAND03
        ),
        exp,
    )
    if native.available():
        np.testing.assert_array_equal(
            native.chacha_combine_masks(seeds, dim, m, prg=CHACHA_PRG_RAND03),
            exp,
        )


def test_streams_actually_differ():
    """Guard against the two tags silently aliasing one stream."""
    seed, dim, m = [1, 2, 3, 4], 64, 433
    v1 = chacha.expand_mask(seed, dim, m)
    r03 = chacha.expand_mask_rand03(seed, dim, m)
    assert not np.array_equal(v1, r03)


# ---------------------------------------------------------------------------
# Wire contract.

def test_bare_rust_shape_means_rand03():
    obj = {"ChaCha": {"modulus": 433, "dimension": 10, "seed_bitsize": 128}}
    scheme = LinearMaskingScheme.from_obj(obj)
    assert scheme.prg == CHACHA_PRG_RAND03
    # and it serializes straight back to the byte-identical Rust shape
    assert scheme.to_obj() == obj


def test_v1_tag_roundtrips():
    scheme = ChaChaMasking(433, 10, 128, prg=CHACHA_PRG_V1)
    obj = scheme.to_obj()
    assert obj["ChaCha"]["prg"] == CHACHA_PRG_V1
    back = LinearMaskingScheme.from_obj(obj)
    assert back == scheme and back.prg == CHACHA_PRG_V1


def test_unknown_prg_fails_loudly_at_parse():
    obj = {"ChaCha": {"modulus": 433, "dimension": 10, "seed_bitsize": 128,
                      "prg": "rand-0.5/chacharng"}}
    with pytest.raises(ValueError, match="unknown ChaCha PRG"):
        LinearMaskingScheme.from_obj(obj)
    with pytest.raises(ValueError, match="unknown ChaCha PRG"):
        ChaChaMasking(433, 10, 128, prg="nonsense")


def test_prg_constants_pinned_across_layers():
    """The wire layer duplicates the literals to stay import-light; the
    native loader keys its symbol map on them too. All three must agree."""
    assert CHACHA_PRG_V1 == chacha.CHACHA_PRG_V1
    assert CHACHA_PRG_RAND03 == chacha.CHACHA_PRG_RAND03
    assert set(native._CHACHA_FNS) == {CHACHA_PRG_V1, CHACHA_PRG_RAND03}
    assert set(chacha._EXPANDERS) == {CHACHA_PRG_V1, CHACHA_PRG_RAND03}


@pytest.mark.parametrize("prg", [CHACHA_PRG_RAND03, CHACHA_PRG_V1])
def test_masking_roundtrip_both_prgs(prg):
    scheme = ChaChaMasking(433, 32, 128, prg=prg)
    masker = masking.new_secret_masker(scheme)
    combiner = masking.new_mask_combiner(scheme)
    unmasker = masking.new_secret_unmasker(scheme)
    s1 = np.arange(32, dtype=np.int64) % 433
    s2 = (np.arange(32, dtype=np.int64) * 7 + 5) % 433
    m1, x1 = masker.mask(s1)
    m2, x2 = masker.mask(s2)
    total = combiner.combine([m1, m2])
    out = unmasker.unmask(total, (x1 + x2) % 433)
    np.testing.assert_array_equal(out, (s1 + s2) % 433)


def test_rand03_mask_then_oracle_combine():
    """A participant masked by the dispatcher must be unmaskable by a PEER
    whose combine is the oracle itself — i.e. a faithful Rust recipient
    recovers the right aggregate from our participation."""
    scheme = ChaChaMasking(433, 50, 128)  # default prg: rand03
    masker = masking.new_secret_masker(scheme)
    s = (np.arange(50, dtype=np.int64) * 3 + 1) % 433
    seed, masked = masker.mask(s)
    peer_mask = Rand03ChaChaRng([int(w) for w in seed]).expand(50, 433)
    np.testing.assert_array_equal((masked - peer_mask) % 433, s)
