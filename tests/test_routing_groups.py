"""Group sharding over the consistent-hash ring (server/routing.py via
sda_tpu.tree.plan.shard_groups) — the population-sharding satellite of
the tree subsystem: deterministic assignment at a fixed key set, rough
balance across G groups, and minimal movement when G changes by one.
"""

import pytest

from sda_tpu.server.routing import HashRing
from sda_tpu.tree.plan import shard_groups


def keys(n, tag="agent"):
    return [f"{tag}-{ix:06d}" for ix in range(n)]


def assignment(shards):
    return {key: ix for ix, shard in enumerate(shards) for key in shard}


class TestDeterminism:
    def test_same_keys_same_shards(self):
        population = keys(500)
        assert shard_groups(population, 7) == shard_groups(population, 7)

    def test_order_independent(self):
        """Assignment is a pure function of the key, so feeding the
        population in a different order shards every key identically."""
        population = keys(300)
        forward = assignment(shard_groups(population, 5))
        backward = assignment(shard_groups(list(reversed(population)), 5))
        assert forward == backward

    def test_matches_ring_directly(self):
        """shard_groups IS the serving ring's mapping — no parallel
        hashing scheme to drift from routing."""
        population = keys(64)
        ring = HashRing([f"group-{ix}" for ix in range(4)])
        got = assignment(shard_groups(population, 4))
        for key in population:
            assert f"group-{got[key]}" == ring.node_for(key)

    def test_single_group_takes_all(self):
        population = keys(40)
        shards = shard_groups(population, 1)
        assert shards == [population]

    def test_zero_groups_rejected(self):
        with pytest.raises(ValueError):
            shard_groups(keys(4), 0)


class TestBalance:
    def test_rough_balance_across_groups(self):
        """The Karger ring with 64 vnodes per group is statistically
        balanced, not perfectly: with a healthy population every group
        must land within a loose factor of the fair share, and no group
        may be empty."""
        population = keys(4000)
        groups = 8
        sizes = [len(s) for s in shard_groups(population, groups)]
        fair = len(population) / groups
        assert sum(sizes) == len(population)
        assert min(sizes) > 0
        assert max(sizes) < 2.5 * fair
        assert min(sizes) > fair / 3.5

    def test_more_replicas_tighten_balance(self):
        population = keys(4000)
        loose = [len(s) for s in shard_groups(population, 8, replicas=8)]
        tight = [len(s) for s in shard_groups(population, 8, replicas=256)]

        def spread(sizes):
            return max(sizes) - min(sizes)

        assert spread(tight) <= spread(loose)


class TestMinimalMovement:
    def test_adding_one_group_moves_about_one_share(self):
        """G -> G+1 must only move ~1/(G+1) of the population (the ring
        property the fleet already relies on for worker churn): movement
        stays well under a full reshuffle, and every key that moved,
        moved INTO the new group — no lateral churn between survivors."""
        population = keys(3000)
        groups = 9
        before = assignment(shard_groups(population, groups))
        after = assignment(shard_groups(population, groups + 1))
        moved = [key for key in population if before[key] != after[key]]
        fair_share = len(population) / (groups + 1)
        assert len(moved) < 2.5 * fair_share  # vs ~N*(G/(G+1)) reshuffled
        assert all(after[key] == groups for key in moved)

    def test_removing_one_group_only_drains_it(self):
        population = keys(3000)
        groups = 10
        before = assignment(shard_groups(population, groups))
        after = assignment(shard_groups(population, groups - 1))
        for key in population:
            if before[key] != groups - 1:  # survivors keep their group
                assert after[key] == before[key]
