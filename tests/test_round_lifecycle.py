"""Round lifecycle supervisor: state machine, deadline sweeper, dead-clerk
detection, quorum-degraded completion, and the typed client surface.

The contract under test (``sda_tpu/server/lifecycle.py``,
docs/robustness.md): every aggregation round carries an explicit
store-persisted state machine (``collecting → frozen → clerking → ready →
revealed`` plus terminal ``degraded``/``failed``/``expired``); the sweeper
diagnoses permanently dead clerks past the clerking deadline — Shamir
rounds degrade to the surviving quorum and still reveal bit-exactly,
additive rounds fail closed with a machine-readable reason; and every
sweep action is a store-arbitrated CAS, so two fleet workers over one
shared backend perform each transition exactly once.
"""

import threading
import time

import numpy as np
import pytest

from sda_tpu import chaos, obs
from sda_tpu.client import SdaClient
from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    AgentId,
    ClerkingResult,
    Committee,
    FullMasking,
    NoMasking,
    PackedShamirSharing,
    Participation,
    ParticipationId,
    RoundExpired,
    RoundFailed,
    RoundStatus,
    Snapshot,
    SnapshotId,
    SodiumEncryption,
)
from sda_tpu.server import (
    new_jsonfs_server,
    new_memory_server,
    new_mongo_server,
    new_sqlite_server,
)
from sda_tpu.server import lifecycle
from sda_tpu.utils import metrics

from util import mock_encryption, new_agent, new_full_agent

GOLDEN = PackedShamirSharing(
    secret_count=3, share_count=8, privacy_threshold=4,
    prime_modulus=433, omega_secrets=354, omega_shares=150,
)

needs_sodium = pytest.mark.skipif(not sodium.available(),
                                  reason="libsodium not present")


@pytest.fixture(autouse=True)
def _clean_registries():
    obs.reset_all()
    chaos.reset()
    yield
    chaos.reset()
    obs.reset_all()


def _mock_world(service, scheme, participants=3):
    """Mock-crypto aggregation with a committee and frozen snapshot jobs
    (the server never opens ciphertexts, so state-machine tests don't
    need libsodium)."""
    recipient, rkey = new_full_agent(service)
    committee = [new_full_agent(service) for _ in range(scheme.output_size)]
    agg = Aggregation(
        id=AggregationId.random(), title="lifecycle", vector_dimension=4,
        modulus=433, recipient=recipient.id, recipient_key=rkey.body.id,
        masking_scheme=NoMasking(), committee_sharing_scheme=scheme,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    service.create_committee(recipient, Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for (a, k) in committee],
    ))
    for i in range(participants):
        agent = new_agent()
        service.create_agent(agent, agent)
        service.create_participation(agent, Participation(
            id=ParticipationId.random(), participant=agent.id,
            aggregation=agg.id, recipient_encryption=None,
            clerk_encryptions=[(a.id, mock_encryption(bytes([i])))
                               for (a, _) in committee],
        ))
    return recipient, committee, agg


def _post_results(service, committee):
    for (agent, _key) in committee:
        job = service.get_clerking_job(agent, agent.id)
        service.create_clerking_result(agent, ClerkingResult(
            job=job.id, clerk=agent.id, encryption=mock_encryption(b"r")))


# ---------------------------------------------------------------------------
# the state machine over protocol events

def test_happy_path_states():
    service = new_memory_server()
    recipient, committee, agg = _mock_world(service, AdditiveSharing(3, 433))
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "collecting"
    assert status.scheme == "additive"
    assert status.committee_size == 3
    assert status.reconstruction_threshold == 3

    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "clerking"
    assert status.snapshot == snap.id
    assert [s for s, _ in status.history] == ["collecting", "frozen",
                                              "clerking"]

    _post_results(service, committee)
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "ready"
    assert status.results == 3

    service.get_snapshot_result(recipient, agg.id, snap.id)
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "revealed"
    # history timestamps are monotone non-decreasing server stamps
    stamps = [ts for _, ts in status.history]
    assert stamps == sorted(stamps)


def test_partial_results_stay_clerking():
    """reconstruction_threshold results make result_ready true but the
    round stays clerking — ready means the FULL committee reported; only
    the sweeper may declare the stragglers dead."""
    service = new_memory_server()
    recipient, committee, agg = _mock_world(service, GOLDEN)
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    _post_results(service, committee[:GOLDEN.reconstruction_threshold])
    status = service.get_round_status(recipient, agg.id)
    assert status.results == GOLDEN.reconstruction_threshold
    assert status.state == "clerking"


def test_replayed_create_aggregation_does_not_reset_round():
    """create_aggregation is a retry-safe upsert: a replayed create after
    a lost response must not snap an in-flight round back to collecting
    (which would erase its snapshot/diagnosis and let a collect deadline
    expire a live round)."""
    service = new_memory_server()
    recipient, committee, agg = _mock_world(service, AdditiveSharing(3, 433))
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)
    assert service.get_round_status(recipient, agg.id).state == "clerking"
    service.create_aggregation(recipient, agg)  # the client's retry
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "clerking"
    assert status.snapshot == snap.id
    # deleting really does start over
    service.delete_aggregation(recipient, agg.id)
    service.create_aggregation(recipient, agg)
    assert service.get_round_status(recipient, agg.id).state == "collecting"


def test_stale_snapshot_cannot_resurrect_terminal_round():
    """A snapshot pipeline racing an already-expired round must not pull
    it back to frozen/clerking: terminal verdicts the client was already
    told stay terminal."""
    service = new_memory_server()
    service.server.round_deadlines = lifecycle.RoundDeadlines(
        collecting_s=0.5)
    recipient, _, agg = _mock_world(service, AdditiveSharing(3, 433))
    sweeper = lifecycle.RoundSweeper(service.server)
    sweeper.sweep_once(now=time.time() + 10)
    assert service.get_round_status(recipient, agg.id).state == "expired"
    # the delayed snapshot still runs (nothing blocks it protocol-side)...
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    # ...but the round's verdict is unchanged
    assert service.get_round_status(recipient, agg.id).state == "expired"


def test_round_status_roundtrip():
    status = RoundStatus(
        aggregation=AggregationId.random(), state="degraded",
        snapshot=SnapshotId.random(), scheme="shamir", committee_size=8,
        reconstruction_threshold=7, results=7,
        dead_clerks=[AgentId.random()], reason="r", deadline_at=1.5,
        updated_at=2.5, history=[["clerking", 1.0], ["degraded", 2.5]],
    )
    assert RoundStatus.from_obj(status.to_obj()) == status


# ---------------------------------------------------------------------------
# the sweeper: deadlines + dead-clerk diagnosis

def test_sweeper_expires_collecting_past_deadline():
    service = new_memory_server()
    service.server.round_deadlines = lifecycle.RoundDeadlines(
        collecting_s=0.5)
    recipient, _, agg = _mock_world(service, AdditiveSharing(3, 433))
    sweeper = lifecycle.RoundSweeper(service.server)
    assert sweeper.sweep_once(now=time.time())["actions"] == []
    summary = sweeper.sweep_once(now=time.time() + 10)
    assert [a["to"] for a in summary["actions"]] == ["expired"]
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "expired"
    assert "collecting deadline" in status.reason
    # terminal: a later sweep never acts again
    assert sweeper.sweep_once(now=time.time() + 20)["actions"] == []


def test_sweeper_shamir_dead_clerk_degrades_then_reveals():
    service = new_memory_server()
    service.server.round_deadlines = lifecycle.RoundDeadlines(clerking_s=0.5)
    recipient, committee, agg = _mock_world(service, GOLDEN)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)
    dead_clerk = committee[0][0]
    _post_results(service, committee[1:])  # 7 of 8 == threshold

    sweeper = lifecycle.RoundSweeper(service.server)
    summary = sweeper.sweep_once(now=time.time() + 10)
    assert [a["to"] for a in summary["actions"]] == ["degraded"]
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "degraded"
    assert [str(c) for c in status.dead_clerks] == [str(dead_clerk.id)]
    assert "surviving quorum" in status.reason
    # the reveal completes the degraded round: clerking→degraded→revealed
    service.get_snapshot_result(recipient, agg.id, snap.id)
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "revealed"
    assert [s for s, _ in status.history][-3:] == ["clerking", "degraded",
                                                   "revealed"]


def test_sweeper_additive_dead_clerk_fails_closed():
    service = new_memory_server()
    service.server.round_deadlines = lifecycle.RoundDeadlines(clerking_s=0.5)
    recipient, committee, agg = _mock_world(service, AdditiveSharing(4, 433))
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    _post_results(service, committee[1:])  # 3 of 4: unrecoverable

    sweeper = lifecycle.RoundSweeper(service.server)
    summary = sweeper.sweep_once(now=time.time() + 10)
    assert [a["to"] for a in summary["actions"]] == ["failed"]
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "failed"
    assert "additive sharing cannot recover" in status.reason
    # zero admitted participations were lost on the way to the verdict
    assert service.server.aggregation_store.count_participations(agg.id) == 3


def test_sweeper_quorum_unreachable_fails():
    service = new_memory_server()
    service.server.round_deadlines = lifecycle.RoundDeadlines(clerking_s=0.5)
    recipient, committee, agg = _mock_world(service, GOLDEN)
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    _post_results(service, committee[2:])  # 6 of 8 < threshold 7, 2 dead

    sweeper = lifecycle.RoundSweeper(service.server)
    summary = sweeper.sweep_once(now=time.time() + 10)
    assert [a["to"] for a in summary["actions"]] == ["failed"]
    status = service.get_round_status(recipient, agg.id)
    assert status.state == "failed"
    assert "quorum unreachable" in status.reason
    assert len(status.dead_clerks) == 2


def test_sweeper_spares_actively_leased_jobs():
    """An undone job under a LIVE lease means its clerk is working right
    now: no dead-clerk verdict, even past the clerking deadline."""
    service = new_memory_server()
    service.server.round_deadlines = lifecycle.RoundDeadlines(clerking_s=0.5)
    service.server.clerking_lease_seconds = 3600.0  # nobody expires today
    recipient, committee, agg = _mock_world(service, AdditiveSharing(3, 433))
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    _post_results(service, committee[1:])
    # the remaining clerk POLLS (stamping a one-hour lease) but has not
    # posted its result yet — slow, not dead
    slow_agent = committee[0][0]
    assert service.get_clerking_job(slow_agent, slow_agent.id) is not None
    sweeper = lifecycle.RoundSweeper(service.server)
    assert sweeper.sweep_once(now=time.time() + 10)["actions"] == []
    assert service.get_round_status(recipient, agg.id).state == "clerking"


def test_no_deadline_means_no_sweeper_action():
    """Default deadlines (all None): states are tracked but nothing ever
    expires — bit-compatible with the pre-supervisor server."""
    service = new_memory_server()
    recipient, committee, agg = _mock_world(service, GOLDEN)
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    sweeper = lifecycle.RoundSweeper(service.server)
    assert sweeper.sweep_once(now=time.time() + 1e6)["actions"] == []
    assert service.get_round_status(recipient, agg.id).state == "clerking"


def test_sweep_metrics_and_statusz_rounds_table():
    service = new_memory_server()
    service.server.round_deadlines = lifecycle.RoundDeadlines(clerking_s=0.5)
    recipient, committee, agg = _mock_world(service, AdditiveSharing(4, 433))
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    _post_results(service, committee[1:])
    sweeper = lifecycle.RoundSweeper(service.server)
    sweeper.sweep_once(now=time.time() + 10)
    # sweep latency histogram (exposed on /metrics) + transition counters
    assert metrics.histogram_report("server.round.sweep")[
        "server.round.sweep"]["count"] >= 1
    counters = metrics.counter_report("server.round.")
    assert counters["server.round.state.failed"] == 1
    assert counters["server.round.dead_clerks"] == 1
    report = lifecycle.rounds_report(service.server)
    assert report["count"] == 1
    assert report["by_state"] == {"failed": 1}
    assert report["recent"][0]["reason"]


# ---------------------------------------------------------------------------
# fleet arbitration: exactly one worker wins each sweep action

@pytest.mark.parametrize("backend", ["memory", "sqlite", "jsonfs",
                                     "fakemongo"])
def test_sweep_single_winner_across_two_handles(backend, tmp_path):
    if backend == "memory":
        from sda_tpu.server import SdaServerService
        from sda_tpu.server.core import SdaServer
        from sda_tpu.server.memory import (
            MemoryAggregationsStore,
            MemoryAgentsStore,
            MemoryAuthTokensStore,
            MemoryClerkingJobsStore,
        )

        stores = dict(
            agents_store=MemoryAgentsStore(),
            auth_tokens_store=MemoryAuthTokensStore(),
            aggregation_store=MemoryAggregationsStore(),
            clerking_job_store=MemoryClerkingJobsStore(),
        )
        a, b = SdaServerService(SdaServer(**stores)), \
            SdaServerService(SdaServer(**stores))
    elif backend == "sqlite":
        path = tmp_path / "shared.db"
        a, b = new_sqlite_server(path), new_sqlite_server(path)
    elif backend == "jsonfs":
        root = tmp_path / "shared-jfs"
        a, b = new_jsonfs_server(root), new_jsonfs_server(root)
    else:
        from fake_mongo import FakeDatabase

        db = FakeDatabase()
        a, b = new_mongo_server(db), new_mongo_server(db)
    for handle in (a, b):
        handle.server.round_deadlines = lifecycle.RoundDeadlines(
            clerking_s=0.5)

    recipient, committee, agg = _mock_world(a, GOLDEN)
    a.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    _post_results(b, committee[1:])  # results through the PEER handle

    now = time.time() + 10
    results = [None, None]
    sweepers = [lifecycle.RoundSweeper(a.server),
                lifecycle.RoundSweeper(b.server)]

    def sweep(ix):
        results[ix] = sweepers[ix].sweep_once(now=now)

    threads = [threading.Thread(target=sweep, args=(ix,)) for ix in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    actions = results[0]["actions"] + results[1]["actions"]
    assert [a_["to"] for a_ in actions] == ["degraded"]  # exactly one winner
    # both handles observe the winner's transition, and zero
    # participations were lost along the way
    for handle in (a, b):
        assert handle.server.get_round_status(agg.id).state == "degraded"
        assert handle.server.aggregation_store.count_participations(
            agg.id) == 3


# ---------------------------------------------------------------------------
# the HTTP surface

def test_round_status_over_http_and_acl():
    from sda_tpu.http import SdaHttpClient, SdaHttpServer
    from sda_tpu.protocol import PermissionDenied

    service = new_memory_server()
    http_server = SdaHttpServer(service, bind="127.0.0.1:0",
                                statusz_endpoint=True)
    http_server.start_background()
    try:
        proxy = SdaHttpClient(http_server.address, token="lifecycle-test")
        recipient = new_agent()
        proxy.create_agent(recipient, recipient)
        stranger = new_agent()
        proxy.create_agent(stranger, stranger)
        key = new_full_agent(service)[1]  # key via the in-process seam
        agg = Aggregation(
            id=AggregationId.random(), title="http", vector_dimension=4,
            modulus=433, recipient=recipient.id,
            recipient_key=key.body.id, masking_scheme=NoMasking(),
            committee_sharing_scheme=AdditiveSharing(2, 433),
            recipient_encryption_scheme=SodiumEncryption(),
            committee_encryption_scheme=SodiumEncryption(),
        )
        proxy.create_aggregation(recipient, agg)
        status = proxy.get_round_status(recipient, agg.id)
        assert isinstance(status, RoundStatus)
        assert status.state == "collecting"
        assert status.aggregation == agg.id
        # recipient-only: the diagnosis names dead clerks
        with pytest.raises(PermissionDenied):
            proxy.get_round_status(stranger, agg.id)
        # the /statusz rounds table serves the same store-wide view
        statusz = http_server.statusz()
        assert statusz["rounds"]["by_state"] == {"collecting": 1}
    finally:
        http_server.shutdown()


# ---------------------------------------------------------------------------
# the blocking client: await_result + typed failures

def test_await_result_raises_typed_round_failed():
    service = new_memory_server()
    recipient, committee, agg = _mock_world(service, AdditiveSharing(3, 433))
    dead = str(committee[0][0].id)
    service.server.aggregation_store.put_round_state({
        "aggregation": str(agg.id), "state": "failed", "snapshot": None,
        "scheme": "additive", "committee_size": 3,
        "reconstruction_threshold": 3, "dead_clerks": [dead],
        "reason": "boom", "deadline_at": None, "updated_at": time.time(),
        "history": [["failed", time.time()]],
    })
    client = SdaClient(recipient, MemoryKeystore(), service)
    with pytest.raises(RoundFailed) as err:
        client.await_result(agg.id, deadline=5.0)
    assert err.value.reason == "boom"
    assert err.value.state == "failed"
    assert [str(c) for c in err.value.dead_clerks] == [dead]
    assert not isinstance(err.value, RoundExpired)


def test_await_result_expired_round_raises_round_expired():
    service = new_memory_server()
    recipient, _, agg = _mock_world(service, AdditiveSharing(3, 433))
    service.server.aggregation_store.put_round_state({
        "aggregation": str(agg.id), "state": "expired", "snapshot": None,
        "scheme": "additive", "committee_size": 3,
        "reconstruction_threshold": 3, "dead_clerks": [],
        "reason": "took too long", "deadline_at": None,
        "updated_at": time.time(), "history": [],
    })
    client = SdaClient(recipient, MemoryKeystore(), service)
    with pytest.raises(RoundExpired, match="took too long"):
        client.await_result(agg.id)


def test_await_result_client_deadline():
    service = new_memory_server()
    recipient, _, agg = _mock_world(service, AdditiveSharing(3, 433))
    client = SdaClient(recipient, MemoryKeystore(), service)
    t0 = time.monotonic()
    with pytest.raises(RoundExpired, match="client-side"):
        client.await_result(agg.id, deadline=0.3, poll_interval=0.05)
    assert time.monotonic() - t0 < 5.0


@needs_sodium
def test_await_result_returns_output():
    """The success path: a straggler clerk finishes in the background and
    the blocked recipient wakes up with the bit-exact aggregate."""
    service = new_memory_server()

    def new_client():
        keystore = MemoryKeystore()
        client = SdaClient(SdaClient.new_agent(keystore), keystore, service)
        client.upload_agent()
        return client

    recipient = new_client()
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    clerks = []
    for _ in range(2):
        clerk = new_client()
        clerk.upload_encryption_key(clerk.new_encryption_key())
        clerks.append(clerk)
    agg = Aggregation(
        id=AggregationId.random(), title="await", vector_dimension=3,
        modulus=433, recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(2, 433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(agg)
    recipient.begin_aggregation_with(
        agg.id, [c.agent.id for c in clerks[:2]])
    committee = service.get_committee(recipient.agent, agg.id)
    members = {str(cid) for cid, _ in committee.clerks_and_keys}
    for row in ([1, 2, 3], [4, 5, 6]):
        participant = new_client()
        participant.participate(row, agg.id)
    recipient.end_aggregation(agg.id)

    def run_clerks():
        time.sleep(0.3)
        for clerk in clerks:
            if str(clerk.agent.id) in members:
                clerk.run_chores(-1)

    worker = threading.Thread(target=run_clerks)
    worker.start()
    try:
        output = recipient.await_result(agg.id, deadline=30.0,
                                        poll_interval=0.05)
    finally:
        worker.join()
    np.testing.assert_array_equal(output.positive().values, [5, 7, 9])
    assert service.get_round_status(recipient.agent,
                                    agg.id).state == "revealed"


# ---------------------------------------------------------------------------
# reveal-time quorum robustness (satellite: decrypt_result fix)

@needs_sodium
def test_reveal_skips_unknown_clerk_result(monkeypatch):
    """A result from a clerk outside the committee must not abort the
    reveal from inside the crypto pool: it is skipped with a counted
    warning and the remaining quorum reconstructs bit-exactly."""
    service = new_memory_server()

    def new_client():
        keystore = MemoryKeystore()
        client = SdaClient(SdaClient.new_agent(keystore), keystore, service)
        client.upload_agent()
        return client

    recipient = new_client()
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    candidates = {recipient.agent.id: recipient}
    for _ in range(GOLDEN.share_count):
        clerk = new_client()
        clerk.upload_encryption_key(clerk.new_encryption_key())
        candidates[clerk.agent.id] = clerk
    agg = Aggregation(
        id=AggregationId.random(), title="tamper", vector_dimension=4,
        modulus=433, recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=NoMasking(), committee_sharing_scheme=GOLDEN,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(agg)
    recipient.begin_aggregation(agg.id)
    committee = service.get_committee(recipient.agent, agg.id)
    for row in ([1, 2, 3, 4], [2, 3, 4, 5]):
        new_client().participate(row, agg.id)
    recipient.end_aggregation(agg.id)
    for cid, _ in committee.clerks_and_keys:
        candidates[cid].run_chores(-1)

    original = service.get_snapshot_result

    def tampered(caller, aggregation, snapshot):
        result = original(caller, aggregation, snapshot)
        # a stale/hostile result whose clerk is NOT in the committee
        result.clerk_encryptions.append(ClerkingResult(
            job=result.clerk_encryptions[0].job, clerk=AgentId.random(),
            encryption=mock_encryption(b"junk")))
        return result

    monkeypatch.setattr(service, "get_snapshot_result", tampered)
    output = recipient.reveal_aggregation(agg.id)
    np.testing.assert_array_equal(output.positive().values, [3, 5, 7, 9])
    assert metrics.counter_report()["recipient.result.unknown_clerk"] == 1


def test_additive_reconstructor_fails_closed_below_full_set():
    from sda_tpu.crypto.sharing import AdditiveReconstructor

    recon = AdditiveReconstructor(AdditiveSharing(3, 433))
    shares = [(i, np.array([i + 1, i + 2], dtype=np.int64)) for i in range(3)]
    assert recon.reconstruct(shares) is not None
    with pytest.raises(ValueError, match="need at least 3"):
        recon.reconstruct(shares[:2])


# ---------------------------------------------------------------------------
# quorum reconstruction coverage (satellite: oracle + JAX lanes)

def test_exact_quorum_matches_full_committee_both_lanes(monkeypatch):
    """Exactly-``reconstruction_threshold`` survivors reconstruct the
    same secrets as the full committee — on the host oracle lane AND the
    JAX device lane — and survivor-set truncation never retraces."""
    from sda_tpu import fields
    from sda_tpu.crypto import sharing
    from sda_tpu.crypto.sharing import (
        PackedShamirReconstructor,
        PackedShamirShareGenerator,
    )
    from sda_tpu.obs import devprof

    rng = np.random.default_rng(11)
    secrets = rng.integers(0, GOLDEN.prime_modulus, size=10)
    shares = PackedShamirShareGenerator(GOLDEN).generate(secrets)
    r = GOLDEN.reconstruction_threshold
    survivor_sets = [
        list(range(GOLDEN.share_count)),          # everyone
        list(range(r)),                           # exact quorum, prefix
        [0, 2, 3, 4, 5, 6, 7],                    # exact quorum, with a hole
    ]
    for lane_max in (1 << 30, 0):  # host oracle lane, then device lane
        monkeypatch.setattr(sharing, "HOST_PATH_MAX", lane_max)
        recon = PackedShamirReconstructor(GOLDEN, dimension=len(secrets))
        baseline = fields.packed_reconstruct._cache_size()
        for survivors in survivor_sets:
            got = recon.reconstruct([(i, shares[i]) for i in survivors])
            np.testing.assert_array_equal(got, secrets)
        if lane_max == 0:
            # fixed-survivor-count truncation: one compiled [r+1, B]
            # kernel serves every survivor set — zero retraces (the PR 4
            # devprof tripwire, extended to the quorum path)
            assert fields.packed_reconstruct._cache_size() == baseline + 1
            totals = devprof.compile_totals()["functions"]
            assert totals["fields.packed_reconstruct"]["retraces"] == 0
        with pytest.raises(ValueError, match="need at least"):
            recon.reconstruct([(i, shares[i]) for i in range(r - 1)])


# ---------------------------------------------------------------------------
# permanent-death failpoints (satellite: chaos layer)

def test_clerk_dies_failpoint_latches_forever():
    class NeverPolled:
        def get_clerking_job(self, caller, clerk):  # pragma: no cover
            raise AssertionError("a dead clerk must never poll")

    chaos.configure("clerk.dies", kill=True, times=1)
    client = SdaClient(new_agent(), MemoryKeystore(), NeverPolled())
    assert client.clerk_once() is False
    assert client._dead
    # disarming the failpoint does NOT resurrect the clerk: death is
    # permanent for the rest of the drill
    chaos.reset()
    assert client.clerk_once() is False


def test_clerk_dies_times_kills_exactly_k_distinct_clerks():
    service = new_memory_server()
    recipient, committee, agg = _mock_world(service, AdditiveSharing(3, 433))
    service.create_snapshot(
        recipient, Snapshot(id=SnapshotId.random(), aggregation=agg.id))
    chaos.configure("clerk.dies", kill=True, times=2)
    clients = [SdaClient(agent, MemoryKeystore(), service)
               for (agent, _) in committee[:2]]
    for client in clients:
        client.run_chores(-1)  # first run dies; the latch holds after
        client.run_chores(-1)
    assert all(c._dead for c in clients)
    # the budget is spent on exactly K distinct clerks: a third clerk
    # would NOT be killed
    assert chaos.evaluate("clerk.dies", kinds=("kill",)) is None
    # the dead clerks' jobs were never polled, let alone leased
    jobs = service.server.clerking_job_store.list_snapshot_jobs(
        service.server.get_round_status(agg.id).snapshot)
    assert all(not done and leased == 0.0
               for (_j, _c, done, leased) in jobs)


def test_participant_dies_failpoint_skips_contribution():
    class NeverCalled:
        def __getattr__(self, name):  # pragma: no cover
            raise AssertionError(f"dead participant called service.{name}")

    chaos.configure("participant.dies", kill=True, times=1)
    client = SdaClient(new_agent(), MemoryKeystore(), NeverCalled())
    assert client.participate([1, 2, 3], AggregationId.random()) is None
    assert client._dead
    assert metrics.counter_report()["participant.died"] == 1


def test_chaos_spec_parses_kill_kind():
    chaos.configure_from_spec("clerk.dies=kill,times=2", seed=7)
    assert chaos.evaluate("clerk.dies", kinds=("kill",)).kind == "kill"
    assert chaos.evaluate("clerk.dies", kinds=("kill",)).kind == "kill"
    assert chaos.evaluate("clerk.dies", kinds=("kill",)) is None  # times=2
