"""Contract of the persistent-compile-cache helpers (utils/backend.py).

The cache is the short-window survival lever (a tunnel window must not
re-pay a previous window's compiles), so its gating — never on CPU,
shared dir derivation, graceful degradation — is pinned off-chip.
"""

import os

from sda_tpu.utils.backend import compile_cache_dir, enable_compile_cache


def test_cpu_platform_is_gated_off(tmp_path):
    target = tmp_path / "cache"
    assert enable_compile_cache("cpu", str(target)) is None
    # gated BEFORE any filesystem effect
    assert not target.exists()


def test_axon_platform_sets_cache_dir(tmp_path):
    import jax

    target = tmp_path / "cache"
    got = enable_compile_cache("axon", str(target))
    try:
        assert got == str(target)
        assert target.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(target)
        # every entry cached: through the tunnel even fast compiles cost
        # a scarce-window round-trip
        assert jax.config.jax_persistent_cache_min_entry_size_bytes == -1
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.0
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_cache_dir_is_repo_root_derived():
    d = compile_cache_dir()
    assert os.path.basename(d) == ".jax_compile_cache"
    # repo root = the directory holding sda_tpu/
    root = os.path.dirname(d)
    assert os.path.isdir(os.path.join(root, "sda_tpu"))


def test_hw_check_cache_stats_uses_shared_dir(tmp_path, monkeypatch):
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks"))
    import hw_check

    # empty/missing dir reports zeros instead of raising
    monkeypatch.setattr(
        "sda_tpu.utils.backend.compile_cache_dir",
        lambda: str(tmp_path / "nonexistent"))
    assert hw_check._cache_stats() == {"entries": 0, "bytes": 0}

    d = tmp_path / "cache"
    d.mkdir()
    (d / "a").write_bytes(b"xy")
    (d / "b").write_bytes(b"z")
    monkeypatch.setattr(
        "sda_tpu.utils.backend.compile_cache_dir", lambda: str(d))
    assert hw_check._cache_stats() == {"entries": 2, "bytes": 3}
