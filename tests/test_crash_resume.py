"""Crash/resume durability: the "durable the moment it exists" claim
(server/core.py:8-10).

A round is started on a durable backend (sqlite / jsonfs), the server
"process" is dropped MID-ROUND — after participations landed, before the
snapshot — by discarding every live server object (and closing the sqlite
handle), then the store is reopened by a brand-new server and the round
must complete bit-exactly. The reference's checkpoint/resume story
(SURVEY.md §5.4) is exactly this: restart resumes from the store tree.
"""

import numpy as np
import pytest

from sda_tpu.client import SdaClient
from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.protocol import (
    Aggregation,
    AggregationId,
    FullMasking,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.server import new_jsonfs_server, new_sqlite_server

needs_sodium = pytest.mark.skipif(not sodium.available(), reason="libsodium not present")

GOLDEN = PackedShamirSharing(
    secret_count=3, share_count=8, privacy_threshold=4,
    prime_modulus=433, omega_secrets=354, omega_shares=150,
)


def _open_server(backend, tmp_path):
    if backend == "sqlite":
        return new_sqlite_server(tmp_path / "server.db")
    return new_jsonfs_server(tmp_path / "store")


def _drop_server(service):
    """Simulate losing the server process: every in-memory handle dies.
    For sqlite, close the connection so nothing survives but the file."""
    db = getattr(service.server.agents_store, "db", None)
    if db is not None:
        db.conn.close()


@needs_sodium
@pytest.mark.parametrize("backend", ["sqlite", "jsonfs"])
def test_round_survives_server_crash_between_participation_and_snapshot(
    backend, tmp_path
):
    # --- life 1: setup + participations --------------------------------
    service = _open_server(backend, tmp_path)

    def new_client(svc):
        keystore = MemoryKeystore()
        client = SdaClient(SdaClient.new_agent(keystore), keystore, svc)
        client.upload_agent()
        return client

    recipient = new_client(service)
    recipient_key = recipient.new_encryption_key()
    recipient.upload_encryption_key(recipient_key)

    # client objects (and their keystores) survive: the CRASH is server-side
    clients = {recipient.agent.id: recipient}
    for _ in range(GOLDEN.share_count):
        clerk = new_client(service)
        clerk.upload_encryption_key(clerk.new_encryption_key())
        clients[clerk.agent.id] = clerk

    agg = Aggregation(
        id=AggregationId.random(), title="crash-resume",
        vector_dimension=4, modulus=433,
        recipient=recipient.agent.id, recipient_key=recipient_key,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=GOLDEN,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(agg)
    recipient.begin_aggregation(agg.id)

    for offset in range(3):
        participant = new_client(service)
        participant.participate(
            [1 + offset, 2 + offset, 3 + offset, 4 + offset], agg.id
        )

    # --- the crash: between participation and snapshot ------------------
    _drop_server(service)
    del service

    # --- life 2: reopen the store, finish the round ---------------------
    resumed = _open_server(backend, tmp_path)
    for client in clients.values():
        client.service = resumed  # same agents, new server process

    status = resumed.get_aggregation_status(recipient.agent, agg.id)
    assert status.number_of_participations == 3  # durable the moment it existed

    recipient.end_aggregation(agg.id)  # snapshot on the resumed server
    committee = resumed.get_committee(recipient.agent, agg.id)
    for clerk_id, _ in committee.clerks_and_keys:
        clients[clerk_id].run_chores(-1)

    output = recipient.reveal_aggregation(agg.id)
    # sum over participants of [1+o, 2+o, 3+o, 4+o], o in 0..2
    np.testing.assert_array_equal(output.positive().values, [6, 9, 12, 15])


@needs_sodium
@pytest.mark.parametrize("backend", ["sqlite", "jsonfs"])
def test_round_survives_server_crash_after_snapshot(backend, tmp_path):
    """Second crash point: snapshot (and its job queue) already durable;
    the resumed server only serves clerking and the reveal."""
    service = _open_server(backend, tmp_path)

    def new_client(svc):
        keystore = MemoryKeystore()
        client = SdaClient(SdaClient.new_agent(keystore), keystore, svc)
        client.upload_agent()
        return client

    recipient = new_client(service)
    recipient_key = recipient.new_encryption_key()
    recipient.upload_encryption_key(recipient_key)
    clients = {recipient.agent.id: recipient}
    for _ in range(GOLDEN.share_count):
        clerk = new_client(service)
        clerk.upload_encryption_key(clerk.new_encryption_key())
        clients[clerk.agent.id] = clerk

    agg = Aggregation(
        id=AggregationId.random(), title="crash-after-snapshot",
        vector_dimension=4, modulus=433,
        recipient=recipient.agent.id, recipient_key=recipient_key,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=GOLDEN,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(agg)
    recipient.begin_aggregation(agg.id)
    for offset in range(2):
        participant = new_client(service)
        participant.participate(
            [1 + offset, 2 + offset, 3 + offset, 4 + offset], agg.id
        )
    recipient.end_aggregation(agg.id)  # snapshot enqueued in life 1

    _drop_server(service)
    del service

    resumed = _open_server(backend, tmp_path)
    for client in clients.values():
        client.service = resumed

    committee = resumed.get_committee(recipient.agent, agg.id)
    for clerk_id, _ in committee.clerks_and_keys:
        clients[clerk_id].run_chores(-1)
    output = recipient.reveal_aggregation(agg.id)
    np.testing.assert_array_equal(output.positive().values, [3, 5, 7, 9])
