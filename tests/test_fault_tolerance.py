"""Fault injection: clerk dropout and quorum reconstruction.

The reference has no fault-injection tests; its resilience is protocol-native
(SURVEY.md §5.3): packed Shamir tolerates clerk loss because a snapshot's
result is ready as soon as ``reconstruction_threshold`` results exist
(server/src/server.rs:115-121) and reconstruction interpolates through an
arbitrary surviving index set (client/src/receive.rs:127-138,
protocol/src/crypto.rs:146-153). These tests exercise exactly that:
kill clerks, assert the round still reveals bit-exactly — or fails closed
when the quorum cannot be met.
"""

import numpy as np
import pytest

from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.client import SdaClient
from sda_tpu.fields import numtheory, oracle
from sda_tpu.protocol import (
    Aggregation,
    AggregationId,
    AgentId,
    EncryptionKeyId,
    FullMasking,
    NoMasking,
    NotFound,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.server import new_memory_server

GOLDEN = PackedShamirSharing(
    secret_count=3, share_count=8, privacy_threshold=4,
    prime_modulus=433, omega_secrets=354, omega_shares=150,
)


# ---------------------------------------------------------------------------
# Kernel-level quorum property: every reconstructing subset is exact

def test_packed_reconstruct_every_minimal_subset():
    """share -> reconstruct == id for ALL size-7 subsets of 8 clerk rows."""
    import itertools

    s = GOLDEN
    rng = np.random.default_rng(7)
    secrets = rng.integers(0, s.prime_modulus, size=11)
    B = -(-len(secrets) // s.secret_count)
    randomness = rng.integers(0, s.prime_modulus, size=(s.privacy_threshold, B))
    shares = oracle.packed_share_from_randomness(secrets, randomness, s)  # [n, B]
    r = s.reconstruction_threshold
    assert r == 7
    for subset in itertools.combinations(range(s.share_count), r):
        got = oracle.packed_reconstruct(
            subset, shares[list(subset)], s, dimension=len(secrets)
        )
        np.testing.assert_array_equal(got, secrets)


def test_packed_reconstruct_below_quorum_rejected():
    s = GOLDEN
    with pytest.raises(ValueError, match="need at least"):
        numtheory.packed_reconstruct_matrix(
            s.secret_count, s.share_count, s.privacy_threshold,
            s.prime_modulus, s.omega_secrets, s.omega_shares,
            tuple(range(s.reconstruction_threshold - 1)),
        )


def test_large_committee_no_reconstruct_recompile(monkeypatch):
    """80-clerk committee (81 = 3^4 share points): reconstruction across
    many different survivor sets/counts must reuse ONE compiled kernel —
    the fixed-survivor truncation (SURVEY §7d) keys the jit on a single
    [r+1, B] shape (round-1 verdict: per-subset shapes would compile-storm
    large committees)."""
    from sda_tpu import fields
    from sda_tpu.crypto import sharing
    from sda_tpu.crypto.sharing import (
        PackedShamirReconstructor, PackedShamirShareGenerator,
    )

    # force the device path: this test measures device-kernel compiles, and
    # the small-work host dispatch would otherwise serve these tiny shapes
    monkeypatch.setattr(sharing, "HOST_PATH_MAX", 0)

    t, p, w2, w3 = numtheory.generate_packed_params(3, 80, 20)
    s = PackedShamirSharing(3, 80, t, p, w2, w3)
    rng = np.random.default_rng(17)
    secrets = rng.integers(0, 433, size=31)
    shares = PackedShamirShareGenerator(s).generate(secrets)
    recon = PackedShamirReconstructor(s, dimension=len(secrets))
    r = s.reconstruction_threshold

    baseline = fields.packed_reconstruct._cache_size()
    for survivors in [
        list(range(80)),                      # everyone
        list(range(1, 80)),                   # one dropout
        sorted(rng.choice(80, size=r + 5, replace=False)),
        sorted(rng.choice(80, size=r, replace=False)),  # exact quorum
        sorted(rng.choice(80, size=r, replace=False)),
    ]:
        got = recon.reconstruct([(i, shares[i]) for i in survivors])
        np.testing.assert_array_equal(got, secrets)
    assert fields.packed_reconstruct._cache_size() == baseline + 1, (
        "reconstruction recompiled for a different survivor set"
    )

    with pytest.raises(ValueError, match="need at least"):
        recon.reconstruct([(i, shares[i]) for i in range(r - 1)])


# ---------------------------------------------------------------------------
# Protocol-level dropout: full loop with killed clerks

needs_sodium = pytest.mark.skipif(not sodium.available(), reason="libsodium not present")


def _new_client(service):
    keystore = MemoryKeystore()
    agent = SdaClient.new_agent(keystore)
    client = SdaClient(agent, keystore, service)
    client.upload_agent()
    return client


def _build_round(service, masking):
    recipient = _new_client(service)
    recipient_key = recipient.new_encryption_key()
    recipient.upload_encryption_key(recipient_key)

    clerks = {}
    for _ in range(GOLDEN.share_count + 1):  # spares: recipient is a candidate too
        clerk = _new_client(service)
        clerk.upload_encryption_key(clerk.new_encryption_key())
        clerks[clerk.agent.id] = clerk
    clerks[recipient.agent.id] = recipient

    aggregation = Aggregation(
        id=AggregationId.random(),
        title="dropout",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=masking,
        committee_sharing_scheme=GOLDEN,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(aggregation)
    recipient.begin_aggregation(aggregation.id)

    for offset in range(2):
        participant = _new_client(service)
        participant.participate([1 + offset, 2 + offset, 3 + offset, 4 + offset],
                                aggregation.id)
    recipient.end_aggregation(aggregation.id)

    committee = service.get_committee(recipient.agent, aggregation.id)
    members = [clerks[cid] for (cid, _) in committee.clerks_and_keys]
    return recipient, aggregation, members


@needs_sodium
@pytest.mark.parametrize("masking", [NoMasking(), FullMasking(433)])
def test_clerk_dropout_at_quorum_reveals_exact(masking):
    """Kill one clerk of 8: 7 results == reconstruction_threshold -> exact."""
    service = new_memory_server()
    recipient, aggregation, members = _build_round(service, masking)

    dead = members[3]  # arbitrary victim; never polls its job
    for clerk in members:
        if clerk is not dead:
            clerk.run_chores(-1)

    status = recipient.service.get_aggregation_status(recipient.agent, aggregation.id)
    snap = status.snapshots[0]
    assert snap.number_of_clerking_results == GOLDEN.reconstruction_threshold
    assert snap.result_ready

    output = recipient.reveal_aggregation(aggregation.id)
    np.testing.assert_array_equal(output.positive().values, [3, 5, 7, 9])


@needs_sodium
def test_clerk_dropout_below_quorum_fails_closed():
    """Kill two clerks of 8: 6 results < threshold -> not ready, no reveal."""
    service = new_memory_server()
    recipient, aggregation, members = _build_round(service, NoMasking())

    for clerk in members[2:]:
        clerk.run_chores(-1)

    status = recipient.service.get_aggregation_status(recipient.agent, aggregation.id)
    snap = status.snapshots[0]
    assert snap.number_of_clerking_results == GOLDEN.reconstruction_threshold - 1
    assert not snap.result_ready
    with pytest.raises(NotFound, match="not ready"):
        recipient.reveal_aggregation(aggregation.id)


@needs_sodium
def test_late_clerk_completes_round_after_not_ready():
    """A straggler clerk finishing later flips the round to ready — the
    reference's stateless re-poll resume model (SURVEY.md §5.4)."""
    service = new_memory_server()
    recipient, aggregation, members = _build_round(service, NoMasking())

    for clerk in members[2:]:
        clerk.run_chores(-1)
    status = recipient.service.get_aggregation_status(recipient.agent, aggregation.id)
    assert not status.snapshots[0].result_ready

    members[0].run_chores(-1)  # straggler wakes up
    status = recipient.service.get_aggregation_status(recipient.agent, aggregation.id)
    assert status.snapshots[0].result_ready
    output = recipient.reveal_aggregation(aggregation.id)
    np.testing.assert_array_equal(output.positive().values, [3, 5, 7, 9])
