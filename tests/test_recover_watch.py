"""benchmarks/recover_watch_records.py — the stranded-evidence replay.

A hardware window that dies mid-suite leaves real measurements inside
HW_WATCH.jsonl's full_run stages; the recovery tool merges them into
BENCH_SUITE.json with provenance. It runs rarely and only after losing a
window, so its parsing/guards are pinned here instead of being trusted to
work the one time they matter.
"""

import importlib.util
import json
import os
import subprocess
import sys

_BENCH_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks")


def _write_watch_log(path, full_runs):
    with open(path, "w") as f:
        for ts, stages in full_runs:
            f.write(json.dumps({"event": "probe", "alive": True,
                                "ts": ts}) + "\n")
            f.write(json.dumps({"event": "full_run", "rc": None, "ts": ts,
                                "stages": stages}) + "\n")


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "recover_watch_records",
        os.path.join(_BENCH_DIR, "recover_watch_records.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_captured_records_newest_window_wins_and_skips_errors(tmp_path):
    tool = _load_tool()
    log = str(tmp_path / "watch.jsonl")
    _write_watch_log(log, [
        ("2026-07-30T15:00:00+00:00", [
            {"suite": {"platform": "tpu", "device_kind": "v5"}},
            {"config": "packed-1m", "value": 1.0, "unit": "el/s",
             "platform": "tpu", "recorded_at": "2026-07-30T15:00:01+00:00"},
            {"config": "lenet-60k", "error": "Boom"},
            {"stage": "sweep", "p_block": 8, "ok": True},  # not a config
        ]),
        ("2026-07-30T18:00:00+00:00", [
            {"suite": {"platform": "tpu", "device_kind": "v5"}},
            {"config": "packed-1m", "value": 2.0, "unit": "el/s",
             "platform": "tpu", "recorded_at": "2026-07-30T18:00:01+00:00"},
        ]),
    ])
    records, meta = tool.captured_records(log)
    assert meta == {"platform": "tpu", "device_kind": "v5"}
    assert len(records) == 1  # error stub and sweep stage excluded
    rec = records[0]
    assert rec["config"] == "packed-1m" and rec["value"] == 2.0
    assert rec["recovered_from"].startswith("HW_WATCH.jsonl full_run")
    # the config's own recorded_at is kept, not the full_run ts
    assert rec["recorded_at"] == "2026-07-30T18:00:01+00:00"


def test_recovery_merge_respects_newer_direct_records(tmp_path):
    """End-to-end through the CLI: a stranded capture must merge, but
    never clobber a direct-run record that is newer than it."""
    log = str(tmp_path / "watch.jsonl")
    _write_watch_log(log, [
        ("2026-07-30T15:00:00+00:00", [
            {"suite": {"platform": "tpu", "device_kind": "v5"}},
            {"config": "packed-1m", "value": 5e9, "unit": "el/s",
             "platform": "tpu", "recorded_at": "2026-07-30T15:00:01+00:00"},
            {"config": "lenet-60k", "value": 8e9, "unit": "el/s",
             "platform": "tpu", "recorded_at": "2026-07-30T15:00:02+00:00"},
        ]),
    ])
    out = str(tmp_path / "BENCH_SUITE.json")
    with open(out, "w") as f:
        json.dump({"suite": {"platform": "tpu"}, "results": [
            # newer direct record than the capture: must survive
            {"config": "packed-1m", "value": 6e9, "platform": "tpu",
             "recorded_at": "2026-07-30T16:00:00+00:00"},
        ]}, f)
    # the tool writes ../BENCH_SUITE.json relative to itself, so run it
    # from a scratch copy of the benchmarks dir
    scratch = tmp_path / "benchmarks"
    scratch.mkdir()
    for name in ("recover_watch_records.py", "suite.py"):
        with open(os.path.join(_BENCH_DIR, name)) as f:
            (scratch / name).write_text(f.read())
    r = subprocess.run(
        [sys.executable, str(scratch / "recover_watch_records.py"),
         "--watch-log", log],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-1500:]
    with open(out) as f:
        results = {x["config"]: x for x in json.load(f)["results"]}
    assert results["packed-1m"]["value"] == 6e9  # newer direct kept
    assert results["lenet-60k"]["value"] == 8e9  # stranded capture merged
    assert "recovered_from" in results["lenet-60k"]
