"""Fast-path rounds (uint32 Solinas) vs generic rounds and the protocol sum.

single_chip_round and SimulatedPod auto-select the fastfield kernels when
the scheme prime qualifies; these tests pin that selection AND that results
stay bit-exact against plain integer aggregation.
"""

import jax
import numpy as np
import pytest

from sda_tpu.fields import fastfield, numtheory
from sda_tpu.mesh import SimulatedPod, make_mesh, single_chip_round
from sda_tpu.protocol import FullMasking, NoMasking, PackedShamirSharing


def fast_scheme():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    assert fastfield.supported(p)
    return PackedShamirSharing(3, 8, t, p, w2, w3)


@pytest.mark.parametrize("masking", ["none", "full"])
def test_single_chip_fast_round_exact(masking):
    s = fast_scheme()
    mask = FullMasking(s.prime_modulus) if masking == "full" else NoMasking()
    fn = jax.jit(single_chip_round(s, mask))
    rng = np.random.default_rng(5)
    inputs = rng.integers(0, 1 << 20, size=(7, 123))
    out = np.asarray(fn(jax.numpy.asarray(inputs), jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


def test_single_chip_fast_round_accepts_uint32_inputs():
    s = fast_scheme()
    fn = jax.jit(single_chip_round(s, FullMasking(s.prime_modulus)))
    rng = np.random.default_rng(6)
    inputs = rng.integers(0, 1 << 20, size=(5, 60)).astype(np.uint32)
    out = np.asarray(fn(jax.numpy.asarray(inputs), jax.random.PRNGKey(4)))
    np.testing.assert_array_equal(out, inputs.astype(np.int64).sum(0) % s.prime_modulus)


def test_single_chip_fast_round_canonicalizes_int32_negatives():
    s = fast_scheme()
    p = s.prime_modulus
    fn = jax.jit(single_chip_round(s, NoMasking()))
    inputs = np.array([[-1, -7, 5, 0], [3, 7, -5, 1]], dtype=np.int32)
    out = np.asarray(fn(jax.numpy.asarray(inputs), jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(out, inputs.astype(np.int64).sum(0) % p)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (1, 8)])
def test_pod_fast_round_exact(mesh_shape):
    s = fast_scheme()
    pod = SimulatedPod(s, FullMasking(s.prime_modulus), mesh=make_mesh(*mesh_shape))
    assert pod._sp is not None, "pod should select the uint32 fast path"
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 1 << 20, size=(16, 48))
    out = np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(9)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_pod_golden_prime_uses_generic_path():
    """p=433 (reference conformance vector) must not enter the fast path and
    must still be exact."""
    s = PackedShamirSharing(3, 8, 4, 433, 354, 150)
    pod = SimulatedPod(s, mesh=make_mesh(8, 1))
    assert pod._sp is None
    rng = np.random.default_rng(8)
    inputs = rng.integers(0, 50, size=(16, 12))
    out = np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(1)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


def test_large_committee_scheme_round():
    """n=26 committee (m3=27, m2=16): generator finds a Solinas prime with
    432 | p-1 and the fast round stays exact at radix-3 scale."""
    t, p, w2, w3 = numtheory.generate_packed_params(11, 26, 26)
    s = PackedShamirSharing(11, 26, t, p, w2, w3)
    assert s.reconstruction_threshold == t + 11 <= 26
    fn = jax.jit(single_chip_round(s, FullMasking(p) if fastfield.supported(p)
                                   else NoMasking()))
    rng = np.random.default_rng(31)
    inputs = rng.integers(0, 1 << 16, size=(4, 11 * 7))
    out = np.asarray(fn(jax.numpy.asarray(inputs), jax.random.PRNGKey(6)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % p)


@pytest.mark.parametrize("masking", ["none", "full", "chacha"])
@pytest.mark.parametrize("dim", [96, 123, 240, 241])  # 96 = exactly 1 tile
def test_single_chip_round_dim_tiled_exact(masking, dim):
    """The dim-tiled schedule (lax.scan over fixed-width tiles) must be
    bit-exact vs plain aggregation for every masking scheme, including a
    ragged last tile and dims off the tile grain. ChaCha pins that each
    tile reads ITS window of the global mask stream (d_block0)."""
    from sda_tpu.protocol import ChaChaMasking

    s = fast_scheme()
    p = s.prime_modulus
    d_cha = -(-dim // 8) * 8  # chacha requires whole 8-dim blocks
    d = d_cha if masking == "chacha" else dim
    mask = {"none": NoMasking(), "full": FullMasking(p),
            "chacha": ChaChaMasking(p, d, 128)}[masking]
    fn = jax.jit(single_chip_round(s, mask, dim_tile=96))
    rng = np.random.default_rng(11)
    inputs = rng.integers(0, 1 << 20, size=(9, d))
    out = np.asarray(fn(jax.numpy.asarray(inputs), jax.random.PRNGKey(7)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % p)


def test_single_chip_round_dim_tile_wider_than_dim_is_untiled():
    s = fast_scheme()
    fn = jax.jit(single_chip_round(s, FullMasking(s.prime_modulus),
                                   dim_tile=4096))
    rng = np.random.default_rng(12)
    inputs = rng.integers(0, 1 << 20, size=(5, 60))
    out = np.asarray(fn(jax.numpy.asarray(inputs), jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


@pytest.mark.parametrize("dim", [384, 250])
def test_pallas_round_dim_tiled_exact(dim):
    """Dim-tiled pallas driver (interpret mode): one kernel round per tile
    scanned over the dim axis, exact incl. ragged tails off the grain."""
    import jax.numpy as jnp

    from sda_tpu.fields.pallas_round import single_chip_round_pallas
    from util import external_bits as ext

    s = fast_scheme()
    p = s.prime_modulus
    rng = np.random.default_rng(13)
    x = rng.integers(0, 1 << 20, size=(6, dim)).astype(np.uint32)
    out = single_chip_round_pallas(
        s, FullMasking(p), tile=128, interpret=True, external_bits_fn=ext,
        dim_tile=96,
    )(jnp.asarray(x), jax.random.PRNGKey(9))
    np.testing.assert_array_equal(
        np.asarray(out), x.astype(np.int64).sum(axis=0) % p)


@pytest.mark.parametrize("P", [1, 2])
def test_single_participant_edge(P):
    """P=1/P=2 rounds: the smallest participant counts exercise pb-clamp
    and single-term folds in every single-chip path."""
    import jax.numpy as jnp

    from sda_tpu.fields.pallas_round import single_chip_round_pallas
    from sda_tpu.mesh import StreamingAggregator

    s = fast_scheme()
    p = s.prime_modulus
    rng = np.random.default_rng(2)
    x = rng.integers(0, 1 << 20, size=(P, 384)).astype(np.uint32)
    exp = x.astype(np.int64).sum(axis=0) % p
    from util import external_bits as ext

    key = jax.random.PRNGKey(1)

    out_xla = jax.jit(single_chip_round(s, FullMasking(p)))(jnp.asarray(x), key)
    out_pl = single_chip_round_pallas(
        s, FullMasking(p), tile=128, interpret=True, external_bits_fn=ext
    )(jnp.asarray(x), key)
    out_st = StreamingAggregator(
        s, FullMasking(p), participants_chunk=1, dim_chunk=96
    ).aggregate(x, key=key)
    for name, out in [("xla", out_xla), ("pallas", out_pl), ("streaming", out_st)]:
        np.testing.assert_array_equal(np.asarray(out), exp, err_msg=name)
