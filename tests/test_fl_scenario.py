"""FL scenario suite (sda_tpu/fl): the canonical workload end-to-end.

Fast tier-1 coverage runs the ``linear`` family over the in-process
memory store — the same driver the ci.sh LeNet drill runs over
HTTP + sqlite with a dead clerk. The contract under test everywhere:
every revealed round is bit-exact vs the plaintext quantized sum of its
frozen participant set, churned devices resolve exactly-once, and the
dropout-weighted update still learns.
"""

import gzip
import json
import struct

import numpy as np
import pytest

from sda_tpu import chaos
from sda_tpu.fl import (
    FLProfile,
    gaussian_accounting,
    load_mnist_idx,
    run_fl,
    shard_dataset,
    synthetic_classification,
)


def _needs_sodium():
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")


# ---------------------------------------------------------------------------
# data shim

def test_synthetic_data_is_seed_deterministic():
    a = synthetic_classification(64, 32, image_shape=(8, 8, 1), seed=9)
    b = synthetic_classification(64, 32, image_shape=(8, 8, 1), seed=9)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    c = synthetic_classification(64, 32, image_shape=(8, 8, 1), seed=10)
    assert not np.array_equal(a[0], c[0])
    # eval drawn after train from one stream: growing train_size must not
    # reshuffle the evaluation set of a fixed seed's run
    assert a[0].dtype == np.float32 and a[1].dtype == np.int32
    assert a[0].shape == (64, 8, 8, 1) and a[2].shape == (32, 8, 8, 1)


def test_shard_dataset_partitions_evenly():
    x = np.arange(50, dtype=np.float32)[:, None]
    y = np.arange(50, dtype=np.int32)
    shards = shard_dataset(x, y, 4, seed=1)
    assert len(shards) == 4
    assert all(len(sx) == 12 for sx, _ in shards)  # remainder dropped
    seen = np.concatenate([sy for _, sy in shards])
    assert len(set(seen.tolist())) == 48  # disjoint
    again = shard_dataset(x, y, 4, seed=1)
    for (sx, sy), (tx, ty) in zip(shards, again):
        np.testing.assert_array_equal(sx, tx)
    with pytest.raises(ValueError, match="shard"):
        shard_dataset(x[:2], y[:2], 4)


def _write_idx_images(path, images, compress=False):
    payload = struct.pack(">IIII", 0x00000803, *images.shape) \
        + images.astype(np.uint8).tobytes()
    opener = gzip.open if compress else open
    with opener(path, "wb") as f:
        f.write(payload)


def _write_idx_labels(path, labels, compress=False):
    payload = struct.pack(">II", 0x00000801, len(labels)) \
        + labels.astype(np.uint8).tobytes()
    opener = gzip.open if compress else open
    with opener(path, "wb") as f:
        f.write(payload)


def test_mnist_idx_loader_roundtrip(tmp_path):
    rng = np.random.default_rng(0)
    train = rng.integers(0, 256, size=(10, 28, 28), dtype=np.uint8)
    test = rng.integers(0, 256, size=(4, 28, 28), dtype=np.uint8)
    # mixed plain/gzip: the loader must find either spelling
    _write_idx_images(tmp_path / "train-images-idx3-ubyte", train)
    _write_idx_labels(tmp_path / "train-labels-idx1-ubyte.gz",
                      np.arange(10) % 10, compress=True)
    _write_idx_images(tmp_path / "t10k-images-idx3-ubyte.gz", test,
                      compress=True)
    _write_idx_labels(tmp_path / "t10k-labels-idx1-ubyte", np.arange(4))
    tx, ty, ex, ey = load_mnist_idx(str(tmp_path), limit=8, eval_limit=3)
    assert tx.shape == (8, 28, 28, 1) and ex.shape == (3, 28, 28, 1)
    assert tx.dtype == np.float32 and float(tx.max()) <= 1.0
    np.testing.assert_array_equal(ty, np.arange(8) % 10)
    assert ey.tolist() == [0, 1, 2]


def test_mnist_idx_loader_missing_files(tmp_path):
    with pytest.raises(FileNotFoundError, match="train-images"):
        load_mnist_idx(str(tmp_path))


# ---------------------------------------------------------------------------
# DP accounting

def test_gaussian_accounting_composition():
    one = gaussian_accounting(2.0, 1, clip=1.0, dim=100)
    ten = gaussian_accounting(2.0, 10, clip=1.0, dim=100)
    assert ten["epsilon"] > one["epsilon"] > 0
    assert ten["rho_zcdp"] == pytest.approx(10 * one["rho_zcdp"])
    quieter = gaussian_accounting(8.0, 10, clip=1.0, dim=100)
    assert quieter["epsilon"] < ten["epsilon"]
    assert one["clip_l2"] == pytest.approx(10.0)  # clip * sqrt(dim)
    with pytest.raises(ValueError, match="sigma"):
        gaussian_accounting(0.0, 1, clip=1.0, dim=4)
    with pytest.raises(ValueError, match="delta"):
        gaussian_accounting(1.0, 1, clip=1.0, dim=4, delta=1.5)


# ---------------------------------------------------------------------------
# churn plan epoch keying

def test_churn_schedule_epoch_key():
    base = chaos.churn_schedule(16, 0.5, seed=3)
    e0 = chaos.churn_schedule(16, 0.5, seed=3, epoch=0)
    e1 = chaos.churn_schedule(16, 0.5, seed=3, epoch=1)
    # per-epoch plans are independent draws but reproducible
    assert e0 != e1
    assert e0 == chaos.churn_schedule(16, 0.5, seed=3, epoch=0)
    assert base == chaos.churn_schedule(16, 0.5, seed=3)  # legacy key stable


def test_poison_schedule_epoch_key():
    """The attacker plan follows churn_schedule's (seed, epoch) key
    discipline on a DISJOINT key, so poisoning and churn compose as
    uncorrelated seeded draws."""
    base = chaos.poison_schedule(16, 0.5, seed=3)
    e0 = chaos.poison_schedule(16, 0.5, seed=3, epoch=0)
    e1 = chaos.poison_schedule(16, 0.5, seed=3, epoch=1)
    assert e0 != e1
    assert e0 == chaos.poison_schedule(16, 0.5, seed=3, epoch=0)
    assert base == chaos.poison_schedule(16, 0.5, seed=3)
    # disjoint from the churn key: same (agents, rate, seed, epoch) must
    # not select the same agents as the churn plan does
    churn = chaos.churn_schedule(16, 0.5, seed=3, epoch=0)
    assert [e["attacker"] for e in e0] != [c["departs"] for c in churn]
    # rate edges and validation
    assert not any(e["attacker"] for e in chaos.poison_schedule(8, 0.0))
    assert all(e["attacker"] for e in chaos.poison_schedule(8, 1.0))
    with pytest.raises(ValueError, match="rate"):
        chaos.poison_schedule(8, 1.5)


def test_parse_poison_kind():
    assert chaos.parse_poison_kind("boost:-8") == {
        "kind": "boost", "factor": -8.0, "trigger_dim": None}
    assert chaos.parse_poison_kind("signflip")["factor"] == -1.0
    assert chaos.parse_poison_kind("backdoor:7")["trigger_dim"] == 7
    for bad in ("boost", "boost:1", "boost:x", "signflip:2",
                "backdoor", "backdoor:-1", "gradient_ascent"):
        with pytest.raises(ValueError):
            chaos.parse_poison_kind(bad)
    # corrupt_delta: boost scales, backdoor is a training-time no-op
    delta = np.array([1.0, -2.0], dtype=np.float32)
    np.testing.assert_array_equal(
        chaos.corrupt_delta(delta, chaos.parse_poison_kind("signflip")),
        -delta)
    np.testing.assert_array_equal(
        chaos.corrupt_delta(delta, chaos.parse_poison_kind("backdoor:0")),
        delta)


# ---------------------------------------------------------------------------
# the scenario driver (linear family, in-process: the tier-1 smoke)

def test_fl_round_trip_with_churn():
    _needs_sodium()
    report = run_fl(FLProfile(participants=5, rounds=2, churn=0.4,
                              target_accuracy=0.5, seed=3))
    assert report["exact"] is True
    assert report["rounds_exact"] == report["rounds_run"] == 2
    assert report["reached_target"] is True
    assert report["leaks"] == 0 and report["client_failures"] == 0
    # the accuracy curve actually learned through the secure rounds
    assert report["final_accuracy"] > report["initial_accuracy"]
    churn = report["churn"]
    assert churn["participants_churned"] >= 1
    assert churn["participants_resumed"] == churn["participants_churned"]
    # every round accounts for the full population: frozen + dropped = P
    for row in report["per_round"]:
        assert row["participations"] + row["dropped"] == 5
    # the record is BENCH-shaped with the lower-is-better tag
    assert report["direction"] == "lower" and report["unit"] == "rounds"
    assert report["value"] == report["rounds_to_target"]
    # scheduler-minted epochs: ids are the deterministic uuid5 sequence
    from sda_tpu.service.scheduler import epoch_aggregation_id

    assert report["per_round"][0]["aggregation"] == str(
        epoch_aggregation_id("fl-3", 0))
    assert report["per_round"][1]["aggregation"] == str(
        epoch_aggregation_id("fl-3", 1))


def test_fl_dead_clerk_degrades_every_round():
    _needs_sodium()
    report = run_fl(FLProfile(participants=4, rounds=2, dead_clerks=1,
                              target_accuracy=0.5, seed=1))
    assert report["exact"] is True
    assert report["degraded_rounds"] == report["rounds_run"] == 2
    assert report["dead_clerks"] and len(report["dead_clerks"]) == 1
    for row in report["per_round"]:
        assert row["state"] == "revealed"  # degraded -> revealed, never hung


def test_fl_is_seed_deterministic_and_dp_noise_is_seeded():
    _needs_sodium()
    profile = FLProfile(participants=4, rounds=2, target_accuracy=0.99,
                        dp_sigma=0.05, seed=11)
    a = run_fl(profile)
    b = run_fl(profile)
    # bit-exactness is checked BEFORE the DP noise (the noise is the
    # recipient's post-processing of the already-verified aggregate)
    assert a["exact"] is True and b["exact"] is True
    assert a["accuracy_by_round"] == b["accuracy_by_round"]
    dp = a["dp"]
    assert dp["sigma"] == 0.05 and dp["epsilon"] > 0
    assert dp["rounds"] == 2
    assert json.dumps(a["dp"])  # the block must be JSON-able


def test_fl_tree_population_mode():
    _needs_sodium()
    report = run_fl(FLProfile(participants=9, rounds=1, tree_group_size=3,
                              target_accuracy=0.5, seed=5))
    assert report["exact"] is True
    assert report["reached_target"] is True
    assert report["per_round"][0]["groups"] >= 2
    assert report["per_round"][0]["depth"] == 2
    assert report["sharing"] == "tree-additive 3"


def test_fl_poisoning_undefended_vs_norm_clip():
    """The A/B at one seed: the same seeded attacker plan degrades the
    undefended run and is absorbed by the codec's norm clip — BOTH stay
    bit-exact (poisoning corrupts inputs, never the protocol) and both
    count every tainted share upload at the clerks."""
    _needs_sodium()
    base = dict(participants=5, rounds=2, target_accuracy=0.9, seed=3,
                poison=0.4)
    undef = run_fl(FLProfile(**base))
    defend = run_fl(FLProfile(**base, norm_clip=0.5))
    for rep in (undef, defend):
        assert rep["exact"] is True
        assert rep["rounds_exact"] == rep["rounds_run"] == 2
        assert rep["client_failures"] == 0
        atk = rep["attack"]
        assert atk["attackers_total"] >= 1
        assert atk["shares_tainted"] == atk["attackers_total"]
        assert atk["out_of_range_detections"] >= atk["attackers_total"]
    # same seeded plan, different outcome: that is the defense
    assert (undef["attack"]["attackers_by_round"]
            == defend["attack"]["attackers_by_round"])
    assert undef["final_accuracy"] < 0.5
    assert defend["final_accuracy"] >= 0.9
    assert undef["attack"]["defended"] is False
    assert defend["attack"]["defended"] is True
    # the quantizer block surfaces the armed defense and its headroom
    q = defend["quantizer"]
    assert q["norm_clip"] == 0.5 and q["headroom_margin"] > 0
    assert q["q_max"] * q["max_summands"] < q["modulus"] // 2


def test_fl_backdoor_reports_attack_success_curve():
    """backdoor:DIM is a training-time attack: main accuracy is not the
    signal — the report must carry the trigger-measured success curve."""
    _needs_sodium()
    report = run_fl(FLProfile(participants=5, rounds=2, poison=0.4,
                              poison_kind="backdoor:3",
                              target_accuracy=0.5, seed=3))
    assert report["exact"] is True
    atk = report["attack"]
    assert atk["parsed"]["trigger_dim"] == 3
    curve = atk["backdoor_success_by_round"]
    assert isinstance(curve, list) and len(curve) == 2  # one per round
    assert all(0.0 <= v <= 1.0 for v in curve)
    assert atk["backdoor_success_final"] == curve[-1]


def test_fl_tree_robust_trimmed_mean():
    """Tree mode with --fl-tree-robust: signflip attackers inside leaf
    groups, the root's per-coordinate trimmed mean over unmasked leaf
    subtotals holds the target where magnitude defenses are blind."""
    _needs_sodium()
    report = run_fl(FLProfile(participants=9, rounds=2, tree_group_size=3,
                              poison=0.25, poison_kind="signflip",
                              tree_robust=True, target_accuracy=0.9,
                              seed=5))
    assert report["exact"] is True and report["reached_target"] is True
    assert ", robust" in report["mode"]
    atk = report["attack"]
    assert atk["tree_robust"] is True and atk["attackers_total"] >= 1
    assert atk["out_of_range_detections"] >= 1
    for row in report["per_round"]:
        assert row["robust_leaves"] == 3


def test_fl_profile_validation():
    _needs_sodium()
    with pytest.raises(ValueError, match="devices"):
        run_fl(FLProfile(participants=1))
    with pytest.raises(ValueError, match="dead clerks"):
        run_fl(FLProfile(tree_group_size=3, dead_clerks=1))
    with pytest.raises(ValueError, match="fleet"):
        run_fl(FLProfile(tree_group_size=3, fleet=2))
    # every rejected knob combination names BOTH knobs in its message
    with pytest.raises(ValueError, match="chaos_rate and tree_group_size"):
        run_fl(FLProfile(tree_group_size=3, chaos_rate=0.1))
    with pytest.raises(ValueError, match="poison"):
        run_fl(FLProfile(poison=1.5))
    with pytest.raises(ValueError, match="tree_robust and tree_group_size"):
        run_fl(FLProfile(tree_robust=True))
    with pytest.raises(ValueError, match="norm_clip"):
        run_fl(FLProfile(norm_clip=-1.0))
    with pytest.raises(ValueError, match="unknown poison kind"):
        run_fl(FLProfile(poison=0.2, poison_kind="explode"))
    with pytest.raises(ValueError, match="mnist_dir"):
        run_fl(FLProfile(family="lenet", dataset="mnist"))
    with pytest.raises(ValueError, match="28x28x1"):
        run_fl(FLProfile(family="linear", dataset="mnist", mnist_dir="/x"))
    with pytest.raises(ValueError, match="unknown family"):
        run_fl(FLProfile(family="resnet"))


def test_fl_http_round_trip():
    """One round over a REAL HTTP server: the wire path (binary codec
    negotiation included) must not change the verdict."""
    _needs_sodium()
    report = run_fl(FLProfile(participants=4, rounds=1, http=True,
                              target_accuracy=0.5, seed=2))
    assert report["exact"] is True and report["reached_target"] is True
    assert "HTTP" in report["mode"]


def test_input_bench_shape():
    """The participate-input bench (satellite of the ndarray pass-through
    fix) reports both rungs; no perf assertion — CI boxes are noisy."""
    _needs_sodium()
    from sda_tpu.loadgen.inputbench import run_input_bench

    report = run_input_bench(dim=2048, repeats=2)
    assert report["dim"] == 2048
    for key in ("convert_list_ms", "convert_array_ms", "seal_list_ms",
                "seal_array_ms", "value"):
        assert isinstance(report[key], (int, float)), key
    assert json.dumps(report)
