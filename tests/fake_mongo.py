"""Minimal in-memory pymongo-compatible fake for exercising the Mongo store.

Implements exactly the surface sda_tpu.server.mongo uses — replace_one /
update_one (upsert, $setOnInsert, matched_count), find/find_one with
sorts, delete_one/many, update_many with $addToSet, count_documents,
find_one_and_update with $set and sort, $or and range operators —
including Mongo's array-field equality semantics ({"snapshots": "x"}
matches documents whose ``snapshots`` list contains "x"). Lets the whole
store test suite run without a mongod; a real deployment uses pymongo.
"""

from __future__ import annotations

import copy
import threading
from typing import Any, Dict, List, Optional


def _matches(doc: Dict[str, Any], query: Dict[str, Any]) -> bool:
    for field, cond in query.items():
        if field == "$or":
            if not any(_matches(doc, sub) for sub in cond):
                return False
            continue
        value = doc.get(field)
        if isinstance(cond, dict):
            for op, arg in cond.items():
                if op == "$regex":
                    import re

                    if not isinstance(value, str) or re.search(arg, value) is None:
                        return False
                elif op == "$in":
                    if value not in arg:
                        return False
                elif op == "$exists":
                    if (field in doc) != bool(arg):
                        return False
                elif op in ("$lte", "$lt", "$gte", "$gt"):
                    if value is None:
                        return False
                    if op == "$lte" and not value <= arg:
                        return False
                    if op == "$lt" and not value < arg:
                        return False
                    if op == "$gte" and not value >= arg:
                        return False
                    if op == "$gt" and not value > arg:
                        return False
                else:
                    raise NotImplementedError(f"fake_mongo: operator {op}")
        elif isinstance(value, list) and not isinstance(cond, list):
            if cond not in value:  # Mongo array-contains equality
                return False
        elif value != cond:
            return False
    return True


def _apply_update(doc: Dict[str, Any], update: Dict[str, Any]) -> None:
    for op, fields in update.items():
        if op == "$set":
            doc.update(fields)
        elif op == "$addToSet":
            for field, item in fields.items():
                arr = doc.setdefault(field, [])
                if item not in arr:
                    arr.append(item)
        elif op == "$setOnInsert":
            pass  # applies only on upsert-insert, handled by update_one
        else:
            raise NotImplementedError(f"fake_mongo: update op {op}")


class _DeleteResult:
    def __init__(self, deleted_count: int):
        self.deleted_count = deleted_count


class _UpdateResult:
    def __init__(self, matched_count: int, upserted_id: Any = None):
        self.matched_count = matched_count
        self.upserted_id = upserted_id


class _Cursor:
    def __init__(self, docs: List[Dict[str, Any]]):
        self._docs = docs

    def sort(self, key_or_list, direction: int = 1) -> "_Cursor":
        keys = (
            key_or_list if isinstance(key_or_list, list)
            else [(key_or_list, direction)]
        )
        docs = self._docs
        for field, d in reversed(keys):
            docs = sorted(docs, key=lambda doc: doc.get(field), reverse=d < 0)
        return _Cursor(docs)

    def __iter__(self):
        return iter(copy.deepcopy(self._docs))


class FakeCollection:
    def __init__(self):
        self._docs: Dict[Any, Dict[str, Any]] = {}
        self._lock = threading.RLock()

    def _find(self, query: Dict[str, Any]) -> List[Dict[str, Any]]:
        return [d for d in self._docs.values() if _matches(d, query)]

    def replace_one(self, filter: Dict[str, Any], doc: Dict[str, Any],
                    upsert: bool = False) -> _UpdateResult:
        with self._lock:
            found = self._find(filter)
            if found:
                self._docs[found[0]["_id"]] = copy.deepcopy(doc)
            elif upsert:
                self._docs[doc["_id"]] = copy.deepcopy(doc)
            return _UpdateResult(matched_count=len(found[:1]))

    def update_one(self, filter: Dict[str, Any], update: Dict[str, Any],
                   upsert: bool = False) -> _UpdateResult:
        with self._lock:
            found = self._find(filter)
            if found:
                _apply_update(self._docs[found[0]["_id"]], update)
                return _UpdateResult(matched_count=1)
            if upsert:
                # insert path: $setOnInsert fields apply, plus filter _id
                doc = dict(update.get("$setOnInsert", {}))
                if "_id" in filter and "_id" not in doc:
                    doc["_id"] = filter["_id"]
                _apply_update(doc, {k: v for k, v in update.items()
                                    if k != "$setOnInsert"})
                self._docs[doc["_id"]] = copy.deepcopy(doc)
                return _UpdateResult(matched_count=0, upserted_id=doc["_id"])
            return _UpdateResult(matched_count=0)

    def find_one(self, query: Dict[str, Any], sort=None) -> Optional[Dict[str, Any]]:
        with self._lock:
            docs = self._find(query)
            if sort:
                docs = list(_Cursor(docs).sort(sort)._docs)
            return copy.deepcopy(docs[0]) if docs else None

    def find(self, query: Optional[Dict[str, Any]] = None) -> _Cursor:
        with self._lock:
            return _Cursor(self._find(query or {}))

    def delete_one(self, query: Dict[str, Any]):
        with self._lock:
            found = self._find(query)[:1]
            for doc in found:
                del self._docs[doc["_id"]]
            return _DeleteResult(len(found))

    def delete_many(self, query: Dict[str, Any]):
        with self._lock:
            found = self._find(query)
            for doc in found:
                del self._docs[doc["_id"]]
            return _DeleteResult(len(found))

    def update_many(self, query: Dict[str, Any], update: Dict[str, Any]):
        with self._lock:
            found = self._find(query)
            for doc in found:
                _apply_update(self._docs[doc["_id"]], update)
            return _UpdateResult(len(found))

    def count_documents(self, query: Dict[str, Any]) -> int:
        with self._lock:
            return len(self._find(query))

    def find_one_and_update(self, query: Dict[str, Any], update: Dict[str, Any],
                            sort=None):
        """Returns the PRE-update document (pymongo default), atomically."""
        with self._lock:
            found = self._find(query)
            if not found:
                return None
            if sort:
                found = list(_Cursor(found).sort(sort)._docs)
            doc = found[0]
            before = copy.deepcopy(doc)
            _apply_update(self._docs[doc["_id"]], update)
            return before


class FakeDatabase:
    def __init__(self):
        self._collections: Dict[str, FakeCollection] = {}
        self._lock = threading.RLock()

    def command(self, name: str):
        if name != "ping":
            raise NotImplementedError(name)
        return {"ok": 1}

    def __getattr__(self, name: str) -> FakeCollection:
        with self._lock:
            if name not in self._collections:
                self._collections[name] = FakeCollection()
            return self._collections[name]
