"""Packed Paillier: the reference's declared-but-disabled scheme, working.

The reference comments out ``AdditiveEncryptionScheme::PackedPaillier``
(protocol/src/crypto.rs:164-174) — here it is implemented for real, so these
tests have no Rust-side conformance anchor beyond the four parameter names
and ``batch_size() == component_count`` (crypto.rs:181-186). Coverage:
number-theory core, packing windows, wire framing, keystore round-trips,
homomorphic combining, and the golden full protocol loop with Paillier in
both encryption slots.
"""

import numpy as np
import pytest

from sda_tpu.client import SdaClient
from sda_tpu.crypto import (
    CryptoModule,
    MemoryKeystore,
    encryption,
    paillier,
    paillier_combine,
    sodium,
)
from sda_tpu.protocol import (
    AdditiveEncryptionScheme,
    AdditiveSharing,
    Aggregation,
    AggregationId,
    AgentId,
    ChaChaMasking,
    EncryptionKey,
    EncryptionKeyId,
    FullMasking,
    PackedPaillierEncryption,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.server import new_memory_server


SCHEME = PackedPaillierEncryption(
    component_count=3, component_bitsize=32, max_value_bitsize=16,
    min_modulus_bitsize=512,
)


@pytest.fixture(scope="module")
def keypair():
    return encryption.new_encryption_keypair(SCHEME)


# ---------------------------------------------------------------------------
# number-theory core

def test_probable_prime_basics():
    assert paillier.is_probable_prime(2)
    assert paillier.is_probable_prime(433)
    assert paillier.is_probable_prime(2**61 - 1)  # Mersenne prime
    assert not paillier.is_probable_prime(1)
    assert not paillier.is_probable_prime(433 * 433)
    assert not paillier.is_probable_prime(2**62 - 1)


def test_random_prime_width():
    p = paillier.random_prime(64)
    assert p.bit_length() == 64
    assert paillier.is_probable_prime(p)


def test_keygen_encrypt_decrypt_roundtrip():
    pk, sk = paillier.keygen(512)
    assert pk.n == sk.p * sk.q
    assert pk.bitsize == 512
    for m in [0, 1, 433, pk.n - 1]:
        assert paillier.decrypt(sk, paillier.encrypt(pk, m)) == m


def test_encryption_is_randomized():
    pk, _ = paillier.keygen(512)
    assert paillier.encrypt(pk, 42) != paillier.encrypt(pk, 42)


def test_homomorphic_addition():
    pk, sk = paillier.keygen(512)
    c = paillier.add(pk, paillier.encrypt(pk, 1000), paillier.encrypt(pk, 2345))
    assert paillier.decrypt(sk, c) == 3345


def test_plaintext_range_enforced():
    pk, _ = paillier.keygen(512)
    with pytest.raises(ValueError):
        paillier.encrypt(pk, pk.n)
    with pytest.raises(ValueError):
        paillier.encrypt(pk, -1)


def test_key_byte_roundtrip():
    pk, sk = paillier.keygen(512)
    assert paillier.PaillierPublicKey.from_bytes(pk.to_bytes()) == pk
    assert paillier.PaillierSecretKey.from_bytes(sk.to_bytes()) == sk


# ---------------------------------------------------------------------------
# packing

def test_pack_unpack_roundtrip():
    values = [0, 65535, 433]
    m = paillier.pack(values, 32)
    assert paillier.unpack(m, 3, 32) == values


def test_pack_rejects_oversized_component():
    with pytest.raises(ValueError):
        paillier.pack([1 << 32], 32)
    with pytest.raises(ValueError):
        paillier.pack([-1], 32)


def test_packed_components_add_independently():
    """Sums stay inside their windows: packed ints add componentwise."""
    a, b = [1, 2, 3], [40, 50, 60]
    total = paillier.pack(a, 32) + paillier.pack(b, 32)
    assert paillier.unpack(total, 3, 32) == [41, 52, 63]


# ---------------------------------------------------------------------------
# scheme serde

def test_scheme_serde_roundtrip():
    obj = SCHEME.to_obj()
    assert obj == {
        "PackedPaillier": {
            "component_count": 3,
            "component_bitsize": 32,
            "max_value_bitsize": 16,
            "min_modulus_bitsize": 512,
        }
    }
    assert AdditiveEncryptionScheme.from_obj(obj) == SCHEME
    assert SCHEME.batch_size == 3  # crypto.rs:181-186
    assert SCHEME.additive_capacity == 1 << 16


def test_scheme_parameter_validation():
    with pytest.raises(ValueError):  # value bound exceeds window
        PackedPaillierEncryption(3, 16, 32, 512)
    with pytest.raises(ValueError):  # plaintext wider than modulus floor
        PackedPaillierEncryption(32, 32, 16, 512)


def test_keystore_serde_roundtrip(keypair, tmp_path):
    from sda_tpu.store import Filebased

    store = Filebased(tmp_path)
    key_id = EncryptionKeyId.random()
    store.put_encryption_keypair(key_id, keypair)
    loaded = store.get_encryption_keypair(key_id)
    assert loaded.ek == keypair.ek
    assert loaded.dk.variant == "PackedPaillier"
    assert loaded.dk.value.data == keypair.dk.value.data


# ---------------------------------------------------------------------------
# encryptor / decryptor seam

def test_share_encrypt_decrypt_roundtrip(keypair):
    keystore = MemoryKeystore()
    key_id = EncryptionKeyId.random()
    keystore.put_encryption_keypair(key_id, keypair)

    shares = [5, 0, 65535, 433, 1]  # not a multiple of component_count
    enc = encryption.new_share_encryptor(keypair.ek, SCHEME).encrypt(shares)
    assert enc.variant == "PackedPaillier"
    out = encryption.new_share_decryptor(key_id, SCHEME, keystore).decrypt(enc)
    np.testing.assert_array_equal(out, shares)


def test_encryptor_rejects_out_of_bound_share(keypair):
    enc = encryption.new_share_encryptor(keypair.ek, SCHEME)
    with pytest.raises(ValueError):
        enc.encrypt([1 << 16])  # max_value_bitsize=16
    with pytest.raises(ValueError):
        enc.encrypt([-1])


def test_encryptor_rejects_undersized_key():
    small = encryption.new_encryption_keypair(
        PackedPaillierEncryption(3, 32, 16, 256)
    )
    with pytest.raises(ValueError):
        encryption.PackedPaillierEncryptor(small.ek, SCHEME)


def test_sodium_key_rejected_for_paillier(keypair):
    if not sodium.available():
        pytest.skip("libsodium not present")
    sodium_kp = encryption.new_encryption_keypair()
    with pytest.raises(ValueError):
        encryption.new_share_encryptor(sodium_kp.ek, SCHEME)
    with pytest.raises(ValueError):
        encryption.new_share_encryptor(keypair.ek, SodiumEncryption())


# ---------------------------------------------------------------------------
# homomorphic combining — the point of the scheme

def test_homomorphic_share_combine(keypair):
    keystore = MemoryKeystore()
    key_id = EncryptionKeyId.random()
    keystore.put_encryption_keypair(key_id, keypair)
    enc = encryption.new_share_encryptor(keypair.ek, SCHEME)

    rng = np.random.default_rng(7)
    vectors = rng.integers(0, 433, size=(5, 7))
    combined = paillier_combine(
        keypair.ek, SCHEME, [enc.encrypt(v) for v in vectors]
    )
    out = encryption.new_share_decryptor(key_id, SCHEME, keystore).decrypt(combined)
    # integer sums (no window overflow), so the modular sum is recoverable
    np.testing.assert_array_equal(out, vectors.sum(axis=0))
    np.testing.assert_array_equal(out % 433, vectors.sum(axis=0) % 433)


def test_combine_enforces_additive_capacity(keypair):
    tight = PackedPaillierEncryption(3, 17, 16, 512)  # capacity 2^1
    enc = encryption.new_share_encryptor(keypair.ek, tight)
    batches = [enc.encrypt([1, 2, 3]) for _ in range(3)]
    with pytest.raises(ValueError):
        paillier_combine(keypair.ek, tight, batches)


def test_combine_capacity_survives_nesting(keypair):
    """Summand counts ride the wire frame: incremental acc = combine(acc, new)
    cannot sneak past the window-overflow bound."""
    tight = PackedPaillierEncryption(3, 17, 16, 512)  # capacity 2
    enc = encryption.new_share_encryptor(keypair.ek, tight)
    acc = paillier_combine(
        keypair.ek, tight, [enc.encrypt([1, 2, 3]), enc.encrypt([4, 5, 6])]
    )
    with pytest.raises(ValueError):  # 2 + 1 accumulated summands > 2
        paillier_combine(keypair.ek, tight, [acc, enc.encrypt([7, 8, 9])])

    # incremental combining up to exactly the capacity stays exact
    roomy = PackedPaillierEncryption(3, 32, 16, 512)
    enc2 = encryption.new_share_encryptor(keypair.ek, roomy)
    keystore = MemoryKeystore()
    key_id = EncryptionKeyId.random()
    keystore.put_encryption_keypair(key_id, keypair)
    acc2 = enc2.encrypt([1, 1, 1])
    for _ in range(4):
        acc2 = paillier_combine(keypair.ek, roomy, [acc2, enc2.encrypt([1, 1, 1])])
    out = encryption.new_share_decryptor(key_id, roomy, keystore).decrypt(acc2)
    np.testing.assert_array_equal(out, [5, 5, 5])


def test_decryptor_rejects_truncated_payloads(keypair):
    from sda_tpu.protocol import Binary, Encryption

    keystore = MemoryKeystore()
    key_id = EncryptionKeyId.random()
    keystore.put_encryption_keypair(key_id, keypair)
    dec = encryption.new_share_decryptor(key_id, SCHEME, keystore)

    enc = encryption.new_share_encryptor(keypair.ek, SCHEME).encrypt([1, 2, 3])
    truncated = Encryption("PackedPaillier", Binary(enc.value.data[:-4]))
    with pytest.raises(ValueError):  # frame declares more bytes than remain
        dec.decrypt(truncated)
    with pytest.raises(ValueError):  # empty payload: truncated varint
        dec.decrypt(Encryption("PackedPaillier", Binary(b"")))
    with pytest.raises(ValueError):  # unterminated varint
        dec.decrypt(Encryption("PackedPaillier", Binary(b"\x80" * 12)))


def test_combine_rejects_wrong_key_variant(keypair):
    if not sodium.available():
        pytest.skip("libsodium not present")
    enc = encryption.new_share_encryptor(keypair.ek, SCHEME).encrypt([1, 2, 3])
    sodium_kp = encryption.new_encryption_keypair()
    with pytest.raises(ValueError):
        paillier_combine(sodium_kp.ek, SCHEME, [enc])


def test_decryption_key_rejects_unknown_variant():
    from sda_tpu.crypto import DecryptionKey

    with pytest.raises(ValueError):
        DecryptionKey("PackedRSA", None)
    with pytest.raises(ValueError):
        DecryptionKey.from_obj({"sodium": "AAAA"})


def test_combine_rejects_mismatched_batches(keypair):
    enc = encryption.new_share_encryptor(keypair.ek, SCHEME)
    with pytest.raises(ValueError):
        paillier_combine(
            keypair.ek, SCHEME, [enc.encrypt([1, 2, 3]), enc.encrypt([1, 2])]
        )
    with pytest.raises(ValueError):
        paillier_combine(keypair.ek, SCHEME, [])


# ---------------------------------------------------------------------------
# golden full loop, Paillier in both encryption slots (full_loop.rs shape)

@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
@pytest.mark.parametrize(
    "sharing, masking, recipient_scheme",
    [
        (AdditiveSharing(share_count=3, modulus=433), FullMasking(433), SCHEME),
        (PackedShamirSharing(3, 8, 4, 433, 354, 150), FullMasking(433), SCHEME),
        # ChaCha "masks" on the recipient slot are 32-bit seed words, so
        # that slot needs a >= 32-bit fresh-value window
        (
            PackedShamirSharing(3, 8, 4, 433, 354, 150),
            ChaChaMasking(433, 4, 128),
            PackedPaillierEncryption(3, 33, 32, 512),
        ),
    ],
    ids=["additive", "packed-shamir", "chacha-mask"],
)
def test_full_loop_with_paillier_encryption(sharing, masking, recipient_scheme):
    service = new_memory_server()

    def new_client():
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        return SdaClient(agent, keystore, service)

    recipient = new_client()
    recipient_key = recipient.new_encryption_key(recipient_scheme)
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)

    aggregation = Aggregation(
        id=AggregationId.random(),
        title="paillier loop",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=masking,
        committee_sharing_scheme=sharing,
        recipient_encryption_scheme=recipient_scheme,
        committee_encryption_scheme=SCHEME,
    )
    recipient.upload_aggregation(aggregation)

    clerks = [new_client() for _ in range(8)]
    for clerk in clerks:
        clerk.upload_agent()
        clerk.upload_encryption_key(clerk.new_encryption_key(SCHEME))

    recipient.begin_aggregation(aggregation.id)

    for _ in range(2):
        participant = new_client()
        participant.upload_agent()
        participant.participate([1, 2, 3, 4], aggregation.id)

    recipient.end_aggregation(aggregation.id)
    recipient.run_chores(-1)
    for clerk in clerks:
        clerk.run_chores(-1)

    output = recipient.reveal_aggregation(aggregation.id)
    np.testing.assert_array_equal(output.positive().values, [2, 4, 6, 8])


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
@pytest.mark.parametrize("device", [False, True], ids=["host", "device"])
@pytest.mark.parametrize("capacity_bits", [16, 1], ids=["one-batch", "chunked"])
def test_server_premixes_paillier_clerk_columns(capacity_bits, device,
                                                monkeypatch):
    """Opt-in broker premixing: with PackedPaillier committee encryption the
    snapshot combines each clerk's ciphertext column homomorphically, so a
    clerk downloads ceil(N/capacity) batches instead of N — and the round
    stays exact. capacity 2^1 forces the chunked path (5 participants ->
    3 combined batches). The device variant routes the fold through the
    limb-Montgomery kernel (folds below the size floor stay on host —
    the protocol outcome must be identical either way)."""
    if device:
        monkeypatch.setenv("SDA_PREMIX_DEVICE", "1")
        monkeypatch.setattr(
            "sda_tpu.crypto.encryption._DEVICE_PREMIX_MIN_MODMULS", 1)
    else:
        monkeypatch.delenv("SDA_PREMIX_DEVICE", raising=False)
    service = new_memory_server()
    service.server.premix_paillier = True
    scheme = PackedPaillierEncryption(3, 16 + capacity_bits, 16, 512)

    def new_client():
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        return SdaClient(agent, keystore, service)

    recipient = new_client()
    recipient_key = recipient.new_encryption_key(SCHEME)
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)
    aggregation = Aggregation(
        id=AggregationId.random(),
        title="premix",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SCHEME,
        committee_encryption_scheme=scheme,
    )
    recipient.upload_aggregation(aggregation)
    clerks = [new_client() for _ in range(3)]
    for clerk in clerks:
        clerk.upload_agent()
        clerk.upload_encryption_key(clerk.new_encryption_key(scheme))
    recipient.begin_aggregation(aggregation.id)

    n_participants = 5
    rng = np.random.default_rng(11)
    vectors = rng.integers(0, 433, size=(n_participants, 4))
    for v in vectors:
        participant = new_client()
        participant.upload_agent()
        participant.participate([int(x) for x in v], aggregation.id)
    recipient.end_aggregation(aggregation.id)

    # inspect the enqueued jobs BEFORE clerking: columns must be premixed
    capacity = scheme.additive_capacity
    expected_batches = -(-n_participants // capacity)
    store = service.server.clerking_job_store
    seen_jobs = 0
    for clerk in clerks + [recipient]:
        job = store.poll_clerking_job(clerk.agent.id)
        if job is None:
            continue
        seen_jobs += 1
        assert len(job.encryptions) == expected_batches, (
            f"clerk column not premixed: {len(job.encryptions)} batches"
        )
    assert seen_jobs == 3

    recipient.run_chores(-1)
    for clerk in clerks:
        clerk.run_chores(-1)
    output = recipient.reveal_aggregation(aggregation.id)
    np.testing.assert_array_equal(
        output.positive().values, vectors.sum(axis=0) % 433
    )


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_premix_flag_leaves_sodium_aggregations_untouched():
    service = new_memory_server()
    service.server.premix_paillier = True
    # reuse the standard sodium full loop via the shared helper
    import test_full_loop as fl

    fl.check_full_aggregation(fl.agg_default(), service)


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_premix_survives_malformed_participation():
    """Untrusted uploads can't wedge the snapshot: a forged ciphertext frame
    makes the server skip premixing for the affected columns (enqueued
    unmixed) instead of failing the recipient's end_aggregation."""
    from sda_tpu.protocol import Binary, Encryption

    service = new_memory_server()
    service.server.premix_paillier = True

    def new_client():
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        return SdaClient(agent, keystore, service)

    recipient = new_client()
    recipient_key = recipient.new_encryption_key(SCHEME)
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)
    aggregation = Aggregation(
        id=AggregationId.random(),
        title="premix-hostile",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SCHEME,
        committee_encryption_scheme=SCHEME,
    )
    recipient.upload_aggregation(aggregation)
    clerks = [new_client() for _ in range(3)]
    for clerk in clerks:
        clerk.upload_agent()
        clerk.upload_encryption_key(clerk.new_encryption_key(SCHEME))
    recipient.begin_aggregation(aggregation.id)

    honest = new_client()
    honest.upload_agent()
    honest.participate([1, 2, 3, 4], aggregation.id)

    # hostile participant: clone an honest participation shape but replace
    # every clerk encryption with a frame claiming capacity summands
    hostile = new_client()
    hostile.upload_agent()
    participation = hostile.new_participation([5, 6, 7, 8], aggregation.id)
    forged = bytes([3, 0x7F]) + bytes(8)  # count=3, summands=127 -> huge varint ok
    participation.clerk_encryptions = [
        (cid, Encryption("PackedPaillier", Binary(forged)))
        for (cid, _) in participation.clerk_encryptions
    ]
    service.create_participation(hostile.agent, participation)

    # the snapshot must still succeed — columns fall back to unmixed
    recipient.end_aggregation(aggregation.id)
    store = service.server.clerking_job_store
    job = store.poll_clerking_job(clerks[0].agent.id)
    if job is None:
        job = store.poll_clerking_job(recipient.agent.id)
    assert job is not None
    assert len(job.encryptions) == 2  # unmixed: one per participation


def test_crt_decrypt_matches_textbook():
    """The CRT shortcut must agree with the textbook lambda/mu path."""
    pk, sk = paillier.keygen(512)
    n, n2 = pk.n, pk.n_squared
    lam = (sk.p - 1) * (sk.q - 1) // __import__("math").gcd(sk.p - 1, sk.q - 1)
    mu = pow((pow(1 + n, lam, n2) - 1) // n, -1, n)

    rng = np.random.default_rng(23)
    for _ in range(25):
        m = int(rng.integers(0, 1 << 62)) * int(rng.integers(1, 1 << 60)) % n
        c = paillier.encrypt(pk, m)
        textbook = (pow(c, lam, n2) - 1) // n * mu % n
        assert paillier.decrypt(sk, c) == textbook == m


def test_unframe_fuzz_never_crashes():
    """Random garbage payloads must raise ValueError (or parse), never
    IndexError/OverflowError/hang."""
    from sda_tpu.crypto.encryption import _unframe_paillier

    rng = np.random.default_rng(31)
    for size in [0, 1, 2, 7, 64, 512]:
        for _ in range(50):
            raw = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
            try:
                count, summands, cts = _unframe_paillier(raw)
                assert count >= 0 and summands >= 1
            except ValueError:
                pass


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_premix_over_http_seam(tmp_path):
    """Premixing is transparent at the REST seam: clerks polling over HTTP
    receive the homomorphically combined batches and the round stays exact."""
    from sda_tpu.http.client import SdaHttpClient
    from sda_tpu.http.server import SdaHttpServer
    from sda_tpu.store import Filebased

    service = new_memory_server()
    service.server.premix_paillier = True
    httpd = SdaHttpServer(service, bind="127.0.0.1:0").start_background()
    try:
        def new_client(name):
            ks = Filebased(tmp_path / name)
            agent = SdaClient.new_agent(ks)
            return SdaClient(agent, ks, SdaHttpClient(httpd.address, ks))

        recipient = new_client("recipient")
        recipient_key = recipient.new_encryption_key(SCHEME)
        recipient.upload_agent()
        recipient.upload_encryption_key(recipient_key)
        aggregation = Aggregation(
            id=AggregationId.random(),
            title="premix-http",
            vector_dimension=4,
            modulus=433,
            recipient=recipient.agent.id,
            recipient_key=recipient_key,
            masking_scheme=FullMasking(433),
            committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
            recipient_encryption_scheme=SCHEME,
            committee_encryption_scheme=SCHEME,
        )
        recipient.upload_aggregation(aggregation)
        clerks = [new_client(f"clerk-{i}") for i in range(3)]
        for clerk in clerks:
            clerk.upload_agent()
            clerk.upload_encryption_key(clerk.new_encryption_key(SCHEME))
        recipient.begin_aggregation(aggregation.id)
        for i in range(4):
            participant = new_client(f"p-{i}")
            participant.upload_agent()
            participant.participate([i, 2, 3, 4], aggregation.id)
        recipient.end_aggregation(aggregation.id)

        # each elected clerk's job, fetched over REST, holds ONE premixed
        # batch (election picks 3 of the 4 paillier-keyed agents — the
        # recipient is eligible too — in store-dependent order)
        premixed_jobs = 0
        for member in clerks + [recipient]:
            polled = service.get_clerking_job(member.agent, member.agent.id)
            if polled is not None:
                assert len(polled.encryptions) == 1
                premixed_jobs += 1
        assert premixed_jobs == 3

        recipient.run_chores(-1)
        for clerk in clerks:
            clerk.run_chores(-1)
        output = recipient.reveal_aggregation(aggregation.id)
        np.testing.assert_array_equal(
            output.positive().values, [6 % 433, 8, 12, 16]
        )
    finally:
        httpd.shutdown()


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_committee_election_filters_by_key_variant():
    """A Paillier aggregation must not elect Sodium-keyed clerks (they
    could never decrypt their jobs); election skips them and fails
    loudly when too few matching candidates exist."""
    service = new_memory_server()

    def new_client():
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        return SdaClient(agent, keystore, service)

    recipient = new_client()
    recipient_key = recipient.new_encryption_key(SCHEME)
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)
    aggregation = Aggregation(
        id=AggregationId.random(),
        title="election",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SCHEME,
        committee_encryption_scheme=SCHEME,
    )
    recipient.upload_aggregation(aggregation)

    # 3 sodium-keyed decoys + (recipient + 1) paillier-keyed candidates:
    # one short of the 3-clerk committee -> loud error
    paillier_clerks = []
    for _ in range(3):
        decoy = new_client()
        decoy.upload_agent()
        decoy.upload_encryption_key(decoy.new_encryption_key())
    clerk = new_client()
    clerk.upload_agent()
    clerk.upload_encryption_key(clerk.new_encryption_key(SCHEME))
    paillier_clerks.append(clerk)
    from sda_tpu.protocol import NotFound

    with pytest.raises(NotFound, match="PackedPaillier"):
        recipient.begin_aggregation(aggregation.id)

    # a third matching candidate arrives — holding BOTH key types, so the
    # election must pick its PAILLIER key id, not just the right agent
    third = new_client()
    third.upload_agent()
    third.upload_encryption_key(third.new_encryption_key())  # sodium decoy key
    third_paillier_key = third.new_encryption_key(SCHEME)
    third.upload_encryption_key(third_paillier_key)
    paillier_clerks.append(third)
    recipient.begin_aggregation(aggregation.id)
    committee = service.get_committee(recipient.agent, aggregation.id)
    eligible = {c.agent.id for c in paillier_clerks} | {recipient.agent.id}
    elected = dict(committee.clerks_and_keys)
    # exactly 3 eligible agents for a 3-clerk committee: all must be in,
    # and the dual-keyed agent must be paired with its PAILLIER key id
    assert set(elected) == eligible
    assert elected[third.agent.id] == third_paillier_key


def test_combine_device_premix_bit_identical(keypair, monkeypatch, caplog):
    """SDA_PREMIX_DEVICE=1 routes the fold through the batched limb-
    Montgomery kernel — the framed ciphertext product must be BYTE-
    identical to the host fold (the clerk-side flow decrypts whatever the
    broker enqueued; a single differing limb corrupts share sums). A
    device failure would silently fall back to the host fold and make
    this comparison vacuous, so the fallback warning is asserted ABSENT."""
    import logging

    enc = encryption.new_share_encryptor(keypair.ek, SCHEME)
    rng = np.random.default_rng(23)
    vectors = rng.integers(0, 433, size=(9, 24))
    batches = [enc.encrypt(v) for v in vectors]
    monkeypatch.delenv("SDA_PREMIX_DEVICE", raising=False)
    host = paillier_combine(keypair.ek, SCHEME, batches)
    monkeypatch.setenv("SDA_PREMIX_DEVICE", "1")
    with caplog.at_level(logging.WARNING):
        dev = paillier_combine(keypair.ek, SCHEME, batches)
    assert not any("falling back to host fold" in r.message
                   for r in caplog.records), "device kernel never ran"
    assert dev.value.data == host.value.data


def test_combine_device_premix_chunked_partials(keypair, monkeypatch, caplog):
    """Row counts above the chunk bound fold chunk products of products —
    still byte-identical (identity-ciphertext padding never shows)."""
    import logging

    from sda_tpu.crypto import encryption as enc_mod

    enc = encryption.new_share_encryptor(keypair.ek, SCHEME)
    rng = np.random.default_rng(29)
    vectors = rng.integers(0, 433, size=(11, 24))
    batches = [enc.encrypt(v) for v in vectors]
    host = paillier_combine(keypair.ek, SCHEME, batches)
    monkeypatch.setenv("SDA_PREMIX_DEVICE", "1")
    monkeypatch.setattr(enc_mod, "_DEVICE_PREMIX_CHUNK_ROWS", 4)
    with caplog.at_level(logging.WARNING):
        dev = paillier_combine(keypair.ek, SCHEME, batches)
    assert not any("falling back to host fold" in r.message
                   for r in caplog.records), "device kernel never ran"
    assert dev.value.data == host.value.data


def test_combine_device_premix_falls_back_on_device_failure(
        keypair, monkeypatch, caplog):
    """A broken device path must degrade to the host fold with a warning,
    never a wrong or missing result (premixing is an optimization)."""
    import logging

    from sda_tpu.crypto import encryption as enc_mod

    enc = encryption.new_share_encryptor(keypair.ek, SCHEME)
    vectors = np.arange(9 * 24).reshape(9, 24) % 433
    batches = [enc.encrypt(v) for v in vectors]
    host = paillier_combine(keypair.ek, SCHEME, batches)
    monkeypatch.setenv("SDA_PREMIX_DEVICE", "1")

    def boom(pk, rows):
        raise RuntimeError("no device")

    monkeypatch.setattr(enc_mod, "_device_premix_rows", boom)
    with caplog.at_level(logging.WARNING):
        dev = paillier_combine(keypair.ek, SCHEME, batches)
    assert dev.value.data == host.value.data
    assert any("falling back to host fold" in r.message for r in caplog.records)
