"""Profiler-trace parsing for the hardware timing cross-check
(utils/traceparse.py; consumed by benchmarks/hw_check.py trace_check)."""

import gzip
import json

import jax
import numpy as np

from sda_tpu.utils import traceparse


def synthetic_trace():
    """A Chrome trace shaped like an XProf capture: one TPU device lane
    (pid 2) plus host lanes that must be ignored."""
    return {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "name": "process_name", "pid": 3,
         "args": {"name": "python"}},
        # device lane: 3 executions of the round module + an unrelated op
        {"ph": "X", "pid": 2, "tid": 1, "name": "jit_round_fn",
         "ts": 0, "dur": 900.0},
        {"ph": "X", "pid": 2, "tid": 1, "name": "jit_round_fn",
         "ts": 1000, "dur": 1000.0},
        {"ph": "X", "pid": 2, "tid": 1, "name": "jit_round_fn",
         "ts": 2100, "dur": 1100.0},
        {"ph": "X", "pid": 2, "tid": 1, "name": "jit_tiny_fetch",
         "ts": 3300, "dur": 5.0},
        # host event with a jit-ish name: wrong lane, must not count
        {"ph": "X", "pid": 1, "tid": 9, "name": "jit_round_fn",
         "ts": 0, "dur": 99999.0},
    ]}


def test_device_lane_detection_and_stats():
    tr = synthetic_trace()
    assert traceparse.device_lane_pids(tr) == {2: "/device:TPU:0"}
    stats = traceparse.device_module_stats(tr)
    assert set(stats) == {"jit_round_fn", "jit_tiny_fetch"}
    assert stats["jit_round_fn"]["count"] == 3
    assert stats["jit_round_fn"]["median_us"] == 1000.0
    assert stats["jit_round_fn"]["total_us"] == 3000.0
    assert traceparse.dominant_module(stats) == "jit_round_fn"

    # even-length lists take the midpoint average (hw_check traces an even
    # number of dispatches, so every real run hits this case)
    tr["traceEvents"].append({"ph": "X", "pid": 2, "tid": 1,
                              "name": "jit_round_fn", "ts": 4000, "dur": 100.0})
    stats = traceparse.device_module_stats(tr)
    assert stats["jit_round_fn"]["count"] == 4
    assert stats["jit_round_fn"]["median_us"] == 950.0  # (900+1000)/2


def test_no_device_lane_is_empty_not_error():
    tr = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU"}},
        {"ph": "X", "pid": 1, "tid": 1, "name": "jit_x", "ts": 0, "dur": 1.0},
    ]}
    assert traceparse.device_module_stats(tr) == {}
    assert traceparse.dominant_module({}) is None


def test_load_latest_trace_roundtrip(tmp_path):
    d = tmp_path / "plugins" / "profile" / "2026_01_01"
    d.mkdir(parents=True)
    tr = synthetic_trace()
    with gzip.open(d / "host.trace.json.gz", "wt") as f:
        json.dump(tr, f)
    loaded = traceparse.load_latest_trace(str(tmp_path))
    assert loaded == tr
    assert traceparse.load_latest_trace(str(tmp_path / "empty")) is None


def test_real_cpu_capture_has_no_device_lane(tmp_path):
    """A real jax.profiler capture on the CPU backend parses cleanly and
    reports no accelerator lane — the hw_check stage's advisory path."""
    fn = jax.jit(lambda x: (x @ x).sum())
    x = jax.numpy.ones((64, 64))
    jax.block_until_ready(fn(x))
    logdir = str(tmp_path / "trace")
    with jax.profiler.trace(logdir):
        jax.block_until_ready(fn(x))
    tr = traceparse.load_latest_trace(logdir)
    assert tr is not None and "traceEvents" in tr
    assert traceparse.dominant_module(traceparse.device_module_stats(tr)) is None
