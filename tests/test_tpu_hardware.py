"""Real-TPU smoke tests (env-gated; the suite itself is CPU-hermetic).

Run with ``SDA_TEST_TPU=1 pytest tests/test_tpu_hardware.py`` on a machine
with a live chip. Each test runs in a subprocess because the conftest pins
this interpreter to the virtual-CPU mesh and backends cannot be swapped
reliably mid-suite; the subprocess selects the TPU programmatically
(utils/backend.py) and asserts exactness on hardware — the one thing the
interpret-mode Pallas tests (test_pallas_round.py) cannot cover.
"""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("SDA_TEST_TPU") != "1",
    reason="real-TPU smoke tests need SDA_TEST_TPU=1 and a live chip",
)

_CHECK = """
import numpy as np
from sda_tpu.utils.backend import use_platform
use_platform("axon")
import jax, jax.numpy as jnp
from sda_tpu.fields import numtheory
from sda_tpu.fields.pallas_round import single_chip_round_pallas
from sda_tpu.mesh import SimulatedPod, StreamingAggregator, make_mesh, single_chip_round
from sda_tpu.protocol import ChaChaMasking, FullMasking, PackedShamirSharing

t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
scheme = PackedShamirSharing(3, 8, t, p, w2, w3)
rng = np.random.default_rng(7)
inputs = jnp.asarray(rng.integers(0, 1 << 20, size=(24, 6144), dtype=np.uint32))
key = jax.random.PRNGKey(5)
expected = np.asarray(inputs).sum(axis=0) % p
for build in (single_chip_round, single_chip_round_pallas):
    fn = jax.jit(build(scheme, FullMasking(p)))
    out = jax.device_get(fn(inputs, key))
    assert np.array_equal(out, expected), f"{build.__name__} wrong on TPU"
# device-ChaCha seed masks and the degenerate 1x1 pod, on hardware
fnc = jax.jit(single_chip_round(scheme, ChaChaMasking(p, 6144, 128)))
assert np.array_equal(jax.device_get(fnc(inputs, key)), expected)
pod = SimulatedPod(scheme, FullMasking(p), mesh=make_mesh(1, 1))
assert np.array_equal(np.asarray(pod.aggregate(np.asarray(inputs), key=key)), expected)
agg = StreamingAggregator(scheme, ChaChaMasking(p, 6144, 128),
                          participants_chunk=8, dim_chunk=3072)
assert np.array_equal(agg.aggregate(np.asarray(inputs), key=key), expected)
print("TPU_EXACT_OK")
"""


def test_rounds_exact_on_hardware():
    r = subprocess.run(
        [sys.executable, "-c", _CHECK], capture_output=True, text=True,
        timeout=420,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "TPU_EXACT_OK" in r.stdout
