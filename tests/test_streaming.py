"""Streamed (chunked) rounds: exactness across tilings, paths, maskings.

The streaming driver must produce the exact participant-sum regardless of
how the [P, d] matrix is tiled — including remainder chunks on both axes —
on both the uint32 Solinas fast path and the generic s64 path.
"""

import jax
import numpy as np
import pytest

from sda_tpu.fields import fastfield, numtheory
from sda_tpu.mesh import (
    StreamingAggregator,
    array_block_provider,
    synthetic_block_provider,
)
from sda_tpu.protocol import FullMasking, NoMasking, PackedShamirSharing

GOLDEN = PackedShamirSharing(3, 8, 4, 433, 354, 150)  # generic path


def fast_scheme():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    assert fastfield.supported(p)
    return PackedShamirSharing(3, 8, t, p, w2, w3)


@pytest.mark.parametrize("scheme_kind", ["fast", "generic"])
@pytest.mark.parametrize("masking", ["none", "full"])
@pytest.mark.parametrize("P,d,pc,dc", [
    (10, 60, 4, 30),    # remainder on the participant axis
    (8, 50, 8, 21),     # remainder on the dim axis (21 % 3 == 0)
    (7, 33, 3, 12),     # remainders on both
    (5, 12, 64, 3 << 20),  # single block
])
def test_streaming_exact(scheme_kind, masking, P, d, pc, dc):
    scheme = fast_scheme() if scheme_kind == "fast" else GOLDEN
    p = scheme.prime_modulus
    mask = FullMasking(p) if masking == "full" else NoMasking()
    agg = StreamingAggregator(scheme, mask, participants_chunk=pc, dim_chunk=dc)
    assert (agg._sp is not None) == (scheme_kind == "fast")
    rng = np.random.default_rng(11)
    inputs = rng.integers(0, min(p, 1 << 20), size=(P, d))
    out = agg.aggregate(inputs, key=jax.random.PRNGKey(2))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % p)


def test_streaming_matches_block_provider_forms():
    scheme = fast_scheme()
    agg = StreamingAggregator(scheme, FullMasking(scheme.prime_modulus),
                              participants_chunk=3, dim_chunk=9)
    rng = np.random.default_rng(13)
    inputs = rng.integers(0, 1 << 16, size=(7, 21))
    direct = agg.aggregate(inputs, key=jax.random.PRNGKey(5))
    via_provider = StreamingAggregator(
        scheme, FullMasking(scheme.prime_modulus),
        participants_chunk=3, dim_chunk=9,
    ).aggregate_blocks(array_block_provider(inputs), 7, 21, jax.random.PRNGKey(5))
    np.testing.assert_array_equal(direct, via_provider)


def test_synthetic_provider_consistent_across_tilings():
    """The virtual matrix must not depend on the tiling used to read it."""
    prov = synthetic_block_provider(modulus=433, seed=9)
    whole = prov(0, 6, 0, 12)
    by_rows = np.concatenate([prov(0, 3, 0, 12), prov(3, 6, 0, 12)], axis=0)
    by_cols = np.concatenate([prov(0, 6, 0, 5), prov(0, 6, 5, 12)], axis=1)
    np.testing.assert_array_equal(whole, by_rows)
    np.testing.assert_array_equal(whole, by_cols)
    assert whole.min() >= 0 and whole.max() < 433
    # and streamed aggregation over it is exact
    scheme = GOLDEN
    agg = StreamingAggregator(scheme, participants_chunk=4, dim_chunk=6)
    out = agg.aggregate_blocks(prov, 6, 12, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(out, prov(0, 6, 0, 12).sum(axis=0) % 433)


def test_dim_chunk_rounds_up_to_scheme_grain():
    # misaligned tile sizes round up to the packing (and, with ChaCha,
    # the 8-word block) grain instead of erroring
    assert StreamingAggregator(GOLDEN, dim_chunk=10).dim_chunk == 12
    from sda_tpu.protocol import ChaChaMasking

    agg = StreamingAggregator(
        GOLDEN, ChaChaMasking(433, 100, 128), dim_chunk=10
    )
    assert agg.dim_chunk == 24  # lcm(secret_count=3, chacha block 8)


# ---------------------------------------------------------------------------
# StreamedPod: streamed x multi-chip composition

needs8 = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)

from util import scheme_lattice_config as _streamed_config


@needs8
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2)])
@pytest.mark.parametrize("config", ["shamir-full", "add-chacha", "basic-chacha"])
def test_streamed_pod_exact(mesh_shape, config):
    """Tiled multi-device rounds (collective-free steps, one transpose per
    dim tile) aggregate exactly, including ragged edge tiles."""
    from sda_tpu.mesh import StreamedPod
    from sda_tpu.mesh.simpod import make_mesh

    dim, participants = 50, 10
    sharing, masking = _streamed_config(config, dim)
    pod = StreamedPod(
        sharing, masking, mesh=make_mesh(*mesh_shape),
        participants_chunk=4, dim_chunk=24,
    )
    rng = np.random.default_rng(21)
    inputs = rng.integers(0, 433, size=(participants, dim))
    out = pod.aggregate(inputs, key=jax.random.PRNGKey(9))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


@needs8
def test_streamed_pod_matches_simulated_pod():
    """One-tile StreamedPod and SimulatedPod agree with the plain sum on
    the same mesh (independent randomness, same aggregate)."""
    from sda_tpu.mesh import SimulatedPod, StreamedPod
    from sda_tpu.mesh.simpod import make_mesh

    mesh = make_mesh(4, 2)
    rng = np.random.default_rng(22)
    inputs = rng.integers(0, 433, size=(8, 48))
    expected = inputs.sum(axis=0) % 433
    streamed = StreamedPod(GOLDEN, FullMasking(433), mesh=mesh,
                           participants_chunk=8, dim_chunk=48)
    pod = SimulatedPod(GOLDEN, FullMasking(433), mesh=mesh)
    np.testing.assert_array_equal(
        streamed.aggregate(inputs, key=jax.random.PRNGKey(1)), expected)
    np.testing.assert_array_equal(
        np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(1))), expected)


@needs8
def test_streamed_pod_large_committee_smoke():
    """80-clerk committee streamed over the mesh (reference scale story)."""
    from sda_tpu.mesh import StreamedPod
    from sda_tpu.mesh.simpod import make_mesh
    from sda_tpu.protocol import AdditiveSharing

    pod = StreamedPod(
        AdditiveSharing(share_count=80, modulus=433),
        mesh=make_mesh(8, 1), participants_chunk=8, dim_chunk=12,
    )
    rng = np.random.default_rng(23)
    inputs = rng.integers(0, 433, size=(12, 20))
    out = pod.aggregate(inputs, key=jax.random.PRNGKey(2))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


def test_streaming_aggregator_chacha_exact_across_tilings():
    """ChaCha seed masks in the single-chip streamed mode: exact aggregate
    for several tilings, including edge tiles not aligned to the 8-word
    ChaCha block grain (the dim tile pads to the grain internally)."""
    import jax

    from sda_tpu.mesh import StreamingAggregator
    from sda_tpu.protocol import ChaChaMasking, PackedShamirSharing

    s = PackedShamirSharing(3, 8, 4, 433, 354, 150)
    rng = np.random.default_rng(41)
    P, d = 13, 100  # d % 24 != 0: every tiling has a ragged edge tile
    x = rng.integers(0, 433, size=(P, d))
    expected = x.sum(axis=0) % 433
    for pc, dc in [(4, 24), (5, 48), (13, 120), (2, 25)]:
        agg = StreamingAggregator(
            s, ChaChaMasking(433, d, 128),
            participants_chunk=pc, dim_chunk=dc,
        )
        out = agg.aggregate(x, key=jax.random.PRNGKey(12))
        np.testing.assert_array_equal(out, expected, err_msg=f"tiling {pc}x{dc}")


def test_streaming_aggregator_additive_schemes():
    """Additive sharing in the streamed single-chip mode (scheme-lattice
    parity with the pod modes), across maskings and ragged tilings."""
    import jax

    from sda_tpu.mesh import StreamingAggregator
    from sda_tpu.protocol import (
        AdditiveSharing,
        ChaChaMasking,
        FullMasking,
        NoMasking,
    )

    rng = np.random.default_rng(53)
    P, d = 11, 70
    x = rng.integers(0, 433, size=(P, d))
    expected = x.sum(axis=0) % 433
    s = AdditiveSharing(share_count=8, modulus=433)
    for masking in (NoMasking(), FullMasking(433), ChaChaMasking(433, d, 128)):
        agg = StreamingAggregator(
            s, masking, participants_chunk=4, dim_chunk=30
        )
        out = agg.aggregate(x, key=jax.random.PRNGKey(21))
        np.testing.assert_array_equal(
            out, expected, err_msg=type(masking).__name__
        )


def test_streaming_checkpoint_resume_bit_identical(tmp_path):
    """A crash mid-round resumes from the snapshot and produces the exact
    bytes of an uninterrupted run (tile keys are pure functions of the
    round key and tile indices), skipping already-folded chunks."""
    import os

    from sda_tpu.mesh import synthetic_block_provider32

    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    s = PackedShamirSharing(3, 8, t, p, w2, w3)
    key = jax.random.PRNGKey(7)
    prov = synthetic_block_provider32(p, seed=4, max_value=1 << 20)
    ck = str(tmp_path / "round.ckpt.npz")

    def agg():
        return StreamingAggregator(
            s, FullMasking(p), participants_chunk=4, dim_chunk=24
        )

    ref = agg().aggregate_blocks(prov, 23, 100, key)

    calls = {"n": 0}

    def flaky(p0, p1, d0, d1):
        calls["n"] += 1
        if calls["n"] == 13:
            raise RuntimeError("simulated crash")
        return prov(p0, p1, d0, d1)

    with pytest.raises(RuntimeError):
        agg().aggregate_blocks(flaky, 23, 100, key, checkpoint_path=ck,
                               checkpoint_every_chunks=2)
    assert os.path.exists(ck)

    resumed_calls = {"n": 0}

    def counting(p0, p1, d0, d1):
        resumed_calls["n"] += 1
        return prov(p0, p1, d0, d1)

    out = agg().aggregate_blocks(counting, 23, 100, key, checkpoint_path=ck,
                                 checkpoint_every_chunks=2)
    np.testing.assert_array_equal(out, ref)
    assert not os.path.exists(ck)  # removed on completion
    total_chunks = (-(-23 // 4)) * (-(-100 // 24))
    assert resumed_calls["n"] < total_chunks  # resume skipped folded chunks


def test_streaming_checkpoint_rejects_foreign_snapshot(tmp_path):
    """A snapshot from a different round (different key) is ignored: the
    fingerprint mismatch forces a clean fresh run, never a silent mix."""
    from sda_tpu.mesh import synthetic_block_provider32

    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    s = PackedShamirSharing(3, 8, t, p, w2, w3)
    prov = synthetic_block_provider32(p, seed=4, max_value=1 << 20)
    ck = str(tmp_path / "round.ckpt.npz")

    def agg():
        return StreamingAggregator(
            s, FullMasking(p), participants_chunk=4, dim_chunk=24
        )

    import os

    calls = {"n": 0}

    def boom(p0, p1, d0, d1):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("crash")
        return prov(p0, p1, d0, d1)

    with pytest.raises(RuntimeError):
        agg().aggregate_blocks(boom, 23, 100, jax.random.PRNGKey(7),
                               checkpoint_path=ck, checkpoint_every_chunks=1)
    assert os.path.exists(ck)  # a key-7 snapshot exists
    # different key: snapshot must not be trusted
    out = agg().aggregate_blocks(prov, 23, 100, jax.random.PRNGKey(8),
                                 checkpoint_path=ck)
    exp = agg().aggregate_blocks(prov, 23, 100, jax.random.PRNGKey(8))
    np.testing.assert_array_equal(out, exp)


@needs8
def test_streamed_pod_checkpoint_resume_bit_identical(tmp_path):
    """StreamedPod (multi-chip) rounds resume from snapshots too: the
    fingerprint additionally pins the mesh shape, and loaded accumulators
    are re-placed with the pod's ('p', 'd') sharding."""
    import os

    from sda_tpu.mesh import StreamedPod, synthetic_block_provider32
    from sda_tpu.mesh.simpod import make_mesh

    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    s = PackedShamirSharing(3, 8, t, p, w2, w3)
    key = jax.random.PRNGKey(9)
    prov = synthetic_block_provider32(p, seed=6, max_value=1 << 20)
    ck = str(tmp_path / "pod.ckpt.npz")

    def pod():
        return StreamedPod(s, FullMasking(p), mesh=make_mesh(4, 2),
                           participants_chunk=8, dim_chunk=24)

    ref = pod().aggregate_blocks(prov, 21, 96, key)

    calls = {"n": 0}

    def flaky(p0, p1, d0, d1):
        calls["n"] += 1
        if calls["n"] == 8:
            raise RuntimeError("crash")
        return prov(p0, p1, d0, d1)

    with pytest.raises(RuntimeError):
        pod().aggregate_blocks(flaky, 21, 96, key, checkpoint_path=ck,
                               checkpoint_every_chunks=2)
    assert os.path.exists(ck)

    counting = {"n": 0}

    def cprov(p0, p1, d0, d1):
        counting["n"] += 1
        return prov(p0, p1, d0, d1)

    resumed = pod()
    out = resumed.aggregate_blocks(cprov, 21, 96, key, checkpoint_path=ck,
                                   checkpoint_every_chunks=2)
    assert resumed.last_resumed
    np.testing.assert_array_equal(out, ref)
    assert not os.path.exists(ck)
    assert counting["n"] < 12  # resume skipped folded chunks


def test_checkpoint_boundary_only_cadence_resumes_exact(tmp_path):
    """checkpoint_every_chunks=0 snapshots at dim-tile boundaries only
    (the flagship e2e cadence — intra-tile snapshots would D2H the
    accumulators through the tunnel every few hundred ms of compute): a
    crash mid-tile resumes from the last completed tile and the round
    stays bit-exact."""
    import os

    from sda_tpu.mesh import StreamingAggregator, synthetic_block_provider32

    s = fast_scheme()
    p = s.prime_modulus
    key = jax.random.PRNGKey(19)
    prov = synthetic_block_provider32(p, seed=21, max_value=1 << 20)
    ck = str(tmp_path / "boundary.ckpt.npz")

    def agg():
        return StreamingAggregator(
            s, FullMasking(p), participants_chunk=4, dim_chunk=24
        )

    ref = agg().aggregate_blocks(prov, 23, 100, key)

    calls = {"n": 0}

    def flaky(p0, p1, d0, d1):
        calls["n"] += 1
        # 6 participant chunks per dim tile: call 15 is the third chunk
        # of dim tile 2 — two chunks are already folded into tile 2's
        # accumulator when the crash lands, but with cadence 0 no
        # intra-tile snapshot exists, so resume must DISCARD that partial
        # fold and rebuild tile 2 from its boundary snapshot
        if calls["n"] == 15:
            raise RuntimeError("simulated crash")
        return prov(p0, p1, d0, d1)

    with pytest.raises(RuntimeError):
        agg().aggregate_blocks(flaky, 23, 100, key, checkpoint_path=ck,
                               checkpoint_every_chunks=0)
    assert os.path.exists(ck)  # the completed-tile boundary snapshot

    resumed = agg()
    resumed_calls = {"n": 0}

    def counting(p0, p1, d0, d1):
        resumed_calls["n"] += 1
        return prov(p0, p1, d0, d1)

    out = resumed.aggregate_blocks(counting, 23, 100, key,
                                   checkpoint_path=ck,
                                   checkpoint_every_chunks=0)
    assert resumed.last_resumed
    np.testing.assert_array_equal(out, ref)
    assert not os.path.exists(ck)
    # dim tiles 0 and 1 (12 chunks) restored from the boundary snapshot;
    # tiles 2..4 re-fed in full — exactly 18 of the 30 chunks
    assert resumed_calls["n"] == 18


# -- uniform_tail: one compiled step/finale shape per round ----------------
# Opt-in tail padding (bench entry points use it so scarce hardware windows
# compile ONE step/finale shape per streamed config instead of paying the
# ragged-tail shapes' extra compiles).

def test_uniform_tail_exact_and_single_step_shape():
    scheme = fast_scheme()
    p = scheme.prime_modulus
    rng = np.random.default_rng(71)
    P, d, pc, dc = 9, 100, 4, 36  # tail tile 100-72=28 -> padded to 36
    x = rng.integers(0, 1 << 16, size=(P, d))
    expected = x.sum(axis=0) % p
    for masking in (NoMasking(), FullMasking(p)):
        agg = StreamingAggregator(
            scheme, masking, participants_chunk=pc, dim_chunk=dc,
            uniform_tail=True)
        out = agg.aggregate(x, key=jax.random.PRNGKey(3))
        np.testing.assert_array_equal(out, expected,
                                      err_msg=type(masking).__name__)
        # THE point of the flag: a ragged round compiles one step shape
        # and one finale shape
        assert len(agg._steps) == 1, list(agg._steps)
        assert len(agg._finals) == 1, list(agg._finals)
        baseline = StreamingAggregator(
            scheme, masking, participants_chunk=pc, dim_chunk=dc)
        base_out = baseline.aggregate(x, key=jax.random.PRNGKey(3))
        np.testing.assert_array_equal(out, base_out)
        # the ragged tails it exists to avoid: full/tail shapes on both
        # axes -> 4 separately compiled steps
        assert len(baseline._steps) == 4, list(baseline._steps)


def test_uniform_tail_chacha_and_additive_exact():
    from sda_tpu.protocol import AdditiveSharing, ChaChaMasking

    rng = np.random.default_rng(73)
    P, d = 11, 100
    x = rng.integers(0, 433, size=(P, d))
    expected = x.sum(axis=0) % 433
    for scheme, masking in [
        (GOLDEN, ChaChaMasking(433, d, 128)),
        (AdditiveSharing(share_count=8, modulus=433), ChaChaMasking(433, d, 128)),
        (AdditiveSharing(share_count=8, modulus=433), FullMasking(433)),
    ]:
        agg = StreamingAggregator(
            scheme, masking, participants_chunk=4, dim_chunk=48,
            uniform_tail=True)
        out = agg.aggregate(x, key=jax.random.PRNGKey(9))
        np.testing.assert_array_equal(
            out, expected,
            err_msg=f"{type(scheme).__name__}/{type(masking).__name__}")


def test_uniform_tail_single_tile_unchanged():
    scheme = fast_scheme()
    rng = np.random.default_rng(77)
    x = rng.integers(0, 1 << 16, size=(5, 30))
    a = StreamingAggregator(scheme, FullMasking(scheme.prime_modulus),
                            participants_chunk=8, dim_chunk=3 << 20,
                            uniform_tail=True)
    out = a.aggregate(x, key=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(out, x.sum(axis=0) % scheme.prime_modulus)
    # dim < dim_chunk: the single tile keeps its grain-rounded size, not
    # the full chunk width
    (shape,) = a._steps
    assert shape[1] < a.dim_chunk


def test_uniform_tail_checkpoint_resume_and_fingerprint(tmp_path):
    import os

    from sda_tpu.mesh import synthetic_block_provider32

    scheme = fast_scheme()
    p = scheme.prime_modulus
    prov = synthetic_block_provider32(p, seed=5, max_value=1 << 16)
    key = jax.random.PRNGKey(8)
    P, d = 10, 100

    def agg(**kw):
        return StreamingAggregator(scheme, FullMasking(p),
                                   participants_chunk=4, dim_chunk=36, **kw)

    ref = agg(uniform_tail=True).aggregate_blocks(prov, P, d, key)
    exp = prov(0, P, 0, d).astype(np.int64).sum(axis=0) % p
    np.testing.assert_array_equal(ref, exp)

    # crash mid-round, resume bit-identically under uniform_tail
    ck = str(tmp_path / "ut.ckpt.npz")
    calls = {"n": 0}

    def flaky(p0, p1, d0, d1):
        calls["n"] += 1
        if calls["n"] == 5:
            raise RuntimeError("boom")
        return prov(p0, p1, d0, d1)

    with pytest.raises(RuntimeError):
        agg(uniform_tail=True).aggregate_blocks(
            flaky, P, d, key, checkpoint_path=ck, checkpoint_every_chunks=1)
    assert os.path.exists(ck)
    resumed = agg(uniform_tail=True)
    out = resumed.aggregate_blocks(prov, P, d, key, checkpoint_path=ck,
                                   checkpoint_every_chunks=1)
    assert resumed.last_resumed
    np.testing.assert_array_equal(out, ref)

    # a snapshot written WITHOUT uniform_tail must not be resumed WITH it
    # (accumulator shapes differ mid-round): fingerprints diverge
    calls["n"] = 0
    with pytest.raises(RuntimeError):
        agg().aggregate_blocks(
            flaky, P, d, key, checkpoint_path=ck, checkpoint_every_chunks=1)
    fresh = agg(uniform_tail=True)
    out2 = fresh.aggregate_blocks(prov, P, d, key, checkpoint_path=ck,
                                  checkpoint_every_chunks=1)
    assert not fresh.last_resumed  # foreign snapshot rejected, clean round
    np.testing.assert_array_equal(out2, ref)


def test_uniform_tail_pallas_streamed_exact():
    """uniform_tail + the PALLAS streamed stage — the exact combination
    the TPU suite runs (interpret-mode kernel, external bits): ragged
    tails on both axes pad to the chunk and the aggregate stays exact,
    with one compiled step shape."""
    from util import external_bits

    scheme = fast_scheme()
    p = scheme.prime_modulus
    rng = np.random.default_rng(91)
    P, d, pc, dc = 10, 100, 4, 36  # ragged on both axes
    x = rng.integers(0, 1 << 16, size=(P, d))
    agg = StreamingAggregator(
        scheme, FullMasking(p), participants_chunk=pc, dim_chunk=dc,
        use_pallas=True, pallas_interpret=True,
        pallas_external_bits_fn=external_bits, uniform_tail=True)
    assert agg.pallas_active
    out = agg.aggregate(x, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(out, x.sum(axis=0) % p)
    assert len(agg._steps) == 1, list(agg._steps)
