"""Golden wire fixtures transcribed from the reference's own sources.

Round-2 verdict, missing #2: the serde fixtures in test_protocol.py were
hand-derived from *reading* the Rust; this file pins the wire format with
byte literals transcribed from the reference's own unit tests and with a
frozen compact-JSON canonical string for every resource that crosses the
wire, each citing the Rust declaration it encodes. A field-order or codec
regression anywhere in sda_tpu.protocol now fails against a literal, not
against our own serializer run twice.

Transcription sources (no cargo in this image, so the fixtures are
transcribed, not captured from execution):

- ``protocol/src/byte_arrays.rs:106-151`` — the reference's serde_test unit
  tests for B8/B32/B64: literal padded-base64 strings for all-zero arrays
  and the a/b/c struct token stream.
- ``protocol/src/helpers.rs:138-142`` — ``canonical() = serde_json::to_vec``:
  compact JSON, struct fields in declaration order; this is the byte string
  Ed25519 signatures cover, so every literal here is signature-critical.
- ``protocol/src/resources.rs`` + ``protocol/src/crypto.rs`` — field
  declaration orders cited per fixture below.

serde-0.9 conventions the literals encode (protocol/Cargo.toml:11):
externally-tagged enums (unit variant -> bare string, struct variant ->
one-key object), Option -> null, tuples -> arrays, padded base64.
"""

import json

import jax

jax.config.update("jax_platforms", "cpu")

from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    AgentId,
    BasicShamirSharing,
    ChaChaMasking,
    ClerkingJobId,
    Encryption,
    EncryptionKey,
    EncryptionKeyId,
    FullMasking,
    NoMasking,
    PackedPaillierEncryption,
    PackedShamirSharing,
    ParticipationId,
    Signature,
    SnapshotId,
    SodiumEncryption,
    VerificationKey,
    VerificationKeyId,
)
from sda_tpu.protocol.helpers import (
    B8,
    B32,
    B64,
    Binary,
    Labelled,
    Signed,
    canonical_json,
)
from sda_tpu.protocol.resources import (
    Agent,
    AggregationStatus,
    ClerkCandidate,
    ClerkingJob,
    ClerkingResult,
    Committee,
    Participation,
    Profile,
    Snapshot,
    SnapshotResult,
    SnapshotStatus,
    labelled_verification_key,
    signed_encryption_key_from_obj,
)

# Fixed ids so every canonical string below is a reproducible literal.
A = AgentId("00000000-0000-0000-0000-00000000000a")
VK = VerificationKeyId("00000000-0000-0000-0000-0000000000b0")
EK = EncryptionKeyId("00000000-0000-0000-0000-0000000000c0")
AG = AggregationId("00000000-0000-0000-0000-0000000000d0")
PA = ParticipationId("00000000-0000-0000-0000-0000000000e0")
SN = SnapshotId("00000000-0000-0000-0000-0000000000f0")
JB = ClerkingJobId("00000000-0000-0000-0000-000000000010")

# The reference's own literals (byte_arrays.rs:108-110, 119-121, 133-149).
B8_ZERO = "AAAAAAAAAAA="
B32_ZERO = "A" * 43 + "="
B64_ZERO = "A" * 86 + "=="


def canon(x) -> str:
    return canonical_json(x.to_obj() if hasattr(x, "to_obj") else x).decode()


# -- byte_arrays.rs fixtures ------------------------------------------------

def test_byte_array_base64_literals():
    """test_b64_raw/test_b64 (byte_arrays.rs:106-124): zero-filled fixed
    arrays serialize to exactly these padded base64 strings."""
    assert B8().to_obj() == B8_ZERO
    assert B32().to_obj() == B32_ZERO
    assert B64().to_obj() == B64_ZERO
    assert B8.from_obj(B8_ZERO) == B8()
    assert B32.from_obj(B32_ZERO) == B32()
    assert B64.from_obj(B64_ZERO) == B64()


def test_byte_array_struct_token_stream():
    """test_serde (byte_arrays.rs:126-151): struct T { a: B8, b: B32,
    c: B64 } serializes field-by-field to the reference's token values,
    in declaration order."""
    t = {"a": B8().to_obj(), "b": B32().to_obj(), "c": B64().to_obj()}
    expected = (
        '{"a":"' + B8_ZERO + '","b":"' + B32_ZERO + '","c":"' + B64_ZERO + '"}'
    )
    assert canonical_json(t).decode() == expected


def test_binary_base64_roundtrip():
    """Binary blobs are padded base64 (helpers.rs:175-216)."""
    assert Binary(b"\x01\x02").to_obj() == "AQI="
    assert Binary.from_obj("AQI=") == Binary(b"\x01\x02")


# -- canonical bytes for every wire resource --------------------------------
# One frozen literal per resource. Field order citations are to the Rust
# struct declarations; `canonical()` serializes in exactly that order
# (helpers.rs:138-142).

def test_canonical_agent():
    """Agent { id, verification_key } (resources.rs:12-17), with
    LabelledVerificationKey = Labelled { id, body } (helpers.rs:146-152)
    and VerificationKey::Sodium(B32) (crypto.rs:34-38)."""
    agent = Agent(
        id=A,
        verification_key=labelled_verification_key(
            VK, VerificationKey("Sodium", B32())
        ),
    )
    assert canon(agent) == (
        '{"id":"00000000-0000-0000-0000-00000000000a",'
        '"verification_key":{"id":"00000000-0000-0000-0000-0000000000b0",'
        '"body":{"Sodium":"' + B32_ZERO + '"}}}'
    )


def test_canonical_profile():
    """Profile { owner, name, twitter_id, keybase_id, website }
    (resources.rs:23-35); Option fields serialize as null."""
    assert canon(Profile(owner=A)) == (
        '{"owner":"00000000-0000-0000-0000-00000000000a","name":null,'
        '"twitter_id":null,"keybase_id":null,"website":null}'
    )


def test_canonical_aggregation():
    """Aggregation (resources.rs:44-67): id, title, vector_dimension,
    modulus, recipient, recipient_key, masking_scheme,
    committee_sharing_scheme, recipient_encryption_scheme,
    committee_encryption_scheme. Unit variants as bare strings
    (LinearMaskingScheme::None crypto.rs:45,
    AdditiveEncryptionScheme::Sodium crypto.rs:162); Additive struct
    variant field order share_count, modulus (crypto.rs:81-87)."""
    agg = Aggregation(
        id=AG, title="t", vector_dimension=4, modulus=433, recipient=A,
        recipient_key=EK, masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    assert canon(agg) == (
        '{"id":"00000000-0000-0000-0000-0000000000d0","title":"t",'
        '"vector_dimension":4,"modulus":433,'
        '"recipient":"00000000-0000-0000-0000-00000000000a",'
        '"recipient_key":"00000000-0000-0000-0000-0000000000c0",'
        '"masking_scheme":"None",'
        '"committee_sharing_scheme":{"Additive":{"share_count":3,"modulus":433}},'
        '"recipient_encryption_scheme":"Sodium",'
        '"committee_encryption_scheme":"Sodium"}'
    )


def test_canonical_clerk_candidate_and_committee():
    """ClerkCandidate { id, keys } (resources.rs:74-80); Committee
    { aggregation, clerks_and_keys } with Vec<(AgentId, EncryptionKeyId)>
    as nested arrays (resources.rs:83-88)."""
    assert canon(ClerkCandidate(id=A, keys=[EK])) == (
        '{"id":"00000000-0000-0000-0000-00000000000a",'
        '"keys":["00000000-0000-0000-0000-0000000000c0"]}'
    )
    assert canon(Committee(aggregation=AG, clerks_and_keys=[(A, EK)])) == (
        '{"aggregation":"00000000-0000-0000-0000-0000000000d0",'
        '"clerks_and_keys":[["00000000-0000-0000-0000-00000000000a",'
        '"00000000-0000-0000-0000-0000000000c0"]]}'
    )


def test_canonical_participation():
    """Participation (resources.rs:92-108): id, participant, aggregation,
    recipient_encryption (Option -> null), clerk_encryptions
    (Vec<(AgentId, Encryption)>); Encryption::Sodium(Binary)
    (crypto.rs:7-10)."""
    part = Participation(
        id=PA, participant=A, aggregation=AG, recipient_encryption=None,
        clerk_encryptions=[(A, Encryption("Sodium", Binary(b"\x01\x02")))],
    )
    assert canon(part) == (
        '{"id":"00000000-0000-0000-0000-0000000000e0",'
        '"participant":"00000000-0000-0000-0000-00000000000a",'
        '"aggregation":"00000000-0000-0000-0000-0000000000d0",'
        '"recipient_encryption":null,'
        '"clerk_encryptions":[["00000000-0000-0000-0000-00000000000a",'
        '{"Sodium":"AQI="}]]}'
    )


def test_canonical_snapshot_job_result():
    """Snapshot { id, aggregation } (resources.rs:116-121); ClerkingJob
    { id, clerk, aggregation, snapshot, encryptions } (resources.rs:128-139);
    ClerkingResult { job, clerk, encryption } (resources.rs:146-153)."""
    assert canon(Snapshot(id=SN, aggregation=AG)) == (
        '{"id":"00000000-0000-0000-0000-0000000000f0",'
        '"aggregation":"00000000-0000-0000-0000-0000000000d0"}'
    )
    job = ClerkingJob(
        id=JB, clerk=A, aggregation=AG, snapshot=SN,
        encryptions=[Encryption("Sodium", Binary(b"\x01\x02"))],
    )
    assert canon(job) == (
        '{"id":"00000000-0000-0000-0000-000000000010",'
        '"clerk":"00000000-0000-0000-0000-00000000000a",'
        '"aggregation":"00000000-0000-0000-0000-0000000000d0",'
        '"snapshot":"00000000-0000-0000-0000-0000000000f0",'
        '"encryptions":[{"Sodium":"AQI="}]}'
    )
    res = ClerkingResult(
        job=JB, clerk=A, encryption=Encryption("Sodium", Binary(b"\x01\x02"))
    )
    assert canon(res) == (
        '{"job":"00000000-0000-0000-0000-000000000010",'
        '"clerk":"00000000-0000-0000-0000-00000000000a",'
        '"encryption":{"Sodium":"AQI="}}'
    )


def test_canonical_status_and_result():
    """AggregationStatus { aggregation, number_of_participations, snapshots }
    (resources.rs:156-164); SnapshotStatus { id, number_of_clerking_results,
    result_ready } (resources.rs:167-175); SnapshotResult { snapshot,
    number_of_participations, clerk_encryptions, recipient_encryptions }
    (resources.rs:179-188)."""
    ss = SnapshotStatus(id=SN, number_of_clerking_results=2, result_ready=True)
    assert canon(ss) == (
        '{"id":"00000000-0000-0000-0000-0000000000f0",'
        '"number_of_clerking_results":2,"result_ready":true}'
    )
    ast = AggregationStatus(
        aggregation=AG, number_of_participations=5, snapshots=[ss]
    )
    assert canon(ast) == (
        '{"aggregation":"00000000-0000-0000-0000-0000000000d0",'
        '"number_of_participations":5,'
        '"snapshots":[{"id":"00000000-0000-0000-0000-0000000000f0",'
        '"number_of_clerking_results":2,"result_ready":true}]}'
    )
    res = ClerkingResult(
        job=JB, clerk=A, encryption=Encryption("Sodium", Binary(b"\x01\x02"))
    )
    sr = SnapshotResult(
        snapshot=SN, number_of_participations=5, clerk_encryptions=[res],
        recipient_encryptions=None,
    )
    assert canon(sr) == (
        '{"snapshot":"00000000-0000-0000-0000-0000000000f0",'
        '"number_of_participations":5,'
        '"clerk_encryptions":[{"job":"00000000-0000-0000-0000-000000000010",'
        '"clerk":"00000000-0000-0000-0000-00000000000a",'
        '"encryption":{"Sodium":"AQI="}}],'
        '"recipient_encryptions":null}'
    )


def test_canonical_signed_encryption_key():
    """SignedEncryptionKey = Signed<Labelled<EncryptionKeyId, EncryptionKey>>
    (resources.rs:40): Signed { signature, signer, body } (helpers.rs:99-107)
    around Labelled { id, body } (helpers.rs:146-152). THE
    signature-critical payload: the inner Labelled's canonical bytes are
    what sign_export signs (client/src/crypto/signing/mod.rs:72-103)."""
    labelled = Labelled(EK, EncryptionKey("Sodium", B32()))
    assert labelled.canonical() == (
        '{"id":"00000000-0000-0000-0000-0000000000c0",'
        '"body":{"Sodium":"' + B32_ZERO + '"}}'
    ).encode()
    signed = Signed(
        signature=Signature("Sodium", B64()), signer=A, body=labelled
    )
    assert canon(signed) == (
        '{"signature":{"Sodium":"' + B64_ZERO + '"},'
        '"signer":"00000000-0000-0000-0000-00000000000a",'
        '"body":{"id":"00000000-0000-0000-0000-0000000000c0",'
        '"body":{"Sodium":"' + B32_ZERO + '"}}}'
    )
    assert signed_encryption_key_from_obj(json.loads(canon(signed))) == signed


def test_canonical_scheme_variants():
    """Scheme enums: PackedShamir field order secret_count, share_count,
    privacy_threshold, prime_modulus, omega_secrets, omega_shares
    (crypto.rs:98-113); Full { modulus } (crypto.rs:50-52); ChaCha
    { modulus, dimension, seed_bitsize } (crypto.rs:59-63); PackedPaillier
    field order component_count, component_bitsize, max_value_bitsize,
    min_modulus_bitsize per the reference's declared-but-disabled variant
    (crypto.rs:164-174 — our framework enables it)."""
    assert canon(PackedShamirSharing(3, 8, 4, 433, 354, 150)) == (
        '{"PackedShamir":{"secret_count":3,"share_count":8,'
        '"privacy_threshold":4,"prime_modulus":433,'
        '"omega_secrets":354,"omega_shares":150}}'
    )
    # BasicShamir: field order share_count, privacy_threshold, prime_modulus
    # per the reference's declared-but-disabled variant (crypto.rs:89-95 —
    # our framework enables it)
    assert canon(BasicShamirSharing(5, 2, 433)) == (
        '{"BasicShamir":{"share_count":5,"privacy_threshold":2,'
        '"prime_modulus":433}}'
    )
    assert canon(FullMasking(433)) == '{"Full":{"modulus":433}}'
    assert canon(ChaChaMasking(433, 10, 128)) == (
        '{"ChaCha":{"modulus":433,"dimension":10,"seed_bitsize":128}}'
    )
    assert canon(SodiumEncryption()) == '"Sodium"'
    assert canon(PackedPaillierEncryption(2, 48, 32, 512)) == (
        '{"PackedPaillier":{"component_count":2,"component_bitsize":48,'
        '"max_value_bitsize":32,"min_modulus_bitsize":512}}'
    )
