"""Dim-tile schedule tail handling: property coverage of scan_dim_tiles.

The model-scale plane (mesh/devscale.py) leans on the tiled schedule at
dimensions that are never a multiple of the tile grain, so the tail
arithmetic is load-bearing: a dim off the grain must produce BIT-EXACT
results vs the untiled reference for the full (sharing x masking)
lattice, including the exactly-one-tile and one-element-tail edges.
``tile_plan`` is the shared arithmetic (the in-program scan and the
host-driven model-scale loop both slice with it), pinned directly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sda_tpu.fields import numtheory
from sda_tpu.fields.dimtile import TilePlan, scan_dim_tiles, tile_plan
from sda_tpu.mesh import single_chip_round
from sda_tpu.protocol import (
    AdditiveSharing,
    FullMasking,
    NoMasking,
    PackedShamirSharing,
)


def _packed():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    return PackedShamirSharing(3, 8, t, p, w2, w3)


def _additive():
    return AdditiveSharing(share_count=5, modulus=(1 << 29) - 679)


# -- tile_plan: the shared schedule arithmetic --------------------------------

def test_tile_plan_rounds_width_to_grain():
    assert tile_plan(200, 24, 90) == TilePlan(96, 3, 88)
    assert tile_plan(96, 24, 96) == TilePlan(96, 1, 0)


def test_tile_plan_narrow_dim_shrinks_to_one_grain_rounded_tile():
    # a wide tile knob must not inflate small shapes
    plan = tile_plan(50, 24, 4096)
    assert plan == TilePlan(72, 1, 22)
    assert plan.padded_dim == 72


def test_tile_plan_one_element_tail():
    # dim = one full tile + 1 element: the tail tile is all padding but 1
    plan = tile_plan(97, 24, 96)
    assert plan.width == 96 and plan.n_tiles == 2 and plan.pad == 95


def test_tile_plan_rejects_bad_knobs():
    with pytest.raises(ValueError):
        tile_plan(10, 8, 0)
    with pytest.raises(ValueError):
        tile_plan(10, 0, 8)


def test_tile_plan_covers_every_dim_property():
    # property sweep: for any (dim, grain, tile) the plan tiles cover the
    # dim exactly once with grain-aligned width
    rng = np.random.default_rng(20260804)
    for _ in range(200):
        grain = int(rng.integers(1, 30))
        dim = int(rng.integers(1, 2000))
        tile = int(rng.integers(1, 500))
        plan = tile_plan(dim, grain, tile)
        assert plan.width % grain == 0
        assert plan.n_tiles * plan.width == dim + plan.pad
        assert 0 <= plan.pad < plan.width


# -- scan_dim_tiles tails: the four (sharing x masking) configs ---------------

def _round_pair(scheme, masking, dim_tile):
    tiled = jax.jit(single_chip_round(scheme, masking, dim_tile=dim_tile))
    untiled = jax.jit(single_chip_round(scheme, masking))
    return tiled, untiled


CONFIGS = [
    ("packed-none", _packed, NoMasking),
    ("packed-full", _packed, "full"),
    ("additive-none", _additive, NoMasking),
    ("additive-full", _additive, "full"),
]


@pytest.mark.parametrize("name,make_scheme,mask_kind", CONFIGS,
                         ids=[c[0] for c in CONFIGS])
def test_tail_dims_bit_exact_vs_untiled_reference(name, make_scheme,
                                                  mask_kind):
    """Dims OFF the tile grain: the tiled schedule must reveal the same
    bytes as the untiled program (both equal the plain column sum — the
    aggregate is deterministic, so this IS bit-exactness)."""
    scheme = make_scheme()
    m = getattr(scheme, "prime_modulus", None) or scheme.modulus
    masking = NoMasking() if mask_kind is NoMasking else FullMasking(m)
    T = 96  # grain 24 (packed k=3 x 8) / 8 (additive): 96 fits both
    tiled, untiled = _round_pair(scheme, masking, T)
    rng = np.random.default_rng(hash(name) % (1 << 31))
    # the edges the satellite names, plus a seeded off-grain dim:
    #   T      — exactly one tile (runs the scan, not the direct path)
    #   T + 1  — one-element tail (tail tile all padding but one column)
    dims = [T, T + 1, 2 * T + 7, int(rng.integers(T + 2, 4 * T))]
    for i, dim in enumerate(dims):
        inputs = rng.integers(0, 1 << 20, size=(5, dim), dtype=np.int64)
        key = jax.random.PRNGKey(dim)
        out_t = np.asarray(tiled(jnp.asarray(inputs), key))
        expected = inputs.sum(axis=0) % m
        np.testing.assert_array_equal(out_t, expected,
                                      err_msg=f"{name} tiled dim={dim}")
        if i < 2:  # anchor the untiled reference at the edge dims (each
            # extra dim costs a full-width compile; the aggregate is the
            # deterministic column sum either way)
            out_u = np.asarray(untiled(jnp.asarray(inputs), key))
            np.testing.assert_array_equal(out_u, expected,
                                          err_msg=f"{name} untiled "
                                                  f"dim={dim}")


def test_one_element_dim_runs_direct_path():
    # dim=1 is narrower than any tile: the direct (no scan) path
    scheme = _packed()
    fn = jax.jit(single_chip_round(scheme, FullMasking(scheme.prime_modulus),
                                   dim_tile=96))
    out = np.asarray(fn(jnp.asarray([[7], [11]]), jax.random.PRNGKey(0)))
    assert out.tolist() == [18]
