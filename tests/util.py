"""Shared test fixtures: fake-crypto agent factories and service contexts.

Modeled on the reference harness (integration-tests/src/lib.rs): CRUD/logic
tests use agents with all-zero keys and signatures (:51-71) since the server
never verifies signatures; full-loop tests use real crypto via SdaClient.
The fixture decides how distributed the system is — in-process memory,
durable JSON files, or HTTP (the same tests run against each seam).
"""

from __future__ import annotations

from sda_tpu.protocol import (
    Agent,
    AgentId,
    B32,
    B64,
    Binary,
    Encryption,
    EncryptionKey,
    EncryptionKeyId,
    Labelled,
    Signature,
    Signed,
    VerificationKey,
    VerificationKeyId,
)


def new_agent() -> Agent:
    return Agent(
        id=AgentId.random(),
        verification_key=Labelled(VerificationKeyId.random(), VerificationKey("Sodium", B32())),
    )


def new_key_for_agent(agent: Agent) -> Signed:
    return Signed(
        signature=Signature("Sodium", B64()),
        signer=agent.id,
        body=Labelled(EncryptionKeyId.random(), EncryptionKey("Sodium", B32())),
    )


def new_full_agent(service):
    agent = new_agent()
    service.create_agent(agent, agent)
    key = new_key_for_agent(agent)
    service.create_encryption_key(agent, key)
    return agent, key


def mock_encryption(data: bytes) -> Encryption:
    """Raw bytes posing as a ciphertext — server logic never opens them
    (reference mock pattern: integration-tests/tests/service.rs:29-47)."""
    return Encryption("Sodium", Binary(data))


# ---------------------------------------------------------------------------
# Real-MongoDB seam (reference: integration-tests/src/lib.rs:110-140 runs the
# same suites against a live mongod with a random per-test database, dropped
# after). Enabled by SDA_TEST_MONGO_URI; in-image runs use the fake instead.

def mongo_real_params():
    """Extra fixture params when a live mongod is configured."""
    import os

    return ["mongo-real"] if os.environ.get("SDA_TEST_MONGO_URI") else []


def new_mongo_real_service(request):
    """SdaServerService on a fresh random database of the configured
    mongod; registers a finalizer that drops the database."""
    import os
    import uuid

    import pytest

    from sda_tpu.server import mongo as mongo_mod
    from sda_tpu.server import new_mongo_server

    uri = os.environ.get("SDA_TEST_MONGO_URI")
    if not mongo_mod.available():
        pytest.skip("SDA_TEST_MONGO_URI set but pymongo is not installed")
    import pymongo

    client = pymongo.MongoClient(uri, serverSelectionTimeoutMS=5000)
    dbname = "sda_test_" + uuid.uuid4().hex[:12]

    def drop():
        client.drop_database(dbname)
        client.close()

    request.addfinalizer(drop)
    return new_mongo_server(client[dbname])


def scheme_lattice_config(name, dim, *, additive_share_count=8):
    """masking x sharing point of the golden scheme lattice (reference
    pluggability: masking/mod.rs:33-94 x sharing/mod.rs:35-96), mod 433."""
    from sda_tpu.protocol import (
        AdditiveSharing,
        BasicShamirSharing,
        ChaChaMasking,
        FullMasking,
        PackedShamirSharing,
    )

    if name.startswith("add"):
        sharing = AdditiveSharing(share_count=additive_share_count, modulus=433)
    elif name.startswith("basic"):
        sharing = BasicShamirSharing(share_count=8, privacy_threshold=4,
                                     prime_modulus=433)
    else:
        sharing = PackedShamirSharing(3, 8, 4, 433, 354, 150)
    masking = {
        "none": None,
        "full": FullMasking(433),
        "chacha": ChaChaMasking(433, dim, 128),
    }[name.split("-")[1]]
    return sharing, masking


def external_bits(key, P, draws, B):
    """[P, 2*draws, B] uint32 pre-drawn bits for the Pallas round's
    external-randomness mode (layout contract: pallas_round.py) — shared
    by the interpret-mode kernel tests."""
    import jax
    import jax.numpy as jnp

    return jax.random.bits(key, (P, 2 * draws, B), dtype=jnp.uint32)
