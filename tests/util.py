"""Shared test fixtures: fake-crypto agent factories and service contexts.

Modeled on the reference harness (integration-tests/src/lib.rs): CRUD/logic
tests use agents with all-zero keys and signatures (:51-71) since the server
never verifies signatures; full-loop tests use real crypto via SdaClient.
The fixture decides how distributed the system is — in-process memory,
durable JSON files, or HTTP (the same tests run against each seam).
"""

from __future__ import annotations

from sda_tpu.protocol import (
    Agent,
    AgentId,
    B32,
    B64,
    Binary,
    Encryption,
    EncryptionKey,
    EncryptionKeyId,
    Labelled,
    Signature,
    Signed,
    VerificationKey,
    VerificationKeyId,
)


def new_agent() -> Agent:
    return Agent(
        id=AgentId.random(),
        verification_key=Labelled(VerificationKeyId.random(), VerificationKey("Sodium", B32())),
    )


def new_key_for_agent(agent: Agent) -> Signed:
    return Signed(
        signature=Signature("Sodium", B64()),
        signer=agent.id,
        body=Labelled(EncryptionKeyId.random(), EncryptionKey("Sodium", B32())),
    )


def new_full_agent(service):
    agent = new_agent()
    service.create_agent(agent, agent)
    key = new_key_for_agent(agent)
    service.create_encryption_key(agent, key)
    return agent, key


def mock_encryption(data: bytes) -> Encryption:
    """Raw bytes posing as a ciphertext — server logic never opens them
    (reference mock pattern: integration-tests/tests/service.rs:29-47)."""
    return Encryption("Sodium", Binary(data))
