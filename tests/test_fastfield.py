"""Bit-exactness of the uint32 Solinas fast path vs exact integer math.

Every kernel in sda_tpu.fields.fastfield must agree with Python big-int
arithmetic on worst-case operands; the fast path may only change speed,
never results (SURVEY.md §2.2 oracle discipline).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from sda_tpu.fields import fastfield as ff
from sda_tpu.fields import numtheory

P29 = 536870233   # 2^29 - 679, ≡ 1 mod 72
P28 = 268435009   # 2^28 - 447, ≡ 1 mod 72


@pytest.mark.parametrize("p,expected", [
    (P29, True),
    (P28, True),
    (433, False),                  # too small
    ((1 << 30) + 3, False),        # b = 31 > 29
    ((1 << 29) - (1 << 15), False) # delta too large
])
def test_try_from_gating(p, expected):
    assert (ff.SolinasPrime.try_from(p) is not None) == expected
    assert ff.supported(p) == expected


@pytest.fixture(params=[P29, P28])
def sp(request):
    return ff.SolinasPrime.try_from(request.param)


def test_canon32_full_range(sp):
    rng = np.random.default_rng(0)
    p = sp.p
    v = np.concatenate([
        rng.integers(0, 1 << 32, size=20000, dtype=np.uint64).astype(np.uint32),
        np.array([0, 1, p - 1, p, p + 1, 2**32 - 1, 2**31, 2**30], dtype=np.uint32),
    ])
    got = np.asarray(ff.canon32(jnp.asarray(v), sp))
    np.testing.assert_array_equal(got.astype(object), v.astype(object) % p)


def test_addsub_mulconst(sp):
    rng = np.random.default_rng(1)
    p = sp.p
    a = rng.integers(0, p, size=20000).astype(np.uint32)
    b = rng.integers(0, p, size=20000).astype(np.uint32)
    np.testing.assert_array_equal(
        np.asarray(ff.modadd32(jnp.asarray(a), jnp.asarray(b), sp)).astype(object),
        (a.astype(object) + b) % p,
    )
    np.testing.assert_array_equal(
        np.asarray(ff.modsub32(jnp.asarray(a), jnp.asarray(b), sp)).astype(object),
        (a.astype(object) - b) % p,
    )
    for c in (0, 1, p - 1, 12345, (1 << 30) % p, (1 << 32) % p):
        got = np.asarray(ff.mulmod32_const(jnp.asarray(a), c, sp))
        np.testing.assert_array_equal(got.astype(object), a.astype(object) * c % p)


def test_modsum32(sp):
    rng = np.random.default_rng(2)
    p = sp.p
    # worst case: all terms p-1, count straddling the fold fan-in
    for n in (1, 2, 7, 8, 9, 100, 1000):
        x = np.full((n, 33), p - 1, dtype=np.uint32)
        got = np.asarray(ff.modsum32(jnp.asarray(x), sp, axis=0))
        np.testing.assert_array_equal(got.astype(object), (n * (p - 1)) % p)
    x = rng.integers(0, p, size=(321, 50)).astype(np.uint32)
    got = np.asarray(ff.modsum32(jnp.asarray(x), sp, axis=0))
    np.testing.assert_array_equal(got.astype(object), x.astype(object).sum(0) % p)


def test_modmatmul32_worst_case(sp):
    rng = np.random.default_rng(3)
    p = sp.p
    for (n, k, B) in [(8, 8, 257), (3, 9, 130), (16, 16, 64), (1, 1, 8)]:
        M = rng.integers(0, p, size=(n, k))
        M[:, : min(2, k)] = p - 1
        V = rng.integers(0, p, size=(k, B)).astype(np.uint32)
        V[:, : min(5, B)] = p - 1
        got = np.asarray(ff.modmatmul32(M, jnp.asarray(V), sp))
        exp = (M.astype(object) @ V.astype(object)) % p
        np.testing.assert_array_equal(got.astype(object), exp)


def test_np_oracle_matches_bigint(sp):
    """np_modmatmul32 (the module's own NumPy oracle) must agree with the
    exact bigint product — it is what audits device results elsewhere."""
    rng = np.random.default_rng(9)
    p = sp.p
    M = rng.integers(0, p, size=(8, 7))
    V = rng.integers(0, p, size=(7, 65)).astype(np.uint32)
    got = ff.np_modmatmul32(M, V, sp)
    exp = (M.astype(object) @ V.astype(object)) % p
    np.testing.assert_array_equal(got.astype(object), exp)
    # and the device kernel agrees with the oracle
    dev = np.asarray(ff.modmatmul32(M, jnp.asarray(V), sp))
    np.testing.assert_array_equal(dev, got)


def test_modmatmul32_batched(sp):
    rng = np.random.default_rng(4)
    p = sp.p
    M = rng.integers(0, p, size=(8, 8))
    V = rng.integers(0, p, size=(5, 8, 33)).astype(np.uint32)
    got = np.asarray(ff.modmatmul32(M, jnp.asarray(V), sp))
    exp = np.stack([
        (M.astype(object) @ V[i].astype(object)) % p for i in range(V.shape[0])
    ])
    np.testing.assert_array_equal(got.astype(object), exp)


def test_uniform32_range_and_mean(sp):
    u = np.asarray(ff.uniform32(jax.random.PRNGKey(7), (100000,), sp))
    assert u.dtype == np.uint32
    assert int(u.max()) < sp.p
    assert abs(u.mean() / sp.p - 0.5) < 0.01


def test_generated_packed_params_prefer_solinas():
    """The default prime generator should land on fast-path primes when a
    Solinas candidate exists in range (so flagship rounds use uint32)."""
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    assert ff.supported(p), f"generated prime {p} misses the fast path"
    numtheory.validate_packed_scheme(3, 8, t, p, w2, w3)
    # out-of-range request still produces a valid (generic-path) scheme
    t2, p2, _, _ = numtheory.generate_packed_params(3, 8, 30)
    assert p2 >= (1 << 30) and numtheory.is_prime(p2)
