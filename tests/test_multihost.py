"""Multi-controller execution: TWO OS processes, each owning 4 CPU devices,
jointly run one SimulatedPod round over gRPC collectives — the same
multi-process code path a real multi-host TPU deployment uses
(mesh/multihost.py). Each process contributes only its process-local
participant rows; both must independently reveal the identical global
aggregate.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")

port, pid = sys.argv[1], int(sys.argv[2])
from sda_tpu.mesh import multihost
multihost.initialize(f"localhost:{port}", num_processes=2, process_id=pid)

import numpy as np
from sda_tpu.mesh import SimulatedPod, make_multislice_mesh
from sda_tpu.protocol import FullMasking, PackedShamirSharing

assert jax.process_count() == 2
assert len(jax.devices()) == 8          # global view
assert len(jax.local_devices()) == 4    # this host's slice

scheme = PackedShamirSharing(3, 8, 4, 433, 354, 150)
# one slice block per process: participant data never crosses hosts
mesh = make_multislice_mesh(2, 2, 2)
pod = SimulatedPod(scheme, masking_scheme=FullMasking(433), mesh=mesh)

def rows(process):  # deterministic, RAGGED per-process participant rows
    return np.random.default_rng(100 + process).integers(
        0, 433, size=(2 + process, 12)
    )

out = multihost.aggregate_process_local(
    pod, rows(pid), key=jax.random.PRNGKey(7)
)
expected = (rows(0).sum(axis=0) + rows(1).sum(axis=0)) % 433
np.testing.assert_array_equal(out, expected)

# streamed flagship-scale path: every process streams its own rows in
# tiles; RAGGED local counts (5 rows on process 0, 4 on process 1) and
# several dim tiles
from sda_tpu.mesh import StreamedPod
from sda_tpu.protocol import AdditiveSharing, ChaChaMasking
spod = StreamedPod(
    AdditiveSharing(share_count=8, modulus=433),
    ChaChaMasking(433, 40, 128),
    mesh=mesh, participants_chunk=4, dim_chunk=16,
)
def srows(process):  # ragged: 5 rows on process 0, 4 on process 1
    return np.random.default_rng(900 + process).integers(
        0, 433, size=(5 - process, 40)
    )
mine = srows(pid)
def strict_provider(lp0, lp1, d0, d1):
    # the driver must never ask for rows beyond what THIS process declared
    assert 0 <= lp0 <= lp1 <= mine.shape[0], (lp0, lp1, mine.shape)
    return mine[lp0:lp1, d0:d1]
sout = multihost.streamed_aggregate_process_local(
    spod, strict_provider,
    local_participants=mine.shape[0], dimension=40, key=jax.random.PRNGKey(9),
)
np.testing.assert_array_equal(sout, (srows(0).sum(0) + srows(1).sum(0)) % 433)

# clerk-dropout round (round-2 verdict #6): kill process 1's entire clerk
# contribution. On the (4, 2) mesh, process 1 hosts p-shards 2-3 = clerk
# rows 4..7; with k=2, n=8, t=1 the reconstruction threshold is 3, so the
# finale reveals exactly from process-0-hosted rows alone — no value that
# lives on process 1's devices after the clerk scatter enters the result.
from sda_tpu.fields import numtheory
t2, p2, w22, w32 = numtheory.generate_packed_params(2, 8, 8)
assert t2 + 2 <= 4, "quorum must fit in process 0's clerk rows"
dscheme = PackedShamirSharing(2, 8, t2, p2, w22, w32)
dpod = StreamedPod(
    dscheme, FullMasking(p2), mesh=mesh,
    participants_chunk=4, dim_chunk=16,
    surviving_clerks=(0, 1, 2, 3),  # every row process 0 hosts
)
def drows(process):
    return np.random.default_rng(700 + process).integers(
        0, p2, size=(4, 36)
    )
mine_d = drows(pid)
dout = multihost.streamed_aggregate_process_local(
    dpod, lambda lp0, lp1, d0, d1: mine_d[lp0:lp1, d0:d1],
    local_participants=4, dimension=36, key=jax.random.PRNGKey(13),
)
np.testing.assert_array_equal(dout, (drows(0).sum(0) + drows(1).sum(0)) % p2)
print(f"MULTIHOST_OK process={pid}", flush=True)
"""


def test_two_process_pod_round():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(port), str(pid)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=540)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"process {pid} failed:\n{err[-3000:]}"
        assert f"MULTIHOST_OK process={pid}" in out


_CK_WORKER = r"""
import os
import sys

import jax
jax.config.update("jax_platforms", "cpu")

port, pid, attempt, ckdir = (sys.argv[1], int(sys.argv[2]),
                             int(sys.argv[3]), sys.argv[4])
from sda_tpu.mesh import multihost
multihost.initialize(f"localhost:{port}", num_processes=2, process_id=pid)

import numpy as np
from sda_tpu.mesh import StreamedPod, make_multislice_mesh
from sda_tpu.protocol import AdditiveSharing, FullMasking

mesh = make_multislice_mesh(2, 2, 2)
spod = StreamedPod(
    AdditiveSharing(share_count=8, modulus=433), FullMasking(433),
    mesh=mesh, participants_chunk=4, dim_chunk=16,
)

def rows(process):
    return np.random.default_rng(40 + process).integers(0, 433, size=(8, 48))

mine = rows(pid)
calls = {"n": 0}

def provider(lp0, lp1, d0, d1):
    calls["n"] += 1
    if attempt == 0 and calls["n"] > 4:
        # simulate the fleet dying mid-round (both ranks hit the same
        # lockstep tile, like a preemption)
        os._exit(3)
    return mine[lp0:lp1, d0:d1]

out = multihost.streamed_aggregate_process_local(
    spod, provider, local_participants=8, dimension=48,
    key=jax.random.PRNGKey(21),
    checkpoint_path=f"{ckdir}/ck", checkpoint_every_chunks=1,
)
np.testing.assert_array_equal(out, (rows(0).sum(0) + rows(1).sum(0)) % 433)
print(f"CK_OK rank={pid} calls={calls['n']}", flush=True)
"""


def _launch_ck_workers(port, attempt, ckdir):
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return [
        subprocess.Popen(
            [sys.executable, "-c", _CK_WORKER, str(port), str(pid),
             str(attempt), str(ckdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(2)
    ]


def test_multihost_streamed_checkpoint_resume(tmp_path):
    """The fleet dies mid-round; a relaunch resumes from the coordinated
    per-rank snapshots and reveals EXACTLY — including the staggered case
    where one rank's newest snapshot is lost (its slot file deleted, as
    if that rank crashed before its last save landed): every rank falls
    back to the newest cursor all of them still hold."""
    import numpy as np

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    # attempt 0: both ranks die after 4 provider calls. The first exit
    # can kill the peer through the coordination service (rc 1,
    # "connection reset") before it reaches its own os._exit(3) — either
    # death is a valid mid-round crash, and any cursor spread it leaves
    # is what the two-slot history exists for.
    procs = _launch_ck_workers(port, 0, tmp_path)
    for p in procs:
        out, err = p.communicate(timeout=540)
        assert p.returncode != 0, (p.returncode, err[-2000:])

    # simulate rank 1 having crashed BEFORE its newest save landed: drop
    # its newest slot — but only when the surviving (older) cursor still
    # exists in rank 0's history, else the two-slot spread is exceeded
    # and the fleet would (correctly) restart from scratch, which is not
    # the path under test
    def cursor(path):
        with np.load(path) as z:
            return (int(z["di"]), int(z["pi"]), int(z["done_dims"]))

    def rank_slots(rank):
        return [p for p in (tmp_path / f"ck.r{rank}of2.{s}" for s in "ab")
                if p.exists()]

    assert rank_slots(1), "rank 1 saved no snapshot"
    if len(rank_slots(1)) == 2:
        older, newest = sorted(rank_slots(1), key=cursor)
        if cursor(older) in {cursor(p) for p in rank_slots(0)}:
            newest.unlink()
    # resume is possible iff some cursor exists in both ranks' histories
    common = ({cursor(p) for p in rank_slots(0)}
              & {cursor(p) for p in rank_slots(1)})
    resume_expected = bool(common)

    # attempt 1: fresh processes resume and finish exactly
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port2 = s.getsockname()[1]
    procs = _launch_ck_workers(port2, 1, tmp_path)
    full_calls = (16 // 4) * (48 // 16)  # p-tiles x d-tiles = 12
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=540)
        assert p.returncode == 0, f"rank {pid} failed:\n{err[-3000:]}"
        assert f"CK_OK rank={pid}" in out
        calls = int(out.split("calls=")[1].split()[0])
        if resume_expected:
            assert calls < full_calls, (calls, full_calls)
        else:  # coordinated restart: still exact, full provider sweep
            assert calls == full_calls, (calls, full_calls)

    # snapshots removed on completion
    leftovers = list(tmp_path.glob("ck.r*"))
    assert not leftovers, leftovers


_QUAD_WORKER = r"""
import os
import sys

import jax
jax.config.update("jax_platforms", "cpu")

port, pid, attempt, ckdir = (sys.argv[1], int(sys.argv[2]),
                             int(sys.argv[3]), sys.argv[4])
from sda_tpu.mesh import multihost
multihost.initialize(f"localhost:{port}", num_processes=4, process_id=pid)

import numpy as np
from sda_tpu.mesh import StreamedPod, make_multislice_mesh
from sda_tpu.protocol import AdditiveSharing, ChaChaMasking

assert jax.process_count() == 4
assert len(jax.devices()) == 8          # global view
assert len(jax.local_devices()) == 2    # this host's slice

# FOUR slices of (1 participant-shard x 2 dim-shards): every process owns
# exactly one slice, so the per-stage 'd' collectives stay inside a slice
# (ICI) and only the participant fold crosses the four slice boundaries
# (DCN) — the SURVEY §5.8 layout rule at fleet width.
mesh = make_multislice_mesh(4, 1, 2)
spod = StreamedPod(
    AdditiveSharing(share_count=8, modulus=433),
    ChaChaMasking(433, 48, 128),
    mesh=mesh, participants_chunk=4, dim_chunk=16,
)

def rows(process):  # ragged local counts: 3/2/2/2 rows across the ranks
    return np.random.default_rng(500 + process).integers(
        0, 433, size=(2 + (process == 0), 48)
    )

mine = rows(pid)
calls = {"n": 0}

def provider(lp0, lp1, d0, d1):
    assert 0 <= lp0 <= lp1 <= mine.shape[0], (lp0, lp1, mine.shape)
    calls["n"] += 1
    if attempt == 0 and calls["n"] > 2 + pid:
        # STAGGERED loss: each rank dies at a different tile count, so the
        # surviving snapshot histories genuinely disagree (rank 0 first;
        # its death may also kill peers through the coordination service
        # before they reach their own limits — any spread is valid)
        os._exit(3)
    return mine[lp0:lp1, d0:d1]

out = multihost.streamed_aggregate_process_local(
    spod, provider, local_participants=mine.shape[0], dimension=48,
    key=jax.random.PRNGKey(33),
    checkpoint_path=f"{ckdir}/qk", checkpoint_every_chunks=1,
)
expected = sum(rows(r).sum(axis=0) for r in range(4)) % 433
np.testing.assert_array_equal(out, expected)
print(f"QUAD_OK rank={pid} calls={calls['n']}", flush=True)
"""


def _launch_quad_workers(port, attempt, ckdir):
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    return [
        subprocess.Popen(
            [sys.executable, "-c", _QUAD_WORKER, str(port), str(pid),
             str(attempt), str(ckdir)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for pid in range(4)
    ]


def test_four_process_multislice_staggered_loss_resume(tmp_path):
    """Fleet-width evidence in one test (round-4 verdict #6): FOUR
    processes over a 4-slice multislice mesh run a streamed ChaCha round,
    die with STAGGERED per-rank cursors mid-round (plus one rank's newest
    snapshot deleted, as if it crashed before the save landed), and a
    full relaunch resumes from the newest cursor common to all four
    histories — or restarts cleanly when none exists — revealing the
    exact aggregate either way."""
    import numpy as np

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    procs = _launch_quad_workers(port, 0, tmp_path)
    for p in procs:
        out, err = p.communicate(timeout=540)
        assert p.returncode != 0, (p.returncode, err[-2000:])

    def cursor(path):
        with np.load(path) as z:
            return (int(z["di"]), int(z["pi"]), int(z["done_dims"]))

    def rank_slots(rank):
        return [p for p in (tmp_path / f"qk.r{rank}of4.{s}" for s in "ab")
                if p.exists()]

    assert any(rank_slots(r) for r in range(4)), "no rank saved a snapshot"
    # simulate rank 3 crashing before its newest save landed — but only
    # when dropping it still leaves a cursor shared with every other rank,
    # else the (correct) from-scratch restart path would be exercised
    # instead of the resume under test
    slots3 = rank_slots(3)
    if len(slots3) == 2:
        older, newest = sorted(slots3, key=cursor)
        if all(cursor(older) in {cursor(p) for p in rank_slots(r)}
               for r in range(3)):
            newest.unlink()
    histories = [{cursor(p) for p in rank_slots(r)} for r in range(4)]
    resume_expected = bool(set.intersection(*histories)) if all(
        histories) else False

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port2 = s.getsockname()[1]
    procs = _launch_quad_workers(port2, 1, tmp_path)
    # lockstep tile schedule: global p-tiles x d-tiles with the GLOBAL
    # participant count padded to the chunk (3+2+2+2=9 -> 12/4=3 p-tiles,
    # 48/16=3 d-tiles)
    full_calls = 3 * 3
    for pid, p in enumerate(procs):
        out, err = p.communicate(timeout=540)
        assert p.returncode == 0, f"rank {pid} failed:\n{err[-3000:]}"
        assert f"QUAD_OK rank={pid}" in out
        calls = int(out.split("calls=")[1].split()[0])
        if resume_expected:
            assert calls < full_calls, (calls, full_calls)
        else:
            assert calls == full_calls, (calls, full_calls)

    leftovers = list(tmp_path.glob("qk.r*"))
    assert not leftovers, leftovers
