"""Model-scale device plane (mesh/devscale.py + loadgen/devscale.py).

The composition ROADMAP item 3 asked for, pinned piece by piece:

- the watermark tile rule derives grain-aligned widths that scale with
  the budget (no magic constants);
- the sharded scan round (ONE shard_map program streaming dim tiles via
  scan_dim_tiles) is bit-exact vs the plain column sum on the XLA lane
  AND the fused Pallas lane (interpret mode, external randomness) —
  which proves lane equality, since the aggregate is deterministic;
- the DeviceTileSink feeds the streamed pod device-resident tiles,
  bit-exact with the direct provider, prefetched in stream order;
- the DeviceTileCombiner matches crypto.sharing.mod_combine bit-for-bit
  (canonical and unreduced inputs) with one compiled fold shape;
- run_devscale emits the full BENCH record with the comparability tags
  the regression gate keys on.
"""

import jax
import numpy as np
import pytest

from util import external_bits

from sda_tpu import obs
from sda_tpu.crypto.sharing import mod_combine
from sda_tpu.fields import numtheory
from sda_tpu.mesh import (
    DeviceTileCombiner,
    DeviceTileSink,
    ModelScaleRound,
    StreamedPod,
    make_mesh,
    watermark_dim_tile,
)
from sda_tpu.mesh.devscale import bytes_per_dim_column, stream_schedule
from sda_tpu.mesh.streaming import synthetic_block_provider32
from sda_tpu.obs import devprof
from sda_tpu.protocol import (
    ChaChaMasking,
    FullMasking,
    NoMasking,
    PackedShamirSharing,
)
from sda_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_all()
    yield
    obs.reset_all()
    devprof.enable_cost_analysis(False)


def fast_scheme():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    return PackedShamirSharing(3, 8, t, p, w2, w3)


def needs_devices(n):
    return pytest.mark.skipif(
        len(jax.devices()) < n, reason=f"needs {n} virtual devices")


# -- the watermark tile rule --------------------------------------------------

def test_watermark_tile_scales_with_budget_and_stays_on_grain():
    s = fast_scheme()
    mask = FullMasking(s.prime_modulus)
    small = watermark_dim_tile(s, mask, participants_chunk=8, p_shards=4,
                               d_shards=2, watermark_bytes=1 << 20)
    big = watermark_dim_tile(s, mask, participants_chunk=8, p_shards=4,
                             d_shards=2, watermark_bytes=1 << 26)
    grain = 24 * 2  # lcm(k=3, 8 chacha words) x d_shards
    assert small % grain == 0 and big % grain == 0
    assert big > small, "a larger budget must afford a wider tile"
    # more resident participants per device -> narrower tiles
    crowded = watermark_dim_tile(s, mask, participants_chunk=64, p_shards=4,
                                 d_shards=2, watermark_bytes=1 << 20)
    assert crowded < small


def test_watermark_tile_clamps_to_dim_and_floor():
    s = fast_scheme()
    mask = FullMasking(s.prime_modulus)
    tiny_budget = watermark_dim_tile(
        s, mask, participants_chunk=8, p_shards=4, d_shards=2,
        watermark_bytes=1)
    assert tiny_budget == 24 * 2, "floor is one mesh grain"
    clamped = watermark_dim_tile(
        s, mask, participants_chunk=8, p_shards=4, d_shards=2,
        watermark_bytes=1 << 34, dim=1000)
    assert clamped == -(-1000 // 48) * 48


def test_bytes_per_dim_column_counts_masking():
    s = fast_scheme()
    masked = bytes_per_dim_column(s, FullMasking(s.prime_modulus), 8)
    unmasked = bytes_per_dim_column(s, NoMasking(), 8)
    assert masked > unmasked > 0


def test_hbm_watermark_env_override(monkeypatch):
    monkeypatch.setenv("SDA_HBM_WATERMARK", str(123456789))
    assert devprof.hbm_watermark() == 123456789
    monkeypatch.delenv("SDA_HBM_WATERMARK")
    default = devprof.hbm_watermark()
    assert 0 < default <= devprof.HBM_WATERMARK_DEFAULTS["cpu"]


def test_watermark_report_shape(monkeypatch):
    monkeypatch.setenv("SDA_HBM_WATERMARK", "1000")
    block = devprof.watermark_report(peak_bytes=800)
    assert block["within_watermark"] and block["hbm_watermark_ratio"] == 0.8
    over = devprof.watermark_report(peak_bytes=1500)
    assert not over["within_watermark"]


# -- the sharded scan round ---------------------------------------------------

@needs_devices(8)
@pytest.mark.parametrize("mesh_shape", [(4, 2), (8, 1)])
@pytest.mark.parametrize("masking", [
    "none", "full",
    # the device ChaCha expansion compiles are the expensive part of the
    # lattice: covered in the full CI pytest pass, not the tier-1 cut
    pytest.param("chacha", marks=pytest.mark.slow),
])
def test_model_scale_round_xla_lane_exact(mesh_shape, masking):
    s = fast_scheme()
    p = s.prime_modulus
    mask = {"none": None, "full": FullMasking(p),
            "chacha": ChaChaMasking(p, 250, 128)}[masking]
    r = ModelScaleRound(s, mask, mesh=make_mesh(*mesh_shape), dim_tile=96)
    rng = np.random.default_rng(1)
    # ragged: P off the p axis, dim off the tile grain AND the mesh grain
    x = rng.integers(0, 1 << 20, size=(13, 250), dtype=np.int64)
    out = np.asarray(r.aggregate(x, jax.random.PRNGKey(2)))
    np.testing.assert_array_equal(out, x.sum(axis=0) % p)


@needs_devices(8)
def test_model_scale_round_pallas_lane_exact_vs_xla():
    s = fast_scheme()
    p = s.prime_modulus
    key = jax.random.PRNGKey(5)
    rng = np.random.default_rng(3)
    x = rng.integers(0, 1 << 20, size=(16, 288), dtype=np.int64)
    kw = dict(mesh=make_mesh(4, 2), dim_tile=96)
    xla = ModelScaleRound(s, FullMasking(p), **kw)
    pl = ModelScaleRound(s, FullMasking(p), use_pallas=True,
                         pallas_interpret=True,
                         pallas_external_bits_fn=external_bits, **kw)
    assert pl.pallas_active and not xla.pallas_active
    out_x = np.asarray(xla.aggregate(x, key))
    out_p = np.asarray(pl.aggregate(x, key))
    expected = x.sum(axis=0) % p
    np.testing.assert_array_equal(out_x, expected)
    # the aggregate is deterministic, so XLA lane == Pallas lane bit-
    # for-bit whatever randomness each drew (masks cancel per tile,
    # random polynomial rows are annihilated by reconstruction)
    np.testing.assert_array_equal(out_p, out_x)


@needs_devices(8)
def test_model_scale_round_quorum_reveal():
    s = fast_scheme()
    p = s.prime_modulus
    survivors = tuple(range(s.reconstruction_threshold))
    r = ModelScaleRound(s, FullMasking(p), mesh=make_mesh(4, 2),
                        dim_tile=96, surviving_clerks=survivors)
    rng = np.random.default_rng(4)
    x = rng.integers(0, 1 << 20, size=(8, 192), dtype=np.int64)
    out = np.asarray(r.aggregate(x, jax.random.PRNGKey(6)))
    np.testing.assert_array_equal(out, x.sum(axis=0) % p)


@needs_devices(8)
def test_model_scale_round_watermark_default_tile():
    s = fast_scheme()
    r = ModelScaleRound(s, FullMasking(s.prime_modulus),
                        mesh=make_mesh(4, 2))
    assert r.dim_tile % r._grain == 0 and r.dim_tile > 0


# -- streamed pod: uniform tails ---------------------------------------------

@needs_devices(8)
def test_streamed_pod_uniform_tail_exact_and_single_step_shape():
    s = fast_scheme()
    p = s.prime_modulus
    pod = StreamedPod(s, FullMasking(p), mesh=make_mesh(4, 2),
                      participants_chunk=8, dim_chunk=96, uniform_tail=True)
    rng = np.random.default_rng(5)
    x = rng.integers(0, 1 << 20, size=(19, 250), dtype=np.int64)
    out = pod.aggregate(x, key=jax.random.PRNGKey(7))
    np.testing.assert_array_equal(np.asarray(out), x.sum(axis=0) % p)
    prof = devprof.profile("stream.pod.step")
    assert len(prof.shapes) == 1, prof.block_shapes()
    assert prof.retraces == 0


# -- the host -> device sink --------------------------------------------------

def test_stream_schedule_mirrors_drive_order():
    # 2 participant chunks x 3 uniform d tiles, drive order d-outer
    sched = stream_schedule(10, 250, 8, 96, 48, uniform_tail=True)
    assert sched[0] == (0, 8, 0, 96, 96)
    assert sched[1] == (8, 10, 0, 96, 96)
    assert sched[-1] == (8, 10, 192, 250, 96)  # uniform tail keeps width
    ragged = stream_schedule(10, 250, 8, 96, 48, uniform_tail=False)
    assert ragged[-1] == (8, 10, 192, 250, 96)  # grain-rounded 58 -> 96


@needs_devices(8)
def test_sink_fed_streamed_pod_bit_exact_and_prefetched():
    from jax.sharding import NamedSharding, PartitionSpec as P

    s = fast_scheme()
    p = s.prime_modulus
    key = jax.random.PRNGKey(11)
    host = synthetic_block_provider32(p, seed=9)

    def make_pod():
        return StreamedPod(s, FullMasking(p), mesh=make_mesh(4, 2),
                           participants_chunk=8, dim_chunk=96,
                           uniform_tail=True)

    pod = make_pod()
    sink = DeviceTileSink(host, 20, 250, pod.participants_chunk,
                          pod.dim_chunk, grain=pod._grain, uniform_tail=True,
                          sharding=NamedSharding(pod.mesh, P("p", "d")))
    out_sink = pod.aggregate_blocks(sink.provider(), 20, 250, key)
    out_direct = make_pod().aggregate_blocks(host, 20, 250, key)
    np.testing.assert_array_equal(out_sink, out_direct)
    counters = metrics.counter_report("devscale.sink.")
    assert counters.get("devscale.sink.hit", 0) == 9  # 3 p-chunks x 3 tiles
    assert counters.get("devscale.sink.miss", 0) == 0


def test_sink_out_of_order_request_degrades_to_direct_decode():
    host = synthetic_block_provider32(433, seed=1)
    sink = DeviceTileSink(host, 8, 96, 8, 48, grain=24, uniform_tail=True)
    get = sink.provider()
    # not the predicted first block: correct bytes, counted as a miss
    blk = np.asarray(get(0, 8, 48, 96))
    np.testing.assert_array_equal(blk, np.asarray(host(0, 8, 48, 96)))
    assert metrics.counter_report("devscale.sink.").get(
        "devscale.sink.miss") == 1


# -- the device tile combiner -------------------------------------------------

def test_device_tile_combiner_matches_mod_combine():
    p = fast_scheme().prime_modulus
    rng = np.random.default_rng(13)
    vecs = [rng.integers(0, p, size=1000).astype(np.int64)
            for _ in range(9)]
    c = DeviceTileCombiner(p, dim_tile=192)  # 1000 = 5x192 + tail 40
    c.fold(np.stack(vecs[:4]))
    c.fold(np.stack(vecs[4:8]))
    c.fold(vecs[8])  # single-vector bundle
    np.testing.assert_array_equal(c.result(), mod_combine(vecs, p))
    prof = devprof.profile("devscale.clerk_combine")
    # one compiled fold shape per bundle-rows value (4-row and 1-row)
    assert prof.retraces <= 1 and len(prof.shapes) <= 2


def test_device_tile_combiner_unreduced_inputs():
    # Paillier-premixed clerk batches decrypt to UNREDUCED sums: the
    # device fold must canonicalize exactly like mod_combine
    p = 433
    rng = np.random.default_rng(14)
    vecs = [rng.integers(0, 10 * p, size=50).astype(np.int64)
            for _ in range(3)]
    c = DeviceTileCombiner(p, dim_tile=32)
    for v in vecs:
        c.fold(v)
    np.testing.assert_array_equal(c.result(), mod_combine(vecs, p))


def test_device_tile_combiner_empty_and_dim_guard():
    c = DeviceTileCombiner(433)
    assert c.result().size == 0 and c.folded == 0
    c.fold(np.ones((2, 10), dtype=np.int64))
    with pytest.raises(ValueError, match="bundle dim"):
        c.fold(np.ones((2, 11), dtype=np.int64))


def test_device_tile_combiner_watermark_sized_tile(monkeypatch):
    monkeypatch.setenv("SDA_HBM_WATERMARK", str(1 << 20))
    c = DeviceTileCombiner(fast_scheme().prime_modulus)
    c.fold(np.ones((4, 100_000), dtype=np.int64))
    assert c._dim_tile is not None and 128 <= c._dim_tile
    assert c._plan_t.n_tiles >= 1
    np.testing.assert_array_equal(
        c.result(), np.full(100_000, 4, dtype=np.int64))


# -- the benched configuration ------------------------------------------------

@needs_devices(8)
@pytest.mark.slow  # ci.sh runs the same path every CI as the devscale drill
def test_run_devscale_record_smoke():
    from sda_tpu.loadgen import DevScaleProfile, run_devscale

    record = run_devscale(DevScaleProfile(
        dim=25_000, participants=8, participants_chunk=8,
        p_shards=4, d_shards=2, rounds=3, seed=20260804))
    assert record["ok"] and record["exact"]
    assert record["retraces"] == 0 and record["warm_program_reused"]
    assert record["tile_rule"] == "hbm_watermark"
    assert record["dim_tile"] % 48 == 0
    assert record["clerk_fed"]["exact"]
    assert record["clerk_fed"]["sink_misses"] == 0
    assert record["scan_lane"]["exact"]
    assert record["hbm"]["within_watermark"]
    assert record["value"] > 0
    # the comparability tags the regression gate keys on
    for tag in ("dim", "p_shards", "d_shards", "pallas", "platform"):
        assert tag in record, tag
    assert record["roofline_utilization"] is not None
    assert record["compiled_shapes"] == {"stream.pod.step": 1,
                                         "stream.pod.finale": 1}


@needs_devices(8)
@pytest.mark.slow  # the ci.sh devscale drill runs the pallas lane fixed-seed
def test_run_devscale_pallas_interpret_lane():
    from sda_tpu.loadgen import DevScaleProfile, run_devscale

    record = run_devscale(DevScaleProfile(
        dim=4_800, participants=8, participants_chunk=8,
        p_shards=4, d_shards=2, rounds=2, pallas=True,
        pallas_interpret=True, clerk_fed=False, seed=1))
    assert record["ok"] and record["exact"] and record["pallas"]
    assert record["scan_lane"]["exact"]


def test_flagship_dims_pinned():
    from sda_tpu.fl import FLAGSHIP_FAMILIES, flagship_dim, flagship_dims

    dims = flagship_dims()
    assert set(FLAGSHIP_FAMILIES) <= set(dims)
    assert dims["mobilelite"] == 3_731_890   # MobileLite default config
    assert dims["lora"] == 11_782_400        # LoRAMLP adapter sub-tree
    assert dims["devscale"] == 100_000_000   # the ROADMAP model-scale rung
    with pytest.raises(ValueError, match="unknown flagship family"):
        flagship_dim("resnet")
