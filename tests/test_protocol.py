"""Tier-1 serde tests for the protocol layer.

Golden JSON shapes mirror the reference's serde output (external enum
tagging, uuid strings, base64 blobs, declaration-ordered fields) so the two
implementations stay wire-compatible; cf. reference byte-array round-trip
tests (protocol/src/byte_arrays.rs:101-151).
"""

import json

import pytest

from sda_tpu.protocol import (
    B32,
    B64,
    Agent,
    AgentId,
    Aggregation,
    AggregationId,
    AdditiveSharing,
    Binary,
    ChaChaMasking,
    Committee,
    Encryption,
    EncryptionKey,
    EncryptionKeyId,
    FullMasking,
    Labelled,
    LinearMaskingScheme,
    LinearSecretSharingScheme,
    NoMasking,
    PackedShamirSharing,
    Participation,
    ParticipationId,
    Signature,
    Signed,
    SodiumEncryption,
    VerificationKey,
    VerificationKeyId,
    canonical_json,
    signed_encryption_key_from_obj,
)


def test_resource_id_roundtrip():
    a = AgentId.random()
    assert AgentId.from_obj(a.to_obj()) == a
    assert len(a.to_obj()) == 36  # hyphenated uuid
    with pytest.raises(ValueError):
        AgentId("not-a-uuid")


def test_resource_id_types_distinct():
    a = AgentId("00000000-0000-0000-0000-000000000001")
    b = ParticipationId("00000000-0000-0000-0000-000000000001")
    assert a != b  # distinct id types never compare equal


def test_byte_arrays():
    b = B32(bytes(range(32)))
    assert B32.from_obj(b.to_obj()) == b
    with pytest.raises(ValueError):
        B32(bytes(31))
    # default is all-zero, like the reference test factories
    assert B32().data == bytes(32)


def test_binary_base64():
    blob = Binary(b"\x00\x01\xfe\xff")
    assert Binary.from_obj(blob.to_obj()) == blob
    assert blob.to_obj() == "AAH+/w=="


def test_enum_tagging():
    e = Encryption.sodium(b"ciphertext")
    obj = e.to_obj()
    assert list(obj) == ["Sodium"]
    assert Encryption.from_obj(obj) == e

    key = EncryptionKey("Sodium", B32())
    assert EncryptionKey.from_obj(key.to_obj()) == key


def test_masking_scheme_serde():
    for scheme in [
        NoMasking(),
        FullMasking(modulus=433),
        ChaChaMasking(modulus=433, dimension=4, seed_bitsize=128),
    ]:
        assert LinearMaskingScheme.from_obj(scheme.to_obj()) == scheme
    assert NoMasking().to_obj() == "None"
    assert not NoMasking().has_mask
    assert FullMasking(433).has_mask
    assert json.dumps(FullMasking(433).to_obj()) == '{"Full": {"modulus": 433}}'


def test_sharing_scheme_derived_properties():
    # crypto.rs:117-155 derived-property semantics
    additive = AdditiveSharing(share_count=3, modulus=433)
    assert additive.input_size == 1
    assert additive.output_size == 3
    assert additive.privacy_threshold == 2
    assert additive.reconstruction_threshold == 3

    shamir = PackedShamirSharing(
        secret_count=3,
        share_count=8,
        privacy_threshold=4,
        prime_modulus=433,
        omega_secrets=354,
        omega_shares=150,
    )
    assert shamir.input_size == 3
    assert shamir.output_size == 8
    assert shamir.privacy_threshold == 4
    assert shamir.reconstruction_threshold == 7  # t + k

    for scheme in [additive, shamir]:
        assert LinearSecretSharingScheme.from_obj(scheme.to_obj()) == scheme


def test_aggregation_roundtrip():
    agg = Aggregation(
        id=AggregationId.random(),
        title="foo",
        vector_dimension=4,
        modulus=433,
        recipient=AgentId.random(),
        recipient_key=EncryptionKeyId.random(),
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    assert Aggregation.from_obj(agg.to_obj()) == agg
    # replace() mirrors Rust struct-update syntax used throughout tests
    agg2 = agg.replace(title="bar")
    assert agg2.title == "bar" and agg2.id == agg.id


def test_participation_roundtrip_with_optional():
    p = Participation(
        id=ParticipationId.random(),
        participant=AgentId.random(),
        aggregation=AggregationId.random(),
        recipient_encryption=None,
        clerk_encryptions=[(AgentId.random(), Encryption.sodium(b"abc"))],
    )
    assert Participation.from_obj(p.to_obj()) == p
    p2 = Participation.from_obj(
        json.loads(json.dumps(p.to_obj()))
    )  # through actual JSON text
    assert p2 == p

    p3 = Participation(
        id=p.id,
        participant=p.participant,
        aggregation=p.aggregation,
        recipient_encryption=Encryption.sodium(b"mask"),
        clerk_encryptions=p.clerk_encryptions,
    )
    assert Participation.from_obj(p3.to_obj()) == p3


def test_signed_labelled_canonical_bytes():
    """Canonical bytes are compact declaration-ordered JSON (helpers.rs:138-142)."""
    key_id = EncryptionKeyId("11111111-2222-3333-4444-555555555555")
    labelled = Labelled(key_id, EncryptionKey("Sodium", B32()))
    expected = (
        '{"id":"11111111-2222-3333-4444-555555555555",'
        '"body":{"Sodium":"' + "A" * 43 + '="}}'
    )
    assert labelled.canonical() == expected.encode()

    signed = Signed(
        signature=Signature("Sodium", B64()),
        signer=AgentId.random(),
        body=labelled,
    )
    obj = signed.to_obj()
    assert list(obj) == ["signature", "signer", "body"]
    assert signed_encryption_key_from_obj(obj) == signed


def test_committee_tuple_encoding():
    c = Committee(
        aggregation=AggregationId.random(),
        clerks_and_keys=[(AgentId.random(), EncryptionKeyId.random()) for _ in range(3)],
    )
    obj = c.to_obj()
    assert isinstance(obj["clerks_and_keys"][0], list)  # Vec<(A,B)> -> nested arrays
    assert Committee.from_obj(obj) == c


def test_agent_roundtrip():
    agent = Agent(
        id=AgentId.random(),
        verification_key=Labelled(VerificationKeyId.random(), VerificationKey("Sodium", B32())),
    )
    assert Agent.from_obj(agent.to_obj()) == agent
