"""Test configuration: hermetic 8-device virtual CPU mesh.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the multichip
path; benches run on the real chip). Must run before jax is imported.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
