"""Test configuration: hermetic 8-device virtual CPU mesh.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-runs the multichip
path; real-chip perf is bench.py's job).

Environment quirk: this image's sitecustomize registers an `axon` TPU PJRT
plugin in every interpreter and *programmatically* sets jax_platforms, so the
JAX_PLATFORMS env var alone is ignored — we must override via jax.config
before any backend initializes. XLA_FLAGS is read at backend init, which
hasn't happened yet when conftest loads.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")
