"""Binary wire codec (``application/x-sda-bin``) contract.

Three layers of pinning:

- **Golden round-trips**: for each hot-path resource, the binary decode of
  the binary encode equals the object AND equals what the JSON wire
  produces from the same object — one resource, two wires, same value.
- **Golden bytes**: a fixed participation encodes to pinned hex, so a
  silent format drift (field order, endianness, framing) fails loudly
  instead of corrupting cross-version traffic.
- **Mixed-version negotiation** over the real HTTP stack: bin-capable
  client against an old JSON-only server stays JSON; a JSON-pinned client
  against a bin server stays JSON; auto against bin upgrades — and every
  combination completes a bit-exact round.
"""

import uuid

import numpy as np
import pytest

from sda_tpu.protocol import (
    AgentId,
    AggregationId,
    Binary,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Encryption,
    Participation,
    ParticipationId,
    SnapshotId,
    bincodec,
)


def _uuid(n: int) -> uuid.UUID:
    return uuid.UUID(int=n)


def _participation(recipient_encryption=True, clerks=3) -> Participation:
    return Participation(
        id=ParticipationId(_uuid(1)),
        participant=AgentId(_uuid(2)),
        aggregation=AggregationId(_uuid(3)),
        recipient_encryption=(
            Encryption("Sodium", Binary(b"mask-ciphertext"))
            if recipient_encryption else None
        ),
        clerk_encryptions=[
            (AgentId(_uuid(10 + i)),
             Encryption("Sodium", Binary(bytes([i]) * (i + 2))))
            for i in range(clerks)
        ],
    )


def _job() -> ClerkingJob:
    return ClerkingJob(
        id=ClerkingJobId(_uuid(4)),
        clerk=AgentId(_uuid(5)),
        aggregation=AggregationId(_uuid(6)),
        snapshot=SnapshotId(_uuid(7)),
        encryptions=[
            Encryption("Sodium", Binary(b"column-entry-0")),
            Encryption("PackedPaillier", Binary(b"\x02\x01\x01\x2a")),
        ],
    )


def _result() -> ClerkingResult:
    return ClerkingResult(
        job=ClerkingJobId(_uuid(8)),
        clerk=AgentId(_uuid(9)),
        encryption=Encryption("Sodium", Binary(b"combined")),
    )


# -- golden round-trips ------------------------------------------------------

@pytest.mark.parametrize("resource", [
    _participation(),
    _participation(recipient_encryption=False, clerks=1),
    _participation(clerks=0),
    _job(),
    _result(),
], ids=["participation", "participation-nomask", "participation-empty",
        "job", "result"])
def test_round_trip_equals_json_wire(resource):
    decoded = bincodec.decode(bincodec.encode(resource))
    assert decoded == resource
    # same value as the JSON wire derives from the same object
    assert decoded == type(resource).from_obj(resource.to_obj())


def test_golden_bytes_pinned():
    # format drift tripwire: any change to field order, framing, or
    # endianness must show up here as a deliberate golden update
    raw = bincodec.encode(_participation(clerks=1))
    assert raw.hex() == (
        "53444142"  # magic "SDAB"
        "01"        # version
        "01"        # tag: participation
        "00000000000000000000000000000001"  # id
        "00000000000000000000000000000002"  # participant
        "00000000000000000000000000000003"  # aggregation
        "01"        # recipient encryption present
        "00"        # variant Sodium
        "000f" + b"mask-ciphertext".hex() +  # u1 array frame, len 15
        "01"        # one clerk encryption
        "0000000000000000000000000000000a"  # clerk id
        "00"        # variant Sodium
        "0002"      # u1 array frame, len 2
        "0000"      # payload bytes([0]) * 2
    )


def test_binary_is_smaller_than_json():
    import json

    p = _participation(clerks=8)
    assert len(bincodec.encode(p)) < len(json.dumps(p.to_obj()).encode())


# -- array primitive ---------------------------------------------------------

@pytest.mark.parametrize("arr", [
    np.array([], dtype=np.int64),
    np.array([-5, 0, 7, 2**62, -(2**62)], dtype=np.int64),
    np.arange(100, dtype=np.uint32),
    np.frombuffer(b"raw-bytes", dtype=np.uint8),
])
def test_array_round_trip(arr):
    out = []
    bincodec.write_array(out, arr)
    decoded, pos = bincodec.read_array(b"".join(out), 0)
    assert pos == len(b"".join(out))
    assert decoded.dtype.kind == arr.dtype.kind
    np.testing.assert_array_equal(decoded, arr)


def test_array_rejects_garbage():
    with pytest.raises(ValueError):
        bincodec.read_array(b"\xff\x04abcd", 0)  # unknown dtype tag
    out = []
    bincodec.write_array(out, np.array([1, 2], dtype=np.int64))
    with pytest.raises(ValueError):
        bincodec.read_array(b"".join(out)[:-3], 0)  # truncated payload


# -- malformed payloads ------------------------------------------------------

@pytest.mark.parametrize("mutate", [
    lambda raw: b"JSON" + raw[4:],          # bad magic
    lambda raw: raw[:4] + b"\x63" + raw[5:],  # wrong version
    lambda raw: raw[:5] + b"\x7f" + raw[6:],  # unknown tag
    lambda raw: raw[:-1],                    # truncated
    lambda raw: raw + b"\x00",               # trailing bytes
], ids=["magic", "version", "tag", "truncated", "trailing"])
def test_malformed_payload_raises(mutate):
    raw = bincodec.encode(_participation())
    with pytest.raises(ValueError):
        bincodec.decode(mutate(raw))


def test_decode_rejects_wrong_resource_for_typed_decoder():
    with pytest.raises(ValueError):
        bincodec.decode_clerking_job(bincodec.encode(_result()))


# -- incremental (feed-based) decode ----------------------------------------

_FEED_RESOURCES = [
    _participation(),
    _participation(recipient_encryption=False, clerks=1),
    _participation(clerks=0),
    _job(),
    _result(),
]


@pytest.mark.parametrize("resource", _FEED_RESOURCES, ids=[
    "participation", "participation-nomask", "participation-empty",
    "job", "result"])
@pytest.mark.parametrize("chunk", [1, 2, 3, 7, 16, 64, 10_000])
def test_feed_decoder_matches_one_shot_at_every_chunk_size(resource, chunk):
    """The streaming decoder is the same wire contract, delivered in
    arbitrary network-chunk slices — byte-for-byte equal results."""
    raw = bincodec.encode(resource)
    decoder = bincodec.FeedDecoder()
    for pos in range(0, len(raw), chunk):
        decoder.feed(raw[pos:pos + chunk])
    assert decoder.done
    assert decoder.fed_bytes == len(raw)
    assert decoder.finish() == resource
    # the convenience iterator wrapper agrees
    assert bincodec.decode_stream(
        raw[pos:pos + chunk] for pos in range(0, len(raw), chunk)
    ) == resource


def test_feed_decoder_expect_tag_pins_resource_kind():
    raw = bincodec.encode(_result())
    decoder = bincodec.FeedDecoder(bincodec.TAG_PARTICIPATION)
    with pytest.raises(ValueError):
        decoder.feed(raw)


def test_feed_decoder_truncation_and_trailing():
    raw = bincodec.encode(_participation())
    decoder = bincodec.FeedDecoder()
    decoder.feed(raw[:-1])
    assert not decoder.done
    with pytest.raises(ValueError):
        decoder.finish()  # truncated
    decoder = bincodec.FeedDecoder()
    with pytest.raises(ValueError):
        decoder.feed(raw + b"\x00")  # trailing bytes
    # trailing bytes in a LATER chunk are caught too
    decoder = bincodec.FeedDecoder()
    decoder.feed(raw)
    with pytest.raises(ValueError):
        decoder.feed(b"\x00")


@pytest.mark.parametrize("mutate", [
    lambda raw: b"JSON" + raw[4:],
    lambda raw: raw[:4] + b"\x63" + raw[5:],
    lambda raw: raw[:5] + b"\x7f" + raw[6:],
], ids=["magic", "version", "tag"])
def test_feed_decoder_malformed_header_raises_midstream(mutate):
    raw = mutate(bincodec.encode(_participation()))
    decoder = bincodec.FeedDecoder()
    with pytest.raises(ValueError):
        for pos in range(0, len(raw), 3):
            decoder.feed(raw[pos:pos + 3])


def test_feed_decoder_releases_consumed_bytes():
    """O(frame) memory: after feeding everything but the tail, the
    internal buffer holds only the unparsed remainder — consumed field
    bytes (the big ciphertexts) are not retained as raw input."""
    big = Participation(
        id=ParticipationId(_uuid(1)), participant=AgentId(_uuid(2)),
        aggregation=AggregationId(_uuid(3)), recipient_encryption=None,
        clerk_encryptions=[
            (AgentId(_uuid(10 + i)),
             Encryption("Sodium", Binary(bytes(200_000))))
            for i in range(8)
        ],
    )
    raw = bincodec.encode(big)
    decoder = bincodec.FeedDecoder()
    for pos in range(0, len(raw), 65536):
        decoder.feed(raw[pos:pos + 65536])
        # transient buffer never holds more than one unparsed frame tail
        assert len(decoder._buf) < 256_000
    assert decoder.finish() == big


# -- mixed-version negotiation over the real HTTP stack ----------------------

sodium_available = pytest.importorskip(
    "sda_tpu.crypto.sodium", reason="libsodium needed"
).available()
pytestmark_http = pytest.mark.skipif(not sodium_available,
                                     reason="libsodium not present")


@pytest.fixture
def codec_counters():
    from sda_tpu import obs
    from sda_tpu.utils import metrics

    obs.reset_all()
    yield lambda: metrics.counter_report("http.codec.")
    obs.reset_all()


def _run_round(codec: str, bin_server: bool):
    import test_full_loop as tfl
    from sda_tpu.http import SdaHttpClient, SdaHttpServer
    from sda_tpu.server import new_memory_server

    server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0",
                           bin_codec=bin_server).start_background()
    try:
        proxy = SdaHttpClient(server.address, token="codec-test", codec=codec)
        tfl.check_full_aggregation(tfl.agg_default(), proxy)
    finally:
        server.shutdown()


@pytestmark_http
def test_auto_client_upgrades_against_bin_server(codec_counters):
    _run_round("auto", bin_server=True)
    counters = codec_counters()
    # hot POSTs (participations + results) binary, job downloads binary
    assert counters.get("http.codec.bin.in", 0) > 0
    assert counters.get("http.codec.bin.out", 0) > 0


@pytestmark_http
def test_auto_client_stays_json_against_old_server(codec_counters):
    # old server: no advert, no binary parsing — the round still works
    _run_round("auto", bin_server=False)
    counters = codec_counters()
    assert counters.get("http.codec.bin.in", 0) == 0
    assert counters.get("http.codec.bin.out", 0) == 0


@pytestmark_http
def test_json_pinned_client_stays_json_against_bin_server(codec_counters):
    _run_round("json", bin_server=True)
    counters = codec_counters()
    assert counters.get("http.codec.bin.in", 0) == 0
    assert counters.get("http.codec.bin.out", 0) == 0
    assert counters.get("http.codec.json.in", 0) > 0


@pytestmark_http
def test_forced_bin_client_against_bin_server(codec_counters):
    _run_round("bin", bin_server=True)
    counters = codec_counters()
    assert counters.get("http.codec.bin.in", 0) > 0
    assert counters.get("http.codec.json.in", 0) == 0


def test_unknown_codec_mode_rejected():
    from sda_tpu.http import SdaHttpClient

    with pytest.raises(ValueError):
        SdaHttpClient("http://localhost:1", token="t", codec="cbor")
