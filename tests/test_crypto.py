"""Tier-1 tests for the crypto layer: varint wire format, sealed boxes,
signing, scheme-dispatched sharing/masking round-trips, keystores."""

import numpy as np
import pytest

from sda_tpu.crypto import (
    CryptoModule,
    MemoryKeystore,
    encryption,
    masking,
    sharing,
    signing,
    sodium,
    varint,
)
from sda_tpu.protocol import (
    AdditiveSharing,
    Agent,
    AgentId,
    ChaChaMasking,
    FullMasking,
    NoMasking,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.store import Filebased

pytestmark = pytest.mark.skipif(
    not sodium.available(), reason="libsodium not present"
)

GOLDEN_SHAMIR = PackedShamirSharing(3, 8, 4, 433, 354, 150)


def make_agent(keystore):
    crypto = CryptoModule(keystore)
    return Agent(id=AgentId.random(), verification_key=crypto.new_verification_key()), crypto


# ---------------------------------------------------------------------------
# varint wire format

def test_varint_roundtrip_edges():
    vals = np.array(
        [0, 1, -1, 2, -2, 63, 64, -64, -65, 127, 128, 300, -300,
         2**31 - 1, -(2**31), 2**62, -(2**62), 2**63 - 1, -(2**63)],
        dtype=np.int64,
    )
    enc = varint.encode(vals)
    np.testing.assert_array_equal(varint.decode(enc), vals)


def test_varint_zigzag_wire_bytes():
    # zigzag: 0->0, -1->1, 1->2, -2->3; single-byte encodings
    assert varint.encode(np.array([0], dtype=np.int64)) == b"\x00"
    assert varint.encode(np.array([-1], dtype=np.int64)) == b"\x01"
    assert varint.encode(np.array([1], dtype=np.int64)) == b"\x02"
    # 64 -> zigzag 128 -> LEB128 0x80 0x01
    assert varint.encode(np.array([64], dtype=np.int64)) == b"\x80\x01"


def test_varint_bulk_random():
    rng = np.random.default_rng(0)
    vals = rng.integers(-(2**62), 2**62, size=100_000, dtype=np.int64)
    np.testing.assert_array_equal(varint.decode(varint.encode(vals)), vals)


def test_varint_malformed():
    with pytest.raises(ValueError):
        varint.decode(b"\x80")  # dangling continuation
    with pytest.raises(ValueError):
        varint.decode(b"\xff" * 9 + b"\x7f")  # 10th byte overflows u64
    assert varint.decode(b"").shape == (0,)


def test_randomness_modes():
    from sda_tpu.crypto import rand

    assert rand.get_mode() == "secure"  # OS-seeded ChaCha by default
    a = rand.uniform((100,), 433)
    assert a.min() >= 0 and a.max() < 433 and a.dtype == np.int64
    b = rand.uniform((4, 25), 433, mode="fast")
    assert b.shape == (4, 25) and b.min() >= 0 and b.max() < 433
    with pytest.raises(ValueError):
        rand.set_mode("bogus")


# ---------------------------------------------------------------------------
# sealed boxes + signing

def test_sealedbox_roundtrip_and_tamper():
    pk, sk = sodium.box_keypair()
    msg = b"the shares"
    boxed = sodium.seal(msg, pk)
    assert sodium.seal_open(boxed, pk, sk) == msg
    tampered = bytes([boxed[0] ^ 1]) + boxed[1:]
    with pytest.raises(ValueError):
        sodium.seal_open(tampered, pk, sk)
    pk2, sk2 = sodium.box_keypair()
    with pytest.raises(ValueError):
        sodium.seal_open(boxed, pk2, sk2)  # wrong recipient


def test_share_encryptor_decryptor():
    ks = MemoryKeystore()
    crypto = CryptoModule(ks)
    key_id = crypto.new_encryption_key()
    keypair = ks.get_encryption_keypair(key_id)
    enc = crypto.new_share_encryptor(keypair.ek, SodiumEncryption())
    dec = crypto.new_share_decryptor(key_id, SodiumEncryption())
    shares = np.array([0, 1, 432, 5_000_000, 7], dtype=np.int64)
    ct = enc.encrypt(shares)
    assert ct.variant == "Sodium"
    np.testing.assert_array_equal(dec.decrypt(ct), shares)


def test_sign_export_and_verify():
    ks = MemoryKeystore()
    agent, crypto = make_agent(ks)
    key_id = crypto.new_encryption_key()
    signed = crypto.sign_export(agent, key_id)
    assert signed is not None and signed.signer == agent.id
    assert signing.signature_is_valid(agent, signed)
    # tamper with the body -> invalid
    from sda_tpu.protocol import B32, EncryptionKey, Labelled

    tampered = type(signed)(
        signature=signed.signature,
        signer=signed.signer,
        body=Labelled(signed.body.id, EncryptionKey("Sodium", B32(bytes(32)))),
    )
    assert not signing.signature_is_valid(agent, tampered)
    # spoofed signer -> error
    other, _ = make_agent(MemoryKeystore())
    with pytest.raises(ValueError):
        signing.signature_is_valid(other, signed)


# ---------------------------------------------------------------------------
# scheme-dispatched sharing

@pytest.mark.parametrize("scheme", [AdditiveSharing(3, 433), GOLDEN_SHAMIR])
def test_share_combine_reconstruct(scheme):
    gen = sharing.new_share_generator(scheme)
    comb = sharing.new_share_combiner(scheme)
    secrets_a = [1, 2, 3, 4]
    secrets_b = [1, 2, 3, 4]
    shares_a = gen.generate(secrets_a)
    shares_b = gen.generate(secrets_b)
    assert len(shares_a) == scheme.output_size
    combined = [comb.combine([sa, sb]) for sa, sb in zip(shares_a, shares_b)]
    recon = sharing.new_secret_reconstructor(scheme, 4)
    out = recon.reconstruct(list(enumerate(combined)))
    np.testing.assert_array_equal(out % 433, [2, 4, 6, 8])


def test_shamir_reconstruct_with_dropout():
    gen = sharing.new_share_generator(GOLDEN_SHAMIR)
    shares = gen.generate([7, 8, 9, 10, 11])
    recon = sharing.new_secret_reconstructor(GOLDEN_SHAMIR, 5)
    subset = [(i, shares[i]) for i in (7, 5, 4, 3, 2, 1, 0)]
    np.testing.assert_array_equal(recon.reconstruct(subset), [7, 8, 9, 10, 11])


# ---------------------------------------------------------------------------
# masking

@pytest.mark.parametrize(
    "scheme",
    [NoMasking(), FullMasking(433), ChaChaMasking(433, 6, 128)],
)
def test_masking_roundtrip(scheme):
    masker = masking.new_secret_masker(scheme)
    combiner = masking.new_mask_combiner(scheme)
    unmasker = masking.new_secret_unmasker(scheme)
    s1 = np.array([1, 2, 3, 4, 5, 6], dtype=np.int64)
    s2 = np.array([10, 20, 30, 40, 50, 60], dtype=np.int64)
    m1, x1 = masker.mask(s1)
    m2, x2 = masker.mask(s2)
    if scheme.has_mask:
        assert not np.array_equal(x1, s1)  # masked secrets hide inputs
    total_masked = (x1 + x2) % 433
    total_mask = combiner.combine([m1, m2])
    out = unmasker.unmask(total_mask, total_masked)
    np.testing.assert_array_equal(out, (s1 + s2) % 433)


def test_chacha_mask_is_seed_sized():
    scheme = ChaChaMasking(433, 1000, 128)
    masker = masking.new_secret_masker(scheme)
    seed, masked = masker.mask(np.zeros(1000, dtype=np.int64))
    assert seed.shape == (4,)  # 128 bits -> 4 u32 words, not O(d)
    assert masked.shape == (1000,)


# ---------------------------------------------------------------------------
# file keystore

def test_filebased_keystore_roundtrip(tmp_path):
    ks = Filebased(tmp_path)
    crypto = CryptoModule(ks)
    key_id = crypto.new_encryption_key()
    agent, _ = make_agent(ks)

    ks2 = Filebased(tmp_path)  # reopen from disk
    assert ks2.get_encryption_keypair(key_id) is not None
    assert ks2.get_signature_keypair(agent.verification_key.id) is not None
    assert ks2.get_encryption_keypair(type(key_id).random()) is None

    ks.put_alias("agent", "some-id")
    ks.put("some-id", {"hello": 1})
    assert ks2.get_aliased("agent") == {"hello": 1}


def test_crypto_module_with_file_keystore_encrypt(tmp_path):
    ks = Filebased(tmp_path)
    crypto = CryptoModule(ks)
    key_id = crypto.new_encryption_key()
    keypair = ks.get_encryption_keypair(key_id)
    ct = crypto.new_share_encryptor(keypair.ek, SodiumEncryption()).encrypt([1, 2, 3])
    out = crypto.new_share_decryptor(key_id, SodiumEncryption()).decrypt(ct)
    np.testing.assert_array_equal(out, [1, 2, 3])


# ---------------------------------------------------------------------------
# small-work host dispatch (phone-sized vectors skip the device entirely)

def test_small_work_host_path_is_exact_and_device_free(monkeypatch):
    """Phone-sized rounds must not pay XLA compile/dispatch latency: below
    HOST_PATH_MAX the scheme ops run on the NumPy oracle path, bit-identical
    to the device path given the same randomness."""
    from sda_tpu import fields
    from sda_tpu.crypto import rand, sharing
    from sda_tpu.crypto.masking import FullMasker
    from sda_tpu.crypto.sharing import (
        AdditiveShareGenerator,
        PackedShamirReconstructor,
        PackedShamirShareGenerator,
        ShareCombiner,
    )
    from sda_tpu.protocol import AdditiveSharing, PackedShamirSharing

    pss = PackedShamirSharing(3, 8, 4, 433, 354, 150)
    adds = AdditiveSharing(share_count=3, modulus=433)
    rng = np.random.default_rng(5)
    secrets = rng.integers(0, 433, size=10)

    fixed = rand.uniform((pss.privacy_threshold, 4), 433)
    monkeypatch.setattr(rand, "uniform", lambda shape, m, mode=None: fixed.copy())

    device_before = fields.packed_reconstruct._cache_size()
    host_shares = PackedShamirShareGenerator(pss).generate(secrets)
    monkeypatch.setattr(sharing, "HOST_PATH_MAX", 0)
    # re-run the SAME randomness on the device path
    device_shares = PackedShamirShareGenerator(pss).generate(secrets)
    for h, d in zip(host_shares, device_shares):
        np.testing.assert_array_equal(h, d)

    monkeypatch.setattr(sharing, "HOST_PATH_MAX", 1 << 16)
    recon = PackedShamirReconstructor(pss, dimension=10)
    got = recon.reconstruct(list(enumerate(host_shares))[: pss.reconstruction_threshold + 1])
    np.testing.assert_array_equal(got, secrets)
    # reconstruction of this tiny round never compiled a device kernel
    assert fields.packed_reconstruct._cache_size() == device_before

    combined = ShareCombiner(433).combine([s % 433 for s in host_shares[:3]])
    np.testing.assert_array_equal(
        combined, np.stack(host_shares[:3]).sum(axis=0) % 433
    )

    masker = FullMasker(433)
    monkeypatch.setattr(
        rand, "uniform", lambda shape, m, mode=None: np.full(shape, 7, dtype=np.int64)
    )
    mask, masked = masker.mask(secrets)
    np.testing.assert_array_equal(masked, (secrets + 7) % 433)
    np.testing.assert_array_equal(masker.unmask(mask, masked), secrets)

