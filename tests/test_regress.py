"""Regression-gate contract (``sda_tpu.obs.regress`` / ``sda-bench``).

Golden fixtures in ``tests/fixtures/regress/`` cover the four scenarios
the gate must get right: a clean pass, a confirmed regression (synthetic
2x slowdown), a noisy-but-within-threshold record, and the honest
error-record bench line (skipped, never flagged). The committed repo
trajectory BENCH_r01-r05 itself must gate green — that is the
acceptance bar every future perf PR inherits.
"""

import glob
import json
import os

import pytest

from sda_tpu.obs import regress

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "regress")


def _fx(*names):
    return [os.path.join(FIXTURES, n) for n in names]


def _history(*extra):
    base = [f"BENCH_r{n:02d}.json" for n in range(1, 6)]
    return _fx(*base, *extra)


# -- scenarios ---------------------------------------------------------------

def test_clean_trajectory_passes():
    assert regress.main(_history()) == 0
    result = regress.check(regress.load_records(_history()))
    assert result["checked"]
    assert result["regressions"] == []
    # r01 (no parsed measurement) skipped, not flagged
    assert any("r01" in s["path"] for s in result["skipped"])


def test_clean_continuation_passes():
    assert regress.main(_history("BENCH_r06_clean.json")) == 0


def test_synthetic_2x_slowdown_is_confirmed_regression():
    paths = _history("BENCH_r06_regression.json")
    assert regress.main(paths) == 1
    result = regress.check(regress.load_records(paths))
    assert "value" in result["regressions"]
    assert "round_seconds_marginal" in result["regressions"]
    by_metric = {r["metric"]: r for r in result["rows"]}
    assert by_metric["value"]["verdict"] == "REGRESSION"
    # compile_seconds stays advisory: never gates the exit code
    assert not by_metric.get("compile_seconds", {"gates": False})["gates"]


def test_noisy_within_threshold_passes():
    paths = _history("BENCH_r06_noisy.json")
    assert regress.main(paths) == 0
    result = regress.check(regress.load_records(paths))
    row = {r["metric"]: r for r in result["rows"]}["value"]
    # the deficit is real and visible, but inside the noise threshold
    assert row["delta"] < -0.10
    assert row["verdict"].startswith("pass")


def test_error_record_as_newest_is_skipped_not_flagged():
    paths = _history("BENCH_r06_error.json")
    assert regress.main(paths) == 0
    result = regress.check(regress.load_records(paths))
    assert any("r06_error" in s["path"] for s in result["skipped"])
    # the gate falls back to the newest REAL record (r05)
    assert result["newest"].endswith("BENCH_r05.json")
    assert result["regressions"] == []


def test_advisory_mode_reports_but_exits_zero(capsys):
    paths = _history("BENCH_r06_regression.json")
    assert regress.main(paths + ["--advisory"]) == 0
    assert "REGRESSION" in capsys.readouterr().out


def test_malformed_record_exits_2(tmp_path):
    bad = tmp_path / "BENCH_r99.json"
    bad.write_text("this is not json")
    assert regress.main(_history() + [str(bad)]) == 2
    shapeless = tmp_path / "BENCH_r98.json"
    shapeless.write_text(json.dumps({"hello": "world"}))
    assert regress.main(_history() + [str(shapeless)]) == 2


def test_platform_mismatch_is_not_compared(tmp_path):
    # a TPU record following CPU history has no comparable window: the
    # 3-orders CPU/chip gap must never read as a 1000x "improvement",
    # nor a later CPU fallback as a 1000x regression
    rec = json.load(open(_fx("BENCH_r05.json")[0]))
    rec["n"] = 6
    rec["parsed"]["platform"] = "tpu"
    rec["parsed"]["value"] = rec["parsed"]["value"] * 700
    path = tmp_path / "BENCH_r06.json"
    path.write_text(json.dumps(rec))
    result = regress.check(regress.load_records(_history() + [str(path)]))
    assert not result["checked"]
    assert "insufficient comparable history" in result["note"]


def test_codec_tagged_record_is_not_compared_to_untagged_history():
    # a codec-tagged record (the wire codec IS the variable under test in
    # the ci.sh codec drill) opens its own trajectory: a 2x-slower value
    # tagged codec=bin must NOT gate against the untagged JSON-wire
    # history — and must not pass as its continuation either
    paths = _history("BENCH_r06_codec_bin.json")
    result = regress.check(regress.load_records(paths))
    assert not result["checked"]
    assert "insufficient comparable history" in result["note"]
    assert regress.main(paths) == 0


def test_codec_tagged_records_gate_among_themselves(tmp_path):
    # same-codec records DO form a comparable window: a 2x slowdown
    # within the bin-wire trajectory is still a confirmed regression
    base = json.load(open(_fx("BENCH_r06_codec_bin.json")[0]))
    paths = []
    for n, value in enumerate([3600000, 3650000, 3580000, 1700000], start=6):
        rec = json.loads(json.dumps(base))
        rec["n"] = n
        rec["parsed"]["value"] = value
        rec["parsed"]["round_seconds_marginal"] = 1e7 / value
        path = tmp_path / f"BENCH_r{n:02d}.json"
        path.write_text(json.dumps(rec))
        paths.append(str(path))
    result = regress.check(regress.load_records(_history() + paths))
    assert result["checked"]
    assert "value" in result["regressions"]


def test_devscale_tags_open_their_own_trajectory(tmp_path):
    # model-scale device records key comparability on (dim, p_shards,
    # d_shards, pallas): a dim-1e8 sharded record must never gate
    # against single-chip history — and WITHIN the devscale lineage, a
    # different mesh topology or kernel lane opens a fresh window too
    base = {"metric": "model-scale device round elements/sec",
            "platform": "cpu", "dim": 100_000_000, "p_shards": 4,
            "d_shards": 2, "pallas": False}
    paths = []
    for n, value in enumerate([5_000_000, 5_100_000, 4_900_000], start=1):
        rec = dict(base, value=value)
        path = tmp_path / f"BENCH_d{n:02d}.json"
        path.write_text(json.dumps(rec))
        paths.append(str(path))
    # same tags: a 2x slowdown gates
    slow = tmp_path / "BENCH_d09.json"
    slow.write_text(json.dumps(dict(base, value=2_400_000)))
    result = regress.check(regress.load_records(paths + [str(slow)]))
    assert result["checked"] and "value" in result["regressions"]
    # a different topology must NOT gate against that history
    other = tmp_path / "BENCH_d10.json"
    other.write_text(json.dumps(dict(base, p_shards=8, d_shards=1,
                                     value=2_400_000)))
    result = regress.check(regress.load_records(paths + [str(other)]))
    assert not result["checked"]
    # ... nor the other kernel lane, nor a different dim
    lane = tmp_path / "BENCH_d11.json"
    lane.write_text(json.dumps(dict(base, pallas=True, value=2_400_000)))
    assert not regress.check(regress.load_records(paths + [str(lane)]))[
        "checked"]
    dim = tmp_path / "BENCH_d12.json"
    dim.write_text(json.dumps(dict(base, dim=3_731_890, value=2_400_000)))
    assert not regress.check(regress.load_records(paths + [str(dim)]))[
        "checked"]


def test_devscale_advisory_metrics_reported_not_gated(tmp_path):
    # roofline utilization and the hbm watermark ratio ride the record as
    # advisory rows: a worse newest value is REPORTED but never exits 1
    base = {"metric": "model-scale device round elements/sec",
            "platform": "cpu", "dim": 1000, "p_shards": 4, "d_shards": 2,
            "pallas": False, "value": 5_000_000,
            "roofline_utilization": 0.5, "hbm_watermark_ratio": 0.4}
    paths = []
    for n in range(3):
        path = tmp_path / f"BENCH_a{n:02d}.json"
        path.write_text(json.dumps(base))
        paths.append(str(path))
    worse = tmp_path / "BENCH_a09.json"
    worse.write_text(json.dumps(dict(base, roofline_utilization=0.1,
                                     hbm_watermark_ratio=0.99)))
    result = regress.check(regress.load_records(paths + [str(worse)]))
    rows = {r["metric"]: r for r in result["rows"]}
    assert not rows["roofline_utilization"]["gates"]
    assert not rows["hbm_watermark_ratio"]["gates"]
    assert result["regressions"] == []
    assert regress.main(paths + [str(worse)]) == 0


def test_record_carried_direction_lower(tmp_path):
    # the FL suite's rounds-to-target record tags itself direction=lower:
    # MORE rounds is the regression, fewer is an improvement — the gate
    # must honor the tag instead of the default higher-is-better
    def record(n, value):
        path = tmp_path / f"FL_r{n:02d}.json"
        path.write_text(json.dumps({
            "metric": "rounds to target accuracy 0.8 (secure FedAvg)",
            "value": value, "direction": "lower", "unit": "rounds",
            "platform": "cpu", "seed": 1,
        }))
        return str(path)

    history = [record(n, v) for n, v in enumerate([3, 3, 4])]
    worse = regress.check(regress.load_records(history + [record(9, 8)]))
    assert worse["checked"]
    assert "value" in worse["regressions"]
    better = regress.check(regress.load_records(history + [record(9, 2)]))
    assert better["checked"]
    assert better["regressions"] == []


def test_json_output_mode(capsys):
    assert regress.main(_history() + ["--json"]) == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(out)
    assert parsed["checked"] and parsed["regressions"] == []


def test_raw_bench_line_appended_after_wrappers_is_gated(tmp_path):
    # a fresh RAW bench line (no driver wrapper) appended after the
    # committed wrapper trajectory must be treated as the NEWEST record
    # and gated — not lose a seq tiebreak and silently become history
    slow = json.load(open(_fx("BENCH_r06_regression.json")[0]))["parsed"]
    path = tmp_path / "fresh_run.json"
    path.write_text(json.dumps(slow))
    paths = _history() + [str(path)]
    result = regress.check(regress.load_records(paths))
    assert result["newest"] == str(path)
    assert "value" in result["regressions"]
    assert regress.main(paths) == 1


# -- the committed repo trajectory itself ------------------------------------

def test_committed_bench_trajectory_gates_green():
    committed = sorted(glob.glob(os.path.join(regress.repo_root(),
                                              "BENCH_r*.json")))
    if len(committed) < 3:
        pytest.skip("repo has no committed bench trajectory")
    assert regress.main(committed) == 0


def test_committed_trajectory_with_synthetic_2x_slowdown_fails(tmp_path):
    committed = sorted(glob.glob(os.path.join(regress.repo_root(),
                                              "BENCH_r*.json")))
    if len(committed) < 3:
        pytest.skip("repo has no committed bench trajectory")
    records = regress.load_records(committed)
    real = [e for e in records if e["record"] is not None]
    # synthesize the slowdown from the newest record that HAS comparable
    # history — tagged records (codec, fleet_nodes) open fresh lineages
    # with nothing to gate against, by design
    newest = next(
        e for e in reversed(real)
        if sum(regress._comparable(e["record"], other["record"])
               for other in real if other is not e) >= 2)
    slow = {"n": 99, "cmd": "synthetic", "rc": 0, "tail": "",
            "parsed": dict(newest["record"])}
    slow["parsed"]["value"] = newest["record"]["value"] / 2
    if isinstance(newest["record"].get("round_seconds_marginal"),
                  (int, float)):
        slow["parsed"]["round_seconds_marginal"] = \
            newest["record"]["round_seconds_marginal"] * 2
    path = tmp_path / "BENCH_r99.json"
    path.write_text(json.dumps(slow))
    assert regress.main(committed + [str(path)]) == 1


# -- the sda-bench front-end -------------------------------------------------

def test_sda_bench_check_forwards_to_regress():
    from sda_tpu.cli import bench as sda_bench

    assert sda_bench.main(["--check", *_history()]) == 0
    assert sda_bench.main(
        ["--check", *_history("BENCH_r06_regression.json")]) == 1
    assert sda_bench.main(
        ["--check", "--advisory",
         *_history("BENCH_r06_regression.json")]) == 0
