"""Device-side ChaCha20 expansion vs the host oracle — bit-exact.

CHACHA_PRG_V1 is a versioned wire spec (fields/chacha.py): the jnp
implementation must reproduce it word-for-word, including the overdraw
layout and the mod reduction, for any seed and modulus — and the combined
(recipient hot loop) path must match per-seed host expansion summed.
"""

import numpy as np
import pytest

from sda_tpu.fields import chacha, chacha_jax


@pytest.mark.parametrize("seed", [
    [0], [1, 2, 3, 4], [0xFFFFFFFF] * 8, [0xDEADBEEF, 0x12345678],
])
@pytest.mark.parametrize("nblocks", [1, 3, 7])
def test_block_words_match_host(seed, nblocks):
    seed_words = np.zeros(8, dtype=np.uint32)
    for i, w in enumerate(seed):
        seed_words[i] = np.uint32(w)
    got = np.asarray(chacha_jax.chacha_block_words(seed_words, 0, nblocks=nblocks))
    exp = chacha.chacha_block_words(seed, 0, nblocks)
    np.testing.assert_array_equal(got, exp)


@pytest.mark.parametrize("modulus", [433, 536870233, (1 << 61) + 1 - 2])
@pytest.mark.parametrize("dimension", [1, 7, 8, 9, 100, 1000])
def test_expand_mask_matches_host(modulus, dimension):
    seed = chacha.random_seed(128)
    got = chacha_jax.expand_mask(seed, dimension, modulus, prg=chacha.CHACHA_PRG_V1)
    exp = chacha.expand_mask(seed, dimension, modulus)
    np.testing.assert_array_equal(got, exp)


def test_combine_masks_matches_host_sum():
    modulus, dimension = 536870233, 257
    seeds = [chacha.random_seed(128) for _ in range(5)]
    got = chacha_jax.combine_masks(seeds, dimension, modulus, prg=chacha.CHACHA_PRG_V1)
    exp = np.zeros(dimension, dtype=np.int64)
    for s in seeds:
        exp = (exp + chacha.expand_mask(s, dimension, modulus)) % modulus
    np.testing.assert_array_equal(got, exp)


def test_combine_masks_large_modulus_no_i64_overflow():
    """A flat int64 sum of S masks wraps once S*(modulus-1) >= 2^63; the
    chunked modular fold must stay exact (advisor round-1 finding)."""
    modulus = (1 << 61) - 1  # 4+ masks of this size overflow a flat i64 sum
    dimension = 33
    seeds = [chacha.random_seed(128) for _ in range(9)]
    got = chacha_jax.combine_masks(seeds, dimension, modulus, prg=chacha.CHACHA_PRG_V1)
    exp = np.zeros(dimension, dtype=object)
    for s in seeds:
        exp = (exp + chacha.expand_mask(s, dimension, modulus)) % modulus
    np.testing.assert_array_equal(got, exp.astype(np.int64))


def test_combine_masks_rejects_out_of_range_modulus():
    with pytest.raises(ValueError):
        chacha_jax.combine_masks([[1]], 4, 1 << 62, prg=chacha.CHACHA_PRG_V1)


def test_native_oracle_agreement():
    """When the C++ kernel is available, all three implementations agree."""
    from sda_tpu import native

    if not native.available():
        pytest.skip("native library unavailable")
    modulus, dimension = 433, 123
    seed = [7, 11, 13, 17]
    a = chacha.expand_mask(seed, dimension, modulus)
    b = chacha_jax.expand_mask(seed, dimension, modulus, prg=chacha.CHACHA_PRG_V1)
    c = native.chacha_expand_mask(seed, dimension, modulus, prg=chacha.CHACHA_PRG_V1)
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(a, c)
