"""Snapshot pipelining: several rounds of one aggregation, independently.

SURVEY §2.4: the reference server supports multiple snapshots per
aggregation (server/src/server.rs:104-129) but its client never drives
them. Here the recipient can freeze successive participation sets with
``snapshot_aggregation`` and reveal each round by snapshot id: round A
(first two participants) and round B (all four) clerked and revealed
independently, each bit-exact for its own frozen set.
"""

import numpy as np
import pytest

from sda_tpu.client import SdaClient
from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    FullMasking,
    SodiumEncryption,
)
from sda_tpu.server import new_memory_server

pytestmark = pytest.mark.skipif(not sodium.available(), reason="libsodium not present")


def _client(service):
    ks = MemoryKeystore()
    c = SdaClient(SdaClient.new_agent(ks), ks, service)
    c.upload_agent()
    return c


def test_two_pipelined_snapshots_reveal_their_own_sets():
    service = new_memory_server()
    recipient = _client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    clerks = [_client(service) for _ in range(3)]
    for c in clerks:
        c.upload_encryption_key(c.new_encryption_key())

    agg = Aggregation(
        id=AggregationId.random(), title="pipeline", vector_dimension=4, modulus=433,
        recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(agg)
    recipient.begin_aggregation(agg.id)

    # round A: two participants
    for offset in (0, 1):
        _client(service).participate([1 + offset, 2, 3, 4], agg.id)
    snap_a = recipient.snapshot_aggregation(agg.id)
    for c in clerks + [recipient]:
        c.run_chores(-1)
    out_a = recipient.reveal_aggregation(agg.id, snapshot_id=snap_a)
    np.testing.assert_array_equal(out_a.positive().values, [3, 4, 6, 8])

    # round B: two more participants join; B's frozen set is all four
    for offset in (2, 3):
        _client(service).participate([1 + offset, 2, 3, 4], agg.id)
    snap_b = recipient.snapshot_aggregation(agg.id)
    for c in clerks + [recipient]:
        c.run_chores(-1)
    out_b = recipient.reveal_aggregation(agg.id, snapshot_id=snap_b)
    np.testing.assert_array_equal(out_b.positive().values, [10, 8, 12, 16])

    # round A's result is still addressable after B completed
    out_a2 = recipient.reveal_aggregation(agg.id, snapshot_id=snap_a)
    np.testing.assert_array_equal(out_a2.positive().values, [3, 4, 6, 8])

    # unknown snapshot id fails closed
    from sda_tpu.protocol import NotFound, SnapshotId

    with pytest.raises(NotFound):
        recipient.reveal_aggregation(agg.id, snapshot_id=SnapshotId.random())
