"""Fleet-plane tests: contended-idempotent snapshots, consistent-hash
routing, early lease release, and the multi-process launcher.

The scale-out contract (docs/scaling.md) is that N independent server
handles over ONE shared backend behave like one server: snapshot creation
is single-winner at the store (not merely retry-idempotent within a
process), the loser converges on the winner's frozen set and deterministic
``uuid5(snapshot, clerk)`` job set bit-exactly, and a draining worker
hands its clerking-job leases back so a peer reissues them immediately.
These tests race two REAL handles per backend — two connections for
sqlite, two store instances over one directory for jsonfs, one shared
dict-backed store for memory, one shared fake database for mongo — which
is exactly the sharing shape two ``sdad`` OS processes have.
"""

import threading

import pytest

from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    ClerkingResult,
    Committee,
    NoMasking,
    Participation,
    ParticipationId,
    Snapshot,
    SnapshotId,
    SodiumEncryption,
)
from sda_tpu.server import (
    SdaServerService,
    new_jsonfs_server,
    new_mongo_server,
    new_sqlite_server,
)
from sda_tpu.server.core import SdaServer
from sda_tpu.server.routing import NODE_HEADER, HashRing

from util import mock_encryption, new_agent, new_full_agent

BACKENDS = ["memory", "sqlite", "jsonfs", "fakemongo"]


def _two_handles(backend, tmp_path):
    """Two INDEPENDENT service handles over one shared backend — the
    sharing shape of two fleet worker processes."""
    if backend == "memory":
        from sda_tpu.server.memory import (
            MemoryAggregationsStore,
            MemoryAgentsStore,
            MemoryAuthTokensStore,
            MemoryClerkingJobsStore,
        )

        stores = dict(
            agents_store=MemoryAgentsStore(),
            auth_tokens_store=MemoryAuthTokensStore(),
            aggregation_store=MemoryAggregationsStore(),
            clerking_job_store=MemoryClerkingJobsStore(),
        )
        return SdaServerService(SdaServer(**stores)), \
            SdaServerService(SdaServer(**stores))
    if backend == "sqlite":
        path = tmp_path / "shared.db"
        return new_sqlite_server(path), new_sqlite_server(path)
    if backend == "jsonfs":
        root = tmp_path / "shared-jfs"
        return new_jsonfs_server(root), new_jsonfs_server(root)
    from fake_mongo import FakeDatabase

    db = FakeDatabase()
    return new_mongo_server(db), new_mongo_server(db)


@pytest.fixture(params=BACKENDS)
def handles(request, tmp_path):
    return _two_handles(request.param, tmp_path)


def _world(service, clerks=4, participants=6):
    recipient, recipient_key = new_full_agent(service)
    committee = [new_full_agent(service) for _ in range(clerks)]
    agg = Aggregation(
        id=AggregationId.random(), title="fleet", vector_dimension=4,
        modulus=433, recipient=recipient.id,
        recipient_key=recipient_key.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=clerks,
                                                 modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    service.create_committee(recipient, Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for (a, k) in committee],
    ))
    for i in range(participants):
        agent = new_agent()
        service.create_agent(agent, agent)
        service.create_participation(agent, Participation(
            id=ParticipationId.random(), participant=agent.id,
            aggregation=agg.id, recipient_encryption=None,
            clerk_encryptions=[(a.id, mock_encryption(bytes([i])))
                               for (a, _) in committee],
        ))
    return recipient, committee, agg


# ---------------------------------------------------------------------------
# contended-idempotent snapshot creation


def test_contended_create_snapshot_single_winner(handles):
    """Two handles race the FULL snapshot pipeline on the same snapshot
    id: exactly one store-level winner, one snapshot record, exactly one
    job per clerk (zero duplicates, zero lost), identical frozen set."""
    a, b = handles
    recipient, committee, agg = _world(a, clerks=4, participants=6)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)

    barrier = threading.Barrier(2)
    errors = []

    def race(service):
        try:
            barrier.wait()
            service.create_snapshot(recipient, snap)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=race, args=(s,)) for s in (a, b)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors

    # one snapshot record, visible through BOTH handles
    for service in (a, b):
        store = service.server.aggregation_store
        assert store.list_snapshots(agg.id) == [snap.id]
        assert store.get_snapshot(agg.id, snap.id) is not None
        assert store.has_snapshot_freeze(agg.id, snap.id)
        assert store.count_participations_snapshot(agg.id, snap.id) == 6

    # exactly one job per clerk, same deterministic id through both
    # handles, full frozen column each — convergence, not duplication
    from sda_tpu.server.snapshot import clerking_job_id

    for clerk, _ in committee:
        expected_id = clerking_job_id(snap.id, clerk.id)
        for service in (a, b):
            job = service.server.clerking_job_store.get_clerking_job(
                clerk.id, expected_id)
            assert job is not None, "clerk lost its job"
            assert job.id == expected_id
            assert len(job.encryptions) == 6
        # the queue holds ONLY that one job: polling it away empties it
        store = a.server.clerking_job_store
        first = store.poll_clerking_job(clerk.id)
        assert first is not None and first.id == expected_id
        store.create_clerking_result(ClerkingResult(
            job=first.id, clerk=clerk.id,
            encryption=mock_encryption(b"done")))
        assert store.poll_clerking_job(clerk.id) is None, "duplicate job"


def test_store_level_conditional_inserts(handles):
    """The two store primitives under the contract: ``create_snapshot``
    and ``snapshot_participations`` each return True exactly once when
    raced from two handles, and never overwrite the winner."""
    a, b = handles
    recipient, committee, agg = _world(a, clerks=2, participants=3)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)

    for op in ("snapshot_participations", "create_snapshot"):
        barrier = threading.Barrier(2)
        outcomes = []
        lock = threading.Lock()

        def race(store, op=op):
            barrier.wait()
            if op == "create_snapshot":
                won = store.create_snapshot(snap)
            else:
                won = store.snapshot_participations(agg.id, snap.id)
            with lock:
                outcomes.append(bool(won))

        threads = [
            threading.Thread(target=race,
                             args=(s.server.aggregation_store,))
            for s in (a, b)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(outcomes) == [False, True], \
            f"{op}: want exactly one winner, got {outcomes}"

    # and a replay AFTER the race is a clean loss on both handles
    for service in (a, b):
        store = service.server.aggregation_store
        assert store.create_snapshot(snap) is False
        assert store.snapshot_participations(agg.id, snap.id) is False
        assert store.count_participations_snapshot(agg.id, snap.id) == 3


def test_late_participation_does_not_widen_frozen_set(handles):
    """A participation landing between the winner's freeze and the
    loser's converge must NOT enter the frozen set (mixing share
    generations across clerk columns is the failure mode)."""
    a, b = handles
    recipient, committee, agg = _world(a, clerks=2, participants=4)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)

    assert a.server.aggregation_store.snapshot_participations(
        agg.id, snap.id) is True
    # late arrival through the OTHER handle
    agent = new_agent()
    b.create_agent(agent, agent)
    b.create_participation(agent, Participation(
        id=ParticipationId.random(), participant=agent.id,
        aggregation=agg.id, recipient_encryption=None,
        clerk_encryptions=[(c.id, mock_encryption(b"late"))
                           for (c, _) in committee],
    ))
    assert b.server.aggregation_store.snapshot_participations(
        agg.id, snap.id) is False
    for service in (a, b):
        assert service.server.aggregation_store \
            .count_participations_snapshot(agg.id, snap.id) == 4


# ---------------------------------------------------------------------------
# early lease release (graceful drain)


@pytest.mark.parametrize("backend", BACKENDS)
def test_release_clerking_job_lease(backend, tmp_path):
    """A released lease makes the job immediately pollable by the peer
    handle; done or never-leased jobs release as False."""
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a, clerks=1, participants=2)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    clerk = committee[0][0]
    store_a = a.server.clerking_job_store
    store_b = b.server.clerking_job_store

    lease = store_a.lease_clerking_job(clerk.id, lease_seconds=300.0)
    assert lease is not None
    job, _expires = lease
    # leased: invisible to the peer until the visibility timeout
    assert store_b.lease_clerking_job(clerk.id, lease_seconds=300.0) is None

    assert store_a.release_clerking_job_lease(clerk.id, job.id) is True
    # released: the peer's next poll gets it immediately
    release = store_b.lease_clerking_job(clerk.id, lease_seconds=300.0)
    assert release is not None and release[0].id == job.id

    # releasing an already-released lease is a no-op
    assert store_a.release_clerking_job_lease(clerk.id, job.id) in (
        True, False)  # b holds it now; a's release hands it back again
    store_b.create_clerking_result(ClerkingResult(
        job=job.id, clerk=clerk.id, encryption=mock_encryption(b"done")))
    # done: nothing to release, nothing to poll
    assert store_b.release_clerking_job_lease(clerk.id, job.id) is False
    assert store_a.lease_clerking_job(clerk.id, lease_seconds=1.0) is None


@pytest.mark.parametrize("backend", BACKENDS)
def test_release_is_compare_and_release(backend, tmp_path):
    """A drain must not release a lease that lapsed and was re-granted to
    a peer: releasing with the ORIGINAL expiry instant is a no-op, so a
    third worker cannot be handed the peer's in-flight job."""
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a, clerks=1, participants=1)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    clerk = committee[0][0]
    store_a = a.server.clerking_job_store
    store_b = b.server.clerking_job_store

    job, old_expires = store_a.lease_clerking_job(
        clerk.id, lease_seconds=5.0, now=1000.0)
    # the lease lapses unanswered; peer b re-leases (reissue)
    job2, new_expires = store_b.lease_clerking_job(
        clerk.id, lease_seconds=5.0, now=2000.0)
    assert job2.id == job.id and new_expires != old_expires
    # a's drain, arriving late with its stale expiry, must not touch it
    assert store_a.release_clerking_job_lease(
        clerk.id, job.id, expires=old_expires) is False
    assert store_a.lease_clerking_job(
        clerk.id, lease_seconds=5.0, now=2001.0) is None, \
        "stale release exposed the peer's active lease"
    # the current holder's release (matching expiry) works
    assert store_b.release_clerking_job_lease(
        clerk.id, job.id, expires=new_expires) is True
    assert store_a.lease_clerking_job(
        clerk.id, lease_seconds=5.0, now=2002.0) is not None


@pytest.mark.parametrize("backend", BACKENDS)
def test_contended_lease_grant_single_winner(backend, tmp_path):
    """Two handles racing ``lease_clerking_job`` for the same clerk must
    grant the one queued job exactly once — the jsonfs read-check-write
    is flock-arbitrated across processes, sqlite by the conditional
    UPDATE, memory/mongo by their store locks."""
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a, clerks=1, participants=1)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    clerk = committee[0][0]

    barrier = threading.Barrier(2)
    grants = []
    lock = threading.Lock()

    def race(store):
        barrier.wait()
        got = store.lease_clerking_job(clerk.id, lease_seconds=300.0)
        with lock:
            grants.append(got)

    threads = [
        threading.Thread(target=race, args=(s.server.clerking_job_store,))
        for s in (a, b)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(g is not None for g in grants) == 1, \
        f"want exactly one lease grant, got {grants}"


@pytest.mark.parametrize("backend", ["memory", "sqlite"])
def test_release_held_leases_on_drain(backend, tmp_path):
    """``SdaServer.release_held_leases`` (the drain step) returns every
    lease this server granted, and a peer handle reissues instantly."""
    a, b = _two_handles(backend, tmp_path)
    a.server.clerking_lease_seconds = 300.0
    b.server.clerking_lease_seconds = 300.0
    recipient, committee, agg = _world(a, clerks=3, participants=2)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)

    leased = [a.server.poll_clerking_job(c.id) for (c, _) in committee]
    assert all(j is not None for j in leased)
    # all three held by server a: peer polls come back empty
    assert all(b.server.poll_clerking_job(c.id) is None
               for (c, _) in committee)

    assert a.server.release_held_leases() == 3
    assert a.server.release_held_leases() == 0  # drained is drained
    reissued = [b.server.poll_clerking_job(c.id) for (c, _) in committee]
    assert sorted(str(j.id) for j in reissued) == \
        sorted(str(j.id) for j in leased)


# ---------------------------------------------------------------------------
# consistent-hash routing


def test_ring_deterministic_and_complete():
    nodes = [f"w{i}" for i in range(4)]
    r1, r2 = HashRing(nodes), HashRing(list(nodes))
    keys = [f"agg-{i}" for i in range(500)]
    assert [r1.node_for(k) for k in keys] == [r2.node_for(k) for k in keys]
    spread = r1.spread(keys)
    assert set(spread) == set(nodes)
    assert all(count > 0 for count in spread.values()), spread
    # 64 vnodes per worker keeps the imbalance bounded
    assert max(spread.values()) <= 4 * min(spread.values()), spread


def test_ring_minimal_movement_on_node_loss():
    """Draining one of four workers moves ONLY the drained worker's keys:
    every key owned by a survivor keeps its owner (cache affinity is why
    the ring exists)."""
    nodes = [f"w{i}" for i in range(4)]
    before = HashRing(nodes)
    after = HashRing([n for n in nodes if n != "w2"])
    keys = [f"agg-{i}" for i in range(500)]
    for key in keys:
        owner = before.node_for(key)
        if owner != "w2":
            assert after.node_for(key) == owner
        else:
            assert after.node_for(key) in after.nodes


def test_ring_preferred_failover_order():
    ring = HashRing(["a", "b", "c"])
    pref = ring.preferred("some-aggregation", count=3)
    assert pref[0] == ring.node_for("some-aggregation")
    assert sorted(pref) == ["a", "b", "c"]  # distinct, all nodes
    assert ring.preferred("some-aggregation", count=99) == pref


def test_ring_rejects_degenerate_input():
    with pytest.raises(ValueError):
        HashRing([])
    with pytest.raises(ValueError):
        HashRing(["a"], replicas=0)
    assert HashRing(["a", "a", "b"]).nodes == ["a", "b"]  # deduped


# ---------------------------------------------------------------------------
# node identity on the HTTP plane


def test_node_id_on_responses_statusz_metrics():
    """A node-tagged server stamps X-SDA-Node on every response, labels
    /metrics samples with node_id, and reports fleet.peers in /statusz."""
    import requests

    from sda_tpu.http import SdaHttpServer
    from sda_tpu.server import new_memory_server
    from sda_tpu import obs

    obs.reset_all()
    srv = SdaHttpServer(
        new_memory_server(), bind="127.0.0.1:0",
        metrics_endpoint=True, statusz_endpoint=True,
        node_id="wX", fleet_peers=3,
    ).start_background()
    try:
        ping = requests.get(srv.address + "/v1/ping")
        assert ping.headers.get(NODE_HEADER) == "wX"
        statusz = requests.get(srv.address + "/statusz").json()
        assert statusz["node_id"] == "wX"
        assert statusz["fleet"]["peers"] == 3
        metrics_text = requests.get(srv.address + "/metrics").text
        assert 'node_id="wX"' in metrics_text
    finally:
        srv.shutdown()
        obs.reset_all()


def test_no_node_header_when_solo():
    import requests

    from sda_tpu.http import SdaHttpServer
    from sda_tpu.server import new_memory_server

    srv = SdaHttpServer(
        new_memory_server(), bind="127.0.0.1:0").start_background()
    try:
        assert NODE_HEADER not in requests.get(srv.address + "/v1/ping").headers
    finally:
        srv.shutdown()


def test_node_id_lands_on_server_spans():
    """Round timelines attribute hops to workers: the server-side span of
    a traced request carries the node_id attribute."""
    import requests

    from sda_tpu.http import SdaHttpServer
    from sda_tpu.server import new_memory_server
    from sda_tpu import obs

    obs.reset_all()
    srv = SdaHttpServer(
        new_memory_server(), bind="127.0.0.1:0", node_id="w7",
    ).start_background()
    try:
        requests.get(srv.address + "/v1/ping")
        spans = [s for s in obs.finished_spans()
                 if s.name.startswith("http.server")]
        assert spans, "expected a server span"
        assert all(s.attributes.get("node_id") == "w7" for s in spans)
    finally:
        srv.shutdown()
        obs.reset_all()


# ---------------------------------------------------------------------------
# the launcher: real worker processes over one shared store


def test_fleet_launcher_two_workers_shared_sqlite(tmp_path):
    """Spawn 2 real `sdad` processes over one WAL sqlite file: distinct
    addresses and node ids, X-SDA-Node names the serving worker, both see
    the SAME store, and SIGTERM drains both with zero leaked requests."""
    import requests

    from sda_tpu.server.fleet import Fleet

    fleet = Fleet(2, ["--sqlite", str(tmp_path / "shared.db")],
                  extra_args=["--statusz", "--job-lease", "5"])
    try:
        fleet.start(timeout_s=120.0)
        addresses = fleet.addresses
        assert sorted(addresses) == ["w0", "w1"]
        assert len(set(addresses.values())) == 2
        for node, address in addresses.items():
            ping = requests.get(address + "/v1/ping", timeout=10)
            assert ping.headers.get(NODE_HEADER) == node
            statusz = requests.get(address + "/statusz", timeout=10).json()
            assert statusz["node_id"] == node
            assert statusz["fleet"]["peers"] == 2
            assert statusz["store"] == "sqlite"
        # shared store: an agent registered via w0 is readable via w1
        agent = new_agent()
        w0, w1 = addresses["w0"], addresses["w1"]
        created = requests.post(
            w0 + "/v1/agents/me", json=agent.to_obj(),
            auth=(str(agent.id), "fleet-test-token"), timeout=10)
        assert created.status_code in (200, 201)
        fetched = requests.get(
            w1 + f"/v1/agents/{agent.id}",
            auth=(str(agent.id), "fleet-test-token"), timeout=10)
        assert fetched.status_code == 200
        assert fetched.json()["id"] == str(agent.id)
    finally:
        summaries = fleet.stop()
    assert len(summaries) == 2
    for summary in summaries:
        assert not summary.get("killed"), summaries
        assert summary["leaked"] == 0
    assert all(w.returncode == 0 for w in fleet.workers)


def test_fleet_rejects_memory_backend(tmp_path):
    from sda_tpu.server.fleet import Fleet

    with pytest.raises(ValueError, match="memory"):
        Fleet(2, ["--memory"])
    with pytest.raises(ValueError):
        Fleet(0, ["--sqlite", str(tmp_path / "x.db")])


def test_fleetd_flag_mapping():
    """The `sda-fleet` CLI maps its flags onto per-worker `sdad` flags
    without spawning anything."""
    from sda_tpu.cli.fleetd import build_parser, worker_extra_args

    args = build_parser().parse_args(
        ["-n", "3", "--sqlite", "db", "--job-lease", "7", "--metrics",
         "--statusz", "--rate-limit", "50", "--drain-grace", "2"])
    extra = worker_extra_args(args)
    assert extra[:2] == ["--drain-grace", "2.0"]
    assert ["--job-lease", "7.0"] == extra[2:4]
    assert "--metrics" in extra and "--statusz" in extra
    assert ["--rate-limit", "50.0"] == \
        [extra[extra.index("--rate-limit")], extra[extra.index("--rate-limit") + 1]]
    assert "--rate-burst" not in extra
