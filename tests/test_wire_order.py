"""Mechanical wire-format cross-check against the reference source.

The golden serde vectors in test_protocol.py were hand-derived from
reading the Rust; a single mis-read field order would break
cross-implementation signatures undetectably (canonical-JSON signing
serializes fields in declaration order — helpers.rs:101-142). This test
removes the single point of failure by deriving the field order a SECOND
way: parse the reference's struct/enum-variant declarations straight out
of `/root/reference/protocol/src/*.rs` (treated as data, not code) and
compare against the key order our ``to_obj`` dict literals emit,
extracted via ``ast`` from our own source. Both sides are obtained
mechanically, so agreement means our wire order matches the reference's
serde declaration order field-for-field.
"""

import ast
import inspect
import re
from pathlib import Path

import pytest

import sda_tpu.protocol.helpers as helpers_mod
import sda_tpu.protocol.resources as resources_mod
import sda_tpu.protocol.crypto as crypto_mod

REF = Path("/root/reference/protocol/src")

pytestmark = pytest.mark.skipif(
    not REF.exists(), reason="reference checkout not present"
)


# -- reference side: parse `pub struct` / enum struct-variant fields -------

def rust_struct_fields(source: str):
    """{struct_name: [field, ...]} for every `pub struct Name { pub f: T }`."""
    out = {}
    for m in re.finditer(
        r"pub struct (\w+)(?:<[^>]*>)?\s*(?:where[^{]*)?\{(.*?)\n\}",
        source, re.S,
    ):
        fields = re.findall(r"pub (\w+)\s*:", m.group(2))
        if fields:
            out[m.group(1)] = fields
    return out


def rust_variant_fields(source: str):
    """{variant_name: [field, ...]} for struct-like enum variants.

    Commented-out variants (e.g. BasicShamir, PackedPaillier) are
    stripped first so they do not shadow live declarations.
    """
    live = re.sub(r"(?m)^\s*//.*$", "", source)
    out = {}
    for m in re.finditer(r"(?m)^    (\w+)\s*\{([^}]*)\}", live):
        fields = re.findall(r"(\w+)\s*:", m.group(2))
        if fields:
            out[m.group(1)] = fields
    return out


# -- our side: first dict literal returned by to_obj, via ast --------------

def to_obj_key_order(cls):
    """Key order of the dict literal(s) in ``cls.to_obj``.

    Returns the outer dict's keys; if the outer dict is a single-key
    externally-tagged wrapper ({"Variant": {...}}) returns the inner
    dict's keys instead (serde external enum tagging).
    """
    tree = ast.parse(inspect.getsource(cls.to_obj).lstrip())
    for node in ast.walk(tree):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Dict):
            d = node.value
            keys = [k.value for k in d.keys if isinstance(k, ast.Constant)]
            if len(keys) == 1 and isinstance(d.values[0], ast.Dict):
                inner = d.values[0]
                return [k.value for k in inner.keys if isinstance(k, ast.Constant)]
            return keys
    raise AssertionError(f"{cls.__name__}.to_obj has no dict-literal return")


# -- the cross-checks ------------------------------------------------------

def test_resource_structs_match_reference_field_order():
    ref = rust_struct_fields((REF / "resources.rs").read_text())
    checked = 0
    for name, fields in ref.items():
        cls = getattr(resources_mod, name, None)
        assert cls is not None, f"reference struct {name} has no counterpart"
        assert to_obj_key_order(cls) == fields, f"{name} wire order diverges"
        checked += 1
    assert checked >= 10  # all protocol nouns present in resources.rs


def test_helper_structs_match_reference_field_order():
    ref = rust_struct_fields((REF / "helpers.rs").read_text())
    for name in ("Signed", "Labelled"):
        cls = getattr(helpers_mod, name)
        assert to_obj_key_order(cls) == ref[name], f"{name} wire order diverges"


def test_scheme_variants_match_reference_field_order():
    ref = rust_variant_fields((REF / "crypto.rs").read_text())
    ours = {
        "Full": crypto_mod.FullMasking,
        "ChaCha": crypto_mod.ChaChaMasking,
        "Additive": crypto_mod.AdditiveSharing,
        "PackedShamir": crypto_mod.PackedShamirSharing,
    }
    for variant, cls in ours.items():
        assert to_obj_key_order(cls) == ref[variant], (
            f"{variant} wire order diverges"
        )
