"""Embedded participant (native C core) joins ordinary Python rounds.

The reference's declared-but-unreleased /embeddable-client (reference
README.md:196-204) exposes the client compute "in a C-friendly" API for
mobile/embedded apps. The TPU build's analog is
``sda_embed_participate`` (native/src/sda_native.cpp) + the
``client.embed`` transport shim. These tests pin the wire-compatibility
claim end-to-end: a participation whose every byte of crypto was produced
by the C core must decrypt, clerk, and reveal exactly alongside pure
Python participants — across the none/full/chacha masking lattice.
"""

import numpy as np
import pytest

from sda_tpu import native
from sda_tpu.client import SdaClient
from sda_tpu.client.embed import new_participation_embedded, participate_embedded
from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    AgentId,
    ChaChaMasking,
    EncryptionKeyId,
    FullMasking,
    NoMasking,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.server import new_memory_server

pytestmark = pytest.mark.skipif(
    not (sodium.available() and native.available()),
    reason="libsodium or native library not present",
)

DIM, MOD = 5, 433


def _agg(masking, dim=DIM) -> Aggregation:
    return Aggregation(
        id=AggregationId.random(),
        title="embedded",
        vector_dimension=dim,
        modulus=MOD,
        recipient=AgentId.random(),
        recipient_key=EncryptionKeyId.random(),
        masking_scheme=masking,
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=MOD),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )


def _client(service):
    ks = MemoryKeystore()
    c = SdaClient(SdaClient.new_agent(ks), ks, service)
    c.upload_agent()
    return c


def _round(masking, embedded_input, python_inputs):
    """One aggregation where ONE participation is built by the C core."""
    service = new_memory_server()
    recipient = _client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    agg = _agg(masking).replace(recipient=recipient.agent.id,
                                recipient_key=rkey)
    recipient.upload_aggregation(agg)
    clerks = [_client(service) for _ in range(4)]
    for c in clerks:
        c.upload_encryption_key(c.new_encryption_key())
    recipient.begin_aggregation(agg.id)

    embedded = _client(service)
    participate_embedded(embedded, embedded_input, agg.id)
    for vals in python_inputs:
        _client(service).participate(vals, agg.id)

    recipient.end_aggregation(agg.id)
    recipient.run_chores(-1)
    for c in clerks:
        c.run_chores(-1)
    out = recipient.reveal_aggregation(agg.id).positive().values
    expected = (np.asarray([embedded_input] + list(python_inputs))
                .sum(axis=0) % MOD)
    np.testing.assert_array_equal(out, expected)


@pytest.mark.parametrize("masking", [
    NoMasking(),
    FullMasking(MOD),
    ChaChaMasking(MOD, DIM, 128),
], ids=["none", "full", "chacha"])
def test_embedded_participation_reveals_exact(masking):
    _round(masking,
           embedded_input=[5, 10, 432, 0, 7],
           python_inputs=[[1, 2, 3, 4, 5], [9, 8, 7, 6, 5]])


def test_embedded_only_round():
    """A round where EVERY participant is the C core."""
    service = new_memory_server()
    recipient = _client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    agg = _agg(FullMasking(MOD)).replace(recipient=recipient.agent.id,
                                         recipient_key=rkey)
    recipient.upload_aggregation(agg)
    clerks = [_client(service) for _ in range(4)]
    for c in clerks:
        c.upload_encryption_key(c.new_encryption_key())
    recipient.begin_aggregation(agg.id)
    inputs = [[i + j for j in range(DIM)] for i in range(1, 4)]
    for vals in inputs:
        participate_embedded(_client(service), vals, agg.id)
    recipient.end_aggregation(agg.id)
    recipient.run_chores(-1)
    for c in clerks:
        c.run_chores(-1)
    out = recipient.reveal_aggregation(agg.id).positive().values
    np.testing.assert_array_equal(
        out, np.asarray(inputs).sum(axis=0) % MOD)


def test_embedded_canonicalizes_negative_and_large_inputs():
    _round(NoMasking(),
           embedded_input=[-1, MOD + 5, 2 * MOD, -MOD, 3],
           python_inputs=[[1, 1, 1, 1, 1]])


def _shamir_round(sharing, masking, embedded_input, python_inputs,
                  n_clerks=8, dim=DIM):
    """A committee round with one C-core participation: the share matrix
    (when Shamir) is computed host-side, evaluated in C, and the Python
    clerks/recipient must reconstruct the exact sum (the golden
    full_loop.rs PackedShamir config at p=433, omega=354/150)."""
    service = new_memory_server()
    recipient = _client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    agg = _agg(masking, dim=dim).replace(
        recipient=recipient.agent.id, recipient_key=rkey,
        committee_sharing_scheme=sharing,
    )
    recipient.upload_aggregation(agg)
    clerks = [_client(service) for _ in range(n_clerks)]
    for c in clerks:
        c.upload_encryption_key(c.new_encryption_key())
    recipient.begin_aggregation(agg.id)
    participate_embedded(_client(service), embedded_input, agg.id)
    for vals in python_inputs:
        _client(service).participate(vals, agg.id)
    recipient.end_aggregation(agg.id)
    recipient.run_chores(-1)
    for c in clerks:
        c.run_chores(-1)
    out = recipient.reveal_aggregation(agg.id).positive().values
    expected = (np.asarray([embedded_input] + list(python_inputs))
                .sum(axis=0) % MOD)
    np.testing.assert_array_equal(out, expected)


@pytest.mark.parametrize("masking", [
    NoMasking(), FullMasking(MOD), ChaChaMasking(MOD, DIM, 128),
], ids=["none", "full", "chacha"])
def test_embedded_packed_shamir_reveals_exact(masking):
    _shamir_round(PackedShamirSharing(3, 8, 4, MOD, 354, 150), masking,
                  embedded_input=[1, 2, 3, 4, 5],
                  python_inputs=[[10, 20, 30, 40, 50]])


def test_embedded_basic_shamir_reveals_exact():
    from sda_tpu.protocol import BasicShamirSharing

    _shamir_round(BasicShamirSharing(share_count=8, privacy_threshold=3,
                                     prime_modulus=MOD),
                  FullMasking(MOD),
                  embedded_input=[7, 0, 432, 1, 2],
                  python_inputs=[[3, 3, 3, 3, 3], [5, 4, 3, 2, 1]])


def test_embed_core_blob_shapes():
    """Direct C-ABI contract: blob counts/sizes and masking gating."""
    pks = [sodium.box_keypair()[0] for _ in range(3)]
    rpk, _ = sodium.box_keypair()
    rec, clerk_blobs = native.embed_participate(
        [1, 2, 3], MOD, 3, masking="none", clerk_pks=pks)
    assert rec is None and len(clerk_blobs) == 3
    for b in clerk_blobs:
        assert len(b) >= 48 + 3  # sealedbox overhead + one byte per value
    rec, _ = native.embed_participate(
        [1, 2, 3], MOD, 3, masking="chacha", seed_bits=128,
        recipient_pk=rpk, clerk_pks=pks)
    # chacha uploads the SEED (4 words), not an O(d) mask
    assert rec is not None and len(rec) <= 48 + 4 * 10
    with pytest.raises(ValueError):
        native.embed_participate([1], MOD, 2, masking="full",
                                 recipient_pk=b"short", clerk_pks=pks[:2])


def test_embedded_chacha_odd_seed_bits():
    """seed_bitsize not a multiple of 32 rounds up to whole words, exactly
    like chacha.random_seed — any Python-accepted aggregation must work."""
    _round(ChaChaMasking(MOD, DIM, 80),
           embedded_input=[4, 3, 2, 1, 0],
           python_inputs=[[2, 2, 2, 2, 2]])


def test_embedded_rejects_scheme_modulus_drift():
    service = new_memory_server()
    recipient = _client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    agg = _agg(NoMasking()).replace(
        recipient=recipient.agent.id, recipient_key=rkey,
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=431),
    )
    recipient.upload_aggregation(agg)
    for _ in range(4):
        c = _client(service)
        c.upload_encryption_key(c.new_encryption_key())
    recipient.begin_aggregation(agg.id)
    with pytest.raises(ValueError, match="sharing modulus"):
        new_participation_embedded(_client(service), [1] * DIM, agg.id)


def test_embedded_shamir_two_ring_masking():
    """The production ring split: Shamir shares over a ~2^29 NTT prime,
    masks over the aggregation modulus 433 (the CLI's capacity-headroom
    policy) — the embedded participation must still reveal exactly."""
    from sda_tpu.fields import numtheory

    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    sharing = PackedShamirSharing(3, 8, t, p, w2, w3)
    service = new_memory_server()
    recipient = _client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    agg = _agg(FullMasking(MOD)).replace(
        recipient=recipient.agent.id, recipient_key=rkey,
        committee_sharing_scheme=sharing,
    )
    recipient.upload_aggregation(agg)
    clerks = [_client(service) for _ in range(8)]
    for c in clerks:
        c.upload_encryption_key(c.new_encryption_key())
    recipient.begin_aggregation(agg.id)
    participate_embedded(_client(service), [1, 2, 3, 4, 5], agg.id)
    _client(service).participate([100, 200, 300, 400, 430], agg.id)
    recipient.end_aggregation(agg.id)
    recipient.run_chores(-1)
    for c in clerks:
        c.run_chores(-1)
    out = recipient.reveal_aggregation(agg.id).positive().values
    np.testing.assert_array_equal(
        out, (np.asarray([[1, 2, 3, 4, 5], [100, 200, 300, 400, 430]])
              .sum(axis=0) % MOD))


def test_embed_blobs_decode_to_telescoping_shares():
    """Wire-level check below the protocol: decrypt every C-built clerk
    blob with the clerk's secret key, varint-decode, and verify the share
    vectors telescope to the canonical secret (additive) — the exact
    parsing path the Python clerks run."""
    from sda_tpu.crypto import varint

    secret = [5, -3, 432, 1000, 0]
    n = 4
    keys = [sodium.box_keypair() for _ in range(n)]
    rec, blobs = native.embed_participate(
        secret, MOD, n, masking="none",
        clerk_pks=[pk for pk, _ in keys])
    assert rec is None
    decoded = []
    for (pk, sk), blob in zip(keys, blobs):
        decoded.append(varint.decode(sodium.seal_open(blob, pk, sk)))
    total = np.sum(decoded, axis=0) % MOD
    np.testing.assert_array_equal(
        total, np.asarray(secret, dtype=np.int64) % MOD)
    for share in decoded:  # canonical residues on the wire
        assert share.min() >= 0 and share.max() < MOD


def test_embed_full_mask_blob_decodes_and_cancels():
    """Recipient blob = varint(mask); clerk shares telescope to the
    MASKED secret; mask subtraction recovers the canonical input."""
    from sda_tpu.crypto import varint

    secret = [1, 2, 3]
    n = 3
    keys = [sodium.box_keypair() for _ in range(n)]
    rpk, rsk = sodium.box_keypair()
    rec, blobs = native.embed_participate(
        secret, MOD, n, masking="full", recipient_pk=rpk,
        clerk_pks=[pk for pk, _ in keys])
    mask = varint.decode(sodium.seal_open(rec, rpk, rsk))
    shares = [varint.decode(sodium.seal_open(b, pk, sk))
              for (pk, sk), b in zip(keys, blobs)]
    masked = np.sum(shares, axis=0) % MOD
    np.testing.assert_array_equal(
        (masked - mask) % MOD, np.asarray(secret) % MOD)


def test_embed_wrapper_validation_errors():
    pks = [sodium.box_keypair()[0] for _ in range(3)]
    with pytest.raises(ValueError, match="masking must be one of"):
        native.embed_participate([1], MOD, 3, masking="bogus",
                                 clerk_pks=pks)
    with pytest.raises(ValueError, match="one clerk public key"):
        native.embed_participate([1], MOD, 3, clerk_pks=pks[:2])
    with pytest.raises(ValueError, match="32 bytes"):
        native.embed_participate([1], MOD, 3, masking="full",
                                 recipient_pk=b"x" * 31, clerk_pks=pks)
    with pytest.raises(ValueError, match="share_matrix must be"):
        native.embed_participate(
            [1], MOD, 3, clerk_pks=pks,
            share_matrix=np.zeros((2, 5), dtype=np.int64), secret_count=1)
    with pytest.raises(ValueError, match="secret_count"):
        native.embed_participate(
            [1], MOD, 3, clerk_pks=pks,
            share_matrix=np.zeros((3, 5), dtype=np.int64), secret_count=0)


def test_embedded_randomized_config_sweep():
    """Property sweep: random dims/committees/schemes/maskings — every
    embedded participation must reveal exactly next to a Python one."""
    from sda_tpu.protocol import BasicShamirSharing

    rng = np.random.default_rng(2026)
    for trial in range(6):
        dim = int(rng.integers(1, 40))
        scheme_pick = trial % 3
        if scheme_pick == 0:
            n = int(rng.integers(2, 6))
            sharing = AdditiveSharing(share_count=n, modulus=MOD)
        elif scheme_pick == 1:
            sharing = PackedShamirSharing(3, 8, 4, MOD, 354, 150)
            n = 8
        else:
            t = int(rng.integers(1, 4))
            sharing = BasicShamirSharing(share_count=8,
                                         privacy_threshold=t,
                                         prime_modulus=MOD)
            n = 8
        masking = [NoMasking(), FullMasking(MOD),
                   ChaChaMasking(MOD, dim, 128)][int(rng.integers(0, 3))]
        emb = rng.integers(0, MOD, size=dim).tolist()
        py = rng.integers(0, MOD, size=dim).tolist()
        _shamir_round(sharing, masking, emb, [py], n_clerks=n, dim=dim)


def test_embedded_near_64bit_modulus():
    """Edge coverage at a huge ring (just below the 2^62 share bound):
    uniform rejection sampling's acceptance zone, 9-10-byte varints, and
    the output-capacity sizing all get exercised; the round must reveal
    exactly against Python clerks."""
    # 2^63-1: the largest ring an i64 share can carry (additive sharing
    # only needs a ring modulus; primality unused). Shares >= 2^62 zigzag
    # to TEN-byte varints, exercising the encoder's widest path and
    # varint.decode's 10th-byte overflow guard
    big = (1 << 63) - 1
    from sda_tpu.crypto import varint

    n = 3
    dim = 32  # enough draws that some share >= 2^62 w.p. 1 - 2^-64
    keys = [sodium.box_keypair() for _ in range(n)]
    secret = [0, 1, big - 1, 123456789012345678] + list(range(dim - 4))
    rec, blobs = native.embed_participate(
        secret, big, n, masking="none",
        clerk_pks=[pk for pk, _ in keys])
    assert rec is None  # masking none: no recipient blob, large ring or not
    decoded = [varint.decode(sodium.seal_open(b, pk, sk))
               for (pk, sk), b in zip(keys, blobs)]
    # telescoping mod big, computed in Python ints to avoid i64 overflow
    total = [(sum(int(s[i]) for s in decoded)) % big
             for i in range(len(secret))]
    assert total == [v % big for v in secret]
    widest = 0
    for share in decoded:
        assert share.min() >= 0 and int(share.max()) < big
        widest = max(widest, int(share.max()))
    # the 10-byte varint path actually ran
    assert widest >= (1 << 62)
