"""Tier-3: the golden end-to-end conformance suite.

Mirrors the reference's full_loop.rs: real crypto, 1 recipient + 8 clerks +
2 participants, full mask/share/clerk/reveal cycle asserting the exact sum
[2, 4, 6, 8] over the four scheme configurations (full_loop.rs:29-67) —
plain additive, Full mask, ChaCha mask, and PackedShamir(8 shares,
threshold 4, p=433, omega=354/150). These four configs are the conformance
bar for the TPU-native build.
"""

import numpy as np
import pytest

from sda_tpu.client import SdaClient
from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    AgentId,
    ChaChaMasking,
    EncryptionKeyId,
    FullMasking,
    NoMasking,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.server import new_jsonfs_server, new_memory_server, new_sqlite_server
from sda_tpu.store import Filebased

pytestmark = pytest.mark.skipif(not sodium.available(), reason="libsodium not present")


def agg_default() -> Aggregation:
    return Aggregation(
        id=AggregationId.random(),
        title="foo",
        vector_dimension=4,
        modulus=433,
        recipient=AgentId.random(),
        recipient_key=EncryptionKeyId.random(),
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )


def new_client(service, tmp_path=None):
    keystore = MemoryKeystore() if tmp_path is None else Filebased(tmp_path)
    agent = SdaClient.new_agent(keystore)
    return SdaClient(agent, keystore, service)


def check_full_aggregation(aggregation: Aggregation, service):
    # prepare recipient
    recipient = new_client(service)
    recipient_key = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)

    aggregation = aggregation.replace(
        recipient=recipient.agent.id, recipient_key=recipient_key
    )
    recipient.upload_aggregation(aggregation)

    # prepare clerks
    clerks = [new_client(service) for _ in range(8)]
    for clerk in clerks:
        clerk_key = clerk.new_encryption_key()
        clerk.upload_agent()
        clerk.upload_encryption_key(clerk_key)

    # assign committee
    recipient.begin_aggregation(aggregation.id)

    # participate
    participants = [new_client(service) for _ in range(2)]
    for participant in participants:
        participant.upload_agent()
        participant.participate([1, 2, 3, 4], aggregation.id)

    # close aggregation
    recipient.end_aggregation(aggregation.id)

    status = service.get_aggregation_status(recipient.agent, aggregation.id)
    assert status.aggregation == aggregation.id
    assert status.number_of_participations == len(participants)
    assert len(status.snapshots) == 1
    assert status.snapshots[0].number_of_clerking_results == 0
    assert not status.snapshots[0].result_ready

    # perform clerking — the recipient may be in the committee too, since it
    # also registered an encryption key (full_loop.rs:131 runs its chores)
    recipient.run_chores(-1)
    for clerk in clerks:
        clerk.run_chores(-1)

    status = service.get_aggregation_status(recipient.agent, aggregation.id)
    committee_size = aggregation.committee_sharing_scheme.output_size
    assert status.snapshots[0].number_of_clerking_results == committee_size
    assert status.snapshots[0].result_ready

    # reveal
    output = recipient.reveal_aggregation(aggregation.id)
    np.testing.assert_array_equal(output.positive().values, [2, 4, 6, 8])


import util as _util


@pytest.fixture(
    params=["memory", "jsonfs", "sqlite", "mongo", "http"]
    + _util.mongo_real_params()
)
def service(request, tmp_path):
    if request.param == "memory":
        yield new_memory_server()
    elif request.param == "mongo":
        from fake_mongo import FakeDatabase
        from sda_tpu.server import new_mongo_server

        yield new_mongo_server(FakeDatabase())
    elif request.param == "mongo-real":
        yield _util.new_mongo_real_service(request)
    elif request.param == "sqlite":
        yield new_sqlite_server(tmp_path / "sda.db")
    elif request.param == "jsonfs":
        yield new_jsonfs_server(tmp_path)
    else:
        # full REST stack in one process (reference: with_service fixture,
        # integration-tests/src/lib.rs:147-178)
        from sda_tpu.http import SdaHttpClient, SdaHttpServer

        http_server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0")
        http_server.start_background()
        proxy = SdaHttpClient(http_server.address, store=Filebased(tmp_path / "tokens"))
        yield proxy
        http_server.shutdown()


def test_simple(service):
    check_full_aggregation(agg_default(), service)


def test_with_fullmask(service):
    check_full_aggregation(
        agg_default().replace(masking_scheme=FullMasking(modulus=433)), service
    )


def test_with_chachamask(service):
    check_full_aggregation(
        agg_default().replace(
            masking_scheme=ChaChaMasking(modulus=433, dimension=4, seed_bitsize=128)
        ),
        service,
    )


def test_with_packedshamir(service):
    check_full_aggregation(
        agg_default().replace(
            committee_sharing_scheme=PackedShamirSharing(
                secret_count=3,
                share_count=8,
                privacy_threshold=4,
                prime_modulus=433,
                omega_secrets=354,
                omega_shares=150,
            )
        ),
        service,
    )


def test_with_basicshamir(service):
    """Beyond the reference's enabled surface: the declared-but-disabled
    BasicShamir variant (crypto.rs:89-95) through the complete protocol
    stack — 3-of-5 quorum, ChaCha masking."""
    from sda_tpu.protocol import BasicShamirSharing

    check_full_aggregation(
        agg_default().replace(
            committee_sharing_scheme=BasicShamirSharing(
                share_count=5, privacy_threshold=2, prime_modulus=433,
            ),
            masking_scheme=ChaChaMasking(433, 4, 128),
        ),
        service,
    )


def test_packedshamir_with_clerk_dropout(service):
    """Beyond the reference suite: reconstruction succeeds when one clerk
    never does its job (fault tolerance, crypto.rs:146-153), exercising the
    dynamic surviving-subset Lagrange path through the whole stack."""
    aggregation = agg_default().replace(
        committee_sharing_scheme=PackedShamirSharing(3, 8, 4, 433, 354, 150)
    )
    recipient = new_client(service)
    recipient_key = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)
    aggregation = aggregation.replace(
        recipient=recipient.agent.id, recipient_key=recipient_key
    )
    recipient.upload_aggregation(aggregation)

    clerks = [new_client(service) for _ in range(8)]
    for clerk in clerks:
        key = clerk.new_encryption_key()
        clerk.upload_agent()
        clerk.upload_encryption_key(key)
    recipient.begin_aggregation(aggregation.id)

    for _ in range(2):
        p = new_client(service)
        p.upload_agent()
        p.participate([1, 2, 3, 4], aggregation.id)
    recipient.end_aggregation(aggregation.id)

    committee = service.get_committee(recipient.agent, aggregation.id)
    committee_ids = {cid for cid, _ in committee.clerks_and_keys}
    workers = [recipient] + clerks
    dropped = next(w for w in workers if w.agent.id in committee_ids)
    for worker in workers:
        if worker is dropped:
            continue  # one committee member goes dark
        worker.run_chores(-1)

    status = service.get_aggregation_status(recipient.agent, aggregation.id)
    assert status.snapshots[0].number_of_clerking_results == 7  # of 8
    assert status.snapshots[0].result_ready  # threshold is t+k = 7

    output = recipient.reveal_aggregation(aggregation.id)
    np.testing.assert_array_equal(output.positive().values, [2, 4, 6, 8])
