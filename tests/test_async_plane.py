"""The async event-loop HTTP plane + long-poll clerk job delivery.

Both serving planes ride one dispatch core (``http/base.py``), so most
tests here are parametrized over ``threaded`` and ``async`` and pin the
contracts that must not drift: wire behavior parity, the long-poll
contract (``GET /v1/clerking-jobs?wait=S`` — immediate return, empty
timeout semantics, wake-on-fan-out, old-peer fallback), drain waking
parked long-polls with 503 + ``Connection: close`` and ``leaked == 0``,
the shared ``/statusz`` document, and the ``server.job.pickup``
histogram behind the BENCH metric.
"""

from __future__ import annotations

import json
import threading
import time

import pytest
import requests

from sda_tpu import obs
from sda_tpu.client import SdaClient
from sda_tpu.http import SdaHttpClient, server_class
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    FullMasking,
    InvalidCredentials,
    NotFound,
    Participation,
    ParticipationId,
    ServerError,
    SodiumEncryption,
)
from sda_tpu.protocol import bincodec
from sda_tpu.server import new_memory_server
from sda_tpu.utils import metrics

from util import mock_encryption, new_agent, new_full_agent

PLANES = ("threaded", "async")

TOKEN = "async-plane-test-token"


@pytest.fixture(params=PLANES)
def plane(request):
    return request.param


@pytest.fixture(autouse=True)
def _clean_obs():
    obs.reset_all()
    yield
    obs.reset_all()


def start_server(plane, service=None, **kwargs):
    service = service or new_memory_server()
    server = server_class(plane == "async")(
        service, bind="127.0.0.1:0", **kwargs)
    return server.start_background()


def proxied_world(server, n_clerks=3):
    """The fake-crypto world of test_service, built OVER the wire: a
    committee whose fanned-out jobs carry mock ciphertexts (the broker
    never opens them), so job-delivery mechanics test without libsodium."""
    proxy = SdaHttpClient(server.address, token=TOKEN)
    recipient, recipient_key = new_full_agent(proxy)
    clerks = [new_full_agent(proxy) for _ in range(n_clerks)]
    agg = Aggregation(
        id=AggregationId.random(),
        title="longpoll-test",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.id,
        recipient_key=recipient_key.body.id,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=n_clerks,
                                                 modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    proxy.create_aggregation(recipient, agg)
    from sda_tpu.protocol import Committee

    proxy.create_committee(recipient, Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for (a, k) in clerks],
    ))
    return proxy, recipient, clerks, agg


def participate_one(proxy, agg, n_clerks=3, tag="p0"):
    p_agent = new_agent()
    proxy.create_agent(p_agent, p_agent)
    participation = Participation(
        id=ParticipationId.random(),
        participant=p_agent.id,
        aggregation=agg.id,
        recipient_encryption=mock_encryption(f"mask-{tag}".encode()),
        clerk_encryptions=[(p_agent.id,
                            mock_encryption(f"{tag}-c{c}".encode()))
                           for c in range(n_clerks)],
    )
    proxy.create_participation(p_agent, participation)
    return p_agent, participation


def snapshot(proxy, recipient, agg):
    from sda_tpu.protocol import Snapshot, SnapshotId

    sid = SnapshotId.random()
    proxy.create_snapshot(recipient, Snapshot(id=sid, aggregation=agg.id))
    return sid


# ---------------------------------------------------------------------------
# wire parity

def test_basic_wire_parity(plane):
    """CRUD + error-mapping smoke on each plane: 200/201, option-None via
    X-Resource-Not-Found, bare-404 NotFound, 401 on bad auth."""
    server = start_server(plane)
    try:
        proxy = SdaHttpClient(server.address, token=TOKEN)
        assert proxy.ping().running
        agent, _key = new_full_agent(proxy)
        assert proxy.get_agent(agent, agent.id).id == agent.id
        from sda_tpu.protocol import AgentId

        assert proxy.get_agent(agent, AgentId.random()) is None
        response = requests.get(server.address + "/v1/nope",
                                auth=(str(agent.id), TOKEN))
        assert response.status_code == 404
        assert "X-Resource-Not-Found" not in response.headers
        bad = SdaHttpClient(server.address, token="wrong-token")
        with pytest.raises(InvalidCredentials):
            bad.get_agent(agent, agent.id)
        # request-id echoed, codec advertised — on both planes
        pong = requests.get(server.address + "/v1/ping")
        assert pong.headers.get("X-Request-Id")
        assert pong.headers.get(bincodec.CODECS_HEADER) == "bin"
    finally:
        server.shutdown()


def test_statusz_documents_match_across_planes():
    """The shared builder (http/base.py): identical key sets, correct
    plane tag, and the lease block's pickup/held fields present — the
    fields fleet-mode aggregation scrapes must not drift."""
    docs = {}
    for plane in PLANES:
        server = start_server(plane, statusz_endpoint=True)
        try:
            docs[plane] = requests.get(server.address + "/statusz").json()
        finally:
            server.shutdown()
    assert set(docs["threaded"]) == set(docs["async"])
    assert docs["threaded"]["plane"] == "threaded"
    assert docs["async"]["plane"] == "async"
    for doc in docs.values():
        assert doc["lease"]["held"] == 0
        assert "pickup_ms" in doc["lease"]
        assert doc["longpoll"]["parked"] == 0


def test_streamed_bin_participation_upload(plane):
    """A binary participation body decodes through the incremental
    FeedDecoder on both planes — same 201, same stored resource."""
    server = start_server(plane)
    try:
        proxy, recipient, clerks, agg = proxied_world(server)
        p_agent = new_agent()
        proxy.create_agent(p_agent, p_agent)
        participation = Participation(
            id=ParticipationId.random(),
            participant=p_agent.id,
            aggregation=agg.id,
            recipient_encryption=mock_encryption(b"m" * 100_000),
            clerk_encryptions=[(p_agent.id, mock_encryption(b"c" * 50_000))
                               for _ in range(3)],
        )
        raw = bincodec.encode_participation(participation)
        response = requests.post(
            server.address + "/v1/aggregations/participations", data=raw,
            headers={"Content-Type": bincodec.CONTENT_TYPE},
            auth=(str(p_agent.id), TOKEN))
        assert response.status_code == 201, response.text
        status = proxy.get_aggregation_status(recipient, agg.id)
        assert status.number_of_participations == 1
        # malformed frame (bad magic, fails on the FIRST fed chunk with
        # most of the body still unread) -> 400, connection stays usable
        session = requests.Session()
        response = session.post(
            server.address + "/v1/aggregations/participations",
            data=b"XXXX" + raw[4:],
            headers={"Content-Type": bincodec.CONTENT_TYPE},
            auth=(str(p_agent.id), TOKEN))
        assert response.status_code == 400
        # keep-alive framing survived the mid-stream error
        assert session.get(server.address + "/v1/ping").status_code == 200
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# long-poll contract

def test_longpoll_empty_wait_expires_with_resource_not_found(plane):
    server = start_server(plane)
    try:
        proxy = SdaHttpClient(server.address, token=TOKEN)
        agent, _ = new_full_agent(proxy)
        t0 = time.monotonic()
        assert proxy.await_clerking_job(agent, agent.id, wait_s=0.5) is None
        elapsed = time.monotonic() - t0
        assert 0.4 <= elapsed < 5.0
        # wait=0 degenerates to the immediate-return path
        t0 = time.monotonic()
        assert proxy.await_clerking_job(agent, agent.id, wait_s=0.0) is None
        assert time.monotonic() - t0 < 0.5
        # a garbled wait is a 400, not a parked request
        response = requests.get(
            server.address + "/v1/clerking-jobs", params={"wait": "bogus"},
            auth=(str(agent.id), TOKEN))
        assert response.status_code == 400
    finally:
        server.shutdown()


def test_longpoll_delivers_job_fanned_out_while_parked(plane):
    """The headline behavior: a clerk parked BEFORE the snapshot exists
    receives its job as soon as fan-out fires the wakeup — far faster
    than any polling interval — and the pickup histogram records it."""
    server = start_server(plane)
    server.sda_service.server.clerking_lease_seconds = 30.0
    try:
        proxy, recipient, clerks, agg = proxied_world(server)
        participate_one(proxy, agg)
        clerk_agent = clerks[0][0]
        got = {}

        def parked_poll():
            got["job"] = proxy.await_clerking_job(clerk_agent,
                                                  clerk_agent.id,
                                                  wait_s=20.0)
            got["at"] = time.monotonic()

        t = threading.Thread(target=parked_poll, daemon=True)
        t.start()
        time.sleep(0.4)  # let the request park server-side
        t0 = time.monotonic()
        snapshot(proxy, recipient, agg)
        t.join(timeout=10)
        assert not t.is_alive()
        assert got["job"] is not None
        assert got["job"].clerk == clerk_agent.id
        # delivered on the wakeup hop, not a polling cadence
        assert got["at"] - t0 < 2.0
        pickup = metrics.histogram_report("server.job.pickup").get(
            "server.job.pickup")
        assert pickup and pickup["count"] >= 1
    finally:
        server.shutdown()


def test_parked_longpoll_holds_admission_slot(plane):
    """``max_inflight`` bounds parked long-polls identically on both
    planes: the admission slot covers the parked time (a parked clerk IS
    in-flight work), so with the cap filled by a parked poll every other
    request sheds 503 until the park resolves — and the slot comes back
    once it does."""
    server = start_server(plane, max_inflight=1)
    try:
        proxy = SdaHttpClient(server.address, token=TOKEN)
        agent, _ = new_full_agent(proxy)
        done = {}

        def park():
            done["job"] = proxy.await_clerking_job(agent, agent.id,
                                                   wait_s=2.0)

        t = threading.Thread(target=park, daemon=True)
        t.start()
        time.sleep(0.5)  # let the long-poll reach its server-side park
        response = requests.get(server.address + "/v1/ping",
                                auth=(str(agent.id), TOKEN))
        assert response.status_code == 503
        assert "Retry-After" in response.headers
        t.join(timeout=10)
        assert not t.is_alive()
        assert done["job"] is None
        response = requests.get(server.address + "/v1/ping",
                                auth=(str(agent.id), TOKEN))
        assert response.status_code == 200
    finally:
        server.shutdown()


def test_longpoll_old_peer_fallback():
    """Against a server without the long-poll route (bare 404) the
    client degrades to the immediate-return poll — transparently and
    permanently for that proxy."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class OldHandler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/v1/clerking-jobs"):
                body = b'{"error": "no such route"}'
            elif self.path.startswith("/v1/aggregations/any/jobs"):
                self.send_response(404)
                self.send_header("X-Resource-Not-Found", "true")
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")
                return
            else:
                body = b"{}"
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), OldHandler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        host, port = httpd.server_address[:2]
        proxy = SdaHttpClient(f"http://{host}:{port}", token=TOKEN)
        agent = new_agent()
        assert proxy.await_clerking_job(agent, agent.id, wait_s=5.0) is None
        assert proxy._peer_longpoll is False
        counters = metrics.counter_report("http.longpoll.")
        assert counters.get("http.longpoll.unsupported") == 1
        # subsequent calls skip the dead route entirely
        assert proxy.await_clerking_job(agent, agent.id, wait_s=5.0) is None
        assert metrics.counter_report("http.longpoll.")[
            "http.longpoll.unsupported"] == 1
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_inprocess_seam_longpoll_and_clerk_poll():
    """The in-process mirror: SdaServerService.await_clerking_job parks
    on the job wakeup; SdaClient.clerk_poll(wait_s=...) rides it."""
    service = new_memory_server()
    recipient, recipient_key = new_full_agent(service)
    clerks = [new_full_agent(service) for _ in range(3)]
    agg = Aggregation(
        id=AggregationId.random(), title="seam", vector_dimension=4,
        modulus=433, recipient=recipient.id,
        recipient_key=recipient_key.body.id,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    from sda_tpu.protocol import Committee, Snapshot, SnapshotId

    service.create_committee(recipient, Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for (a, k) in clerks]))
    p_agent = new_agent()
    service.create_agent(p_agent, p_agent)
    service.create_participation(p_agent, Participation(
        id=ParticipationId.random(), participant=p_agent.id,
        aggregation=agg.id,
        recipient_encryption=mock_encryption(b"m"),
        clerk_encryptions=[(p_agent.id, mock_encryption(f"c{c}".encode()))
                           for c in range(3)]))

    clerk_agent = clerks[0][0]
    from sda_tpu.crypto import Keystore

    class _NullKeystore(Keystore):
        def put(self, *a, **k):
            raise NotImplementedError

        def get(self, *a, **k):
            return None

    client = SdaClient.__new__(SdaClient)  # no crypto needed for polling
    client.agent = clerk_agent
    client.service = service
    client._dead = False
    got = {}

    def parked():
        got["job"] = client.clerk_poll(wait_s=10.0)
        got["at"] = time.monotonic()

    t = threading.Thread(target=parked, daemon=True)
    t.start()
    time.sleep(0.2)
    t0 = time.monotonic()
    service.create_snapshot(recipient, Snapshot(id=SnapshotId.random(),
                                                aggregation=agg.id))
    t.join(timeout=5)
    assert not t.is_alive()
    assert got["job"] is not None and got["job"].clerk == clerk_agent.id
    assert got["at"] - t0 < 1.0  # wakeup hop, not the 10s budget


# ---------------------------------------------------------------------------
# drain under parked long-polls (satellite): a draining worker must wake
# parked clerks with 503 + Connection: close — not hold them to timeout —
# and still drain with leaked == 0. Raced on both planes.

def test_drain_wakes_parked_longpolls(plane):
    server = start_server(plane)
    try:
        proxy = SdaHttpClient(server.address, token=TOKEN)
        agents = [new_full_agent(proxy)[0] for _ in range(3)]
        results = {}

        def parked(ix, agent):
            # raw request (no retries): the 503 itself is the assertion
            response = requests.get(
                server.address + "/v1/clerking-jobs",
                params={"wait": "30"}, auth=(str(agent.id), TOKEN),
                timeout=20)
            results[ix] = (response.status_code,
                           response.headers.get("Connection"),
                           response.headers.get("Retry-After"))

        threads = [threading.Thread(target=parked, args=(ix, agent),
                                    daemon=True)
                   for ix, agent in enumerate(agents)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if server.statusz()["longpoll"]["parked"] >= 3:
                break
            time.sleep(0.02)
        assert server.statusz()["longpoll"]["parked"] >= 3
        t0 = time.monotonic()
        summary = server.drain(grace_s=10.0)
        drain_wall = time.monotonic() - t0
        for t in threads:
            t.join(timeout=10)
        assert all(not t.is_alive() for t in threads)
        # woken immediately — nowhere near the 30s park budget
        assert drain_wall < 8.0
        assert summary["leaked"] == 0
        assert len(results) == 3
        for status, connection, retry_after in results.values():
            assert status == 503
            assert (connection or "").lower() == "close"
            assert retry_after is not None
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# run_clerk loop + relay Retry-After satellite

class _FlakyService:
    """await_clerking_job-less service whose poll alternates transient
    ServerError (with a Retry-After hint) and empty."""

    def __init__(self):
        self.polls = 0

    def get_clerking_job(self, caller, clerk):
        self.polls += 1
        if self.polls == 1:
            error = ServerError("brownout")
            error.retry_after = 0.05
            raise error
        return None


def test_run_clerk_absorbs_transients_and_deadline():
    service = _FlakyService()
    client = SdaClient.__new__(SdaClient)
    client.agent = new_agent()
    client.service = service
    client._dead = False
    t0 = time.monotonic()
    processed = client.run_clerk(wait_s=0.0, poll_interval=0.05,
                                 deadline=0.6)
    assert processed == 0
    assert 0.5 <= time.monotonic() - t0 < 5.0
    assert service.polls >= 3  # kept polling through the transient
    assert metrics.counter_report("clerk.").get("clerk.poll.transient") == 1


class _DeadTransportService:
    """Transport whose retry budget keeps exhausting on a refused
    connection: polls raise the raw OSError family (what requests'
    ConnectionError is) until the 'worker' comes back."""

    def __init__(self, outage_polls):
        self.polls = 0
        self.outage_polls = outage_polls

    def get_clerking_job(self, caller, clerk):
        self.polls += 1
        if self.polls <= self.outage_polls:
            raise ConnectionRefusedError("connection refused")
        return None


def test_run_clerk_survives_transport_outage():
    """A restarting worker's refused connections (raw OSError out of the
    transport once ITS retries exhaust) must not kill the clerk daemon —
    the loop backs off and resumes polling when the worker returns."""
    service = _DeadTransportService(outage_polls=2)
    client = SdaClient.__new__(SdaClient)
    client.agent = new_agent()
    client.service = service
    client._dead = False
    processed = client.run_clerk(wait_s=0.0, poll_interval=0.02,
                                 deadline=0.5)
    assert processed == 0
    assert service.polls > 2  # polled THROUGH the outage and beyond it


class _OldPeerService:
    """Transport whose long-poll fallback already tripped: the waiter
    exists but returns immediately (no server-side park)."""

    def __init__(self):
        self.polls = 0

    def longpoll_supported(self):
        return False

    def await_clerking_job(self, caller, clerk, wait_s=0.0):
        return self.get_clerking_job(caller, clerk)

    def get_clerking_job(self, caller, clerk):
        self.polls += 1
        return None


def test_run_clerk_paces_against_old_peer():
    """Once the transport's old-peer fallback trips, empty polls return
    instantly — run_clerk must supply the polling cadence itself, not
    busy-spin at the server."""
    service = _OldPeerService()
    client = SdaClient.__new__(SdaClient)
    client.agent = new_agent()
    client.service = service
    client._dead = False
    processed = client.run_clerk(wait_s=30.0, poll_interval=0.1,
                                 deadline=0.8)
    assert processed == 0
    # jittered ~0.1s cadence inside a 0.8s deadline: a handful of polls,
    # not an unthrottled storm
    assert 2 <= service.polls <= 30


class _ClampedLongpollService:
    """Claims long-poll (waiter present, fallback never tripped) but the
    server clamped the wait to zero: every 'park' returns instantly."""

    def __init__(self):
        self.polls = 0

    def await_clerking_job(self, caller, clerk, wait_s=0.0):
        self.polls += 1
        return None

    def get_clerking_job(self, caller, clerk):
        return self.await_clerking_job(caller, clerk)


def test_run_clerk_paces_when_longpoll_wait_clamped_to_zero():
    """A server with SDA_LONGPOLL_MAX=0 answers empty immediately while
    still looking long-poll-capable — run_clerk must notice the poll
    did not actually park and supply the cadence itself."""
    service = _ClampedLongpollService()
    client = SdaClient.__new__(SdaClient)
    client.agent = new_agent()
    client.service = service
    client._dead = False
    processed = client.run_clerk(wait_s=30.0, poll_interval=0.1,
                                 deadline=0.8)
    assert processed == 0
    # jittered ~0.1s cadence inside 0.8s: a handful of polls, not a storm
    assert 2 <= service.polls <= 30


def test_await_masked_honors_retry_after_and_deadline():
    """Relay satellite: the await_masked poll loop must back off on the
    server's Retry-After hint (not its own fixed cadence) and never
    sleep past the remaining deadline."""
    from sda_tpu.client import relay
    from sda_tpu.protocol import RoundExpired

    class _BrownoutService:
        def __init__(self):
            self.polls = 0

        def get_round_status(self, caller, aggregation):
            self.polls += 1
            error = ServerError("shedding")
            error.retry_after = 0.1
            raise error

    client = SdaClient.__new__(SdaClient)
    client.agent = new_agent()
    client.service = _BrownoutService()
    t0 = time.monotonic()
    with pytest.raises(RoundExpired):
        # poll_interval is huge: only the Retry-After hint can explain
        # multiple polls inside the 0.7s deadline
        relay.await_masked(client, AggregationId.random(),
                           deadline=0.7, poll_interval=30.0)
    wall = time.monotonic() - t0
    assert wall < 5.0  # capped at the remaining deadline, not 30s
    assert client.service.polls >= 3
    assert metrics.counter_report("relay.").get(
        "relay.await.transient", 0) >= 3


# ---------------------------------------------------------------------------
# shared granted-lease sweep (satellite): one implementation, both planes

def test_granted_lease_sweep_shared_and_statusz_held(plane):
    server = start_server(plane, statusz_endpoint=True)
    core = server.sda_service.server if plane == "async" \
        else server.httpd.sda_service.server
    core.clerking_lease_seconds = 0.2
    try:
        proxy, recipient, clerks, agg = proxied_world(server)
        participate_one(proxy, agg)
        snapshot(proxy, recipient, agg)
        clerk_agent = clerks[0][0]
        job = proxy.get_clerking_job(clerk_agent, clerk_agent.id)
        assert job is not None
        assert core.held_lease_count() == 1
        time.sleep(0.3)  # lease lapses
        assert core.held_lease_count() == 0  # sweep dropped it
        assert requests.get(server.address + "/statusz").json()[
            "lease"]["held"] == 0
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# W3C context over the async seam + observability-endpoint exemptions
# (flight-recorder plane satellites: the cross-process joins that let
# sda-trace explain stitch a round from many processes' spools)

def test_traceparent_joins_parked_longpoll_pickup(plane):
    """A clerk's long-poll carries its traceparent across the wire; the
    server span joins the clerk's trace even when the request PARKS and
    resolves on a wakeup hop — and the recorded server-span duration
    covers the parked time (the async plane amends the span after its
    deferred completion), so a forensics timeline shows the real wait."""
    server = start_server(plane)
    server.sda_service.server.clerking_lease_seconds = 30.0
    try:
        proxy, recipient, clerks, agg = proxied_world(server)
        participate_one(proxy, agg)
        clerk_agent = clerks[0][0]
        got = {}

        def parked_poll():
            with obs.span("clerk.pickup-root") as root:
                got["trace"] = root.trace_id
                got["job"] = proxy.await_clerking_job(
                    clerk_agent, clerk_agent.id, wait_s=20.0)

        t = threading.Thread(target=parked_poll, daemon=True)
        t.start()
        time.sleep(0.4)  # let the request park server-side
        snapshot(proxy, recipient, agg)  # fan-out fires the wakeup
        t.join(timeout=10)
        assert not t.is_alive()
        assert got["job"] is not None
        joined = [s for s in obs.finished_spans()
                  if s.name.startswith("http.server")
                  and s.trace_id == got["trace"]]
        assert joined, "server spans must join the clerk's trace"
        parked = max(joined, key=lambda s: s.duration_s or 0.0)
        assert parked.attributes["http.route"].startswith("GET:")
        assert (parked.duration_s or 0.0) >= 0.3
    finally:
        server.shutdown()


def test_metrics_statusz_admission_and_tracing_exempt_under_load(plane):
    """/metrics and /statusz must answer during the exact overload they
    diagnose: with the rate limiter drained so ordinary requests shed
    429, every scrape still lands 200 — and none of them mint a server
    span (a scrape loop must not churn the ring buffer or the spools)."""
    server = start_server(plane, metrics_endpoint=True,
                          statusz_endpoint=True,
                          rate_limit=0.001, rate_burst=1.0)
    try:
        # burn the single admission token, then prove the limiter bites
        statuses = [requests.get(server.address + "/v1/ping").status_code
                    for _ in range(4)]
        assert 429 in statuses
        for _ in range(20):
            m = requests.get(server.address + "/metrics")
            assert m.status_code == 200
            assert "sda_events_total" in m.text
            z = requests.get(server.address + "/statusz")
            assert z.status_code == 200
            assert "admission" in z.json()
        scraped = [s for s in obs.finished_spans()
                   if "/metrics" in s.name or "statusz" in s.name]
        assert scraped == [], "observability endpoints must not be traced"
    finally:
        server.shutdown()
