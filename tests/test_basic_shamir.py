"""BasicShamir — the reference's declared-but-disabled classic Shamir
scheme (protocol/src/crypto.rs:89-95), implemented end-to-end.

Rides the packed machinery as its k=1 degenerate (same [0; secrets;
randomness] column convention, scheme-dispatched Vandermonde/Lagrange
matrices from fields/numtheory.py), so every execution mode is covered:
federated full loop, pod mesh, streamed, Pallas local step, dropout
quorums, and the CLI.
"""

import itertools

import jax
import numpy as np
import pytest

from sda_tpu.crypto.sharing import new_share_generator, new_secret_reconstructor
from sda_tpu.fields import numtheory
from sda_tpu.mesh import SimulatedPod, StreamedPod, StreamingAggregator, make_mesh
from sda_tpu.protocol import BasicShamirSharing, ChaChaMasking, FullMasking

from util import external_bits


def test_scheme_properties_match_reference_declaration():
    """Derived properties per the commented match arms of crypto.rs:117-155:
    input_size 1, output_size n, privacy_threshold t,
    reconstruction_threshold t+1."""
    s = BasicShamirSharing(share_count=5, privacy_threshold=2,
                           prime_modulus=433)
    assert s.input_size == 1 and s.secret_count == 1
    assert s.output_size == 5
    assert s.privacy_threshold == 2
    assert s.reconstruction_threshold == 3
    with pytest.raises(ValueError):
        BasicShamirSharing(5, 0, 433)      # t must be >= 1
    with pytest.raises(ValueError):
        BasicShamirSharing(5, 5, 433)      # t must be < n
    with pytest.raises(ValueError):
        BasicShamirSharing(433, 3, 433)    # points 1..n need p > n


def test_serde_roundtrip():
    s = BasicShamirSharing(8, 3, 433)
    from sda_tpu.protocol import LinearSecretSharingScheme

    assert LinearSecretSharingScheme.from_obj(s.to_obj()) == s


def test_every_minimal_quorum_reconstructs():
    """Any t+1 of n shares reveal; matrix path == reference Shamir math."""
    s = BasicShamirSharing(share_count=5, privacy_threshold=2,
                           prime_modulus=433)
    gen = new_share_generator(s)
    secrets = np.array([7, 100, 432, 0, 1, 211], dtype=np.int64)
    shares = gen.generate(secrets)
    assert len(shares) == 5
    rec = new_secret_reconstructor(s, secrets.size)
    for subset in itertools.combinations(range(5), 3):
        got = rec.reconstruct([(i, shares[i]) for i in subset])
        np.testing.assert_array_equal(got, secrets % 433)
    with pytest.raises(ValueError):
        rec.reconstruct([(0, shares[0]), (1, shares[1])])  # below quorum


def test_shares_hide_the_secret_at_threshold():
    """t shares are an affine function of t uniform coefficients with a
    full-rank (Vandermonde) coefficient matrix, so they are uniform and
    independent of the secret — verified by rank over Z_p."""
    n, t, p = 5, 2, 433
    M = numtheory.basic_share_matrix(n, t, p)
    # columns 2..2+t multiply the randomness; any t rows of that block
    # must be invertible mod p for perfect privacy
    import itertools as it

    def det2(m):
        return (m[0][0] * m[1][1] - m[0][1] * m[1][0]) % p

    R = [[int(M[i][2 + j]) for j in range(t)] for i in range(n)]
    for rows in it.combinations(range(n), t):
        assert det2([R[rows[0]], R[rows[1]]]) != 0


def needs_devices(k):
    return pytest.mark.skipif(
        len(jax.devices()) < k, reason=f"needs {k} virtual devices"
    )


def fast_basic():
    _, p, _, _ = numtheory.generate_packed_params(3, 8, 28)  # Solinas prime
    return BasicShamirSharing(share_count=8, privacy_threshold=3,
                              prime_modulus=p)


@needs_devices(8)
def test_pod_round_with_dropout():
    s = fast_basic()
    pod = SimulatedPod(
        s, masking_scheme=FullMasking(s.prime_modulus), mesh=make_mesh(4, 2),
        surviving_clerks=(0, 2, 4, 7),  # r = t+1 = 4
    )
    rng = np.random.default_rng(11)
    inputs = rng.integers(0, 1 << 20, size=(8, 48))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


@needs_devices(8)
def test_streamed_pod_chacha():
    s = fast_basic()
    spod = StreamedPod(
        s, ChaChaMasking(s.prime_modulus, 48, 128), mesh=make_mesh(4, 2),
        participants_chunk=8, dim_chunk=24,
    )
    rng = np.random.default_rng(12)
    inputs = rng.integers(0, 1 << 20, size=(11, 48))
    out = np.asarray(spod.aggregate(inputs, jax.random.PRNGKey(2)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


def test_streaming_pallas_kernel():
    """The fused Pallas kernel serves BasicShamir unchanged (k=1 columns)."""
    s = fast_basic()
    agg = StreamingAggregator(
        s, FullMasking(s.prime_modulus), participants_chunk=4, dim_chunk=24,
        use_pallas=True, pallas_interpret=True,
        pallas_external_bits_fn=external_bits,
    )
    assert agg.pallas_active
    rng = np.random.default_rng(13)
    inputs = rng.integers(0, 1 << 20, size=(9, 30))
    out = np.asarray(agg.aggregate(inputs, jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


def test_single_chip_pallas_round():
    """single_chip_round_pallas serves BasicShamir via the dispatched
    matrices (interpret mode, external bits)."""
    from sda_tpu.fields.pallas_round import single_chip_round_pallas

    s = fast_basic()
    fn = single_chip_round_pallas(
        s, FullMasking(s.prime_modulus), tile=128, interpret=True,
        external_bits_fn=external_bits,
    )
    rng = np.random.default_rng(15)
    inputs = rng.integers(0, 1 << 20, size=(5, 500))
    out = np.asarray(fn(jax.numpy.asarray(inputs), jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


def test_oracle_matches_device_given_same_randomness():
    s = fast_basic()
    from sda_tpu.fields import oracle
    import sda_tpu.fields as fields
    import jax.numpy as jnp

    secrets = np.arange(24, dtype=np.int64)
    rng = np.random.default_rng(14)
    randomness = rng.integers(0, s.prime_modulus,
                              size=(s.privacy_threshold, 24), dtype=np.int64)
    host = oracle.packed_share_from_randomness(secrets, randomness, s)
    M = jnp.asarray(numtheory.share_matrix_for(s))
    dev = np.asarray(fields.packed_share_from_randomness(
        jnp.asarray(secrets), jnp.asarray(randomness), M,
        prime=s.prime_modulus, secret_count=1,
    ))
    np.testing.assert_array_equal(host, dev)
