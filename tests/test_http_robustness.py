"""HTTP surface robustness: hostile/malformed inputs must map to clean
4xx responses (server-http/src/lib.rs:105-122 error mapping), never 500s
or wedged connections, and serde must round-trip arbitrary valid resources.
"""

import base64
import json
import urllib.error
import urllib.request

import pytest

from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    AgentId,
    ChaChaMasking,
    EncryptionKeyId,
    FullMasking,
    NoMasking,
    PackedPaillierEncryption,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.http.server import SdaHttpServer
from sda_tpu.server import new_memory_server


@pytest.fixture
def srv():
    server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0")
    server.start_background()
    yield server
    server.shutdown()


def _post(url, body: bytes, auth: str = "aa0c2e05-5f7a-4169-9b45-477d57d5b131:tok"):
    req = urllib.request.Request(url, data=body, method="POST")
    req.add_header("Content-Type", "application/json")
    req.add_header(
        "Authorization", "Basic " + base64.b64encode(auth.encode()).decode()
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers)


def test_malformed_json_bodies_return_400_not_500(srv):
    url = srv.address + "/v1/agents/me"
    for body in (b"{", b"not json at all", b"[1,2,", b"\xff\xfe\x00"):
        status, _ = _post(url, body)
        assert status == 400, f"body {body!r} -> {status}"
    # connection/threading still healthy afterwards
    assert urllib.request.urlopen(srv.address + "/v1/ping", timeout=10).status == 200


def test_wrong_shape_resources_return_400(srv):
    url = srv.address + "/v1/agents/me"
    cases = [
        {},  # missing everything
        {"id": 42, "verification_key": None},  # wrong types
        {"id": "aa0c2e05-5f7a-4169-9b45-477d57d5b131",
         "verification_key": {"id": "x", "body": {"Sodium": "!!notbase64!!"}}},
    ]
    for obj in cases:
        status, _ = _post(url, json.dumps(obj).encode())
        assert status == 400, f"{obj} -> {status}"


def test_missing_and_bad_auth_return_401(srv):
    req = urllib.request.Request(
        srv.address + "/v1/aggregations", method="GET"
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        raised = None
    except urllib.error.HTTPError as e:
        raised = e.code
    assert raised == 401

    # garbage Basic header (undecodable base64) also 401, not 500
    req = urllib.request.Request(srv.address + "/v1/aggregations", method="GET")
    req.add_header("Authorization", "Basic %%%garbage%%%")
    try:
        urllib.request.urlopen(req, timeout=10)
        raised = None
    except urllib.error.HTTPError as e:
        raised = e.code
    assert raised == 401


def test_resource_not_found_header_distinguishes_404s(srv, tmp_path):
    """Missing RESOURCE answers 404 + X-Resource-Not-Found (client maps it
    to None); missing ROUTE answers 404 WITHOUT the header (client raises
    NotFound) — lib.rs:338-343 semantics."""
    from sda_tpu.client import SdaClient
    from sda_tpu.http.client import SdaHttpClient
    from sda_tpu.protocol import NotFound
    from sda_tpu.store import Filebased

    ks = Filebased(tmp_path)
    client = SdaClient(SdaClient.new_agent(ks), ks, SdaHttpClient(srv.address, ks))
    client.upload_agent()

    # missing RESOURCE: X-Resource-Not-Found present -> None
    missing = client.service.get_aggregation(
        client.agent, AggregationId.random()
    )
    assert missing is None

    # missing ROUTE (authenticated): 404 without the header
    http = client.service  # SdaHttpClient
    with pytest.raises(NotFound):
        http._get(client.agent, "/v1/definitely/not/a/route")
    user, token = http._auth(client.agent)  # the really-minted token
    req = urllib.request.Request(srv.address + "/v1/definitely/not/a/route")
    req.add_header(
        "Authorization",
        "Basic " + base64.b64encode(f"{user}:{token}".encode()).decode(),
    )
    try:
        urllib.request.urlopen(req, timeout=10)
        headers, code = {}, None
    except urllib.error.HTTPError as e:
        headers, code = dict(e.headers), e.code
    assert code == 404  # authenticated route-miss
    assert "X-Resource-Not-Found" not in headers


# ---------------------------------------------------------------------------
# randomized serde round-trip fuzz

def _random_sharing(rng):
    if rng.choice([True, False]):
        return AdditiveSharing(
            share_count=int(rng.integers(1, 50)), modulus=int(rng.integers(2, 1 << 40))
        )
    return PackedShamirSharing(
        secret_count=int(rng.integers(1, 10)),
        share_count=int(rng.integers(2, 100)),
        privacy_threshold=int(rng.integers(1, 20)),
        prime_modulus=int(rng.integers(2, 1 << 30)),
        omega_secrets=int(rng.integers(1, 1 << 20)),
        omega_shares=int(rng.integers(1, 1 << 20)),
    )


def _random_masking(rng, modulus, dim):
    pick = rng.integers(0, 3)
    if pick == 0:
        return NoMasking()
    if pick == 1:
        return FullMasking(modulus)
    return ChaChaMasking(modulus, dim, int(rng.choice([64, 128, 256])))


def _random_encryption(rng):
    if rng.choice([True, False]):
        return SodiumEncryption()
    mvb = int(rng.integers(1, 40))
    window = mvb + int(rng.integers(0, 20))
    count = int(rng.integers(1, 16))
    return PackedPaillierEncryption(
        count, window, mvb, max(512, count * window + 1)
    )


def test_aggregation_serde_roundtrip_fuzz():
    import numpy as np

    rng = np.random.default_rng(20260730)

    def seeded_id(cls):
        # ids derived from the seeded rng so any failure replays exactly
        return cls(str(__import__("uuid").UUID(bytes=rng.bytes(16), version=4)))

    for _ in range(200):
        dim = int(rng.integers(1, 1 << 24))
        sharing = _random_sharing(rng)
        modulus = getattr(sharing, "modulus", None) or sharing.prime_modulus
        agg = Aggregation(
            id=seeded_id(AggregationId),
            title="t" * int(rng.integers(0, 30)) + str(rng.integers(0, 10**9)),
            vector_dimension=dim,
            modulus=modulus,
            recipient=seeded_id(AgentId),
            recipient_key=seeded_id(EncryptionKeyId),
            masking_scheme=_random_masking(rng, modulus, dim),
            committee_sharing_scheme=sharing,
            recipient_encryption_scheme=_random_encryption(rng),
            committee_encryption_scheme=_random_encryption(rng),
        )
        wire = json.dumps(agg.to_obj())
        back = Aggregation.from_obj(json.loads(wire))
        assert back.to_obj() == agg.to_obj()
        # scheme objects themselves compare equal through the round trip
        assert back.committee_sharing_scheme == agg.committee_sharing_scheme
        assert back.masking_scheme.to_obj() == agg.masking_scheme.to_obj()
        assert back.recipient_encryption_scheme == agg.recipient_encryption_scheme


def test_varint_decode_fuzz_never_crashes():
    """Garbage byte streams: clean ValueError or a valid decode, never an
    unhandled exception — the decoder faces untrusted sealed-box payloads."""
    from sda_tpu.crypto import varint

    import numpy as np

    rng = np.random.default_rng(41)
    for size in [0, 1, 3, 9, 64, 513]:
        for _ in range(50):
            raw = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
            try:
                decoded = varint.decode(raw)
                # decodable garbage must round-trip
                np.testing.assert_array_equal(varint.decode(varint.encode(decoded)), decoded)
            except ValueError:
                pass
