"""Exactly-once device participation: the duplicate/equivocation matrix.

The sporadic-device plane (ISSUE 9): ``create_participation`` is a
single-winner conditional insert keyed by ``(aggregation, participant)``
with a canonical content digest alongside, on all four store backends —
fresh inserts win, byte-identical replays succeed idempotently, any
same-key-different-content upload raises the typed
``ParticipationConflict`` (HTTP 409, terminal for the retrying
transport). The client half is the durable participation journal:
sealed-bundle persistence before the first upload, verbatim re-upload on
resume, so a crashed phone can never double-count itself by recomputing
with fresh randomness.
"""

import threading

import numpy as np
import pytest

from sda_tpu import chaos
from sda_tpu.client import SdaClient, SdaParticipant
from sda_tpu.client.journal import ParticipationJournal
from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    FullMasking,
    NoMasking,
    NotFound,
    Participation,
    ParticipationConflict,
    ParticipationId,
    Snapshot,
    SnapshotId,
    SodiumEncryption,
)
from sda_tpu.server import (
    SdaServerService,
    new_jsonfs_server,
    new_memory_server,
    new_mongo_server,
    new_sqlite_server,
)
from sda_tpu.http import SdaHttpServer
from sda_tpu.server.core import SdaServer
from sda_tpu.utils import metrics

from util import mock_encryption, new_agent, new_full_agent

BACKENDS = ["memory", "sqlite", "jsonfs", "fakemongo"]


@pytest.fixture(autouse=True)
def _clean_slate():
    chaos.reset()
    metrics.reset_counters()
    yield
    chaos.reset()


def _one_service(backend, tmp_path):
    if backend == "memory":
        return new_memory_server()
    if backend == "sqlite":
        return new_sqlite_server(tmp_path / "plane.db")
    if backend == "jsonfs":
        return new_jsonfs_server(tmp_path / "plane-jfs")
    from fake_mongo import FakeDatabase

    return new_mongo_server(FakeDatabase())


def _two_handles(backend, tmp_path):
    """Two INDEPENDENT service handles over one shared backend — the
    sharing shape of two fleet worker processes (test_fleet.py)."""
    if backend == "memory":
        from sda_tpu.server.memory import (
            MemoryAggregationsStore,
            MemoryAgentsStore,
            MemoryAuthTokensStore,
            MemoryClerkingJobsStore,
        )

        stores = dict(
            agents_store=MemoryAgentsStore(),
            auth_tokens_store=MemoryAuthTokensStore(),
            aggregation_store=MemoryAggregationsStore(),
            clerking_job_store=MemoryClerkingJobsStore(),
        )
        return SdaServerService(SdaServer(**stores)), \
            SdaServerService(SdaServer(**stores))
    if backend == "sqlite":
        path = tmp_path / "shared.db"
        return new_sqlite_server(path), new_sqlite_server(path)
    if backend == "jsonfs":
        root = tmp_path / "shared-jfs"
        return new_jsonfs_server(root), new_jsonfs_server(root)
    from fake_mongo import FakeDatabase

    db = FakeDatabase()
    return new_mongo_server(db), new_mongo_server(db)


def _world(service, clerks=2):
    recipient, rkey = new_full_agent(service)
    committee = [new_full_agent(service) for _ in range(clerks)]
    agg = Aggregation(
        id=AggregationId.random(), title="plane", vector_dimension=4,
        modulus=433, recipient=recipient.id,
        recipient_key=rkey.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=clerks,
                                                 modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    return recipient, committee, agg


def _participation(agent, agg, committee, payload=b"x", pid=None):
    return Participation(
        id=pid or ParticipationId.random(), participant=agent.id,
        aggregation=agg.id, recipient_encryption=None,
        clerk_encryptions=[(a.id, mock_encryption(payload))
                           for (a, _) in committee],
    )


# ---------------------------------------------------------------------------
# the duplicate/equivocation matrix, all four backends


@pytest.mark.parametrize("backend", BACKENDS)
def test_fresh_insert_then_byte_identical_replay(backend, tmp_path):
    service = _one_service(backend, tmp_path)
    recipient, committee, agg = _world(service)
    agent = new_agent()
    service.create_agent(agent, agent)
    participation = _participation(agent, agg, committee)

    service.create_participation(agent, participation)
    # the lost-ack retry: the SAME bytes again — idempotent success
    service.create_participation(agent, participation)
    service.create_participation(agent, participation)

    status = service.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 1  # deduped, never doubled
    counters = metrics.counter_report()
    assert counters["server.participation.created"] == 1
    assert counters["server.participation.replayed"] == 2
    assert "server.participation.equivocation" not in counters
    # the replay really served the original bytes back into the round
    stored = service.server.aggregation_store
    snap = SnapshotId.random()
    stored.snapshot_participations(agg.id, snap)
    [frozen] = stored.iter_snapped_participations(agg.id, snap)
    assert frozen.canonical_digest() == participation.canonical_digest()


@pytest.mark.parametrize("backend", BACKENDS)
def test_same_id_different_content_is_rejected(backend, tmp_path):
    """The blind-overwrite hole (seed: sqlite ``DO UPDATE``, jsonfs
    ``_write_json``, memory dict assign silently replaced): re-uploading
    an existing participation id with different bytes must conflict."""
    service = _one_service(backend, tmp_path)
    recipient, committee, agg = _world(service)
    agent = new_agent()
    service.create_agent(agent, agent)
    original = _participation(agent, agg, committee, payload=b"honest")
    service.create_participation(agent, original)

    forged = _participation(agent, agg, committee, payload=b"forged",
                            pid=original.id)
    with pytest.raises(ParticipationConflict):
        service.create_participation(agent, forged)
    # the original bytes survived untouched
    snap = SnapshotId.random()
    store = service.server.aggregation_store
    store.snapshot_participations(agg.id, snap)
    [frozen] = store.iter_snapped_participations(agg.id, snap)
    assert frozen.canonical_digest() == original.canonical_digest()
    assert metrics.counter_report()[
        "server.participation.equivocation"] == 1


@pytest.mark.parametrize("backend", BACKENDS)
def test_same_agent_new_id_is_rejected(backend, tmp_path):
    """The double-count hole: a device that recomputes with fresh
    randomness (new id, new bytes) after a crash must NOT land twice."""
    service = _one_service(backend, tmp_path)
    recipient, committee, agg = _world(service)
    agent = new_agent()
    service.create_agent(agent, agent)
    service.create_participation(
        agent, _participation(agent, agg, committee, payload=b"first"))
    with pytest.raises(ParticipationConflict):
        service.create_participation(
            agent, _participation(agent, agg, committee, payload=b"second"))
    status = service.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 1


@pytest.mark.parametrize("backend", BACKENDS)
def test_foreign_agent_reusing_an_id_is_rejected(backend, tmp_path):
    """A different agent claiming an EXISTING participation id must not
    replace (or alias) the original owner's bundle."""
    service = _one_service(backend, tmp_path)
    recipient, committee, agg = _world(service)
    victim, thief = new_agent(), new_agent()
    service.create_agent(victim, victim)
    service.create_agent(thief, thief)
    original = _participation(victim, agg, committee, payload=b"victim")
    service.create_participation(victim, original)
    with pytest.raises(ParticipationConflict):
        service.create_participation(
            thief, _participation(thief, agg, committee, payload=b"thief",
                                  pid=original.id))


@pytest.mark.parametrize("backend", BACKENDS)
def test_post_freeze_arrival_gets_late_treatment(backend, tmp_path):
    """Exactly-once ingestion must not change the late-arrival contract:
    a post-freeze participation is ACCEPTED (counted aggregation-wide)
    but stays out of the frozen round."""
    service = _one_service(backend, tmp_path)
    recipient, committee, agg = _world(service)
    early = new_agent()
    service.create_agent(early, early)
    service.create_participation(
        early, _participation(early, agg, committee))
    store = service.server.aggregation_store
    snap = SnapshotId.random()
    assert store.snapshot_participations(agg.id, snap) is True

    late = new_agent()
    service.create_agent(late, late)
    service.create_participation(late, _participation(late, agg, committee))
    assert store.count_participations_snapshot(agg.id, snap) == 1
    status = service.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 2


@pytest.mark.parametrize("backend", BACKENDS)
def test_raced_two_uploaders_same_key_single_winner(backend, tmp_path):
    """Two handles (the two-process sharing shape) racing DIFFERENT
    bundles under one (aggregation, participant) key: exactly one winner
    per backend, the loser typed-rejected, never both stored."""
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a)
    agent = new_agent()
    a.create_agent(agent, agent)
    uploads = [
        (a, _participation(agent, agg, committee, payload=b"via-a")),
        (b, _participation(agent, agg, committee, payload=b"via-b")),
    ]
    outcomes = [None, None]

    def upload(ix):
        service, participation = uploads[ix]
        try:
            service.create_participation(agent, participation)
            outcomes[ix] = "won"
        except ParticipationConflict:
            outcomes[ix] = "conflict"

    threads = [threading.Thread(target=upload, args=(ix,))
               for ix in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(outcomes) == ["conflict", "won"]
    status = a.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 1
    # the stored bundle is the winner's, intact
    snap = SnapshotId.random()
    store = b.server.aggregation_store
    store.snapshot_participations(agg.id, snap)
    [frozen] = store.iter_snapped_participations(agg.id, snap)
    winner_ix = outcomes.index("won")
    assert frozen.canonical_digest() == \
        uploads[winner_ix][1].canonical_digest()


@pytest.mark.parametrize("backend", BACKENDS)
def test_raced_identical_replay_is_idempotent(backend, tmp_path):
    """Two handles racing the SAME bytes (a resumed device retrying via a
    second server): both succeed, exactly one row exists."""
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a)
    agent = new_agent()
    a.create_agent(agent, agent)
    participation = _participation(agent, agg, committee)
    errors = []

    def upload(service):
        try:
            service.create_participation(agent, participation)
        except Exception as e:  # pragma: no cover - the failure under test
            errors.append(e)

    threads = [threading.Thread(target=upload, args=(s,)) for s in (a, b)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    status = a.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 1


def test_conflict_is_semantic_for_the_store_breaker():
    """A rejected equivocation is detection WORKING: it must pass through
    a breaker-wrapped store uncounted (a flood of equivocating devices
    must never trip the breaker open)."""
    from sda_tpu.server.breaker import CircuitBreaker, wrap_server_stores

    service = new_memory_server()
    breaker = wrap_server_stores(service.server,
                                 CircuitBreaker(threshold=2, recovery_s=9.0))
    recipient, committee, agg = _world(service)
    agent = new_agent()
    service.create_agent(agent, agent)
    service.create_participation(
        agent, _participation(agent, agg, committee, payload=b"first"))
    for _ in range(5):  # well past the trip threshold
        with pytest.raises(ParticipationConflict):
            service.create_participation(
                agent, _participation(agent, agg, committee,
                                      payload=b"equiv"))
    assert breaker.report()["state"] == "closed"
    assert breaker.report()["times_opened"] == 0


# ---------------------------------------------------------------------------
# the durable journal


def test_journal_record_load_reap(tmp_path):
    journal = ParticipationJournal(tmp_path / "journal")
    agent = new_agent()
    agg_id = AggregationId.random()
    participation = Participation(
        id=ParticipationId.random(), participant=agent.id,
        aggregation=agg_id, recipient_encryption=None,
        clerk_encryptions=[(new_agent().id, mock_encryption(b"j"))],
    )
    assert journal.load(agent.id, agg_id) is None
    journal.record(participation)
    loaded = journal.load(agent.id, agg_id)
    assert loaded.canonical_digest() == participation.canonical_digest()
    assert len(journal) == 1
    assert journal.keys() == [(str(agent.id), str(agg_id))]
    # keyed by (agent, aggregation): a re-record REPLACES, never appends
    journal.record(participation)
    assert len(journal) == 1
    # pending() filters by agent
    assert journal.pending(new_agent().id) == []
    [pending] = journal.pending(agent.id)
    assert pending.id == participation.id
    assert journal.reap(agent.id, agg_id) is True
    assert journal.reap(agent.id, agg_id) is False
    assert journal.load(agent.id, agg_id) is None


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_crash_resume_reuploads_same_bytes(tmp_path):
    """The tentpole flow: seal + journal, crash before the upload, rejoin
    as a fresh client, resume — the server receives the ORIGINAL bytes
    exactly once; a second resume finds nothing pending."""
    service = new_memory_server()
    recipient, agg, clerks = _crypto_world(service)
    journal = ParticipationJournal(tmp_path / "journal")

    keystore = MemoryKeystore()
    device = SdaClient(SdaClient.new_agent(keystore), keystore, service)
    device.upload_agent()
    participation = device.new_participation([1, 2, 3, 4], agg.id)
    journal.record(participation)
    # CRASH: the process dies before upload_participation ever runs

    rejoined = SdaParticipant(device.agent, MemoryKeystore(), service)
    assert rejoined.resume(journal) == 1
    assert len(journal) == 0  # reaped on confirmed upload
    status = service.get_aggregation_status(recipient.agent, agg.id)
    assert status.number_of_participations == 1
    assert rejoined.resume(journal) == 0  # nothing pending: no-op
    counters = metrics.counter_report()
    assert counters["participant.resumed"] == 1
    assert counters["server.participation.created"] == 1

    # the crash-AFTER-upload flavor: journaled, uploaded, ack lost before
    # the reap — resume replays byte-identically, the server dedupes
    keystore2 = MemoryKeystore()
    device2 = SdaClient(SdaClient.new_agent(keystore2), keystore2, service)
    device2.upload_agent()
    p2 = device2.new_participation([5, 6, 7, 8], agg.id)
    journal.record(p2)
    device2.upload_participation(p2)
    # CRASH before the reap; rejoin:
    assert SdaParticipant(device2.agent, MemoryKeystore(),
                          service).resume(journal) == 1
    status = service.get_aggregation_status(recipient.agent, agg.id)
    assert status.number_of_participations == 2
    assert metrics.counter_report()["server.participation.replayed"] == 1


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_journaled_participate_retry_resumes_not_recomputes(tmp_path):
    """Re-running participate(journal=...) after a crash must re-upload
    the JOURNALED bytes (the only ones that replay idempotently), never
    overwrite the entry with a fresh-randomness bundle that would
    conflict against the already-landed upload."""
    service = new_memory_server()
    recipient, agg, clerks = _crypto_world(service)
    journal = ParticipationJournal(tmp_path / "journal")
    ks = MemoryKeystore()
    device = SdaClient(SdaClient.new_agent(ks), ks, service)
    device.upload_agent()
    # mid-upload crash: server holds the bytes, the journal entry lives
    sealed = device.new_participation([1, 2, 3, 4], agg.id)
    journal.record(sealed)
    device.upload_participation(sealed)
    # the user's natural retry of the SAME command converges to success
    device.participate([1, 2, 3, 4], agg.id, journal=journal)
    assert len(journal) == 0
    status = service.get_aggregation_status(recipient.agent, agg.id)
    assert status.number_of_participations == 1
    counters = metrics.counter_report()
    assert counters["participant.journal.recovered"] == 1
    assert counters["server.participation.replayed"] == 1
    assert "server.participation.equivocation" not in counters


def test_http_resume_reaps_orphaned_entry(srv, tmp_path):
    """Over the WIRE, a journal entry for a deleted aggregation must take
    the orphan path (X-Resource-Not-Found 404 -> NotFound), not be
    miscounted as successfully resumed."""
    client = _fast_client(srv)
    recipient, committee, agg = _world(client)
    agent = new_agent()
    client.create_agent(agent, agent)
    journal = ParticipationJournal(tmp_path / "journal")
    journal.record(_participation(agent, agg, committee))
    client.delete_aggregation(recipient, agg.id)
    resumer = SdaClient(agent, MemoryKeystore(), client)
    assert resumer.resume(journal) == 0
    assert len(journal) == 0  # reaped as orphaned, not "resumed"
    counters = metrics.counter_report()
    assert counters["participant.resume.orphaned"] == 1
    assert "participant.resumed" not in counters


def test_resume_reaps_orphaned_and_conflicted_entries(tmp_path):
    service = new_memory_server()
    recipient, committee, agg = _world(service)
    journal = ParticipationJournal(tmp_path / "journal")
    agent = new_agent()
    service.create_agent(agent, agent)

    # orphaned: the journal names an aggregation that no longer exists
    gone = _participation(agent, agg, committee)
    gone.aggregation = AggregationId.random()
    journal.record(gone)
    # conflicted: the server already holds a DIFFERENT bundle for us
    service.create_participation(
        agent, _participation(agent, agg, committee, payload=b"server"))
    journal.record(_participation(agent, agg, committee, payload=b"local"))

    client = SdaClient(agent, MemoryKeystore(), service)
    assert client.resume(journal) == 0  # neither entry lands...
    assert len(journal) == 0            # ...but both are reaped (moot)
    counters = metrics.counter_report()
    assert counters["participant.resume.orphaned"] == 1
    assert counters["participant.resume.conflict"] == 1


# ---------------------------------------------------------------------------
# the full client flow + HTTP seam


def _crypto_world(service, clerks=3):
    """A real-crypto additive world for SdaClient-driven tests; returns
    the recipient CLIENT (its keystore holds the reveal keys), the
    aggregation, and the clerk clients."""
    def _client():
        ks = MemoryKeystore()
        c = SdaClient(SdaClient.new_agent(ks), ks, service)
        c.upload_agent()
        return c

    recipient = _client()
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    clerk_clients = [_client() for _ in range(clerks)]
    for c in clerk_clients:
        c.upload_encryption_key(c.new_encryption_key())
    agg = Aggregation(
        id=AggregationId.random(), title="journal", vector_dimension=4,
        modulus=433, recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=clerks,
                                                 modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(agg)
    recipient.begin_aggregation(agg.id)
    return recipient, agg, clerk_clients


@pytest.fixture()
def srv():
    service = new_memory_server()
    server = SdaHttpServer(service, bind="127.0.0.1:0")
    server.start_background()
    yield server
    server.shutdown()


def _fast_client(srv):
    from sda_tpu.http import SdaHttpClient

    return SdaHttpClient(srv.address, token="plane-token",
                         max_retries=6, backoff_base=0.01, backoff_cap=0.05)


@pytest.mark.chaos
def test_http_identical_replay_after_lost_response(srv):
    """A lost ack + transport retry re-sends the SAME bytes: the server
    answers success via the replay path, one participation exists."""
    client = _fast_client(srv)
    recipient, committee, agg = _world(client)
    agent = new_agent()
    client.create_agent(agent, agent)
    participation = _participation(agent, agg, committee)
    chaos.configure("http.server.response", drop=True, times=1)
    client.create_participation(agent, participation)
    status = client.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 1
    counters = metrics.counter_report()
    assert counters["server.participation.replayed"] >= 1
    assert "http.participation.conflict" not in counters


def test_http_equivocation_is_409_terminal(srv):
    """Same agent, new bundle: HTTP 409, typed, counted, NEVER retried —
    and the server-side sum is untouched."""
    client = _fast_client(srv)
    recipient, committee, agg = _world(client)
    agent = new_agent()
    client.create_agent(agent, agent)
    client.create_participation(
        agent, _participation(agent, agg, committee, payload=b"first"))
    metrics.reset_counters()
    with pytest.raises(ParticipationConflict):
        client.create_participation(
            agent, _participation(agent, agg, committee, payload=b"equiv"))
    counters = metrics.counter_report()
    assert counters["http.participation.conflict"] == 1
    assert counters["server.participation.equivocation"] == 1
    # terminal: one attempt, zero transport retries spent on the 409
    assert "http.retry.attempt" not in counters
    status = client.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 1


@pytest.mark.chaos
@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_http_journal_resume_under_lost_response(srv, tmp_path):
    """Crash-resume over the real wire with the lost-ack failpoint armed:
    the journaled bytes land exactly once."""
    proxy = _fast_client(srv)
    recipient_ks = MemoryKeystore()
    recipient = SdaClient(SdaClient.new_agent(recipient_ks), recipient_ks,
                          proxy)
    recipient.upload_agent()
    rkey = recipient.new_encryption_key()
    recipient.upload_encryption_key(rkey)
    clerks = []
    for _ in range(3):
        ks = MemoryKeystore()
        c = SdaClient(SdaClient.new_agent(ks), ks, proxy)
        c.upload_agent()
        c.upload_encryption_key(c.new_encryption_key())
        clerks.append(c)
    agg = Aggregation(
        id=AggregationId.random(), title="wire-journal",
        vector_dimension=4, modulus=433,
        recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=FullMasking(433),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(agg)
    recipient.begin_aggregation(agg.id)

    journal = ParticipationJournal(tmp_path / "journal")
    ks = MemoryKeystore()
    device = SdaClient(SdaClient.new_agent(ks), ks, proxy)
    device.upload_agent()
    # the device uploads, the server stores, the ack is DROPPED; the
    # transport retries (byte-identical) and the journal entry survives
    # until the reap — then the device "crashes" before reaping anyway,
    # simulated by recording the entry back
    sealed = device.new_participation([1, 2, 3, 4], agg.id)
    journal.record(sealed)
    chaos.configure("http.server.response", drop=True, times=1)
    device.upload_participation(sealed)
    # rejoin from a cold process: replay is deduped server-side
    rejoined = SdaParticipant(device.agent, MemoryKeystore(), proxy)
    assert rejoined.resume(journal) == 1
    status = proxy.get_aggregation_status(recipient.agent, agg.id)
    assert status.number_of_participations == 1
    counters = metrics.counter_report()
    assert counters["server.participation.replayed"] >= 1

    # and the round still reveals bit-exactly with the resumed bundle in
    recipient.end_aggregation(agg.id)
    for c in clerks + [recipient]:  # the recipient may be elected
        c.run_chores(-1)
    out = recipient.reveal_aggregation(agg.id)
    np.testing.assert_array_equal(out.positive().values, [1, 2, 3, 4])


# ---------------------------------------------------------------------------
# churn schedule + the in-process churn round on every backend


def test_churn_schedule_is_seeded_and_alternates():
    a = chaos.churn_schedule(64, 0.4, seed=9)
    b = chaos.churn_schedule(64, 0.4, seed=9)
    assert a == b  # deterministic for a given (agents, rate, seed)
    assert a != chaos.churn_schedule(64, 0.4, seed=10)
    departures = [p for p in a if p["departs"]]
    assert departures, "40% of 64 must produce departures"
    # phases alternate by departure ordinal, starting mid-upload: every
    # plan with >= 1 departure exercises the lost-ack replay path
    phases = [p["phase"] for p in departures]
    assert phases[0] == "mid-upload"
    assert all(ph == ("mid-upload" if i % 2 == 0 else "pre-upload")
               for i, ph in enumerate(phases))
    assert all(p["rejoins"] for p in departures)
    assert all(p["phase"] is None for p in a if not p["departs"])
    assert chaos.churn_schedule(8, 0.0, seed=1) == [
        {"index": i, "departs": False, "phase": None, "rejoins": False}
        for i in range(8)
    ]
    with pytest.raises(ValueError):
        chaos.churn_schedule(8, 1.5)


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
@pytest.mark.parametrize("backend", BACKENDS)
def test_churn_round_reveals_bit_exactly(backend, tmp_path):
    """A ≥20%-churn round on EVERY backend (fake mongo included): each
    departure journals, crashes at its scheduled point, rejoins, resumes;
    the reveal is bit-exact with zero double counts."""
    service = _one_service(backend, tmp_path)
    recipient, agg, clerk_clients = _crypto_world(service)
    journal = ParticipationJournal(tmp_path / "journal")
    participants, dim, modulus = 10, 4, 433
    plan = chaos.churn_schedule(participants, 0.5, seed=13)
    assert sum(p["departs"] for p in plan) >= 2  # >= 20% churn

    rng = np.random.default_rng(13)
    inputs = rng.integers(0, modulus, size=(participants, dim),
                          dtype=np.int64)
    departed = []
    for i, row in enumerate(inputs):
        ks = MemoryKeystore()
        device = SdaClient(SdaClient.new_agent(ks), ks, service)
        device.upload_agent()
        if plan[i]["departs"]:
            sealed = device.new_participation([int(x) for x in row], agg.id)
            journal.record(sealed)
            if plan[i]["phase"] == "mid-upload":
                device.upload_participation(sealed)
            departed.append(device.agent)
        else:
            device.participate([int(x) for x in row], agg.id,
                               journal=journal)
    assert len(journal) == len(departed)  # confirmed uploads were reaped
    for agent in departed:
        assert SdaParticipant(agent, MemoryKeystore(),
                              service).resume(journal) == 1

    # one equivocation probe: fresh randomness from a churned agent must
    # be rejected and must not perturb the sum
    probe = SdaClient(departed[0], MemoryKeystore(), service)
    with pytest.raises(ParticipationConflict):
        probe.participate([0] * dim, agg.id)

    status = service.get_aggregation_status(recipient.agent, agg.id)
    assert status.number_of_participations == participants  # zero doubles
    counters = metrics.counter_report()
    mid_uploads = sum(p["departs"] and p["phase"] == "mid-upload"
                      for p in plan)
    assert counters["server.participation.replayed"] == mid_uploads
    assert counters["server.participation.equivocation"] == 1
    assert counters["server.participation.created"] == participants

    # ...and the round reveals bit-exactly with every resumed bundle in
    recipient.end_aggregation(agg.id)
    for c in clerk_clients + [recipient]:  # the recipient may be elected
        c.run_chores(-1)
    out = recipient.reveal_aggregation(agg.id)
    np.testing.assert_array_equal(out.positive().values,
                                  inputs.sum(axis=0) % modulus)
