"""Device perf plane: compile/retrace telemetry, roofline cost analysis,
the /statusz debug endpoint, and the compile-count tripwires.

The tripwires guard two pinned claims:

- ``mesh/streaming.py``: "at most two compiled shapes per axis" (full
  chunk + remainder) — the compile-cache survival lever next to
  ``tests/test_compile_cache.py``'s persistent-cache contract;
- a repeated ``SimulatedPod.aggregate`` with identical shapes triggers
  ZERO retraces, while a forced shape change mid-run emits an
  ``xla.retrace`` span event into the exported trace.
"""

import numpy as np
import pytest
import requests

from sda_tpu import obs
from sda_tpu.fields import numtheory
from sda_tpu.http import SdaHttpServer
from sda_tpu.mesh import SimulatedPod, StreamingAggregator
from sda_tpu.obs import devprof
from sda_tpu.protocol import FullMasking, PackedShamirSharing
from sda_tpu.server import new_memory_server
from sda_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _fresh_obs():
    obs.reset_all()
    yield
    obs.reset_all()
    devprof.enable_cost_analysis(False)


def _scheme():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    return PackedShamirSharing(3, 8, t, p, w2, w3), p


# -- compile-count tripwires -------------------------------------------------

def test_simpod_identical_shapes_zero_retraces():
    scheme, p = _scheme()
    pod = SimulatedPod(scheme, FullMasking(p))
    rng = np.random.default_rng(0)
    x = rng.integers(0, 1 << 20, size=(8, 48), dtype=np.int64)
    out = None
    for _ in range(3):
        out = pod.aggregate(x)
    assert (np.asarray(out).astype(object)
            == x.astype(object).sum(axis=0) % p).all()
    prof = devprof.profile("mesh.simpod.round")
    assert prof.calls == 3
    assert prof.compiles == 1, "identical shapes must reuse the compile"
    assert prof.retraces == 0
    assert len(prof.shapes) == 1
    assert metrics.counter_report("xla.compile.retrace") == {}


def test_simpod_shape_change_midrun_emits_retrace_span_event():
    scheme, p = _scheme()
    pod = SimulatedPod(scheme, FullMasking(p))
    rng = np.random.default_rng(0)
    pod.aggregate(rng.integers(0, 99, size=(8, 48), dtype=np.int64))
    # forcing a shape change mid-run: the next dispatch pays a retrace
    pod.aggregate(rng.integers(0, 99, size=(8, 96), dtype=np.int64))
    prof = devprof.profile("mesh.simpod.round")
    assert prof.compiles == 2 and prof.retraces == 1
    counters = metrics.counter_report("xla.compile.retrace")
    assert counters.get("xla.compile.retrace") == 1
    assert counters.get("xla.compile.retrace.mesh.simpod.round") == 1
    # ... and the retrace is attributed in the exported trace, parented
    # into the round that paid it (aggregate runs under timed_phase)
    trace = obs.chrome_trace()
    instants = [e for e in trace["traceEvents"]
                if e.get("ph") == "i" and e["name"] == "xla.retrace"]
    assert len(instants) == 1
    assert instants[0]["args"]["function"] == "mesh.simpod.round"
    round_spans = [e for e in trace["traceEvents"]
                   if e.get("ph") == "X" and e["name"] == "mesh.round"]
    assert instants[0]["args"]["span_id"] in {
        e["args"]["span_id"] for e in round_spans}


def test_streaming_at_most_two_compiled_shapes_per_axis():
    scheme, p = _scheme()
    agg = StreamingAggregator(scheme, FullMasking(p),
                              participants_chunk=4, dim_chunk=24)
    rng = np.random.default_rng(1)
    # ragged on BOTH axes: 10 = 2x4 + 2 participants, 60 = 2x24 + 12 dims
    x = rng.integers(0, 1 << 10, size=(10, 60), dtype=np.int64)
    out = agg.aggregate(x)
    assert (np.asarray(out).astype(object)
            == x.astype(object).sum(axis=0) % p).all()
    steps = devprof.profile("stream.step").block_shapes()
    assert steps, "stream.step never profiled"
    p_shapes = {s[0] for s in steps}
    d_shapes = {s[1] for s in steps}
    assert len(p_shapes) <= 2, f"participant-axis shapes {p_shapes}"
    assert len(d_shapes) <= 2, f"dim-axis shapes {d_shapes}"
    finales = devprof.profile("stream.finale").block_shapes()
    assert len({s[-1] for s in finales}) <= 2


def test_model_scale_rounds_one_shape_per_stage_zero_retraces():
    """The sharded+streamed model-scale path (mesh/devscale.py drives
    StreamedPod with uniform tails): repeated same-shape rounds must
    register at most ONE compiled shape per stage, and a TILE-COUNT
    change (a different dim at the same tile width) must reuse the
    per-tile step program — only the per-dim-size finale may add a
    shape."""
    import jax

    from sda_tpu.mesh import StreamedPod, make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    scheme, p = _scheme()
    pod = StreamedPod(scheme, FullMasking(p), mesh=make_mesh(4, 2),
                      participants_chunk=8, dim_chunk=96, uniform_tail=True)
    rng = np.random.default_rng(7)
    x = rng.integers(0, 1 << 10, size=(16, 250), dtype=np.int64)
    for _ in range(3):  # 3 rounds, 3 tiles each: same shapes throughout
        out = pod.aggregate(x, key=jax.random.PRNGKey(1))
    assert (np.asarray(out) == x.sum(axis=0) % p).all()
    step = devprof.profile("stream.pod.step")
    finale = devprof.profile("stream.pod.finale")
    assert len(step.shapes) == 1, step.block_shapes()
    assert len(finale.shapes) == 1
    assert step.retraces == 0 and finale.retraces == 0
    step_compiles = step.compiles
    # 5 tiles instead of 3: the per-tile program must NOT retrace
    x2 = rng.integers(0, 1 << 10, size=(16, 460), dtype=np.int64)
    out2 = pod.aggregate(x2, key=jax.random.PRNGKey(2))
    assert (np.asarray(out2) == x2.sum(axis=0) % p).all()
    step = devprof.profile("stream.pod.step")
    assert len(step.shapes) == 1, \
        f"tile-count change retraced the per-tile program: " \
        f"{step.block_shapes()}"
    assert step.compiles == step_compiles and step.retraces == 0
    assert metrics.counter_report("xla.compile.retrace") == {}


def test_streaming_uniform_tail_single_step_shape():
    scheme, p = _scheme()
    agg = StreamingAggregator(scheme, FullMasking(p), participants_chunk=4,
                              dim_chunk=24, uniform_tail=True)
    rng = np.random.default_rng(2)
    x = rng.integers(0, 1 << 10, size=(10, 60), dtype=np.int64)
    out = agg.aggregate(x)
    assert (np.asarray(out).astype(object)
            == x.astype(object).sum(axis=0) % p).all()
    prof = devprof.profile("stream.step")
    assert len(prof.shapes) == 1, prof.block_shapes()
    assert prof.compiles == 1 and prof.retraces == 0


# -- cost analysis / roofline ------------------------------------------------

def test_cost_analysis_feeds_roofline_block():
    devprof.enable_cost_analysis()
    scheme, p = _scheme()
    pod = SimulatedPod(scheme, FullMasking(p))
    rng = np.random.default_rng(3)
    pod.aggregate(rng.integers(0, 99, size=(8, 48), dtype=np.int64))
    block = devprof.roofline(seconds=0.25)
    assert block["flops"] > 0
    assert block["bytes"] > 0
    assert block["arithmetic_intensity"] > 0
    assert 0 < block["utilization"] < 1
    assert block["attainable_flops_per_s"] > 0
    assert block["hbm_peak_bytes"] > 0
    assert "mesh.simpod.round" in block["phases"]
    # peak-HBM watermark gauges land in the metrics registry
    gauges = metrics.gauge_report("device.hbm.")
    assert gauges.get("device.hbm.peak_bytes", 0) > 0
    assert gauges.get("device.hbm.peak_bytes.mesh.simpod.round", 0) > 0


def test_cost_analysis_off_by_default_keeps_single_compile(monkeypatch):
    monkeypatch.delenv("SDA_DEVPROF_COST", raising=False)
    assert not devprof.cost_analysis_enabled()
    scheme, p = _scheme()
    pod = SimulatedPod(scheme, FullMasking(p))
    pod.aggregate(np.ones((8, 48), dtype=np.int64))
    prof = devprof.profile("mesh.simpod.round")
    assert prof.costs == {}, "cost analysis must stay an entry-point opt-in"


def test_roofline_block_math():
    # AI = 10 flops/byte; attainable capped by compute peak; 50% achieved
    block = devprof.roofline_block(
        1000.0, 100.0, seconds=1.0, platform="cpu")
    peaks = block["peaks"]
    attainable = min(peaks["flops_per_s"],
                     10.0 * peaks["hbm_bytes_per_s"])
    assert block["arithmetic_intensity"] == 10.0
    assert block["attainable_flops_per_s"] == attainable
    assert block["utilization"] == pytest.approx(1000.0 / attainable)


def test_reset_all_clears_devprof_state():
    devprof.profile("unit.fn").calls = 5
    metrics.count("xla.compile.retrace")
    obs.reset_all()
    assert devprof.report() == {}
    assert metrics.counter_report("xla.") == {}


def test_wrappers_built_before_reset_keep_reporting():
    # module-level instrumented functions (fields/sharing.py) are wrapped
    # at import, long before any obs.reset_all(); stats from calls AFTER
    # a reset must land in the fresh registry, not an orphaned profile
    import jax.numpy as jnp

    from sda_tpu.fields import sharing

    obs.reset_all()
    sharing.combine(jnp.ones((3, 8), jnp.int64), modulus=97)
    prof = devprof.profile("fields.combine")
    assert prof.calls == 1
    assert "fields.combine" in devprof.report()


def test_eager_function_never_counts_compiles():
    # a non-jit callable wrapped for call counting must not fabricate
    # "compiles"/"retraces" per new argument shape
    eager = devprof.instrument("unit.eager", lambda x: x * 2)
    assert eager(np.ones((2,))) is not None
    assert eager(np.ones((4,))) is not None
    prof = devprof.profile("unit.eager")
    assert prof.calls == 2 and len(prof.shapes) == 2
    assert prof.compiles == 0 and prof.retraces == 0
    assert metrics.counter_report("xla.compile.retrace") == {}


def test_instrument_passes_through_inside_outer_trace():
    import jax
    import jax.numpy as jnp

    inner = devprof.instrument("unit.inner", jax.jit(lambda v: v * 2))

    @jax.jit
    def outer(v):
        return inner(v) + 1

    out = outer(jnp.arange(4))
    assert list(np.asarray(out)) == [1, 3, 5, 7]
    # the traced call must not count as a device dispatch
    assert devprof.profile("unit.inner").calls == 0
    assert devprof.profile("unit.inner").compiles == 0


# -- /statusz ----------------------------------------------------------------

def test_statusz_off_by_default_and_reports_when_enabled():
    srv = SdaHttpServer(new_memory_server(),
                        bind="127.0.0.1:0").start_background()
    try:
        assert requests.get(srv.address + "/statusz").status_code == 404
    finally:
        srv.shutdown()
    srv = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0",
                        statusz_endpoint=True).start_background()
    try:
        requests.get(srv.address + "/v1/ping")
        r = requests.get(srv.address + "/statusz")
        assert r.status_code == 200
        payload = r.json()
        assert payload["uptime_s"] >= 0
        assert payload["store"] == "memory"
        assert "inflight" in payload and "inflight_peak" in payload
        assert payload["lease"]["lease_seconds"] is None
        assert "functions" in payload["devprof"]
        assert "cache" in payload["devprof"]
    finally:
        srv.shutdown()
