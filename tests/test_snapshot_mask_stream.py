"""Streamed snapshot-mask collection (server/snapshot.py +
put_snapshot_mask_chunk across the store backends): pipeline memory must
stay O(batch) while the durable mask and the reveal stay bit-identical —
the tree-scale satellite of the hierarchy PR.
"""

import json

import numpy as np
import pytest

from sda_tpu.protocol import Encryption, SnapshotId
from sda_tpu.server import new_jsonfs_server, new_memory_server, new_sqlite_server


def enc(ix):
    return Encryption.sodium(b"mask-%04d" % ix)


@pytest.fixture(params=["memory", "sqlite", "jsonfs"])
def agg_store(request, tmp_path):
    if request.param == "memory":
        service = new_memory_server()
    elif request.param == "sqlite":
        service = new_sqlite_server(str(tmp_path / "db.sqlite"))
    else:
        service = new_jsonfs_server(str(tmp_path / "jfs"))
    return service.server.aggregation_store


class TestChunkStore:
    def test_chunks_concatenate_in_order(self, agg_store):
        snap = SnapshotId.random()
        agg_store.put_snapshot_mask_chunk(snap, 0, [enc(0), enc(1)])
        agg_store.put_snapshot_mask_chunk(snap, 1, [enc(2)])
        agg_store.put_snapshot_mask_chunk(snap, 2, [enc(3), enc(4)])
        assert agg_store.get_snapshot_mask(snap) == [enc(i) for i in range(5)]

    def test_trim_drops_excess_chunks(self, agg_store):
        """A replay chunked with a LARGER batch (fewer chunks) ends with
        a trim that drops the crashed predecessor's excess chunks."""
        snap = SnapshotId.random()
        for ix in range(4):
            agg_store.put_snapshot_mask_chunk(snap, ix, [enc(100 + ix)])
        agg_store.put_snapshot_mask_chunk(snap, 0, [enc(0)])
        agg_store.put_snapshot_mask_chunk(snap, 1, [enc(1)])
        agg_store.trim_snapshot_mask_chunks(snap, 2)
        assert agg_store.get_snapshot_mask(snap) == [enc(0), enc(1)]

    def test_contended_identical_streams_converge(self, agg_store):
        """Two fleet workers replaying one pipeline write IDENTICAL chunk
        sequences (same frozen set, same batch size); chunk writes are
        pure upserts, so EVERY intermediate interleaving shows a correct
        prefix-or-complete mask and the end state is exact."""
        snap = SnapshotId.random()
        stream = [(0, [enc(0), enc(1)]), (1, [enc(2)]), (2, [enc(3)])]
        # worker A writes 0,1; worker B replays the whole stream; worker
        # A finishes with its identical chunk 2 — and after B's chunk 2
        # landed, no later write can make the mask regress
        agg_store.put_snapshot_mask_chunk(snap, *stream[0])
        agg_store.put_snapshot_mask_chunk(snap, *stream[1])
        for ix, chunk in stream:
            agg_store.put_snapshot_mask_chunk(snap, ix, chunk)
        complete = [enc(i) for i in range(4)]
        assert agg_store.get_snapshot_mask(snap) == complete
        agg_store.put_snapshot_mask_chunk(snap, *stream[2])
        agg_store.trim_snapshot_mask_chunks(snap, 3)
        assert agg_store.get_snapshot_mask(snap) == complete

    def test_create_snapshot_mask_still_whole(self, agg_store):
        """The legacy one-shot API keeps working (chunk 0 underneath)."""
        snap = SnapshotId.random()
        agg_store.create_snapshot_mask(snap, [enc(0), enc(1)])
        assert agg_store.get_snapshot_mask(snap) == [enc(0), enc(1)]
        agg_store.create_snapshot_mask(snap, [enc(9)])
        assert agg_store.get_snapshot_mask(snap) == [enc(9)]

    def test_missing_mask_is_none(self, agg_store):
        assert agg_store.get_snapshot_mask(SnapshotId.random()) is None


class TestLegacyFallback:
    def test_sqlite_reads_pre_chunking_rows(self, tmp_path):
        store = new_sqlite_server(
            str(tmp_path / "db.sqlite")).server.aggregation_store
        snap = SnapshotId.random()
        store._exec(
            "INSERT INTO snapshot_masks (snapshot, doc) VALUES (?, ?)",
            (str(snap), json.dumps([enc(0).to_obj(), enc(1).to_obj()])),
        )
        assert store.get_snapshot_mask(snap) == [enc(0), enc(1)]

    def test_jsonfs_reads_pre_chunking_file(self, tmp_path):
        store = new_jsonfs_server(str(tmp_path / "jfs")).server \
            .aggregation_store
        snap = SnapshotId.random()
        path = store.root / "masks" / f"{snap}.json"
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps([enc(7).to_obj()]))
        assert store.get_snapshot_mask(snap) == [enc(7)]


class TestPipelineBounded:
    """The snapshot pipeline itself: O(batch) chunks, bit-exact reveal."""

    def test_full_round_streams_bounded_chunks(self, monkeypatch):
        from sda_tpu.crypto import sodium

        if not sodium.available():
            pytest.skip("libsodium not present")
        from test_full_loop import agg_default, new_client

        monkeypatch.setenv("SDA_SNAPSHOT_MASK_BATCH", "4")
        service = new_memory_server()
        store = service.server.aggregation_store
        chunks = []
        original = store.put_snapshot_mask_chunk

        def recording(snapshot, index, encryptions):
            chunks.append((index, len(encryptions)))
            return original(snapshot, index, encryptions)

        monkeypatch.setattr(store, "put_snapshot_mask_chunk", recording)

        from sda_tpu.protocol import FullMasking

        aggregation = agg_default().replace(masking_scheme=FullMasking(433))
        recipient = new_client(service)
        recipient_key = recipient.new_encryption_key()
        recipient.upload_agent()
        recipient.upload_encryption_key(recipient_key)
        aggregation = aggregation.replace(
            recipient=recipient.agent.id, recipient_key=recipient_key)
        recipient.upload_aggregation(aggregation)
        clerks = [new_client(service) for _ in range(3)]
        for clerk in clerks:
            clerk.upload_agent()
            clerk.upload_encryption_key(clerk.new_encryption_key())
        recipient.begin_aggregation(aggregation.id)
        for _ in range(10):
            participant = new_client(service)
            participant.upload_agent()
            participant.participate([1, 2, 3, 4], aggregation.id)
        recipient.end_aggregation(aggregation.id)

        # the memory bound: 10 masks through batch 4 -> chunks 4/4/2,
        # never a full-population materialization
        assert chunks == [(0, 4), (1, 4), (2, 2)]
        for clerk in [recipient] + clerks:
            clerk.run_chores(-1)
        output = recipient.reveal_aggregation(aggregation.id)
        np.testing.assert_array_equal(
            output.positive().values, [10, 20, 30, 40])

    def test_replayed_pipeline_converges(self, monkeypatch):
        """Re-running the snapshot pipeline (crash replay / contended
        peer) rewrites the identical chunk stream — the stored mask is
        unchanged."""
        from sda_tpu.crypto import sodium

        if not sodium.available():
            pytest.skip("libsodium not present")
        from test_full_loop import agg_default, new_client

        from sda_tpu.protocol import FullMasking, Snapshot, SnapshotId
        from sda_tpu.server import snapshot as snapshot_mod

        monkeypatch.setenv("SDA_SNAPSHOT_MASK_BATCH", "2")
        service = new_memory_server()
        aggregation = agg_default().replace(masking_scheme=FullMasking(433))
        recipient = new_client(service)
        recipient_key = recipient.new_encryption_key()
        recipient.upload_agent()
        recipient.upload_encryption_key(recipient_key)
        aggregation = aggregation.replace(
            recipient=recipient.agent.id, recipient_key=recipient_key)
        recipient.upload_aggregation(aggregation)
        for _ in range(3):
            clerk = new_client(service)
            clerk.upload_agent()
            clerk.upload_encryption_key(clerk.new_encryption_key())
        recipient.begin_aggregation(aggregation.id)
        for _ in range(5):
            participant = new_client(service)
            participant.upload_agent()
            participant.participate([1, 2, 3, 4], aggregation.id)

        snap = Snapshot(id=SnapshotId.random(), aggregation=aggregation.id)
        assert snapshot_mod.snapshot(service.server, snap) is True
        store = service.server.aggregation_store
        first = store.get_snapshot_mask(snap.id)
        assert len(first) == 5
        # replay: the record exists, the pipeline short-circuits and the
        # mask is untouched
        assert snapshot_mod.snapshot(service.server, snap) is False
        assert store.get_snapshot_mask(snap.id) == first
        # a second worker racing BEFORE the record commit re-runs the
        # collection against the same frozen set: identical chunks
        snapshot_mod._collect_masks_streamed(
            service.server, aggregation, snap)
        assert store.get_snapshot_mask(snap.id) == first
