"""Deterministic chaos layer: failpoint registry semantics, retrying
transport behavior under injected 500s/drops/delays, create-once POST
retry safety, clerking-job lease/reissue across all three durable-capable
backends, and the end-to-end chaos round (ISSUE 1 acceptance).

Everything here is seeded: a failing schedule replays exactly.
"""

import os
import time

import pytest

from sda_tpu import chaos
from sda_tpu.chaos import FailpointRegistry, InjectedFault
from sda_tpu.http import SdaHttpClient, SdaHttpServer
from sda_tpu.protocol import (
    AgentId,
    AggregationId,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    ServerError,
    Snapshot,
    SnapshotId,
)
from sda_tpu.server import new_memory_server
from sda_tpu.utils import metrics

from util import mock_encryption, new_agent, new_full_agent


@pytest.fixture(autouse=True)
def _clean_slate():
    chaos.reset()
    metrics.reset_counters()
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# registry semantics

def test_failpoint_unarmed_is_noop():
    assert chaos.fail("never.configured") is None


def test_failpoint_times_schedule_is_exact():
    chaos.configure("fp.times", error=True, times=2)
    for i in range(5):
        if i < 2:
            with pytest.raises(InjectedFault):
                chaos.fail("fp.times")
        else:
            assert chaos.fail("fp.times") is None
    assert chaos.report()["fp.times"] == {"hits": 5, "triggers": 2}
    assert metrics.counter_report()["chaos.fp.times"] == 2


def test_failpoint_after_and_every():
    chaos.configure("fp.sched", error=True, after=2, every=3)
    outcomes = []
    for _ in range(11):
        try:
            chaos.fail("fp.sched")
            outcomes.append(False)
        except InjectedFault:
            outcomes.append(True)
    # hits 0,1 skipped; then every 3rd starting at hit 2
    assert outcomes == [False, False, True, False, False,
                        True, False, False, True, False, False]


def test_failpoint_rate_is_deterministic_per_seed():
    def schedule(seed):
        registry = FailpointRegistry()
        registry.configure("fp.rate", error=True, rate=0.3, seed=seed)
        out = []
        for _ in range(50):
            try:
                registry.fail("fp.rate")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    assert schedule(7) == schedule(7)  # reproducible
    assert schedule(7) != schedule(8)  # and actually seed-dependent
    assert 0 < sum(schedule(7)) < 50  # neither never nor always


def test_failpoint_custom_exception_and_delay():
    chaos.configure("fp.exc", error=ValueError("boom"))
    with pytest.raises(ValueError, match="boom"):
        chaos.fail("fp.exc")
    chaos.configure("fp.delay", delay=0.05)
    t0 = time.perf_counter()
    assert chaos.fail("fp.delay").kind == "delay"
    assert time.perf_counter() - t0 >= 0.05


def test_evaluate_kinds_filter_does_not_consume():
    """A call site that can only express some kinds ignores other armed
    kinds WITHOUT burning the schedule (counters stay honest)."""
    chaos.configure("fp.kinds", error=True, times=1)
    assert chaos.evaluate("fp.kinds", kinds=("drop",)) is None
    assert chaos.report()["fp.kinds"] == {"hits": 0, "triggers": 0}
    assert "chaos.fp.kinds" not in metrics.counter_report()
    # the single budgeted trigger is still live for a capable site
    assert chaos.evaluate("fp.kinds", kinds=("error",)).kind == "error"


def test_configure_from_spec():
    chaos.configure_from_spec(
        "fp.a=error,times=1;fp.b=drop;fp.c=delay:0.01,rate=0.5", seed=3
    )
    with pytest.raises(InjectedFault):
        chaos.fail("fp.a")
    assert chaos.fail("fp.a") is None
    assert chaos.evaluate("fp.b").kind == "drop"
    with pytest.raises(ValueError):
        chaos.configure_from_spec("fp.bad=explode")


def test_taint_is_a_first_class_kind():
    """`taint` (adversarial share corruption, ISSUE 16) rides the same
    registry discipline as drop/kill: armable directly and via spec,
    expressible-kinds filtered, exactly-one-kind validated."""
    chaos.configure("fp.taint", taint=True, times=1)
    action = chaos.evaluate("fp.taint", kinds=("taint",))
    assert action is not None and action.kind == "taint"
    assert chaos.evaluate("fp.taint", kinds=("taint",)) is None  # budget
    # a site that cannot express taint ignores it without consuming
    chaos.configure("fp.taint2", taint=True)
    assert chaos.evaluate("fp.taint2", kinds=("error", "drop")) is None
    assert chaos.report()["fp.taint2"] == {"hits": 0, "triggers": 0}
    # spec syntax and the exactly-one-kind rule
    chaos.configure_from_spec("fp.taint3=taint,times=2", seed=1)
    assert chaos.evaluate("fp.taint3", kinds=("taint",)).kind == "taint"
    with pytest.raises(ValueError, match="taint"):
        chaos.configure("fp.both", taint=True, error=True)


# ---------------------------------------------------------------------------
# retrying transport

@pytest.fixture
def srv():
    server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0")
    server.start_background()
    yield server
    server.shutdown()


def _fast_client(srv, **kw):
    kw.setdefault("max_retries", 6)
    kw.setdefault("backoff_base", 0.005)
    kw.setdefault("backoff_cap", 0.02)
    return SdaHttpClient(srv.address, token="test-token", **kw)


def test_get_retries_through_injected_500s(srv):
    client = _fast_client(srv)
    chaos.configure("http.server.request", error=True, times=2)
    assert client.ping().running  # 2 failures absorbed, then success
    counters = metrics.counter_report()
    assert counters["chaos.http.server.request"] == 2
    assert counters["http.retry.attempt"] == 2
    assert counters["http.retry.status_5xx"] == 2
    assert counters["http.retry.recovered"] == 1
    assert counters["http.status.500"] == 2


def test_get_retries_through_connection_drops(srv):
    client = _fast_client(srv)
    chaos.configure("http.server.request", drop=True, times=2)
    assert client.ping().running
    counters = metrics.counter_report()
    assert counters["chaos.http.server.request"] == 2
    assert counters["http.retry.connection"] == 2
    assert counters["http.retry.recovered"] == 1


def test_retries_exhaust_to_server_error(srv):
    client = _fast_client(srv, max_retries=2)
    chaos.configure("http.server.request", error=True)  # always
    with pytest.raises(ServerError):
        client.ping()
    counters = metrics.counter_report()
    assert counters["http.retry.attempt"] == 2  # 3 tries, 2 retries
    assert counters["http.retry.exhausted"] == 1
    assert counters["chaos.http.server.request"] == 3
    assert "http.retry.recovered" not in counters


def test_per_operation_deadline_caps_retries(srv):
    # generous retry count but a tiny deadline: the clock must win
    client = _fast_client(srv, max_retries=50, backoff_base=0.05,
                          backoff_cap=0.05, deadline=0.12)
    chaos.configure("http.server.request", error=True)
    t0 = time.perf_counter()
    with pytest.raises(ServerError):
        client.ping()
    assert time.perf_counter() - t0 < 2.0
    assert metrics.counter_report()["http.retry.attempt"] < 50


def test_timeout_configurable_constructor_beats_env(srv, monkeypatch):
    assert SdaHttpClient(srv.address).timeout == 60.0  # historical default
    monkeypatch.setenv("SDA_HTTP_TIMEOUT", "7.5")
    assert SdaHttpClient(srv.address).timeout == 7.5
    assert SdaHttpClient(srv.address, timeout=3.0).timeout == 3.0
    monkeypatch.setenv("SDA_HTTP_TIMEOUT", "not-a-number")
    assert SdaHttpClient(srv.address).timeout == 60.0


def test_post_lost_response_retries_without_duplicate_side_effects(srv):
    """The create-once pillar: the server processes a POST but the response
    is dropped; the client retries; exactly ONE participation exists."""
    from sda_tpu.protocol import (
        AdditiveSharing, Aggregation, EncryptionKeyId, NoMasking,
        Participation, ParticipationId, SodiumEncryption,
    )

    client = _fast_client(srv)
    agent, _ = new_full_agent(client)
    agg = Aggregation(
        id=AggregationId.random(), title="retry", vector_dimension=4,
        modulus=433, recipient=agent.id,
        recipient_key=EncryptionKeyId.random(),
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=8, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    client.create_aggregation(agent, agg)

    participation = Participation(
        id=ParticipationId.random(), participant=agent.id,
        aggregation=agg.id, recipient_encryption=None,
        clerk_encryptions=[],
    )
    # drop exactly the next response AFTER the server has processed it
    chaos.configure("http.server.response", drop=True, times=1)
    client.create_participation(agent, participation)

    counters = metrics.counter_report()
    assert counters["chaos.http.server.response"] == 1
    assert counters["http.retry.connection"] == 1
    assert counters["http.retry.recovered"] == 1
    status = client.get_aggregation_status(agent, agg.id)
    assert status.number_of_participations == 1  # deduped, not doubled


def test_unclassified_post_route_is_rejected(srv):
    client = _fast_client(srv)
    agent = new_agent()
    with pytest.raises(AssertionError, match="not classified retry-safe"):
        client._post(agent, "/v1/definitely/new/route", {})


# ---------------------------------------------------------------------------
# clerking-job lease / reissue (store level, all backends)

def _job(clerk_id, snapshot_id, n):
    return ClerkingJob(
        id=ClerkingJobId(f"00000000-0000-4000-8000-00000000000{n}"),
        clerk=clerk_id,
        aggregation=AggregationId.random(),
        snapshot=snapshot_id,
        encryptions=[mock_encryption(b"x")],
    )


def _jobs_store(kind, tmp_path):
    if kind == "memory":
        from sda_tpu.server.memory import MemoryClerkingJobsStore

        return MemoryClerkingJobsStore()
    if kind == "sqlite":
        from sda_tpu.server.sqlite import SqliteClerkingJobsStore, SqliteDb

        return SqliteClerkingJobsStore(SqliteDb(tmp_path / "lease.db"))
    if kind == "mongo":
        from fake_mongo import FakeDatabase
        from sda_tpu.server.mongo import MongoClerkingJobsStore

        return MongoClerkingJobsStore(FakeDatabase())
    from sda_tpu.server.jsonfs import JsonClerkingJobsStore

    return JsonClerkingJobsStore(tmp_path / "jobs")


@pytest.mark.parametrize("kind", ["memory", "sqlite", "jsonfs", "mongo"])
def test_lease_hides_held_jobs_and_reissues_expired(kind, tmp_path):
    store = _jobs_store(kind, tmp_path)
    clerk = AgentId.random()
    snap = SnapshotId.random()
    job1, job2 = _job(clerk, snap, 1), _job(clerk, snap, 2)
    store.enqueue_clerking_job(job1)
    store.enqueue_clerking_job(job2)

    # first lease pulls job1; a concurrent worker must get job2, not a dup
    got1, exp1 = store.lease_clerking_job(clerk, 30.0, now=1000.0)
    assert got1.id == job1.id and exp1 == 1030.0
    got2, _ = store.lease_clerking_job(clerk, 30.0, now=1001.0)
    assert got2.id == job2.id
    # both held: nothing visible
    assert store.lease_clerking_job(clerk, 30.0, now=1002.0) is None

    # job1's lease expires without a result: REISSUED to the next poller
    before = metrics.counter_report().get("server.job.reissued", 0)
    got3, exp3 = store.lease_clerking_job(clerk, 30.0, now=1031.0)
    assert got3.id == job1.id and exp3 == 1061.0
    assert metrics.counter_report()["server.job.reissued"] == before + 1

    # a completed job never comes back, even after its lease expires
    store.create_clerking_result(
        ClerkingResult(job=job1.id, clerk=clerk, encryption=mock_encryption(b"s"))
    )
    got4, _ = store.lease_clerking_job(clerk, 30.0, now=5000.0)
    assert got4.id == job2.id
    assert store.lease_clerking_job(clerk, 30.0, now=5000.5) is None


@pytest.mark.parametrize("kind", ["memory", "sqlite", "jsonfs", "mongo"])
def test_enqueue_does_not_resurrect_completed_job(kind, tmp_path):
    """Snapshot retries re-enqueue deterministically-id'd jobs; a job whose
    result already landed must stay done."""
    store = _jobs_store(kind, tmp_path)
    clerk = AgentId.random()
    job = _job(clerk, SnapshotId.random(), 3)
    store.enqueue_clerking_job(job)
    store.create_clerking_result(
        ClerkingResult(job=job.id, clerk=clerk, encryption=mock_encryption(b"s"))
    )
    assert store.poll_clerking_job(clerk) is None
    store.enqueue_clerking_job(job)  # the retry
    assert store.poll_clerking_job(clerk) is None
    assert store.lease_clerking_job(clerk, 30.0) is None
    assert store.list_results(job.snapshot) == [job.id]


def test_service_poll_uses_lease_when_enabled():
    service = new_memory_server()
    service.server.clerking_lease_seconds = 30.0
    clerk_agent, _ = new_full_agent(service)
    job = _job(clerk_agent.id, SnapshotId.random(), 4)
    service.server.clerking_job_store.enqueue_clerking_job(job)

    first = service.get_clerking_job(clerk_agent, clerk_agent.id)
    assert first is not None and first.id == job.id
    # held lease: the job is invisible to this clerk's next worker
    assert service.get_clerking_job(clerk_agent, clerk_agent.id) is None
    counters = metrics.counter_report()
    assert counters["server.job.leased"] == 1
    assert counters["server.job.polled"] == 1


def test_snapshot_creation_is_idempotent():
    """A retried snapshot POST (same snapshot id) must not duplicate
    clerking jobs — deterministic job ids + the create-once existence
    check (what makes the snapshot route retry-safe)."""
    from sda_tpu.protocol import (
        AdditiveSharing, Aggregation, Committee, NoMasking,
        Participation, ParticipationId, SodiumEncryption,
    )

    service = new_memory_server()
    recipient, rkey = new_full_agent(service)
    clerk_agents = [new_full_agent(service) for _ in range(2)]
    agg = Aggregation(
        id=AggregationId.random(), title="idem", vector_dimension=2,
        modulus=433, recipient=recipient.id, recipient_key=rkey.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=2, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    service.create_committee(recipient, Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for a, k in clerk_agents],
    ))
    service.create_participation(recipient, Participation(
        id=ParticipationId.random(), participant=recipient.id,
        aggregation=agg.id, recipient_encryption=None,
        clerk_encryptions=[(a.id, mock_encryption(b"c")) for a, _ in clerk_agents],
    ))

    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)
    jobs_first = {
        str(service.get_clerking_job(a, a.id).id) for a, _ in clerk_agents
    }
    service.create_snapshot(recipient, snap)  # the retry
    jobs_second = {
        str(service.get_clerking_job(a, a.id).id) for a, _ in clerk_agents
    }
    assert jobs_first == jobs_second
    counters = metrics.counter_report()
    assert counters["server.snapshot.created"] == 1
    assert counters["server.snapshot.duplicate"] == 1
    # per-clerk queue depth is still exactly one job
    for a, _ in clerk_agents:
        store = service.server.clerking_job_store
        assert len(store._queues[a.id]) == 1

    # crash-replay flavor: the snapshot RECORD is lost (it commits last)
    # but the frozen set survives; a late participation arrives (from a
    # FRESH device — exactly-once ingestion forbids a second bundle from
    # the same agent); the replay must re-use the ORIGINAL frozen set,
    # not re-freeze with the newcomer (mixing share generations across
    # clerk columns)
    agg_store = service.server.aggregation_store
    del agg_store._snapshots[agg.id][snap.id]  # simulate the crash point
    late_agent, _ = new_full_agent(service)
    service.create_participation(late_agent, Participation(
        id=ParticipationId.random(), participant=late_agent.id,
        aggregation=agg.id, recipient_encryption=None,
        clerk_encryptions=[(a.id, mock_encryption(b"late")) for a, _ in clerk_agents],
    ))
    service.create_snapshot(recipient, snap)  # the replay
    assert agg_store.count_participations_snapshot(agg.id, snap.id) == 1


@pytest.mark.parametrize("kind", ["memory", "sqlite", "jsonfs", "mongo"])
def test_frozen_empty_set_reads_as_frozen(kind, tmp_path):
    """has_snapshot_freeze must distinguish frozen-EMPTY from unfrozen —
    otherwise a crash-replay after an empty freeze would re-freeze a
    late participation into the set."""
    if kind == "memory":
        from sda_tpu.server.memory import MemoryAggregationsStore

        store = MemoryAggregationsStore()
    elif kind == "sqlite":
        from sda_tpu.server.sqlite import SqliteAggregationsStore, SqliteDb

        store = SqliteAggregationsStore(SqliteDb(tmp_path / "f.db"))
    elif kind == "mongo":
        from fake_mongo import FakeDatabase
        from sda_tpu.server.mongo import MongoAggregationsStore

        store = MongoAggregationsStore(FakeDatabase())
    else:
        from sda_tpu.server.jsonfs import JsonAggregationsStore

        store = JsonAggregationsStore(tmp_path / "agg")
    agg, snap = AggregationId.random(), SnapshotId.random()
    assert not store.has_snapshot_freeze(agg, snap)
    store.snapshot_participations(agg, snap)  # zero participations exist
    assert store.has_snapshot_freeze(agg, snap)
    assert store.count_participations_snapshot(agg, snap) == 0


# ---------------------------------------------------------------------------
# shutdown leak detection (satellite)

def test_shutdown_leak_detection(monkeypatch):
    server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0")
    server.start_background()
    # make the worker thread look wedged: join "times out", thread "alive"
    monkeypatch.setattr(server._thread, "join", lambda timeout=None: None)
    monkeypatch.setattr(server._thread, "is_alive", lambda: True)
    server.shutdown()
    assert metrics.counter_report()["http.shutdown.leaked"] == 1


# ---------------------------------------------------------------------------
# end-to-end: the acceptance round (ISSUE 1)

@pytest.mark.chaos
def test_chaos_round_completes_bit_exactly():
    """Full aggregation round over HTTP with >=10% injected request
    failures and one clerk abandoning a pulled job: lease reissue +
    retrying transport must still land the bit-exact sum."""
    from sda_tpu.chaos.drill import run_chaos_drill
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")

    seed = int(os.environ.get("SDA_CHAOS_SEED", "20260803"))
    report = run_chaos_drill(participants=5, dim=4, rate=0.2, seed=seed,
                             lease_seconds=0.5)
    assert report["ready"], report
    assert report["exact"], report
    assert report["injected_ratio"] >= 0.10, report
    counters = report["counters"]
    assert counters["chaos.clerk.abandon_job"] == 1
    assert counters["server.job.reissued"] >= 1
    assert counters["chaos.http.server.request"] > 0
    assert counters["http.retry.attempt"] > 0
    assert counters["http.retry.recovered"] > 0


@pytest.mark.chaos
def test_chaos_round_schedule_is_reproducible():
    """Same seed -> same injection schedule (trigger counts match across
    runs; hit counts may differ slightly with thread timing)."""
    from sda_tpu.chaos.drill import run_chaos_drill
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")

    a = run_chaos_drill(participants=3, dim=2, rate=0.2, seed=11,
                        lease_seconds=0.4)
    b = run_chaos_drill(participants=3, dim=2, rate=0.2, seed=11,
                        lease_seconds=0.4)
    assert a["exact"] and b["exact"]
    for name in ("clerk.abandon_job", "http.server.response",
                 "store.create_participation"):
        assert a["failpoints"][name]["triggers"] == b["failpoints"][name]["triggers"]
