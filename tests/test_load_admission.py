"""Load & admission-control subsystem: histograms under contention, the
token-bucket/in-flight shedding path, Retry-After honoring in the client,
the /metrics exposition endpoint, and the loadgen smoke drill.

Companion to tests/test_observability.py (counters/phases) and
tests/test_chaos.py (fault injection): this file covers the capacity
plane added with sda_tpu/loadgen — see docs/load.md.
"""

import threading
import time

import pytest
import requests

from sda_tpu import chaos
from sda_tpu.crypto import sodium
from sda_tpu.http import SdaHttpClient, SdaHttpServer
from sda_tpu.http.admission import AdmissionControl, TokenBucket
from sda_tpu.http.server import route_label
from sda_tpu.protocol import ServerError
from sda_tpu.server import new_memory_server
from sda_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _fresh_metrics():
    metrics.reset_all()
    yield
    metrics.reset_all()


# -- metrics: histograms, gauges, contention --------------------------------

def test_histogram_report_percentiles_ordered_and_bounded():
    for ms in range(1, 1001):  # 1ms .. 1s uniform
        metrics.observe("unit.lat", ms / 1e3)
    s = metrics.histogram_report("unit.")["unit.lat"]
    assert s["count"] == 1000
    assert abs(s["sum"] - sum(range(1, 1001)) / 1e3) < 1e-6
    assert s["min"] == 1e-3 and s["max"] == 1.0
    assert s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
    # log-bucketed quantiles overestimate by at most one bucket (~19%)
    assert 0.5 <= s["p50"] <= 0.5 * 1.2
    assert 0.99 <= s["p99"] <= 0.99 * 1.2


def test_histogram_tiny_and_huge_values_do_not_blow_up():
    metrics.observe("unit.wide", 0.0)
    metrics.observe("unit.wide", 1e-9)
    metrics.observe("unit.wide", 3600.0)
    s = metrics.histogram_report()["unit.wide"]
    assert s["count"] == 3
    assert s["max"] == 3600.0
    assert s["p99"] <= 3600.0 * 1.2


def test_multithreaded_count_and_observe_totals_are_exact():
    """The satellite contract: totals under contention are EXACT — the
    registry takes a real lock, not a racy read-modify-write."""
    threads, per_thread = 8, 2000

    def hammer():
        for i in range(per_thread):
            metrics.count("unit.contended")
            metrics.observe("unit.contended.lat", (i % 100 + 1) / 1e4)

    workers = [threading.Thread(target=hammer) for _ in range(threads)]
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    assert metrics.counter_report()["unit.contended"] == threads * per_thread
    hist = metrics.histogram_report()["unit.contended.lat"]
    assert hist["count"] == threads * per_thread
    expected_sum = threads * sum((i % 100 + 1) / 1e4 for i in range(per_thread))
    assert abs(hist["sum"] - expected_sum) < 1e-6


def test_gauges_set_and_max():
    metrics.gauge_set("unit.depth", 3)
    metrics.gauge_set("unit.depth", 1)
    metrics.gauge_max("unit.peak", 5)
    metrics.gauge_max("unit.peak", 2)
    assert metrics.gauge_report("unit.") == {"unit.depth": 1, "unit.peak": 5}


def test_prometheus_text_exposition_format():
    metrics.count("unit.requests", 3)
    metrics.gauge_set("unit.depth", 2)
    metrics.observe("unit.lat", 0.005)
    text = metrics.prometheus_text()
    assert 'sda_events_total{name="unit.requests"} 3' in text
    assert 'sda_gauge{name="unit.depth"} 2' in text
    assert 'sda_histogram_bucket{name="unit.lat",le="+Inf"} 1' in text
    assert 'sda_histogram_count{name="unit.lat"} 1' in text
    # cumulative bucket for a 5ms observation exists with a finite bound
    assert 'sda_histogram_bucket{name="unit.lat",le="0.005' in text


# -- admission primitives ---------------------------------------------------

def test_token_bucket_refill_schedule():
    b = TokenBucket(rate=10.0, burst=2.0, now=100.0)
    assert b.try_take(100.0) == 0.0
    assert b.try_take(100.0) == 0.0
    wait = b.try_take(100.0)  # empty: exactly one token away
    assert wait == pytest.approx(0.1)
    assert b.try_take(100.0 + wait) == 0.0  # honoring the hint succeeds
    assert b.try_take(1000.0) == 0.0  # long idle refills to burst, not more
    assert b.try_take(1000.0) == 0.0
    assert b.try_take(1000.0) > 0.0


def test_admission_control_inflight_and_release():
    ac = AdmissionControl(max_inflight=2)
    assert ac.admit("a") is None
    assert ac.admit("b") is None
    shed = ac.admit("c")
    assert shed is not None and shed.status == 503 and shed.retry_after > 0
    ac.release()
    assert ac.admit("c") is None
    assert metrics.counter_report()["http.throttled.inflight"] == 1
    assert metrics.gauge_report()["http.inflight.peak"] == 2


def test_token_bucket_clamps_sub_token_burst():
    # burst < 1 could never admit yet would promise finite Retry-After
    # hints forever — the clamp keeps the config meaningful
    b = TokenBucket(rate=10.0, burst=0.5, now=0.0)
    assert b.try_take(0.0) == 0.0
    assert b.try_take(0.0) > 0.0


def test_sheds_do_not_pollute_route_latency_histograms():
    srv = _server(rate_limit=5.0, rate_burst=1)
    try:
        codes = [requests.get(srv.address + "/v1/ping").status_code
                 for _ in range(4)]
        assert codes.count(429) == 3
    finally:
        srv.shutdown()
    report = metrics.histogram_report("http.latency.")
    assert report["http.latency.GET:/v1/ping"]["count"] == 1  # served only
    assert report["http.latency.shed"]["count"] == 3


def test_admission_zero_rate_blocks_without_crashing():
    ac = AdmissionControl(rate=0.0)
    shed = ac.admit("a")
    assert shed is not None and shed.status == 429 and shed.retry_after > 0


def test_inflight_shed_does_not_burn_the_rate_token():
    ac = AdmissionControl(max_inflight=1, rate=10.0, burst=2.0)
    assert ac.admit("a") is None          # one token spent, slot taken
    shed = ac.admit("a")
    assert shed is not None and shed.status == 503  # concurrency, not rate
    ac.release()
    # the 503 must not have cost a token: the second token is still there
    assert ac.admit("a") is None


def test_route_templates_cover_every_dispatched_route():
    """Drift tripwire: ROUTE_TEMPLATES is maintained next to the shared
    dispatch table (http/base.py, serving BOTH planes) — a route added to
    dispatch without a template would silently fold its latency into the
    'unmatched' bucket."""
    import inspect
    import re as _re

    from sda_tpu.http import base as base_mod

    src = inspect.getsource(base_mod._dispatch_inner)
    routes = set(_re.findall(r'path == "([^"]+)"', src))
    routes |= {
        pattern.replace("({_ID})", "{id}")
        for pattern in _re.findall(r'm\(rf"([^"]+)"\)', src)
    }
    assert len(routes) >= 15, "dispatch-table parse went stale"
    missing = routes - base_mod.ROUTE_TEMPLATES
    assert not missing, f"routes without a latency template: {missing}"


def test_route_label_collapses_ids_and_bounds_cardinality():
    uid = "3f2a0000-0000-4000-8000-00000000abcd"
    assert route_label("GET", f"/v1/agents/{uid}") == "GET:/v1/agents/{id}"
    assert (route_label("GET", f"/v1/aggregations/{uid}/snapshots/{uid}/result")
            == "GET:/v1/aggregations/{id}/snapshots/{id}/result")
    assert route_label("GET", "/v1/ping") == "GET:/v1/ping"
    assert route_label("GET", "/../../etc/passwd") == "GET:unmatched"
    assert route_label("POST", "/v1/agents/not-an-id") == "POST:unmatched"


# -- server-side shedding over real HTTP ------------------------------------

def _server(**kwargs) -> SdaHttpServer:
    return SdaHttpServer(
        new_memory_server(), bind="127.0.0.1:0", **kwargs
    ).start_background()


def test_rate_limit_sheds_429_with_retry_after_before_store_work():
    srv = _server(rate_limit=5.0, rate_burst=2)
    try:
        codes = [requests.get(srv.address + "/v1/ping").status_code
                 for _ in range(5)]
        assert codes[:2] == [200, 200]
        assert 429 in codes
        shed = requests.get(srv.address + "/v1/ping")
        assert shed.status_code == 429
        assert float(shed.headers["Retry-After"]) > 0.0
        counters = metrics.counter_report()
        assert counters["http.throttled.rate"] >= 3
        # the shed happened BEFORE any service/store work: no server.*
        # counters moved for throttled hits, only http ones
        assert metrics.counter_report("server.") == {}
    finally:
        srv.shutdown()


def test_rate_limit_is_per_agent():
    srv = _server(rate_limit=5.0, rate_burst=1)
    try:
        # distinct agent ids (valid uuids — garbled usernames fall back to
        # the per-address bucket) get distinct buckets: nobody sheds
        agents = [f"00000000-0000-4000-8000-00000000000{i}" for i in range(3)]
        for agent in agents:
            r = requests.get(srv.address + "/v1/ping", auth=(agent, "t"))
            assert r.status_code == 200, agent
        # the same agent again inside the refill window does shed
        r = requests.get(srv.address + "/v1/ping", auth=(agents[0], "t"))
        assert r.status_code == 429
    finally:
        srv.shutdown()


@pytest.mark.chaos
def test_inflight_cap_sheds_503_while_handler_is_busy():
    chaos.reset()
    srv = _server(max_inflight=1)
    try:
        # park one request inside the handler via an injected delay, then
        # probe from a second connection: the cap must shed it with 503
        chaos.configure("http.server.request", delay=0.6, times=1)
        slow = threading.Thread(
            target=lambda: requests.get(srv.address + "/v1/ping")
        )
        slow.start()
        time.sleep(0.2)  # let the slow request take its in-flight slot
        probe = requests.get(srv.address + "/v1/ping")
        slow.join()
        assert probe.status_code == 503
        assert float(probe.headers["Retry-After"]) > 0.0
        assert metrics.counter_report()["http.throttled.inflight"] >= 1
    finally:
        chaos.reset()
        srv.shutdown()


def test_latency_histograms_per_route():
    srv = _server()
    try:
        requests.get(srv.address + "/v1/ping")
        requests.get(srv.address + "/v1/ping")
        requests.get(srv.address + "/v1/definitely-not-a-route")
    finally:
        srv.shutdown()
    report = metrics.histogram_report("http.latency.")
    assert report["http.latency.GET:/v1/ping"]["count"] == 2
    assert report["http.latency.GET:unmatched"]["count"] == 1
    assert report["http.latency.GET:/v1/ping"]["p99"] > 0.0


def test_metrics_endpoint_off_by_default_on_when_enabled():
    srv = _server()
    try:
        assert requests.get(srv.address + "/metrics").status_code == 404
    finally:
        srv.shutdown()
    srv = _server(metrics_endpoint=True)
    try:
        requests.get(srv.address + "/v1/ping")
        r = requests.get(srv.address + "/metrics")
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        assert 'sda_events_total{name="http.request"}' in r.text
        assert 'sda_histogram_bucket{name="http.latency.GET:/v1/ping"' in r.text
    finally:
        srv.shutdown()


# -- client honors Retry-After ----------------------------------------------

def test_client_honors_retry_after_and_converges():
    srv = _server(rate_limit=10.0, rate_burst=1)
    try:
        with SdaHttpClient(srv.address, token="t", max_retries=8,
                           backoff_base=0.01, backoff_cap=0.05) as client:
            for _ in range(4):
                client.ping()  # throttled pings must converge via the hint
        counters = metrics.counter_report()
        assert counters["http.retry.after_hint"] >= 2
        assert counters["http.retry.status_429"] >= 2
        assert counters["http.retry.after_hint"] == counters["http.retry.status_429"]
        assert "http.status.500" not in counters
    finally:
        srv.shutdown()


def test_client_caps_retry_after_at_the_op_deadline():
    srv = _server(rate_limit=0.01, rate_burst=1)  # next token: ~100s away
    try:
        client = SdaHttpClient(srv.address, token="t", max_retries=8,
                               backoff_base=0.01, deadline=0.5)
        client.ping()  # burst token
        t0 = time.monotonic()
        with pytest.raises(ServerError, match="429"):
            client.ping()
        # a naive implementation would sleep the full 100s hint; the
        # deadline must cap it
        assert time.monotonic() - t0 < 5.0
        assert metrics.counter_report()["http.retry.exhausted"] == 1
        client.close()
    finally:
        srv.shutdown()


# -- loadgen smoke (tier-1: tiny N, deterministic seed) ---------------------

@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_loadgen_closed_loop_smoke():
    from sda_tpu.loadgen import LoadProfile, run_load

    report = run_load(LoadProfile(
        participants=6, dim=4, arrivals="closed", concurrency=3, seed=0,
        timeout_s=60,
    ))
    assert report["completed"] == 6
    assert report["client_failures"] == 0
    assert report["ready"] and report["exact"], report
    assert report["errors_5xx"] == 0
    assert report["admitted_participations"] == 6
    # non-empty per-route histogram report with sane tails
    lat = report["latency_ms"]
    assert lat, "empty latency report"
    post = lat["POST:/v1/aggregations/participations"]
    assert post["count"] == 6
    assert 0 < post["p50_ms"] <= post["p99_ms"] <= post["max_ms"]
    assert set(report["phases_ms"]) == {"register", "participate"}


@pytest.mark.skipif(not sodium.available(), reason="libsodium not present")
def test_loadgen_overload_sheds_429_and_still_exact():
    """The acceptance property at smoke scale: under a forced overload
    profile the server sheds with 429/Retry-After — zero 5xx, zero lost
    participations among admitted requests — and clients converge."""
    from sda_tpu.loadgen import LoadProfile, run_load

    report = run_load(LoadProfile(
        participants=5, dim=4, arrivals="open", target_rps=100.0,
        concurrency=3, seed=1, rate_limit=15.0, rate_burst=2, timeout_s=90,
    ))
    assert report["shed_429"] > 0, report
    assert report["errors_5xx"] == 0
    assert report["client_failures"] == 0
    assert report["ready"] and report["exact"], report
    assert report["retries"]["http.retry.after_hint"] > 0
    assert report["throttled"]["http.throttled.rate"] == report["shed_429"]
    assert report["admitted_participations"] == 5
