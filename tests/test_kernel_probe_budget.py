"""The kernel probe's component-budget algebra (pure host math).

The on-chip probe times four kernel variants and solves for the
fold/PRNG/matmul/overhead components; the solve must invert the
generative model exactly, or a scarce hardware window publishes a wrong
attribution (the round-4 review caught a sign error in an earlier
formulation — this pins the fixed one).
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

from kernel_probe import solve_budget  # noqa: E402


def _timings(O, F, R, M):
    return {
        "fold_only": O + F,
        "prng_only": O + R,
        "no_matmul": O + F + R,
        "full": O + F + R + M,
    }


@pytest.mark.parametrize("O,F,R,M", [
    (0.002, 0.010, 0.006, 0.001),
    (0.0, 0.5, 0.25, 0.125),
    (0.01, 0.0, 0.0, 0.0),     # pure overhead
    (0.0005, 0.03, 0.001, 0.02),
])
def test_solve_inverts_generative_model(O, F, R, M):
    # each component asserted against its independently-known generative
    # value — the only form that catches a sign/term error (a components-
    # sum-to-full check telescopes to a tautology for ANY overhead formula)
    got = solve_budget(_timings(O, F, R, M))
    assert got["overhead_s"] == pytest.approx(O)
    assert got["fold_s"] == pytest.approx(F)
    assert got["prng_s"] == pytest.approx(R)
    assert got["matmul_s"] == pytest.approx(M)
