"""Tier-3: the CLI walkthrough — the reference's shell example end-to-end.

Mirrors docs/simple-cli-example.sh: one `sdad` server, a recipient + three
clerks with keys, three keyless participants, additive 3-way sharing of
10-dim mod-433 vectors, expected reveal ``0 2 2 4 4 6 6 8 8 10``.
Runs the real argparse CLI against a live HTTP server.
"""

import pytest

from sda_tpu.crypto import sodium
from sda_tpu.http import SdaHttpServer
from sda_tpu.server import new_jsonfs_server

from sda_tpu.cli.main import main as sda_main

pytestmark = pytest.mark.skipif(not sodium.available(), reason="libsodium not present")


@pytest.fixture
def httpd(tmp_path):
    server = SdaHttpServer(new_jsonfs_server(tmp_path / "server"), bind="127.0.0.1:0")
    server.start_background()
    yield server
    server.shutdown()


def test_simple_cli_walkthrough(httpd, tmp_path, capsys):
    url = httpd.address

    def sda(identity, *args):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity), *args])
        assert rc == 0
        return capsys.readouterr().out.strip()

    # recipient + three clerks, all with encryption keys
    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "agent", "create")
        sda(who, "agent", "keys", "create")

    # participants don't need encryption keys
    for who in ("part-1", "part-2", "part-3"):
        sda(who, "agent", "create")

    assert sda("recipient", "ping") == '{"running": true}'

    agg_id = sda(
        "recipient", "aggregations", "create", "aggro",
        "--dimension", "10", "--modulus", "433", "--shares", "3",
    )
    sda("recipient", "aggregations", "begin", agg_id)

    sda("part-1", "participate", agg_id, "0", "1", "2", "3", "4", "5", "6", "7", "8", "9")
    sda("part-2", "participate", agg_id, "0", "0", "0", "0", "0", "0", "0", "0", "0", "0")
    sda("part-3", "participate", agg_id, "0", "1", "0", "1", "0", "1", "0", "1", "0", "1")

    sda("recipient", "aggregations", "end", agg_id)

    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "clerk", "--once")

    # the reference walkthrough's expected final reveal (README.md)
    assert sda("recipient", "aggregations", "reveal", agg_id) == "0 2 2 4 4 6 6 8 8 10"

    listed = sda("recipient", "aggregations", "list")
    assert agg_id in listed


def test_cli_journal_participate_and_resume(httpd, tmp_path, capsys):
    """`participate --journal` + `sda resume`: a journaled upload reaps
    its entry; a journal entry left by a 'crash' resumes to the SAME
    bytes (deduped server-side), and the round reveals exactly."""
    url = httpd.address

    def sda(identity, *args, rc_expected=0):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity),
                       *args])
        assert rc == rc_expected
        return capsys.readouterr().out.strip()

    sda("recipient", "agent", "create")
    sda("recipient", "agent", "keys", "create")
    for who in ("clerk-1", "clerk-2", "clerk-3"):
        sda(who, "agent", "create")
        sda(who, "agent", "keys", "create")
    agg_id = sda(
        "recipient", "aggregations", "create", "journaled",
        "--dimension", "4", "--modulus", "433", "--shares", "3",
    )
    sda("recipient", "aggregations", "begin", agg_id)

    # the happy path: journal written before the upload, reaped after
    sda("part-1", "participate", agg_id, "1", "2", "3", "4", "--journal")
    journal_dir = tmp_path / "agent" / "part-1" / "journal"
    assert list(journal_dir.glob("*.json")) == []  # reaped on confirm
    assert sda("part-1", "resume") == \
        "nothing journaled; all participations confirmed"

    # the crash path: seal + journal WITHOUT uploading (a device that
    # died mid-participate), then `sda resume` re-uploads the same bytes
    from sda_tpu.client import SdaClient
    from sda_tpu.client.journal import ParticipationJournal
    from sda_tpu.cli.main import load_client
    from sda_tpu.protocol import AggregationId

    class _Args:
        identity = str(tmp_path / "agent" / "part-2")
        server = url

    crashed = load_client(_Args)
    crashed.upload_agent()
    sealed = crashed.new_participation([4, 3, 2, 1],
                                       AggregationId(agg_id))
    ParticipationJournal(tmp_path / "agent" / "part-2"
                         / "journal").record(sealed)
    out = sda("part-2", "resume")
    assert out == "resumed 1 of 1 journaled participation(s); 0 still pending"

    sda("recipient", "aggregations", "end", agg_id)
    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "clerk", "--once")
    assert sda("recipient", "aggregations", "reveal", agg_id) == "5 5 5 5"


def test_cli_shamir_aggregation(httpd, tmp_path, capsys):
    url = httpd.address

    def sda(identity, *args):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity), *args])
        assert rc == 0
        return capsys.readouterr().out.strip()

    sda("recipient", "agent", "create")
    sda("recipient", "agent", "keys", "create")
    for i in range(8):
        sda(f"clerk-{i}", "agent", "create")
        sda(f"clerk-{i}", "agent", "keys", "create")
    agg_id = sda(
        "recipient", "aggregations", "create", "shamir-run",
        "--dimension", "4", "--modulus", "433",
        "--sharing", "shamir", "--shares", "8", "--mask", "chacha",
    )
    sda("recipient", "aggregations", "begin", agg_id)
    sda("p", "participate", agg_id, "1", "2", "3", "4")
    sda("q", "participate", agg_id, "1", "2", "3", "4")
    sda("recipient", "aggregations", "end", agg_id)
    for i in range(8):
        sda(f"clerk-{i}", "clerk", "--once")
    sda("recipient", "clerk", "--once")
    assert sda("recipient", "aggregations", "reveal", agg_id) == "2 4 6 8"


def test_cli_paillier_aggregation(httpd, tmp_path, capsys):
    """--encryption paillier: homomorphic-capable encryption in both slots,
    Paillier keys via `keys create --encryption paillier` (512-bit keys to
    keep the test fast; default is 2048)."""
    url = httpd.address

    def sda(identity, *args):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity), *args])
        assert rc == 0
        return capsys.readouterr().out.strip()

    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "agent", "create")
        sda(who, "agent", "keys", "create",
            "--encryption", "paillier", "--paillier-modulus-bits", "512")

    agg_id = sda(
        "recipient", "aggregations", "create", "paillier-run",
        "--dimension", "4", "--modulus", "433", "--shares", "3",
        "--mask", "full", "--encryption", "paillier",
        "--paillier-modulus-bits", "512",
    )
    sda("recipient", "aggregations", "begin", agg_id)
    sda("p", "participate", agg_id, "1", "2", "3", "4")
    sda("q", "participate", agg_id, "10", "20", "30", "40")
    sda("recipient", "aggregations", "end", agg_id)
    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "clerk", "--once")
    assert sda("recipient", "aggregations", "reveal", agg_id) == "11 22 33 44"


def test_cli_paillier_errors_are_friendly(httpd, tmp_path, capsys):
    """Misconfigured Paillier options exit 1 with an actionable message,
    never a traceback (round-2 advisor findings)."""
    url = httpd.address

    def sda(identity, *args):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity), *args])
        out = capsys.readouterr()
        return rc, out.out.strip(), out.err

    # keys create with a modulus too small for even one window: friendly error
    rc, _, _ = sda("tiny", "agent", "create")
    assert rc == 0
    rc, _, err = sda("tiny", "agent", "keys", "create",
                     "--encryption", "paillier", "--paillier-modulus-bits", "32")
    assert rc == 1
    assert "error:" in err and "--paillier-modulus-bits" in err

    # aggregations create --encryption paillier over a Sodium primary key:
    # caught at create time with a pointer to the fix, not at participation
    rc, _, _ = sda("mismatched", "agent", "create")
    assert rc == 0
    rc, _, _ = sda("mismatched", "agent", "keys", "create")  # Sodium key
    assert rc == 0
    rc, _, err = sda(
        "mismatched", "aggregations", "create", "bad-run",
        "--dimension", "4", "--modulus", "433", "--shares", "3",
        "--encryption", "paillier", "--paillier-modulus-bits", "512",
    )
    assert rc == 1
    assert "Sodium" in err and "keys create --encryption paillier" in err


def test_sim_cli_clerk_dropout(capsys, monkeypatch):
    """`sda-sim --drop-clerks`: the finale reveals exactly from the
    surviving quorum; below-quorum drops fail fast with a clear error."""
    import json

    from sda_tpu.cli import sim

    # skip the TPU probe: conftest already pinned the CPU backend
    monkeypatch.setenv("SDA_SIM_PLATFORM", "cpu")

    rc = sim.main([
        "--participants", "8", "--dim", "99", "--clerks", "8",
        "--drop-clerks", "6", "--verify",
    ])
    assert rc == 0
    result = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert result["exact"] is True and result["dropped_clerks"] == [6]

    rc = sim.main([
        "--participants", "8", "--dim", "99", "--clerks", "8",
        "--drop-clerks", "0,1,2,3,4",
    ])
    assert rc == 1
    assert "below the reconstruction threshold" in capsys.readouterr().err


def test_sim_cli_multihost(tmp_path, capsys):
    """`sda-sim --multihost 2` spawns two real worker processes over gRPC
    collectives and prints exactly one JSON result line (worker chatter
    filtered), exact against the distributed plain sum."""
    import json

    from sda_tpu.cli import sim

    rc = sim.main([
        "--participants", "8", "--dim", "24", "--clerks", "8",
        "--multihost", "2", "--devices-per-process", "4", "--verify",
    ])
    assert rc == 0
    out_lines = capsys.readouterr().out.strip().splitlines()
    assert len(out_lines) == 1
    result = json.loads(out_lines[0])
    assert result["mode"].startswith("multihost x2")
    assert result["exact"] is True

    # invalid combination is rejected before any process spawns
    rc = sim.main([
        "--participants", "8", "--dim", "24", "--clerks", "8",
        "--multihost", "3",
    ])
    assert rc == 1


def test_cli_model_participation_fixed_point(httpd, tmp_path, capsys):
    """`participate --model file.npy` + `reveal --fixed-point-bits --mean`:
    the secure mean of float model vectors through the real CLI equals the
    plaintext quantized oracle exactly."""
    import numpy as np

    from sda_tpu.models import FixedPointCodec

    url = httpd.address
    m31 = (1 << 31) - 1

    def sda(identity, *args):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity),
                       *args])
        assert rc == 0
        return capsys.readouterr().out.strip()

    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "agent", "create")
        sda(who, "agent", "keys", "create")
    agg_id = sda(
        "recipient", "aggregations", "create", "fedavg",
        "--dimension", "6", "--modulus", str(m31), "--shares", "3",
    )
    sda("recipient", "aggregations", "begin", agg_id)

    rng = np.random.default_rng(0)
    vecs = rng.normal(0, 1, size=(2, 6))
    for i, vec in enumerate(vecs):
        path = tmp_path / f"update{i}.npy"
        np.save(path, vec)
        # NO prior `agent create`: --model as a fresh identity's first
        # command must self-register before its service reads
        sda(f"part-{i}", "participate", agg_id, "--model", str(path),
            "--clip", "4.0")

    sda("recipient", "aggregations", "end", agg_id)

    # a straggler arriving AFTER the snapshot froze the set: counted by
    # the aggregation status but not in the revealed sum — the decoded
    # mean must divide by the snapshot's 2, not the status's 3
    late = tmp_path / "late.npy"
    np.save(late, rng.normal(0, 1, size=6))
    sda("part-late", "participate", agg_id, "--model", str(late),
        "--clip", "4.0")

    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "clerk", "--once")

    out = sda("recipient", "aggregations", "reveal", agg_id,
              "--fixed-point-bits", "16", "--mean")
    got = np.array([float(v) for v in out.split()])
    codec = FixedPointCodec(m31, 16, 1024, clip=4.0)
    oracle = np.stack([codec.quantize(v) for v in vecs]).sum(0) \
        / codec.scale / 2
    np.testing.assert_array_equal(got, oracle)

    # --mean without --fixed-point-bits is a usage error, not raw ints
    rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / "recipient"),
                   "aggregations", "reveal", agg_id, "--mean"])
    assert rc == 1
    assert "--fixed-point-bits" in capsys.readouterr().err

    # guard rails: both values and --model, and a wrong-dimension model
    rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / "part-0"),
                   "participate", agg_id, "1", "2",
                   "--model", str(tmp_path / "update0.npy")])
    assert rc == 1
    assert "not both" in capsys.readouterr().err
    bad = tmp_path / "bad.npy"
    np.save(bad, np.zeros(5))
    rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / "part-0"),
                   "participate", agg_id, "--model", str(bad)])
    assert rc == 1
    assert "6" in capsys.readouterr().err


def test_cli_profile_and_chosen_committee(httpd, tmp_path, capsys):
    """`agent profile set/show` and `aggregations begin --clerk ...` — the
    reference README's 'Doing more' aspirations (external-trust profiles,
    recipient-chosen committees) at the CLI surface."""
    import json as _json

    url = httpd.address

    def sda(identity, *args, rc=0):
        got = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity),
                        *args])
        assert got == rc, capsys.readouterr()
        return capsys.readouterr()

    sda("recipient", "agent", "create")
    sda("recipient", "agent", "keys", "create")

    # profile publish + public read-back through REST
    sda("clerk-0", "agent", "create")
    sda("clerk-0", "agent", "profile", "set", "--name", "Clerk Zero",
        "--keybase", "clerk0", "--website", "https://clerk0.example")
    own = _json.loads(sda("clerk-0", "agent", "profile", "show").out)
    assert own["name"] == "Clerk Zero" and own["keybase_id"] == "clerk0"
    clerk0_id = _json.loads(sda("clerk-0", "agent", "show").out)["id"]
    seen = _json.loads(
        sda("recipient", "agent", "profile", "show", clerk0_id).out)
    assert seen["website"] == "https://clerk0.example"

    # recipient-chosen committee: exact clerks, in the chosen order
    clerk_ids = [clerk0_id]
    sda("clerk-0", "agent", "keys", "create")
    for i in range(1, 4):
        sda(f"clerk-{i}", "agent", "create")
        sda(f"clerk-{i}", "agent", "keys", "create")
        clerk_ids.append(
            _json.loads(sda(f"clerk-{i}", "agent", "show").out)["id"])

    agg_id = sda("recipient", "aggregations", "create", "chosen",
                 "--dimension", "4", "--modulus", "433",
                 "--shares", "3").out.strip()
    chosen = [clerk_ids[2], clerk_ids[0], clerk_ids[3]]
    sda("recipient", "aggregations", "begin", agg_id,
        "--clerk", chosen[0], "--clerk", chosen[1], "--clerk", chosen[2])

    from sda_tpu.client import SdaClient
    from sda_tpu.crypto import MemoryKeystore
    from sda_tpu.http import SdaHttpClient
    from sda_tpu.protocol import AggregationId
    from sda_tpu.store import Filebased

    proxy = SdaHttpClient(url, store=Filebased(tmp_path / "probe"))
    ks = MemoryKeystore()
    probe = SdaClient(SdaClient.new_agent(ks), ks, proxy)
    probe.upload_agent()
    committee = proxy.get_committee(probe.agent, AggregationId(agg_id))
    assert [str(c) for c, _ in committee.clerks_and_keys] == chosen

    # full round still reveals exactly with the chosen committee
    sda("p1", "participate", agg_id, "1", "2", "3", "4")
    sda("p2", "participate", agg_id, "4", "3", "2", "1")
    sda("recipient", "aggregations", "end", agg_id)
    for i in range(4):
        sda(f"clerk-{i}", "clerk", "--once")
    assert sda("recipient", "aggregations", "reveal",
               agg_id).out.strip() == "5 5 5 5"

    # guard rails: wrong count, keyless clerk
    err = sda("recipient", "aggregations", "begin", agg_id,
              "--clerk", chosen[0], rc=1).err
    assert "exactly 3" in err
    sda("nokey", "agent", "create")
    nokey_id = _json.loads(sda("nokey", "agent", "show").out)["id"]
    err = sda("recipient", "aggregations", "begin", agg_id,
              "--clerk", chosen[0], "--clerk", chosen[1],
              "--clerk", nokey_id, rc=1).err
    assert "not a committee candidate" in err


def test_cli_embedded_participation(httpd, tmp_path, capsys):
    """`participate --embedded`: the C-core participation over real REST,
    mixed with a Python participant — the walkthrough sum must still be
    exact (the embeddable-client path, reference README.md:196-204)."""
    from sda_tpu import native
    from sda_tpu.crypto import sodium

    if not (sodium.available() and native.available()):
        pytest.skip("libsodium or native library not present")
    url = httpd.address

    def sda(identity, *args):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity),
                       *args])
        assert rc == 0
        return capsys.readouterr().out.strip()

    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "agent", "create")
        sda(who, "agent", "keys", "create")
    for who in ("part-1", "part-2"):
        sda(who, "agent", "create")

    agg_id = sda(
        "recipient", "aggregations", "create", "embedded-round",
        "--dimension", "4", "--modulus", "433", "--shares", "3",
        "--mask", "chacha",
    )
    sda("recipient", "aggregations", "begin", agg_id)
    sda("part-1", "participate", agg_id, "1", "2", "3", "4", "--embedded")
    sda("part-2", "participate", agg_id, "10", "20", "30", "40")
    sda("recipient", "aggregations", "end", agg_id)
    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "clerk", "--once")
    assert sda("recipient", "aggregations", "reveal", agg_id) == "11 22 33 44"


def test_cli_embedded_shamir_participation(httpd, tmp_path, capsys):
    """`participate --embedded` over a packed-Shamir committee via REST."""
    from sda_tpu import native
    from sda_tpu.crypto import sodium

    if not (sodium.available() and native.available()):
        pytest.skip("libsodium or native library not present")
    url = httpd.address

    def sda(identity, *args):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity),
                       *args])
        assert rc == 0
        return capsys.readouterr().out.strip()

    for who in ("recipient",) + tuple(f"clerk-{i}" for i in range(8)):
        sda(who, "agent", "create")
        sda(who, "agent", "keys", "create")
    sda("part", "agent", "create")
    agg_id = sda(
        "recipient", "aggregations", "create", "shamir-embedded",
        "--dimension", "4", "--modulus", "433",
        "--sharing", "shamir", "--shares", "8",
    )
    sda("recipient", "aggregations", "begin", agg_id)
    sda("part", "participate", agg_id, "1", "2", "3", "4", "--embedded")
    sda("recipient", "aggregations", "end", agg_id)
    for who in ("recipient",) + tuple(f"clerk-{i}" for i in range(8)):
        sda(who, "clerk", "--once")
    assert sda("recipient", "aggregations", "reveal", agg_id) == "1 2 3 4"


def test_cli_embedded_rejects_paillier_cleanly(httpd, tmp_path, capsys):
    """`participate --embedded` on a Paillier aggregation: clear error,
    exit 1, no traceback (the embedded core is Sodium-only)."""
    from sda_tpu import native
    from sda_tpu.crypto import sodium

    if not (sodium.available() and native.available()):
        pytest.skip("libsodium or native library not present")
    url = httpd.address

    def sda(identity, *args, expect_rc=0):
        rc = sda_main(["-s", url, "-i", str(tmp_path / "agent" / identity),
                       *args])
        assert rc == expect_rc
        return capsys.readouterr()

    for who in ("recipient", "clerk-1", "clerk-2", "clerk-3"):
        sda(who, "agent", "create")
        sda(who, "agent", "keys", "create",
            "--encryption", "paillier", "--paillier-modulus-bits", "512")
    sda("part", "agent", "create")
    agg_id = sda(
        "recipient", "aggregations", "create", "paillier-round",
        "--dimension", "4", "--modulus", "433", "--shares", "3",
        "--encryption", "paillier", "--paillier-modulus-bits", "512",
    ).out.strip()
    sda("recipient", "aggregations", "begin", agg_id)
    captured = sda("part", "participate", agg_id, "1", "2", "3", "4",
                   "--embedded", expect_rc=1)
    assert "embedded participation failed" in captured.err
    assert "Sodium" in captured.err
