"""The tree planner (sda_tpu/tree/plan.py): topology, deterministic ids,
and the privacy-threshold / quorum composition math — including the
degenerate G=1 tree, whose leaf round is scheme-identical to a flat
round (the bit-exact end-to-end half lives in test_tree_round.py).
"""

import pytest

from sda_tpu.protocol import (
    AdditiveSharing,
    AgentId,
    EncryptionKeyId,
    FullMasking,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.tree.plan import plan_tree

PACKED = PackedShamirSharing(
    secret_count=3, share_count=8, privacy_threshold=4,
    prime_modulus=433, omega_secrets=354, omega_shares=150,
)
ADDITIVE = AdditiveSharing(share_count=3, modulus=433)


def participants(n):
    return [f"p-{ix:05d}" for ix in range(n)]


class TestTopology:
    def test_two_level_tree_by_default(self):
        plan = plan_tree(participants(100), group_size=10)
        assert plan.depth() == 2
        leaves = plan.leaves()
        assert len(leaves) == 10
        assert sum(len(leaf.members) for leaf in leaves) == 100
        assert plan.root.level == 0
        assert all(leaf.level == 1 for leaf in leaves)
        assert all(leaf.parent is plan.root for leaf in leaves)

    def test_degenerate_single_group_is_leaf_plus_root(self):
        """G=1: one leaf holding everyone under one root — a flat round
        plus exactly one relay hop."""
        plan = plan_tree(participants(7), group_size=16)
        assert plan.depth() == 2
        assert len(plan.leaves()) == 1
        assert plan.leaves()[0].members == participants(7)
        assert len(plan.relay_nodes()) == 1

    def test_fanout_stacks_levels(self):
        plan = plan_tree(participants(64), group_size=4, fanout=4)
        assert len(plan.leaves()) == 16
        assert plan.depth() == 3  # 16 leaves / fanout 4 -> 4 -> 1
        for node in plan.nodes():
            assert node.is_leaf or node.fan_in() <= 4

    def test_deterministic_aggregation_ids(self):
        a = plan_tree(participants(30), group_size=10, seed="fixed")
        b = plan_tree(participants(30), group_size=10, seed="fixed")
        assert [str(n.aggregation_id) for n in a.nodes()] == \
            [str(n.aggregation_id) for n in b.nodes()]
        c = plan_tree(participants(30), group_size=10, seed="other")
        assert str(a.root.aggregation_id) != str(c.root.aggregation_id)

    def test_empty_ring_shards_dropped(self):
        """A ring shard with no members is dropped at plan time: every
        planned leaf has at least one participant (an empty leaf would
        feed a zero-length reconstruction upward), and the survivors
        keep their ring group indices."""
        for n in (2, 3, 5, 17):
            plan = plan_tree(participants(n), group_size=1,
                             seed=f"empty-{n}")
            leaves = plan.leaves()
            assert all(leaf.members for leaf in leaves)
            assert sum(len(leaf.members) for leaf in leaves) == n
            assert len({leaf.group for leaf in leaves}) == len(leaves)

    def test_group_of(self):
        plan = plan_tree(participants(40), group_size=10)
        for leaf in plan.leaves():
            for member in leaf.members:
                assert plan.group_of(member) == leaf.group
        with pytest.raises(KeyError):
            plan.group_of("not-a-participant")


class TestComposition:
    def test_level_table_thresholds(self):
        """Per-level privacy/quorum table: every level carries its
        committee's thresholds — the composition claim is that an
        adversary must exceed privacy_threshold at some SINGLE level."""
        plan = plan_tree(participants(120), group_size=16)
        table = plan.level_table(PACKED)
        assert [row["level"] for row in table] == [0, 1]
        root_row, leaf_row = table
        assert root_row["kind"] == "root" and root_row["rounds"] == 1
        assert leaf_row["kind"] == "leaf"
        assert leaf_row["rounds"] == len(plan.leaves())
        for row in table:
            assert row["committee_size"] == 8
            assert row["privacy_threshold"] == 4
            assert row["reconstruction_threshold"] == 7  # t + k
        assert root_row["max_fan_in"] == len(plan.leaves())
        assert leaf_row["max_fan_in"] == max(
            len(leaf.members) for leaf in plan.leaves())

    def test_mixed_schemes_per_level(self):
        plan = plan_tree(participants(60), group_size=20)
        table = plan.level_table(PACKED, internal_sharing=ADDITIVE)
        root_row, leaf_row = table
        assert leaf_row["privacy_threshold"] == 4
        assert leaf_row["reconstruction_threshold"] == 7
        # additive at the root: n-of-n — everyone is required
        assert root_row["privacy_threshold"] == 2
        assert root_row["reconstruction_threshold"] == 3

    def test_degenerate_tree_matches_flat_committee(self):
        """G=1 leaf round == the flat round's committee shape: same
        scheme object, same thresholds — flat-equivalence at the math
        level (bit-exact reveal pinned end-to-end elsewhere)."""
        plan = plan_tree(participants(9), group_size=9)
        table = plan.level_table(PACKED)
        leaf_row = table[1]
        assert leaf_row["rounds"] == 1
        assert leaf_row["max_fan_in"] == 9
        assert leaf_row["committee_size"] == PACKED.output_size
        assert leaf_row["privacy_threshold"] == PACKED.privacy_threshold
        assert (leaf_row["reconstruction_threshold"]
                == PACKED.reconstruction_threshold)

    def test_headroom_one_ring_is_wrap_free(self):
        """modulus == prime: all arithmetic is mod p, no headroom needed
        no matter the fan-in (the drill committees' configuration)."""
        plan = plan_tree(participants(400), group_size=100)
        plan.validate_headroom(433, PACKED)  # must not raise

    def test_headroom_two_ring_guard(self):
        """modulus < prime: the exact integer sum must fit under the
        prime, so an oversized fan-in is rejected at PLAN time, not
        discovered as a silently wrong reveal."""
        scheme = PackedShamirSharing(
            secret_count=3, share_count=8, privacy_threshold=4,
            prime_modulus=433, omega_secrets=354, omega_shares=150,
        )
        small = plan_tree(participants(8), group_size=2)
        small.validate_headroom(100, scheme)  # 2 * 99 < 433: fine
        big = plan_tree(participants(80), group_size=10)
        with pytest.raises(ValueError, match="headroom"):
            big.validate_headroom(100, scheme)  # 10 * 99 >= 433


class TestBuildAggregations:
    def _relays(self, plan):
        return [(AgentId.random(), EncryptionKeyId.random())
                for _ in plan.relay_nodes()]

    def _build(self, plan, **overrides):
        root_recipient = overrides.pop("root_recipient", AgentId.random())
        root_key = overrides.pop("root_recipient_key",
                                 EncryptionKeyId.random())
        kwargs = dict(
            title="t", vector_dimension=4, modulus=433,
            masking_scheme=FullMasking(433),
            leaf_sharing=ADDITIVE,
            recipient_encryption_scheme=SodiumEncryption(),
            committee_encryption_scheme=SodiumEncryption(),
            root_recipient=root_recipient,
            root_recipient_key=root_key,
            relays=overrides.pop("relays", self._relays(plan)),
        )
        kwargs.update(overrides)
        return root_recipient, root_key, plan.build_aggregations(**kwargs)

    def test_tree_links_wired(self):
        plan = plan_tree(participants(30), group_size=10)
        root_recipient, root_key, aggs = self._build(plan)
        root_agg = aggs[plan.root.path]
        assert root_agg.tree.parent is None
        assert root_agg.recipient == root_recipient
        assert len(root_agg.tree.children) == 3
        # the root's own masks already seal to its recipient: no redirect
        assert root_agg.tree.mask_recipient_key is None
        for leaf in plan.leaves():
            agg = aggs[leaf.path]
            assert agg.tree.root == plan.root.aggregation_id
            assert agg.tree.parent == plan.root.aggregation_id
            assert agg.tree.level == 1 and agg.tree.group == leaf.group
            # the privacy hinge: leaf masks seal to the ROOT, past the relay
            assert agg.tree.mask_recipient == root_recipient
            assert agg.tree.mask_recipient_key == root_key
            assert agg.recipient != root_recipient
            assert agg.id in root_agg.tree.children

    def test_serde_round_trip(self):
        from sda_tpu.protocol import Aggregation

        plan = plan_tree(participants(12), group_size=6)
        _, _, aggs = self._build(plan)
        for agg in aggs.values():
            back = Aggregation.from_obj(agg.to_obj())
            assert back == agg
            assert back.tree.to_obj() == agg.tree.to_obj()

    def test_flat_wire_shape_unchanged(self):
        """A flat aggregation serializes WITHOUT a tree key — the exact
        reference wire shape old peers parse."""
        from sda_tpu.protocol import Aggregation, AggregationId

        flat = Aggregation(
            id=AggregationId.random(), title="flat", vector_dimension=4,
            modulus=433, recipient=AgentId.random(),
            recipient_key=EncryptionKeyId.random(),
            masking_scheme=FullMasking(433),
            committee_sharing_scheme=ADDITIVE,
            recipient_encryption_scheme=SodiumEncryption(),
            committee_encryption_scheme=SodiumEncryption(),
        )
        assert "tree" not in flat.to_obj()

    def test_relay_count_mismatch_rejected(self):
        plan = plan_tree(participants(30), group_size=10)
        with pytest.raises(ValueError, match="relay"):
            self._build(plan, relays=[(AgentId.random(),
                                       EncryptionKeyId.random())])

    def test_mask_ring_mismatch_rejected(self):
        plan = plan_tree(participants(10), group_size=5)
        with pytest.raises(ValueError, match="ring"):
            self._build(plan, masking_scheme=FullMasking(101))
