"""Native C++ kernels vs the Python/numpy implementations: bit-exact.

The native library is the performance path for host-side work (recipient
seed re-expansion, exact modmatmul audits); every function must agree with
the Python spec to the bit.
"""

import numpy as np
import pytest

from sda_tpu import native
from sda_tpu.fields import chacha, numtheory
from sda_tpu.fields.modular import np_modmatmul

pytestmark = pytest.mark.skipif(
    not native.available(), reason="C++ toolchain unavailable"
)


def test_native_modmatmul_matches_python_ints():
    rng = np.random.default_rng(0)
    p = (1 << 31) - 1  # Mersenne prime, above the jnp kernel limit on purpose
    a = rng.integers(0, p, size=(5, 37), dtype=np.int64)
    b = rng.integers(0, p, size=(37, 11), dtype=np.int64)
    got = native.modmatmul(a, b, p)
    expect = [
        [sum(int(a[i, k]) * int(b[k, j]) for k in range(37)) % p for j in range(11)]
        for i in range(5)
    ]
    np.testing.assert_array_equal(got, expect)


def test_native_modmatmul_matches_numpy_kernel():
    rng = np.random.default_rng(1)
    p = 754974721
    a = rng.integers(0, p, size=(8, 16), dtype=np.int64)
    b = rng.integers(0, p, size=(16, 100), dtype=np.int64)
    np.testing.assert_array_equal(native.modmatmul(a, b, p), np_modmatmul(a, b, p))


def test_native_modsum():
    rng = np.random.default_rng(2)
    x = rng.integers(0, 433, size=(50, 200), dtype=np.int64)
    np.testing.assert_array_equal(
        native.modsum_axis0(x, 433), x.sum(axis=0) % 433
    )


def test_native_chacha_bit_exact_with_python_spec():
    seed = [0xDEADBEEF, 0x12345678, 0x9ABCDEF0, 0x0F0F0F0F]
    for dim, m in [(1, 433), (1000, 433), (257, 754974721), (64, 2)]:
        np.testing.assert_array_equal(
            native.chacha_expand_mask(seed, dim, m),
            chacha.expand_mask(seed, dim, m),
        )


def test_native_chacha_combine():
    seeds = np.array([[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]], dtype=np.int64)
    dim, m = 500, 433
    expect = np.zeros(dim, dtype=np.int64)
    for s in seeds:
        expect = (expect + chacha.expand_mask([int(w) for w in s], dim, m)) % m
    np.testing.assert_array_equal(
        native.chacha_combine_masks(seeds, dim, m), expect
    )


def test_masking_layer_uses_native_consistently():
    """The ChaCha masker round-trips identically whichever backend serves it."""
    from sda_tpu.crypto import masking
    from sda_tpu.protocol import ChaChaMasking

    masker = masking.new_secret_masker(ChaChaMasking(433, 100, 128))
    s = np.arange(100, dtype=np.int64) % 433
    seed, masked = masker.mask(s)
    total = masking.new_mask_combiner(ChaChaMasking(433, 100, 128)).combine([seed])
    out = masking.new_secret_unmasker(ChaChaMasking(433, 100, 128)).unmask(total, masked)
    np.testing.assert_array_equal(out, s)
