"""Native C++ kernels vs the Python/numpy implementations: bit-exact.

The native library is the performance path for host-side work (recipient
seed re-expansion, exact modmatmul audits); every function must agree with
the Python spec to the bit.
"""

import numpy as np
import pytest

from sda_tpu import native
from sda_tpu.fields import chacha, numtheory
from sda_tpu.fields.modular import np_modmatmul

pytestmark = pytest.mark.skipif(
    not native.available(), reason="C++ toolchain unavailable"
)


def test_native_modmatmul_matches_python_ints():
    rng = np.random.default_rng(0)
    p = (1 << 31) - 1  # Mersenne prime, above the jnp kernel limit on purpose
    a = rng.integers(0, p, size=(5, 37), dtype=np.int64)
    b = rng.integers(0, p, size=(37, 11), dtype=np.int64)
    got = native.modmatmul(a, b, p)
    expect = [
        [sum(int(a[i, k]) * int(b[k, j]) for k in range(37)) % p for j in range(11)]
        for i in range(5)
    ]
    np.testing.assert_array_equal(got, expect)


def test_native_modmatmul_matches_numpy_kernel():
    rng = np.random.default_rng(1)
    p = 754974721
    a = rng.integers(0, p, size=(8, 16), dtype=np.int64)
    b = rng.integers(0, p, size=(16, 100), dtype=np.int64)
    np.testing.assert_array_equal(native.modmatmul(a, b, p), np_modmatmul(a, b, p))


def test_native_modsum():
    rng = np.random.default_rng(2)
    x = rng.integers(0, 433, size=(50, 200), dtype=np.int64)
    np.testing.assert_array_equal(
        native.modsum_axis0(x, 433), x.sum(axis=0) % 433
    )


def test_native_chacha_bit_exact_with_python_spec():
    seed = [0xDEADBEEF, 0x12345678, 0x9ABCDEF0, 0x0F0F0F0F]
    for dim, m in [(1, 433), (1000, 433), (257, 754974721), (64, 2)]:
        np.testing.assert_array_equal(
            native.chacha_expand_mask(seed, dim, m, prg=chacha.CHACHA_PRG_V1),
            chacha.expand_mask(seed, dim, m),
        )


def test_native_chacha_combine():
    seeds = np.array([[1, 2, 3, 4], [5, 6, 7, 8], [9, 10, 11, 12]], dtype=np.int64)
    dim, m = 500, 433
    expect = np.zeros(dim, dtype=np.int64)
    for s in seeds:
        expect = (expect + chacha.expand_mask([int(w) for w in s], dim, m)) % m
    np.testing.assert_array_equal(
        native.chacha_combine_masks(seeds, dim, m, prg=chacha.CHACHA_PRG_V1),
        expect,
    )


def test_masking_layer_uses_native_consistently():
    """The ChaCha masker round-trips identically whichever backend serves it."""
    from sda_tpu.crypto import masking
    from sda_tpu.protocol import ChaChaMasking

    masker = masking.new_secret_masker(ChaChaMasking(433, 100, 128))
    s = np.arange(100, dtype=np.int64) % 433
    seed, masked = masker.mask(s)
    total = masking.new_mask_combiner(ChaChaMasking(433, 100, 128)).combine([seed])
    out = masking.new_secret_unmasker(ChaChaMasking(433, 100, 128)).unmask(total, masked)
    np.testing.assert_array_equal(out, s)


def test_native_powmod_matches_pow():
    """Montgomery ladder == CPython pow across sizes, including the
    Paillier shapes (2048-bit exponent mod 4096-bit n^2)."""
    import random

    random.seed(11)
    for bits in (64, 127, 256, 1024, 2048):
        mod = random.getrandbits(bits) | 1 | (1 << (bits - 1))
        base = random.getrandbits(bits + 7)
        exp = random.getrandbits(random.choice([1, 64, bits]))
        assert native.powmod(base, exp, mod) == pow(base, exp, mod)
    assert native.powmod(5, 0, 7) == 1
    assert native.powmod(0, 123, 97) == 0
    mod = random.getrandbits(2048) | 1 | (1 << 2047)
    bases = [random.getrandbits(2040) for _ in range(4)]
    e = random.getrandbits(1024)
    assert native.powmod_batch(bases, e, mod) == [pow(b, e, mod) for b in bases]
    with pytest.raises(ValueError):
        native.powmod(2, 3, 10)  # even modulus unsupported


def test_paillier_uses_native_powmod_consistently():
    """Paillier encrypt/decrypt are identical with and without the native
    ladder (the hook is a pure speedup, never a semantic change)."""
    from sda_tpu.crypto import paillier

    pk, sk = paillier.keygen(512)
    m = 123456789
    c = paillier.encrypt(pk, m, r=987654321 % pk.n)
    # force the pure-Python path for the same inputs
    orig = paillier._powmod
    try:
        paillier._powmod = pow
        c_py = paillier.encrypt(pk, m, r=987654321 % pk.n)
        m_py = paillier.decrypt(sk, c)
    finally:
        paillier._powmod = orig
    assert c == c_py
    assert paillier.decrypt(sk, c) == m_py == m
