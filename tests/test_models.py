"""Model layer: fixed-point codec, families, secure FedAvg (both surfaces).

The exactness contract under test: the secure modular sum of encoded
deltas decodes to the *exact* sum of the quantized deltas — FedAvg through
the protocol equals FedAvg on plaintext quantized values bit-for-bit.
"""

import numpy as np
import pytest

from sda_tpu.models import (
    FederatedSession,
    FixedPointCodec,
    LeNet,
    LoRAMLP,
    LocalTrainer,
    MobileLite,
    lora_adapter_params,
    merge_lora_params,
    param_count,
    pod_fedavg_round,
    ravel_pytree,
)

M31 = (1 << 31) - 1  # Mersenne prime, the widest additive modulus allowed


# ---------------------------------------------------------------------------
# codec

def test_codec_sum_exactness():
    rng = np.random.default_rng(7)
    codec = FixedPointCodec(M31, fractional_bits=16, max_summands=10, clip=8.0)
    xs = rng.normal(0, 2, size=(10, 64))
    encoded = np.stack([codec.encode(x) for x in xs])
    secure_sum = np.mod(encoded.sum(axis=0), M31)
    expected = np.stack([codec.quantize(x) for x in xs]).sum(axis=0) / codec.scale
    np.testing.assert_array_equal(codec.decode_sum(secure_sum, 10), expected)


def test_codec_negative_and_clip():
    codec = FixedPointCodec(M31, fractional_bits=8, max_summands=1, clip=2.0)
    enc = codec.encode(np.array([-1.5, 2.0, -2.0, 5.0, -5.0]))
    assert (enc >= 0).all() and (enc < M31).all()
    dec = codec.decode_sum(enc, 1)
    np.testing.assert_array_equal(dec, [-1.5, 2.0, -2.0, 2.0, -2.0])


def test_codec_capacity_guards():
    with pytest.raises(ValueError, match="headroom"):
        FixedPointCodec(433, fractional_bits=8, max_summands=1000)
    with pytest.raises(ValueError, match="capacity"):
        FixedPointCodec(M31, fractional_bits=16, max_summands=100, clip=1e6)
    codec = FixedPointCodec(M31, fractional_bits=16, max_summands=2, clip=1.0)
    with pytest.raises(ValueError, match="summands"):
        codec.decode_sum(np.zeros(4, np.int64), 3)


def test_codec_device_matches_host():
    rng = np.random.default_rng(11)
    codec = FixedPointCodec(M31, fractional_bits=12, max_summands=4, clip=4.0)
    x = rng.normal(0, 1.5, size=(3, 32))
    host = np.stack([codec.encode(row) for row in x])
    dev = np.asarray(codec.encode_device(x))
    np.testing.assert_array_equal(host, dev)


@pytest.mark.parametrize("modulus,fractional_bits,max_summands,clip", [
    (M31, 12, 4, 4.0),            # wide modulus, generous headroom
    (M31, 16, 100, 1.0),          # fine grid, many summands
    ((1 << 20), 8, 3, None),      # small power-of-two modulus, derived clip
    ((1 << 24) - 3, 4, 50, None),  # coarse grid at the capacity-derived cap
])
def test_codec_host_device_bit_exact_property_matrix(
        modulus, fractional_bits, max_summands, clip):
    """The host/device codec claim, at the edges: ``encode`` ==
    ``encode_device`` element-wise over clip boundaries, negative halves,
    the q_max boundary, half-to-even rounding ties, and a random cloud —
    the exactness argument of docs/models.md leans on this equality."""
    codec = FixedPointCodec(modulus, fractional_bits=fractional_bits,
                            max_summands=max_summands, clip=clip)
    step = 1.0 / codec.scale
    eps = step / 8.0
    ties = (np.arange(-9, 9, dtype=np.float64) + 0.5) * step  # .5 grid ties
    probes = np.concatenate([
        np.array([0.0, -0.0, codec.clip, -codec.clip,          # clip edges
                  codec.clip - eps, -codec.clip + eps,
                  codec.clip + 1.0, -codec.clip - 1.0,         # beyond clip
                  codec.clip * 3, -codec.clip * 3]),
        ties, -ties[::-1],                                     # half-to-even
        np.array([step, -step, step / 2, -step / 2,            # neg halves
                  1.5 * step, -1.5 * step, 2.5 * step, -2.5 * step]),
        np.random.default_rng(17).normal(0, codec.clip, size=64),
    ])
    host = codec.encode(probes)
    dev = np.asarray(codec.encode_device(probes), dtype=np.int64)
    np.testing.assert_array_equal(host, dev)
    # both paths clamp the quantized value to the q_max boundary exactly
    q_max = int(round(codec.clip * codec.scale))
    centered = host - np.where(host > modulus // 2, modulus, 0)
    assert centered.max() == q_max and centered.min() == -q_max
    # and the ties actually rounded half to EVEN on both paths
    tie_q = codec.quantize(ties)
    assert (tie_q % 2 == 0).all(), tie_q


@pytest.mark.parametrize("modulus,fractional_bits,max_summands,clip", [
    (M31, 12, 4, 4.0),
    ((1 << 20), 8, 3, None),
])
def test_codec_adversarial_floats_clamp_deterministically(
        modulus, fractional_bits, max_summands, clip):
    """NaN/±Inf from a hostile (or merely diverged) client must clamp
    deterministically on BOTH lanes — NaN -> 0, ±Inf -> ±clip — never an
    undefined float->int64 cast. ``np.clip`` passes NaN through, so this
    pins the explicit scrub; and host/device bit-identity must survive
    the adversarial corners too."""
    codec = FixedPointCodec(modulus, fractional_bits=fractional_bits,
                            max_summands=max_summands, clip=clip)
    probes = np.array([np.nan, -np.nan, np.inf, -np.inf,
                       np.float64(1e300), -np.float64(1e300),  # f32 overflow
                       0.5, -codec.clip / 2], dtype=np.float64)
    q = codec.quantize(probes)
    q_max = codec.q_max
    expected = np.array([0, 0, q_max, -q_max, q_max, -q_max,
                         int(round(0.5 * codec.scale)),
                         -int(round(codec.clip / 2 * codec.scale))],
                        dtype=np.int64)
    np.testing.assert_array_equal(q, expected)
    host = codec.encode(probes)
    assert (host >= 0).all() and (host < modulus).all()
    dev = np.asarray(codec.encode_device(probes), dtype=np.int64)
    np.testing.assert_array_equal(host, dev)
    # a NaN-poisoned vector still decodes: the aggregate of one scrubbed
    # encoding is the scrubbed quantized value, exactly
    np.testing.assert_array_equal(
        codec.decode_sum(host, 1), q.astype(np.float64) / codec.scale)


def test_codec_norm_clip_projects_by_construction():
    """The L2 defense: vectors inside the ball pass through untouched
    (bit-identical to a norm_clip-free codec); vectors outside are
    projected onto the ball — the quantized norm lands at norm_clip
    regardless of how hard the attacker boosted."""
    base = FixedPointCodec(M31, fractional_bits=16, max_summands=4,
                           clip=1.0)
    clipped = FixedPointCodec(M31, fractional_bits=16, max_summands=4,
                              clip=1.0, norm_clip=0.5)
    rng = np.random.default_rng(23)
    inside = rng.normal(0, 1, size=64)
    inside *= 0.4 / np.linalg.norm(inside)
    np.testing.assert_array_equal(clipped.encode(inside),
                                  base.encode(inside))
    boosted = inside * -80.0  # boost:-80 attacker
    q = clipped.quantize(boosted)
    norm = np.linalg.norm(q.astype(np.float64) / clipped.scale)
    assert abs(norm - 0.5) < 1e-3, norm
    # NaN scrub happens before the norm: a single NaN cannot zero the
    # whole projection or poison the reduction
    poisoned = inside.copy()
    poisoned[0] = np.nan
    assert np.isfinite(
        clipped.quantize(poisoned).astype(np.float64)).all()


def test_codec_norm_clip_is_host_lane_only():
    """The L2 reduction is not bit-reproducible between numpy and XLA, so
    a norm-clipped codec must refuse the device encode path with a typed
    error instead of silently forking host/device encodings."""
    with pytest.raises(ValueError, match="norm_clip must be positive"):
        FixedPointCodec(M31, fractional_bits=8, max_summands=2,
                        norm_clip=0.0)
    codec = FixedPointCodec(M31, fractional_bits=8, max_summands=2,
                            norm_clip=1.0)
    with pytest.raises(ValueError, match="host-lane"):
        codec.encode_device(np.zeros(4, np.float32))
    assert "norm_clip" in repr(codec)


def test_codec_decode_rejects_empty_summand_set():
    """decode_sum/decode_mean with summands < 1 is always a caller bug
    (empty frozen set): typed error, not ZeroDivisionError or a silent
    'sum of nothing'."""
    codec = FixedPointCodec(M31, fractional_bits=8, max_summands=4)
    with pytest.raises(ValueError, match="at least one summand"):
        codec.decode_mean(np.zeros(4, np.int64), 0)
    with pytest.raises(ValueError, match="at least one summand"):
        codec.decode_sum(np.zeros(4, np.int64), -2)


def test_modulus_mismatch_is_rejected():
    """A codec/aggregation modulus mismatch must fail loudly, not decode
    garbage (both FedAvg surfaces validate it)."""
    from sda_tpu.mesh import SimulatedPod, make_mesh
    from sda_tpu.protocol import AdditiveSharing

    pod = SimulatedPod(AdditiveSharing(share_count=8, modulus=M31),
                       mesh=make_mesh(4, 2))
    codec = FixedPointCodec((1 << 29) - 3, fractional_bits=8,
                            max_summands=2, clip=1.0)
    with pytest.raises(ValueError, match="modulus"):
        pod_fedavg_round(pod, codec, np.zeros(8), np.zeros((2, 8)))


def test_ravel_pytree_roundtrip():
    import jax.numpy as jnp

    tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": {"c": jnp.ones((4,), jnp.float32), "d": jnp.zeros(())}}
    vec, unravel = ravel_pytree(tree)
    assert vec.shape == (11,)
    back = unravel(vec + 1.0)
    np.testing.assert_array_equal(np.asarray(back["a"]),
                                  np.arange(6).reshape(2, 3) + 1)
    assert np.asarray(back["b"]["d"]).shape == ()


# ---------------------------------------------------------------------------
# families

def test_lenet_is_the_60k_family():
    import jax

    model = LeNet()
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 28, 28, 1), np.float32))
    n = param_count(params)
    assert 50_000 < n < 80_000, n
    out = model.apply(params, np.zeros((2, 28, 28, 1), np.float32))
    assert out.shape == (2, 10)


def test_mobilelite_and_lora_forward():
    import jax

    tiny = MobileLite(width=8, block_channels=(16, 24))
    params = tiny.init(jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32))
    assert tiny.apply(params, np.zeros((2, 32, 32, 3), np.float32)).shape == (2, 10)

    lora = LoRAMLP(features=64, layers=2, rank=4)
    lp = lora.init(jax.random.PRNGKey(1), np.zeros((1, 16), np.float32))
    assert lora.apply(lp, np.zeros((3, 16), np.float32)).shape == (3, 10)
    adapters = lora_adapter_params(lp)
    assert set(adapters) == {"lora_a_0", "lora_b_0", "lora_a_1", "lora_b_1"}
    merged = merge_lora_params(lp, adapters)
    assert param_count(merged) == param_count(lp)


def test_family_flagship_sizes():
    """The default widths land on the benchmark workload sizes."""
    import jax

    mob = MobileLite()
    mp = jax.eval_shape(
        lambda k: mob.init(k, np.zeros((1, 32, 32, 3), np.float32)),
        jax.random.PRNGKey(0))
    n_mob = param_count(mp)
    assert 2_500_000 < n_mob < 5_000_000, n_mob

    lora = LoRAMLP()
    lp = jax.eval_shape(
        lambda k: lora.init(k, np.zeros((1, 4096), np.float32)),
        jax.random.PRNGKey(0))
    n_ad = param_count(lora_adapter_params(lp))
    assert 9_000_000 < n_ad < 18_000_000, n_ad


# ---------------------------------------------------------------------------
# secure FedAvg — protocol surface

def _new_client(service):
    from sda_tpu.client import SdaClient
    from sda_tpu.crypto import MemoryKeystore

    ks = MemoryKeystore()
    return SdaClient(SdaClient.new_agent(ks), ks, service)


def test_federated_session_exact_round():
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")
    from sda_tpu.protocol import (
        AdditiveSharing,
        Aggregation,
        AggregationId,
        NoMasking,
        SodiumEncryption,
    )
    from sda_tpu.server import new_memory_server

    dim, n_part = 24, 3
    service = new_memory_server()
    recipient = _new_client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(rkey)
    clerks = [_new_client(service) for _ in range(3)]
    for c in clerks:
        ck = c.new_encryption_key()
        c.upload_agent()
        c.upload_encryption_key(ck)
    participants = [_new_client(service) for _ in range(n_part)]
    for p in participants:
        p.upload_agent()

    template = Aggregation(
        id=AggregationId.random(), title="fedavg", vector_dimension=dim,
        modulus=M31, recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=M31),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    codec = FixedPointCodec(M31, fractional_bits=16, max_summands=n_part, clip=4.0)
    session = FederatedSession(template, codec, recipient, clerks, participants)

    rng = np.random.default_rng(3)
    deltas = rng.normal(0, 1, size=(n_part, dim))
    mean = session.round(list(deltas))
    expected = np.stack([codec.quantize(d) for d in deltas]).sum(0) \
        / codec.scale / n_part
    np.testing.assert_array_equal(mean, expected)

    # a second round creates a fresh aggregation and still reveals exactly
    mean2 = session.round(list(-deltas))
    np.testing.assert_array_equal(mean2, -expected)


def test_federated_session_packed_shamir_semantics():
    """FedAvg over Packed-Shamir: values live in Z_m but are SHARED in
    Z_p (p > m). Negative encodings sit near m, so exactness needs
    n_participants * m < p — the codec's modulus is m and the final
    positive() lift mod m recovers the centered sum. Pins that the wrap
    algebra composes (reference: crypto.rs derived properties +
    receive.rs:14-21 lift)."""
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")
    from sda_tpu.fields import numtheory
    from sda_tpu.protocol import (
        Aggregation,
        AggregationId,
        FullMasking,
        PackedShamirSharing,
        SodiumEncryption,
    )
    from sda_tpu.server import new_memory_server

    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    m = 1 << 20  # n * m = 3 * 2^20 << p = 5.4e8: no Z_p wrap
    dim, n_part = 12, 3
    service = new_memory_server()
    recipient = _new_client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(rkey)
    clerks = [_new_client(service) for _ in range(8)]
    for c in clerks:
        ck = c.new_encryption_key()
        c.upload_agent()
        c.upload_encryption_key(ck)
    participants = [_new_client(service) for _ in range(n_part)]
    for part in participants:
        part.upload_agent()

    template = Aggregation(
        id=AggregationId.random(), title="fedavg-shamir",
        vector_dimension=dim, modulus=m,
        recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=FullMasking(m),
        committee_sharing_scheme=PackedShamirSharing(3, 8, t, p, w2, w3),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    codec = FixedPointCodec(m, fractional_bits=8, max_summands=n_part)
    session = FederatedSession(template, codec, recipient, clerks,
                               participants)
    rng = np.random.default_rng(9)
    deltas = rng.normal(0, 100, size=(n_part, dim))  # mixed signs, clipped
    mean = session.round(list(deltas))
    expected = np.stack([codec.quantize(d) for d in deltas]).sum(0) \
        / codec.scale / n_part
    np.testing.assert_array_equal(mean, expected)

    # fault tolerance composes with the model layer: one clerk never runs
    # chores, the reconstruction threshold (t + k = 4+3 = 7 of 8) is still
    # met, and the round reveals the exact mean (crypto.rs:146-153)
    session_drop = FederatedSession(
        template, codec, recipient,
        [c for c in clerks if c is not clerks[5]], participants)
    mean2 = session_drop.round(list(-deltas))
    np.testing.assert_array_equal(mean2, -expected)


def test_federated_session_surfaces_typed_round_verdict():
    """A round that cannot complete (additive sharing, one clerk never
    clerks) must surface a typed lifecycle verdict from ``await_result``
    within the deadline — not hang, not silently decode a partial
    committee sum, not a bare NotFound."""
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")
    from sda_tpu.protocol import (
        AdditiveSharing,
        Aggregation,
        AggregationId,
        NoMasking,
        RoundFailed,
        SodiumEncryption,
    )
    from sda_tpu.server import new_memory_server

    dim, n_part = 8, 2
    service = new_memory_server()
    recipient = _new_client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(rkey)
    clerks = [_new_client(service) for _ in range(3)]
    for c in clerks:
        c.upload_agent()
        c.upload_encryption_key(c.new_encryption_key())
    participants = [_new_client(service) for _ in range(n_part)]
    for p in participants:
        p.upload_agent()
    template = Aggregation(
        id=AggregationId.random(), title="fedavg-dead", vector_dimension=dim,
        modulus=M31, recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=NoMasking(),
        # a 4-of-4 committee over exactly 4 key-holders (recipient + 3
        # clerks): election MUST include clerk 2, whose chores the
        # session below never runs — deterministic regardless of the
        # uuid-sorted suggestion order (the recipient's own chores ARE
        # run by FederatedSession.round)
        committee_sharing_scheme=AdditiveSharing(share_count=4, modulus=M31),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    codec = FixedPointCodec(M31, fractional_bits=8, max_summands=n_part,
                            clip=1.0)
    # clerk 2 never runs chores: additive n-of-n can never reconstruct
    session = FederatedSession(template, codec, recipient, clerks[:2],
                               participants)
    deltas = np.random.default_rng(1).normal(0, 0.5, size=(n_part, dim))
    with pytest.raises(RoundFailed):  # RoundExpired subclasses RoundFailed
        session.round(list(deltas), deadline=1.0)


def test_participation_input_ndarray_fast_path():
    """The encoded int64 ndarray goes through ``participate`` without a
    per-element Python conversion; raw float arrays are rejected (a
    silent float->int64 truncation would bypass the codec contract)."""
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")
    from sda_tpu.protocol import (
        AdditiveSharing,
        Aggregation,
        AggregationId,
        NoMasking,
        SodiumEncryption,
    )
    from sda_tpu.server import new_memory_server

    service = new_memory_server()
    recipient = _new_client(service)
    rkey = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(rkey)
    clerks = [_new_client(service) for _ in range(3)]
    for c in clerks:
        c.upload_agent()
        c.upload_encryption_key(c.new_encryption_key())
    participant = _new_client(service)
    participant.upload_agent()
    aggregation = Aggregation(
        id=AggregationId.random(), title="nd", vector_dimension=16,
        modulus=M31, recipient=recipient.agent.id, recipient_key=rkey,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=M31),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(aggregation)
    recipient.begin_aggregation(aggregation.id)
    codec = FixedPointCodec(M31, fractional_bits=8, max_summands=2, clip=1.0)
    encoded = codec.encode(np.random.default_rng(2).normal(0, 0.4, size=16))
    assert encoded.dtype == np.int64
    participant.participate(encoded, aggregation.id)  # ndarray, no list()
    status = service.get_aggregation_status(recipient.agent, aggregation.id)
    assert status.number_of_participations == 1
    with pytest.raises(ValueError, match="FixedPointCodec"):
        participant.new_participation(
            np.zeros(16, dtype=np.float64), aggregation.id)


# ---------------------------------------------------------------------------
# secure FedAvg — mesh surface + real training

def test_pod_fedavg_training_improves():
    """Two secure FedAvg rounds on the 8-device pod mesh train a real model.

    Linear-regression MLP on synthetic data; every client update is encoded,
    shared, and aggregated through SimulatedPod. Loss must drop and the
    aggregate must match the plaintext quantized mean exactly.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from sda_tpu.mesh import SimulatedPod, make_mesh
    from sda_tpu.protocol import AdditiveSharing

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(8,))
    xs = rng.normal(size=(4, 16, 8)).astype(np.float32)  # 4 clients
    ys = (xs @ w_true).astype(np.float32)

    def loss_fn(params, batch):
        x, y = batch
        pred = x @ params["w"] + params["b"]
        return jnp.mean((pred - y) ** 2)

    trainer = LocalTrainer(loss_fn, optax.sgd(0.05))
    global_params = {"w": jnp.zeros((8,), jnp.float32),
                     "b": jnp.zeros((), jnp.float32)}
    global_vec, unravel = ravel_pytree(global_params)

    pod = SimulatedPod(AdditiveSharing(share_count=8, modulus=M31),
                       mesh=make_mesh(4, 2))
    codec = FixedPointCodec(M31, fractional_bits=16, max_summands=4, clip=4.0)

    def global_loss(params):
        return float(np.mean([loss_fn(params, (xs[i], ys[i])) for i in range(4)]))

    losses = [global_loss(global_params)]
    for _ in range(2):
        client_vecs = []
        for i in range(4):
            p = unravel(global_vec)
            st = trainer.init_state(p)
            batches = (jnp.tile(xs[i][None], (3, 1, 1)),
                       jnp.tile(ys[i][None], (3, 1)))
            p, st, _ = trainer.fit(p, st, batches)
            vec, _ = ravel_pytree(p)
            client_vecs.append(vec)

        # plaintext oracle for the same quantized round
        deltas = np.stack(client_vecs) - global_vec[None, :]
        expected_mean = np.stack(
            [codec.quantize(d) for d in deltas]).sum(0) / codec.scale / 4

        key = jax.random.PRNGKey(len(losses))
        new_vec = pod_fedavg_round(pod, codec, global_vec, client_vecs, key)
        np.testing.assert_allclose(new_vec - global_vec, expected_mean,
                                   rtol=0, atol=0)
        global_vec = new_vec
        global_params = unravel(global_vec)
        losses.append(global_loss(global_params))

    assert losses[-1] < losses[0] * 0.7, losses


def test_streamed_fedavg_lora_adapters():
    """pod_fedavg_round is polymorphic over the aggregation surfaces: the
    same call drives StreamedPod (the HBM-exceeding large-model path, i.e.
    the lora-13m setting) with LoRA adapter vectors, exactly."""
    import jax

    from sda_tpu.mesh import StreamedPod, make_mesh
    from sda_tpu.protocol import AdditiveSharing

    lora = LoRAMLP(features=32, layers=2, rank=4)
    lp = lora.init(jax.random.PRNGKey(0), np.zeros((1, 16), np.float32))
    adapters = lora_adapter_params(lp)
    gvec, unravel = ravel_pytree(adapters)

    pod = StreamedPod(AdditiveSharing(share_count=8, modulus=M31),
                      mesh=make_mesh(4, 2), dim_chunk=256)
    codec = FixedPointCodec(M31, fractional_bits=16, max_summands=3, clip=2.0)

    rng = np.random.default_rng(5)
    client_vecs = gvec[None, :] + rng.normal(0, 0.1, size=(3, gvec.size))
    deltas = client_vecs - gvec[None, :]
    expected = np.stack([codec.quantize(d) for d in deltas]).sum(0) \
        / codec.scale / 3

    new_vec = pod_fedavg_round(pod, codec, gvec, client_vecs,
                               jax.random.PRNGKey(9))
    # compare the updated vector itself: (g + m) - g re-rounds in float64
    np.testing.assert_array_equal(new_vec, gvec + expected)
    merged = merge_lora_params(lp, unravel(new_vec))
    assert lora.apply(merged, np.zeros((2, 16), np.float32)).shape == (2, 10)
