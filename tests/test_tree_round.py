"""End-to-end hierarchical rounds through the real server stack
(sda_tpu/tree/round.py + client/relay.py): bit-exactness vs the flat
reference (including the degenerate G=1 tree), per-level privacy
mechanics (masks sealed past the relay), quorum-degraded leaves feeding
survivors up, failed leaves failing the root with a reason naming the
leaf, and parent/child linkage on the round documents.
"""

import numpy as np
import pytest

from sda_tpu.crypto import sodium
from sda_tpu.server import lifecycle, new_memory_server
from sda_tpu.tree import run_tree_round

pytestmark = pytest.mark.skipif(not sodium.available(),
                                reason="libsodium not present")


def inputs_for(n, dim=4, seed=0, modulus=433):
    rng = np.random.default_rng(seed)
    return rng.integers(0, modulus, size=(n, dim), dtype=np.int64)


class TestBitExact:
    def test_tree_reveals_flat_sum(self):
        report = run_tree_round(
            inputs_for(9), group_size=4, sharing="additive",
            masking="full", seed=7)
        assert report["exact"] is True
        assert report["flat_exact"] is True
        assert report["depth"] == 2
        assert report["root_state"] == "revealed"
        assert report["relays"] == report["groups"]

    def test_degenerate_single_group_equals_flat(self):
        """G=1: every participant in one leaf, one relay hop — the tree
        reveal is bit-exact with the flat reference round."""
        report = run_tree_round(
            inputs_for(6, seed=3), group_size=32, sharing="additive",
            masking="full", seed=3)
        assert report["groups"] == 1
        assert report["exact"] is True
        assert report["flat_exact"] is True

    def test_chacha_masking_forwards_seeds(self):
        report = run_tree_round(
            inputs_for(8, seed=5), group_size=3, sharing="additive",
            masking="chacha", seed=5)
        assert report["exact"] is True
        assert report["flat_exact"] is True
        assert report["counters"].get("relay.masks_forwarded", 0) == 8

    def test_dropout_shrinks_the_sum_exactly(self):
        report = run_tree_round(
            inputs_for(12, seed=11), group_size=4, sharing="additive",
            masking="full", seed=11, dropout_rate=0.5)
        assert report["participants_dropped"] >= 1
        assert report["exact"] is True
        assert report["flat_exact"] is True


class TestRelayPrivacy:
    def test_masks_seal_past_the_relay(self):
        """The privacy hinge, mechanically: every leaf mask ciphertext
        opens with the ROOT's key (the exact reveal proves it) and the
        relay's own key CANNOT open it — a relay never sees an unmasked
        value."""
        from sda_tpu.client import SdaClient
        from sda_tpu.crypto import MemoryKeystore
        from sda_tpu.protocol import (
            AdditiveSharing, FullMasking, SodiumEncryption)
        from sda_tpu.tree.plan import plan_tree

        service = new_memory_server()

        def new_client():
            keystore = MemoryKeystore()
            agent = SdaClient.new_agent(keystore)
            client = SdaClient(agent, keystore, service)
            client.upload_agent()
            return client

        root = new_client()
        root_key = root.new_encryption_key()
        root.upload_encryption_key(root_key)
        relay = new_client()
        relay_key = relay.new_encryption_key()
        relay.upload_encryption_key(relay_key)
        clerks = []
        for _ in range(3):
            clerk = new_client()
            clerk.upload_encryption_key(clerk.new_encryption_key())
            clerks.append(clerk)
        participant = new_client()

        plan = plan_tree([str(participant.agent.id)], group_size=4)
        aggs = plan.build_aggregations(
            title="privacy", vector_dimension=4, modulus=433,
            masking_scheme=FullMasking(433),
            leaf_sharing=AdditiveSharing(share_count=3, modulus=433),
            recipient_encryption_scheme=SodiumEncryption(),
            committee_encryption_scheme=SodiumEncryption(),
            root_recipient=root.agent.id, root_recipient_key=root_key,
            relays=[(relay.agent.id, relay_key)],
        )
        leaf = plan.leaves()[0]
        relay.upload_aggregation(aggs[leaf.path])
        relay.begin_aggregation_with(
            leaf.aggregation_id, [c.agent.id for c in clerks])
        participant.participate([1, 2, 3, 4], leaf.aggregation_id)
        uploaded = list(
            service.server.aggregation_store._participations[
                leaf.aggregation_id].values())
        assert len(uploaded) == 1
        mask_ct = uploaded[0].recipient_encryption
        assert mask_ct is not None
        # the root opens it; the relay must not be able to
        root_decryptor = root.crypto.new_share_decryptor(
            root_key, aggs[leaf.path].recipient_encryption_scheme)
        assert len(root_decryptor.decrypt(mask_ct)) == 4
        relay_decryptor = relay.crypto.new_share_decryptor(
            relay_key, aggs[leaf.path].recipient_encryption_scheme)
        with pytest.raises(Exception):
            relay_decryptor.decrypt(mask_ct)


class TestRelayResume:
    def test_crashed_relay_replays_byte_identically(self, tmp_path):
        """A relay that dies in the lost-ack window (upload ingested, ack
        never seen) must replay its journaled bytes on restart — the
        server dedupes the byte-identical re-upload instead of rejecting
        a fresh-randomness recompute as an equivocation."""
        from sda_tpu.client import SdaClient, relay
        from sda_tpu.client.journal import ParticipationJournal
        from sda_tpu.crypto import MemoryKeystore
        from sda_tpu.protocol import (
            AdditiveSharing, FullMasking, SodiumEncryption)
        from sda_tpu.tree.plan import plan_tree
        from sda_tpu.utils import metrics

        service = new_memory_server()

        def new_client():
            keystore = MemoryKeystore()
            agent = SdaClient.new_agent(keystore)
            client = SdaClient(agent, keystore, service)
            client.upload_agent()
            return client

        def keyed(client):
            client.upload_encryption_key(client.new_encryption_key())
            return client

        root = new_client()
        root_key = root.new_encryption_key()
        root.upload_encryption_key(root_key)
        relay_client = new_client()
        relay_key = relay_client.new_encryption_key()
        relay_client.upload_encryption_key(relay_key)
        participant = new_client()
        plan = plan_tree([str(participant.agent.id)], group_size=4,
                         seed="resume")
        scheme = AdditiveSharing(share_count=3, modulus=433)
        aggs = plan.build_aggregations(
            title="resume", vector_dimension=4, modulus=433,
            masking_scheme=FullMasking(433), leaf_sharing=scheme,
            recipient_encryption_scheme=SodiumEncryption(),
            committee_encryption_scheme=SodiumEncryption(),
            root_recipient=root.agent.id, root_recipient_key=root_key,
            relays=[(relay_client.agent.id, relay_key)],
        )
        leaf = plan.leaves()[0]
        root_node = plan.root
        clerks = {node.path: [keyed(new_client()) for _ in range(3)]
                  for node in plan.nodes()}
        for node in plan.nodes():
            owner = root if node.is_root else relay_client
            owner.upload_aggregation(aggs[node.path])
            owner.begin_aggregation_with(
                node.aggregation_id,
                [c.agent.id for c in clerks[node.path]])
        participant.participate([1, 2, 3, 4], leaf.aggregation_id)
        relay_client.end_aggregation(leaf.aggregation_id)
        for clerk in clerks[leaf.path]:
            clerk.run_chores(-1)

        # first attempt: seal + journal + upload, then "crash" before
        # the reap — exactly what relay_up does up to the lost ack
        journal = ParticipationJournal(str(tmp_path))
        total = relay.await_masked(relay_client, leaf.aggregation_id,
                                   deadline=30)
        participation = relay_client.new_participation(
            [int(v) for v in total.values], root_node.aggregation_id)
        participation.forwarded_masks = list(total.mask_encryptions)
        journal.record(participation)
        relay_client.upload_participation(participation)  # ack "lost"

        # restart: relay_up with the journal replays the SAME bytes
        relay.relay_up(relay_client, leaf.aggregation_id,
                       root_node.aggregation_id, deadline=30,
                       journal=journal)
        assert metrics.counter_report().get(
            "server.participation.replayed", 0) >= 1
        assert journal.load(relay_client.agent.id,
                            root_node.aggregation_id) is None  # reaped
        status = root.service.get_aggregation_status(
            root.agent, root_node.aggregation_id)
        assert status.number_of_participations == 1  # never double-counted

        # the round still completes exactly
        root.end_aggregation(root_node.aggregation_id)
        for clerk in clerks[root_node.path]:
            clerk.run_chores(-1)
        out = root.await_result(root_node.aggregation_id, deadline=30)
        np.testing.assert_array_equal(out.positive().values, [1, 2, 3, 4])


class TestLeafFailureModes:
    def test_dead_clerk_degrades_leaf_root_stays_exact(self):
        """Packed Shamir leaf loses one clerk: the sweeper declares the
        leaf degraded, the relay completes from the surviving quorum,
        and the ROOT round reveals bit-exactly."""
        report = run_tree_round(
            inputs_for(8, seed=3), group_size=4, sharing="packed",
            masking="full", seed=3, dead_clerks_leaf=1)
        leaf_states = {path: s for path, s in report["node_states"].items()
                       if s.get("group") is not None}
        assert report["node_states"][report["dead_clerk_leaf"]][
            "state"] == "degraded"
        assert report["root_state"] == "revealed"
        assert report["exact"] is True
        assert report["flat_exact"] is True
        # the other leaf was untouched (disjoint committees)
        others = [s for path, s in leaf_states.items()
                  if path != report["dead_clerk_leaf"]]
        assert all(s["state"] == "ready" for s in others)

    def test_failed_leaf_fails_root_naming_the_leaf(self):
        """Additive leaf loses a clerk: unrecoverable — the leaf goes
        terminal failed and the sweeper's tree propagation fails the
        ROOT with a machine-readable reason naming the child round."""
        report = run_tree_round(
            inputs_for(8, seed=3), group_size=4, sharing="additive",
            masking="full", seed=3, dead_clerks_leaf=1,
            flat_reference=False)
        dead_leaf = report["dead_clerk_leaf"]
        leaf_state = report["node_states"][dead_leaf]
        assert leaf_state["state"] == "failed"
        assert report["root_state"] == "failed"
        assert report["failure"]["type"] == "RoundFailed"
        # machine-readable: the root's reason names the failed child
        failed_leaf_id = [
            str(s) for s in report["root_children"]
        ]
        assert "child round" in report["root_reason"]
        assert any(cid in report["root_reason"] for cid in failed_leaf_id)
        assert "additive sharing cannot recover" in report["root_reason"]


class TestLinkage:
    def test_round_documents_expose_parent_and_children(self):
        """RoundStatus + the /statusz rounds table carry the tree
        linkage: the root names its children, each leaf its parent — a
        stuck tree is diagnosable from any worker."""
        service = new_memory_server()
        report = run_tree_round(
            inputs_for(6, seed=9), group_size=3, sharing="additive",
            masking="full", seed=9, service=service,
            flat_reference=False)
        assert report["exact"] is True
        docs = service.server.aggregation_store.list_round_states()
        by_id = {d["aggregation"]: d for d in docs}
        roots = [d for d in docs if d.get("children")]
        assert len(roots) == 1
        root_doc = roots[0]
        assert root_doc.get("parent") is None
        assert len(root_doc["children"]) == report["groups"]
        for child_id in root_doc["children"]:
            child = by_id[child_id]
            assert child["parent"] == root_doc["aggregation"]
            assert child["level"] == 1
            assert child["group"] is not None
        # the /statusz table rows carry the linkage too
        table = lifecycle.rounds_report(service.server, limit=16)
        rows = {r["aggregation"]: r for r in table["recent"]}
        assert rows[root_doc["aggregation"]]["children"] == \
            root_doc["children"]
        assert rows[root_doc["children"][0]]["parent"] == \
            root_doc["aggregation"]

    def test_round_status_serde_carries_linkage(self):
        from sda_tpu.protocol import AggregationId, RoundStatus

        status = RoundStatus(
            aggregation=AggregationId("11111111-1111-1111-1111-111111111111"),
            state="clerking",
            parent="22222222-2222-2222-2222-222222222222",
            children=["33333333-3333-3333-3333-333333333333"],
        )
        back = RoundStatus.from_obj(status.to_obj())
        assert str(back.parent) == "22222222-2222-2222-2222-222222222222"
        assert [str(c) for c in back.children] == [
            "33333333-3333-3333-3333-333333333333"]
