"""Gray-failure resilience plane tests (docs/robustness.md).

Four layers under test:

- chaos: the ``brownout`` / ``flap`` / ``partition`` failpoint kinds and
  the composable multi-spec syntax;
- fleet health: heartbeat rows, the single-winner suspect/dead CAS, and
  proactive lease recall — raced across two store handles on all four
  backends (the sharing shape of two ``sdad`` OS processes);
- straggler hedging: a suspect holder's job is speculatively re-leased
  exactly once, and the result commit stays single-winner;
- brownout survival: the store circuit breaker's closed/open/half-open
  lifecycle, retry budget, and 503 + Retry-After shed at the HTTP seam.

The capstone drills SIGKILL a real fleet worker holding leases mid-round
(no drain) and assert a peer completes the round bit-exactly via
heartbeat-recall well before the lease-expiry fallback — on sqlite and
jsonfs, the two in-image cross-process stores.
"""

import threading
import time

import numpy as np
import pytest

from sda_tpu import chaos
from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    ClerkingResult,
    Committee,
    NoMasking,
    NotFound,
    Participation,
    ParticipationId,
    RoundExpired,
    RoundFailed,
    ServerError,
    Snapshot,
    SnapshotId,
    SodiumEncryption,
    StoreUnavailable,
)
from sda_tpu.server import (
    SdaServerService,
    new_jsonfs_server,
    new_mongo_server,
    new_sqlite_server,
)
from sda_tpu.server import health
from sda_tpu.server.breaker import (
    BreakerStore,
    CircuitBreaker,
    wrap_server_stores,
)
from sda_tpu.server.core import SdaServer

from util import mock_encryption, new_agent, new_full_agent

BACKENDS = ["memory", "sqlite", "jsonfs", "fakemongo"]


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    chaos.set_identity(None)
    yield
    chaos.reset()
    chaos.set_identity(None)


def _two_handles(backend, tmp_path):
    """Two INDEPENDENT service handles over one shared backend — the
    sharing shape of two fleet worker processes (test_fleet.py)."""
    if backend == "memory":
        from sda_tpu.server.memory import (
            MemoryAggregationsStore,
            MemoryAgentsStore,
            MemoryAuthTokensStore,
            MemoryClerkingJobsStore,
        )

        stores = dict(
            agents_store=MemoryAgentsStore(),
            auth_tokens_store=MemoryAuthTokensStore(),
            aggregation_store=MemoryAggregationsStore(),
            clerking_job_store=MemoryClerkingJobsStore(),
        )
        return SdaServerService(SdaServer(**stores)), \
            SdaServerService(SdaServer(**stores))
    if backend == "sqlite":
        path = tmp_path / "shared.db"
        return new_sqlite_server(path), new_sqlite_server(path)
    if backend == "jsonfs":
        root = tmp_path / "shared-jfs"
        return new_jsonfs_server(root), new_jsonfs_server(root)
    from fake_mongo import FakeDatabase

    db = FakeDatabase()
    return new_mongo_server(db), new_mongo_server(db)


def _world(service, clerks=2, participants=2):
    recipient, _ = new_full_agent(service)
    committee = [new_full_agent(service) for _ in range(clerks)]
    agg = Aggregation(
        id=AggregationId.random(), title="gray", vector_dimension=4,
        modulus=433, recipient=recipient.id,
        recipient_key=committee[0][1].body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=clerks,
                                                 modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    service.create_committee(recipient, Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for (a, k) in committee],
    ))
    for i in range(participants):
        agent = new_agent()
        service.create_agent(agent, agent)
        service.create_participation(agent, Participation(
            id=ParticipationId.random(), participant=agent.id,
            aggregation=agg.id, recipient_encryption=None,
            clerk_encryptions=[(a.id, mock_encryption(bytes([i])))
                               for (a, _) in committee],
        ))
    return recipient, committee, agg


# ---------------------------------------------------------------------------
# chaos: gray failpoint kinds


def test_brownout_mixes_errors_and_delays_deterministically():
    """Inside the window a brownout hit errors with probability `rate`
    and delays otherwise; the same seed replays the same split."""
    def schedule(seed):
        chaos.reset()
        chaos.configure("fp.brown", brownout=0.0, rate=0.5, window=60.0,
                        seed=seed)
        kinds = []
        for _ in range(32):
            action = chaos.evaluate("fp.brown", kinds=("error", "delay"))
            kinds.append(action.kind)
        return kinds

    a, b = schedule(7), schedule(7)
    assert a == b, "same (seed, name) must replay the same schedule"
    assert set(a) == {"error", "delay"}, a
    assert schedule(8) != a, "different seed must change the schedule"


def test_brownout_heals_after_window():
    chaos.configure("fp.heal", brownout=0.0, rate=1.0, window=30.0, seed=0)
    assert chaos.evaluate("fp.heal", kinds=("error", "delay")) is not None
    point = chaos.registry._points["fp.heal"]
    point.armed_at -= 31.0  # wind the clock: the window has elapsed
    assert chaos.evaluate("fp.heal", kinds=("error", "delay")) is None
    # a healed hit consumed nothing: the schedule only describes the
    # degraded phase
    assert point.hits == 1 and point.triggers == 1


def test_flap_cycles_down_and_up():
    chaos.configure("fp.flap", flap=0.0, rate=1.0, window=10.0, up=10.0,
                    seed=0)
    point = chaos.registry._points["fp.flap"]
    assert chaos.evaluate("fp.flap", kinds=("error", "delay")) is not None
    point.armed_at -= 10.0  # now inside the healthy (up) phase
    assert chaos.evaluate("fp.flap", kinds=("error", "delay")) is None
    point.armed_at -= 10.0  # next down phase of the cycle
    assert chaos.evaluate("fp.flap", kinds=("error", "delay")) is not None


def test_brownout_honors_every():
    chaos.configure("fp.every", brownout=0.0, rate=1.0, window=60.0,
                    every=3, seed=0)
    fired = [chaos.evaluate("fp.every", kinds=("error", "delay"))
             is not None for _ in range(9)]
    assert fired == [True, False, False] * 3


def test_flap_requires_window_and_up():
    with pytest.raises(ValueError, match="flap"):
        chaos.configure("fp.bad", flap=0.01)
    with pytest.raises(ValueError, match="brownout"):
        chaos.configure("fp.bad2", brownout=0.01)


def test_partition_scoped_to_node_identity():
    """A node-scoped partition severs exactly the named process: one
    fleet-wide spec, one partitioned worker."""
    chaos.configure("fp.part", partition=True, node="w0", window=None)
    chaos.set_identity("w1")
    assert chaos.evaluate("fp.part") is None
    chaos.set_identity("w0")
    action = chaos.evaluate("fp.part")
    assert action is not None and action.kind == "error"
    with pytest.raises(chaos.PartitionedFault):
        chaos.fail("fp.part")
    # heals after the window
    chaos.configure("fp.part2", partition=True, node="w0", window=30.0)
    chaos.registry._points["fp.part2"].armed_at -= 31.0
    assert chaos.evaluate("fp.part2") is None


def test_partition_scoped_to_agent():
    chaos.configure("fp.agent", partition=True, agent="alice")
    assert chaos.evaluate("fp.agent", ctx={"agent": "bob"}) is None
    assert chaos.evaluate("fp.agent") is None  # no ctx: no match
    assert chaos.evaluate("fp.agent", ctx={"agent": "alice"}) is not None


def test_partition_returns_503_class_error_over_http():
    """An agent-scoped partition at the HTTP seam 500s exactly that
    agent's requests; everyone else sails through (and the retrying
    client of the partitioned agent eventually gives up with the
    Retry-After-free ServerError)."""
    from sda_tpu.http import SdaHttpClient, SdaHttpServer
    from sda_tpu.server import new_memory_server

    service = new_memory_server()
    server = SdaHttpServer(service, bind="127.0.0.1:0")
    server.start_background()
    try:
        alice, bob = new_agent(), new_agent()
        proxy = SdaHttpClient(server.address, token="gray-test",
                              max_retries=1, backoff_base=0.0,
                              backoff_cap=0.0, deadline=5.0)
        for agent in (alice, bob):
            proxy.create_agent(agent, agent)
        chaos.configure("http.server.request", partition=True,
                        agent=str(alice.id))
        assert proxy.get_agent(bob, bob.id) is not None
        with pytest.raises(ServerError):
            proxy.get_agent(alice, alice.id)
    finally:
        chaos.reset()
        server.shutdown()


# ---------------------------------------------------------------------------
# chaos: composable spec syntax


def test_spec_multi_target_and_gray_kinds():
    specs = chaos.parse_spec(
        "a.x,a.y=brownout:0.02,rate=0.7,for=5;"
        "b=partition,node=w0,agent=alice,for=3;"
        "c=flap:0.01,for=1,up=2,times=4")
    assert set(specs) == {"a.x", "a.y", "b", "c"}
    assert specs["a.x"]["brownout"] == 0.02
    assert specs["a.x"]["window"] == 5.0 and specs["a.x"]["rate"] == 0.7
    assert specs["b"]["partition"] is True and specs["b"]["node"] == "w0"
    assert specs["b"]["agent"] == "alice"
    assert specs["c"]["flap"] == 0.01 and specs["c"]["up"] == 2.0
    chaos.configure_from_spec("a.x,a.y=brownout:0.02,rate=1.0,for=60", seed=3)
    assert chaos.evaluate("a.y", kinds=("error", "delay")) is not None


def test_spec_conflicts_rejected_with_clear_error():
    with pytest.raises(ValueError, match="conflict.*'a'"):
        chaos.parse_spec("a=error;a=kill")
    with pytest.raises(ValueError, match="conflict.*'dup'"):
        chaos.configure_from_specs(["dup=error", "x=kill;dup=drop"])
    # a rejected merge arms NOTHING (no half-applied drill)
    assert chaos.evaluate("x") is None
    with pytest.raises(ValueError, match="unknown key"):
        chaos.parse_spec("a=error,bogus=1")


def test_cli_chaos_spec_flags_compose():
    """`sdad`/`sda-sim` accept repeated --chaos-spec flags (argparse
    append) and the merge rejects cross-flag conflicts."""
    from sda_tpu.cli.serverd import build_parser as sdad_parser
    from sda_tpu.cli.sim import build_parser as sim_parser

    args = sdad_parser().parse_args(
        ["--memory", "--chaos-spec", "a=error", "--chaos-spec",
         "b=brownout:0.01,for=2", "httpd"])
    assert args.chaos_spec == ["a=error", "b=brownout:0.01,for=2"]
    args = sim_parser().parse_args(
        ["--chaos", "--chaos-spec", "a=error", "--chaos-spec", "b=kill"])
    assert args.chaos_spec == ["a=error", "b=kill"]
    with pytest.raises(ValueError, match="conflict"):
        chaos.configure_from_specs(args.chaos_spec + ["a=drop"])


# ---------------------------------------------------------------------------
# fleet health: heartbeats, the suspect/dead CAS, lease recall


@pytest.mark.parametrize("backend", BACKENDS)
def test_heartbeat_roundtrip_and_cas(backend, tmp_path):
    a, b = _two_handles(backend, tmp_path)
    store_a = a.server.clerking_job_store
    store_b = b.server.clerking_job_store
    writer = health.HeartbeatWriter(store_a, "w0")
    writer.beat(now=100.0)
    doc = store_b.get_worker_heartbeat("w0")  # peer sees it (shared store)
    assert doc["state"] == "alive" and doc["ts"] == 100.0
    assert [d["node"] for d in store_b.list_worker_heartbeats()] == ["w0"]
    # CAS: only the matching FROM state transitions
    suspect = dict(doc, state="suspect")
    assert store_b.transition_worker_state("w0", ("dead",), suspect) is False
    assert store_b.transition_worker_state("w0", ("alive",), suspect) is True
    assert store_a.get_worker_heartbeat("w0")["state"] == "suspect"
    # the worker's next beat revives it (plain upsert beats the verdict)
    writer.beat(now=101.0)
    assert store_b.get_worker_heartbeat("w0")["state"] == "alive"
    # a clean stop leaves the terminal 'drained' state
    writer.stop(drained=True)
    assert store_b.get_worker_heartbeat("w0")["state"] == "drained"


@pytest.mark.parametrize("backend", BACKENDS)
def test_raced_dead_declaration_recalls_leases_exactly_once(backend,
                                                            tmp_path):
    """Two competing sweepers over one shared store: the dead CAS is
    single-winner, the dead node's lease is recalled exactly once, and
    the job is reissued to exactly one subsequent poller — no
    double-reissue, no orphaned job."""
    a, b = _two_handles(backend, tmp_path)
    a.server.node_id, b.server.node_id = "w1", "w2"
    recipient, committee, agg = _world(a, clerks=1, participants=1)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    clerk = committee[0][0]
    store_a = a.server.clerking_job_store
    store_b = b.server.clerking_job_store

    # the doomed worker w0 beats once, leases the job, then goes silent
    health.HeartbeatWriter(store_a, "w0").beat(now=1000.0)
    leased = store_a.lease_clerking_job(clerk.id, lease_seconds=300.0,
                                        now=1000.0, owner="w0")
    assert leased is not None
    job = leased[0]
    assert store_b.lease_clerking_job(clerk.id, lease_seconds=300.0,
                                      now=1001.0) is None  # held

    barrier = threading.Barrier(2)
    results = []
    lock = threading.Lock()

    def sweep(handle):
        barrier.wait()
        actions = health.sweep_worker_health(
            handle.server, now=1010.0, suspect_after_s=2.0,
            dead_after_s=5.0)
        with lock:
            results.append(actions)

    threads = [threading.Thread(target=sweep, args=(s,)) for s in (a, b)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    winners = [acts for acts in results if acts]
    assert len(winners) == 1, f"dead CAS must be single-winner: {results}"
    assert winners[0][0]["to"] == "dead"
    assert winners[0][0]["recalled_leases"] == 1
    assert store_a.get_worker_heartbeat("w0")["state"] == "dead"

    # recalled: exactly one poller gets the job back, immediately
    grants = []

    def poll(store, owner):
        barrier.wait()
        grants.append(store.lease_clerking_job(
            clerk.id, lease_seconds=300.0, now=1011.0, owner=owner))

    barrier.reset()
    threads = [threading.Thread(target=poll, args=(store_a, "w1")),
               threading.Thread(target=poll, args=(store_b, "w2"))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    granted = [g for g in grants if g is not None]
    assert len(granted) == 1, f"recall must not double-reissue: {grants}"
    assert granted[0][0].id == job.id
    # the round still completes: the new holder's result lands
    b.server.clerking_job_store.create_clerking_result(ClerkingResult(
        job=job.id, clerk=clerk.id, encryption=mock_encryption(b"done")))
    assert store_a.list_results(snap.id) == [job.id]
    # a second sweep finds nothing left to do
    assert health.sweep_worker_health(a.server, now=1012.0,
                                     suspect_after_s=2.0,
                                     dead_after_s=5.0) == []


@pytest.mark.parametrize("backend", BACKENDS)
def test_recall_spares_other_owners_and_done_jobs(backend, tmp_path):
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a, clerks=2, participants=1)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    (c0, _), (c1, _) = committee
    store = a.server.clerking_job_store
    j0, _ = store.lease_clerking_job(c0.id, 300.0, now=100.0, owner="w0")
    j1, _ = store.lease_clerking_job(c1.id, 300.0, now=100.0, owner="w1")
    # w0's DONE job must not come back either
    store.create_clerking_result(ClerkingResult(
        job=j0.id, clerk=c0.id, encryption=mock_encryption(b"r")))
    assert store.recall_clerking_job_leases("w0") == 0  # done: no lease left
    assert store.recall_clerking_job_leases("w1") == 1
    assert store.recall_clerking_job_leases("w1") == 0  # idempotent
    # w1's job is pollable again; w0's stays done
    regrant = b.server.clerking_job_store.lease_clerking_job(
        c1.id, 300.0, now=101.0)
    assert regrant is not None and regrant[0].id == j1.id
    assert b.server.clerking_job_store.lease_clerking_job(
        c0.id, 300.0, now=101.0) is None


# ---------------------------------------------------------------------------
# straggler hedging


@pytest.mark.parametrize("backend", BACKENDS)
def test_hedge_targets_only_suspect_holders(backend, tmp_path):
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a, clerks=1, participants=1)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    clerk = committee[0][0]
    store_a = a.server.clerking_job_store
    store_b = b.server.clerking_job_store
    job, _ = store_a.lease_clerking_job(clerk.id, 300.0, now=100.0,
                                        owner="w0")
    # holder healthy: no hedge
    assert store_b.hedge_clerking_job(clerk.id, ["w9"], 300.0,
                                      now=101.0, owner="w1") is None
    assert store_b.hedge_clerking_job(clerk.id, [], 300.0,
                                      now=101.0, owner="w1") is None
    # holder suspect: hedged exactly once — the second hedger sees the
    # lease now owned by w1 (not suspect) and backs off
    hedged = store_b.hedge_clerking_job(clerk.id, ["w0"], 300.0,
                                        now=101.0, owner="w1")
    assert hedged is not None and hedged[0].id == job.id
    assert store_a.hedge_clerking_job(clerk.id, ["w0"], 300.0,
                                      now=102.0, owner="w2") is None
    # a lapsed lease is NOT hedged (the plain reissue path owns it)
    assert store_b.hedge_clerking_job(clerk.id, ["w1"], 300.0,
                                      now=500.0, owner="w2") is None


@pytest.mark.parametrize("backend", BACKENDS)
def test_hedged_commit_is_single_winner(backend, tmp_path):
    """Original holder and hedged copy both upload: one result row, no
    duplicate, no error — duplicate partial sums are impossible."""
    a, b = _two_handles(backend, tmp_path)
    recipient, committee, agg = _world(a, clerks=1, participants=1)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    clerk = committee[0][0]
    job, _ = a.server.clerking_job_store.lease_clerking_job(
        clerk.id, 300.0, now=100.0, owner="w0")
    hedged = b.server.clerking_job_store.hedge_clerking_job(
        clerk.id, ["w0"], 300.0, now=101.0, owner="w1")
    assert hedged is not None
    result = ClerkingResult(job=job.id, clerk=clerk.id,
                            encryption=mock_encryption(b"sum"))
    b.server.clerking_job_store.create_clerking_result(result)
    # the straggler wakes up and uploads too: idempotent no-op
    a.server.clerking_job_store.create_clerking_result(result)
    assert a.server.clerking_job_store.list_results(snap.id) == [job.id]
    # and the job never comes back
    assert a.server.clerking_job_store.lease_clerking_job(
        clerk.id, 300.0, now=102.0) is None


def test_server_poll_hedges_via_heartbeat_table(tmp_path):
    """The server-level wiring: an empty lease poll consults the
    heartbeat table and hedges a stale holder's job."""
    a, b = _two_handles("sqlite", tmp_path)
    a.server.node_id, b.server.node_id = "w0", "w1"
    a.server.clerking_lease_seconds = 300.0
    b.server.clerking_lease_seconds = 300.0
    b.server.hedge_suspect_after_s = 1.0
    recipient, committee, agg = _world(a, clerks=1, participants=1)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    a.create_snapshot(recipient, snap)
    clerk = committee[0][0]
    # w0 heartbeats, leases the job through the SERVER path, goes silent
    health.HeartbeatWriter(a.server.clerking_job_store, "w0").beat(
        now=time.time() - 30.0)
    job = a.server.poll_clerking_job(clerk.id)
    assert job is not None
    # w1's poll: nothing unleased, but w0 is stale -> hedged
    hedged = b.server.poll_clerking_job(clerk.id)
    assert hedged is not None and hedged.id == job.id
    from sda_tpu.utils import metrics

    assert metrics.counter_report("server.job.").get("server.job.hedged")


# ---------------------------------------------------------------------------
# store circuit breaker


class _FlakyStore:
    def __init__(self):
        self.failing = False
        self.calls = 0

    def ping(self):
        self.calls += 1
        if self.failing:
            raise OSError("store down")
        return None

    def lookup(self):
        self.calls += 1
        if self.failing:
            raise OSError("store down")
        raise NotFound("no such thing")


def test_breaker_opens_sheds_and_recovers():
    breaker = CircuitBreaker(threshold=3, recovery_s=30.0,
                             failure_window_s=60.0, budget_rate=0.0,
                             budget_cap=0.0)
    store = BreakerStore(_FlakyStore(), breaker)
    store._inner.failing = True
    for _ in range(3):
        with pytest.raises(OSError):
            store.ping()
    assert breaker.state == "open"
    # open: shed WITHOUT touching the store, with a Retry-After hint
    calls = store._inner.calls
    with pytest.raises(StoreUnavailable) as exc:
        store.ping()
    assert store._inner.calls == calls
    assert 0 < exc.value.retry_after <= 30.0
    # recovery elapses -> half-open: exactly one probe goes through
    breaker._opened_at -= 31.0
    store._inner.failing = False
    assert store.ping() is None
    assert breaker.state == "closed"
    report = breaker.report()
    assert report["times_opened"] == 1
    assert report["time_to_recover_s"] > 0


def test_breaker_windowed_failures_survive_interleaved_successes():
    """A browning-out store fails GRAY: successes between the failures
    must not reset the verdict (the consecutive-counter trap)."""
    breaker = CircuitBreaker(threshold=3, recovery_s=30.0,
                             failure_window_s=60.0, budget_rate=0.0,
                             budget_cap=0.0)
    store = BreakerStore(_FlakyStore(), breaker)
    for _ in range(2):
        store._inner.failing = True
        with pytest.raises(OSError):
            store.ping()
        store._inner.failing = False
        store.ping()  # interleaved success
    store._inner.failing = True
    with pytest.raises(OSError):
        store.ping()
    assert breaker.state == "open", \
        "3 failures in the window must trip regardless of successes"


def test_breaker_failed_probe_reopens():
    breaker = CircuitBreaker(threshold=1, recovery_s=30.0,
                             budget_rate=0.0, budget_cap=0.0)
    store = BreakerStore(_FlakyStore(), breaker)
    store._inner.failing = True
    with pytest.raises(OSError):
        store.ping()
    assert breaker.state == "open"
    breaker._opened_at -= 31.0
    with pytest.raises(OSError):
        store.ping()  # the probe itself fails
    assert breaker.state == "open" and breaker.times_opened == 2


def test_breaker_retry_budget_absorbs_blips():
    """With budget, a one-shot failure is retried immediately and never
    counts toward the verdict; without tokens it does."""
    breaker = CircuitBreaker(threshold=1, recovery_s=1.0,
                             budget_rate=0.0, budget_cap=1.0)

    class OneShot:
        def __init__(self):
            self.fails_left = 1

        def op(self):
            if self.fails_left:
                self.fails_left -= 1
                raise OSError("blip")
            return "ok"

    store = BreakerStore(OneShot(), breaker)
    assert store.op() == "ok"  # retried on the budget token
    assert breaker.state == "closed"
    # budget exhausted (cap 1, refill 0): the next blip trips threshold=1
    store._inner.fails_left = 1
    with pytest.raises(OSError):
        store.op()
    assert breaker.state == "open"


def test_breaker_semantic_errors_pass_through_uncounted():
    breaker = CircuitBreaker(threshold=1, recovery_s=1.0,
                             budget_rate=0.0, budget_cap=0.0)
    store = BreakerStore(_FlakyStore(), breaker)
    for _ in range(5):
        with pytest.raises(NotFound):
            store.lookup()
    assert breaker.state == "closed", \
        "a NotFound is an answer, not an infrastructure failure"


def test_breaker_open_maps_to_503_retry_after_over_http():
    """The HTTP seam: an open breaker sheds with 503 + Retry-After and
    zero store touches; the retrying client converges once it closes."""
    import requests

    from sda_tpu.http import SdaHttpServer
    from sda_tpu.server import new_memory_server

    service = new_memory_server()
    breaker = wrap_server_stores(service.server, CircuitBreaker(
        threshold=1, recovery_s=30.0, budget_rate=0.0, budget_cap=0.0))
    server = SdaHttpServer(service, bind="127.0.0.1:0")
    server.start_background()
    try:
        agent = new_agent()
        created = requests.post(
            server.address + "/v1/agents/me", json=agent.to_obj(),
            auth=(str(agent.id), "token"), timeout=10)
        assert created.status_code == 201
        # trip the breaker through the store seam
        chaos.configure("store.poll_clerking_job", error=True, times=1)
        with pytest.raises(Exception):
            service.server.clerking_job_store.poll_clerking_job(agent.id)
        assert breaker.state == "open"
        shed = requests.get(
            server.address + f"/v1/agents/{agent.id}",
            auth=(str(agent.id), "token"), timeout=10)
        assert shed.status_code == 503
        assert float(shed.headers["Retry-After"]) > 0
        # recovery: the probe closes it and the route answers again
        breaker._opened_at -= 31.0
        ok = requests.get(
            server.address + f"/v1/agents/{agent.id}",
            auth=(str(agent.id), "token"), timeout=10)
        assert ok.status_code == 200
        assert breaker.state == "closed"
    finally:
        chaos.reset()
        server.shutdown()


# ---------------------------------------------------------------------------
# await_result herd hygiene (satellite: jitter + Retry-After)


class _ScriptedService:
    """get_round_status raises scripted transients, then reports a
    terminal verdict; tracks how often it was polled."""

    def __init__(self, transients, final_state="failed"):
        self.transients = list(transients)
        self.final_state = final_state
        self.polls = 0

    def get_round_status(self, caller, aggregation):
        self.polls += 1
        if self.transients:
            raise self.transients.pop(0)
        from sda_tpu.protocol import RoundStatus

        return RoundStatus(
            aggregation=aggregation, state=self.final_state, snapshot=None,
            scheme="additive", committee_size=1,
            reconstruction_threshold=1, results=0, dead_clerks=[],
            reason="scripted", deadline_at=None, updated_at=None,
            history=[])

    def get_aggregation_status(self, caller, aggregation):
        return None


def _client_with(service):
    from sda_tpu.client import SdaClient
    from sda_tpu.crypto import MemoryKeystore

    return SdaClient(new_agent(), MemoryKeystore(), service)


def test_await_result_survives_transients_and_honors_retry_after():
    shed = StoreUnavailable("browning out", retry_after=0.05)
    service = _ScriptedService([shed, shed])
    client = _client_with(service)
    t0 = time.monotonic()
    with pytest.raises(RoundFailed):
        client.await_result(AggregationId.random(), deadline=10.0,
                            poll_interval=0.01)
    elapsed = time.monotonic() - t0
    assert service.polls == 3, "both transients absorbed, verdict on #3"
    # two Retry-After-hinted sleeps, each jittered in [0.5, 1.5) x 0.05
    assert elapsed >= 2 * 0.05 * 0.5


def test_await_result_deadline_survives_endless_transients():
    service = _ScriptedService([ServerError("boom")] * 10_000)
    client = _client_with(service)
    with pytest.raises(RoundExpired, match="deadline"):
        client.await_result(AggregationId.random(), deadline=0.2,
                            poll_interval=0.01)
    assert service.polls > 1


def test_await_result_unbounded_wait_propagates_dead_server():
    """deadline=None tolerates a brownout but must NOT spin forever on a
    permanently dead server: a long unbroken transient streak (each
    element already past the transport's own retry budget) propagates."""
    service = _ScriptedService([ServerError("connection refused")] * 10_000)
    client = _client_with(service)
    with pytest.raises(ServerError, match="connection refused"):
        client.await_result(AggregationId.random(), deadline=None,
                            poll_interval=0.001)
    assert service.polls == 8, "streak bound: 8 consecutive, then raise"


def test_await_result_jitter_is_seeded_per_agent():
    """The jitter RNG is deterministic per (agent, aggregation): the
    same client replays the same schedule, two clients differ."""
    import random

    agg = AggregationId.random()
    client_a = _client_with(_ScriptedService([]))
    client_b = _client_with(_ScriptedService([]))
    draws = {
        name: [random.Random(f"{c.agent.id}:{agg}").random()
               for _ in range(4)]
        for name, c in (("a", client_a), ("b", client_b))
    }
    assert draws["a"] == [random.Random(
        f"{client_a.agent.id}:{agg}").random() for _ in range(4)]
    assert draws["a"] != draws["b"]


def test_drained_heartbeat_lands_after_graceful_drain(tmp_path):
    """A SIGTERM'd worker's terminal 'drained' row is written AFTER the
    drain hands leases back (a worker killed mid-drain must look
    stale-alive — diagnosable — never prematurely 'drained')."""
    from sda_tpu.server.fleet import Fleet

    fleet = Fleet(1, ["--sqlite", str(tmp_path / "one.db")],
                  extra_args=["--heartbeat", "0.25", "--job-lease", "5"])
    try:
        fleet.start(timeout_s=120.0)
    finally:
        summaries = fleet.stop()
    assert summaries and summaries[0].get("leaked") == 0
    store = new_sqlite_server(tmp_path / "one.db").server.clerking_job_store
    assert store.get_worker_heartbeat("w0")["state"] == "drained"


# ---------------------------------------------------------------------------
# the capstone: SIGKILL a fleet worker holding leases mid-round


def _run_sigkill_drill(tmp_path, backend_args, lease_seconds=30.0):
    """Two real `sdad` workers over one shared store; w0 grants itself
    every clerking-job lease and is SIGKILLed (no drain); w1's heartbeat
    detector must recall the leases and the round must complete
    bit-exactly well inside the lease-expiry fallback."""
    from sda_tpu.client import SdaClient
    from sda_tpu.crypto import MemoryKeystore, sodium
    from sda_tpu.http import SdaHttpClient
    from sda_tpu.protocol import FullMasking
    from sda_tpu.server.fleet import Fleet

    if not sodium.available():
        pytest.skip("needs libsodium (real crypto round)")

    scheme = AdditiveSharing(share_count=3, modulus=433)
    fleet = Fleet(2, backend_args, extra_args=[
        "--job-lease", str(lease_seconds),
        "--heartbeat", "0.25", "--suspect-after", "0.5",
        "--dead-after", "1.0", "--round-sweep", "0.2",
        "--statusz",
    ])
    kill_to_done_s = None
    try:
        fleet.start(timeout_s=120.0)
        w0, w1 = fleet.addresses["w0"], fleet.addresses["w1"]

        proxy_w1 = SdaHttpClient(w1, token="gray-drill",
                                 max_retries=8, backoff_base=0.01,
                                 backoff_cap=0.1)
        proxy_w0 = SdaHttpClient(w0, token="gray-drill",
                                 max_retries=2, backoff_base=0.01,
                                 backoff_cap=0.05, deadline=10.0)

        def new_client():
            keystore = MemoryKeystore()
            agent = SdaClient.new_agent(keystore)
            return SdaClient(agent, keystore, proxy_w1)

        recipient = new_client()
        recipient.upload_agent()
        recipient_key = recipient.new_encryption_key()
        recipient.upload_encryption_key(recipient_key)
        candidates = {recipient.agent.id: recipient}
        for _ in range(scheme.share_count):
            clerk = new_client()
            clerk.upload_agent()
            clerk.upload_encryption_key(clerk.new_encryption_key())
            candidates[clerk.agent.id] = clerk
        agg = Aggregation(
            id=AggregationId.random(), title="sigkill-drill",
            vector_dimension=4, modulus=scheme.modulus,
            recipient=recipient.agent.id, recipient_key=recipient_key,
            masking_scheme=FullMasking(scheme.modulus),
            committee_sharing_scheme=scheme,
            recipient_encryption_scheme=SodiumEncryption(),
            committee_encryption_scheme=SodiumEncryption(),
        )
        recipient.upload_aggregation(agg)
        recipient.begin_aggregation(agg.id)
        committee = recipient.service.get_committee(recipient.agent, agg.id)
        clerks = [candidates[cid] for cid, _ in committee.clerks_and_keys]

        inputs = np.arange(4 * 4, dtype=np.int64).reshape(4, 4) % 433
        for row in inputs:
            participant = new_client()
            participant.upload_agent()
            participant.participate([int(x) for x in row], agg.id)
        recipient.end_aggregation(agg.id)  # snapshot + job fan-out

        # every clerking job is leased THROUGH w0 — and every poll
        # response is "lost" with the worker, the gray-failure shape:
        # leases live in the shared store, the work never happens
        for clerk in clerks:
            doomed = proxy_w0.get_clerking_job(clerk.agent, clerk.agent.id)
            assert doomed is not None, "w0 must grant each clerk's lease"
        fleet.kill("w0")
        t_kill = time.monotonic()

        # the committee keeps polling via the surviving worker: nothing
        # is pollable until w1's detector declares w0 dead and recalls
        deadline = time.monotonic() + 20.0
        done = False
        while time.monotonic() < deadline and not done:
            for clerk in clerks:
                try:
                    clerk.run_chores(-1)
                except ServerError:
                    pass  # transient while the fleet re-converges
            status = recipient.service.get_aggregation_status(
                recipient.agent, agg.id)
            done = bool(
                status is not None and status.snapshots
                and status.snapshots[0].number_of_clerking_results
                >= scheme.share_count)
            if not done:
                time.sleep(0.05)
        assert done, "round stalled: heartbeat recall never freed the leases"
        kill_to_done_s = time.monotonic() - t_kill

        output = recipient.await_result(agg.id, deadline=10.0)
        expected = inputs.sum(axis=0) % 433
        assert (output.positive().values == expected).all(), \
            "zero lost participations, bit-exact reveal"
        # MTTR: well under the lease-expiry fallback (the pre-heartbeat
        # recovery path would idle ~lease_seconds)
        assert kill_to_done_s < lease_seconds / 2, (
            f"recovered in {kill_to_done_s:.1f}s — not meaningfully "
            f"faster than the {lease_seconds}s lease-expiry fallback")

        # the surviving worker's statusz names the dead peer
        import requests

        statusz = requests.get(w1 + "/statusz", timeout=10).json()
        assert statusz["fleet_health"]["w0"]["state"] == "dead"
        assert statusz["fleet_health"]["w1"]["state"] == "alive"
    finally:
        summaries = fleet.stop()
    # w1 drains clean; w0 was SIGKILLed so it reports killed-or-dead
    by_node = {s.get("node_id"): s for s in summaries if s.get("node_id")}
    assert by_node.get("w1", {}).get("leaked") == 0
    return kill_to_done_s


@pytest.mark.chaos
def test_sigkill_worker_midround_recovers_via_heartbeats_sqlite(tmp_path):
    _run_sigkill_drill(tmp_path, ["--sqlite", str(tmp_path / "shared.db")])


@pytest.mark.chaos
def test_sigkill_worker_midround_recovers_via_heartbeats_jsonfs(tmp_path):
    _run_sigkill_drill(tmp_path, ["--jfs", str(tmp_path / "shared-jfs")])
