"""Property tests for the field kernels — the discipline the reference lacks.

Covers: share∘reconstruct == id for the golden p=433/omega=354/150 vector
(reference fixture: integration-tests/tests/full_loop.rs:55-67), arbitrary
surviving subsets, device-vs-oracle bit-exactness, large-prime limb paths,
PRG range, and scheme-parameter generation.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from sda_tpu.fields import (
    additive_share,
    additive_share_from_randomness,
    chacha,
    combine,
    modmatmul,
    np_modmatmul,
    numtheory,
    oracle,
    packed_reconstruct,
    packed_share,
    packed_share_from_randomness,
    uniform_mod,
)
from sda_tpu.protocol import PackedShamirSharing

GOLDEN = PackedShamirSharing(
    secret_count=3, share_count=8, privacy_threshold=4,
    prime_modulus=433, omega_secrets=354, omega_shares=150,
)


def golden_matrices():
    M = numtheory.packed_share_matrix(3, 8, 4, 433, 354, 150)
    return M


def test_golden_scheme_validates():
    numtheory.validate_packed_scheme(3, 8, 4, 433, 354, 150)
    with pytest.raises(ValueError):
        numtheory.validate_packed_scheme(3, 8, 5, 433, 354, 150)  # m2 not pow2
    with pytest.raises(ValueError):
        numtheory.validate_packed_scheme(3, 8, 4, 433, 354, 151)  # wrong order


def test_packed_share_reconstruct_roundtrip_all_indices():
    key = jax.random.PRNGKey(0)
    secrets = jnp.array([1, 2, 3, 4], dtype=jnp.int64)
    M = jnp.asarray(golden_matrices())
    shares = packed_share(key, secrets, M, prime=433, secret_count=3, privacy_threshold=4)
    assert shares.shape == (8, 2)  # 8 clerks, ceil(4/3)=2 batches

    L = numtheory.packed_reconstruct_matrix(3, 8, 4, 433, 354, 150, tuple(range(8)))
    out = packed_reconstruct(shares, jnp.asarray(L), prime=433, dimension=4)
    np.testing.assert_array_equal(np.asarray(out), [1, 2, 3, 4])


@pytest.mark.parametrize("subset", [
    (0, 1, 2, 3, 4, 5, 6),       # minimal: t+k = 7
    (1, 3, 4, 5, 6, 7, 0),       # order should not matter
    (7, 6, 5, 4, 3, 2, 1),
    (0, 1, 2, 3, 4, 5, 6, 7),    # superset
])
def test_packed_reconstruct_from_subsets(subset):
    """Fault tolerance: any t+k of n shares reconstruct (crypto.rs:146-153)."""
    key = jax.random.PRNGKey(42)
    secrets = jnp.array([10, 20, 30, 40, 50], dtype=jnp.int64)
    M = jnp.asarray(golden_matrices())
    shares = packed_share(key, secrets, M, prime=433, secret_count=3, privacy_threshold=4)
    L = numtheory.packed_reconstruct_matrix(3, 8, 4, 433, 354, 150, subset)
    picked = jnp.stack([shares[i] for i in subset])
    out = packed_reconstruct(picked, jnp.asarray(L), prime=433, dimension=5)
    np.testing.assert_array_equal(np.asarray(out), [10, 20, 30, 40, 50])


def test_packed_reconstruct_too_few_shares():
    with pytest.raises(ValueError):
        numtheory.packed_reconstruct_matrix(3, 8, 4, 433, 354, 150, (0, 1, 2, 3, 4, 5))


def test_additivity_of_shares():
    """Share-wise sums reconstruct to the sum of secrets — the protocol's core
    linearity (clerk combine, combiner.rs:15-30)."""
    key1, key2 = jax.random.split(jax.random.PRNGKey(7))
    a = jnp.array([1, 2, 3, 4], dtype=jnp.int64)
    b = jnp.array([1, 2, 3, 4], dtype=jnp.int64)
    M = jnp.asarray(golden_matrices())
    sa = packed_share(key1, a, M, prime=433, secret_count=3, privacy_threshold=4)
    sb = packed_share(key2, b, M, prime=433, secret_count=3, privacy_threshold=4)
    summed = combine(jnp.stack([sa, sb]), modulus=433)
    L = numtheory.packed_reconstruct_matrix(3, 8, 4, 433, 354, 150, tuple(range(8)))
    out = packed_reconstruct(summed, jnp.asarray(L), prime=433, dimension=4)
    np.testing.assert_array_equal(np.asarray(out), [2, 4, 6, 8])


def test_device_matches_oracle_bit_exact():
    """Same randomness -> identical shares from jnp kernels and numpy oracle."""
    rng = np.random.default_rng(0)
    secrets = rng.integers(0, 433, size=17)
    B = -(-17 // 3)
    randomness = rng.integers(0, 433, size=(4, B))
    M = jnp.asarray(golden_matrices())
    dev = packed_share_from_randomness(
        jnp.asarray(secrets), jnp.asarray(randomness), M, prime=433, secret_count=3
    )
    orc = oracle.packed_share_from_randomness(secrets, randomness, GOLDEN)
    np.testing.assert_array_equal(np.asarray(dev), orc)

    # additive path: device kernel vs oracle on identical draws
    draws = rng.integers(0, 433, size=(2, 17))
    dev_add = additive_share_from_randomness(
        jnp.asarray(secrets), jnp.asarray(draws), modulus=433
    )
    orc_add = oracle.additive_share_from_randomness(secrets, draws, 433)
    np.testing.assert_array_equal(np.asarray(dev_add), orc_add)
    np.testing.assert_array_equal(oracle.combine(orc_add, 433), secrets % 433)


def test_additive_share_reconstruct():
    key = jax.random.PRNGKey(3)
    secrets = jnp.arange(100, dtype=jnp.int64) % 433
    shares = additive_share(key, secrets, share_count=5, modulus=433)
    assert shares.shape == (5, 100)
    np.testing.assert_array_equal(np.asarray(combine(shares, modulus=433)), np.asarray(secrets))
    # every share uniform-ish in range
    assert int(shares.min()) >= 0 and int(shares.max()) < 433


def test_vmapped_participants():
    """Participant parallelism = vmap over the leading axis (SURVEY §2.4)."""
    key = jax.random.PRNGKey(9)
    P, d = 6, 10
    secrets = jnp.tile(jnp.arange(d, dtype=jnp.int64)[None, :], (P, 1))
    keys = jax.random.split(key, P)
    M = jnp.asarray(golden_matrices())
    share_fn = lambda k, s: packed_share(k, s, M, prime=433, secret_count=3, privacy_threshold=4)
    shares = jax.vmap(share_fn)(keys, secrets)            # [P, n, B]
    summed = combine(shares, modulus=433)                 # [n, B]
    L = numtheory.packed_reconstruct_matrix(3, 8, 4, 433, 354, 150, tuple(range(8)))
    out = packed_reconstruct(summed, jnp.asarray(L), prime=433, dimension=d)
    np.testing.assert_array_equal(np.asarray(out), (np.arange(d) * P) % 433)


def test_large_prime_limb_path():
    """31-bit prime exercises the limb modmatmul; checked against python ints."""
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, min_modulus_bits=30)
    assert p.bit_length() >= 30 and numtheory.is_prime(p)
    scheme = PackedShamirSharing(3, 8, t, p, w2, w3)
    key = jax.random.PRNGKey(1)
    secrets = jnp.array([p - 1, 0, 123456789, p - 2, 17], dtype=jnp.int64)
    M = jnp.asarray(numtheory.packed_share_matrix(3, 8, t, p, w2, w3))
    shares = packed_share(key, secrets, M, prime=p, secret_count=3, privacy_threshold=t)
    L = numtheory.packed_reconstruct_matrix(3, 8, t, p, w2, w3, (0, 2, 3, 5, 6, 7, 1))
    picked = jnp.stack([shares[i] for i in (0, 2, 3, 5, 6, 7, 1)])
    out = packed_reconstruct(picked, jnp.asarray(L), prime=p, dimension=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(secrets))

    # cross-check one matmul against exact python ints
    a = np.asarray(M)[:2, :]
    b = np.random.default_rng(5).integers(0, p, size=(a.shape[1], 3))
    expect = [[sum(int(a[i, k]) * int(b[k, j]) for k in range(a.shape[1])) % p
               for j in range(3)] for i in range(2)]
    got = np_modmatmul(a, b, p)
    np.testing.assert_array_equal(got, expect)
    got_dev = modmatmul(jnp.asarray(a), jnp.asarray(b), p)
    np.testing.assert_array_equal(np.asarray(got_dev), expect)


def test_modmatmul_batched_contraction_axis():
    """Regression: the overflow guard must size from the contraction axis
    (b.shape[-2]), not the leading batch dim."""
    p = 2**31 - 100
    a = np.full((2, 8), p - 1, dtype=np.int64)
    b = np.full((1, 8, 3), p - 1, dtype=np.int64)
    expect = (8 * (p - 1) * (p - 1)) % p
    np.testing.assert_array_equal(np_modmatmul(a, b, p), np.full((1, 2, 3), expect))
    got = modmatmul(jnp.asarray(a), jnp.asarray(b), p)
    np.testing.assert_array_equal(np.asarray(got), np.full((1, 2, 3), expect))
    with pytest.raises(ValueError):
        modmatmul(jnp.asarray(a), jnp.asarray(b), 1 << 31)  # modulus cap enforced


def test_uniform_mod_range_and_determinism():
    key = jax.random.PRNGKey(11)
    draws = uniform_mod(key, (1000,), 433)
    assert int(draws.min()) >= 0 and int(draws.max()) < 433
    draws2 = uniform_mod(key, (1000,), 433)
    np.testing.assert_array_equal(np.asarray(draws), np.asarray(draws2))
    # coarse uniformity: all residue classes hit for small modulus
    assert len(np.unique(np.asarray(uniform_mod(key, (5000,), 7)))) == 7


def test_chacha_prg_deterministic_and_in_range():
    seed = [0xDEADBEEF, 0x12345678, 0x9ABCDEF0, 0x0F0F0F0F]
    m1 = chacha.expand_mask(seed, 1000, 433)
    m2 = chacha.expand_mask(seed, 1000, 433)
    np.testing.assert_array_equal(m1, m2)
    assert m1.min() >= 0 and m1.max() < 433
    m3 = chacha.expand_mask([1, 2, 3, 4], 1000, 433)
    assert not np.array_equal(m1, m3)
    # prefix stability: longer expansion extends shorter one
    np.testing.assert_array_equal(chacha.expand_mask(seed, 100, 433), m1[:100])


def test_chacha_known_vector():
    """Pin the ChaCha20 permutation: all-zero key/counter block 0, LE words."""
    w = chacha.chacha_block_words([0] * 8, 0, 1)[0]
    assert w.dtype == np.uint32
    # first words of the standard ChaCha20 zero-key keystream (block 0)
    assert int(w[0]) == 0xADE0B876
    assert int(w[1]) == 0x903DF1A0
    w2 = chacha.chacha_block_words([0] * 8, 0, 2)
    np.testing.assert_array_equal(w, w2[0])  # counter-parallel generation consistent
    with pytest.raises(ValueError):
        chacha.chacha_block_words([0] * 9, 0, 1)  # oversized seed rejected


def test_generate_packed_params():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8)
    numtheory.validate_packed_scheme(3, 8, t, p, w2, w3)
    assert t == 4  # next_pow2(3+2)=8 -> t=8-3-1
    with pytest.raises(ValueError):
        numtheory.generate_packed_params(3, 7)  # 8 not a power of 3
