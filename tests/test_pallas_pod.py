"""Fused Pallas kernel inside the pod/streamed local steps (interpret mode).

Round-2 verdict, weak #2: the Pallas kernel only served the single-chip
path. These tests pin the kernel-backed local step of SimulatedPod /
StreamedPod / StreamingAggregator, bit-exact against the plain participant
sum — which also proves equality with the XLA path, since both modes
compute the same deterministic aggregate (masks cancel in the final
subtract; random polynomial rows are annihilated by reconstruction).
External-bits mode stands in for the TPU PRNG, which interpret mode on CPU
cannot run (pallas_round.py randomness contract).
"""

import jax
import numpy as np
import pytest

from sda_tpu.fields import numtheory
from sda_tpu.mesh import SimulatedPod, StreamedPod, StreamingAggregator, make_mesh
from sda_tpu.protocol import (AdditiveSharing, ChaChaMasking, FullMasking,
                              NoMasking, PackedShamirSharing)

from util import external_bits

GOLDEN = PackedShamirSharing(3, 8, 4, 433, 354, 150)  # 433 is not Solinas


def fast_scheme():
    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 28)
    return PackedShamirSharing(3, 8, t, p, w2, w3)


def needs_devices(n):
    return pytest.mark.skipif(
        len(jax.devices()) < n, reason=f"needs {n} virtual devices"
    )


@needs_devices(8)
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2)])
@pytest.mark.parametrize("masking", ["none", "full", "chacha"])
def test_pod_pallas_matches_sum(mesh_shape, masking):
    s = fast_scheme()
    mask = {"none": None, "full": FullMasking(s.prime_modulus),
            "chacha": ChaChaMasking(s.prime_modulus, 48, 128)}[masking]
    pod = SimulatedPod(
        s, masking_scheme=mask, mesh=make_mesh(*mesh_shape),
        use_pallas=True, pallas_interpret=True,
        pallas_external_bits_fn=external_bits,
    )
    assert pod.pallas_active
    rng = np.random.default_rng(3)
    inputs = rng.integers(0, 1 << 20, size=(16, 48))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(
        out, inputs.sum(axis=0) % s.prime_modulus
    )


@needs_devices(8)
def test_streamed_pod_pallas_matches_sum_and_xla():
    s = fast_scheme()
    kw = dict(
        masking_scheme=FullMasking(s.prime_modulus), mesh=make_mesh(4, 2),
        participants_chunk=8, dim_chunk=24,
    )
    pallas_pod = StreamedPod(
        s, use_pallas=True, pallas_interpret=True,
        pallas_external_bits_fn=external_bits, **kw,
    )
    xla_pod = StreamedPod(s, **kw)
    assert pallas_pod.pallas_active and not xla_pod.pallas_active
    rng = np.random.default_rng(4)
    inputs = rng.integers(0, 1 << 20, size=(20, 60))  # ragged tiles both axes
    key = jax.random.PRNGKey(11)
    expected = inputs.sum(axis=0) % s.prime_modulus
    np.testing.assert_array_equal(np.asarray(pallas_pod.aggregate(inputs, key)), expected)
    np.testing.assert_array_equal(np.asarray(xla_pod.aggregate(inputs, key)), expected)


@pytest.mark.parametrize("masking", ["none", "full", "chacha"])
def test_streaming_aggregator_pallas_matches_sum(masking):
    s = fast_scheme()
    mask = {"none": None, "full": FullMasking(s.prime_modulus),
            "chacha": ChaChaMasking(s.prime_modulus, 51, 128)}[masking]
    agg = StreamingAggregator(
        s, masking_scheme=mask, participants_chunk=8, dim_chunk=24,
        use_pallas=True, pallas_interpret=True,
        pallas_external_bits_fn=external_bits,
    )
    assert agg.pallas_active
    rng = np.random.default_rng(5)
    inputs = rng.integers(0, 1 << 20, size=(13, 51))  # ragged edge tiles
    out = np.asarray(agg.aggregate(inputs, jax.random.PRNGKey(2)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


@needs_devices(8)
def test_streamed_pod_pallas_chacha_matches_sum():
    """ChaCha x pallas on the streamed mesh: the wire-PRG mask expands at
    each tile's global (participant, dim) offset before the kernel's
    mask-free pass — wrong tile_base/d_block0 plumbing would corrupt the
    aggregate on multi-tile runs."""
    s = fast_scheme()
    dim = 96  # several dim tiles of 24; all ChaCha-block aligned
    spod = StreamedPod(
        s, ChaChaMasking(s.prime_modulus, dim, 128), mesh=make_mesh(4, 2),
        participants_chunk=8, dim_chunk=24,
        use_pallas=True, pallas_interpret=True,
        pallas_external_bits_fn=external_bits,
    )
    assert spod.pallas_active
    rng = np.random.default_rng(8)
    inputs = rng.integers(0, 1 << 20, size=(20, dim))  # ragged p tiles
    out = np.asarray(spod.aggregate(inputs, jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % s.prime_modulus)


def test_pallas_gating():
    s = fast_scheme()
    # explicit request over unsupported configs is an error, not a silent
    # fallback
    with pytest.raises(ValueError):
        StreamingAggregator(GOLDEN, use_pallas=True)  # non-Solinas prime
    with pytest.raises(ValueError):  # additive sharing: no kernel path
        StreamingAggregator(
            AdditiveSharing(share_count=8, modulus=s.prime_modulus),
            use_pallas=True,
        )
    # env-driven default falls back silently on unsupported configs
    agg = StreamingAggregator(GOLDEN)
    assert not agg.pallas_active


@needs_devices(8)
@pytest.mark.parametrize("survivors", [(0, 1, 2, 3, 4, 5, 6), (1, 2, 3, 4, 5, 6, 7)])
def test_pod_clerk_dropout_quorum_reveals_exact(survivors):
    """Mesh-mode clerk dropout (round-2 verdict #6): a lost device's clerk
    rows never enter the finale; the quorum (r=7 of n=8 for the golden
    scheme) reveals the exact aggregate."""
    pod = SimulatedPod(
        GOLDEN, masking_scheme=FullMasking(433), mesh=make_mesh(4, 2),
        surviving_clerks=survivors,
    )
    rng = np.random.default_rng(6)
    inputs = rng.integers(0, 433, size=(8, 24))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


@needs_devices(8)
def test_streamed_pod_clerk_dropout_exact():
    spod = StreamedPod(
        GOLDEN, FullMasking(433), mesh=make_mesh(4, 2),
        participants_chunk=8, dim_chunk=24,
        surviving_clerks=(0, 2, 3, 4, 5, 6, 7),  # clerk 1's rows lost
    )
    rng = np.random.default_rng(7)
    inputs = rng.integers(0, 433, size=(12, 48))
    out = np.asarray(spod.aggregate(inputs, jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


def test_streaming_aggregator_clerk_dropout_exact():
    agg = StreamingAggregator(
        GOLDEN, participants_chunk=8, dim_chunk=24,
        surviving_clerks=(7, 0, 1, 2, 3, 4, 5),  # arbitrary order quorum
    )
    rng = np.random.default_rng(8)
    inputs = rng.integers(0, 433, size=(9, 30))
    out = np.asarray(agg.aggregate(inputs, jax.random.PRNGKey(4)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


def test_clerk_dropout_validation():
    from sda_tpu.protocol import AdditiveSharing

    with pytest.raises(ValueError):  # below quorum (r=7 for golden)
        StreamingAggregator(GOLDEN, surviving_clerks=(0, 1, 2))
    with pytest.raises(ValueError):  # duplicate index
        StreamingAggregator(GOLDEN, surviving_clerks=(0, 0, 1, 2, 3, 4, 5))
    with pytest.raises(ValueError):  # additive cannot drop clerks
        StreamingAggregator(
            AdditiveSharing(share_count=3, modulus=433),
            surviving_clerks=(0, 1),
        )
    # additive with ALL clerks present is just the normal finale
    agg = StreamingAggregator(
        AdditiveSharing(share_count=3, modulus=433),
        surviving_clerks=(0, 1, 2),
    )
    assert agg.surviving_clerks is None


@needs_devices(8)
def test_pod_26_clerk_committee_with_dropout():
    """The next committee size up (3^3-1 = 26 clerks) on a (2, 4) mesh —
    13 clerk rows per p-shard — with 19 of 26 clerks dropped: the quorum
    of 7 still reveals exactly."""
    t, p, w2, w3 = numtheory.generate_packed_params(3, 26, 28)
    s = PackedShamirSharing(3, 26, t, p, w2, w3)
    assert s.reconstruction_threshold == 7
    pod = SimulatedPod(
        s, masking_scheme=FullMasking(p), mesh=make_mesh(2, 4),
        surviving_clerks=(25, 0, 3, 7, 12, 18, 21),
    )
    rng = np.random.default_rng(9)
    inputs = rng.integers(0, 1 << 20, size=(8, 48))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % p)


@needs_devices(8)
def test_streamed_pod_chacha_with_dropout():
    """ChaCha masking composes with clerk dropout: mask seeds travel
    participant->recipient, so losing clerk rows loses no mask data."""
    spod = StreamedPod(
        GOLDEN, ChaChaMasking(433, 48, 128), mesh=make_mesh(4, 2),
        participants_chunk=8, dim_chunk=24,
        surviving_clerks=(0, 1, 2, 3, 4, 5, 6),
    )
    rng = np.random.default_rng(10)
    inputs = rng.integers(0, 433, size=(11, 48))
    out = np.asarray(spod.aggregate(inputs, jax.random.PRNGKey(6)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


def test_pallas_env_default(monkeypatch):
    s = fast_scheme()
    monkeypatch.setenv("SDA_PALLAS", "1")
    assert StreamingAggregator(s).pallas_active
    assert not StreamingAggregator(GOLDEN).pallas_active  # silent fallback
    monkeypatch.delenv("SDA_PALLAS")
    assert not StreamingAggregator(s).pallas_active
