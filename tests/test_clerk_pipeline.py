"""Batched clerk pipeline: bit-exactness, overlap machinery, doc cache.

The clerk hot path decrypts in bundles on the crypto worker pool and
feeds each bundle into one stacked combine, folding partial sums
modularly. The contract: the revealed aggregate is BIT-EXACT with the
scalar (workers=1, batch=everything) path — under every batch size, under
chaos failpoints, and with the client-side document cache on or off.
"""

import threading
import time

import numpy as np
import pytest

from sda_tpu import chaos, obs
from sda_tpu.client import RecipientOutput, SdaClient
from sda_tpu.crypto import MemoryKeystore, sodium
from sda_tpu.crypto import batch as crypto_batch
from sda_tpu.protocol import (
    Aggregation,
    AggregationId,
    AgentId,
    EncryptionKeyId,
    FullMasking,
    PackedShamirSharing,
    SodiumEncryption,
)
from sda_tpu.server import new_memory_server


# -- pool primitives ---------------------------------------------------------

def test_pmap_preserves_order(monkeypatch):
    monkeypatch.setenv("SDA_CRYPTO_WORKERS", "4")
    crypto_batch.reset()
    try:
        def slow_identity(x):
            time.sleep(0.002 * (7 - x % 8))  # later items finish earlier
            return x * x
        assert crypto_batch.pmap(slow_identity, range(16)) == [
            x * x for x in range(16)]
    finally:
        crypto_batch.reset()


def test_pmap_propagates_exceptions(monkeypatch):
    monkeypatch.setenv("SDA_CRYPTO_WORKERS", "4")
    crypto_batch.reset()
    try:
        def boom(x):
            if x == 5:
                raise RuntimeError("item 5")
            return x
        with pytest.raises(RuntimeError, match="item 5"):
            crypto_batch.pmap(boom, range(8))
    finally:
        crypto_batch.reset()


@pytest.mark.parametrize("workers", ["0", "1", "4"])
def test_prefetch_map_yields_ordered_batches(monkeypatch, workers):
    monkeypatch.setenv("SDA_CRYPTO_WORKERS", workers)
    crypto_batch.reset()
    try:
        batches = list(crypto_batch.prefetch_map(
            lambda x: x + 100, list(range(10)), batch_size=3))
        assert batches == [[100, 101, 102], [103, 104, 105],
                           [106, 107, 108], [109]]
    finally:
        crypto_batch.reset()


def test_prefetch_map_bounds_staging(monkeypatch):
    # at most (prefetch + 1) batches may ever be in flight or staged:
    # the double buffer, not an unbounded decrypt-everything-first queue
    monkeypatch.setenv("SDA_CRYPTO_WORKERS", "8")
    crypto_batch.reset()
    started = []
    lock = threading.Lock()
    try:
        def track(x):
            with lock:
                started.append(x)
            return x

        stream = crypto_batch.prefetch_map(track, list(range(100)),
                                           batch_size=10, prefetch=1)
        next(stream)
        time.sleep(0.05)  # let any runaway submissions surface
        with lock:
            assert len(started) <= 30  # batch 0 + at most 2 ahead
    finally:
        crypto_batch.reset()


# -- end-to-end bit-exactness ------------------------------------------------

pytestmark_sodium = pytest.mark.skipif(not sodium.available(),
                                       reason="libsodium not present")

SCHEME = PackedShamirSharing(3, 8, 4, 433, 354, 150)
DIM = 6
PARTICIPANTS = 7


def _run_round(seed: int) -> np.ndarray:
    """One full in-process round; returns the revealed positive values."""
    obs.reset_all()
    service = new_memory_server()

    def new_client():
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        return SdaClient(agent, keystore, service)

    recipient = new_client()
    recipient_key = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)

    clerks = [new_client() for _ in range(SCHEME.share_count)]
    for clerk in clerks:
        clerk.upload_agent()
        clerk.upload_encryption_key(clerk.new_encryption_key())

    aggregation = Aggregation(
        id=AggregationId.random(),
        title="pipeline-equivalence",
        vector_dimension=DIM,
        modulus=SCHEME.prime_modulus,
        recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=FullMasking(SCHEME.prime_modulus),
        committee_sharing_scheme=SCHEME,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(aggregation)
    recipient.begin_aggregation(aggregation.id)

    rng = np.random.default_rng(seed)
    inputs = rng.integers(0, SCHEME.prime_modulus,
                          size=(PARTICIPANTS, DIM), dtype=np.int64)
    for row in inputs:
        participant = new_client()
        participant.upload_agent()
        participant.participate([int(x) for x in row], aggregation.id)

    recipient.end_aggregation(aggregation.id)
    # several sweeps: under the chaos profile a worker may abandon a job
    # mid-sweep (clerk.abandon_job drop) — the job stays queued and a
    # later sweep picks it up, exactly like a re-polling clerk fleet
    for _ in range(4):
        for worker in [recipient] + clerks:
            worker.run_chores(-1)

    output = recipient.reveal_aggregation(aggregation.id)
    expected = inputs.sum(axis=0) % SCHEME.prime_modulus
    np.testing.assert_array_equal(output.positive().values, expected)
    return np.asarray(output.positive().values)


@pytestmark_sodium
@pytest.mark.parametrize("batch,workers", [
    ("1", "0"),    # scalar: one vector at a time, no threads
    ("2", "4"),    # tiny bundles, real overlap
    ("3", "2"),
    ("4096", "8"),  # one bundle for everything
])
def test_batched_clerk_is_bit_exact_with_scalar(monkeypatch, batch, workers):
    monkeypatch.setenv("SDA_CLERK_BATCH", batch)
    monkeypatch.setenv("SDA_CRYPTO_WORKERS", workers)
    crypto_batch.reset()
    try:
        # the fixed seed pins the participant inputs, so _run_round's
        # internal assert against the plain sum IS the scalar verdict —
        # every parametrization must land on identical values
        out = _run_round(seed=20260803)
        assert out.shape == (DIM,)
    finally:
        crypto_batch.reset()


@pytestmark_sodium
def test_batched_clerk_exact_under_chaos(monkeypatch):
    # the pipeline must stay bit-exact when failpoints abandon clerk jobs
    # mid-round (lease reissue brings them back) — chaos changes WHO
    # processes a job, never the partial sums
    monkeypatch.setenv("SDA_CLERK_BATCH", "2")
    monkeypatch.setenv("SDA_CRYPTO_WORKERS", "4")
    crypto_batch.reset()
    chaos.reset()
    try:
        chaos.configure("clerk.abandon_job", drop=True, after=1, every=3,
                        times=4)
        _run_round(seed=20260803)  # asserts exactness internally
    finally:
        chaos.reset()
        crypto_batch.reset()


@pytestmark_sodium
def test_cache_disabled_round_still_exact(monkeypatch):
    monkeypatch.setenv("SDA_CLIENT_CACHE", "0")
    _run_round(seed=7)


@pytestmark_sodium
@pytest.mark.parametrize("batch", ["1", "3"])
def test_device_tile_clerk_combine_bit_exact(monkeypatch, batch):
    # SDA_CLERK_DEVICE_TILES=1: decrypted bundles fold into the device-
    # resident tiled accumulator (mesh/devscale.py DeviceTileCombiner)
    # instead of host numpy — the revealed bytes must not change
    # (_run_round asserts against the plain sum internally)
    from sda_tpu.utils import metrics as _metrics

    monkeypatch.setenv("SDA_CLERK_DEVICE_TILES", "1")
    monkeypatch.setenv("SDA_CLERK_BATCH", batch)
    monkeypatch.setenv("SDA_CRYPTO_WORKERS", "2")
    crypto_batch.reset()
    try:
        _run_round(seed=20260804)
        counters = _metrics.counter_report("clerk.device_tiles")
        assert counters.get("clerk.device_tiles.bundle", 0) > 0, \
            "device-tile path never engaged"
    finally:
        crypto_batch.reset()


# -- document cache ----------------------------------------------------------

class _CountingService:
    """Service wrapper counting the immutable-doc fetches."""

    def __init__(self, inner):
        self._inner = inner
        self.counts = {"get_aggregation": 0, "get_committee": 0,
                       "get_encryption_key": 0}

    def __getattr__(self, name):
        fn = getattr(self._inner, name)
        if name in self.counts:
            def counted(*args, **kwargs):
                self.counts[name] += 1
                return fn(*args, **kwargs)
            return counted
        return fn


@pytestmark_sodium
def test_clerk_polling_uses_cached_documents():
    service = new_memory_server()
    counting = _CountingService(service)

    def new_client(svc):
        keystore = MemoryKeystore()
        agent = SdaClient.new_agent(keystore)
        return SdaClient(agent, keystore, svc)

    recipient = new_client(service)
    recipient_key = recipient.new_encryption_key()
    recipient.upload_agent()
    recipient.upload_encryption_key(recipient_key)

    clerks = [new_client(service) for _ in range(SCHEME.share_count)]
    for clerk in clerks:
        clerk.upload_agent()
        clerk.upload_encryption_key(clerk.new_encryption_key())

    aggregation = Aggregation(
        id=AggregationId.random(), title="cache", vector_dimension=DIM,
        modulus=SCHEME.prime_modulus, recipient=recipient.agent.id,
        recipient_key=recipient_key,
        masking_scheme=FullMasking(SCHEME.prime_modulus),
        committee_sharing_scheme=SCHEME,
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(aggregation)
    recipient.begin_aggregation(aggregation.id)

    for _ in range(3):
        p = new_client(service)
        p.upload_agent()
        p.participate([1] * DIM, aggregation.id)

    # three pipelined snapshots -> three jobs per committee member
    for _ in range(3):
        recipient.snapshot_aggregation(aggregation.id)

    committee = service.get_committee(recipient.agent, aggregation.id)
    committee_ids = {cid for cid, _ in committee.clerks_and_keys}
    worker = next(c for c in [recipient] + clerks
                  if c.agent.id in committee_ids)
    counted_clerk = SdaClient(worker.agent, worker.crypto.keystore, counting)
    processed = 0
    while counted_clerk.clerk_once():
        processed += 1
    assert processed == 3
    # one fetch each despite three jobs: the cache held between polls
    assert counting.counts["get_aggregation"] == 1
    assert counting.counts["get_committee"] == 1
    # recipient key verified once, not once per job
    assert counting.counts["get_encryption_key"] == 1


# -- RecipientOutput lanes ---------------------------------------------------

def test_recipient_output_int64_lane_stays_numpy():
    out = RecipientOutput(433, [-5, 0, 432, 440])
    lifted = out.positive()
    assert lifted.values.dtype == np.int64
    np.testing.assert_array_equal(lifted.values, [428, 0, 432, 7])


def test_recipient_output_bigint_lane():
    modulus = (1 << 80) + 13  # beyond int64: object lane, no silent wrap
    values = [-(1 << 70), 1 << 79, 7]
    out = RecipientOutput(modulus, values)
    assert out.values.dtype == object
    lifted = out.positive()
    assert lifted.values.dtype == object
    assert list(lifted.values) == [v % modulus for v in values]
