"""Batched limb-domain Montgomery premix (crypto/paillier_tpu.py).

The prototype must be BIT-exact against python-int arithmetic — a single
wrong carry in a 4096-bit product silently corrupts every aggregate the
server premixes. Reference anchors: protocol/src/crypto.rs:164-174
(PackedPaillier), server/src/snapshot.rs:4-47 (premixing).
"""

import numpy as np
import pytest

from sda_tpu.crypto import paillier
from sda_tpu.crypto.paillier_tpu import MontgomeryContext


def _rng_ints(rng, m, n):
    return [int(rng.integers(0, 1 << 62)) % m for _ in range(n)]


@pytest.mark.parametrize("bits", [64, 200, 521])
def test_mont_mul_exact_vs_python(bits):
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(bits)
    m = (1 << bits) | int(rng.integers(1, 1 << 32)) | 1  # odd, bits+ wide
    ctx = MontgomeryContext(m)
    Rinv = pow(ctx.R, -1, m)
    a = _rng_ints(rng, m, 6) + [0, 1, m - 1]
    b = _rng_ints(rng, m, 6) + [m - 1, 0, m - 1]
    mont = jax.jit(ctx.mont_mul_fn())
    out = mont(jnp.asarray(ctx.to_limbs(a)), jnp.asarray(ctx.to_limbs(b)))
    got = ctx.from_limbs(np.asarray(out))
    for ai, bi, gi in zip(a, b, got):
        assert gi == (ai * bi * Rinv) % m, (ai, bi)


def test_mont_mul_output_canonical_and_reduced():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    m = ((1 << 256) | int(rng.integers(1, 1 << 40))) | 1
    ctx = MontgomeryContext(m)
    a = _rng_ints(rng, m, 16)
    mont = jax.jit(ctx.mont_mul_fn())
    out = np.asarray(mont(jnp.asarray(ctx.to_limbs(a)),
                          jnp.asarray(ctx.to_limbs(a))))
    assert out.min() >= 0 and out.max() <= 255  # canonical limbs
    for v in ctx.from_limbs(out):
        assert 0 <= v < m  # fully reduced


def test_premix_matches_python_product():
    rng = np.random.default_rng(11)
    m = ((1 << 300) | int(rng.integers(1, 1 << 40))) | 1
    ctx = MontgomeryContext(m)
    P, B = 7, 4
    cts = [[int(rng.integers(0, 1 << 62)) % m for _ in range(B)]
           for _ in range(P)]
    got = ctx.premix(cts)
    for b in range(B):
        want = 1
        for p in range(P):
            want = (want * cts[p][b]) % m
        assert got[b] == want


def test_premix_is_paillier_homomorphic_sum():
    """End-to-end against the host Paillier: premixing real ciphertexts on
    the accelerator decrypts to the sum of the plaintexts."""
    pk, sk = paillier.keygen(512)
    ctx = MontgomeryContext(pk.n_squared)
    rng = np.random.default_rng(13)
    P, B = 5, 3
    plains = [[int(rng.integers(0, 1 << 48)) for _ in range(B)]
              for _ in range(P)]
    cts = [[paillier.encrypt(pk, plains[p][b]) for b in range(B)]
           for p in range(P)]
    got = ctx.premix(cts)
    for b in range(B):
        host = cts[0][b]
        for p in range(1, P):
            host = paillier.add(pk, host, cts[p][b])
        assert got[b] == host  # bit-identical ciphertext product
        assert paillier.decrypt(sk, got[b]) == sum(
            plains[p][b] for p in range(P))
