"""utils.benchtime — the tunnel-safe marginal timer every bench relies on.

All recorded throughput numbers flow through marginal_seconds (round-2
postmortem: naive block_until_ready timing over-reported by 200x), so its
chain sizing and fallback arithmetic get direct coverage.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sda_tpu.utils.benchtime import chain_seconds, marginal_seconds


def _dispatch(work=2048):
    x = jnp.arange(work, dtype=jnp.float32)

    def d(i):
        return jnp.sin(x + i).sum()

    return d


def test_chain_seconds_scales_with_reps():
    d = _dispatch(1 << 18)
    chain_seconds(d, 1)  # warm: first call pays op compilation
    t1 = chain_seconds(d, 1)
    t40 = chain_seconds(d, 40)
    assert t1 > 0
    # 40 serialized reps must exceed 1: catches a regression that
    # ignores the reps argument
    assert t40 > t1


def test_marginal_seconds_respects_max_reps_and_reports_chain():
    per, info = marginal_seconds(_dispatch(), target_seconds=0.2, max_reps=7)
    assert per > 0
    chain = info["chain"]
    # r2 is clamped by max_reps even though max(10, ...) wants more
    assert chain["r2"] <= 7
    assert 1 <= chain["r1"] <= chain["r2"]
    assert info["probe_s"] > 0
    assert info["fixed_overhead_s"] >= 0


def test_marginal_seconds_fallback_when_difference_is_noise():
    # max_reps=1 forces r1 == r2 == 1: the (t2-t1)/(r2-r1) form is
    # undefined, so the helper must fall back to t2/r2 instead of
    # dividing by zero or returning a negative time
    per, info = marginal_seconds(_dispatch(), target_seconds=0.1, max_reps=1)
    assert per > 0
    assert info["chain"]["r1"] == info["chain"]["r2"] == 1


def test_marginal_time_is_sane_for_known_workload():
    # marginal per-rep must be below the time of a full 1-rep chain
    # (which includes fixed overhead) for any real dispatch
    d = _dispatch(1 << 16)
    per, info = marginal_seconds(d, target_seconds=0.5, max_reps=32)
    assert per <= info["probe_s"] * 1.5 + 1e-3


def test_pallas_knobs_are_env_only(monkeypatch):
    # library runtime must not depend on the mutable committed sweep
    # artifact (ADVICE r3): without env vars the defaults apply even when
    # a knobs record exists on disk
    from sda_tpu.utils import benchtime

    monkeypatch.setattr(benchtime, "_knobs_record",
                        lambda: {"p_block": 64, "tile": 4096,
                                 "stream_pc": 100})
    for var in ("SDA_PALLAS_PBLOCK", "SDA_PALLAS_TILE",
                "SDA_PALLAS_TILE_SOURCE", "SDA_BENCH_STREAM_PC"):
        monkeypatch.delenv(var, raising=False)
    assert benchtime.pallas_knobs() == (16, None)
    assert benchtime.stream_pc_knob() == 64
    assert not benchtime.tile_from_sweep()


def test_export_knobs_to_env_opts_in_and_marks_source(monkeypatch):
    from sda_tpu.utils import benchtime

    monkeypatch.setattr(benchtime, "_knobs_record",
                        lambda: {"p_block": 64, "tile": 4096,
                                 "stream_pc": 100})
    for var in ("SDA_PALLAS_PBLOCK", "SDA_PALLAS_TILE",
                "SDA_PALLAS_TILE_SOURCE", "SDA_BENCH_STREAM_PC"):
        monkeypatch.delenv(var, raising=False)
    benchtime.export_knobs_to_env()
    assert benchtime.pallas_knobs() == (64, 4096)
    assert benchtime.stream_pc_knob() == 100
    # record-sourced tile is marked so small shapes may clamp it
    assert benchtime.tile_from_sweep()


def test_export_knobs_never_overrides_explicit_env(monkeypatch):
    from sda_tpu.utils import benchtime

    monkeypatch.setattr(benchtime, "_knobs_record",
                        lambda: {"p_block": 64, "tile": 4096,
                                 "stream_pc": 100})
    monkeypatch.setenv("SDA_PALLAS_TILE", "1024")
    monkeypatch.setenv("SDA_PALLAS_PBLOCK", "8")
    monkeypatch.setenv("SDA_BENCH_STREAM_PC", "50")
    monkeypatch.delenv("SDA_PALLAS_TILE_SOURCE", raising=False)
    benchtime.export_knobs_to_env()
    assert benchtime.pallas_knobs() == (8, 1024)
    assert benchtime.stream_pc_knob() == 50
    # the explicit tile is NOT sweep-sourced: it must be honored unclamped
    assert not benchtime.tile_from_sweep()
