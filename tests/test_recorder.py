"""Flight recorder plane (ISSUE 18): durable span spools (rotation,
eviction, torn-line tolerance, proc clock anchors), the multi-process
clock-offset merge, round forensics (``sda-trace explain``) over
synthetic and live spools, per-tenant SLO burn-rate evaluation, chaos
fault marks carrying structured ``fault.kind``/``fault.site`` tags, and
the shared histogram-bucket format between ``/metrics`` and spooled
snapshots.
"""

import json
import os
import time

import pytest

from sda_tpu import chaos, obs
from sda_tpu.obs import forensics, recorder, slo, timeline, trace
from sda_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _clean_slate():
    recorder.uninstall()
    chaos.reset()
    obs.reset_all()
    yield
    recorder.uninstall()
    chaos.reset()
    obs.reset_all()


# ---------------------------------------------------------------------------
# recorder: segments, rotation, eviction, crash tolerance

def _sealed(root):
    return [s for s in recorder.list_segments(str(root)) if s["sealed"]]


def test_spool_opens_every_segment_with_proc_anchor(tmp_path):
    rec = recorder.install(str(tmp_path), node_id="w0", snapshot_s=0.0)
    with obs.span("outer", attributes={"k": 1}):
        with obs.span("inner"):
            obs.add_event("tick", n=3)
    recorder.uninstall()
    sealed = _sealed(tmp_path)
    assert sealed, "close() must seal the active segment"
    for seg in sealed:
        with open(seg["path"], encoding="utf-8") as f:
            first = json.loads(f.readline())
        assert first["t"] == "proc"
        assert first["pid"] == rec.pid
        assert first["node"] == "w0"
        assert first["wall_s"] > 0 and first["mono_s"] > 0
    records, torn = recorder.read_spool(str(tmp_path))
    assert torn == 0
    spans = [r for r in records if r["t"] == "span"]
    assert {s["name"] for s in spans} == {"outer", "inner"}
    inner = next(s for s in spans if s["name"] == "inner")
    outer = next(s for s in spans if s["name"] == "outer")
    assert inner["trace"] == outer["trace"]
    assert inner["parent"] == outer["span"]
    assert inner["events"][0]["name"] == "tick"
    assert inner["events"][0]["attrs"] == {"n": 3}
    assert outer["attrs"]["k"] == 1
    assert outer["duration_s"] >= inner["duration_s"] >= 0.0


def test_rotation_by_size_and_oldest_sealed_eviction(tmp_path):
    rec = recorder.FlightRecorder(
        str(tmp_path), node_id="w0",
        segment_bytes=4096, max_bytes=8192, snapshot_s=0.0)
    payload = "x" * 120
    for i in range(300):  # ~36 KB >> the 8 KiB directory cap
        rec.record({"t": "span", "name": "s", "i": i, "pad": payload})
    rec.close()
    assert rec.report()["segments_written"] >= 4
    segs = recorder.list_segments(str(tmp_path))
    assert segs and all(s["sealed"] for s in segs)
    # eviction ran: the earliest segments are gone and what remains is
    # bounded by the cap plus at most one freshly-sealed segment of slack
    names = [s["segment"] for s in segs]
    assert f"spool-w0-{rec.pid}-000001.jsonl" not in names
    assert sum(s["bytes"] for s in segs) <= 8192 + 4096 + 1024
    # records survive in the surviving segments, newest kept
    records, torn = recorder.read_spool(str(tmp_path))
    assert torn == 0
    kept = [r["i"] for r in records if r["t"] == "span"]
    assert kept and kept[-1] == 299


def test_rotation_by_age(tmp_path):
    rec = recorder.FlightRecorder(
        str(tmp_path), node_id="w0", segment_age_s=0.05, snapshot_s=0.0)
    rec.record({"t": "span", "name": "a"})
    time.sleep(0.12)
    rec.record({"t": "span", "name": "b"})
    rec.close()
    assert rec.report()["segments_written"] >= 2
    assert len(_sealed(tmp_path)) >= 2


def test_torn_trailing_line_is_skipped_and_tallied(tmp_path):
    rec = recorder.FlightRecorder(str(tmp_path), node_id="w0",
                                  snapshot_s=0.0)
    rec.record({"t": "span", "name": "whole"})
    rec.close()
    seg = _sealed(tmp_path)[0]
    with open(seg["path"], "a", encoding="utf-8") as f:
        f.write('{"t":"span","name":"torn-by-sigkill')  # no newline
    records, torn = recorder.read_spool(str(tmp_path))
    assert torn == 1
    assert [r["name"] for r in records if r["t"] == "span"] == ["whole"]


def test_install_is_idempotent_and_uninstall_detaches_sink(tmp_path):
    rec = recorder.install(str(tmp_path), node_id="w0", snapshot_s=0.0)
    assert recorder.install(str(tmp_path / "elsewhere")) is rec
    assert recorder.installed() is rec
    assert trace.span_sink() == rec.record_span
    recorder.uninstall()
    assert recorder.installed() is None
    assert trace.span_sink() is None
    # spans after uninstall are not spooled
    with obs.span("after"):
        pass
    records, _ = recorder.read_spool(str(tmp_path))
    assert "after" not in {r.get("name") for r in records}


def test_record_is_a_noop_without_recorder(tmp_path):
    recorder.record({"t": "round", "state": "collecting"})  # must not raise
    assert recorder.read_spool(str(tmp_path)) == ([], 0)


def test_record_metrics_spools_full_snapshot(tmp_path):
    rec = recorder.FlightRecorder(str(tmp_path), node_id="w0",
                                  snapshot_s=0.0)
    metrics.count("spool.test.count", 3)
    metrics.observe("spool.test.latency", 0.01)
    metrics.observe("spool.test.latency", 0.02)
    rec.record_metrics(reason="test")
    rec.close()
    records, _ = recorder.read_spool(str(tmp_path))
    snap = next(r for r in records if r["t"] == "metrics")
    assert snap["reason"] == "test"
    assert snap["node"] == "w0" and snap["pid"] == rec.pid
    assert snap["counters"]["spool.test.count"] == 3
    hist = snap["histograms"]["spool.test.latency"]
    assert hist["count"] == 2
    assert hist["buckets"][-1] == ["+Inf", 2]
    assert sum(1 for _ in hist["buckets"]) >= 2


def test_maybe_install_from_env_honors_knobs(tmp_path, monkeypatch):
    monkeypatch.delenv(recorder.RECORDER_DIR_ENV, raising=False)
    assert recorder.maybe_install_from_env() is None
    spool = tmp_path / "spool"
    monkeypatch.setenv(recorder.RECORDER_DIR_ENV, str(spool))
    monkeypatch.setenv(recorder.SEGMENT_BYTES_ENV, "65536")
    monkeypatch.setenv(recorder.SNAPSHOT_ENV, "0")
    rec = recorder.maybe_install_from_env(node_id="env-w")
    assert rec is not None and recorder.installed() is rec
    assert rec.segment_bytes == 65536
    assert rec.node_id == "env-w"
    assert os.path.isdir(str(spool))


# ---------------------------------------------------------------------------
# clock-offset merge (the multi-process timeline satellite)

def test_clock_offsets_keeps_earliest_anchor_per_process():
    anchors = [
        {"t": "proc", "node": "w0", "pid": 1, "wall_s": 1000.0,
         "mono_s": 100.0, "seq": 1},
        # later segment of the SAME process after a wall-clock step:
        # must not shear the timeline — the earliest anchor wins
        {"t": "proc", "node": "w0", "pid": 1, "wall_s": 1500.0,
         "mono_s": 200.0, "seq": 2},
        {"t": "proc", "node": "w1", "pid": 2, "wall_s": 1000.5,
         "mono_s": 5000.0, "seq": 1},
        {"t": "span", "name": "not-an-anchor"},
    ]
    offsets = timeline.clock_offsets(anchors)
    assert offsets == {("w0", 1): 900.0, ("w1", 2): -3999.5}


def test_normalize_span_records_merges_skewed_processes_causally():
    # w0's perf_counter epoch starts near 100, w1's near 5000; raw
    # mono_s order is w0-first even though w1's span happened first
    records = [
        {"t": "proc", "node": "w0", "pid": 1, "wall_s": 1000.0,
         "mono_s": 100.0},
        {"t": "proc", "node": "w1", "pid": 2, "wall_s": 1000.0,
         "mono_s": 5000.0},
        {"t": "span", "name": "w0.later", "node": "w0", "pid": 1,
         "mono_s": 102.0, "duration_s": 0.1, "trace": "t1", "span": "a"},
        {"t": "span", "name": "w1.earlier", "node": "w1", "pid": 2,
         "mono_s": 5001.0, "duration_s": 0.1, "trace": "t1", "span": "b"},
        {"t": "span", "name": "anchorless", "node": "w9", "pid": 9,
         "start_s": 1001.5, "trace": "t1", "span": "c"},
    ]
    normed = timeline.normalize_span_records(records)
    assert [r["name"] for r in normed] == [
        "w1.earlier", "anchorless", "w0.later"]
    assert normed[0]["norm_s"] == pytest.approx(1001.0)
    assert normed[2]["norm_s"] == pytest.approx(1002.0)
    assert normed[1]["norm_s"] == pytest.approx(1001.5)  # wall fallback
    chrome = timeline.chrome_trace_from_records(records)
    lanes = [ev for ev in chrome["traceEvents"]
             if ev.get("ph") == "M" and ev["name"] == "process_name"]
    assert {m["args"]["name"] for m in lanes} == {
        "w0[1]", "w1[2]", "w9[9]"}
    xs = [ev for ev in chrome["traceEvents"] if ev.get("ph") == "X"]
    assert len({ev["pid"] for ev in xs}) == 3
    assert xs[0]["ts"] <= xs[1]["ts"] <= xs[2]["ts"]


# ---------------------------------------------------------------------------
# forensics: spool indexing + explain

def _seg(segment, *records):
    return [dict(r, _segment=segment) for r in records]


def _anchor(segment, node, pid, wall=1000.0, mono=0.0):
    return dict(_segment=segment, t="proc", node=node, pid=pid,
                wall_s=wall, mono_s=mono, seq=1)


def test_spool_dedupes_amended_spans_keeping_longest():
    records = [
        _anchor("seg-a", "w0", 1),
        *_seg("seg-a",
              {"t": "span", "name": "http.server", "trace": "t1",
               "span": "s1", "mono_s": 1.0, "duration_s": 0.001},
              # the amended parked long-poll re-spool: same id, real wait
              {"t": "span", "name": "http.server", "trace": "t1",
               "span": "s1", "mono_s": 1.0, "duration_s": 4.2}),
    ]
    spool = forensics.Spool(records)
    assert len(spool.spans) == 1
    assert spool.spans[0]["duration_s"] == 4.2
    assert spool.spans[0]["node"] == "w0"  # inherited from its segment


def test_resolve_prefix_unique_ambiguous_missing():
    records = [
        _anchor("seg-a", "w0", 1),
        *_seg("seg-a",
              {"t": "round", "aggregation": "aaaa1111", "state": "revealed",
               "mono_s": 1.0},
              {"t": "round", "aggregation": "aaab2222", "state": "failed",
               "mono_s": 2.0}),
    ]
    spool = forensics.Spool(records)
    assert spool.resolve("aaaa") == "aaaa1111"
    assert spool.resolve("aaab2222") == "aaab2222"
    with pytest.raises(KeyError, match="ambiguous"):
        spool.resolve("aa")
    with pytest.raises(KeyError, match="no aggregation"):
        spool.resolve("zz")


def _synthetic_round_spool():
    """Two dead processes' segments narrating one chaotic round."""
    agg = "feedc0de0001"
    server = [
        _anchor("seg-w0", "w0", 11, wall=2000.0, mono=0.0),
        *_seg(
            "seg-w0",
            # ledger
            {"t": "round", "aggregation": agg, "state": "collecting",
             "previous": None, "tenant": "tenant-a", "mono_s": 0.1},
            {"t": "round", "aggregation": agg, "state": "frozen",
             "previous": "collecting", "mono_s": 0.5},
            {"t": "round", "aggregation": agg, "state": "revealed",
             "previous": "frozen", "reason": "reveal", "mono_s": 0.9},
            {"t": "epoch", "action": "minted", "schedule": "hourly",
             "tenant": "tenant-a", "epoch": 3, "aggregation": agg,
             "mono_s": 0.05},
            # three distinct admissions, one replay, one conflict
            {"t": "span", "name": "server.create_participation",
             "trace": "t1", "span": "sp1", "mono_s": 0.2,
             "duration_s": 0.01, "attrs": {"aggregation": agg}},
            {"t": "span", "name": "server.create_participation",
             "trace": "t1", "span": "sp2", "mono_s": 0.21,
             "duration_s": 0.01, "attrs": {"aggregation": agg}},
            {"t": "span", "name": "server.create_participation",
             "trace": "t2", "span": "sp3", "mono_s": 0.22,
             "duration_s": 0.01, "attrs": {"aggregation": agg}},
            {"t": "span", "name": "server.create_participation",
             "trace": "t2", "span": "sp4", "mono_s": 0.23,
             "duration_s": 0.01,
             "attrs": {"aggregation": agg, "replayed": True}},
            {"t": "span", "name": "server.create_participation",
             "trace": "t2", "span": "sp5", "mono_s": 0.24,
             "duration_s": 0.01,
             "attrs": {"aggregation": agg, "conflict": True}},
            # a shed request and a chaos injection inside an open span:
            # fault record AND chaos.* event — must count ONCE
            {"t": "span", "name": "http.server", "trace": "t1",
             "span": "sp6", "mono_s": 0.3, "duration_s": 0.002,
             "attrs": {"shed": "rate"}},
            {"t": "span", "name": "http.server", "trace": "t1",
             "span": "sp7", "mono_s": 0.35, "duration_s": 0.004,
             "attrs": {},
             "events": [{"name": "chaos.store.put", "time_s": 0.001,
                         "attrs": {"kind": "error",
                                   "fault.kind": "error",
                                   "fault.site": "store.put"}}]},
            {"t": "fault", "site": "store.put", "kind": "error",
             "node": "w0", "trace": "t1", "span": "sp7", "mono_s": 0.35},
            # an evicted-record fault surviving only as a span event
            {"t": "span", "name": "http.server", "trace": "t2",
             "span": "sp8", "mono_s": 0.4, "duration_s": 0.004,
             "events": [{"name": "chaos.http.server.request",
                         "time_s": 0.001,
                         "attrs": {"fault.kind": "delay",
                                   "fault.site": "http.server.request"}}]},
            {"t": "span", "name": "clerk.job", "trace": "t1", "span": "sp9",
             "mono_s": 0.6, "duration_s": 0.05,
             "attrs": {"job": "j1", "abandoned": False}},
            {"t": "span", "name": "clerk.job", "trace": "t2",
             "span": "sp10", "mono_s": 0.6, "duration_s": 0.08,
             "attrs": {"job": "j2"}},
            {"t": "metrics", "mono_s": 0.95, "reason": "close",
             "counters": {"http.retry.attempt": 5,
                          "http.retry.status_500": 2,
                          "server.job.reissued": 1}},
        ),
    ]
    client = [
        # skewed client clock: mono epoch 7000, wall 2000.05
        _anchor("seg-sim", "sim", 22, wall=2000.05, mono=7000.0),
        *_seg(
            "seg-sim",
            {"t": "span", "name": "participant.participate", "trace": "t1",
             "span": "sc1", "mono_s": 7000.1, "duration_s": 0.05,
             "attrs": {"aggregation": agg, "retries": 2}},
            {"t": "span", "name": "participant.participate", "trace": "t2",
             "span": "sc2", "mono_s": 7000.1, "duration_s": 0.05,
             "attrs": {"aggregation": agg}},
            {"t": "span", "name": "participant.resume", "trace": "t2",
             "span": "sc3", "mono_s": 7000.2, "duration_s": 0.02,
             "attrs": {"aggregation": agg}},
            {"t": "span", "name": "recipient.reveal", "trace": "t1",
             "span": "sc4", "mono_s": 7000.8, "duration_s": 0.03,
             "status": "ok",
             "attrs": {"aggregation": agg, "output.sha256": "ab" * 32,
                       "output.dim": 4}},
            # noise from a DIFFERENT round: must not leak into the story
            {"t": "span", "name": "participant.participate",
             "trace": "t-other", "span": "sc5", "mono_s": 7000.3,
             "duration_s": 0.01, "attrs": {"aggregation": "other999"}},
            {"t": "metrics", "mono_s": 7000.9, "reason": "close",
             "counters": {"http.retry.attempt": 3}},
        ),
    ]
    return forensics.Spool(server + client, torn=1), agg


def test_explain_reconstructs_round_from_dead_processes():
    spool, agg = _synthetic_round_spool()
    report = forensics.explain(spool, agg[:6])  # prefix resolve
    assert report["aggregation"] == agg
    assert report["tenant"] == "tenant-a"
    assert report["epoch"] == {"schedule": "hourly", "epoch": 3,
                               "action": "minted"}
    assert report["traces"] == ["t1", "t2"]
    assert report["processes"] == ["sim[22]", "w0[11]"]
    assert report["final_state"] == "revealed"
    assert [s["state"] for s in report["states"]] == [
        "collecting", "frozen", "revealed"]
    assert report["states"][-1]["reason"] == "reveal"
    p = report["participations"]
    assert p == {"created": 3, "replayed": 1, "conflicts": 1,
                 "participant_spans": 2, "resumed": 1}
    assert report["retries"]["total"] == 2  # from span attrs
    # by_cause sums the LAST snapshot of each process fleet-wide
    assert report["retries"]["by_cause"]["attempt"] == 8
    assert report["retries"]["by_cause"]["status_500"] == 2
    assert report["sheds"] == 1
    assert report["lease_reissues"] == 1
    # fault record + matching event deduped; event-only fault still counts
    assert len(report["faults"]) == 2
    sites = {f["site"]: f["kind"] for f in report["faults"]}
    assert sites == {"store.put": "error", "http.server.request": "delay"}
    assert [j["job"] for j in report["clerk_jobs"]] == ["j2", "j1"]
    assert report["reveal"]["status"] == "ok"
    assert report["reveal"]["output_sha256"] == "ab" * 32
    assert report["reveal"]["dim"] == 4
    assert report["spans"] == 14  # the other round's span excluded
    assert report["torn_lines"] == 1
    # clock merge places the client reveal INSIDE the server's ledger
    # window despite the 7000s monotonic skew
    states = {s["state"]: s["time_s"] for s in report["states"]}
    reveal_t = spool.norm_time(
        next(s for s in spool.spans if s["name"] == "recipient.reveal"))
    assert states["frozen"] < reveal_t < states["revealed"] + 0.2
    text = forensics.format_explain(report)
    assert f"round {agg}" in text
    assert "collecting -> frozen -> revealed[reveal]" in text
    assert "3 created (1 replayed, 1 conflicts, 1 resumed)" in text
    assert "sha256=" + "ab" * 32 in text
    assert "1 torn spool line(s) skipped" in text


def test_chrome_trace_filters_to_one_round():
    spool, agg = _synthetic_round_spool()
    whole = forensics.chrome_trace(spool)
    one = forensics.chrome_trace(spool, agg[:6])
    count = lambda tr: sum(
        1 for ev in tr["traceEvents"] if ev.get("ph") == "X")
    assert count(whole) == 15
    assert count(one) == 14


def test_explain_over_a_live_spool_end_to_end(tmp_path):
    agg = "live00aggid"
    recorder.install(str(tmp_path), node_id="t0", snapshot_s=0.0)
    recorder.record({"t": "round", "aggregation": agg,
                     "state": "collecting", "previous": None,
                     "tenant": "tenant-live"})
    with obs.span("load.round", attributes={"aggregation": agg}):
        with obs.span("server.create_participation",
                      attributes={"aggregation": agg}):
            pass
    recorder.record({"t": "round", "aggregation": agg,
                     "state": "revealed", "previous": "collecting"})
    recorder.uninstall()  # every process is now "dead"
    spool = forensics.load_spool(str(tmp_path))
    report = forensics.explain(spool, "live0")
    assert report["final_state"] == "revealed"
    assert report["tenant"] == "tenant-live"
    assert report["participations"]["created"] == 1
    assert report["spans"] == 2
    assert report["processes"] == [f"t0[{os.getpid()}]"]


# ---------------------------------------------------------------------------
# SLOs and burn rates

def test_rounds_from_spool_outcomes_and_inflight():
    records = [
        _anchor("seg-a", "w0", 1),
        *_seg("seg-a",
              {"t": "round", "aggregation": "A", "state": "collecting",
               "tenant": "t1", "mono_s": 1.0},
              {"t": "round", "aggregation": "A", "state": "revealed",
               "mono_s": 3.0},
              {"t": "round", "aggregation": "B", "state": "collecting",
               "tenant": "t1", "mono_s": 2.0},
              {"t": "round", "aggregation": "B", "state": "failed",
               "mono_s": 4.0},
              {"t": "round", "aggregation": "C", "state": "collecting",
               "tenant": "t2", "mono_s": 5.0}),
    ]
    rounds = slo.rounds_from_spool(forensics.Spool(records))
    by_agg = {r["aggregation"]: r for r in rounds}
    assert by_agg["A"]["good"] is True
    assert by_agg["A"]["duration_s"] == pytest.approx(2.0)
    assert by_agg["A"]["tenant"] == "t1"
    assert by_agg["B"]["good"] is False
    assert by_agg["B"]["final_state"] == "failed"
    assert by_agg["C"]["good"] is None  # in flight when the fleet died
    report = slo.evaluate(rounds)
    t1 = report["tenants"]["t1"]
    assert (t1["settled"], t1["good"], t1["in_flight"]) == (2, 1, 0)
    assert t1["availability"] == 0.5
    t2 = report["tenants"]["t2"]
    assert t2["settled"] == 0 and t2["in_flight"] == 1
    assert t2["availability"] is None and t2["met"] is None


def _round(tenant, end_s, good, duration_s=0.5):
    return {"aggregation": f"{tenant}-{end_s}", "tenant": tenant,
            "end_s": end_s, "duration_s": duration_s,
            "final_state": "revealed" if good else "failed",
            "good": good, "states": []}


def test_burn_page_requires_both_windows():
    policy = slo.SloPolicy(availability_target=0.9,
                           windows=((10.0, 100.0, 2.0),))
    # recent blip: every round in the last 10 s is bad, but the long
    # window is dominated by older good rounds -> burn high/low -> NO page
    blip = ([_round("t1", 910.0 + i, True) for i in range(20)]
            + [_round("t1", 995.0, False), _round("t1", 999.0, False)])
    report = slo.evaluate(blip, policy, now_s=1000.0)
    (win,) = report["tenants"]["t1"]["windows"]
    assert win["short"]["burn"] >= 2.0
    assert win["long"]["burn"] < 2.0
    assert not win["page"] and report["alerts"] == []
    # sustained burn: both windows hot -> page
    sustained = [_round("t1", 905.0 + 5 * i, False) for i in range(20)]
    report = slo.evaluate(sustained, policy, now_s=1000.0)
    (win,) = report["tenants"]["t1"]["windows"]
    assert win["page"]
    assert report["alerts"] and "t1" in report["alerts"][0]


def test_latency_target_makes_slow_reveals_bad():
    policy = slo.SloPolicy(availability_target=0.9, latency_target_s=1.0,
                           windows=((300.0, 3600.0, 1.0),))
    rounds = [_round("t1", 999.0, True, duration_s=5.0),
              _round("t1", 998.0, True, duration_s=0.2)]
    report = slo.evaluate(rounds, policy, now_s=1000.0)
    (win,) = report["tenants"]["t1"]["windows"]
    assert win["short"]["bad"] == 1 and win["short"]["total"] == 2
    # availability itself is untouched — latency shares only the budget
    assert report["tenants"]["t1"]["availability"] == 1.0
    text = slo.format_slo(report)
    assert "reveal latency <= 1s" in text
    assert "t1:" in text


def test_slo_now_defaults_to_end_of_recorded_history():
    # a spool written yesterday must not read as "no recent errors"
    policy = slo.SloPolicy(availability_target=0.9,
                           windows=((10.0, 100.0, 2.0),))
    rounds = [_round("t1", 50.0, False), _round("t1", 55.0, False)]
    report = slo.evaluate(rounds, policy)
    assert report["now_s"] == 55.0
    (win,) = report["tenants"]["t1"]["windows"]
    assert win["page"]


# ---------------------------------------------------------------------------
# chaos fault marks (the structured-failpoint satellite)

@pytest.mark.chaos
def test_chaos_injection_tags_span_event_and_spools_fault(tmp_path):
    recorder.install(str(tmp_path), node_id="w0", snapshot_s=0.0)
    chaos.set_identity("w0")
    chaos.configure("obs.test.site", delay=0.001, times=1)
    with obs.span("victim") as victim:
        assert chaos.fail("obs.test.site") is not None
    recorder.uninstall()
    chaos.set_identity(None)
    (event,) = [ev for s in obs.finished_spans() for ev in s.events
                if ev["name"] == "chaos.obs.test.site"]
    assert event["attributes"]["fault.kind"] == "delay"
    assert event["attributes"]["fault.site"] == "obs.test.site"
    assert event["attributes"]["kind"] == "delay"  # legacy tag stays
    records, _ = recorder.read_spool(str(tmp_path))
    (fault,) = [r for r in records if r["t"] == "fault"]
    assert fault["site"] == "obs.test.site"
    assert fault["kind"] == "delay"
    assert fault["node"] == "w0"
    assert fault["trace"] == victim.trace_id
    assert fault["span"] == victim.span_id


# ---------------------------------------------------------------------------
# one bucket format: /metrics exposition vs spooled snapshots

def test_label_escape_round_trips():
    tricky = [
        'plain', 'with "quotes"', 'back\\slash', 'new\nline',
        'GET:/v1/agents/{id}', 'mix \\"n\\" match\n\\', 'a\\nb',
        'trailing backslash\\',
    ]
    for s in tricky:
        assert metrics.unescape_label(metrics._escape_label(s)) == s


def test_snapshot_buckets_match_prometheus_le_lines():
    metrics.reset_all()
    for v in (1e-5, 3e-4, 3e-4, 0.002, 0.1, 7.0):
        metrics.observe("bucket.parity", v)
    snap = metrics.snapshot()["histograms"]["bucket.parity"]
    text_rows = []
    for line in metrics.prometheus_text().splitlines():
        if line.startswith('sda_histogram_bucket{name="bucket.parity"'):
            le = line.split('le="', 1)[1].split('"', 1)[0]
            text_rows.append([metrics.unescape_label(le),
                              int(line.rsplit(" ", 1)[1])])
    assert text_rows == snap["buckets"]
    assert snap["buckets"][-1] == ["+Inf", 6]
    assert snap["count"] == 6
    assert snap["sum"] == pytest.approx(sum((1e-5, 3e-4, 3e-4,
                                             0.002, 0.1, 7.0)))
