"""bench.py reuse of a watch-captured in-window TPU result (verdict #3).

The driver's bench invocation has landed on the CPU fallback four rounds
running because the tunnel never answered at driver time. The watch now
saves bench.py's own in-window TPU line to benchmarks/BENCH_TPU_CAPTURE.json
and a later tunnel-down bench run re-emits it with explicit provenance.
These tests pin the gate: platform must be tpu, the value numeric, the
capture fresh (age window), and the provenance fields present — a stale or
malformed capture falls through to the old CPU-floor behavior.
"""

import datetime
import importlib.util
import json
import os
import sys

_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")
_spec = importlib.util.spec_from_file_location("bench_root", _PATH)
bench = importlib.util.module_from_spec(_spec)
sys.modules["bench_root"] = bench
_spec.loader.exec_module(bench)


def _write(tmp_path, monkeypatch, payload):
    path = tmp_path / "BENCH_TPU_CAPTURE.json"
    path.write_text(json.dumps(payload))
    monkeypatch.setattr(bench, "_CAPTURE_PATH", str(path))
    return path


def _now(hours_ago=0.0):
    return (
        datetime.datetime.now(datetime.timezone.utc)
        - datetime.timedelta(hours=hours_ago)
    ).isoformat(timespec="seconds")


def test_fresh_capture_reused_with_provenance(tmp_path, monkeypatch):
    _write(tmp_path, monkeypatch, {
        "captured_at": _now(2.0),
        "result": {"metric": "x", "value": 5.8e9, "unit": "elements/sec",
                   "platform": "tpu", "vs_baseline": 5.8},
    })
    got = bench._fresh_tpu_capture()
    assert got is not None
    assert got["value"] == 5.8e9 and got["platform"] == "tpu"
    assert got["reused_capture"] is True
    assert "hw_check --watch" in got["provenance"]
    assert "2.0h before this run" in got["provenance"]


def test_stale_capture_rejected(tmp_path, monkeypatch):
    _write(tmp_path, monkeypatch, {
        "captured_at": _now(bench._CAPTURE_MAX_AGE_H + 1),
        "result": {"value": 1e9, "platform": "tpu"},
    })
    assert bench._fresh_tpu_capture() is None


def test_future_timestamp_rejected(tmp_path, monkeypatch):
    _write(tmp_path, monkeypatch, {
        "captured_at": _now(-3.0),  # clock skew / tampering: not "fresh"
        "result": {"value": 1e9, "platform": "tpu"},
    })
    assert bench._fresh_tpu_capture() is None


def test_cpu_capture_rejected(tmp_path, monkeypatch):
    _write(tmp_path, monkeypatch, {
        "captured_at": _now(1.0),
        "result": {"value": 8e6, "platform": "cpu"},
    })
    assert bench._fresh_tpu_capture() is None


def test_malformed_capture_rejected(tmp_path, monkeypatch):
    for payload in ({}, {"captured_at": _now(1.0)},
                    {"captured_at": _now(1.0), "result": {"platform": "tpu"}},
                    {"captured_at": "not-a-date",
                     "result": {"value": 1.0, "platform": "tpu"}}):
        _write(tmp_path, monkeypatch, payload)
        assert bench._fresh_tpu_capture() is None
    monkeypatch.setattr(bench, "_CAPTURE_PATH",
                        str(tmp_path / "missing.json"))
    assert bench._fresh_tpu_capture() is None
