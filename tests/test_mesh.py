"""Simulated-pod tests on the virtual 8-device CPU mesh.

Validates that the one-program SPMD round (psum_scatter transpose+combine,
all_gather reconstruct) computes exactly what the protocol stack computes.
"""

import os

import jax
import numpy as np
import pytest

from sda_tpu.mesh import SimulatedPod, default_mesh_shape, make_mesh, single_chip_round
from sda_tpu.protocol import (
    AdditiveSharing,
    ChaChaMasking,
    FullMasking,
    PackedShamirSharing,
)

GOLDEN = PackedShamirSharing(3, 8, 4, 433, 354, 150)


from util import scheme_lattice_config as _pod_scheme_config


def needs_devices(n):
    return pytest.mark.skipif(
        len(jax.devices()) < n, reason=f"needs {n} virtual devices"
    )


@needs_devices(8)
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_pod_aggregate_matches_sum(mesh_shape):
    mesh = make_mesh(*mesh_shape)
    pod = SimulatedPod(GOLDEN, mesh=mesh)
    P_total, d = 16, 48  # divisible by p axis and by k*d_shards for all shapes
    rng = np.random.default_rng(0)
    inputs = rng.integers(0, 20, size=(P_total, d))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


@needs_devices(8)
def test_pod_with_full_masking():
    pod = SimulatedPod(GOLDEN, masking_scheme=FullMasking(433), mesh=make_mesh(4, 2))
    P_total, d = 8, 24
    rng = np.random.default_rng(1)
    inputs = rng.integers(0, 433, size=(P_total, d))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


@needs_devices(8)
def test_pod_deterministic_given_key():
    pod = SimulatedPod(GOLDEN, mesh=make_mesh(4, 2))
    inputs = np.ones((8, 24), dtype=np.int64)
    key = jax.random.PRNGKey(7)
    a = np.asarray(pod.aggregate(inputs, key))
    b = np.asarray(pod.aggregate(inputs, key))
    np.testing.assert_array_equal(a, b)


@needs_devices(8)
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2)])
@pytest.mark.parametrize("config", [
    "add-none", "add-full", "add-chacha", "shamir-none", "shamir-full",
    "shamir-chacha", "basic-none", "basic-full", "basic-chacha",
])
def test_pod_scheme_parity(mesh_shape, config):
    """Every masking x sharing point of the scheme lattice runs in pod mode
    and aggregates exactly — round-1 verdict: only shamir/full did."""
    dim = 50  # off-grain on purpose: exercises auto-padding for every config
    sharing, masking = _pod_scheme_config(config, dim)
    pod = SimulatedPod(sharing, masking_scheme=masking, mesh=make_mesh(*mesh_shape))
    rng = np.random.default_rng(11)
    inputs = rng.integers(0, 433, size=(6, dim))
    out = np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


@pytest.mark.parametrize("config", [
    "add-none", "add-full", "add-chacha", "shamir-none", "shamir-full",
    "shamir-chacha", "basic-none", "basic-full", "basic-chacha",
])
def test_single_chip_scheme_parity(config):
    """The collective-free round covers the same scheme lattice (ChaCha
    dims must align to the 8-draw ChaCha block)."""
    dim = 48
    sharing, masking = _pod_scheme_config(config, dim)
    if config.startswith("add"):
        sharing = AdditiveSharing(share_count=3, modulus=433)  # golden 3-way
    fn = jax.jit(single_chip_round(sharing, masking))
    rng = np.random.default_rng(12)
    inputs = rng.integers(0, 433, size=(5, dim))
    out = np.asarray(fn(inputs, jax.random.PRNGKey(4)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


def test_single_chip_additive_large_modulus():
    """Additive sharing needs no prime: any ring modulus < 2^62 works —
    including moduli where a flat int64 sum of 8 shares would wrap 2^63
    (reviewer repro: modsum must chunk-fold, not plain-sum)."""
    m = (1 << 61) + 3
    fn = jax.jit(single_chip_round(
        AdditiveSharing(share_count=8, modulus=m), FullMasking(m)))
    rng = np.random.default_rng(13)
    inputs = rng.integers(0, 1 << 50, size=(6, 16))
    out = np.asarray(fn(inputs, jax.random.PRNGKey(5)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % m)


@needs_devices(8)
def test_pod_chacha_sharding_invariant():
    """Seed-compressed masks must expand consistently across dim shards:
    the same round key yields the same aggregate on a (8,1) and a (4,2)
    mesh, and both equal the plain sum."""
    dim = 48
    sharing, masking = _pod_scheme_config("shamir-chacha", dim)
    rng = np.random.default_rng(14)
    inputs = rng.integers(0, 433, size=(8, dim))
    outs = []
    for shape in [(8, 1), (4, 2)]:
        pod = SimulatedPod(sharing, masking_scheme=masking,
                           mesh=make_mesh(*shape))
        outs.append(np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(6))))
    np.testing.assert_array_equal(outs[0], inputs.sum(axis=0) % 433)
    np.testing.assert_array_equal(outs[1], inputs.sum(axis=0) % 433)


@needs_devices(8)
def test_pod_large_committee_exact():
    """80-clerk Packed-Shamir committee (81 = 3^4 points) as one SPMD
    round: the clerk axis splits 10 rows per device over the 8-way p axis."""
    from sda_tpu.fields import numtheory

    t, p, w2, w3 = numtheory.generate_packed_params(3, 80, 20)
    s = PackedShamirSharing(3, 80, t, p, w2, w3)
    pod = SimulatedPod(s, mesh=make_mesh(8, 1))
    rng = np.random.default_rng(15)
    inputs = rng.integers(0, 433, size=(8, 24))
    out = np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % p)


def test_default_mesh_shape():
    assert default_mesh_shape(8, 8) == (8, 1)
    assert default_mesh_shape(6, 8) == (2, 3)
    assert default_mesh_shape(5, 8) == (1, 5)


@needs_devices(8)
def test_pod_auto_padding():
    """Shapes off the mesh/scheme grain are zero-padded, not rejected
    (round-1 verdict: divisibility errors pushed padding onto callers)."""
    pod = SimulatedPod(GOLDEN, mesh=make_mesh(4, 2))
    rng = np.random.default_rng(4)
    for P_total, dim in [(7, 24), (8, 25), (5, 7)]:
        inputs = rng.integers(0, 433, size=(P_total, dim))
        out = np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(1)))
        assert out.shape == (dim,)
        np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


def test_pod_scheme_validation():
    with pytest.raises(ValueError):
        SimulatedPod(GOLDEN, mesh=make_mesh(8, 1), masking_scheme="bogus")
    with pytest.raises(ValueError):
        # mask modulus must equal the sharing prime or masks don't cancel
        SimulatedPod(GOLDEN, mesh=make_mesh(8, 1), masking_scheme=FullMasking(1000))


@needs_devices(8)
def test_pod_noncanonical_inputs():
    """Regression: unmasked inputs outside [0, p) must be canonicalized
    before sharing, not silently overflowed."""
    from sda_tpu.mesh import single_chip_round
    import jax.numpy as jnp

    from sda_tpu.fields import numtheory
    from sda_tpu.protocol import PackedShamirSharing

    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 29)
    scheme = PackedShamirSharing(3, 8, t, p, w2, w3)
    fn = jax.jit(single_chip_round(scheme))
    inputs = jnp.full((4, 6), 1 << 40, dtype=jnp.int64)
    out = np.asarray(fn(inputs, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(out, np.full(6, (4 * (1 << 40)) % p))


def test_share_sum_stage_equals_per_participant_fold():
    """_share_sum_stage's linearity fusion must be bit-exact vs summing
    per-participant share rows drawn from the same key (both schemes,
    both field paths)."""
    import jax.numpy as jnp

    from sda_tpu.fields import numtheory, sharing
    from sda_tpu.fields.ops import FieldOps
    from sda_tpu.mesh.simpod import _build_matrices, _share_sum_stage

    key = jax.random.PRNGKey(17)
    rng = np.random.default_rng(17)

    for scheme in (
        GOLDEN,                                    # generic int64 path
        PackedShamirSharing(                       # uint32 Solinas path
            3, 8, *numtheory.generate_packed_params(3, 8, 28)[0:1],
            *numtheory.generate_packed_params(3, 8, 28)[1:],
        ),
        AdditiveSharing(share_count=8, modulus=433),
    ):
        mod = getattr(scheme, "prime_modulus", getattr(scheme, "modulus", None))
        f = FieldOps.create(mod)
        M_host, _ = _build_matrices(scheme)
        masked = f.to_residues(rng.integers(0, mod, size=(5, 36)))
        fused = np.asarray(_share_sum_stage(scheme, f, M_host, masked, key))
        if isinstance(scheme, PackedShamirSharing):
            if f.sp is not None:
                per = sharing.packed_share32(
                    key, masked, M_host, f.sp,
                    secret_count=scheme.secret_count,
                    privacy_threshold=scheme.privacy_threshold,
                )
            else:
                per = sharing.packed_share(
                    key, masked, jnp.asarray(M_host),
                    prime=scheme.prime_modulus,
                    secret_count=scheme.secret_count,
                    privacy_threshold=scheme.privacy_threshold,
                )
        else:
            per = sharing.additive_share(
                key, masked, share_count=scheme.share_count, modulus=mod
            )
        np.testing.assert_array_equal(
            fused, np.asarray(f.sum(per, axis=0)),
            err_msg=f"linearity fusion diverged for {type(scheme).__name__}",
        )


@needs_devices(8)
def test_pod_aggregate_fn_compiles_and_runs():
    """aggregate_fn: the raw jitted SPMD round exposed for benchmarking and
    compile checks must lower and execute on mesh-aligned shapes."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = make_mesh(8, 1)
    pod = SimulatedPod(GOLDEN, FullMasking(433), mesh=mesh)
    P_total, d_total = 16, 24
    fn = pod.aggregate_fn(P_total, d_total)
    rng = np.random.default_rng(3)
    x = rng.integers(0, 433, size=(P_total, d_total))
    dev = jax.device_put(
        jnp.asarray(x), NamedSharding(mesh, PartitionSpec("p", "d"))
    )
    out = np.asarray(fn(dev, jax.random.PRNGKey(4)))
    np.testing.assert_array_equal(out, x.sum(axis=0) % 433)


@needs_devices(8)
def test_multislice_mesh_pod_and_streamed_exact():
    """2 slices x 2 p-shards x 2 d-shards: the slice-major participant axis
    (make_multislice_mesh layout rule — d stays on intra-slice ICI, only the
    p-fold crosses the DCN boundary) is transparent to both pod modes."""
    from sda_tpu.mesh import StreamedPod, make_multislice_mesh

    mesh = make_multislice_mesh(2, 2, 2)
    assert mesh.devices.shape == (4, 2)
    assert mesh.axis_names == ("p", "d")
    # slice-contiguity: each slice's block holds consecutive devices
    flat = mesh.devices.reshape(2, 2, 2).reshape(2, -1)
    for slice_devs in flat:
        ids = sorted(d.id for d in slice_devs)
        assert ids == list(range(ids[0], ids[0] + 4))

    rng = np.random.default_rng(3)
    inputs = rng.integers(0, 50, size=(8, 24))
    pod = SimulatedPod(GOLDEN, masking_scheme=FullMasking(433), mesh=mesh)
    np.testing.assert_array_equal(
        np.asarray(pod.aggregate(inputs, key=jax.random.PRNGKey(0))),
        inputs.sum(axis=0) % 433,
    )

    streamed = StreamedPod(
        AdditiveSharing(share_count=8, modulus=433),
        ChaChaMasking(433, 24, 128),
        mesh=mesh,
        participants_chunk=4,
        dim_chunk=12,
    )
    np.testing.assert_array_equal(
        np.asarray(streamed.aggregate(inputs, key=jax.random.PRNGKey(1))),
        inputs.sum(axis=0) % 433,
    )


@pytest.mark.parametrize("n_devices,shapes", [
    (16, ((8, 2), (4, 4), (2, 8))),
    (32, ((8, 4), (4, 8))),
])
def test_wide_virtual_mesh_rounds_subprocess(n_devices, shapes):
    """16- and 32-device meshes (beyond the suite's 8 virtual devices):
    packed + BasicShamir quorum rounds on several (p, d) factorizations.
    Runs in a subprocess because the virtual device count is fixed at
    backend init (round-3 verdict #6: the 8x1 shape can't catch the
    divisibility/sharding bugs wider meshes and d-heavy shards can)."""
    import subprocess
    import sys
    import textwrap

    code = textwrap.dedent(f"""
        from sda_tpu.utils.backend import force_cpu
        force_cpu({n_devices})
        import jax
        import numpy as np
        from sda_tpu.mesh import SimulatedPod, make_mesh
        from sda_tpu.protocol import (BasicShamirSharing, FullMasking,
                                      PackedShamirSharing)

        scheme = PackedShamirSharing(3, 8, 4, 433, 354, 150)
        basic = BasicShamirSharing(share_count=8, privacy_threshold=3,
                                   prime_modulus=433)
        rng = np.random.default_rng(0)
        for ps, ds in {shapes!r}:
            mesh = make_mesh(ps, ds)
            dim = scheme.secret_count * ds * 4
            x = rng.integers(0, 50, size=(2 * ps + 1, dim))
            exp = x.sum(axis=0) % 433
            pod = SimulatedPod(scheme, masking_scheme=FullMasking(433),
                               mesh=mesh)
            np.testing.assert_array_equal(
                np.asarray(pod.aggregate(x, key=jax.random.PRNGKey(1))), exp)
            bpod = SimulatedPod(basic, masking_scheme=FullMasking(433),
                                mesh=mesh, surviving_clerks=(1, 3, 5, 7))
            np.testing.assert_array_equal(
                np.asarray(bpod.aggregate(x, key=jax.random.PRNGKey(2))), exp)
            print("OK", ps, ds, flush=True)
    """)
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=900,
                       env={**os.environ, "XLA_FLAGS": ""})
    assert r.returncode == 0, r.stderr[-2000:]
    for ps, ds in shapes:
        assert f"OK {ps} {ds}" in r.stdout
