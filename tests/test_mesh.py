"""Simulated-pod tests on the virtual 8-device CPU mesh.

Validates that the one-program SPMD round (psum_scatter transpose+combine,
all_gather reconstruct) computes exactly what the protocol stack computes.
"""

import jax
import numpy as np
import pytest

from sda_tpu.mesh import SimulatedPod, default_mesh_shape, make_mesh
from sda_tpu.protocol import FullMasking, PackedShamirSharing

GOLDEN = PackedShamirSharing(3, 8, 4, 433, 354, 150)


def needs_devices(n):
    return pytest.mark.skipif(
        len(jax.devices()) < n, reason=f"needs {n} virtual devices"
    )


@needs_devices(8)
@pytest.mark.parametrize("mesh_shape", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_pod_aggregate_matches_sum(mesh_shape):
    mesh = make_mesh(*mesh_shape)
    pod = SimulatedPod(GOLDEN, mesh=mesh)
    P_total, d = 16, 48  # divisible by p axis and by k*d_shards for all shapes
    rng = np.random.default_rng(0)
    inputs = rng.integers(0, 20, size=(P_total, d))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


@needs_devices(8)
def test_pod_with_full_masking():
    pod = SimulatedPod(GOLDEN, masking_scheme=FullMasking(433), mesh=make_mesh(4, 2))
    P_total, d = 8, 24
    rng = np.random.default_rng(1)
    inputs = rng.integers(0, 433, size=(P_total, d))
    out = np.asarray(pod.aggregate(inputs))
    np.testing.assert_array_equal(out, inputs.sum(axis=0) % 433)


@needs_devices(8)
def test_pod_deterministic_given_key():
    pod = SimulatedPod(GOLDEN, mesh=make_mesh(4, 2))
    inputs = np.ones((8, 24), dtype=np.int64)
    key = jax.random.PRNGKey(7)
    a = np.asarray(pod.aggregate(inputs, key))
    b = np.asarray(pod.aggregate(inputs, key))
    np.testing.assert_array_equal(a, b)


def test_default_mesh_shape():
    assert default_mesh_shape(8, 8) == (8, 1)
    assert default_mesh_shape(6, 8) == (2, 3)
    assert default_mesh_shape(5, 8) == (1, 5)


@needs_devices(8)
def test_pod_shape_validation():
    pod = SimulatedPod(GOLDEN, mesh=make_mesh(4, 2))
    with pytest.raises(ValueError):
        pod.aggregate(np.ones((7, 24), dtype=np.int64))  # P not divisible by 4
    with pytest.raises(ValueError):
        pod.aggregate(np.ones((8, 25), dtype=np.int64))  # d not divisible by k*d'
    with pytest.raises(ValueError):
        SimulatedPod(GOLDEN, mesh=make_mesh(8, 1), masking_scheme="bogus")
    with pytest.raises(ValueError):
        # mask modulus must equal the sharing prime or masks don't cancel
        SimulatedPod(GOLDEN, mesh=make_mesh(8, 1), masking_scheme=FullMasking(1000))


@needs_devices(8)
def test_pod_noncanonical_inputs():
    """Regression: unmasked inputs outside [0, p) must be canonicalized
    before sharing, not silently overflowed."""
    from sda_tpu.mesh import single_chip_round
    import jax.numpy as jnp

    from sda_tpu.fields import numtheory
    from sda_tpu.protocol import PackedShamirSharing

    t, p, w2, w3 = numtheory.generate_packed_params(3, 8, 29)
    scheme = PackedShamirSharing(3, 8, t, p, w2, w3)
    fn = jax.jit(single_chip_round(scheme))
    inputs = jnp.full((4, 6), 1 << 40, dtype=jnp.int64)
    out = np.asarray(fn(inputs, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(out, np.full(6, (4 * (1 << 40)) % p))
