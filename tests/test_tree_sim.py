"""The population-scale tree simulator (sda_tpu/tree/sim.py): exactness
of the tree algebra against the flat reference walk, bounded per-node
memory independent of the population, determinism, and a BENCH-shaped
record the regression gate parses.
"""

import json

import pytest

from sda_tpu.obs import regress
from sda_tpu.tree import simulate_population_round


class TestSimExactness:
    def test_tree_total_matches_flat_walk(self):
        record = simulate_population_round(
            20_000, group_size=2048, dim=4, batch=512, seed=3)
        assert record["exact"] is True
        assert record["groups"] == 10
        assert record["depth"] == 2

    def test_deterministic_at_fixed_seed(self):
        a = simulate_population_round(
            5_000, group_size=512, dim=4, batch=256, seed=11)
        b = simulate_population_round(
            5_000, group_size=512, dim=4, batch=256, seed=11)
        for key in ("exact", "groups", "peak_node_elements", "group_min",
                    "group_max"):
            assert a[key] == b[key]

    def test_multi_level_tree(self):
        record = simulate_population_round(
            8_000, group_size=256, fanout=8, dim=2, batch=128, seed=5)
        assert record["exact"] is True
        assert record["depth"] >= 3


class TestBoundedMemory:
    def test_peak_is_batch_bound_not_population(self):
        """The acceptance bound: peak live elements per node is a
        function of (batch, dim) only — growing the population 4x leaves
        it untouched."""
        small = simulate_population_round(
            5_000, group_size=1024, dim=4, batch=256, seed=7)
        large = simulate_population_round(
            20_000, group_size=1024, dim=4, batch=256, seed=7)
        assert small["bounded"] and large["bounded"]
        assert large["peak_node_elements"] <= large["bound_elements"]
        assert large["peak_node_elements"] == small["peak_node_elements"]
        assert large["bound_elements"] == small["bound_elements"]
        # the measured half: tracemalloc peak of the 4x population's
        # streaming pass stays under the SAME batch-derived bound
        assert large["peak_pass_bytes"] <= large["bound_pass_bytes"]
        assert large["bound_pass_bytes"] == small["bound_pass_bytes"]

    @pytest.mark.slow
    def test_full_population_1e5(self):
        """The headline drill size: a fixed-seed 10^5-participant
        2-level tree completes, bit-exact, with bounded per-node
        memory."""
        record = simulate_population_round(100_000, seed=20260803)
        assert record["participants"] == 100_000
        assert record["depth"] == 2
        assert record["exact"] is True
        assert record["bounded"] is True


class TestBenchRecord:
    def test_record_parses_through_the_gate(self, tmp_path):
        record = simulate_population_round(
            5_000, group_size=512, dim=4, batch=256, seed=1)
        for key in ("metric", "value", "unit", "platform", "seed"):
            assert key in record
        path = tmp_path / "TREE_r01.json"
        path.write_text(json.dumps(record))
        entries = regress.load_records([str(path)])
        assert len(entries) == 1
        assert entries[0]["record"] is not None
        assert entries[0]["record"]["value"] == record["value"]
        # one record seeds its metric's window: the gate passes (advisory
        # first-of-metric), never errors on the shape
        verdict = regress.check(entries)
        assert verdict["regressions"] == []
