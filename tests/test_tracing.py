"""Distributed tracing (ISSUE 3): span layer semantics, W3C context
propagation across the HTTP seam, trace integrity under adversity
(throttle retries, chaos 500s/drops, lease-reissued clerking jobs), the
Chrome-trace export tree, X-Request-Id correlation, JSON logs joined to
traces, the unified observability reset, and the Prometheus exposition
golden consistency check.
"""

import json
import logging
import time

import pytest

from sda_tpu import chaos, obs
from sda_tpu.http import SdaHttpClient, SdaHttpServer
from sda_tpu.server import new_memory_server
from sda_tpu.utils import metrics
from sda_tpu.utils.logsetup import JsonFormatter


@pytest.fixture(autouse=True)
def _clean_slate():
    chaos.reset()
    obs.reset_all()
    yield
    chaos.reset()
    obs.reset_all()
    obs.seed_ids(None)


# ---------------------------------------------------------------------------
# span layer semantics

def test_span_nesting_parents_and_buffer():
    with obs.span("outer", attributes={"k": 1}) as outer:
        assert obs.current_span() is outer
        with obs.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
            assert inner.parent_id == outer.span_id
            obs.add_event("tick", n=3)
            obs.set_attribute("marked", True)
        assert obs.current_span() is outer
    assert obs.current_span() is None
    spans = obs.finished_spans()
    assert [s.name for s in spans] == ["inner", "outer"]  # finish order
    inner_, outer_ = spans
    assert outer_.parent_id is None
    assert outer_.attributes["k"] == 1
    assert inner_.attributes["marked"] is True
    assert inner_.events[0]["name"] == "tick"
    assert inner_.events[0]["attributes"] == {"n": 3}
    assert inner_.duration_s is not None and inner_.duration_s >= 0.0


def test_span_error_status_on_exception():
    with pytest.raises(RuntimeError):
        with obs.span("boom"):
            raise RuntimeError("kapow")
    span = obs.finished_spans()[-1]
    assert span.status == "error"
    assert "kapow" in span.attributes["error"]


def test_explicit_remote_parent_adopts_trace():
    remote = obs.SpanContext("ab" * 16, "cd" * 8)
    with obs.span("local-root"):
        with obs.span("adopted", parent=remote) as adopted:
            assert adopted.trace_id == remote.trace_id
            assert adopted.parent_id == remote.span_id


def test_deterministic_ids_under_seed():
    def run():
        obs.reset_spans()
        with obs.span("a"):
            with obs.span("b"):
                pass
        return [(s.trace_id, s.span_id) for s in obs.finished_spans()]

    obs.seed_ids(1234)
    first = run()
    obs.seed_ids(1234)
    second = run()
    assert first == second
    obs.seed_ids(None)
    assert run() != first  # cryptographically random again


def test_traceparent_roundtrip_and_garbage():
    ctx = obs.SpanContext("0123456789abcdef" * 2, "fedcba9876543210")
    header = obs.format_traceparent(ctx)
    assert header == f"00-{ctx.trace_id}-{ctx.span_id}-01"
    parsed = obs.parse_traceparent(header)
    assert parsed == ctx
    for garbage in (None, "", "nonsense", "00-short-short-01",
                    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # zero trace
                    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # zero span
                    "zz-" + "1" * 32 + "-" + "2" * 16 + "-01"):
        assert obs.parse_traceparent(garbage) is None, garbage


def test_job_links_bounded_and_lookup():
    ctx = obs.SpanContext("11" * 16, "22" * 8)
    obs.link_job("job-1", ctx)
    obs.link_job("job-none", None)  # ignored
    assert obs.job_link("job-1") == ctx
    assert obs.job_link("job-none") is None
    assert obs.job_link("never") is None


def test_chrome_trace_export_structure():
    with obs.span("participant.mask"):
        obs.add_event("chaos.fake", kind="error")
    trace = obs.chrome_trace()
    events = trace["traceEvents"]
    metas = [e for e in events if e["ph"] == "M"]
    xs = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert any(m["args"]["name"] == "participant" for m in metas)
    assert len(xs) == 1 and len(instants) == 1
    x = xs[0]
    assert x["name"] == "participant.mask"
    assert x["dur"] >= 0 and x["ts"] > 0
    assert set(x["args"]) >= {"trace_id", "span_id"}
    assert instants[0]["name"] == "chaos.fake"
    assert instants[0]["args"]["span_id"] == x["args"]["span_id"]


def test_merge_chrome_traces_remaps_pids():
    a = {"traceEvents": [{"name": "x", "ph": "X", "pid": 1, "ts": 0, "dur": 1}]}
    b = {"traceEvents": [{"name": "y", "ph": "X", "pid": 1, "ts": 0, "dur": 1}]}
    merged = obs.merge_chrome_traces(a, b)
    pids = [e["pid"] for e in merged["traceEvents"]]
    assert len(set(pids)) == 2  # no collision after merge


def test_timeline_critical_path_and_slowest():
    def fake(name, span_id, parent_id, start, dur, trace="t1" * 16):
        s = obs.Span(name, trace, span_id, parent_id, "internal", None)
        s.start_s = start
        s.duration_s = dur
        return s

    root = fake("round", "a" * 16, None, 0.0, 10.0)
    fast = fake("load.participant", "b" * 16, "a" * 16, 1.0, 2.0)
    slow = fake("load.participant", "c" * 16, "a" * 16, 2.0, 7.0)
    leaf = fake("http.client GET /x", "d" * 16, "c" * 16, 8.0, 0.9)
    spans = [root, fast, slow, leaf]
    timelines = obs.round_timelines(spans)
    assert len(timelines) == 1
    t = timelines[0]
    assert t["root"] == "round" and t["spans"] == 4
    # critical path follows the child that ENDED last at each level
    assert [p["name"] for p in t["critical_path"]] == [
        "round", "load.participant", "http.client GET /x"]
    exemplars = obs.slowest_spans("load.participant", n=1, spans=spans)
    assert exemplars[0]["span_id"] == "c" * 16
    assert exemplars[0]["critical_path"][0]["duration_ms"] == 7000.0


def test_reset_all_clears_every_registry():
    from sda_tpu.utils import phase_report, timed_phase

    metrics.count("reset.test")
    metrics.gauge_set("reset.gauge", 1.0)
    metrics.observe("reset.hist", 0.5)
    with timed_phase("reset.phase"):
        pass
    obs.link_job("reset-job", obs.SpanContext("33" * 16, "44" * 8))
    assert obs.finished_spans() and phase_report()
    obs.reset_all()
    assert obs.finished_spans() == []
    assert phase_report() == {}
    assert metrics.counter_report() == {}
    assert metrics.gauge_report() == {}
    assert metrics.histogram_report() == {}
    assert obs.job_link("reset-job") is None


# ---------------------------------------------------------------------------
# propagation across the HTTP seam

def _server_client(**server_kwargs):
    server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0",
                           **server_kwargs).start_background()
    client = SdaHttpClient(server.address, token="trace-test-token",
                           max_retries=8, backoff_base=0.01, backoff_cap=0.1)
    return server, client


def _spans_by_name(prefix):
    return [s for s in obs.finished_spans() if s.name.startswith(prefix)]


def test_traceparent_joins_server_to_client_trace():
    server, client = _server_client()
    try:
        with obs.span("op-root") as root:
            assert client.ping().running
    finally:
        server.shutdown()
    attempts = _spans_by_name("http.attempt")
    servers = _spans_by_name("http.server")
    assert attempts and servers
    assert all(s.trace_id == root.trace_id for s in attempts + servers)
    # the server span's parent is the exact attempt that carried the header
    assert servers[0].parent_id in {a.span_id for a in attempts}
    assert servers[0].attributes["http.status"] == 200
    assert servers[0].attributes["http.route"] == "GET:/v1/ping"


def test_trace_survives_429_retry_after_convergence():
    # burst 1 @ 2/s: the second immediate ping is shed with Retry-After
    # and must converge through the hint — in the SAME trace
    server, client = _server_client(rate_limit=2.0, rate_burst=1.0)
    try:
        with obs.span("op-root") as root:
            assert client.ping().running
            assert client.ping().running
    finally:
        server.shutdown()
    assert metrics.counter_report()["http.retry.status_429"] >= 1
    retried = [s for s in _spans_by_name("http.attempt")
               if s.attributes["attempt"] >= 1]
    assert retried, "expected at least one retry attempt span"
    hinted = [s for s in _spans_by_name("http.attempt")
              if "retry_after_s" in s.attributes]
    assert hinted and all(s.attributes["retry_after_s"] >= 0 for s in hinted)
    shed = [s for s in _spans_by_name("http.server")
            if s.attributes.get("http.status") == 429]
    assert shed and all(s.attributes.get("shed") for s in shed)
    for s in _spans_by_name("http.attempt") + _spans_by_name("http.server"):
        assert s.trace_id == root.trace_id


def test_trace_survives_chaos_500_and_drop():
    server, client = _server_client()
    try:
        chaos.configure("http.server.request", error=True, times=1)
        chaos.configure("http.server.response", drop=True, times=1)
        with obs.span("op-root") as root:
            assert client.ping().running
    finally:
        chaos.reset()
        server.shutdown()
    assert metrics.counter_report()["http.retry.recovered"] >= 1
    servers = _spans_by_name("http.server")
    assert all(s.trace_id == root.trace_id for s in servers)
    injected = [ev for s in servers for ev in s.events
                if ev["name"].startswith("chaos.")]
    kinds = {ev["attributes"]["kind"] for ev in injected}
    assert kinds == {"error", "drop"}  # both injections visible in the trace
    # the 500'd attempt and the successful one are siblings under one op
    ops = _spans_by_name("http.client GET /v1/ping")
    assert ops and ops[-1].attributes.get("retries", 0) >= 1


def test_x_request_id_echoed_and_logged():
    import io
    import urllib.request

    buf = io.StringIO()
    handler = logging.StreamHandler(buf)
    http_log = logging.getLogger("sda_tpu.http.server")
    http_log.addHandler(handler)
    old_level = http_log.level
    http_log.setLevel(logging.INFO)
    server = SdaHttpServer(new_memory_server(), bind="127.0.0.1:0",
                           trace_log=True).start_background()
    try:
        # minted when absent
        with urllib.request.urlopen(server.address + "/v1/ping") as resp:
            minted = resp.headers.get("X-Request-Id")
            assert minted and len(minted) == 16
        # reused when present
        req = urllib.request.Request(server.address + "/v1/ping",
                                     headers={"X-Request-Id": "my-correlation"})
        with urllib.request.urlopen(req) as resp:
            assert resp.headers.get("X-Request-Id") == "my-correlation"
        # 4xx replies carry the id in the log line
        try:
            urllib.request.urlopen(server.address + "/v1/nonexistent")
        except urllib.error.HTTPError as e:
            assert e.headers.get("X-Request-Id")
    finally:
        server.shutdown()
        http_log.removeHandler(handler)
        http_log.setLevel(old_level)
    lines = buf.getvalue().splitlines()
    assert any("-> 401" in l and "request_id=" in l for l in lines)
    assert any(l.startswith("trace ") for l in lines)  # --trace span lines
    # the request id is recorded on the server span too
    assert any(s.attributes.get("request_id") == "my-correlation"
               for s in _spans_by_name("http.server"))


def test_json_log_format_carries_trace_ids(monkeypatch):
    from sda_tpu.utils.logsetup import configure_logging, log_format

    monkeypatch.setenv("SDA_LOG_FORMAT", "json")
    assert log_format() == "json"
    configure_logging(1)  # must not raise even when already configured
    formatter = JsonFormatter()
    record = logging.LogRecord("sda_tpu.test", logging.INFO, __file__, 1,
                               "hello %s", ("world",), None)
    with obs.span("logged-op") as span:
        obj = json.loads(formatter.format(record))
        assert obj["message"] == "hello world"
        assert obj["level"] == "INFO"
        assert obj["logger"] == "sda_tpu.test"
        assert obj["trace_id"] == span.trace_id
        assert obj["span_id"] == span.span_id
    outside = json.loads(formatter.format(record))
    assert "trace_id" not in outside  # no active span, no stamp


# ---------------------------------------------------------------------------
# full-round trace integrity (real crypto over real HTTP)

def _run_http_round(lease_seconds=None, abandon_once=False):
    """One full additive round over HTTP under a ``round`` root span;
    returns (root_span, revealed_output, expected)."""
    import numpy as np

    from sda_tpu.client import SdaClient
    from sda_tpu.crypto import MemoryKeystore
    from sda_tpu.protocol import (
        AdditiveSharing,
        Aggregation,
        AggregationId,
        FullMasking,
        SodiumEncryption,
    )

    service = new_memory_server()
    if lease_seconds is not None:
        service.server.clerking_lease_seconds = lease_seconds
    server = SdaHttpServer(service, bind="127.0.0.1:0").start_background()
    try:
        proxy = SdaHttpClient(server.address, token="round-test-token",
                              max_retries=8, backoff_base=0.01,
                              backoff_cap=0.1)

        def new_client():
            keystore = MemoryKeystore()
            agent = SdaClient.new_agent(keystore)
            client = SdaClient(agent, keystore, proxy)
            client.upload_agent()
            return client

        with obs.span("round") as root:
            recipient = new_client()
            recipient_key = recipient.new_encryption_key()
            recipient.upload_encryption_key(recipient_key)
            clerks = []
            for _ in range(3):
                clerk = new_client()
                clerk.upload_encryption_key(clerk.new_encryption_key())
                clerks.append(clerk)
            agg = Aggregation(
                id=AggregationId.random(), title="trace-round",
                vector_dimension=4, modulus=433,
                recipient=recipient.agent.id, recipient_key=recipient_key,
                masking_scheme=FullMasking(433),
                committee_sharing_scheme=AdditiveSharing(share_count=3,
                                                         modulus=433),
                recipient_encryption_scheme=SodiumEncryption(),
                committee_encryption_scheme=SodiumEncryption(),
            )
            recipient.upload_aggregation(agg)
            recipient.begin_aggregation(agg.id)
            inputs = [[1, 2, 3, 4], [10, 20, 30, 40]]
            for row in inputs:
                new_client().participate(row, agg.id)
            recipient.end_aggregation(agg.id)
            if abandon_once:
                chaos.configure("clerk.abandon_job", drop=True, times=1)
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                # the recipient holds a key too, so the election may have
                # put it on the committee — run its chores as well
                for clerk in clerks + [recipient]:
                    clerk.run_chores(-1)
                status = recipient.service.get_aggregation_status(
                    recipient.agent, agg.id)
                if (status and status.snapshots
                        and status.snapshots[0].result_ready
                        and status.snapshots[0].number_of_clerking_results
                        >= 3):
                    break
                time.sleep(0.05)
            output = recipient.reveal_aggregation(agg.id)
        expected = (np.array(inputs).sum(axis=0) % 433).tolist()
        return root, output, expected
    finally:
        chaos.reset()
        server.shutdown()


def _sodium_or_skip():
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")


def test_round_exports_one_connected_trace(tmp_path):
    """ISSUE 3 acceptance: the exported Chrome trace holds participant,
    server, clerk, and recipient spans under ONE trace id with correct
    parent links, and tracing changes no protocol bytes (bit-exact)."""
    _sodium_or_skip()
    root, output, expected = _run_http_round()
    assert output.positive().values.tolist() == expected  # bit-exact

    trace = obs.export_chrome_trace(str(tmp_path / "round.trace.json"))
    reloaded = json.loads((tmp_path / "round.trace.json").read_text())
    assert reloaded == trace
    xs = [e for e in reloaded["traceEvents"] if e["ph"] == "X"]
    by_id = {e["args"]["span_id"]: e for e in xs}
    in_round = [e for e in xs if e["args"]["trace_id"] == root.trace_id]
    roles = {e["name"].split(" ")[0].split(".")[0] for e in in_round}
    assert {"participant", "clerk", "recipient", "server", "http",
            "round"} <= roles
    # every parent link resolves, and walking up from ANY span in the
    # round trace reaches the root
    root_event = next(e for e in in_round
                      if "parent_id" not in e["args"])
    assert root_event["name"] == "round"
    for e in in_round:
        seen = set()
        node = e
        while "parent_id" in node["args"]:
            assert node["args"]["parent_id"] in by_id, node["name"]
            assert node["args"]["span_id"] not in seen  # no cycles
            seen.add(node["args"]["span_id"])
            node = by_id[node["args"]["parent_id"]]
        assert node["args"]["span_id"] == root_event["args"]["span_id"]
    # cross-process link: server spans are children of client attempts
    crossed = [e for e in in_round if e["name"].startswith("http.server")
               and by_id[e["args"]["parent_id"]]["name"] == "http.attempt"]
    assert crossed, "no server span parented to a client attempt"


def test_reissued_clerk_job_parents_to_original_trace():
    """A lease-reissued clerking job (first pull abandoned) must re-join
    the round trace that enqueued it — not start a trace of its own."""
    _sodium_or_skip()
    root, output, expected = _run_http_round(lease_seconds=0.3,
                                             abandon_once=True)
    assert output.positive().values.tolist() == expected
    jobs = [s for s in obs.finished_spans() if s.name == "clerk.job"]
    abandoned = [s for s in jobs if s.attributes.get("abandoned")]
    assert len(abandoned) == 1
    # the reissue: the same job id processed again, successfully
    job_id = abandoned[0].attributes["job"]
    reissues = [s for s in jobs
                if s.attributes["job"] == job_id
                and not s.attributes.get("abandoned")]
    assert reissues, "abandoned job was never reissued"
    assert all(s.trace_id == root.trace_id for s in jobs)
    counters = metrics.counter_report()
    assert counters["server.job.reissued"] >= 1


# ---------------------------------------------------------------------------
# Prometheus exposition golden consistency (satellite)

def test_prometheus_histogram_lines_are_mutually_consistent():
    """_bucket lines must be cumulative and non-decreasing, the +Inf
    bucket must equal _count, and _sum must match the observed total —
    for every histogram, including multi-decade ones."""
    values = {
        "golden.fast": [2e-6, 5e-6, 5e-6, 1e-4],
        "golden.slow": [0.001, 0.5, 0.5, 3.0, 30.0],
    }
    for name, vs in values.items():
        for v in vs:
            metrics.observe(name, v)
    metrics.count("golden.counter", 7)
    metrics.gauge_set("golden.gauge", 2.5)
    text = metrics.prometheus_text()
    assert 'sda_events_total{name="golden.counter"} 7' in text
    assert 'sda_gauge{name="golden.gauge"} 2.5' in text
    import re

    for name, vs in values.items():
        buckets = re.findall(
            rf'sda_histogram_bucket{{name="{name}",le="([^"]+)"}} (\d+)',
            text)
        assert buckets[-1][0] == "+Inf"
        bounds = [float(b) for b, _ in buckets[:-1]]
        counts = [int(c) for _, c in buckets]
        assert bounds == sorted(bounds)
        assert counts == sorted(counts)  # cumulative => non-decreasing
        assert counts[-2] == counts[-1] == len(vs)  # last finite == +Inf
        # every observation is <= some finite bound it was counted under
        assert all(v <= bounds[-1] for v in vs)
        m = re.search(rf'sda_histogram_sum{{name="{name}"}} ([0-9.e+-]+)',
                      text)
        assert m and abs(float(m.group(1)) - sum(vs)) < 1e-9 * max(1, sum(vs))
        m = re.search(rf'sda_histogram_count{{name="{name}"}} (\d+)', text)
        assert m and int(m.group(1)) == len(vs)
        # the report view agrees with the exposition view
        summary = metrics.histogram_report(name)[name]
        assert summary["count"] == len(vs)
        assert abs(summary["sum"] - sum(vs)) < 1e-9 * max(1, sum(vs))
