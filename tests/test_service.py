"""Tier-2: server-logic loop with mocked ciphertexts (reference: service.rs).

Exercises the scheduling machine end-to-end without real crypto: many
participants, snapshot transpose correctness (each clerk's job carries
exactly its column), job-queue drain, result_ready thresholding, and final
result routing.
"""

import pytest

from sda_tpu.protocol import (
    AdditiveSharing,
    Aggregation,
    AggregationId,
    ClerkingResult,
    Committee,
    FullMasking,
    NoMasking,
    NotFound,
    Participation,
    ParticipationId,
    PermissionDenied,
    Snapshot,
    SnapshotId,
    SodiumEncryption,
)
from sda_tpu.server import new_jsonfs_server, new_memory_server, new_sqlite_server

import util
from util import mock_encryption, new_agent, new_full_agent

N_PARTICIPANTS = 100
N_CLERKS = 3


@pytest.fixture(
    params=["memory", "jsonfs", "sqlite", "mongo"] + util.mongo_real_params()
)
def service(request, tmp_path):
    if request.param == "memory":
        return new_memory_server()
    if request.param == "sqlite":
        return new_sqlite_server(tmp_path / "sda.db")
    if request.param == "mongo":
        from fake_mongo import FakeDatabase
        from sda_tpu.server import new_mongo_server

        return new_mongo_server(FakeDatabase())
    if request.param == "mongo-real":
        return util.new_mongo_real_service(request)
    return new_jsonfs_server(tmp_path)


def build_world(service, masking=False):
    recipient, recipient_key = new_full_agent(service)
    clerks = [new_full_agent(service) for _ in range(N_CLERKS)]
    agg = Aggregation(
        id=AggregationId.random(),
        title="scale-test",
        vector_dimension=4,
        modulus=433,
        recipient=recipient.id,
        recipient_key=recipient_key.body.id,
        masking_scheme=FullMasking(433) if masking else NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=N_CLERKS, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(recipient, agg)
    committee = Committee(
        aggregation=agg.id,
        clerks_and_keys=[(a.id, k.body.id) for (a, k) in clerks],
    )
    service.create_committee(recipient, committee)
    return recipient, clerks, agg, committee


def participate_all(service, agg, masking=False):
    participants = []
    for i in range(N_PARTICIPANTS):
        p_agent = new_agent()
        service.create_agent(p_agent, p_agent)
        participation = Participation(
            id=ParticipationId.random(),
            participant=p_agent.id,
            aggregation=agg.id,
            recipient_encryption=(
                mock_encryption(f"mask-{i}".encode()) if masking else None
            ),
            clerk_encryptions=[
                (None, mock_encryption(f"p{i}-c{c}".encode())) for c in range(N_CLERKS)
            ],
        )
        # clerk ids in clerk_encryptions are positional on the server side;
        # fill with the participant id (the transpose never reads them)
        participation.clerk_encryptions = [
            (p_agent.id, e) for (_, e) in participation.clerk_encryptions
        ]
        service.create_participation(p_agent, participation)
        participants.append(p_agent)
    return participants


def test_snapshot_transpose_and_drain(service):
    recipient, clerks, agg, committee = build_world(service)
    participate_all(service, agg)

    status = service.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == N_PARTICIPANTS

    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)

    # each clerk's job holds exactly its own column of the matrix
    # (reference assertion: service.rs:89-92)
    for c, (clerk, _) in enumerate(clerks):
        job = service.get_clerking_job(clerk, clerk.id)
        assert job is not None and job.clerk == clerk.id and job.snapshot == snap.id
        payloads = {bytes(e.value.data) for e in job.encryptions}
        assert payloads == {f"p{i}-c{c}".encode() for i in range(N_PARTICIPANTS)}

        # posting the result drains the queue
        service.create_clerking_result(
            clerk,
            ClerkingResult(job=job.id, clerk=clerk.id, encryption=mock_encryption(b"sum")),
        )
        assert service.get_clerking_job(clerk, clerk.id) is None

        status = service.get_aggregation_status(recipient, agg.id)
        snap_status = status.snapshots[0]
        assert snap_status.number_of_clerking_results == c + 1
        # ready exactly when reconstruction_threshold (= n for additive) reached
        assert snap_status.result_ready == (c + 1 >= N_CLERKS)

    result = service.get_snapshot_result(recipient, agg.id, snap.id)
    assert result.number_of_participations == N_PARTICIPANTS
    assert len(result.clerk_encryptions) == N_CLERKS
    assert {str(r.clerk) for r in result.clerk_encryptions} == {
        str(c.id) for c, _ in clerks
    }
    assert result.recipient_encryptions is None  # no masking


def test_snapshot_collects_masks(service):
    recipient, clerks, agg, _ = build_world(service, masking=True)
    participate_all(service, agg, masking=True)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)
    result = service.get_snapshot_result(recipient, agg.id, snap.id)
    masks = {bytes(e.value.data) for e in result.recipient_encryptions}
    assert masks == {f"mask-{i}".encode() for i in range(N_PARTICIPANTS)}


def test_late_participation_excluded_from_snapshot(service):
    """The snapshot freezes its set; late arrivals join the next round."""
    recipient, clerks, agg, _ = build_world(service)
    participate_all(service, agg)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)

    late = new_agent()
    service.create_agent(late, late)
    service.create_participation(
        late,
        Participation(
            id=ParticipationId.random(),
            participant=late.id,
            aggregation=agg.id,
            recipient_encryption=None,
            clerk_encryptions=[(late.id, mock_encryption(b"late")) for _ in range(N_CLERKS)],
        ),
    )
    result = service.get_snapshot_result(recipient, agg.id, snap.id)
    assert result.number_of_participations == N_PARTICIPANTS  # frozen set

    snap2 = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap2)
    result2 = service.get_snapshot_result(recipient, agg.id, snap2.id)
    assert result2.number_of_participations == N_PARTICIPANTS + 1


def test_snapshot_result_requires_matching_snapshot(service):
    """Regression: a snapshot id from another aggregation (or a bogus one)
    must not leak artifacts — the result is None unless the snapshot belongs
    to the queried aggregation."""
    recipient, clerks, agg, _ = build_world(service)
    participate_all(service, agg)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)

    assert service.get_snapshot_result(recipient, agg.id, SnapshotId.random()) is None

    # second aggregation owned by someone else; its recipient must not read
    # the first aggregation's snapshot through their own aggregation id
    other_recipient, other_key = new_full_agent(service)
    other_agg = Aggregation(
        id=AggregationId.random(),
        title="other",
        vector_dimension=4,
        modulus=433,
        recipient=other_recipient.id,
        recipient_key=other_key.body.id,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=N_CLERKS, modulus=433),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    service.create_aggregation(other_recipient, other_agg)
    assert service.get_snapshot_result(other_recipient, other_agg.id, snap.id) is None


def test_clerking_result_spoof_denied(service):
    recipient, clerks, agg, _ = build_world(service)
    participate_all(service, agg)
    snap = Snapshot(id=SnapshotId.random(), aggregation=agg.id)
    service.create_snapshot(recipient, snap)

    (clerk0, _), (clerk1, _) = clerks[0], clerks[1]
    job0 = service.get_clerking_job(clerk0, clerk0.id)
    # clerk1 cannot post a result for clerk0's job (server.rs:351-360)
    with pytest.raises((PermissionDenied, NotFound)):
        service.create_clerking_result(
            clerk1,
            ClerkingResult(job=job0.id, clerk=clerk0.id, encryption=mock_encryption(b"x")),
        )
    # clerk1 cannot poll clerk0's queue either
    with pytest.raises(PermissionDenied):
        service.get_clerking_job(clerk1, clerk0.id)


def test_participation_to_unknown_aggregation(service):
    p = new_agent()
    service.create_agent(p, p)
    with pytest.raises(NotFound):
        service.create_participation(
            p,
            Participation(
                id=ParticipationId.random(),
                participant=p.id,
                aggregation=AggregationId.random(),
                recipient_encryption=None,
                clerk_encryptions=[],
            ),
        )


def test_participation_retry_deduped(service):
    """Same participation id uploaded twice counts once (resources.rs:93-101)."""
    recipient, clerks, agg, _ = build_world(service)
    p = new_agent()
    service.create_agent(p, p)
    participation = Participation(
        id=ParticipationId.random(),
        participant=p.id,
        aggregation=agg.id,
        recipient_encryption=None,
        clerk_encryptions=[(p.id, mock_encryption(b"x")) for _ in range(N_CLERKS)],
    )
    service.create_participation(p, participation)
    service.create_participation(p, participation)  # network retry
    status = service.get_aggregation_status(recipient, agg.id)
    assert status.number_of_participations == 1
