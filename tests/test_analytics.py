"""Tier-1: the federated analytics plane (sda_tpu/analytics).

Three layers of coverage:

- the shared field-sizing contract: ``field_headroom_check`` /
  ``field_capacity`` agree with ``FixedPointCodec``'s own rule (the two
  cannot drift — they ARE one function now), and every encoder binds
  through it with a typed ``FieldSizingError`` on misconfiguration;
- encoder/decoder unit semantics against plaintext ground truth — the
  substrate isn't involved: ``encode`` sums are decoded directly;
- the sketch error contracts as SEEDED PROPERTY TESTS: >= 100 seeded
  populations asserting count-min overestimate-only + the ε–δ bound and
  count-sketch unbiasedness within the declared confidence, plus the
  adversarial tail case (one ultra-heavy hitter dominating the stream);
- one in-process scenario smoke over the real multi-tenant scheduled
  stack (libsodium-gated), and the CLI's typed flag-combination
  refusals.
"""

import math

import numpy as np
import pytest

from sda_tpu.analytics import (
    ABMetricEncoder,
    CountMinEncoder,
    CountSketchEncoder,
    HistogramEncoder,
    QuantileEncoder,
    expand_kinds,
    make_encoder,
)
from sda_tpu.models.encoding import (
    FieldSizingError,
    FixedPointCodec,
    field_capacity,
    field_headroom_check,
)

MOD = 1 << 24
SEEDS = 120  # >= 100 seeded populations for the property tests


def _aggregate(encoder, per_device_values):
    """Plaintext secure-sum stand-in: sum of residue uploads mod m —
    exactly what the round reveals."""
    total = np.zeros(encoder.dim, dtype=np.int64)
    for value in per_device_values:
        total = (total + encoder.encode(value)) % encoder.modulus
    return total


# ---------------------------------------------------------------------------
# the shared headroom rule (satellite: one contract, two callers)


def test_field_capacity_matches_codec_rule():
    for modulus, summands in ((433, 3), (1 << 16, 10), (1 << 24, 100)):
        assert field_capacity(modulus, summands) \
            == (modulus // 2 - 1) // summands


def test_field_headroom_check_margin_and_refusal():
    # 100 * 50 = 5000 <= 2^14//2 - 1 = 8191: margin 3191
    assert field_headroom_check(100, 50, 1 << 14) == 8191 - 5000
    with pytest.raises(FieldSizingError, match="decodable band"):
        field_headroom_check(100, 100, 1 << 14)
    # the typed error names the caller's context
    with pytest.raises(FieldSizingError, match="MyEncoder"):
        field_headroom_check(100, 100, 1 << 14, context="MyEncoder")


def test_codec_and_helper_cannot_drift():
    # the codec's constructor seals its own invariant through the SAME
    # helper: any (modulus, summands, q_max) it accepts must pass the
    # helper, and the refusal is the helper's typed error
    codec = FixedPointCodec(1 << 16, 8, max_summands=10, clip=1.0)
    assert field_headroom_check(codec.q_max, codec.max_summands,
                                codec.modulus) >= 0
    with pytest.raises(FieldSizingError, match="headroom"):
        FixedPointCodec(433, 8, max_summands=300)


def test_encoder_bind_is_the_same_contract():
    enc = HistogramEncoder(0.0, 1.0, bins=4, samples_per_device=100)
    # 100 per-coordinate max * 200 devices = 20000 > 433//2 - 1
    with pytest.raises(FieldSizingError, match="HistogramEncoder"):
        enc.bind(433, 200)
    margin = enc.bind(MOD, 200).headroom_margin
    assert margin == field_headroom_check(100, 200, MOD)


def test_unbound_encoder_refuses_encode():
    enc = HistogramEncoder(bins=4)
    with pytest.raises(FieldSizingError, match="bind"):
        enc.encode([0.5])


def test_ab_second_moment_dominates_sizing():
    # the q^2 lane is the point of the typed error: a modulus that fits
    # FedAvg deltas is far too small for sum-of-squares
    enc = ABMetricEncoder(arms=2, lo=0.0, hi=1.0, fractional_bits=10)
    assert enc.max_abs == enc.q_max ** 2
    with pytest.raises(FieldSizingError, match="ABMetricEncoder"):
        enc.bind(1 << 16, 100)
    enc.bind(1 << 31, 100)


def test_decode_sum_errors_name_aggregation_context():
    codec = FixedPointCodec(1 << 16, 8, max_summands=4)
    values = codec.encode(np.zeros(3))
    with pytest.raises(ValueError, match="at least one summand") as e:
        codec.decode_sum(values, 0)
    assert "dim 3" in str(e.value) and str(codec.modulus) in str(e.value)
    with pytest.raises(ValueError, match="exceeds configured capacity") as e:
        codec.decode_sum(values, 9)
    assert "dim 3" in str(e.value) and "wrapped" in str(e.value)


def test_registry_round_trip_and_unknown_kind():
    enc = make_encoder("histogram", bins=8)
    assert isinstance(enc, HistogramEncoder) and enc.bins == 8
    with pytest.raises(ValueError, match="registered"):
        make_encoder("bogus")


def test_expand_kinds_aliases_and_refusal():
    assert expand_kinds("heavy") == ["countmin", "countsketch"]
    assert expand_kinds("all")[0] == "histogram" and len(
        expand_kinds("all")) == 5
    assert expand_kinds("ab,histogram,ab") == ["ab", "histogram"]
    with pytest.raises(ValueError, match="unknown analytics profile"):
        expand_kinds("histogram,nope")


# ---------------------------------------------------------------------------
# encoder/decoder unit semantics


def test_histogram_exact_counts_and_edge_clamp():
    enc = HistogramEncoder(0.0, 1.0, bins=4, samples_per_device=4)
    enc.bind(MOD, 8)
    devices = [[0.1, 0.1, 0.6, 0.9], [-5.0, 7.0, float("nan"), 0.3]]
    revealed = _aggregate(enc, devices)
    block = enc.decode(revealed, len(devices))
    # -5.0 and NaN clamp to bin 0, 7.0 to the last bin
    assert block["counts"].tolist() == [4, 1, 1, 2]
    assert block["total"] == 8


def test_histogram_contribution_magnitude_is_enforced():
    enc = HistogramEncoder(0.0, 1.0, bins=2, samples_per_device=2)
    enc.bind(MOD, 8)
    with pytest.raises(FieldSizingError, match="samples_per_device"):
        enc.encode([0.1, 0.2, 0.3])


def test_quantile_within_one_grid_step():
    enc = QuantileEncoder(0.0, 1.0, bins=64, samples_per_device=16)
    enc.bind(MOD, 8)
    rng = np.random.default_rng(7)
    devices = [rng.uniform(0, 1, 16) for _ in range(8)]
    revealed = _aggregate(enc, devices)
    flat = np.sort(np.concatenate(devices))
    for q in (0.1, 0.5, 0.9):
        est = float(enc.decode_quantiles(revealed, [q])[0])
        rank = min(flat.size - 1, max(0, math.ceil(q * flat.size) - 1))
        assert abs(est - flat[rank]) <= enc.grid_step + 1e-12


def test_quantile_empty_population_is_typed():
    enc = QuantileEncoder(bins=4, samples_per_device=1)
    enc.bind(MOD, 8)
    with pytest.raises(ValueError, match="empty population"):
        enc.decode_quantiles(np.zeros(4, np.int64), [0.5])


def test_ab_mean_variance_exact_in_quantized_domain():
    enc = ABMetricEncoder(arms=2, lo=0.0, hi=1.0, fractional_bits=6)
    enc.bind(MOD, 16)
    devices = [(0, 0.25), (0, 0.75), (1, 0.5), (1, 0.5), (1, 0.9)]
    revealed = _aggregate(enc, devices)
    block = enc.decode(revealed, len(devices))
    arm0, arm1 = block["arms"]["arm0"], block["arms"]["arm1"]
    assert arm0["count"] == 2 and arm1["count"] == 3
    q = np.array([enc.quantize(0.25), enc.quantize(0.75)], np.float64)
    assert arm0["mean"] == pytest.approx(q.mean() / enc.scale, abs=1e-12)
    assert arm0["variance"] == pytest.approx(
        (np.mean(q * q) - q.mean() ** 2) / enc.scale ** 2, abs=1e-12)
    assert block["total"] == 5


def test_ab_empty_arm_decodes_to_none():
    enc = ABMetricEncoder(arms=3, lo=0.0, hi=1.0)
    enc.bind(MOD, 4)
    revealed = _aggregate(enc, [(0, 0.5)])
    block = enc.decode(revealed, 1)
    assert block["arms"]["arm2"]["count"] == 0
    assert block["arms"]["arm2"]["mean"] is None


def test_sketch_seed_mismatch_breaks_agreement():
    # recipient and devices MUST share the hash family: a decoder with a
    # different seed reads garbage — this is why the seed rides the
    # aggregation identity in the scenario
    enc_a = CountMinEncoder(width=32, depth=3, seed=1, items_per_device=4)
    enc_b = CountMinEncoder(width=32, depth=3, seed=2, items_per_device=4)
    enc_a.bind(MOD, 8)
    enc_b.bind(MOD, 8)
    devices = [["x"] * 4 for _ in range(8)]
    revealed = _aggregate(enc_a, devices)
    assert enc_a.estimate(revealed, "x") == 32
    assert enc_b.estimate(revealed, "x") < 32  # wrong family, wrong cells


# ---------------------------------------------------------------------------
# sketch error contracts: seeded property tests (>= 100 populations)


def _zipf_stream(rng, devices, items_per_device, domain):
    raw = rng.zipf(1.5, size=(devices, items_per_device))
    idx = np.minimum(raw - 1, domain - 1)
    return [[f"k{int(i)}" for i in row] for row in idx]


def test_countmin_overestimate_only_and_eps_delta_bound():
    """Count-min over >= 100 seeded populations: EVERY point query is an
    overestimate (a single underestimate is a hard failure — collisions
    can only add), and the ``est <= true + eps * N`` bound holds with
    frequency >= 1 - delta across the whole query corpus (binomial
    slack on the failure budget)."""
    width, depth, domain = 32, 4, 40
    enc_proto = CountMinEncoder(width=width, depth=depth, seed=0,
                                items_per_device=8)
    eps, delta = enc_proto.eps, enc_proto.delta
    queries = 0
    violations = 0
    for seed in range(SEEDS):
        rng = np.random.default_rng(seed)
        enc = CountMinEncoder(width=width, depth=depth, seed=seed * 7 + 1,
                              items_per_device=8)
        enc.bind(MOD, 8)
        devices = _zipf_stream(rng, 8, 8, domain)
        revealed = _aggregate(enc, devices)
        truth = {}
        for row in devices:
            for item in row:
                truth[item] = truth.get(item, 0) + 1
        total = sum(truth.values())
        for i in range(domain):
            item = f"k{i}"
            true = truth.get(item, 0)
            est = enc.estimate(revealed, item)
            assert est >= true, (
                f"seed {seed}: count-min UNDERestimated {item}: "
                f"{est} < {true}")
            queries += 1
            if est > true + eps * total:
                violations += 1
    # failure budget: mean + 6 binomial sigmas over the whole corpus
    budget = queries * delta
    allowance = budget + 6.0 * math.sqrt(budget * (1 - delta)) + 1
    assert violations <= allowance, (
        f"{violations} eps-violations over {queries} queries breaks "
        f"delta={delta:.4g} (allowance {allowance:.1f})")


def test_countsketch_unbiased_and_bounded():
    """Count-sketch over >= 100 seeded populations: the estimator is
    unbiased (the mean signed error across independently-seeded sketches
    of the same item concentrates at 0), and per-query error exceeds
    the declared ``sqrt(3 F2 / width)`` bound no more often than the
    declared delta (with binomial slack)."""
    width, depth, domain = 32, 5, 40
    delta = math.exp(-depth / 6.0)
    queries = 0
    violations = 0
    signed_errors = []
    for seed in range(SEEDS):
        rng = np.random.default_rng(10_000 + seed)
        enc = CountSketchEncoder(width=width, depth=depth,
                                 seed=seed * 13 + 5, items_per_device=8)
        enc.bind(MOD, 8)
        devices = _zipf_stream(rng, 8, 8, domain)
        revealed = _aggregate(enc, devices)
        truth = {}
        for row in devices:
            for item in row:
                truth[item] = truth.get(item, 0) + 1
        f2 = float(sum(c * c for c in truth.values()))
        bound = enc.error_bound(f2)
        for i in range(domain):
            item = f"k{i}"
            err = enc.estimate(revealed, item) - truth.get(item, 0)
            signed_errors.append(err)
            queries += 1
            if abs(err) > bound:
                violations += 1
    budget = queries * delta
    allowance = budget + 6.0 * math.sqrt(budget * (1 - delta)) + 1
    assert violations <= allowance
    # unbiasedness: the grand mean of signed errors concentrates at 0 —
    # systematic bias on the heavy zipf head would push it far outside
    mean_err = float(np.mean(signed_errors))
    sem = float(np.std(signed_errors)) / math.sqrt(len(signed_errors))
    assert abs(mean_err) <= 6.0 * sem + 1e-9, (
        f"count-sketch biased: mean signed error {mean_err:.4f} "
        f"(sem {sem:.4f})")


def test_sketches_survive_single_ultra_heavy_hitter():
    """The adversarial tail: one item carries ~95% of the stream. The
    sketch contracts must hold where they are weakest — count-min's
    eps*N bound balloons with N, and count-sketch's F2 bound balloons
    with the heavy hitter's square — and both must still rank the
    ultra-heavy item first at every seed."""
    width, depth, domain = 32, 4, 20
    for seed in range(SEEDS):
        rng = np.random.default_rng(20_000 + seed)
        devices = []
        for _ in range(8):
            row = ["whale"] * 15 + [f"k{int(rng.integers(0, domain))}"]
            devices.append(row)
        truth = {}
        for row in devices:
            for item in row:
                truth[item] = truth.get(item, 0) + 1
        total = sum(truth.values())
        f2 = float(sum(c * c for c in truth.values()))
        candidates = ["whale"] + [f"k{i}" for i in range(domain)]

        cm = CountMinEncoder(width=width, depth=depth, seed=seed + 1,
                             items_per_device=16)
        cm.bind(MOD, 8)
        revealed = _aggregate(cm, devices)
        assert cm.estimate(revealed, "whale") >= truth["whale"]
        hits = cm.heavy_hitters(revealed, candidates, 0.5, total)
        assert hits and hits[0][0] == "whale"

        cs = CountSketchEncoder(width=width, depth=depth, seed=seed + 1,
                                items_per_device=16)
        cs.bind(MOD, 8)
        revealed = _aggregate(cs, devices)
        err = abs(cs.estimate(revealed, "whale") - truth["whale"])
        assert err <= cs.error_bound(f2) + 1e-9
        hits = cs.heavy_hitters(revealed, candidates, 0.5, total)
        assert hits and hits[0][0] == "whale"


def test_signed_contributions_ride_nonneg_residues():
    # count-sketch uploads are residues in [0, m): a -1 contribution is
    # m-1 on the wire and the centered lift restores it after the sum
    enc = CountSketchEncoder(width=8, depth=1, seed=3, items_per_device=1)
    enc.bind(433, 4)
    item = next(f"i{k}" for k in range(100) if enc._sign(0, f"i{k}") == -1)
    upload = enc.encode([item])
    assert upload.min() >= 0 and upload.max() < 433
    assert 432 in upload  # the -1, as a residue


# ---------------------------------------------------------------------------
# the scenario over the real stack (libsodium-gated) + CLI hygiene


def test_analytics_scenario_smoke_in_process():
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")
    from sda_tpu.analytics import AnalyticsProfile, run_analytics

    report = run_analytics(AnalyticsProfile(
        kinds=["histogram", "ab"], participants=3, epochs=2,
        values_per_device=4, seed=11))
    assert report["exact"] and report["bounds_ok"]
    assert report["leaks"] == 0 and report["client_failures"] == 0
    assert report["rounds_exact"] == 4  # 2 tenants x 2 epochs
    assert report["unit"] == "values/s" and report["value"] > 0
    tenant = report["per_tenant"]["analytics-histogram-0"]
    assert tenant["contract"] == "exact"
    assert tenant["headroom_margin"] >= 0


def test_analytics_scenario_refuses_oversized_encoder():
    from sda_tpu.analytics import AnalyticsProfile, run_analytics

    # the packed-sharing order constraints floor the prime near 2^21, so
    # the derived aggregation modulus caps per-coordinate sums at 32767:
    # 40000 samples/device cannot fit, and the typed refusal names the
    # encoder and fires BEFORE any service spins up (sizing is checked
    # after crypto availability, so gate)
    from sda_tpu.crypto import sodium

    if not sodium.available():
        pytest.skip("libsodium not present")
    with pytest.raises(FieldSizingError, match="HistogramEncoder"):
        run_analytics(AnalyticsProfile(
            kinds=["histogram"], participants=4, values_per_device=40000,
            modulus_bits=14))


def test_cli_analytics_rejects_profile_combos(capsys):
    from sda_tpu.cli import sim

    assert sim.main(["--analytics", "histogram", "--fl"]) == 1
    err = capsys.readouterr().err
    assert "--analytics" in err and "--fl" in err

    assert sim.main(["--analytics", "histogram", "--poison", "0.2"]) == 1
    err = capsys.readouterr().err
    assert "--poison" in err and "--analytics" in err

    assert sim.main(["--analytics", "histogram", "--devscale"]) == 1
    err = capsys.readouterr().err
    assert "--analytics" in err and "--devscale" in err


def test_cli_analytics_rejects_unknown_kind(capsys):
    from sda_tpu.cli import sim

    assert sim.main(["--analytics", "nope"]) == 1
    assert "unknown analytics profile" in capsys.readouterr().err
