#!/usr/bin/env bash
# CI entry point (reference analog: Jenkinsfile — unit tests, integration
# tests across service seams, and the shell walkthrough).
#
# Usage: bash ci.sh          # full run on the CPU backend
set -euo pipefail
cd "$(dirname "$0")"

echo "== pytest (unit + integration + conformance, virtual 8-device mesh)"
python -m pytest tests/ -q -m 'not chaos'

echo "== chaos (fault injection under a fixed seed: failpoints, retry, lease/reissue)"
env SDA_CHAOS_SEED=20260803 python -m pytest tests/ -q -m chaos

echo "== loadgen smoke (fixed seed, closed-loop, zero 5xx, histogram report)"
LOAD_REPORT=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --load --participants 24 --dim 4 \
  --load-arrivals closed --load-concurrency 8 --load-seed 20260803)
LOAD_REPORT="$LOAD_REPORT" python - <<'PY'
import json, os
report = json.loads(os.environ["LOAD_REPORT"].strip().splitlines()[-1])
assert report["ready"] and report["exact"], report
assert report["client_failures"] == 0, report
assert report["errors_5xx"] == 0, report["status_counts"]
assert report["latency_ms"], "empty per-route histogram report"
assert report["phases_ms"], "empty phase histogram report"
# round lifecycle: a healthy load run must never degrade or fail a round
assert report["rounds_degraded"] == 0, report
assert report["rounds_failed"] == 0, report
print(f"loadgen smoke OK: {report['load_requests']} load-phase requests, "
      f"{report['sustained_rps']} rps sustained")
PY

echo "== dead-clerk drill (fixed seed: 1 permanently dead clerk; Shamir degrades bit-exact, additive fails closed)"
DEAD_SHAMIR=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --chaos --dead-clerks 1 \
  --chaos-seed 20260803 --chaos-rate 0.05)
DEAD_ADDITIVE=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --chaos --dead-clerks 1 \
  --chaos-sharing additive --chaos-seed 20260803 --chaos-rate 0.05)
ROUND_RECORD=$(mktemp /tmp/sda-round-XXXX.json)
DEAD_SHAMIR="$DEAD_SHAMIR" DEAD_ADDITIVE="$DEAD_ADDITIVE" ROUND_RECORD="$ROUND_RECORD" python - <<'PY'
import json, os
shamir = json.loads(os.environ["DEAD_SHAMIR"].strip().splitlines()[-1])
additive = json.loads(os.environ["DEAD_ADDITIVE"].strip().splitlines()[-1])
# packed Shamir: clerking -> degraded -> revealed, bit-exact vs the
# healthy reference (the surviving 7-of-8 quorum reconstructs exactly)
states = [s for s, _ in shamir["round_history"]]
assert shamir["exact"] is True, shamir
assert "degraded" in states and states[-1] == "revealed", states
assert shamir["round_dead_clerks"], shamir
assert shamir["time_to_degraded_s"] and shamir["time_to_degraded_s"] > 0, shamir
# additive: unrecoverable -> terminal 'failed' with a machine-readable
# reason BEFORE the drill deadline (no hang), surfaced as a typed error
assert additive["round_state"] == "failed", additive
assert additive["round_reason"], additive
assert additive["failure"] and additive["failure"]["type"] == "RoundFailed", additive
assert additive["time_to_failed_s"] and additive["time_to_failed_s"] > 0, additive
record = {
    "metric": "time to degraded (dead-clerk drill, 8-clerk packed Shamir over HTTP)",
    "value": shamir["time_to_degraded_s"], "unit": "seconds",
    "platform": "cpu", "seed": shamir["seed"],
    "clerking_deadline_s": 1.5,
}
with open(os.environ["ROUND_RECORD"], "w") as f:
    json.dump(record, f)
print(f"dead-clerk drill OK: shamir {'->'.join(states)} exact={shamir['exact']} "
      f"time_to_degraded={shamir['time_to_degraded_s']}s; "
      f"additive failed in {additive['time_to_failed_s']}s "
      f"({additive['round_reason'][:60]}...)")
PY
# the detection-latency record must parse as a bench record and gate
# (advisory: first record of its metric — it seeds the trailing window)
python -m sda_tpu.obs.regress --advisory BENCH_r*.json "$ROUND_RECORD"
rm -f "$ROUND_RECORD"

echo "== brownout drill (fixed seed: store browns out mid-clerking; breaker trips, sheds 503+Retry-After, recovers; round bit-exact)"
BROWNOUT=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --chaos --brownout 1.0 \
  --chaos-seed 20260803 --chaos-rate 0.05)
BROWNOUT_RECORD=$(mktemp /tmp/sda-brownout-XXXX.json)
BROWNOUT="$BROWNOUT" BROWNOUT_RECORD="$BROWNOUT_RECORD" python - <<'PY'
import json, os
report = json.loads(os.environ["BROWNOUT"].strip().splitlines()[-1])
# the round must survive the brownout window bit-exactly: every admitted
# participation present, reveal exact, despite a second of store failures
assert report["ready"] and report["exact"], report
breaker = report["breaker"]
# the breaker actually did its job: tripped at least once, shed while
# open, half-opened on probes, and CLOSED again after the window healed
assert breaker["times_opened"] >= 1, breaker
assert breaker["state"] == "closed", breaker
counters = report["counters"]
assert counters.get("server.store.breaker.shed", 0) >= 1, counters
assert counters.get("http.status.503", 0) >= 1, counters
# MTTR: first trip -> final recovery, a hair over the 1 s injected
# window (the recovery probe cadence is 0.25 s)
mttr = report["time_to_recover_s"]
assert mttr and 0 < mttr < 10.0, report
record = {
    "metric": "time to recover (store brownout drill, 1s window, breaker threshold 3)",
    "value": mttr, "unit": "seconds",
    "platform": "cpu", "seed": report["seed"],
    "brownout_s": report["brownout_s"],
    "breaker_recovery_s": breaker["recovery_s"],
}
with open(os.environ["BROWNOUT_RECORD"], "w") as f:
    json.dump(record, f)
print(f"brownout drill OK: exact={report['exact']}, breaker opened "
      f"{breaker['times_opened']}x, shed {counters.get('server.store.breaker.shed')} "
      f"op(s), time_to_recover={mttr}s")
PY
# the MTTR record must parse as a bench record and gate (advisory: first
# record of its metric seeds the trailing window)
python -m sda_tpu.obs.regress --advisory BENCH_r*.json "$BROWNOUT_RECORD"
rm -f "$BROWNOUT_RECORD"

echo "== churn drill (fixed seed: ~40% participant churn, crash mid-upload + journal resume + duplicate retries; bit-exact, zero double counts)"
CHURN=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --chaos --churn 0.35 \
  --chaos-store sqlite --chaos-seed 20260803 --chaos-rate 0.05)
CHURN_RECORD=$(mktemp /tmp/sda-churn-XXXX.json)
CHURN="$CHURN" CHURN_RECORD="$CHURN_RECORD" python - <<'PY'
import json, os
report = json.loads(os.environ["CHURN"].strip().splitlines()[-1])
# the exactly-once verdict: nonzero churn actually happened, every
# departure rejoined via its journal, mid-upload crashes replayed
# byte-identically, the equivocation probe was rejected, and the round
# revealed bit-exactly with ZERO double-counted participations
assert report["exact"] is True, report
assert report["participants_churned"] >= 1, report
assert report["participants_resumed"] == report["participants_churned"], report
assert report["participations_replayed"] >= 1, report
assert report["equivocations_undetected"] == 0, report
assert report["equivocations_detected"] >= 1, report
assert report["double_counted"] == 0, report
record = {
    "metric": "churn drill resume wall (12 participants, ~40% churn, journal resume over HTTP)",
    "value": report["time_to_resume_s"], "unit": "seconds",
    "platform": "cpu", "seed": report["seed"],
    "churn_rate": report["churn_rate"],
    "participants_resumed": report["participants_resumed"],
}
with open(os.environ["CHURN_RECORD"], "w") as f:
    json.dump(record, f)
print(f"churn drill OK: {report['participants_churned']} churned, "
      f"{report['participants_resumed']} resumed, "
      f"{report['participations_replayed']} replayed, "
      f"equivocations detected={report['equivocations_detected']} "
      f"undetected={report['equivocations_undetected']}, "
      f"double_counted={report['double_counted']}, exact={report['exact']}")
PY
# the resume-wall record must parse as a bench record and gate (advisory:
# first record of its metric seeds the trailing window)
python -m sda_tpu.obs.regress --advisory BENCH_r*.json "$CHURN_RECORD"
rm -f "$CHURN_RECORD"

echo "== async-plane A/B (same fixed-seed chaos+churn drill, threaded vs asyncio event-loop plane: bit-exact, identical exactly-once counters)"
AB_THREADED=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --chaos --churn 0.35 \
  --chaos-store sqlite --chaos-seed 20260803 --chaos-rate 0.05)
AB_ASYNC=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --chaos --churn 0.35 \
  --chaos-store sqlite --chaos-seed 20260803 --chaos-rate 0.05 --async-http)
AB_THREADED="$AB_THREADED" AB_ASYNC="$AB_ASYNC" python - <<'PY'
import json, os
threaded = json.loads(os.environ["AB_THREADED"].strip().splitlines()[-1])
asyncp = json.loads(os.environ["AB_ASYNC"].strip().splitlines()[-1])
assert threaded["http_plane"] == "threaded" and asyncp["http_plane"] == "async"
# the plane must be invisible to the protocol: same fixed seed -> same
# bit-exact reveal, same churn resolution, same exactly-once verdicts
for key in ("exact", "ready", "participants_churned", "participants_resumed",
            "participations_replayed", "equivocations_detected",
            "equivocations_undetected", "double_counted",
            "admitted_participations"):
    assert threaded[key] == asyncp[key], (key, threaded[key], asyncp[key])
assert threaded["exact"] is True, threaded
part = lambda rep: {k: v for k, v in rep["counters"].items()
                    if k.startswith("server.participation.")}
assert part(threaded) == part(asyncp), (part(threaded), part(asyncp))
print(f"async-plane A/B OK: exact on both planes, participation counters "
      f"{part(asyncp)} identical, "
      f"{asyncp['participants_resumed']} resumed on each")
PY

echo "== job-pickup bench (fixed seed: long-poll vs 0.5s polling clerks on the async plane; >=10x lower p99 gated)"
PICKUP_RECORD=$(mktemp /tmp/sda-pickup-XXXX.json)
PICKUP=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --pickup \
  --pickup-snapshots 6 --pickup-interval 0.5 --pickup-wait 10 \
  --pickup-seed 20260803)
PICKUP="$PICKUP" PICKUP_RECORD="$PICKUP_RECORD" python - <<'PY'
import json, os
record = json.loads(os.environ["PICKUP"].strip().splitlines()[-1])
# both modes closed their rounds bit-exactly; the long-poll win is the
# acceptance bar: enqueue->lease p99 at least 10x below the polling
# baseline on the same fixed-seed round
assert record["exact"] is True, record
assert record["samples"] >= 40, record
assert record["value"] is not None and record["value"] > 0, record
assert record["speedup_p99"] and record["speedup_p99"] >= 10.0, record
with open(os.environ["PICKUP_RECORD"], "w") as f:
    json.dump(record, f)
print(f"pickup bench OK: long-poll p99 {record['value']}ms vs polling "
      f"{record['polling']['p99_ms']}ms ({record['speedup_p99']}x, "
      f"{record['samples']} samples)")
PY
# the pickup record (direction=lower) must parse and gate advisory
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$PICKUP_RECORD"
rm -f "$PICKUP_RECORD"

echo "== connection storm (10k held connections on one async-plane sdad worker: zero 5xx, bounded RSS, clean drain)"
STORM_RECORD=$(mktemp /tmp/sda-storm-XXXX.json)
STORM=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --connstorm 10000 \
  --connstorm-waves 2 --connstorm-rss-limit 1024)
STORM="$STORM" STORM_RECORD="$STORM_RECORD" python - <<'PY'
import json, os
record = json.loads(os.environ["STORM"].strip().splitlines()[-1])
# the async-plane capacity verdict: every connection opened and served
# on every wave (10k unless the host fd limit clamps — then the record
# says so), zero 5xx from exhaustion (shedding would be 429/503), RSS
# bounded, and the SIGTERM drain still clean with every socket open
assert record["ok"] is True, record
assert record["errors_5xx"] == 0, record["statuses"]
assert record["transport_failures"] == 0, record
assert record["connect_failures"] == 0, record
assert record["leaked"] == 0, record["drain"]
if not record["clamped_by_fd_limit"]:
    assert record["value"] == 10000, record
assert record["rss_bounded"] is True, record
with open(os.environ["STORM_RECORD"], "w") as f:
    json.dump(record, f)
print(f"connstorm OK: {record['value']} connections held "
      f"({record['per_connection_kb']} KiB/conn growth, RSS "
      f"{record['rss_mb']}MiB <= {record['rss_limit_mb']}MiB), "
      f"{sum(w['requests'] for w in record['waves'])} pings, "
      f"drain leaked={record['leaked']}")
PY
# the connection-capacity record must parse and gate advisory
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$STORM_RECORD"
rm -f "$STORM_RECORD"

echo "== devscale drill (fixed seed: sharded tile schedule, interpret-mode Pallas external randomness, shrunk dim; bit-exact vs oracle, zero retraces, HBM under watermark)"
DEVSCALE_RECORD=$(mktemp /tmp/sda-devscale-XXXX.json)
DEVSCALE=$(env JAX_PLATFORMS=cpu SDA_SIM_PLATFORM=cpu python -m sda_tpu.cli.sim --devscale \
  --devscale-dim 25000 --devscale-participants 8 --devscale-shards 4x2 \
  --devscale-pallas --devscale-rounds 3 --devscale-seed 20260804)
DEVSCALE="$DEVSCALE" DEVSCALE_RECORD="$DEVSCALE_RECORD" python - <<'PY'
import json, os
record = json.loads(os.environ["DEVSCALE"].strip().splitlines()[-1])
# the model-scale schedule at a CI-sized dim: the sharded+streamed round
# under interpret-mode Pallas (external randomness) must reveal the
# oracle lane's bytes exactly, reuse ONE compiled shape per stage with
# zero retraces, keep its HBM promise, and the clerk-pipeline-fed
# device-tile sink must reproduce the device-generated lane bit-for-bit
assert record["ok"] is True, record
assert record["exact"] is True, record["oracle"]
assert record["pallas"] is True, record
assert record["retraces"] == 0 and record["warm_program_reused"], record
assert all(v == 1 for v in record["compiled_shapes"].values()), record["compiled_shapes"]
assert record["clerk_fed"]["exact"] is True, record["clerk_fed"]
assert record["clerk_fed"]["sink_misses"] == 0, record["clerk_fed"]
assert record["scan_lane"]["exact"] is True, record["scan_lane"]
assert record["hbm"]["within_watermark"] is True, record["hbm"]
assert record["tile_rule"] == "hbm_watermark", record
with open(os.environ["DEVSCALE_RECORD"], "w") as f:
    json.dump(record, f)
print(f"devscale drill OK: dim {record['dim']} over {record['p_shards']}x"
      f"{record['d_shards']} mesh, tile {record['dim_tile']} "
      f"(hbm ratio {record['hbm_watermark_ratio']}), "
      f"{record['value']} el/s, retraces {record['retraces']}, "
      f"sink hits {record['clerk_fed']['sink_hits']}")
PY
# the devscale record must parse and gate advisory (its comparability
# tags — dim/p_shards/d_shards/pallas — seed a fresh lineage vs the
# committed dim-1e8 record)
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$DEVSCALE_RECORD"
rm -f "$DEVSCALE_RECORD"

echo "== tree drill (fixed seed: 2-level tree over sqlite+HTTP, ~10% leaf dropout, bit-exact vs flat reference; simulated 1e5-participant record)"
TREE=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --tree --participants 24 --dim 4 \
  --tree-group-size 6 --tree-seed 20260803 --tree-dropout 0.1 --tree-sim 100000)
TREE_RECORD=$(mktemp /tmp/sda-tree-XXXX.json)
TREE="$TREE" TREE_RECORD="$TREE_RECORD" python - <<'PY'
import json, os
report = json.loads(os.environ["TREE"].strip().splitlines()[-1])
# the real-crypto rung: a 2-level tree (G leaf rounds + 1 root round)
# over sqlite through real HTTP, leaf dropout injected, every level's
# round revealed, and the ROOT output bit-exact against BOTH the
# surviving-devices expectation and a real flat reference round
assert report["depth"] == 2, report["depth"]
assert report["groups"] >= 2, report
assert report["exact"] is True, report
assert report["flat_exact"] is True, report
assert report["root_state"] == "revealed", report
assert report["participants_dropped"] >= 1, report
# relay accounting: one re-share per leaf group, masks forwarded in-band
assert report["relays"] == report["groups"], report
assert report["counters"].get("relay.masks_forwarded", 0) >= 1, report["counters"]
# tree linkage visible on the round documents (any worker can diagnose)
assert report["root_children"] and len(report["root_children"]) == report["groups"], report
# the simulated population rung: fixed-seed 1e5-participant 2-level tree,
# bit-exact vs the flat walk, peak per-node memory BOUNDED by the batch
sim = report["sim"]
assert sim["participants"] == 100000, sim
assert sim["depth"] == 2, sim
assert sim["exact"] is True, sim
assert sim["bounded"] is True, sim
assert sim["peak_node_elements"] <= sim["bound_elements"], sim
# the MEASURED verdict: tracemalloc peak of the streaming pass stays
# under the batch-derived bound, independent of the population
assert sim["peak_pass_bytes"] <= sim["bound_pass_bytes"], sim
with open(os.environ["TREE_RECORD"], "w") as f:
    json.dump(sim, f)
print(f"tree drill OK: {report['groups']} groups, "
      f"{report['participants_dropped']} dropped, exact={report['exact']} "
      f"flat_exact={report['flat_exact']}; sim 1e5 exact={sim['exact']} "
      f"bounded={sim['bounded']} ({sim['value']} participants/sec)")
PY
# the simulated participants=1e5 record must parse as a bench record and
# gate advisory via sda-bench --check (first record of its metric seeds
# the trailing window; CPU rung numbers are advisory by policy)
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$TREE_RECORD"
rm -f "$TREE_RECORD"

echo "== wire codec A/B (fixed seed: same round JSON vs binary, bit-exact both ways)"
CODEC_JSON=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --load --participants 16 --dim 64 \
  --load-arrivals closed --load-concurrency 4 --load-seed 20260803 \
  --load-store memory --load-codec json)
CODEC_BIN=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --load --participants 16 --dim 64 \
  --load-arrivals closed --load-concurrency 4 --load-seed 20260803 \
  --load-store memory --load-codec bin)
CODEC_JSON="$CODEC_JSON" CODEC_BIN="$CODEC_BIN" python - <<'PY'
import json, os
reports = {}
for codec in ("json", "bin"):
    report = json.loads(os.environ[f"CODEC_{codec.upper()}"].strip().splitlines()[-1])
    # the wire codec must never change the round's outcome
    assert report["ready"] and report["exact"], (codec, report)
    assert report["client_failures"] == 0, (codec, report)
    assert report["codec"] == codec, (codec, report["codec"])
    reports[codec] = report
counters = {c: reports[c].get("codec_counters") or {} for c in reports}
# the bin swarm actually spoke binary; the json swarm never did
assert counters["bin"].get("http.codec.bin.in", 0) > 0, counters["bin"]
assert counters["json"].get("http.codec.bin.in", 0) == 0, counters["json"]
for codec, report in reports.items():
    print(f"codec {codec}: exact={report['exact']} "
          f"rps={report['sustained_rps']} counters={counters[codec]}")
PY

echo "== fleet drill (fixed seed: 2 sdad processes, one shared sqlite store, chaos on, bit-exact)"
FLEET_RECORD=$(mktemp /tmp/sda-fleet-XXXX.json)
FLEET_REPORT=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --load --participants 24 --dim 4 \
  --load-arrivals closed --load-concurrency 8 --load-seed 20260803 \
  --load-store sqlite --load-fleet 2 --load-chaos-rate 0.05)
FLEET_REPORT="$FLEET_REPORT" FLEET_RECORD="$FLEET_RECORD" python - <<'PY'
import json, os
record = json.loads(os.environ["FLEET_REPORT"].strip().splitlines()[-1])
# both rungs (1 worker, 2 workers) must close the round bit-exactly
# with zero lost admitted participations and zero leaked requests —
# even with ~5% of requests 500ing inside the worker processes
assert record["fleet_nodes"] == 2, record
assert record["ready"] and record["exact"], record
assert record["client_failures"] == 0, record
assert record["leaked"] == 0, record
assert record["chaos_rate"] > 0, record
# every worker actually served load-phase traffic
assert all(rps > 0 for rps in record["per_node_load_rps"].values()), \
    record["per_node_load_rps"]
assert isinstance(record["scaling_efficiency"], float), record
with open(os.environ["FLEET_RECORD"], "w") as f:
    json.dump(record, f)
print(f"fleet drill OK: {record['value']} rps @2 workers vs "
      f"{record['baseline_rps']} @1, efficiency "
      f"{record['scaling_efficiency']} ({record['host_cores']} cores), "
      f"exact={record['exact']}")
PY
# the fresh scaling record must parse as a bench record and gate
# (advisory: scaling efficiency is bounded by the CI host's core count)
python -m sda_tpu.obs.regress --advisory BENCH_r*.json "$FLEET_RECORD"
rm -f "$FLEET_RECORD"

echo "== forensics drill (fixed seed: churn+chaos fleet round with the flight recorder on; every process exits, then sda-trace explain reconstructs the round from the spools alone)"
SPOOL_DIR=$(mktemp -d /tmp/sda-spool-XXXX)
FORENSICS_REPORT=$(env JAX_PLATFORMS=cpu SDA_FLIGHT_RECORDER="$SPOOL_DIR" \
  python -m sda_tpu.cli.sim --load --participants 24 --dim 4 \
  --load-arrivals closed --load-concurrency 8 --load-seed 20260803 \
  --load-store sqlite --load-fleet 2 --load-chaos-rate 0.05 --load-churn 0.3)
# the sim process and both fleet workers have exited: the JSONL spool
# segments under $SPOOL_DIR are ALL that remains of the round's telemetry
FORENSICS_REPORT="$FORENSICS_REPORT" SPOOL_DIR="$SPOOL_DIR" python - <<'PY'
import json, os
report = json.loads(os.environ["FORENSICS_REPORT"].strip().splitlines()[-1])
# the recorder-on run itself must stay bit-exact (no protocol bytes change)
assert report["ready"] and report["exact"], report
assert report["output_sha256"], report
from sda_tpu.obs import forensics
spool = forensics.load_spool(os.environ["SPOOL_DIR"])
rep = forensics.explain(spool, report["aggregation"])
# all three processes (sim swarm + 2 sdad workers) spooled segments
assert len(rep["processes"]) >= 3, rep["processes"]
# the round story is complete: every admitted participation visible,
# the ledger reaches revealed, chaos faults attributed site+kind
assert rep["participations"]["created"] == report["admitted_participations"], \
    (rep["participations"], report["admitted_participations"])
assert rep["final_state"] == "revealed", rep["states"]
assert rep["faults"], "no chaos faults attributed in the spools"
assert all(f["site"] and f["kind"] for f in rep["faults"]), rep["faults"]
# bit-exact reveal recorded: the spooled reveal span's digest matches the
# loadgen oracle's digest of the expected plaintext sum
assert rep["reveal"] and rep["reveal"]["output_sha256"] == report["output_sha256"], \
    (rep["reveal"], report["output_sha256"])
print(f"forensics drill OK: {rep['spans']} spans from "
      f"{len(rep['processes'])} dead processes, "
      f"{rep['participations']['created']} participations, "
      f"{len(rep['faults'])} faults attributed, states "
      f"{'->'.join(s['state'] for s in rep['states'])}, reveal digest match")
PY
# the CLI spelling must agree with the library pass (and exit 0)
env SDA_FLIGHT_RECORDER="$SPOOL_DIR" python -m sda_tpu.cli.tracecli segments > /dev/null
env SDA_FLIGHT_RECORDER="$SPOOL_DIR" python -m sda_tpu.cli.tracecli slo > /dev/null
rm -rf "$SPOOL_DIR"

echo "== recorder overhead bench (span hot path, recorder off vs on; BENCH record gated advisory)"
REC_RECORD=$(mktemp /tmp/sda-recbench-XXXX.json)
python -m sda_tpu.loadgen.recorderbench --spans 20000 --max-overhead-pct 400 > "$REC_RECORD"
python -m sda_tpu.obs.regress --advisory BENCH_r*.json "$REC_RECORD"
rm -f "$REC_RECORD"

echo "== soak drill (fixed seed: 2 tenants x 3 pipelined epochs, sqlite + HTTP fleet of 2, ~10% chaos, churn armed; bit-exact per epoch, flat store after retention)"
SOAK_RECORD=$(mktemp /tmp/sda-soak-XXXX.json)
SOAK=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --soak \
  --soak-tenants 2 --soak-epochs 3 --soak-participants 4 \
  --soak-store sqlite --soak-fleet 2 --soak-chaos-rate 0.1 \
  --soak-churn 0.4 --soak-seed 20260803)
SOAK="$SOAK" SOAK_RECORD="$SOAK_RECORD" python - <<'PY'
import json, os
report = json.loads(os.environ["SOAK"].strip().splitlines()[-1])
# the continuous-service verdict: every tenant's every epoch revealed
# bit-exactly, epoch R+1 collected while epoch R clerked (server-stamped
# history), and nothing leaked across epochs or tenants
assert report["exact"] is True, report
assert report["rounds_exact"] == report["rounds"] == 6, report
assert report["pipelined"] is True, report["pipelined_pairs"]
assert report["leaks"] == 0, report
assert report["client_failures"] == 0, report
# the scheduler really was contended (two handles race every mint) and
# every epoch was minted exactly once
sched = report["scheduler"]
assert sched["epochs_minted"] == 6, sched
# churned devices all rejoined via their journals
churn = report["churn"]
assert churn["participants_churned"] >= 1, churn
assert churn["participants_resumed"] == churn["participants_churned"], churn
# retention kept the store flat: every revealed round purged, zero
# leaked rows between epoch 2 and the final epoch, worker RSS flat
retention = report["retention"]
assert retention["purged_rounds"] == 6, retention
assert retention["store_rows_flat"] is True, retention
assert retention["rss_flat"] in (True, None), retention
assert report["fleet"]["leaked"] == 0, report["fleet"]
with open(os.environ["SOAK_RECORD"], "w") as f:
    json.dump(report, f)
print(f"soak drill OK: {report['rounds_exact']}/{report['rounds']} epochs "
      f"exact, pipelined {report['pipelined_pairs']}, "
      f"{retention['purged_rounds']} rounds purged, store rows "
      f"{retention['store_rows_epoch2']}->{retention['store_rows_final']}, "
      f"{report['value']} rounds/hour sustained")
PY
# the rounds_per_hour record must parse as a bench record and gate
# (advisory: first record of its metric seeds the trailing window)
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$SOAK_RECORD"
rm -f "$SOAK_RECORD"

echo "== analytics drill (fixed seed: histogram + count-min tenants, 2 recurring epochs each, sqlite+HTTP; bit-exact sums, decoder errors within declared contracts)"
ANA_RECORD=$(mktemp /tmp/sda-analytics-XXXX.json)
ANA=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --analytics histogram,countmin \
  --analytics-participants 4 --analytics-epochs 2 \
  --analytics-store sqlite --analytics-http --analytics-seed 20260806)
ANA="$ANA" ANA_RECORD="$ANA_RECORD" python - <<'PY'
import json, os
report = json.loads(os.environ["ANA"].strip().splitlines()[-1])
# the analytics verdict: every tenant-epoch's revealed sum equals the
# plaintext sum bit-exactly, and every decoded answer stays within the
# encoder's declared error contract against the seeded ground truth
assert report["exact"] is True, report
assert report["rounds_exact"] == report["rounds"] == 4, report
assert report["bounds_ok"] is True, report
assert report["rounds_within_bounds"] == 4, report
assert report["leaks"] == 0, report
assert report["client_failures"] == 0, report
# the multi-tenant scheduler drove every round: both schedules
# installed, every epoch minted/closed through the cadence-gated tick
sched = report["scheduler"]
assert sched["installed"] == 2, sched
assert sched["epochs_closed"] == 4, sched
per = report["per_tenant"]
hist = per["analytics-histogram-0"]
cm = per["analytics-countmin-1"]
# the exact encoder really was exact; the sketch stayed under eps*N
# with zero delta-budget breaches and no count-min underestimates
assert all(c["error"] == 0.0 for c in hist["checks"]), hist["checks"]
assert all(c["error"] <= c["bound"] and c["underestimates"] == 0
           and c["eps_violations"] <= c["delta_allowance"]
           for c in cm["checks"]), cm["checks"]
with open(os.environ["ANA_RECORD"], "w") as f:
    json.dump(report, f)
print(f"analytics drill OK: {report['rounds_exact']}/{report['rounds']} "
      f"rounds exact, {report['rounds_within_bounds']} within contract, "
      f"{report['value']} values/s")
PY
# the values/s record must parse as a bench record and gate (advisory:
# first record of its metric seeds the trailing window)
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$ANA_RECORD"
rm -f "$ANA_RECORD"

echo "== FL drill (fixed seed: LeNet secure FedAvg, 8 devices, ~25% churn, 1 dead clerk, sqlite+HTTP; target accuracy reached, bit-exact aggregate every round)"
FL_RECORD=$(mktemp /tmp/sda-fl-XXXX.json)
FL=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --fl --participants 8 \
  --fl-family lenet --fl-rounds 3 --fl-local-steps 6 --fl-batch 32 \
  --fl-target 0.8 --fl-churn 0.25 --fl-dead-clerks 1 \
  --fl-store sqlite --fl-http --fl-seed 20260803)
FL="$FL" FL_RECORD="$FL_RECORD" python - <<'PY'
import json, os
report = json.loads(os.environ["FL"].strip().splitlines()[-1])
# the canonical-workload verdict: R secure FedAvg rounds over the real
# stack reach the target accuracy, and EVERY revealed round is bit-exact
# vs the plaintext quantized sum of its frozen participant set — under
# nonzero device dropout AND a permanently dead committee clerk
assert report["exact"] is True, report["failure_samples"]
assert report["rounds_exact"] == report["rounds_run"] == 3, report
assert report["reached_target"] is True, report["accuracy_by_round"]
assert report["rounds_to_target"] <= 3, report
assert report["final_accuracy"] >= report["target_accuracy"], report
# the real (shrunk) LeNet trained and shipped: 61k-dim encoded deltas
assert report["family"] == "lenet" and report["dim"] > 60000, report
# availability churn actually happened and resolved exactly-once: every
# departure resumed via its journal, mid-upload crashes replayed
# byte-identically, pre-upload crashes ARE the rounds' dropout
churn = report["churn"]
assert churn["participants_churned"] >= 1, churn
assert churn["participants_resumed"] == churn["participants_churned"], churn
assert churn["participations_replayed"] >= 1, churn
assert churn["dropped_from_rounds"] >= 1, churn
assert any(r["dropped"] >= 1 for r in report["per_round"]), report["per_round"]
# the dead clerk degraded every round through the lifecycle plane — and
# the surviving Shamir quorum still revealed (never hung, never failed)
assert report["degraded_rounds"] == 3, report
assert all(r["state"] == "revealed" for r in report["per_round"]), report
assert report["leaks"] == 0 and report["client_failures"] == 0, report
with open(os.environ["FL_RECORD"], "w") as f:
    json.dump(report, f)
acc = "->".join(str(a) for a in report["accuracy_by_round"])
print(f"FL drill OK: accuracy {acc} (target {report['target_accuracy']} in "
      f"{report['rounds_to_target']} round(s)), {report['rounds_exact']}/3 "
      f"bit-exact, {churn['participants_churned']} churned/"
      f"{churn['participants_resumed']} resumed/"
      f"{churn['participations_replayed']} replayed, "
      f"{report['degraded_rounds']} degraded round(s)")
PY
# the accuracy-vs-rounds record (direction=lower: MORE rounds to target
# is the regression) must parse as a bench record and gate advisory via
# sda-bench --check (first record of its metric seeds the window)
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$FL_RECORD"
rm -f "$FL_RECORD"
# the participate-input micro-bench behind the ndarray pass-through fix:
# one vectorized normalization at model dim instead of 1e5 int() calls
python -m sda_tpu.loadgen.inputbench --dim 100000

echo "== poisoning drill (fixed seed: boost:-8 at r=0.4 — undefended degrades, norm-clip defense recovers, BOTH bit-exact with clerk-side detections; tree-mode trimmed mean)"
# A/B/C at one seed: the same seeded attacker plan (chaos/poison.py)
# corrupts the same devices in all poisoned legs, so the accuracy
# deltas are attributable to the defense, not the draw
POISON_ARGS=(--fl --participants 5 --fl-rounds 2 --fl-seed 3)
CLEAN=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim "${POISON_ARGS[@]}")
UNDEF=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim "${POISON_ARGS[@]}" \
  --poison 0.4)
DEFEND=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim "${POISON_ARGS[@]}" \
  --poison 0.4 --fl-norm-clip 0.5)
# tree-mode leg: signflip attackers inside leaf groups, robust
# (trimmed-mean) recipient aggregation over unmasked leaf subtotals
TREE=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --fl --participants 9 \
  --fl-rounds 2 --fl-seed 5 --fl-tree-group 3 \
  --poison 0.25 --poison-kind signflip --fl-tree-robust)
POISON_RECORD=$(mktemp /tmp/sda-poison-XXXX.json)
CLEAN="$CLEAN" UNDEF="$UNDEF" DEFEND="$DEFEND" TREE="$TREE" \
  POISON_RECORD="$POISON_RECORD" python - <<'PY'
import json, os
last = lambda k: json.loads(os.environ[k].strip().splitlines()[-1])
clean, undef, defend, tree = map(last, ("CLEAN", "UNDEF", "DEFEND", "TREE"))
# bit-exactness is unconditional: poisoning corrupts INPUTS, never the
# protocol — every revealed round still equals the plaintext quantized
# sum of what was actually submitted (taint adds the field modulus p,
# invisible mod p, so detection and exactness coexist)
for leg in (clean, undef, defend, tree):
    assert leg["exact"] is True, leg.get("failure_samples")
    assert leg["rounds_exact"] == leg["rounds_run"], leg
    assert leg["client_failures"] == 0, leg
# undefended: the boosted updates wreck the model. defended: the codec's
# by-construction L2 projection caps attacker mass; accuracy recovers
assert clean["attack"] is None, clean["attack"]
assert clean["final_accuracy"] >= 0.9, clean["accuracy_by_round"]
assert undef["final_accuracy"] <= clean["final_accuracy"] - 0.5, (
    undef["accuracy_by_round"])
assert defend["final_accuracy"] >= 0.9, defend["accuracy_by_round"]
# both poisoned legs selected the SAME seeded attackers and every
# attacker's tainted (out-of-field) share upload was counted by clerks
for leg in (undef, defend):
    atk = leg["attack"]
    assert atk["attackers_total"] >= 1, atk
    assert atk["shares_tainted"] == atk["attackers_total"], atk
    assert atk["out_of_range_detections"] >= atk["attackers_total"], atk
assert undef["attack"]["attackers_by_round"] == \
    defend["attack"]["attackers_by_round"], (undef["attack"],
                                             defend["attack"])
assert undef["attack"]["defended"] is False, undef["attack"]
assert defend["attack"]["defended"] is True, defend["attack"]
# the quantizer block surfaces the defense and its headroom
assert defend["quantizer"]["norm_clip"] == 0.5, defend["quantizer"]
assert defend["quantizer"]["headroom_margin"] > 0, defend["quantizer"]
# tree mode: trimmed mean over per-leaf subtotals holds the target
# under in-leaf signflip attackers, with detections at leaf clerks
assert tree["reached_target"] is True, tree["accuracy_by_round"]
t = tree["attack"]
assert t["tree_robust"] is True and t["attackers_total"] >= 1, t
assert t["out_of_range_detections"] >= 1, t
assert all(r["robust_leaves"] == 3 for r in tree["per_round"]), (
    tree["per_round"])
record = {
    "metric": ("defended final accuracy under boost:-8 poisoning "
               "(r=0.4, L2 norm clip 0.5, secure FedAvg, 5 devices)"),
    "value": defend["final_accuracy"],
    "direction": "higher",
    "unit": "accuracy",
    "platform": defend["platform"],
    "seed": defend["seed"],
    "attack": {
        "kind": defend["attack"]["kind"],
        "rate": defend["attack"]["rate"],
        "clean_final": clean["final_accuracy"],
        "undefended_final": undef["final_accuracy"],
        "defended_final": defend["final_accuracy"],
        "recovery": round(defend["final_accuracy"]
                          - undef["final_accuracy"], 4),
        "detections": defend["attack"]["out_of_range_detections"],
        "tree_robust_final": tree["final_accuracy"],
    },
}
with open(os.environ["POISON_RECORD"], "w") as f:
    json.dump(record, f)
print(f"poisoning drill OK: clean {clean['final_accuracy']} / undefended "
      f"{undef['final_accuracy']} / defended {defend['final_accuracy']} "
      f"(recovery +{record['attack']['recovery']}), "
      f"{defend['attack']['out_of_range_detections']} clerk detections, "
      f"tree trimmed-mean {tree['final_accuracy']}; all legs bit-exact")
PY
# the defended-accuracy record (direction=higher: a defense that stops
# recovering IS the regression) gates advisory via sda-bench --check
python -m sda_tpu.cli.bench --check --advisory BENCH_r*.json "$POISON_RECORD"
rm -f "$POISON_RECORD"

echo "== trace smoke (fixed seed: Chrome-trace export, one connected round trace, bit-exact)"
TRACE_OUT=$(mktemp /tmp/sda-trace-XXXX.json)
TRACE_REPORT=$(env JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim --load --participants 12 --dim 4 \
  --load-arrivals closed --load-concurrency 4 --load-seed 20260803 \
  --load-store memory --trace-out "$TRACE_OUT")
TRACE_REPORT="$TRACE_REPORT" TRACE_OUT="$TRACE_OUT" python - <<'PY'
import json, os
report = json.loads(os.environ["TRACE_REPORT"].strip().splitlines()[-1])
# the round result must stay bit-exact with tracing enabled
assert report["ready"] and report["exact"], report
trace = json.load(open(os.environ["TRACE_OUT"]))  # must parse as JSON
spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
by_id = {e["args"]["span_id"]: e for e in spans}
traces = {}
for e in spans:
    traces.setdefault(e["args"]["trace_id"], []).append(e)
round_traces = 0
for members in traces.values():
    roles = {e["name"].split(" ")[0].split(".")[0] for e in members}
    # cross-process-connected: a server span whose parent is a client
    # attempt span proves the trace crossed the HTTP hop
    crossed = any(
        e["name"].startswith("http.server")
        and by_id.get(e["args"].get("parent_id", ""), {}).get("name") == "http.attempt"
        for e in members)
    if {"participant", "server", "clerk", "recipient"} <= roles and crossed:
        round_traces += 1
assert round_traces >= 1, f"no connected round trace among {len(traces)}"
print(f"trace smoke OK: {len(spans)} spans, {round_traces} connected round trace(s)")
PY
rm -f "$TRACE_OUT"

echo "== device perf plane (fixed seed: roofline block + compile counters + advisory regression gate)"
PERF_REPORT=$(env SDA_SIM_PLATFORM=cpu JAX_PLATFORMS=cpu python -m sda_tpu.cli.sim \
  --participants 16 --dim 96 --clerks 8 --verify)
PERF_REPORT="$PERF_REPORT" python - <<'PY'
import json, os
report = json.loads(os.environ["PERF_REPORT"].strip().splitlines()[-1])
assert report["exact"], report
roof = report["roofline"]  # the block must parse with all four fields
assert roof["flops"] > 0 and roof["bytes"] > 0, roof
assert roof["arithmetic_intensity"] > 0, roof
assert 0 < roof["utilization"] < 1, roof
assert roof["hbm_peak_bytes"] > 0, roof
compile_counters = {k: v for k, v in report["counters"].items()
                    if k.startswith("xla.compile.")}
assert compile_counters, report["counters"]
assert report["xla"]["functions"]["mesh.simpod.round"]["retraces"] == 0
print(f"device perf plane OK: AI={roof['arithmetic_intensity']}, "
      f"utilization={roof['utilization']} ({roof['platform']} peaks), "
      f"compile counters {compile_counters}")
PY
# advisory on CPU: CPU rung numbers are not gated, but a malformed
# committed record still fails CI (exit 2)
python -m sda_tpu.obs.regress --advisory BENCH_r*.json

echo "== CLI walkthrough (real sdad + sda over HTTP)"
env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu bash docs/walkthrough.sh | tail -1 | {
  read -r reveal
  echo "reveal: $reveal"
  [ "$reveal" = "0 2 2 4 4 6 6 8 8 10" ] || { echo "walkthrough output mismatch"; exit 1; }
}

echo "== examples (protocol-over-REST + streamed checkpoint/resume + embedded)"
python examples/federated_http.py
python examples/streamed_checkpoint.py
python examples/embedded_participant.py

echo "CI OK"
