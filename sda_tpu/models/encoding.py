"""Fixed-point encoding of float model vectors into Z_m.

The reference aggregates i64 vectors and leaves the float<->integer story
to the application ("combining locally trained machine learning models",
reference README.md:3-15; `Secret = i64`, client/src/crypto/mod.rs:33-36).
This module owns that story for the TPU build: a deterministic fixed-point
codec whose central guarantee is *exactness of the aggregate* — the secure
modular sum of encodings decodes to the exact sum of the quantized client
values, provided the configured summand capacity is respected.

Centered representation: a quantized value q in [-Q, Q] is uploaded as
q mod m. Sums stay decodable while |sum q_i| < m/2, so the codec derives
its clip range from (modulus, fractional_bits, max_summands) and refuses
configurations that could wrap. This mirrors the headroom discipline the
reference leaves implicit (values "assumed small enough", sharing/
additive.rs:37-39) but makes it a checked, documented contract.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

__all__ = [
    "FieldSizingError",
    "FixedPointCodec",
    "field_capacity",
    "field_headroom_check",
    "ravel_pytree",
]


class FieldSizingError(ValueError):
    """A configuration whose worst-case aggregate could wrap the field.

    Raised by :func:`field_headroom_check` — the one headroom rule shared
    by :class:`FixedPointCodec` and every analytics encoder
    (``sda_tpu/analytics``), so the two contracts cannot drift. A
    subclass of ``ValueError`` so existing callers keep catching it.
    """


def field_capacity(modulus: int, max_summands: int) -> int:
    """Largest per-coordinate magnitude the centered band can carry.

    A sum of ``max_summands`` contributions each bounded by the returned
    value stays strictly inside the decodable band ``|sum| <= m//2 - 1``
    (centered lift, matching ``RecipientOutput.positive()``'s canonical
    band shifted to (-m/2, m/2]).
    """
    if modulus < 3:
        raise FieldSizingError(f"modulus {modulus} must be >= 3")
    if max_summands < 1:
        raise FieldSizingError(f"max_summands {max_summands} must be >= 1")
    return (modulus // 2 - 1) // int(max_summands)


def field_headroom_check(max_abs: int, max_summands: int, modulus: int,
                         *, context: str = "") -> int:
    """THE modulus-headroom rule: refuse configurations that could wrap.

    Checks that the worst-case aggregate magnitude ``max_abs *
    max_summands`` fits the centered decodable band of ``modulus`` and
    returns the remaining margin (``m//2 - 1 - max_abs*max_summands``,
    always >= 0 on success). Raises :class:`FieldSizingError` naming the
    whole configuration otherwise — a misconfigured encoder is a typed
    error at construction, never a silent wrap at decode.

    ``context`` names the caller (e.g. ``"FixedPointCodec"`` or
    ``"CountMinEncoder(width=64, depth=4)"``) so the error says WHICH
    contract failed.
    """
    max_abs = int(max_abs)
    if max_abs < 1:
        raise FieldSizingError(
            f"{context or 'field sizing'}: max per-coordinate contribution "
            f"{max_abs} must be >= 1")
    cap = field_capacity(modulus, max_summands)
    margin = modulus // 2 - 1 - max_abs * int(max_summands)
    if margin < 0:
        raise FieldSizingError(
            f"{context or 'field sizing'}: per-coordinate contribution up "
            f"to {max_abs} x {max_summands} summands needs a decodable "
            f"band of {max_abs * int(max_summands)}, but modulus {modulus} "
            f"only carries |sum| <= {modulus // 2 - 1} "
            f"(per-coordinate capacity {cap}): increase the modulus or "
            f"lower max_summands")
    return margin


def ravel_pytree(tree):
    """Flatten a pytree of float arrays to one float64 numpy vector.

    Returns (vector, unravel) where unravel maps a same-length float vector
    back to the original structure/shapes/dtypes. This is the TPU analog of
    the reference's "the model IS the vector" convention (README.md:3-15):
    one participation carries one flattened model (or model delta).
    """
    import jax
    from jax import numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = [np.shape(l) for l in leaves]
    dtypes = [np.asarray(l).dtype for l in leaves]
    sizes = [int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes]
    vec = np.concatenate(
        [np.asarray(l, dtype=np.float64).reshape(-1) for l in leaves]
    ) if leaves else np.zeros((0,), np.float64)

    def unravel(flat):
        flat = np.asarray(flat, dtype=np.float64)
        if flat.shape != vec.shape:
            raise ValueError(f"expected shape {vec.shape}, got {flat.shape}")
        out, off = [], 0
        for shape, dtype, size in zip(shapes, dtypes, sizes):
            chunk = flat[off:off + size].reshape(shape).astype(dtype)
            out.append(jnp.asarray(chunk))
            off += size
        return jax.tree_util.tree_unflatten(treedef, out)

    return vec, unravel


class FixedPointCodec:
    """Deterministic fixed-point codec float -> Z_m with summand capacity.

    Parameters
    ----------
    modulus:
        The aggregation modulus m (additive scheme modulus or the Shamir
        prime; resources.rs:44-67 carries it in-band in the Aggregation).
    fractional_bits:
        Scale = 2**fractional_bits. Quantization step is 2**-fractional_bits.
    max_summands:
        Largest number of vectors that will ever be summed under one
        aggregation (participants; clerk partial sums never exceed this).
        The decodable band is |sum| < m/2, so per-value magnitude is capped
        at clip = floor((m//2 - 1) / max_summands) / scale.
    clip:
        Optional tighter magnitude bound (floats are clamped to [-clip, clip]
        before quantization). Must not exceed the capacity-derived bound.
    norm_clip:
        Optional L2 bound enforced BY CONSTRUCTION: any vector whose
        Euclidean norm exceeds it is projected onto the norm_clip ball
        before quantization. This is the input-side poisoning defense —
        a boosted or sign-flipped update cannot contribute more L2 mass
        than an honest one, because the bound lives in the codec every
        client routes through, not in a flag a malicious client could
        skip. Host-lane only: the float64 norm reduction is not
        bit-reproducible across numpy and XLA, so ``encode_device``
        rejects the combination with a typed error.

    Adversarial floats (NaN/±Inf) clamp deterministically on BOTH lanes:
    NaN -> 0, ±Inf -> ±clip — never an undefined int cast (``np.clip``
    passes NaN through, so the scrub happens explicitly first).
    """

    __slots__ = ("modulus", "fractional_bits", "scale", "max_summands",
                 "clip", "norm_clip", "_q_max")

    def __init__(self, modulus: int, fractional_bits: int, max_summands: int,
                 clip: Optional[float] = None,
                 norm_clip: Optional[float] = None):
        modulus = int(modulus)
        if modulus < 3:
            raise ValueError("modulus must be >= 3")
        if max_summands < 1:
            raise ValueError("max_summands must be >= 1")
        self.modulus = modulus
        self.fractional_bits = int(fractional_bits)
        self.scale = float(1 << self.fractional_bits)
        self.max_summands = int(max_summands)
        q_cap = field_capacity(modulus, self.max_summands)
        if q_cap < 1:
            raise FieldSizingError(
                f"modulus {modulus} has no headroom for {max_summands} "
                f"summands: increase the modulus or lower max_summands"
            )
        cap = q_cap / self.scale
        if clip is None:
            clip = cap
        elif clip > cap:
            raise ValueError(
                f"clip {clip} exceeds the exactness capacity {cap:.6g} "
                f"(modulus {modulus}, {max_summands} summands, "
                f"{self.fractional_bits} fractional bits)"
            )
        elif clip <= 0:
            raise ValueError("clip must be positive")
        self.clip = float(clip)
        if norm_clip is not None:
            norm_clip = float(norm_clip)
            if not norm_clip > 0:
                raise ValueError("norm_clip must be positive")
        self.norm_clip = norm_clip
        self._q_max = int(round(self.clip * self.scale))
        # seal the invariant through the SHARED headroom rule (the same
        # one every analytics encoder calls), so the codec's capacity
        # derivation above and the field contract cannot drift apart
        field_headroom_check(max(1, self._q_max), self.max_summands,
                             modulus, context="FixedPointCodec")

    @property
    def q_max(self) -> int:
        """The integer quantization cap: |quantize(x)| <= q_max, so the
        worst-case sum magnitude is q_max * max_summands (< m/2 by the
        constructor's capacity rule)."""
        return self._q_max

    # -- host (numpy) path -------------------------------------------------

    def quantize(self, x) -> np.ndarray:
        """Float array -> signed quantized int64 in [-q_max, q_max].

        Quantization happens in float32 — the same arithmetic the device
        path uses — so host and device encodings are bit-identical (both
        numpy and XLA round half to even). Adversarial floats clamp
        deterministically: NaN -> 0 (np.clip would pass it through into
        an undefined int64 cast), ±Inf -> ±clip. With ``norm_clip``, the
        per-coordinate clamp happens FIRST (bounding every coordinate,
        Inf included), then the L2 projection — computed in float64 so
        the scale factor is deterministic — shrinks the whole vector
        onto the norm ball.
        """
        x32 = np.asarray(x, dtype=np.float32)
        x32 = np.where(np.isnan(x32), np.float32(0.0), x32)
        x32 = np.clip(x32, np.float32(-self.clip), np.float32(self.clip))
        if self.norm_clip is not None:
            x64 = x32.astype(np.float64)
            norm = float(np.sqrt(np.sum(x64 * x64)))
            if norm > self.norm_clip:
                x32 = (x64 * (self.norm_clip / norm)).astype(np.float32)
        q = np.rint(x32 * np.float32(self.scale)).astype(np.int64)
        return np.clip(q, -self._q_max, self._q_max)

    def encode(self, x) -> np.ndarray:
        """Float array -> representatives in [0, modulus) ready to share."""
        return np.mod(self.quantize(x), self.modulus).astype(np.int64)

    def decode_sum(self, values, summands: int = 1) -> np.ndarray:
        """Aggregate in [0, m) -> exact float sum of the quantized inputs.

        ``summands`` is checked against the configured capacity; the lift is
        centered, matching RecipientOutput.positive()'s canonical band
        (receive.rs:14-21) shifted to (-m/2, m/2].
        """
        v = np.asarray(values, dtype=np.int64)
        if summands < 1:
            # a zero/negative summand count is always a caller bug (an
            # empty frozen set, a None participation count propagated
            # into the mean): fail typed here rather than as a
            # ZeroDivisionError inside decode_mean or a silently wrong
            # "sum of zero things" — and name the aggregation context so
            # the error is actionable from a decoder stack trace
            raise ValueError(
                f"decode needs at least one summand, got {summands} "
                f"(aggregation: dim {v.size}, modulus {self.modulus}, "
                f"capacity {self.max_summands} summands; empty frozen "
                "set? use the revealed participation count)"
            )
        if summands > self.max_summands:
            raise ValueError(
                f"{summands} summands exceeds configured capacity "
                f"{self.max_summands} (aggregation: dim {v.size}, "
                f"modulus {self.modulus}); the sum may have wrapped"
            )
        v = np.mod(v, self.modulus)
        half = self.modulus // 2
        centered = v - np.where(v > half, self.modulus, 0)
        return centered.astype(np.float64) / self.scale

    def decode_mean(self, values, summands: int) -> np.ndarray:
        return self.decode_sum(values, summands) / float(summands)

    # -- device (jnp) path -------------------------------------------------

    def encode_device(self, x):
        """jnp float array -> int32 residues in [0, m), jit-friendly.

        Matches the host ``encode`` bit-for-bit: both paths clip, scale,
        and round in float32 (half-to-even). Requires clip * scale within
        float32's exact-integer range (2^24) so the rounded product is
        representable — the constructor's capacity rule keeps realistic
        FedAvg configs far below that. Output dtype is int32 (modulus <
        2^31 per fields/numtheory.py's device-limb constraint) so it feeds
        the pod/streamed paths directly.
        """
        from jax import numpy as jnp

        if self.norm_clip is not None:
            raise ValueError(
                f"norm_clip {self.norm_clip} is a host-lane contract: the "
                "L2 reduction is not bit-reproducible between numpy and "
                "XLA; use the host encode() for norm-clipped configs"
            )
        if self._q_max > (1 << 24):
            raise ValueError(
                f"q_max {self._q_max} exceeds float32's exact-integer range; "
                "use the host encode() for this configuration"
            )
        xf = jnp.asarray(x, jnp.float32)
        xf = jnp.where(jnp.isnan(xf), jnp.float32(0.0), xf)
        xc = jnp.clip(xf, jnp.float32(-self.clip), jnp.float32(self.clip))
        q = jnp.round(xc * jnp.float32(self.scale)).astype(jnp.int32)
        q = jnp.clip(q, -self._q_max, self._q_max)
        return jnp.where(q < 0, q + self.modulus, q).astype(jnp.int32)

    # -- misc ----------------------------------------------------------------

    def __repr__(self):
        norm = ("" if self.norm_clip is None
                else f", norm_clip={self.norm_clip:.6g}")
        return (f"FixedPointCodec(modulus={self.modulus}, "
                f"fractional_bits={self.fractional_bits}, "
                f"max_summands={self.max_summands}, clip={self.clip:.6g}"
                f"{norm})")
