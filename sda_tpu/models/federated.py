"""Secure federated averaging over the SDA stack.

This is the reference's raison d'etre run end-to-end: each participant
trains locally, and only *encoded model deltas* leave the device — masked,
secret-shared across the committee, and revealed as an exact sum by the
recipient (participate.rs:37-113 / clerk.rs:63-107 / receive.rs:80-157 flow).
No individual update is ever visible to the server or any quorum smaller
than the scheme's privacy threshold.

Two execution surfaces, same math:

- ``FederatedSession`` — the real protocol: an `SdaService` (any store or
  the HTTP seam), one aggregation per round, clerks running chores.
- ``pod_fedavg_round`` — the TPU-native fast path: deltas for a whole
  cohort live as a [P, d] device array and one `SimulatedPod`/
  `StreamedPod` round produces the sum via mesh collectives.

The fixed-point codec guarantees the secure sum equals the plaintext sum
of quantized deltas bit-for-bit, so FedAvg here is exactly FedAvg — the
only deviation from float averaging is the quantization step itself.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..protocol import Aggregation, AggregationId
from .encoding import FixedPointCodec, ravel_pytree

__all__ = ["LocalTrainer", "FederatedSession", "pod_fedavg_round"]


class LocalTrainer:
    """Jitted local-steps trainer: params -> params after k optimizer steps.

    ``loss_fn(params, batch) -> scalar`` and an optax optimizer; the k-step
    loop is a `lax.scan` so one compiled program covers the whole local
    epoch regardless of k (no per-step dispatch).
    """

    def __init__(self, loss_fn: Callable, optimizer):
        import jax
        import jax.numpy as jnp
        import optax

        self.loss_fn = loss_fn
        self.optimizer = optimizer

        def fit(params, opt_state, batches):
            def step(carry, batch):
                p, s = carry
                loss, grads = jax.value_and_grad(loss_fn)(p, batch)
                updates, s = optimizer.update(grads, s, p)
                p = optax.apply_updates(p, updates)
                return (p, s), loss

            (params, opt_state), losses = jax.lax.scan(
                step, (params, opt_state), batches)
            return params, opt_state, jnp.mean(losses)

        # devprof registry: a cohort whose batch shapes drift (ragged local
        # datasets) retraces this program once per shape — the compiled-
        # shape registry and retrace span events make that visible instead
        # of silently serializing compile time into the round
        from ..obs import devprof

        self._fit = devprof.instrument("models.local_fit", jax.jit(fit))

    def init_state(self, params):
        return self.optimizer.init(params)

    def fit(self, params, opt_state, batches):
        """batches: pytree of arrays with a leading [k, ...] steps axis."""
        return self._fit(params, opt_state, batches)


class FederatedSession:
    """Drives secure FedAvg rounds through the real protocol stack.

    The caller supplies ready SdaClients (recipient with an uploaded
    encryption key, clerks with keys, participants) and an Aggregation
    *template* whose schemes/modulus/dimension describe the update vector;
    each round clones it under a fresh id (aggregations are one-shot,
    resources.rs:44-67).
    """

    def __init__(self, template: Aggregation, codec: FixedPointCodec,
                 recipient, clerks: Sequence, participants: Sequence):
        if template.vector_dimension <= 0:
            raise ValueError("template.vector_dimension must be positive")
        if template.modulus != codec.modulus:
            raise ValueError(
                f"codec modulus {codec.modulus} != aggregation modulus "
                f"{template.modulus}: the decoded mean would be garbage")
        if len(participants) > codec.max_summands:
            raise ValueError(
                f"{len(participants)} participants exceed the codec capacity "
                f"{codec.max_summands}")
        self.template = template
        self.codec = codec
        self.recipient = recipient
        self.clerks = list(clerks)
        self.participants = list(participants)

    def round(self, deltas: Sequence[np.ndarray], *,
              deadline: float = 60.0) -> np.ndarray:
        """One secure round: encode + participate + clerk + reveal.

        ``deltas`` is one float vector per participant (client_params -
        global_params, pre-raveled). Returns the exact decoded *mean* delta.

        The encoded int64 residue array is handed to ``participate``
        as-is — the client normalizes ndarrays without a per-element
        Python conversion, so a 10^5-dim model costs one vectorized
        pass, not 10^5 ``int()`` calls (sda_tpu/loadgen/inputbench.py
        measures the difference).

        The reveal is driven through the lifecycle plane
        (:meth:`SdaClient.await_result`): a round the supervisor
        declared terminal raises the typed
        :class:`~sda_tpu.protocol.RoundFailed` /
        :class:`~sda_tpu.protocol.RoundExpired` with the server's
        diagnosis, and a quorum-degraded Shamir round reveals bit-exactly
        from the survivors — never a hang, never a silent partial-
        committee sum. The mean divides by the *revealed* participation
        count (the snapshot's frozen set), so a round whose committee
        degraded still averages over exactly the participations it
        actually summed. ``deadline`` bounds the wait client-side.
        """
        if len(deltas) != len(self.participants):
            raise ValueError("one delta per participant required")
        dim = self.template.vector_dimension
        aggregation = self.template.replace(id=AggregationId.random())
        self.recipient.upload_aggregation(aggregation)
        self.recipient.begin_aggregation(aggregation.id)

        for participant, delta in zip(self.participants, deltas):
            delta = np.asarray(delta, dtype=np.float64)
            if delta.shape != (dim,):
                raise ValueError(f"delta shape {delta.shape} != ({dim},)")
            participant.participate(self.codec.encode(delta), aggregation.id)

        self.recipient.end_aggregation(aggregation.id)
        self.recipient.run_chores(-1)
        for clerk in self.clerks:
            clerk.run_chores(-1)

        output = self.recipient.await_result(
            aggregation.id, deadline=deadline, poll_interval=0.05)
        values = output.positive().values
        # None = pre-lifecycle server: fall back to the nominal count. A
        # REVEALED 0 is a real (degenerate) answer — let decode_mean's
        # typed empty-summand guard surface it rather than silently
        # averaging an empty sum over the full population.
        summands = (output.participations
                    if output.participations is not None
                    else len(self.participants))
        return self.codec.decode_mean(values, summands)


def pod_fedavg_round(pod, codec: FixedPointCodec, global_vec: np.ndarray,
                     client_vecs, key=None) -> np.ndarray:
    """TPU-native FedAvg round: cohort deltas -> mesh round -> mean delta.

    ``client_vecs`` is a [P, d] float array (or list of vectors) of client
    parameter vectors; deltas against ``global_vec`` are encoded on device
    and aggregated in ONE pod round (mask + share + psum_scatter + finale
    all via mesh collectives — no per-client protocol messages). Returns the
    new global vector, exactly global + mean(quantized deltas)/scale.
    """
    from jax import numpy as jnp

    global_vec = np.asarray(global_vec, dtype=np.float64)
    stacked = np.asarray(client_vecs, dtype=np.float64)
    if stacked.ndim != 2 or stacked.shape[1] != global_vec.shape[0]:
        raise ValueError(f"client_vecs shape {stacked.shape} incompatible "
                         f"with global {global_vec.shape}")
    n = stacked.shape[0]
    if n > codec.max_summands:
        raise ValueError(f"{n} clients exceed codec capacity {codec.max_summands}")
    pod_modulus = getattr(pod, "modulus", codec.modulus)
    if pod_modulus != codec.modulus:
        raise ValueError(
            f"codec modulus {codec.modulus} != pod modulus {pod_modulus}: "
            "the decoded mean would be garbage")

    deltas = jnp.asarray(stacked - global_vec[None, :], jnp.float32)
    encoded = codec.encode_device(deltas)
    summed = pod.aggregate(encoded, key)
    mean_delta = codec.decode_mean(np.asarray(summed), n)
    return global_vec + mean_delta
