"""Model layer: float models in, secure aggregates out.

Completes the reference's federated-ML story (README.md:3-15) with the
pieces it leaves to the application: fixed-point encoding into Z_m,
flax model families sized to the benchmark workloads, and FedAvg driven
through either the real protocol or the TPU mesh fast path.

The flax-backed families load lazily (PEP 562) so the codec and the
federated drivers work on installs without flax/optax.
"""

from .encoding import (
    FieldSizingError,
    FixedPointCodec,
    field_capacity,
    field_headroom_check,
    ravel_pytree,
)
from .federated import FederatedSession, LocalTrainer, pod_fedavg_round

_FAMILY_EXPORTS = (
    "LeNet",
    "MobileLite",
    "LoRAMLP",
    "lora_adapter_params",
    "merge_lora_params",
    "param_count",
)

__all__ = [
    "FieldSizingError",
    "FixedPointCodec",
    "field_capacity",
    "field_headroom_check",
    "ravel_pytree",
    "FederatedSession",
    "LocalTrainer",
    "pod_fedavg_round",
    *_FAMILY_EXPORTS,
]


def __getattr__(name):
    if name in _FAMILY_EXPORTS:
        from . import families

        return getattr(families, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
