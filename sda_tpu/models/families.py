"""Model families sized to the benchmark workloads (BASELINE.json).

The reference's use case is aggregating *locally trained models*
(README.md:3-15) but it ships no model code — vectors arrive pre-flattened.
The TPU build completes the story with three flax families matching the
benchmark vector sizes, so the end-to-end demos and benches aggregate real
trainable parameter vectors rather than synthetic ints:

- ``LeNet``        — the classic 28x28 convnet, ~61k params (lenet-60k).
- ``MobileLite``   — depthwise-separable inverted-residual stack; at the
                     default width it lands ~3.5M params (mobilenet-3.5m).
- ``LoRAMLP``      — a frozen wide MLP with trainable rank-r adapters; the
                     *adapter* vector is what gets aggregated (lora-13m).

Every family is an ordinary flax module: ``init`` / ``apply`` compose with
jit, vmap, and the mesh shardings like any other JAX model.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

__all__ = ["LeNet", "MobileLite", "LoRAMLP", "lora_adapter_params",
           "merge_lora_params", "param_count"]


def param_count(params) -> int:
    """Total leaf elements; works on arrays and eval_shape structs alike."""
    total = 0
    for p in jax.tree_util.tree_leaves(params):
        shape = p.shape if hasattr(p, "shape") else np.shape(p)
        total += int(np.prod(shape, dtype=np.int64))
    return total


class LeNet(nn.Module):
    """LeNet-5-shaped convnet for 28x28x1 inputs (~61k params)."""

    num_classes: int = 10
    width: int = 1  # multiplier, lets tests shrink the family

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = nn.Conv(6 * w, (5, 5), padding="SAME")(x)
        x = nn.relu(nn.avg_pool(x, (2, 2), (2, 2)))
        x = nn.Conv(16 * w, (5, 5), padding="VALID")(x)
        x = nn.relu(nn.avg_pool(x, (2, 2), (2, 2)))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(120 * w)(x))
        x = nn.relu(nn.Dense(84 * w)(x))
        return nn.Dense(self.num_classes)(x)


class _InvertedResidual(nn.Module):
    """MobileNetV2-style expand -> depthwise -> project block."""

    channels: int
    expand: int = 4
    stride: int = 1

    @nn.compact
    def __call__(self, x):
        cin = x.shape[-1]
        h = nn.Conv(cin * self.expand, (1, 1), use_bias=False)(x)
        h = nn.relu6(nn.GroupNorm(num_groups=1)(h))
        h = nn.Conv(cin * self.expand, (3, 3), strides=(self.stride,) * 2,
                    feature_group_count=cin * self.expand, use_bias=False,
                    padding="SAME")(h)
        h = nn.relu6(nn.GroupNorm(num_groups=1)(h))
        h = nn.Conv(self.channels, (1, 1), use_bias=False)(h)
        h = nn.GroupNorm(num_groups=1)(h)
        if self.stride == 1 and cin == self.channels:
            h = h + x
        return h


class MobileLite(nn.Module):
    """Depthwise-separable convnet in the MobileNetV2 spirit.

    The default (width=40, blocks below) initializes to ~3.7M parameters for
    32x32x3 inputs — the mobilenet-3.5m benchmark vector. GroupNorm stands in
    for BatchNorm so a participant's update is a pure function of its local
    batch (no running statistics to aggregate out-of-band).
    """

    num_classes: int = 10
    width: int = 40
    block_channels: Sequence[int] = (16, 24, 40, 80, 112, 192, 320)

    @nn.compact
    def __call__(self, x):
        w = self.width
        x = nn.Conv(w, (3, 3), strides=(2, 2), use_bias=False, padding="SAME")(x)
        x = nn.relu6(nn.GroupNorm(num_groups=1)(x))
        for i, c in enumerate(self.block_channels):
            stride = 2 if i in (1, 2, 4) else 1
            x = _InvertedResidual(channels=c * w // 32, stride=stride)(x)
            x = _InvertedResidual(channels=c * w // 32)(x)
        x = nn.Conv(40 * w, (1, 1), use_bias=False)(x)
        x = nn.relu6(nn.GroupNorm(num_groups=1)(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes)(x)


class LoRAMLP(nn.Module):
    """Wide MLP whose Dense kernels carry rank-r LoRA adapters.

    Aggregation-relevant split: the *base* params are frozen and identical
    on every participant; only the adapter params (A, B per layer) are
    trained and securely aggregated. ``lora_adapter_params`` extracts that
    trainable sub-tree; at (features=4096, layers=4, rank=400) the adapter
    vector is 11,782,400 params (~11.8M; `fl/flagship.py` pins the exact
    count) — the lora-13m benchmark workload.
    """

    features: int = 4096
    layers: int = 4
    rank: int = 400
    num_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        for i in range(self.layers):
            dense = nn.Dense(self.features, name=f"base_{i}")
            a = self.param(f"lora_a_{i}", nn.initializers.normal(0.02),
                           (x.shape[-1], self.rank))
            b = self.param(f"lora_b_{i}", nn.initializers.zeros,
                           (self.rank, self.features))
            x = nn.relu(dense(x) + (x @ a) @ b)
        return nn.Dense(self.num_classes, name="head")(x)


def lora_adapter_params(params) -> dict:
    """The trainable (aggregated) sub-tree of a LoRAMLP param tree."""
    tree = params["params"] if "params" in params else params
    return {k: v for k, v in tree.items() if k.startswith("lora_")}


def merge_lora_params(params, adapters) -> dict:
    """Rebuild a full param tree from frozen base + aggregated adapters."""
    tree = dict(params["params"] if "params" in params else params)
    tree.update(adapters)
    return {"params": tree} if "params" in params else tree
