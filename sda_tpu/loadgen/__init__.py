"""Load & capacity plane: open/closed-loop workload generation against the
real HTTP stack, with per-route latency histograms and capacity reports.

The measurement counterpart to ``sda_tpu.chaos``: chaos proves the round
survives faults, loadgen proves (and quantifies) how it survives traffic —
sustained RPS, p50/p95/p99 tails per route, shed/retry behavior under the
server's admission control. Entry points: ``sda-sim --load`` (CLI) and
``run_load`` (tests, notebooks). ``docs/load.md`` has the tuning guide.
"""

from .connstorm import ConnstormProfile, run_connstorm
from .devscale import DevScaleProfile, run_devscale
from .driver import (
    LoadProfile,
    latency_report_ms,
    run_fleet_scaling,
    run_load,
)
from .pickup import PickupProfile, run_pickup_bench

# ``inputbench`` (the participation input-path micro-bench behind
# ``python -m sda_tpu.loadgen.inputbench``) is intentionally NOT imported
# eagerly: importing a ``-m`` target from its package __init__ trips
# runpy's double-import warning. ``from sda_tpu.loadgen.inputbench import
# run_input_bench`` for programmatic use.
__all__ = ["ConnstormProfile", "DevScaleProfile", "LoadProfile",
           "PickupProfile", "latency_report_ms", "run_connstorm",
           "run_devscale", "run_fleet_scaling", "run_load",
           "run_pickup_bench"]
