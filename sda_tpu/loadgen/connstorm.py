"""Connection-storm drill: N concurrent open connections on ONE worker.

The async plane's scaling claim is not requests/sec — it's *connections
held*: millions of sporadic phones mostly sit on idle keep-alive sockets
or parked long-polls, and the thread-per-connection plane pays an OS
thread for every one of them. This drill opens ``connections`` real TCP
connections against a single ``sdad`` worker process (spawned as a
subprocess so the driver's and the server's fd budgets don't share one
rlimit), sends one request per connection per wave while HOLDING every
socket open, and verifies:

- zero 5xx — admission may shed (429/503 + Retry-After), exhaustion may
  not error;
- the worker still answers promptly on a late wave with N-1 idle
  connections parked (the event loop does not degrade with idle fds);
- worker RSS stays under a fixed bound (``rss_limit_mb``) — per-
  connection state is buffers + a coroutine, not a thread stack;
- SIGTERM still drains clean (``leaked == 0``) with every connection
  open.

``sda-sim --connstorm N`` prints the BENCH-style record; ci.sh runs the
10k-connection smoke and gates the record advisory (docs/scaling.md).
"""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import List, Optional


@dataclass
class ConnstormProfile:
    connections: int = 10000
    #: request waves over the held connections (wave 1 proves admission
    #: under the connect flood, the last wave proves liveness with every
    #: other connection idle)
    waves: int = 2
    #: concurrent connect/request pipelining bound (driver side)
    concurrency: int = 512
    async_http: bool = True
    #: worker RSS ceiling (MiB) with every connection open. The worker's
    #: import baseline alone is ~350 MiB (jax/numpy); the drill also
    #: reports per-connection growth, which is the number that must stay
    #: O(10 KiB) for the plane's scaling story
    rss_limit_mb: float = 1024.0
    request_timeout_s: float = 60.0
    timeout_s: float = 600.0
    seed: int = 0


def _raise_nofile(need: int) -> int:
    """Best-effort: lift RLIMIT_NOFILE's soft limit toward the hard one;
    returns the resulting soft limit."""
    import resource

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    want = need + 256
    if soft < want:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE,
                               (min(want, hard), hard))
        except (ValueError, OSError):
            pass
        soft, _ = resource.getrlimit(resource.RLIMIT_NOFILE)
    return soft


def _rss_mb(pid: int) -> Optional[float]:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1024.0, 1)
    except (OSError, ValueError, IndexError):
        return None
    return None


class _Conn:
    __slots__ = ("reader", "writer")

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer


async def _request(conn: _Conn, host: str, timeout: float) -> int:
    """One keep-alive GET /v1/ping on an open connection; returns the
    status code (negative for transport failure)."""
    try:
        conn.writer.write(
            (f"GET /v1/ping HTTP/1.1\r\nHost: {host}\r\n"
             f"Connection: keep-alive\r\n\r\n").encode())
        await asyncio.wait_for(conn.writer.drain(), timeout)
        status_line = await asyncio.wait_for(conn.reader.readline(), timeout)
        parts = status_line.decode("latin-1", "replace").split(" ", 2)
        status = int(parts[1])
        content_length = 0
        while True:
            line = await asyncio.wait_for(conn.reader.readline(), timeout)
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode("latin-1", "replace").partition(":")
            if name.strip().lower() == "content-length":
                content_length = int(value.strip() or 0)
        if content_length:
            await asyncio.wait_for(
                conn.reader.readexactly(content_length), timeout)
        return status
    except (asyncio.TimeoutError, asyncio.IncompleteReadError,
            ConnectionError, ValueError, IndexError, OSError):
        return -1


async def _storm(profile: ConnstormProfile, host: str, port: int,
                 proc) -> dict:
    server_pid = proc.pid
    sem = asyncio.Semaphore(profile.concurrency)
    conns: List[Optional[_Conn]] = [None] * profile.connections
    connect_failures = 0

    async def _open(ix: int):
        nonlocal connect_failures
        async with sem:
            for attempt in range(3):
                try:
                    reader, writer = await asyncio.wait_for(
                        asyncio.open_connection(host, port),
                        profile.request_timeout_s)
                    conns[ix] = _Conn(reader, writer)
                    return
                except (OSError, asyncio.TimeoutError):
                    await asyncio.sleep(0.05 * (attempt + 1))
            connect_failures += 1

    t0 = time.perf_counter()
    await asyncio.gather(*(_open(i) for i in range(profile.connections)))
    connect_s = time.perf_counter() - t0
    open_conns = [c for c in conns if c is not None]

    waves = []
    statuses: dict = {}
    for wave in range(profile.waves):
        latencies: List[float] = []

        async def _wave_req(conn: _Conn):
            async with sem:
                w0 = time.perf_counter()
                status = await _request(conn, host,
                                        profile.request_timeout_s)
                latencies.append(time.perf_counter() - w0)
                statuses[status] = statuses.get(status, 0) + 1

        w_start = time.perf_counter()
        await asyncio.gather(*(_wave_req(c) for c in open_conns))
        wall = time.perf_counter() - w_start
        latencies.sort()
        waves.append({
            "requests": len(open_conns),
            "wall_s": round(wall, 3),
            "rps": round(len(open_conns) / wall, 1) if wall else 0.0,
            "p50_ms": round(latencies[len(latencies) // 2] * 1e3, 1)
            if latencies else None,
            "p99_ms": round(
                latencies[min(len(latencies) - 1,
                              int(len(latencies) * 0.99))] * 1e3, 1)
            if latencies else None,
            "rss_mb": _rss_mb(server_pid),
        })
        if wave + 1 < profile.waves:
            await asyncio.sleep(0.5)  # let the fleet of sockets idle

    rss_final = _rss_mb(server_pid)
    # SIGTERM lands NOW, with every socket still open: drain-with-held-
    # connections is the risky case this drill exists to gate — closing
    # first would hand the worker a trivially easier drain
    d0 = time.perf_counter()
    proc.send_signal(signal.SIGTERM)
    loop = asyncio.get_running_loop()
    drain_timed_out = False
    try:
        await asyncio.wait_for(loop.run_in_executor(None, proc.wait), 30)
    except asyncio.TimeoutError:
        drain_timed_out = True
    drain_wall_s = time.perf_counter() - d0
    for conn in open_conns:
        try:
            conn.writer.close()
        except Exception:
            pass
    return {
        "open_connections": len(open_conns),
        "connect_failures": connect_failures,
        "connect_s": round(connect_s, 2),
        "statuses": {str(k): v for k, v in sorted(statuses.items())},
        "waves": waves,
        "rss_mb": rss_final,
        "drained_with_open_connections": len(open_conns),
        "drain_wall_s": round(drain_wall_s, 2),
        "drain_timed_out": drain_timed_out,
    }


def run_connstorm(profile: Optional[ConnstormProfile] = None) -> dict:
    """Spawn one ``sdad`` worker (async plane by default), hold
    ``connections`` open sockets against it, ping in waves, check RSS,
    then SIGTERM-drain it. Returns the BENCH-style record."""
    profile = profile or ConnstormProfile()
    requested = profile.connections
    soft_limit = _raise_nofile(profile.connections)
    achievable = max(64, min(profile.connections, soft_limit - 256))
    clamped = achievable < profile.connections
    if clamped:
        profile = ConnstormProfile(**{**profile.__dict__,
                                      "connections": achievable})

    argv = [sys.executable, "-m", "sda_tpu.cli.serverd", "--memory"]
    if profile.async_http:
        argv.append("--async")
    argv += ["--statusz", "httpd", "--bind", "127.0.0.1:0"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True, env=env)
    try:
        line = proc.stdout.readline()
        if "listening on" not in line:
            raise RuntimeError(f"sdad failed to start: {line!r}")
        address = line.rsplit(" ", 1)[-1].strip()
        host, port = address.split("//", 1)[1].rsplit(":", 1)
        rss_baseline = _rss_mb(proc.pid)
        # _storm itself SIGTERMs and waits out the worker while every
        # socket is still open; this finally is only the crash backstop
        result = asyncio.run(_storm(profile, host, int(port), proc))
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
    drain = None
    for out_line in (proc.stdout.read() or "").splitlines():
        if out_line.startswith("sdad drained "):
            import json as _json

            drain = _json.loads(out_line[len("sdad drained "):])
    errors_5xx = sum(v for k, v in result["statuses"].items()
                     if k.isdigit() and int(k) >= 500 and int(k) != 503)
    shed = sum(v for k, v in result["statuses"].items() if k in ("429",
                                                                 "503"))
    transport_failures = result["statuses"].get("-1", 0)
    rss = result["rss_mb"]
    record = {
        "metric": (f"concurrent open connections on one "
                   f"{'async' if profile.async_http else 'threaded'}-plane "
                   f"worker ({profile.waves} ping waves, held sockets)"),
        "value": result["open_connections"],
        "unit": "connections",
        "platform": "cpu",
        "host_cores": os.cpu_count(),
        "seed": profile.seed,
        "http_plane": "async" if profile.async_http else "threaded",
        "requested_connections": requested,
        "fd_soft_limit": soft_limit,
        "clamped_by_fd_limit": clamped,
        "connect_failures": result["connect_failures"],
        "transport_failures": transport_failures,
        "connect_s": result["connect_s"],
        "waves": result["waves"],
        "statuses": result["statuses"],
        "errors_5xx": errors_5xx,
        "shed": shed,
        "rss_mb": rss,
        "rss_baseline_mb": rss_baseline,
        "rss_growth_mb": (round(rss - rss_baseline, 1)
                          if rss is not None and rss_baseline is not None
                          else None),
        "per_connection_kb": (round(
            (rss - rss_baseline) * 1024.0 / result["open_connections"], 1)
            if rss is not None and rss_baseline is not None
            and result["open_connections"] else None),
        "rss_limit_mb": profile.rss_limit_mb,
        "rss_bounded": (rss <= profile.rss_limit_mb
                        if rss is not None else None),
        "drain": drain,
        "leaked": (drain or {}).get("leaked"),
        "drained_with_open_connections":
            result["drained_with_open_connections"],
        "drain_wall_s": result["drain_wall_s"],
        # the drill verdict ci.sh asserts: every connection served every
        # wave with zero exhaustion errors, memory bounded, and the
        # worker drained clean WHILE every socket was still open
        "ok": bool(
            result["open_connections"] >= min(profile.connections,
                                              achievable)
            and errors_5xx == 0
            and transport_failures == 0
            and result["connect_failures"] == 0
            and (rss is None or rss <= profile.rss_limit_mb)
            and (drain or {}).get("leaked") == 0
            and not result["drain_timed_out"]
        ),
    }
    return record
