"""The model-scale device-plane bench: ``sda-sim --devscale``.

ROADMAP item "device plane at model scale" made benchable: the full
mask -> share -> combine -> reconstruct round at FL-model dimension
(dim >= 1e8), sharded over the ``('p', 'd')`` mesh, streamed through
HBM at the watermark-derived tile width, Pallas-fused when active, with
the clerk-pipeline-fed device-tile sink exercised in the same run. One
BENCH-style record:

- headline ``value`` = ``participants * dim / round_seconds_marginal``
  (elements/sec through the complete round, marginal over the warm
  rounds — round 1 pays the compiles);
- ``exact`` — bit-exactness vs the host oracle lane (full column sums
  at drill dims, seeded sampled windows at model scale where the host
  cannot afford the full object-dtype reference);
- ``retraces == 0`` across rounds and one compiled shape per stage
  (uniform tails — the devprof tripwire, recorded not just asserted);
- ``roofline_utilization`` and the ``hbm`` watermark advisory
  (``hbm_peak_bytes / watermark``) — the two advisory metrics the
  regression gate reports (obs/regress.py);
- comparability tags ``dim / p_shards / d_shards / pallas`` so this
  record NEVER gates against single-chip or different-topology history.

On CPU the record is honest about provenance: ``host_scaled`` marks the
numbers as CPU-CI stand-ins (same schedule, same verdicts — the chip
fields populate when hardware is present).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

__all__ = ["DevScaleProfile", "run_devscale"]


@dataclass
class DevScaleProfile:
    """Knobs for the model-scale round bench (``sda-sim --devscale``)."""

    dim: int = 100_000_000            # target dimension (>= 1e8 = ROADMAP rung)
    family: Optional[str] = None      # mobilelite | lora | devscale (sets dim)
    participants: int = 8
    participants_chunk: int = 8
    p_shards: Optional[int] = None    # default: gcd(devices, committee)
    d_shards: Optional[int] = None
    clerks: int = 8
    modulus_bits: int = 28            # Solinas prime -> uint32 fast path
    mask: str = "full"                # none | full | chacha
    dim_tile: Optional[int] = None    # None -> watermark rule
    pallas: bool = False
    pallas_interpret: bool = False    # CPU drills: interpret-mode kernel
    rounds: int = 3                   # 1 warm + (rounds-1) timed
    seed: int = 0
    scan_lane: Optional[bool] = None  # ModelScaleRound A/B (auto: small dims)
    clerk_fed: bool = True            # DeviceTileSink-fed round
    oracle_windows: int = 4
    oracle_window_cols: int = 4096

    def validate(self) -> None:
        if self.dim <= 0 and not self.family:
            raise ValueError("dim must be positive (or set family)")
        if self.participants <= 0:
            raise ValueError("participants must be positive")
        if self.rounds < 2:
            raise ValueError("rounds must be >= 2 (round 1 is the warmup)")
        if self.mask not in ("none", "full", "chacha"):
            raise ValueError(f"unknown mask {self.mask!r}")


def _oracle_check(out, host_provider, participants, dim, modulus, profile):
    """Bit-exactness vs the host oracle lane: full column sums when the
    host can afford them, seeded sampled windows at model scale."""
    full = dim <= (1 << 17)
    windows = []
    if full:
        windows.append((0, dim))
    else:
        w = min(int(profile.oracle_window_cols), dim)
        rng = np.random.default_rng(profile.seed ^ 0x0AC1E)
        offsets = {0, dim - w}
        for _ in range(max(0, int(profile.oracle_windows) - 2)):
            offsets.add(int(rng.integers(0, max(1, dim - w))))
        windows = sorted((o, o + w) for o in offsets)
    checked = 0
    for d0, d1 in windows:
        block = np.asarray(
            host_provider(0, participants, d0, d1)).astype(np.int64)
        expected = block.sum(axis=0) % modulus
        if not np.array_equal(np.asarray(out[d0:d1]), expected):
            return False, {"mode": "full" if full else "sampled",
                           "windows": len(windows), "cols": checked,
                           "failed_window": [d0, d1]}
        checked += d1 - d0
    return True, {"mode": "full" if full else "sampled",
                  "windows": len(windows), "cols": checked}


def run_devscale(profile: DevScaleProfile) -> dict:
    """Run the model-scale round bench and return the BENCH record."""
    profile.validate()
    import jax

    from .. import obs
    from ..fields import numtheory
    from ..mesh import (
        DeviceTileSink,
        ModelScaleRound,
        StreamedPod,
        default_mesh_shape,
        make_mesh,
        watermark_dim_tile,
    )
    from ..mesh.streaming import (
        synthetic_block_provider32,
        synthetic_device_block_provider32,
    )
    from ..obs import devprof
    from ..protocol import (
        ChaChaMasking,
        FullMasking,
        NoMasking,
        PackedShamirSharing,
    )
    from ..utils import metrics

    dim = int(profile.dim)
    family = profile.family
    if family:
        from ..fl.flagship import flagship_dim

        dim = flagship_dim(family)

    k = 3
    t, p, w2, w3 = numtheory.generate_packed_params(
        k, profile.clerks, profile.modulus_bits)
    scheme = PackedShamirSharing(k, profile.clerks, t, p, w2, w3)
    masking = {
        "none": NoMasking(),
        "full": FullMasking(p),
        "chacha": ChaChaMasking(p, dim, 128),
    }[profile.mask]

    n_devices = len(jax.devices())
    p_shards = profile.p_shards or default_mesh_shape(
        n_devices, scheme.output_size)[0]
    d_shards = profile.d_shards or (n_devices // p_shards)
    mesh = make_mesh(p_shards, d_shards)
    platform = jax.devices()[0].platform
    cpu = platform == "cpu"

    obs.reset_all()
    devprof.install_monitoring()
    devprof.enable_cost_analysis()

    watermark = devprof.hbm_watermark()
    dim_tile = profile.dim_tile or watermark_dim_tile(
        scheme, masking, participants_chunk=profile.participants_chunk,
        p_shards=p_shards, d_shards=d_shards, pallas=profile.pallas,
        watermark_bytes=watermark, dim=dim)

    pallas_kwargs = {}
    if profile.pallas:
        pallas_kwargs = dict(use_pallas=True,
                             pallas_interpret=profile.pallas_interpret)
        if profile.pallas_interpret:
            # interpret mode cannot run the TPU PRNG primitive: inject
            # the external-randomness stream (pallas_round.py contract)
            import jax.numpy as jnp

            def external_bits(key, P, draws, B):
                return jax.random.bits(key, (P, 2 * draws, B),
                                       dtype=jnp.uint32)

            pallas_kwargs["pallas_external_bits_fn"] = external_bits

    pod = StreamedPod(
        scheme, masking, mesh=mesh,
        participants_chunk=profile.participants_chunk,
        dim_chunk=dim_tile, uniform_tail=True, **pallas_kwargs)
    dev_provider = synthetic_device_block_provider32(p, seed=profile.seed)
    host_provider = synthetic_block_provider32(p, seed=profile.seed)
    key = jax.random.PRNGKey(profile.seed)
    P_total = profile.participants

    wall0 = time.perf_counter()
    out = pod.aggregate_blocks(dev_provider, P_total, dim, key)
    warm_s = time.perf_counter() - wall0
    out_warm = np.asarray(out)  # round-key reveal, reused by the sink A/B

    def _stage_compiles():
        return {name: (devprof.profile(name).compiles,
                       len(devprof.profile(name).shapes))
                for name in ("stream.pod.step", "stream.pod.finale")}

    compiles_after_warm = _stage_compiles()
    t0 = time.perf_counter()
    for r in range(1, profile.rounds):
        out = pod.aggregate_blocks(dev_provider, P_total, dim,
                                   jax.random.fold_in(key, r))
    timed_s = time.perf_counter() - t0
    per_round = timed_s / max(1, profile.rounds - 1)
    compiles_after = _stage_compiles()
    retraces = metrics.counter_report("xla.compile.retrace").get(
        "xla.compile.retrace", 0)
    warm_reused = compiles_after == compiles_after_warm

    exact, oracle = _oracle_check(
        out, host_provider, P_total, dim, p, profile)

    # -- clerk-pipeline-fed device tiles: the decode stage (standing in
    # for the decrypt pipeline's product) runs on the crypto pool, lands
    # on the mesh double-buffered, and the SAME round key must reveal
    # the SAME bytes as the device-generated lane
    clerk_fed = None
    if profile.clerk_fed:
        from jax.sharding import NamedSharding, PartitionSpec as P

        sink = DeviceTileSink(
            host_provider, P_total, dim, pod.participants_chunk,
            pod.dim_chunk, grain=pod._grain, uniform_tail=True,
            sharding=NamedSharding(pod.mesh, P("p", "d")))
        s0 = time.perf_counter()
        out_sink = pod.aggregate_blocks(sink.provider(), P_total, dim, key)
        sink_s = time.perf_counter() - s0
        # same round key as the warm round -> identical randomness ->
        # the sink-fed reveal must reproduce the device-generated bytes
        clerk_fed = {
            "exact": bool(np.array_equal(np.asarray(out_sink), out_warm)),
            "round_seconds": round(sink_s, 4),
            "sink_hits": metrics.counter_report("devscale.sink.").get(
                "devscale.sink.hit", 0),
            "sink_misses": metrics.counter_report("devscale.sink.").get(
                "devscale.sink.miss", 0),
        }

    # -- the single-program scan lane (pjit x scan_dim_tiles x pallas):
    # A/B'd when the sharded input is small enough to materialize
    scan_lane = profile.scan_lane
    if scan_lane is None:
        scan_lane = dim * P_total <= (1 << 24)
    scan = None
    if scan_lane:
        inputs = np.asarray(host_provider(0, P_total, 0, dim))
        msr = ModelScaleRound(scheme, masking, mesh=mesh,
                              dim_tile=dim_tile, **pallas_kwargs)
        s0 = time.perf_counter()
        out_scan = np.asarray(msr.aggregate(inputs, key))
        scan_s = time.perf_counter() - s0
        expected = inputs.astype(np.int64).sum(axis=0) % p
        scan = {
            "exact": bool(np.array_equal(out_scan, expected)),
            "round_seconds": round(scan_s, 4),
            "dim_tile": msr.dim_tile,
        }

    wall = time.perf_counter() - wall0
    roofline = devprof.roofline(seconds=wall, platform=platform)
    hbm = devprof.watermark_report(platform=platform)
    value = P_total * dim / per_round if per_round > 0 else 0

    tiles = -(-dim // pod.dim_chunk)
    record = {
        "metric": ("model-scale device round elements/sec "
                   "(packed-Shamir n=%d, %s mask, sharded+streamed)"
                   % (profile.clerks, profile.mask)),
        "value": round(value),
        "unit": "elements/sec",
        "platform": platform,
        "pallas": bool(pod.pallas_active),
        "dim": dim,
        "participants": P_total,
        "p_shards": p_shards,
        "d_shards": d_shards,
        "dim_tile": pod.dim_chunk,
        "tiles": tiles,
        "participants_chunk": pod.participants_chunk,
        "tile_rule": ("explicit" if profile.dim_tile
                      else "hbm_watermark"),
        "rounds": profile.rounds,
        "round_seconds_marginal": round(per_round, 4),
        "compile_seconds": round(max(0.0, warm_s - per_round), 2),
        "exact": bool(exact),
        "oracle": oracle,
        "retraces": int(retraces),
        "warm_program_reused": bool(warm_reused),
        "compiled_shapes": {name: shapes for name, (comp, shapes)
                            in compiles_after.items()},
        "roofline": roofline,
        "roofline_utilization": roofline.get("utilization"),
        "hbm": hbm,
        "hbm_watermark_ratio": hbm.get("hbm_watermark_ratio"),
        "host_scaled": cpu,
        "seed": profile.seed,
        "xla": devprof.compile_totals(),
    }
    if family:
        record["family"] = family
    if clerk_fed is not None:
        record["clerk_fed"] = clerk_fed
    if scan is not None:
        record["scan_lane"] = scan
    if cpu:
        record["note"] = ("CPU CI stand-in: same schedule/verdicts as the "
                          "chip run; real-TPU fields populate when "
                          "hardware is present")
    record["ok"] = bool(
        exact and retraces == 0 and warm_reused
        and (clerk_fed is None or clerk_fed["exact"])
        and (scan is None or scan["exact"])
        and hbm.get("within_watermark", True))
    return record
