"""Flight-recorder overhead benchmark: spans/sec, recorder off vs on.

The recorder's contract (docs/observability.md) is that turning it on is
operationally free — no protocol bytes change and the per-span cost is
one dict build + one buffered line write. This bench holds that promise
the same way every other lever in the BENCH lineage is held: measure the
span hot path with the sink detached, measure it again spooling into a
real segment directory (rotation and eviction armed at realistic caps),
and emit a BENCH-shaped record whose headline ``value`` is
recorder-**on** spans/sec (higher is better) with ``overhead_pct`` riding
as an advisory detail. ci.sh runs it fixed-cap and gates the record
advisory through ``obs/regress.py``.

CLI: ``python -m sda_tpu.loadgen.recorderbench [--spans N]``.
"""

from __future__ import annotations

import argparse
import json
import shutil
import sys
import tempfile
import time

from .. import obs
from ..obs import recorder as recorder_mod

#: Attribute payload shaped like a real server span's (route + ids), so
#: the serialization cost measured is the cost production spans pay.
_ATTRS = {
    "http.method": "POST",
    "http.route": "POST:/v1/aggregations/{id}/participations",
    "request_id": "bench-0000",
    "node_id": "bench-w0",
}


def _spin_spans(n: int) -> float:
    """``n`` parent+child span pairs through the tracer; returns spans/sec
    (2n spans). Events ride on every child like chaos marks would."""
    t0 = time.perf_counter()
    for i in range(n):
        with obs.span("bench.request", attributes=_ATTRS):
            with obs.span("bench.store", attributes={"op": "put", "i": i}):
                obs.add_event("bench.mark", step=i)
    elapsed = time.perf_counter() - t0
    return (2 * n) / elapsed if elapsed > 0 else 0.0


def run_bench(spans: int = 20000, warmup: int = 2000) -> dict:
    """Measure off/on rates in THIS process (the recorder must not be
    already installed) and return the BENCH record dict."""
    if recorder_mod.installed() is not None:
        raise RuntimeError("flight recorder already installed; the off "
                           "rung would not be off")
    pairs = max(1, spans // 2)
    _spin_spans(max(1, warmup // 2))  # warm allocator + ring buffer
    obs.reset_spans()

    off_rate = _spin_spans(pairs)
    obs.reset_spans()

    spool = tempfile.mkdtemp(prefix="sda-recorder-bench-")
    try:
        rec = recorder_mod.install(spool, node_id="bench",
                                   segment_bytes=1 << 20,
                                   max_bytes=8 << 20,
                                   snapshot_s=0.0)
        on_rate = _spin_spans(pairs)
        report = rec.report()
    finally:
        recorder_mod.uninstall()
        shutil.rmtree(spool, ignore_errors=True)
        obs.reset_spans()

    overhead_pct = (
        (off_rate / on_rate - 1.0) * 100.0 if on_rate > 0 else float("inf")
    )
    return {
        "metric": "recorder-on span throughput (2-deep spans with events, "
                  "1MiB segments)",
        "value": round(on_rate, 1),
        "unit": "spans/sec",
        "platform": "cpu",
        "direction": "higher",
        "spans": 2 * pairs,
        "spans_per_sec_off": round(off_rate, 1),
        "spans_per_sec_on": round(on_rate, 1),
        "overhead_pct": round(overhead_pct, 2),
        "segments_written": report["segments_written"],
        "records": report["records"],
        "dropped": report["dropped"],
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sda_tpu.loadgen.recorderbench",
        description="flight-recorder span-throughput overhead bench")
    parser.add_argument("--spans", type=int, default=20000,
                        help="spans per rung (default 20000)")
    parser.add_argument("--max-overhead-pct", type=float, default=None,
                        help="exit 1 when overhead exceeds this (a local "
                             "absolute gate on top of the regress lineage)")
    args = parser.parse_args(argv)
    record = run_bench(spans=args.spans)
    print(json.dumps(record))
    if (args.max_overhead_pct is not None
            and record["overhead_pct"] > args.max_overhead_pct):
        print(f"recorder overhead {record['overhead_pct']}% exceeds "
              f"--max-overhead-pct {args.max_overhead_pct}%",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
