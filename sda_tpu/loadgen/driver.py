"""The load drill: N simulated participants hammer the real HTTP stack.

Where ``chaos/drill.py`` proves the system survives *faults*, this driver
proves it survives *traffic* — and measures the shape of that survival.
It stands up a real ``SdaHttpServer`` on a store backend, runs the full
secure-aggregation round (committee election, participations, clerking,
reveal), and drives the participant phase with one of two classic
workload models:

- **open-loop** (default): participant arrivals are a seeded Poisson
  process at ``target_rps`` — arrivals don't wait for completions, so a
  saturated server sees a growing backlog instead of the flattering
  self-throttling a closed loop gives (the open- vs closed-loop pitfall
  from the Tail-at-Scale literature). Scheduling lag is recorded in the
  ``load.lag`` histogram so coordinated omission is visible.
- **closed-loop**: exactly ``concurrency`` workers issue
  request-after-request — the saturation probe.

Every HTTP request lands in the server's per-route
``http.latency.<route>`` histograms; the driver's own phases ride
``load.phase.register`` / ``load.phase.participate``. The returned
capacity report (BENCH-style JSON via ``sda-sim --load``) carries
sustained RPS, p50/p95/p99 per route, shed/retry/error rates, and the
end-to-end verdict: the revealed sum must still be bit-exact, and every
*admitted* participation must be present — load shedding may slow the
round, never corrupt it.

Tracing: the whole run is one ``round`` trace (``sda_tpu.obs``); each
simulated participant is a ``load.participant`` span parented to it, so
the report can name the slowest participants and the exact span chain
(retry attempts, server handling, store ops) that made them slow — the
``trace_exemplars`` table. Export the full timeline with
``sda-sim --load --trace-out trace.json``.

Overload is a profile, not an accident: arm the server's admission layer
(``rate_limit`` / ``max_inflight``) and the swarm gets 429+``Retry-After``
sheds that the retrying transport converges through — zero 5xx, zero lost
participations. ``chaos_rate`` arms the fault registry on top for the
combined load+chaos drill.
"""

from __future__ import annotations

import concurrent.futures
import random
import tempfile
import threading
import time
from dataclasses import dataclass, replace
from typing import List, Optional

from .. import chaos, obs
from ..client.journal import ParticipationJournal
from ..utils import metrics


@dataclass
class LoadProfile:
    """Everything one load run needs; defaults match the acceptance drill
    (200 participants, open-loop, memory store, admission off)."""

    participants: int = 200
    dim: int = 8
    arrivals: str = "open"              # "open" (Poisson) | "closed"
    target_rps: float = 100.0            # open-loop participant arrival rate
    concurrency: int = 32                # worker pool (closed-loop: exact)
    seed: int = 0
    store: str = "memory"                # memory | sqlite | jsonfs
    store_path: Optional[str] = None
    # admission knobs, armed AFTER round setup (None = off)
    max_inflight: Optional[int] = None
    rate_limit: Optional[float] = None   # per-agent tokens/sec
    rate_burst: float = 4.0
    # per-tenant fairness budget (http/admission.py): the swarm stamps
    # the aggregation's recipient as X-SDA-Tenant, so a hot tenant sheds
    # against its own budget before touching the shared caps
    tenant_rate: Optional[float] = None
    tenant_burst: float = 32.0
    # combined load+chaos drill: fraction of requests to 500 (0 = off)
    chaos_rate: float = 0.0
    # device churn under load (chaos.churn_schedule): this seeded fraction
    # of participants crashes mid-participation — sealed bundle journaled,
    # upload possibly already durable with the ack lost — and rejoins as a
    # fresh client resuming from the journal; the report's ``churn`` block
    # carries the resume/replay counters (docs/load.md)
    churn: float = 0.0
    lease_seconds: float = 2.0
    timeout_s: float = 300.0
    # wire codec for every client in the swarm: "auto" (upgrade on the
    # server's advert), "json" (legacy wire pinned), "bin" (forced binary)
    codec: str = "auto"
    # fleet mode: 0 = the classic in-process server; N >= 1 spawns N real
    # `sdad` OS processes over ONE shared store (sqlite/jsonfs only —
    # memory cannot be shared across processes) and drives all of them,
    # routing participants over the consistent-hash ring and the control
    # plane (snapshot/status/clerk polls) to the aggregation's affinity
    # node (docs/scaling.md)
    fleet: int = 0
    # fleet health plane (server/health.py): when set, every worker
    # heartbeats this often and runs the failure detector (dead after 4
    # intervals) — the report's fleet_health table shows the live verdict
    heartbeat_s: Optional[float] = None
    # serve on the asyncio event-loop plane (http/aserver.py) instead of
    # thread-per-connection; fleet mode passes `sdad --async`. The wire
    # contract is identical — ci.sh pins fixed-seed A/B bit-exactness
    async_http: bool = False


def _percentiles_ms(summary: dict) -> dict:
    """One histogram summary, seconds -> milliseconds, rounded for JSON."""
    return {
        "count": int(summary["count"]),
        "p50_ms": round(summary["p50"] * 1e3, 3),
        "p95_ms": round(summary["p95"] * 1e3, 3),
        "p99_ms": round(summary["p99"] * 1e3, 3),
        "max_ms": round(summary["max"] * 1e3, 3),
        "mean_ms": round(summary["sum"] / summary["count"] * 1e3, 3)
        if summary["count"] else 0.0,
    }


def latency_report_ms(prefix: str = "http.latency.") -> dict:
    """Per-route latency table (ms) from the live histogram registry —
    shared by the load and chaos drill reports."""
    return {
        name[len(prefix):]: _percentiles_ms(summary)
        for name, summary in metrics.histogram_report(prefix).items()
    }


def run_load(profile: LoadProfile) -> dict:
    """Run one full aggregation round under generated load; return the
    capacity report. Requires libsodium (real participant crypto)."""
    import numpy as np

    from ..client import RecipientOutput, SdaClient, output_digest
    from ..crypto import MemoryKeystore, sodium
    from ..http import SdaHttpClient, SdaHttpServer
    from ..protocol import (
        Aggregation,
        AggregationId,
        FullMasking,
        SodiumEncryption,
    )
    from ..server import new_jsonfs_server, new_memory_server, new_sqlite_server

    if not sodium.available():
        raise RuntimeError("the load drill needs libsodium (real crypto round)")
    if profile.arrivals not in ("open", "closed"):
        raise ValueError(f"unknown arrivals model {profile.arrivals!r}")

    # the golden 8-clerk packed-Shamir committee (ONE definition shared
    # with the chaos and tree drills): crypto real, parameters small —
    # the object under test is the transport/store plane, not the field
    # arithmetic
    from ..chaos.drill import golden_packed_scheme

    scheme = golden_packed_scheme()

    obs.reset_all()
    chaos.reset()

    fleet = None
    ring = None

    def _scrape_statusz(address: str) -> dict:
        import requests

        return requests.get(address + "/statusz", timeout=10.0).json()

    def _fleet_scrapes() -> dict:
        """One ``/statusz`` document per worker — the fleet's served
        requests, lease/snapshot counters, and fired failpoints live in
        the worker processes, not in this one."""
        return {
            node: _scrape_statusz(addr)
            for node, addr in fleet.addresses.items()
        }

    def _fleet_request_totals(scrapes: dict) -> dict:
        """Per-node served-request totals from each worker's /statusz
        (the fleet analog of the in-process ``status_counts`` sum)."""
        return {
            node: sum(doc["requests"].values())
            for node, doc in scrapes.items()
        }

    if profile.fleet:
        from ..server.fleet import Fleet

        if profile.store not in ("sqlite", "jsonfs"):
            raise ValueError(
                "fleet mode needs a cross-process store "
                "(store='sqlite' or 'jsonfs'), not "
                f"{profile.store!r}")
        if not profile.store_path:
            raise ValueError("fleet mode needs store_path (the shared "
                             "database file / directory)")
        backend = (["--sqlite", profile.store_path]
                   if profile.store == "sqlite"
                   else ["--jfs", profile.store_path])
        # workers are configured up front (flags, not runtime retuning):
        # lease arbitration + /statusz for per-node tallies always on;
        # admission and chaos only when the profile asks. The in-process
        # path arms admission/chaos AFTER setup — fleet setup traffic is
        # tiny, so whole-run arming keeps the workers stateless.
        extra = ["--job-lease", str(profile.lease_seconds), "--statusz"]
        if profile.async_http:
            extra += ["--async"]
        if profile.heartbeat_s is not None:
            # the gray-failure plane: heartbeats + the failure detector
            # riding each worker's sweeper (suspect at 2 intervals, dead
            # at 4 — the conventional heartbeat multiples)
            extra += ["--heartbeat", str(profile.heartbeat_s),
                      "--dead-after", str(4 * profile.heartbeat_s),
                      "--round-sweep", str(profile.heartbeat_s)]
        if profile.rate_limit is not None:
            extra += ["--rate-limit", str(profile.rate_limit),
                      "--rate-burst", str(profile.rate_burst)]
        if profile.tenant_rate is not None:
            extra += ["--tenant-rate", str(profile.tenant_rate),
                      "--tenant-burst", str(profile.tenant_burst)]
        if profile.max_inflight is not None:
            extra += ["--max-inflight", str(profile.max_inflight)]
        if profile.chaos_rate > 0.0:
            extra += ["--chaos-spec",
                      f"http.server.request=error,rate={profile.chaos_rate}",
                      "--chaos-seed", str(profile.seed)]
        fleet = Fleet(profile.fleet, backend, extra_args=extra,
                      node_prefix="fleet-w")
        fleet.start()
        ring = fleet.ring()
        http_server = None
    else:
        if profile.store == "memory":
            service_impl = new_memory_server()
        elif profile.store == "sqlite":
            service_impl = new_sqlite_server(profile.store_path or ":memory:")
        elif profile.store == "jsonfs":
            if profile.store_path is None:
                raise ValueError("store='jsonfs' needs store_path")
            service_impl = new_jsonfs_server(profile.store_path)
        else:
            raise ValueError(f"unknown store {profile.store!r}")
        service_impl.server.clerking_lease_seconds = profile.lease_seconds

        from ..http import server_class

        http_server = server_class(profile.async_http)(
            service_impl, bind="127.0.0.1:0")
        http_server.start_background()
    # churned devices journal to a real directory — resume reads it as a
    # fresh process would (exactly-once participation, docs/robustness.md)
    journal_dir = tempfile.TemporaryDirectory(prefix="sda-load-journal-")
    failures: List[str] = []
    failures_lock = threading.Lock()
    try:
        with obs.span("round", attributes={"profile": "load",
                                           "participants": profile.participants,
                                           "arrivals": profile.arrivals,
                                           "seed": profile.seed}) as round_span:
            # worker threads have no thread-local context: pass the round
            # context explicitly so every participant span joins the trace
            round_ctx = round_span.context

            def _new_proxy(address: str) -> SdaHttpClient:
                return SdaHttpClient(
                    address,
                    token="load-drill-token",
                    # generous retry budget: under the overload profile
                    # EVERY participant is expected to be shed at least
                    # once and must converge through Retry-After hints
                    # within the deadline
                    max_retries=16, backoff_base=0.01, backoff_cap=0.25,
                    deadline=profile.timeout_s,
                    codec=profile.codec,
                )

            if fleet is not None:
                # one transport per worker; the ring maps any stable key
                # (agent id, aggregation id) to its affinity node — purely
                # advisory, every worker serves every route correctly
                node_proxies = {node: _new_proxy(addr)
                                for node, addr in fleet.addresses.items()}

                def _proxy_for(key) -> SdaHttpClient:
                    return node_proxies[ring.node_for(str(key))]
            else:
                single_proxy = _new_proxy(http_server.address)

                def _proxy_for(key) -> SdaHttpClient:
                    return single_proxy

            def new_client():
                keystore = MemoryKeystore()
                agent = SdaClient.new_agent(keystore)
                # agents ride their own affinity node: participants spread
                # over the whole fleet, each clerk's job polling
                # concentrates where its leases live (docs/scaling.md)
                return SdaClient(agent, keystore, _proxy_for(agent.id))

            # -- setup (unthrottled: admission armed after) ---------------
            recipient = new_client()
            recipient.upload_agent()
            recipient_key = recipient.new_encryption_key()
            recipient.upload_encryption_key(recipient_key)

            candidates = {recipient.agent.id: recipient}
            for _ in range(scheme.share_count):
                clerk = new_client()
                clerk.upload_agent()
                clerk.upload_encryption_key(clerk.new_encryption_key())
                candidates[clerk.agent.id] = clerk

            agg = Aggregation(
                id=AggregationId.random(),
                title="load-drill",
                vector_dimension=profile.dim,
                modulus=scheme.prime_modulus,
                recipient=recipient.agent.id,
                recipient_key=recipient_key,
                masking_scheme=FullMasking(scheme.prime_modulus),
                committee_sharing_scheme=scheme,
                recipient_encryption_scheme=SodiumEncryption(),
                committee_encryption_scheme=SodiumEncryption(),
            )
            if fleet is not None:
                # the round's control plane (snapshot POST, status polls,
                # reveal) rides the aggregation's affinity node from here
                recipient.service = _proxy_for(agg.id)
            # the whole swarm belongs to ONE tenant — the aggregation's
            # recipient; stamping it arms the per-tenant budget bucket
            # when tenant_rate is set (and is harmless otherwise)
            if fleet is not None:
                for proxy in node_proxies.values():
                    proxy.tenant = str(recipient.agent.id)
            else:
                single_proxy.tenant = str(recipient.agent.id)
            recipient.upload_aggregation(agg)
            recipient.begin_aggregation(agg.id)
            committee = recipient.service.get_committee(recipient.agent, agg.id)
            clerks = [candidates[cid] for cid, _ in committee.clerks_and_keys]

            # -- arm admission + chaos, then open the floodgates ----------
            # (fleet workers were armed at spawn via CLI flags — admission
            # and failpoints live in THEIR processes, not this one)
            if fleet is None:
                http_server.configure_admission(
                    max_inflight=profile.max_inflight,
                    rate_limit=profile.rate_limit,
                    rate_burst=profile.rate_burst,
                    tenant_rate=profile.tenant_rate,
                    tenant_burst=profile.tenant_burst,
                )
                if profile.chaos_rate > 0.0:
                    chaos.configure("http.server.request", error=True,
                                    rate=profile.chaos_rate, seed=profile.seed)

            rng = np.random.default_rng(profile.seed)
            inputs = rng.integers(0, scheme.prime_modulus,
                                  size=(profile.participants, profile.dim),
                                  dtype=np.int64)

            churn_plan = (chaos.churn_schedule(profile.participants,
                                               profile.churn,
                                               seed=profile.seed)
                          if profile.churn else None)
            journal = (ParticipationJournal(journal_dir.name)
                       if profile.churn else None)
            churn_stats = {"churned": 0, "resumed": 0}
            churn_lock = threading.Lock()

            def churned_participate(participant, index: int) -> None:
                """The sporadic device under load: seal + journal, crash
                at the seeded point (pre-upload, or mid-upload with the
                ack lost), then rejoin as a fresh client resuming the
                journaled bytes — exactly-once ingestion makes the replay
                idempotent, so the round's sum is unchanged."""
                from ..client import SdaClient
                from ..crypto import MemoryKeystore

                plan = churn_plan[index]
                participation = participant.new_participation(
                    [int(x) for x in inputs[index]], agg.id)
                journal.record(participation)
                if plan["phase"] == "mid-upload":
                    participant.upload_participation(participation)
                # the rejoin: resume needs only the agent identity and
                # the journal — the sealed bytes never get recomputed
                rejoined = SdaClient(participant.agent, MemoryKeystore(),
                                     _proxy_for(participant.agent.id))
                resumed = rejoined.resume(journal)
                with churn_lock:
                    churn_stats["churned"] += 1
                    churn_stats["resumed"] += resumed

            def participant_task(index: int, scheduled: float, t_open: float):
                start = time.perf_counter()
                if profile.arrivals == "open":
                    metrics.observe("load.lag",
                                    max(0.0, (start - t_open) - scheduled))
                with obs.span("load.participant", parent=round_ctx,
                              attributes={"index": index}) as pspan:
                    try:
                        t0 = time.perf_counter()
                        participant = new_client()
                        participant.upload_agent()
                        metrics.observe("load.phase.register",
                                        time.perf_counter() - t0)
                        t1 = time.perf_counter()
                        if churn_plan and churn_plan[index]["departs"]:
                            churned_participate(participant, index)
                        else:
                            participant.participate(
                                [int(x) for x in inputs[index]], agg.id
                            )
                        metrics.observe("load.phase.participate",
                                        time.perf_counter() - t1)
                        return True
                    except Exception as e:
                        # tallied, not fatal: the report decides. Mark the
                        # span by hand — the swallowed exception never
                        # escapes the span context, and failed participants
                        # are exactly the exemplars the trace report must
                        # flag
                        pspan.status = "error"
                        pspan.set_attribute(
                            "error", f"{type(e).__name__}: {e}")
                        with failures_lock:
                            failures.append(f"participant {index}: "
                                            f"{type(e).__name__}: {e}")
                        return False

            arrival_rng = random.Random(profile.seed)
            # ONE scrape round per measurement boundary: the per-status
            # merge and the per-node totals read the same documents
            if fleet is not None:
                scrapes = _fleet_scrapes()
                per_node_setup = _fleet_request_totals(scrapes)
                setup_requests = sum(per_node_setup.values())
            else:
                setup_requests = sum(http_server.status_counts.values())
                per_node_setup = None
            t_load0 = time.perf_counter()
            with concurrent.futures.ThreadPoolExecutor(
                max_workers=max(1, profile.concurrency)
            ) as pool:
                futures = []
                if profile.arrivals == "open":
                    # seeded Poisson arrivals: submit at the scheduled
                    # instant whether or not earlier work finished (open
                    # loop); the bounded pool then queues — the backlog
                    # shows up in load.lag, not in a silently stretched
                    # schedule
                    t_arrival = 0.0
                    for i in range(profile.participants):
                        t_arrival += arrival_rng.expovariate(profile.target_rps)
                        delay = t_arrival - (time.perf_counter() - t_load0)
                        if delay > 0:
                            time.sleep(delay)
                        futures.append(
                            pool.submit(participant_task, i, t_arrival, t_load0)
                        )
                else:
                    for i in range(profile.participants):
                        futures.append(
                            pool.submit(participant_task, i, 0.0, t_load0))
                completed = sum(bool(f.result()) for f in futures)
            load_elapsed = time.perf_counter() - t_load0
            # the headline RPS covers ONLY the participant window: snapshot
            # before the close phase adds clerk polling traffic
            per_node_load = None
            if fleet is not None:
                scrapes = _fleet_scrapes()
                # each worker served exactly ONE /statusz inside the
                # window — the setup-boundary scrape (a scrape's own
                # request is only counted after its document is built,
                # so the end scrape isn't in its own doc). Subtract it:
                # the tallies must be about real load traffic
                per_node_load = {
                    node: max(0, total - per_node_setup.get(node, 0) - 1)
                    for node, total in _fleet_request_totals(scrapes).items()
                }
                load_requests = sum(per_node_load.values())
            else:
                load_requests = (sum(http_server.status_counts.values())
                                 - setup_requests)

            # -- close the round: snapshot, clerking, reveal --------------
            recipient.end_aggregation(agg.id)
            deadline = time.monotonic() + profile.timeout_s
            ready = False
            status = None
            while time.monotonic() < deadline:
                for clerk in clerks:
                    clerk.run_chores(-1)
                status = recipient.service.get_aggregation_status(
                    recipient.agent, agg.id
                )
                if (
                    status is not None
                    and status.snapshots
                    and status.snapshots[0].number_of_clerking_results
                    >= scheme.share_count
                ):
                    ready = True
                    break
                time.sleep(0.05)

            exact = False
            expected_digest = None
            admitted_participations = None
            if status is not None:
                admitted_participations = status.number_of_participations
            # zero lost participations among admitted requests: every
            # participant whose upload was ACKed must be in the round, and
            # with all of them in, the revealed sum must be bit-exact (a
            # failed participant MAY still have landed server-side — lost
            # final ack — so exactness is only decidable at zero failures)
            if ready and completed == profile.participants:
                output = recipient.reveal_aggregation(agg.id)
                expected = inputs.sum(axis=0) % scheme.prime_modulus
                exact = bool((output.positive().values == expected).all())
                # the oracle's digest, computed the same canonical way the
                # reveal span stamps output.sha256: a forensics pass over
                # the spools alone can then assert the recorded reveal was
                # bit-exact (ci.sh forensics drill)
                expected_digest = output_digest(
                    RecipientOutput(scheme.prime_modulus, expected))
    finally:
        failpoint_report = chaos.report()
        chaos.reset()
        total_elapsed = time.perf_counter() - t_load0 \
            if "t_load0" in locals() else 0.0
        if fleet is not None:
            # last scrape BEFORE the drain: the workers' served-request,
            # lease, snapshot, and failpoint state dies with them
            try:
                final_scrapes = _fleet_scrapes()
            except Exception:
                final_scrapes = {}
            status_counts = {}
            for doc in final_scrapes.values():
                for code, count in doc["requests"].items():
                    code = int(code)
                    status_counts[code] = status_counts.get(code, 0) + count
            worker_failpoints = {
                node: doc.get("failpoints") or {}
                for node, doc in final_scrapes.items()
                if doc.get("failpoints")
            }
            if worker_failpoints:
                failpoint_report = worker_failpoints
            drain_summaries = fleet.stop()
        else:
            status_counts = http_server.status_counts
            http_server.shutdown()
        journal_dir.cleanup()

    counters = metrics.counter_report()
    codec_counters = metrics.counter_report("http.codec.") or None
    if fleet is not None:
        # the codec counters are stamped server-side, i.e. in the worker
        # processes: merge their final scrapes so the negotiated-wire
        # field below names what the fleet actually spoke
        from ..server.fleet import merge_statusz_block

        codec_counters = merge_statusz_block(
            final_scrapes.values(), "codec_counters") or None
    # exactly-once ingestion tallies are stamped server-side: in-process
    # runs read the live counters, fleet runs merge the workers' /statusz
    # participation blocks (the counters live in THEIR processes)
    if fleet is not None:
        participation_counters = merge_statusz_block(
            final_scrapes.values(), "participation")
    else:
        participation_counters = metrics.counter_report(
            "server.participation.") or {}
    lag_summary = metrics.histogram_report("load.lag").get("load.lag")
    clerk_job_summary = metrics.histogram_report("clerk.job.").get(
        "clerk.job.seconds")
    # enqueue->lease latency (server.job.pickup): stamped in the server
    # process — live metrics in-process, per-node statusz blocks in fleet
    # mode (the long-poll plane's headline; docs/load.md)
    if fleet is not None:
        pickup_ms = {
            node: (doc.get("lease") or {}).get("pickup_ms")
            for node, doc in final_scrapes.items()
            if (doc.get("lease") or {}).get("pickup_ms")
        } or None
    else:
        pickup_summary = metrics.histogram_report("server.job.pickup").get(
            "server.job.pickup")
        pickup_ms = (_percentiles_ms(pickup_summary)
                     if pickup_summary else None)
    requests_total = sum(status_counts.values())
    shed = sum(v for k, v in status_counts.items() if k == 429)
    errors_5xx = sum(v for k, v in status_counts.items() if k >= 500)
    # round lifecycle verdicts (server/lifecycle.py): a healthy load run
    # must never degrade or fail a round — ci.sh asserts both stay 0.
    # In-process runs read the transition counters; fleet runs read ONE
    # worker's /statusz rounds table (the store is shared, every worker
    # sees the same rounds — summing scrapes would double-count)
    if fleet is not None:
        _any_scrape = next(iter(final_scrapes.values()), {})
        _rounds_by_state = (_any_scrape.get("rounds") or {}).get(
            "by_state") or {}
        rounds_degraded = _rounds_by_state.get("degraded", 0)
        rounds_failed = (_rounds_by_state.get("failed", 0)
                         + _rounds_by_state.get("expired", 0))
    else:
        rounds_degraded = counters.get("server.round.state.degraded", 0)
        rounds_failed = (counters.get("server.round.state.failed", 0)
                         + counters.get("server.round.state.expired", 0))
    report = {
        "mode": (f"loadgen {profile.arrivals}-loop "
                 f"({profile.store} store"
                 + (f", fleet x{profile.fleet}" if profile.fleet else "")
                 + (", overload profile" if profile.rate_limit is not None
                    or profile.max_inflight is not None else "")
                 + (f", chaos rate {profile.chaos_rate}"
                    if profile.chaos_rate else "")
                 + ")"),
        "participants": profile.participants,
        "dim": profile.dim,
        "clerks": scheme.share_count,
        # which serving transport handled the run (docs/scaling.md)
        "http_plane": "async" if profile.async_http else "threaded",
        # the wire the swarm actually spoke (an "auto" run that upgraded
        # records "bin"): the regression gate keys comparability on this,
        # so it must name the negotiated outcome, not the requested mode
        "codec": ("bin" if (codec_counters or {}).get("http.codec.bin.in")
                  or (codec_counters or {}).get("http.codec.bin.out")
                  else "json"),
        "codec_mode": profile.codec,
        "codec_counters": codec_counters,
        "arrivals": profile.arrivals,
        "target_rps": profile.target_rps if profile.arrivals == "open" else None,
        "concurrency": profile.concurrency,
        "seed": profile.seed,
        "admission": {
            "max_inflight": profile.max_inflight,
            "rate_limit": profile.rate_limit,
            "rate_burst": profile.rate_burst,
            "tenant_rate": profile.tenant_rate,
            "tenant_burst": (profile.tenant_burst
                             if profile.tenant_rate is not None else None),
        },
        "completed": completed,
        "client_failures": len(failures),
        "failure_samples": failures[:5] or None,
        "admitted_participations": admitted_participations,
        "ready": ready,
        "exact": exact,
        # join keys for post-mortem forensics: sda-trace explain takes the
        # aggregation id, and the oracle digest must match the reveal
        # span's spooled output.sha256 attribute
        "aggregation": str(agg.id),
        "output_sha256": expected_digest,
        "load_seconds": round(load_elapsed, 4),
        "round_seconds": round(total_elapsed, 4),
        "sustained_rps": round(load_requests / load_elapsed, 1)
        if load_elapsed else 0.0,
        "load_requests": load_requests,
        "requests": requests_total,
        "shed_429": shed,
        "errors_5xx": errors_5xx,
        "rounds_degraded": rounds_degraded,
        "rounds_failed": rounds_failed,
        "status_counts": {str(k): v for k, v in sorted(status_counts.items())},
        "throttled": metrics.counter_report("http.throttled.") or None,
        "retries": metrics.counter_report("http.retry.") or None,
        "inflight_peak": metrics.gauge_report("http.inflight.peak").get(
            "http.inflight.peak"
        ),
        "latency_ms": latency_report_ms(),
        "phases_ms": {
            name[len("load.phase."):]: _percentiles_ms(summary)
            for name, summary in
            metrics.histogram_report("load.phase.").items()
        },
        # clerk-job wall time (decrypt pipeline + combine + re-encrypt +
        # result upload): the host-hot-path headline the batched clerk
        # pipeline moves
        "clerk_job_ms": (_percentiles_ms(clerk_job_summary)
                         if clerk_job_summary else None),
        # enqueue->lease latency: the polling-vs-long-poll BENCH headline
        "job_pickup_ms": pickup_ms,
        "lag_ms": _percentiles_ms(lag_summary) if lag_summary else None,
        # device-churn block (LoadProfile.churn): how many participants
        # crashed + rejoined, and the server's exactly-once verdict on
        # their replays — created vs replayed vs rejected equivocations
        "churn": ({
            "rate": profile.churn,
            "participants_churned": churn_stats["churned"],
            "participants_resumed": churn_stats["resumed"],
            "participations_replayed": participation_counters.get(
                "server.participation.replayed", 0),
            "equivocations": participation_counters.get(
                "server.participation.equivocation", 0),
        } if profile.churn else None),
        # the three slowest participants with the span chain that made them
        # slow (retry attempts, server handling, store ops) — tail
        # ATTRIBUTION, where the latency histograms only show tail SIZE
        "trace_exemplars": obs.slowest_spans("load.participant", n=3) or None,
        "failpoints": failpoint_report or None,
        "counters": {
            k: v for k, v in counters.items()
            if k.startswith(("chaos.", "server.job.", "server.snapshot.",
                             "server.participation."))
        } or None,
    }
    if fleet is not None:
        report["fleet_nodes"] = profile.fleet
        report["fleet"] = {
            # per-worker view, scraped from each /statusz just before the
            # drain: served requests (whole run + load window), job-lease
            # and snapshot-contention counters, admission peaks
            "nodes": {
                node: {
                    "address": fleet.addresses.get(node),
                    "requests": sum(
                        (final_scrapes.get(node, {}).get("requests") or {})
                        .values()),
                    "load_requests": (per_node_load or {}).get(node),
                    "load_rps": round(
                        (per_node_load or {}).get(node, 0) / load_elapsed, 1)
                    if load_elapsed else 0.0,
                    "inflight_peak": final_scrapes.get(node, {})
                    .get("inflight_peak"),
                    "jobs": (final_scrapes.get(node, {}).get("lease") or {})
                    .get("counters"),
                    "snapshot": final_scrapes.get(node, {}).get("snapshot"),
                }
                for node in fleet.node_ids
            },
            "drain": drain_summaries,
            "leaked": sum(int(s.get("leaked", 0) or 0)
                          for s in drain_summaries),
            "released_leases": sum(int(s.get("released_leases", 0) or 0)
                                   for s in drain_summaries),
            # the fleet's own health verdict at the end of the run (any
            # scrape shows the whole shared-store table): a healthy drill
            # must end with every worker alive
            "health": next(
                (doc.get("fleet_health") for doc in final_scrapes.values()
                 if doc.get("fleet_health")), None),
        }
    return report


def run_fleet_scaling(profile: LoadProfile, nodes: int,
                      baseline_nodes: int = 1) -> dict:
    """The scaling drill: the SAME fixed-seed load twice — once against
    ``baseline_nodes`` worker process(es), once against ``nodes`` — each
    over a FRESH copy of the shared store, reported as one BENCH-style
    record the regression gate understands (``sda-bench --check``:
    ``fleet_nodes`` joins the comparability key, ``scaling_efficiency``
    rides as an advisory metric).

    ``scaling_efficiency`` is measured speedup over ideal speedup:
    ``(rps_N / rps_baseline) / (N / baseline)`` — 1.0 is perfectly linear.
    The record carries ``host_cores`` because the ceiling is physical:
    N Python worker processes cannot scale past the cores that exist
    (docs/scaling.md discusses reading the number honestly).
    """
    import os
    import tempfile

    if profile.store not in ("sqlite", "jsonfs"):
        raise ValueError("the scaling drill needs a cross-process store "
                         "(store='sqlite' or 'jsonfs')")
    if nodes < 1 or baseline_nodes < 1 or nodes < baseline_nodes:
        raise ValueError("need nodes >= baseline_nodes >= 1")

    reports = {}
    for n in dict.fromkeys((baseline_nodes, nodes)):
        with tempfile.TemporaryDirectory() as tmp:
            reports[n] = run_load(replace(
                profile, fleet=n, store_path=os.path.join(tmp, "store")))
    base, top = reports[baseline_nodes], reports[nodes]
    speedup = (top["sustained_rps"] / base["sustained_rps"]
               if base["sustained_rps"] else 0.0)
    ideal = nodes / baseline_nodes
    record = {
        "metric": (f"fleet sustained RPS ({profile.arrivals}-loop, "
                   f"{profile.participants} participants, dim "
                   f"{profile.dim}, {profile.store} store)"),
        "value": top["sustained_rps"],
        "unit": "requests/sec",
        "platform": "cpu",  # the serving plane is a host-tier workload
        "host_cores": os.cpu_count(),
        "http_plane": top["http_plane"],
        "codec": top["codec"],
        "seed": profile.seed,
        "chaos_rate": profile.chaos_rate,
        "fleet_nodes": nodes,
        "baseline_nodes": baseline_nodes,
        "baseline_rps": base["sustained_rps"],
        "speedup": round(speedup, 3),
        "ideal_speedup": round(ideal, 3),
        "scaling_efficiency": round(speedup / ideal, 3) if ideal else 0.0,
        "per_node_load_rps": {
            node: stats["load_rps"]
            for node, stats in top["fleet"]["nodes"].items()
        },
        # the verdict is conjunctive: BOTH rungs must close the round
        # bit-exactly with zero leaked requests and zero lost admitted
        # participations — scaling that corrupts is not scaling
        "exact": bool(base["exact"] and top["exact"]),
        "ready": bool(base["ready"] and top["ready"]),
        "client_failures": base["client_failures"] + top["client_failures"],
        "leaked": base["fleet"]["leaked"] + top["fleet"]["leaked"],
        # forensics join keys of the TOP rung (the fleet round the drill
        # is named for): sda-trace explain takes the aggregation id, and
        # the oracle digest is asserted against the spooled reveal span
        "aggregation": top.get("aggregation"),
        "output_sha256": top.get("output_sha256"),
        "admitted_participations": top.get("admitted_participations"),
        "rungs": {
            str(n): {
                key: rep.get(key)
                for key in ("sustained_rps", "load_seconds", "round_seconds",
                            "load_requests", "requests", "completed",
                            "shed_429", "errors_5xx", "exact", "ready",
                            "aggregation")
            }
            for n, rep in reports.items()
        },
    }
    return record
