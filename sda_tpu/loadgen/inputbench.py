"""Participation-input micro-bench: ndarray pass-through vs the
per-element Python conversion the FL session used to do.

``FederatedSession.round`` historically converted every encoded delta to
a Python list — ``[int(v) for v in encoded]`` — before handing it to
``participate``, an O(dim) interpreter loop per participant per round
that also forced ``np.asarray(list)`` to re-materialize the array from
boxed ints. The client normalizes integer ndarrays in one vectorized
pass, so the loop bought nothing. This bench pins the delta at model
scale (default dim 10^5, the lora-13m neighborhood per shard):

    python -m sda_tpu.loadgen.inputbench --dim 100000

Two measurements, best-of-``repeats`` each:

- ``seal``: full ``new_participation`` (mask + share + seal — the real
  participant hot path) fed by list vs ndarray;
- ``convert``: the input-normalization step alone (the pure overhead the
  list path adds).

Requires libsodium (the seal rung runs real sealed-box crypto).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import Optional

import numpy as np

__all__ = ["run_input_bench", "main"]

M31 = (1 << 31) - 1


def _best_of(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def run_input_bench(dim: int = 100_000, repeats: int = 5,
                    seed: int = 0) -> dict:
    """Run both rungs at ``dim``; returns the JSON-able report."""
    from ..client import SdaClient
    from ..crypto import MemoryKeystore, sodium
    from ..models import FixedPointCodec
    from ..protocol import (
        AdditiveSharing,
        Aggregation,
        AggregationId,
        NoMasking,
        SodiumEncryption,
    )
    from ..server import new_memory_server

    if not sodium.available():
        raise RuntimeError("the input bench needs libsodium "
                           "(real participant seal path)")

    service = new_memory_server()

    def new_client():
        keystore = MemoryKeystore()
        client = SdaClient(SdaClient.new_agent(keystore), keystore, service)
        client.upload_agent()
        return client

    recipient = new_client()
    recipient_key = recipient.new_encryption_key()
    recipient.upload_encryption_key(recipient_key)
    clerks = [new_client() for _ in range(3)]
    for clerk in clerks:
        clerk.upload_encryption_key(clerk.new_encryption_key())
    aggregation = Aggregation(
        id=AggregationId.random(), title="input-bench",
        vector_dimension=dim, modulus=M31,
        recipient=recipient.agent.id, recipient_key=recipient_key,
        masking_scheme=NoMasking(),
        committee_sharing_scheme=AdditiveSharing(share_count=3, modulus=M31),
        recipient_encryption_scheme=SodiumEncryption(),
        committee_encryption_scheme=SodiumEncryption(),
    )
    recipient.upload_aggregation(aggregation)
    recipient.begin_aggregation(aggregation.id)

    participant = new_client()
    codec = FixedPointCodec(M31, fractional_bits=16, max_summands=64,
                            clip=4.0)
    rng = np.random.default_rng(seed)
    encoded = codec.encode(rng.normal(0, 1, size=dim))

    # the conversion step alone (what the list path adds per participant)
    convert_list_s = _best_of(
        lambda: np.asarray([int(v) for v in encoded], dtype=np.int64),
        repeats)
    convert_array_s = _best_of(
        lambda: np.asarray(encoded, dtype=np.int64), repeats)

    # the real participant hot path, fed both ways
    seal_list_s = _best_of(
        lambda: participant.new_participation(
            [int(v) for v in encoded], aggregation.id), repeats)
    seal_array_s = _best_of(
        lambda: participant.new_participation(encoded, aggregation.id),
        repeats)

    return {
        "metric": f"participation input normalization (dim {dim})",
        "value": round(convert_list_s / max(convert_array_s, 1e-9), 1),
        "unit": "x speedup (list -> ndarray)",
        "platform": "cpu",
        "seed": seed,
        "dim": dim,
        "repeats": repeats,
        "convert_list_ms": round(convert_list_s * 1e3, 3),
        "convert_array_ms": round(convert_array_s * 1e3, 3),
        "seal_list_ms": round(seal_list_s * 1e3, 3),
        "seal_array_ms": round(seal_array_s * 1e3, 3),
        "seal_saved_ms": round((seal_list_s - seal_array_s) * 1e3, 3),
    }


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sda_tpu.loadgen.inputbench",
        description="participation input-path micro-bench")
    parser.add_argument("--dim", type=int, default=100_000)
    parser.add_argument("--repeats", type=int, default=5)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    print(json.dumps(run_input_bench(args.dim, args.repeats, args.seed)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
