"""Job-pickup A/B bench: long-poll clerking vs the polling baseline.

The long-poll plane's whole point is one number: how long a freshly
fanned-out clerking job waits before a clerk picks it up. Under polling
that latency IS the polling interval (a clerk that just found the queue
empty sleeps through the enqueue); under long-poll it collapses to the
in-process wakeup hop. This bench measures exactly that, as the
server-stamped ``server.job.pickup`` histogram (enqueue -> lease), on
the SAME fixed-seed round driven twice:

- **polling**: every committee clerk runs ``SdaClient.run_clerk`` with
  ``wait_s=0`` — the classic jittered sleep loop at ``poll_interval``;
- **longpoll**: the same clerks run with ``wait_s>0`` — each empty poll
  parks on ``GET /v1/clerking-jobs?wait=S`` until snapshot fan-out wakes
  it.

Both modes serve from the same HTTP plane (``async_http`` selects) so
the delta isolates the *delivery mechanism*, not the transport. The
returned BENCH record's headline is the long-poll p99 (direction:
lower), with the polling baseline and the speedup alongside — ci.sh
gates the ≥10x win (docs/load.md, docs/http.md).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Optional

from .. import chaos, obs
from ..utils import metrics


@dataclass
class PickupProfile:
    participants: int = 6
    dim: int = 4
    #: snapshots per mode: each fans out one job per committee clerk, so
    #: samples = snapshots * 8 (golden committee)
    snapshots: int = 6
    #: the polling baseline's sleep between empty polls — the latency a
    #: polling clerk pays on pickup (0.5 s is a conservative device
    #: cadence; production phones poll far slower)
    poll_interval: float = 0.5
    #: long-poll park budget per request
    wait_s: float = 10.0
    seed: int = 0
    async_http: bool = True
    timeout_s: float = 120.0


def _run_mode(profile: PickupProfile, wait_s: float) -> dict:
    """One fixed-seed multi-snapshot round; returns the pickup summary
    (+ bit-exactness verdict of the final reveal)."""
    import numpy as np

    from ..chaos.drill import golden_packed_scheme
    from ..client import SdaClient
    from ..crypto import MemoryKeystore
    from ..http import SdaHttpClient, server_class
    from ..protocol import (
        Aggregation,
        AggregationId,
        FullMasking,
        SodiumEncryption,
    )
    from ..server import new_memory_server

    scheme = golden_packed_scheme()
    obs.reset_all()
    chaos.reset()
    service = new_memory_server()
    service.server.clerking_lease_seconds = 30.0
    http_server = server_class(profile.async_http)(service,
                                                   bind="127.0.0.1:0")
    http_server.start_background()
    stop = threading.Event()
    threads = []
    try:
        def new_client():
            keystore = MemoryKeystore()
            agent = SdaClient.new_agent(keystore)
            return SdaClient(agent, keystore,
                             SdaHttpClient(http_server.address, token="t"))

        recipient = new_client()
        recipient.upload_agent()
        recipient_key = recipient.new_encryption_key()
        recipient.upload_encryption_key(recipient_key)
        candidates = {recipient.agent.id: recipient}
        for _ in range(scheme.share_count):
            clerk = new_client()
            clerk.upload_agent()
            clerk.upload_encryption_key(clerk.new_encryption_key())
            candidates[clerk.agent.id] = clerk
        agg = Aggregation(
            id=AggregationId.random(), title="pickup-bench",
            vector_dimension=profile.dim, modulus=scheme.prime_modulus,
            recipient=recipient.agent.id, recipient_key=recipient_key,
            masking_scheme=FullMasking(scheme.prime_modulus),
            committee_sharing_scheme=scheme,
            recipient_encryption_scheme=SodiumEncryption(),
            committee_encryption_scheme=SodiumEncryption(),
        )
        recipient.upload_aggregation(agg)
        recipient.begin_aggregation(agg.id)
        committee = recipient.service.get_committee(recipient.agent, agg.id)
        clerks = [candidates[cid] for cid, _ in committee.clerks_and_keys]

        rng = np.random.default_rng(profile.seed)
        inputs = rng.integers(0, scheme.prime_modulus,
                              size=(profile.participants, profile.dim),
                              dtype=np.int64)
        for row in inputs:
            participant = new_client()
            participant.upload_agent()
            participant.participate([int(x) for x in row], agg.id)

        # the committee goes live BEFORE any snapshot exists: polling
        # clerks settle into their sleep cadence, long-poll clerks park —
        # so every fan-out below lands on a steady-state committee
        for clerk in clerks:
            t = threading.Thread(
                target=clerk.run_clerk,
                kwargs=dict(wait_s=wait_s,
                            poll_interval=profile.poll_interval,
                            stop=stop, deadline=profile.timeout_s),
                daemon=True)
            t.start()
            threads.append(t)
        time.sleep(min(1.0, profile.poll_interval))

        stagger = random.Random(profile.seed)
        deadline = time.monotonic() + profile.timeout_s
        done_snapshots = 0
        snapshot_ids = []
        for _ in range(profile.snapshots):
            # decorrelate fan-out from the polling phase: without the
            # seeded stagger, snapshot N+1's timing would be locked to
            # the committee's wake-up from snapshot N
            time.sleep(stagger.uniform(0.1, 1.0) * profile.poll_interval)
            snapshot_ids.append(recipient.snapshot_aggregation(agg.id))
            while time.monotonic() < deadline:
                status = recipient.service.get_aggregation_status(
                    recipient.agent, agg.id)
                counts = {s.id: s.number_of_clerking_results
                          for s in status.snapshots}
                if counts.get(snapshot_ids[-1], 0) >= scheme.share_count:
                    done_snapshots += 1
                    break
                time.sleep(0.02)
        output = recipient.reveal_aggregation(agg.id, snapshot_ids[0])
        expected = inputs.sum(axis=0) % scheme.prime_modulus
        exact = bool((output.positive().values == expected).all())
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        http_server.shutdown()
    summary = metrics.histogram_report("server.job.pickup").get(
        "server.job.pickup")
    longpoll_counters = metrics.counter_report("http.longpoll.") or None
    return {
        "pickup": summary,
        "exact": exact,
        "snapshots_done": done_snapshots,
        "longpoll_counters": longpoll_counters,
    }


def run_pickup_bench(profile: Optional[PickupProfile] = None) -> dict:
    """The A/B: same fixed-seed round, polling then long-poll; returns
    the BENCH record (headline: long-poll pickup p99, direction lower)."""
    profile = profile or PickupProfile()
    from ..crypto import sodium

    if not sodium.available():
        raise RuntimeError("the pickup bench needs libsodium "
                           "(real crypto round)")
    polling = _run_mode(profile, wait_s=0.0)
    longpoll = _run_mode(profile, wait_s=profile.wait_s)

    def _ms(summary, key):
        return round(summary[key] * 1e3, 3) if summary else None

    poll_p99 = _ms(polling["pickup"], "p99")
    lp_p99 = _ms(longpoll["pickup"], "p99")
    return {
        "metric": (f"clerk job-pickup p99 under long-poll "
                   f"(8-clerk committee, {profile.snapshots} snapshots, "
                   f"vs {profile.poll_interval}s polling)"),
        "value": lp_p99,
        "unit": "ms",
        "direction": "lower",
        "platform": "cpu",
        "seed": profile.seed,
        "http_plane": "async" if profile.async_http else "threaded",
        "poll_interval_s": profile.poll_interval,
        "wait_s": profile.wait_s,
        "exact": bool(polling["exact"] and longpoll["exact"]),
        "snapshots": profile.snapshots,
        "samples": int((longpoll["pickup"] or {}).get("count", 0)),
        "longpoll": {
            "p50_ms": _ms(longpoll["pickup"], "p50"),
            "p99_ms": lp_p99,
            "max_ms": _ms(longpoll["pickup"], "max"),
        },
        "polling": {
            "p50_ms": _ms(polling["pickup"], "p50"),
            "p99_ms": poll_p99,
            "max_ms": _ms(polling["pickup"], "max"),
        },
        # the headline ratio ci.sh gates: >= 10x is the acceptance bar
        "speedup_p99": (round(poll_p99 / lp_p99, 2)
                        if poll_p99 and lp_p99 else None),
    }
