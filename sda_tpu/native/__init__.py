"""Native host kernels: build-on-demand C++ shared library via ctypes.

``available()`` gates all use — every caller has a pure-Python/numpy
fallback, so a missing compiler degrades performance, never correctness.
The library is compiled once into the package directory and reused.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import List, Optional, Sequence

import numpy as np

_HERE = Path(__file__).parent
_SRC = _HERE / "src" / "sda_native.cpp"
_LIB_PATH = _HERE / "libsda_native.so"
_ABI_VERSION = 5

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _compile() -> bool:
    # portable codegen only: a -march=native .so cached in the package
    # directory SIGILLs (uncatchable) if the directory later moves to a
    # CPU without those ISA extensions, and it measured no speedup for
    # the __int128 Montgomery ladder anyway
    cmd = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17",
           str(_SRC), "-o", str(_LIB_PATH), "-ldl"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        try:
            stale = not _LIB_PATH.exists() or (
                _SRC.exists() and _LIB_PATH.stat().st_mtime < _SRC.stat().st_mtime
            )
        except OSError:
            stale = not _LIB_PATH.exists()
        if stale:
            if not _SRC.exists() or not _compile():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
            if lib.sda_native_abi_version() != _ABI_VERSION:
                _build_failed = True
                return None
        except OSError:
            _build_failed = True
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        lib.sda_modmatmul_i64.argtypes = [i64p, i64p, i64p] + [ctypes.c_int64] * 4
        lib.sda_modsum_axis0.argtypes = [i64p, i64p] + [ctypes.c_int64] * 3
        lib.sda_chacha_expand_mask.argtypes = [u32p] + [ctypes.c_int64] * 3 + [i64p]
        lib.sda_chacha_expand_mask_r03.argtypes = (
            [u32p] + [ctypes.c_int64] * 3 + [i64p]
        )
        lib.sda_chacha_combine_masks.argtypes = (
            [i64p] + [ctypes.c_int64] * 4 + [i64p, i64p]
        )
        lib.sda_chacha_combine_masks_r03.argtypes = (
            [i64p] + [ctypes.c_int64] * 4 + [i64p, i64p]
        )
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.sda_powmod.argtypes = [
            u64p, u64p, ctypes.c_int64, u64p, ctypes.c_int64, u64p, u64p,
        ]
        lib.sda_powmod_batch.argtypes = [
            u64p, ctypes.c_int64, u64p, ctypes.c_int64, u64p, ctypes.c_int64,
            u64p, u64p,
        ]
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.sda_embed_participate.argtypes = [
            i64p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            u8p, u8p, u8p, ctypes.c_int64, i64p,
        ]
        lib.sda_embed_participate_shamir.argtypes = [
            i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            i64p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            u8p, u8p, u8p, ctypes.c_int64, i64p,
        ]
        _lib = lib
        return lib


def available() -> bool:
    return _load() is not None


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def modmatmul(a: np.ndarray, b: np.ndarray, p: int) -> np.ndarray:
    """Exact (a @ b) mod p in C++ (128-bit accumulation); p < 2^62."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    a = np.ascontiguousarray(a, dtype=np.int64)
    b = np.ascontiguousarray(b, dtype=np.int64)
    m, k = a.shape
    k2, n = b.shape
    if k != k2:
        raise ValueError("shape mismatch")
    out = np.empty((m, n), dtype=np.int64)
    rc = lib.sda_modmatmul_i64(_i64(a), _i64(b), _i64(out), m, k, n, p)
    if rc:
        raise ValueError("sda_modmatmul_i64 failed")
    return out


def modsum_axis0(x: np.ndarray, m: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    x = np.ascontiguousarray(x, dtype=np.int64)
    rows, n = x.shape
    out = np.empty(n, dtype=np.int64)
    rc = lib.sda_modsum_axis0(_i64(x), _i64(out), rows, n, m)
    if rc:
        raise ValueError("sda_modsum_axis0 failed")
    return out


#: wire PRG tag -> native expand/combine symbol pair, keyed on the spec
#: home's constants so a tag rename cannot drift past this map. ``prg`` is
#: REQUIRED at this layer: a defaulted stream choice here could silently
#: expand the wrong stream for a wire seed — the exact hazard the tag
#: exists to prevent.
from ..fields.chacha import CHACHA_PRG_RAND03, CHACHA_PRG_V1  # noqa: E402

_CHACHA_FNS = {
    CHACHA_PRG_V1: ("sda_chacha_expand_mask", "sda_chacha_combine_masks"),
    CHACHA_PRG_RAND03: ("sda_chacha_expand_mask_r03",
                        "sda_chacha_combine_masks_r03"),
}


def chacha_expand_mask(
    seed: Sequence[int], dim: int, modulus: int, *, prg: str
) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if not 0 < modulus < (1 << 62):  # same validation as the Python spec
        raise ValueError("modulus out of range")
    if prg not in _CHACHA_FNS:
        raise ValueError(f"unknown ChaCha PRG {prg!r}")
    seed_arr = np.asarray(list(seed), dtype=np.uint32)
    out = np.empty(dim, dtype=np.int64)
    rc = getattr(lib, _CHACHA_FNS[prg][0])(
        seed_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        seed_arr.size, dim, modulus, _i64(out),
    )
    if rc:
        raise ValueError(f"{_CHACHA_FNS[prg][0]} failed")
    return out


def chacha_combine_masks(
    seeds: np.ndarray, dim: int, modulus: int, *, prg: str
) -> np.ndarray:
    """Sum of expanded masks for [n_seeds, seed_words] i64 seeds — the
    recipient hot loop in one native call."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if not 0 < modulus < (1 << 62):  # same validation as the Python spec
        raise ValueError("modulus out of range")
    if prg not in _CHACHA_FNS:
        raise ValueError(f"unknown ChaCha PRG {prg!r}")
    seeds = np.ascontiguousarray(seeds, dtype=np.int64)
    n_seeds, seed_words = seeds.shape
    scratch = np.empty(dim, dtype=np.int64)
    out = np.empty(dim, dtype=np.int64)
    rc = getattr(lib, _CHACHA_FNS[prg][1])(
        _i64(seeds), n_seeds, seed_words, dim, modulus, _i64(scratch), _i64(out)
    )
    if rc:
        raise ValueError(f"{_CHACHA_FNS[prg][1]} failed")
    return out


def _u64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def _limbs(x: int, count: int) -> np.ndarray:
    return np.frombuffer(x.to_bytes(count * 8, "little"), dtype=np.uint64)


def powmod(base: int, exp: int, mod: int) -> int:
    """``pow(base, exp, mod)`` on the Montgomery C++ ladder — the Paillier
    hot op (~3.5-5x CPython's 30-bit-digit pow at 2048-bit keys). Requires
    an odd modulus; callers fall back to ``pow`` otherwise."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if mod <= 0 or not (mod & 1):
        raise ValueError("modulus must be positive and odd")
    if exp < 0:
        raise ValueError("negative exponents unsupported")
    nl = (mod.bit_length() + 63) // 64
    el = max(1, (exp.bit_length() + 63) // 64)
    scratch = np.zeros(22 * nl + 3, dtype=np.uint64)
    out = np.zeros(nl, dtype=np.uint64)
    rc = lib.sda_powmod(
        _u64(_limbs(base % mod, nl)), _u64(_limbs(exp, el)), el,
        _u64(_limbs(mod, nl)), nl, _u64(scratch), _u64(out),
    )
    if rc:
        raise ValueError("sda_powmod failed")
    return int.from_bytes(out.tobytes(), "little")


_MASKING_KIND = {"none": 0, "full": 1, "chacha": 2, "chacha_rand03": 3}


def embed_participate(
    secret: Sequence[int], modulus: int, share_count: int,
    masking: str = "none", seed_bits: int = 128,
    recipient_pk: bytes = b"", clerk_pks: Sequence[bytes] = (),
    share_matrix=None, secret_count: int = 0,
    mask_modulus: Optional[int] = None,
) -> tuple:
    """The embeddable participant core (C ABI `sda_embed_participate` /
    `sda_embed_participate_shamir`): canonicalize -> mask -> share ->
    varint -> sealed boxes, all in native code. Additive sharing by
    default; pass ``share_matrix`` ([share_count, 1+k+t] canonical
    residues from numtheory.share_matrix_for) + ``secret_count`` for
    packed-Shamir/BasicShamir committees. Returns
    ``(recipient_blob | None, [clerk_blob, ...])`` — raw sealedbox bytes
    wire-compatible with the Python clerks and recipient. Reference
    analog: the declared-but-unreleased /embeddable-client (reference
    README.md:196-204).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if masking not in _MASKING_KIND:
        raise ValueError(f"masking must be one of {sorted(_MASKING_KIND)}")
    if len(clerk_pks) != share_count:
        raise ValueError("need one clerk public key per share")
    if masking != "none" and len(recipient_pk) != 32:
        raise ValueError("recipient_pk must be 32 bytes")
    for pk in clerk_pks:
        if len(pk) != 32:
            raise ValueError("clerk public keys must be 32 bytes")
    arr = np.ascontiguousarray(secret, dtype=np.int64)
    if arr.ndim != 1:
        raise ValueError("secret must be a vector")
    dim = arr.shape[0]
    seal_overhead = 48
    # worst case: 10 varint bytes per value per blob, plus seed words
    cap = (share_count + 1) * (10 * dim + seal_overhead + 128)
    out = np.zeros(cap, dtype=np.uint8)
    lens = np.zeros(1 + share_count, dtype=np.int64)
    u8 = ctypes.POINTER(ctypes.c_uint8)
    rpk = np.frombuffer(
        recipient_pk.ljust(32, b"\0"), dtype=np.uint8).copy()
    cpk = np.frombuffer(b"".join(clerk_pks), dtype=np.uint8).copy()
    if share_matrix is None:
        rc = lib.sda_embed_participate(
            _i64(arr), dim, modulus, share_count,
            _MASKING_KIND[masking], seed_bits,
            rpk.ctypes.data_as(u8), cpk.ctypes.data_as(u8),
            out.ctypes.data_as(u8), cap, _i64(lens),
        )
        what = "sda_embed_participate"
    else:
        mat = np.ascontiguousarray(share_matrix, dtype=np.int64) % modulus
        if mat.ndim != 2 or mat.shape[0] != share_count:
            raise ValueError(
                "share_matrix must be [share_count, 1+k+t]")
        m2 = mat.shape[1]
        if not 1 <= secret_count <= m2 - 1:
            raise ValueError("secret_count inconsistent with share_matrix")
        rc = lib.sda_embed_participate_shamir(
            _i64(arr), dim, modulus,
            mask_modulus if mask_modulus is not None else modulus,
            _i64(mat), share_count, m2, secret_count,
            _MASKING_KIND[masking], seed_bits,
            rpk.ctypes.data_as(u8), cpk.ctypes.data_as(u8),
            out.ctypes.data_as(u8), cap, _i64(lens),
        )
        what = "sda_embed_participate_shamir"
    if rc == 1:
        raise RuntimeError("libsodium unavailable at runtime")
    if rc:
        raise ValueError(f"{what} failed (rc={rc})")
    blobs, pos = [], 0
    for n in lens.tolist():
        blobs.append(out[pos:pos + n].tobytes())
        pos += n
    recipient_blob = blobs[0] if lens[0] else None
    return recipient_blob, blobs[1:]


def powmod_batch(bases: Sequence[int], exp: int, mod: int) -> List[int]:
    """Many bases against one (exp, mod) in a single native call — the
    Paillier batch-encrypt/decrypt shape."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if mod <= 0 or not (mod & 1):
        raise ValueError("modulus must be positive and odd")
    if exp < 0:
        raise ValueError("negative exponents unsupported")
    nl = (mod.bit_length() + 63) // 64
    el = max(1, (exp.bit_length() + 63) // 64)
    count = len(bases)
    base_arr = np.concatenate(
        [_limbs(b % mod, nl) for b in bases]
    ) if count else np.zeros(0, dtype=np.uint64)
    base_arr = np.ascontiguousarray(base_arr, dtype=np.uint64)
    scratch = np.zeros(22 * nl + 3, dtype=np.uint64)
    outs = np.zeros(count * nl, dtype=np.uint64)
    rc = lib.sda_powmod_batch(
        _u64(base_arr), count, _u64(_limbs(exp, el)), el,
        _u64(_limbs(mod, nl)), nl, _u64(scratch), _u64(outs),
    )
    if rc:
        raise ValueError("sda_powmod_batch failed")
    raw = outs.tobytes()
    step = nl * 8
    return [int.from_bytes(raw[i * step:(i + 1) * step], "little")
            for i in range(count)]
