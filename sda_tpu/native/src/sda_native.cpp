// sda-tpu native host kernels.
//
// The reference's native-performance surface is libsodium (C) and the
// threshold-secret-sharing Rust crate; sda-tpu binds libsodium directly via
// ctypes and re-owns the field math here: an exact C++ oracle for the modular
// matmul kernels (independent of numpy/XLA, used for bit-exactness audits)
// plus fast ChaCha20 mask PRGs implementing both CHACHA_PRG_V1 and the
// rand-0.3-compatible CHACHA_PRG_RAND03 (sda_tpu/fields/chacha.py) for the
// recipient's seed re-expansion hot loop (reference:
// client/src/receive.rs:102-118, masking/chacha.rs:57-77).
//
// Build: g++ -O3 -shared -fPIC (see build.py). ABI: plain C, int64/uint32
// buffers owned by the caller.

#include <cstdint>
#include <cstring>

#include <dlfcn.h>

#include <vector>

extern "C" {

// (a[m,k] @ b[k,n]) mod p with exact 128-bit accumulation.
// Entries must be canonical residues in [0, p); p < 2^62.
// Returns 0 on success, nonzero on bad arguments.
int sda_modmatmul_i64(const int64_t* a, const int64_t* b, int64_t* out,
                      int64_t m, int64_t k, int64_t n, int64_t p) {
    if (p <= 0 || m < 0 || k < 0 || n < 0) return 1;
    for (int64_t i = 0; i < m; ++i) {
        for (int64_t j = 0; j < n; ++j) {
            unsigned __int128 acc = 0;
            for (int64_t t = 0; t < k; ++t) {
                acc += (unsigned __int128)(uint64_t)a[i * k + t] *
                       (uint64_t)b[t * n + j];
                // lazy reduction: fold down before the 128-bit accumulator
                // can overflow (p^2 < 2^124, so at most 8 products fit)
                if ((t & 7) == 7) acc %= (uint64_t)p;
            }
            out[i * n + j] = (int64_t)(acc % (uint64_t)p);
        }
    }
    return 0;
}

// Elementwise sum mod m over the leading axis: x[rows, n] -> out[n].
int sda_modsum_axis0(const int64_t* x, int64_t* out, int64_t rows, int64_t n,
                     int64_t m) {
    if (m <= 0 || rows < 0 || n < 0) return 1;
    for (int64_t j = 0; j < n; ++j) out[j] = 0;
    for (int64_t i = 0; i < rows; ++i) {
        const int64_t* row = x + i * n;
        for (int64_t j = 0; j < n; ++j) {
            int64_t v = out[j] + row[j] % m;
            out[j] = v >= m ? v - m : v;
        }
    }
    return 0;
}

// ---------------------------------------------------------------------------
// ChaCha20 (CHACHA_PRG_V1): RFC-7539 constants, key = seed words 0..7
// (zero-padded), block counter in word 12, words 13..15 zero, 20 rounds.

static inline uint32_t rotl32(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
}

#define SDA_QR(a, b, c, d)                                                   \
    a += b; d ^= a; d = rotl32(d, 16);                                       \
    c += d; b ^= c; b = rotl32(b, 12);                                       \
    a += b; d ^= a; d = rotl32(d, 8);                                        \
    c += d; b ^= c; b = rotl32(b, 7);

static void chacha_block(const uint32_t key[8], uint32_t counter,
                         uint32_t out[16]) {
    uint32_t s[16] = {0x61707865u, 0x3320646Eu, 0x79622D32u, 0x6B206574u,
                      key[0], key[1], key[2], key[3],
                      key[4], key[5], key[6], key[7],
                      counter, 0u, 0u, 0u};
    uint32_t x[16];
    std::memcpy(x, s, sizeof(x));
    for (int i = 0; i < 10; ++i) {
        SDA_QR(x[0], x[4], x[8], x[12]);
        SDA_QR(x[1], x[5], x[9], x[13]);
        SDA_QR(x[2], x[6], x[10], x[14]);
        SDA_QR(x[3], x[7], x[11], x[15]);
        SDA_QR(x[0], x[5], x[10], x[15]);
        SDA_QR(x[1], x[6], x[11], x[12]);
        SDA_QR(x[2], x[7], x[8], x[13]);
        SDA_QR(x[3], x[4], x[9], x[14]);
    }
    for (int i = 0; i < 16; ++i) out[i] = x[i] + s[i];
}

// Expand a seed into `dim` uniform draws in [0, modulus) by rejection
// sampling over u64 lanes (two keystream words each, low word first) —
// bit-identical to sda_tpu.fields.chacha.expand_mask.
int sda_chacha_expand_mask(const uint32_t* seed, int64_t seed_words,
                           int64_t dim, int64_t modulus, int64_t* out) {
    if (modulus <= 0 || dim < 0 || seed_words < 0 || seed_words > 8) return 1;
    uint32_t key[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int64_t i = 0; i < seed_words; ++i) key[i] = seed[i];
    const uint64_t m = (uint64_t)modulus;
    // accept v <= zone where zone+1 is the largest multiple of m <= 2^64
    const uint64_t zone =
        (uint64_t)(((((unsigned __int128)1) << 64) / m) * m - 1);
    uint32_t counter = 0;
    int64_t filled = 0;
    uint32_t words[16];
    while (filled < dim) {
        chacha_block(key, counter++, words);
        for (int lane = 0; lane < 8 && filled < dim; ++lane) {
            uint64_t lo = words[2 * lane];
            uint64_t hi = words[2 * lane + 1];
            uint64_t v = (hi << 32) | lo;
            if (v <= zone) out[filled++] = (int64_t)(v % m);
        }
    }
    return 0;
}

// The exact rand-0.3 ChaChaRng stream (CHACHA_PRG_RAND03) — what the
// reference's masker actually draws (client/src/crypto/masking/
// chacha.rs:37-41 via rand 0.3's chacha.rs + distributions/range.rs).
// Same block function; u64 draws take the FIRST keystream word as the
// HIGH half (rand 0.3's default next_u64) and the acceptance zone is
// UINT64_MAX - UINT64_MAX % m, exclusive. Bit-identical to
// sda_tpu.fields.chacha.expand_mask_rand03.
int sda_chacha_expand_mask_r03(const uint32_t* seed, int64_t seed_words,
                               int64_t dim, int64_t modulus, int64_t* out) {
    if (modulus <= 0 || dim < 0 || seed_words < 0 || seed_words > 8) return 1;
    uint32_t key[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int64_t i = 0; i < seed_words; ++i) key[i] = seed[i];
    const uint64_t m = (uint64_t)modulus;
    const uint64_t zone_excl = UINT64_MAX - UINT64_MAX % m;  // accept v < zone
    uint32_t counter = 0;
    int64_t filled = 0;
    uint32_t words[16];
    while (filled < dim) {
        chacha_block(key, counter++, words);
        for (int lane = 0; lane < 8 && filled < dim; ++lane) {
            uint64_t hi = words[2 * lane];
            uint64_t lo = words[2 * lane + 1];
            uint64_t v = (hi << 32) | lo;
            if (v < zone_excl) out[filled++] = (int64_t)(v % m);
        }
    }
    return 0;
}

// Sum of many expanded masks mod m — the recipient hot loop in one call:
// seeds[n_seeds, seed_words] (as i64 per wire convention) -> out[dim].
// `expand` selects the PRG (shared body for the V1 and rand-0.3 entry
// points below).
typedef int (*sda_expand_fn)(const uint32_t*, int64_t, int64_t, int64_t,
                             int64_t*);

static int combine_masks_with(sda_expand_fn expand, const int64_t* seeds,
                              int64_t n_seeds, int64_t seed_words,
                              int64_t dim, int64_t modulus, int64_t* scratch,
                              int64_t* out) {
    if (modulus <= 0) return 1;
    for (int64_t j = 0; j < dim; ++j) out[j] = 0;
    uint32_t seed32[8];
    for (int64_t s = 0; s < n_seeds; ++s) {
        if (seed_words > 8) return 1;
        for (int64_t w = 0; w < seed_words; ++w)
            seed32[w] = (uint32_t)(uint64_t)seeds[s * seed_words + w];
        int rc = expand(seed32, seed_words, dim, modulus, scratch);
        if (rc) return rc;
        for (int64_t j = 0; j < dim; ++j) {
            int64_t v = out[j] + scratch[j];
            out[j] = v >= modulus ? v - modulus : v;
        }
    }
    return 0;
}

int sda_chacha_combine_masks(const int64_t* seeds, int64_t n_seeds,
                             int64_t seed_words, int64_t dim, int64_t modulus,
                             int64_t* scratch, int64_t* out) {
    return combine_masks_with(sda_chacha_expand_mask, seeds, n_seeds,
                              seed_words, dim, modulus, scratch, out);
}

int sda_chacha_combine_masks_r03(const int64_t* seeds, int64_t n_seeds,
                                 int64_t seed_words, int64_t dim,
                                 int64_t modulus, int64_t* scratch,
                                 int64_t* out) {
    return combine_masks_with(sda_chacha_expand_mask_r03, seeds, n_seeds,
                              seed_words, dim, modulus, scratch, out);
}

// ---------------------------------------------------------------------------
// Big-integer Montgomery modular exponentiation — the Paillier hot op.
//
// CPython's pow() on 2048-bit operands runs 30-bit digit arithmetic; this
// CIOS Montgomery ladder on 64-bit limbs (4-bit window, dedicated
// squaring) measures ~3.5-5x faster — 347ms -> ~75ms per Paillier
// encryption at 2048-bit keys (see docs/crypto.md envelope). Limb arrays
// are little-endian uint64, caller-owned; the modulus must be odd (n and
// n^2 always are) with a nonzero top limb.

namespace {

// -n^-1 mod 2^64 via Newton iteration (n odd).
static uint64_t mont_n0inv(uint64_t n0) {
    uint64_t x = 1;
    for (int i = 0; i < 6; ++i) x *= 2 - n0 * x;  // doubles correct bits
    return ~x + 1;  // negate mod 2^64
}

// out = a*b*R^-1 mod n (CIOS), R = 2^(64*nl). a, b < n. scratch t[nl+2].
static void mont_mul(const uint64_t* a, const uint64_t* b, const uint64_t* n,
                     uint64_t n0inv, int64_t nl, uint64_t* t, uint64_t* out) {
    for (int64_t i = 0; i < nl + 2; ++i) t[i] = 0;
    for (int64_t i = 0; i < nl; ++i) {
        // t += a[i] * b
        unsigned __int128 carry = 0;
        for (int64_t j = 0; j < nl; ++j) {
            carry += (unsigned __int128)a[i] * b[j] + t[j];
            t[j] = (uint64_t)carry;
            carry >>= 64;
        }
        carry += t[nl];
        t[nl] = (uint64_t)carry;
        t[nl + 1] = (uint64_t)(carry >> 64);
        // t += m * n, where m = t[0] * n0inv mod 2^64; then t >>= 64
        uint64_t m = t[0] * n0inv;
        carry = (unsigned __int128)m * n[0] + t[0];
        carry >>= 64;
        for (int64_t j = 1; j < nl; ++j) {
            carry += (unsigned __int128)m * n[j] + t[j];
            t[j - 1] = (uint64_t)carry;
            carry >>= 64;
        }
        carry += t[nl];
        t[nl - 1] = (uint64_t)carry;
        t[nl] = t[nl + 1] + (uint64_t)(carry >> 64);
    }
    // conditional subtract: t may be in [0, 2n)
    uint64_t borrow = 0;
    for (int64_t j = 0; j < nl; ++j) {
        unsigned __int128 d =
            (unsigned __int128)t[j] - n[j] - borrow;
        out[j] = (uint64_t)d;
        borrow = (uint64_t)(d >> 64) ? 1 : 0;
    }
    if (t[nl] == 0 && borrow) {  // t < n: keep t
        for (int64_t j = 0; j < nl; ++j) out[j] = t[j];
    }
}

// Montgomery squaring: the ladder is ~5 squares per multiply, and a
// schoolbook square needs only the upper-triangle products doubled —
// ~35% fewer 128-bit multiplies than mont_mul. Computes the full 2nl-limb
// square into s, then a separate REDC pass. scratch s[2*nl+1].
static void mont_sqr(const uint64_t* a, const uint64_t* n, uint64_t n0inv,
                     int64_t nl, uint64_t* s, uint64_t* out) {
    for (int64_t i = 0; i < 2 * nl + 1; ++i) s[i] = 0;
    // off-diagonal products once
    for (int64_t i = 0; i < nl; ++i) {
        unsigned __int128 carry = 0;
        for (int64_t j = i + 1; j < nl; ++j) {
            carry += (unsigned __int128)a[i] * a[j] + s[i + j];
            s[i + j] = (uint64_t)carry;
            carry >>= 64;
        }
        s[i + nl] += (uint64_t)carry;  // no overflow: slot untouched so far
    }
    // double, then add the diagonal
    uint64_t carry1 = 0;
    for (int64_t i = 0; i < 2 * nl; ++i) {
        uint64_t v = s[i];
        s[i] = (v << 1) | carry1;
        carry1 = v >> 63;
    }
    unsigned __int128 carry = 0;
    for (int64_t i = 0; i < nl; ++i) {
        carry += (unsigned __int128)a[i] * a[i] + s[2 * i];
        s[2 * i] = (uint64_t)carry;
        carry = (carry >> 64) + s[2 * i + 1];
        s[2 * i + 1] = (uint64_t)carry;
        carry >>= 64;
    }
    // REDC: nl rounds of m = s[i]*n0inv; s += m*n << (64*i)
    for (int64_t i = 0; i < nl; ++i) {
        uint64_t m = s[i] * n0inv;
        unsigned __int128 c2 = (unsigned __int128)m * n[0] + s[i];
        c2 >>= 64;
        for (int64_t j = 1; j < nl; ++j) {
            c2 += (unsigned __int128)m * n[j] + s[i + j];
            s[i + j] = (uint64_t)c2;
            c2 >>= 64;
        }
        // propagate the carry into the high limbs
        for (int64_t j = i + nl; c2 && j <= 2 * nl; ++j) {
            c2 += s[j];
            s[j] = (uint64_t)c2;
            c2 >>= 64;
        }
    }
    // result = s[nl .. 2nl] (may be >= n once)
    uint64_t borrow = 0;
    for (int64_t j = 0; j < nl; ++j) {
        unsigned __int128 d =
            (unsigned __int128)s[nl + j] - n[j] - borrow;
        out[j] = (uint64_t)d;
        borrow = (uint64_t)(d >> 64) ? 1 : 0;
    }
    if (s[2 * nl] == 0 && borrow) {
        for (int64_t j = 0; j < nl; ++j) out[j] = s[nl + j];
    }
}

// R^2 mod n by 2*64*nl doublings of 1 (cheap next to the ladder).
static void mont_rr(const uint64_t* n, int64_t nl, uint64_t* rr) {
    for (int64_t i = 0; i < nl; ++i) rr[i] = 0;
    rr[0] = 1;
    // rr < n invariant; double with conditional subtract
    for (int64_t bit = 0; bit < 2 * 64 * nl; ++bit) {
        uint64_t carry = 0;
        for (int64_t j = 0; j < nl; ++j) {
            uint64_t v = rr[j];
            rr[j] = (v << 1) | carry;
            carry = v >> 63;
        }
        // subtract n if rr >= n (or the shift overflowed)
        bool ge = carry != 0;
        if (!ge) {
            ge = true;
            for (int64_t j = nl - 1; j >= 0; --j) {
                if (rr[j] != n[j]) { ge = rr[j] > n[j]; break; }
            }
        }
        if (ge) {
            uint64_t borrow = 0;
            for (int64_t j = 0; j < nl; ++j) {
                unsigned __int128 d =
                    (unsigned __int128)rr[j] - n[j] - borrow;
                rr[j] = (uint64_t)d;
                borrow = (uint64_t)(d >> 64) ? 1 : 0;
            }
        }
    }
}

}  // namespace

// out = base^exp mod n. Little-endian uint64 limbs; base/out have nl limbs
// (base < n), exp has el limbs, n odd with n[nl-1] != 0. Fixed 4-bit
// window. scratch must hold 22 * nl + 3 limbs; pass null to have the
// function refuse (keeps the ABI allocation-free).
//
// NOT constant-time: the ladder skips leading zero windows, multiplies
// only on nonzero windows, and the Montgomery reductions take
// data-dependent conditional subtracts — execution time leaks the
// exponent's zero-window pattern and effective bit length. CPython's
// pow (the fallback path) is variable-time too. Acceptable for this
// repo's threat model (Paillier decrypt runs on the clerk's own
// machine; the wire carries ciphertexts, not timings), but a deployment
// where an adversary can time individual decryptions at high resolution
// should use a constant-time bignum library instead. See docs/crypto.md.
int sda_powmod(const uint64_t* base, const uint64_t* exp, int64_t el,
               const uint64_t* n, int64_t nl, uint64_t* scratch,
               uint64_t* out) {
    if (!base || !exp || !n || !out || !scratch) return 1;
    if (nl <= 0 || el < 0 || (n[0] & 1) == 0 || n[nl - 1] == 0) return 1;
    uint64_t n0inv = mont_n0inv(n[0]);
    uint64_t* table = scratch;             // 16 * nl: window powers (mont)
    uint64_t* rr = table + 16 * nl;        // nl
    uint64_t* acc = rr + nl;               // nl
    uint64_t* tmp = acc + nl;              // nl
    uint64_t* t = tmp + nl;                // nl + 2 (CIOS scratch)
    uint64_t* sq = t + nl + 2;             // 2 * nl + 1 (squaring scratch)
    mont_rr(n, nl, rr);
    // table[1] = base in Montgomery form; table[0] = 1 in Montgomery form
    mont_mul(base, rr, n, n0inv, nl, t, table + nl);
    uint64_t* one = tmp;
    for (int64_t j = 0; j < nl; ++j) one[j] = (j == 0);
    mont_mul(one, rr, n, n0inv, nl, t, table);  // mont(1) = R mod n
    for (int w = 2; w < 16; ++w)
        mont_mul(table + (w - 1) * nl, table + nl, n, n0inv, nl, t,
                 table + w * nl);
    // top-down 4-bit ladder
    for (int64_t j = 0; j < nl; ++j) acc[j] = table[j];  // mont(1)
    int64_t top = el - 1;
    while (top >= 0 && exp[top] == 0) --top;
    bool started = false;
    for (int64_t i = top; i >= 0; --i) {
        for (int shift = 60; shift >= 0; shift -= 4) {
            int w = (int)((exp[i] >> shift) & 0xF);
            if (started) {
                mont_sqr(acc, n, n0inv, nl, sq, acc);
                mont_sqr(acc, n, n0inv, nl, sq, acc);
                mont_sqr(acc, n, n0inv, nl, sq, acc);
                mont_sqr(acc, n, n0inv, nl, sq, acc);
            }
            if (w != 0) {
                mont_mul(acc, table + w * nl, n, n0inv, nl, t, acc);
                started = true;
            } else if (!started) {
                continue;  // skip leading zeros entirely
            }
        }
    }
    // leave Montgomery form: acc * 1
    mont_mul(acc, one, n, n0inv, nl, t, out);
    return 0;
}

// Batch variant: `count` bases against one (exp, n) — the Paillier premix
// and clerk-batch shapes. bases/outs are [count, nl].
int sda_powmod_batch(const uint64_t* bases, int64_t count, const uint64_t* exp,
                     int64_t el, const uint64_t* n, int64_t nl,
                     uint64_t* scratch, uint64_t* outs) {
    for (int64_t i = 0; i < count; ++i) {
        int rc = sda_powmod(bases + i * nl, exp, el, n, nl, scratch,
                            outs + i * nl);
        if (rc) return rc;
    }
    return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Embeddable participant core.
//
// The reference declares (and never released) an /embeddable-client that
// "wraps client and client-http to expose the client functionality in a
// C-friendly" API for mobile/embedded apps (reference README.md:196-204).
// This is the TPU build's analog of its compute half: the COMPLETE
// participant crypto — canonicalize -> mask (none/full/chacha) ->
// additive-share -> zigzag-varint -> libsodium sealed boxes — behind one
// C call, wire-compatible with the Python clerks/recipient (same varint
// and sealedbox formats, crypto/varint.py + crypto/sodium.py). Transport
// stays with the embedding host, exactly the split the reference intended
// (its embeddable client wrapped client-http separately).
//
// libsodium is loaded at RUNTIME (dlopen), so this file builds — and every
// other export works — on machines without it; callers get return code 1.

namespace {

typedef int (*fn_sodium_init)(void);
typedef void (*fn_randombytes_buf)(void*, size_t);
typedef int (*fn_crypto_box_seal)(unsigned char*, const unsigned char*,
                                  unsigned long long, const unsigned char*);

struct Sodium {
    fn_randombytes_buf randombytes = nullptr;
    fn_crypto_box_seal seal = nullptr;
    bool ok = false;
};

static Sodium& sodium() {
    static Sodium s = [] {
        Sodium r;
        // keep in sync with crypto/sodium.py _SONAMES: a host where the
        // Python client finds sodium must not fail the embedded core
        const char* names[] = {"libsodium.so.23", "libsodium.so",
                               "libsodium.so.26", "libsodium.so.18",
                               nullptr};
        void* h = nullptr;
        for (int i = 0; names[i] && !h; ++i)
            h = dlopen(names[i], RTLD_NOW);
        if (!h) return r;
        fn_sodium_init init = (fn_sodium_init)dlsym(h, "sodium_init");
        r.randombytes = (fn_randombytes_buf)dlsym(h, "randombytes_buf");
        r.seal = (fn_crypto_box_seal)dlsym(h, "crypto_box_seal");
        // sodium_init: 0 fresh, 1 already initialized, -1 failure
        if (init && r.randombytes && r.seal && init() >= 0) r.ok = true;
        return r;
    }();
    return s;
}

const int64_t kSealBytes = 48;  // crypto_box_SEALBYTES (x25519 pk + MAC)

// exact uniform draws in [0, m): rejection over u64 (no modulo bias),
// bulk-filled — one randombytes_buf call per vector, per-lane redraw only
// on rejection (probability < 2^-32 for the moduli in play)
static void uniform_fill(Sodium& s, uint64_t m, int64_t* dst, int64_t n) {
    const uint64_t zone =
        (uint64_t)(((((unsigned __int128)1) << 64) / m) * m - 1);
    std::vector<uint64_t> buf((size_t)n);
    s.randombytes(buf.data(), (size_t)n * sizeof(uint64_t));
    for (int64_t i = 0; i < n; ++i) {
        uint64_t v = buf[(size_t)i];
        while (v > zone) s.randombytes(&v, sizeof v);
        dst[i] = (int64_t)(v % m);
    }
}

// zigzag + LEB128, matching sda_tpu.crypto.varint (the reference's
// integer-encoding VarInt inside sealed boxes, encryption/sodium.rs:36-45)
static void varint_append(std::vector<uint8_t>& out, int64_t x) {
    uint64_t u = ((uint64_t)x << 1) ^ (uint64_t)(x >> 63);
    do {
        uint8_t b = u & 0x7F;
        u >>= 7;
        if (u) b |= 0x80;
        out.push_back(b);
    } while (u);
}

// (row . vals) mod m with u128 accumulation; entries canonical < m < 2^62.
// The fold cadence keeps partials exact: 8 products of (2^62-1)^2 plus a
// carried residue stay under 2^127 — the ONE place this invariant lives.
static uint64_t moddot_row(const int64_t* row, const uint64_t* vals,
                           int32_t n, uint64_t m) {
    unsigned __int128 acc = 0;
    int cnt = 0;
    for (int32_t j = 0; j < n; ++j) {
        acc += (unsigned __int128)(uint64_t)row[j] * vals[j];
        if (++cnt == 8) {
            acc %= m;
            cnt = 0;
        }
    }
    return (uint64_t)(acc % m);
}

static int seal_blob(Sodium& s, const std::vector<uint8_t>& msg,
                     const uint8_t* pk, uint8_t* out, int64_t cap,
                     int64_t* written) {
    int64_t need = (int64_t)msg.size() + kSealBytes;
    if (need > cap) return 2;
    if (s.seal(out, msg.data(), (unsigned long long)msg.size(), pk) != 0)
        return 4;
    *written = need;
    return 0;
}

// Canonicalize the secrets into `masked`, apply the masking scheme, and
// seal the recipient payload (mask vector / chacha seed) at `out`.
// Shared by the additive and Shamir entry points; *rec_written = 0 for
// masking none. Returns the usual embed rc.
static int mask_phase(Sodium& s, const int64_t* secret, int64_t dim,
                      int64_t modulus, int32_t masking_kind,
                      int32_t seed_bits, const uint8_t* recipient_pk,
                      uint8_t* out, int64_t out_cap,
                      std::vector<int64_t>& masked, int64_t* rec_written) {
    const uint64_t m = (uint64_t)modulus;
    masked.resize((size_t)dim);
    for (int64_t i = 0; i < dim; ++i) {
        int64_t c = secret[i] % modulus;
        if (c < 0) c += modulus;
        masked[(size_t)i] = c;
    }
    *rec_written = 0;
    if (masking_kind == 0) return 0;
    std::vector<uint8_t> payload;
    if (masking_kind == 1) {
        payload.reserve((size_t)dim * 5);
        std::vector<int64_t> mask((size_t)dim);
        uniform_fill(s, m, mask.data(), dim);
        for (int64_t i = 0; i < dim; ++i) {
            uint64_t v = (uint64_t)masked[(size_t)i]
                       + (uint64_t)mask[(size_t)i];
            if (v >= m) v -= m;
            masked[(size_t)i] = (int64_t)v;
            varint_append(payload, mask[(size_t)i]);
        }
    } else {
        // ceil to whole 32-bit words, matching chacha.random_seed: any
        // seed_bitsize the Python client accepts must work embedded too
        if (seed_bits <= 0 || seed_bits > 256) return 3;
        int words = (seed_bits + 31) / 32;
        uint32_t seed[8] = {0};
        s.randombytes(seed, (size_t)words * 4);
        std::vector<int64_t> mask((size_t)dim);
        // kind 2 = CHACHA_PRG_V1, kind 3 = CHACHA_PRG_RAND03 (the stream a
        // bare Rust-shaped scheme implies; rand-0.3 interop)
        int rc_expand = masking_kind == 3
            ? sda_chacha_expand_mask_r03(seed, words, dim, modulus, mask.data())
            : sda_chacha_expand_mask(seed, words, dim, modulus, mask.data());
        if (rc_expand)
            return 3;
        for (int64_t i = 0; i < dim; ++i) {
            uint64_t v = (uint64_t)masked[(size_t)i]
                       + (uint64_t)mask[(size_t)i];
            if (v >= m) v -= m;
            masked[(size_t)i] = (int64_t)v;
        }
        // the uploaded "mask" is the seed itself (masking/chacha.rs
        // semantics): the recipient re-expands it
        for (int w = 0; w < words; ++w)
            varint_append(payload, (int64_t)seed[w]);
    }
    return seal_blob(s, payload, recipient_pk, out, out_cap, rec_written);
}

}  // namespace

extern "C" {

// Full participant compute for one aggregation input.
//
//   secret[dim]    any int64 values; canonicalized mod `modulus`
//   masking_kind   0 = none, 1 = full, 2 = chacha CHACHA_PRG_V1,
//                  3 = chacha CHACHA_PRG_RAND03 (seed_bits in 32..256,
//                  multiple of 32)
//   recipient_pk   32-byte Curve25519 pk (ignored for masking none)
//   clerk_pks      share_count x 32 bytes, committee order
//   out/out_cap    packed output: [recipient blob][clerk 0 blob]...[n-1]
//   out_lens       int64[1 + share_count]: recipient blob length (0 when
//                  masking none), then each clerk blob length
//
// Sharing here is additive; Shamir committees use the sibling
// sda_embed_participate_shamir below (host-computed share matrix).
// Returns 0 ok, 1 libsodium unavailable, 2 out_cap too small,
// 3 bad arguments, 4 sealing failure.
int sda_embed_participate(
    const int64_t* secret, int64_t dim, int64_t modulus,
    int32_t share_count, int32_t masking_kind, int32_t seed_bits,
    const uint8_t* recipient_pk, const uint8_t* clerk_pks,
    uint8_t* out, int64_t out_cap, int64_t* out_lens) {
    if (dim < 0 || modulus <= 0 || share_count < 1) return 3;
    if (masking_kind < 0 || masking_kind > 3) return 3;
    Sodium& s = sodium();
    if (!s.ok) return 1;
    const uint64_t m = (uint64_t)modulus;
    std::vector<int64_t> masked;
    std::vector<uint8_t> payload;
    int64_t pos = 0, written = 0;
    int rc0 = mask_phase(s, secret, dim, modulus, masking_kind, seed_bits,
                         recipient_pk, out, out_cap, masked, &written);
    if (rc0) return rc0;
    out_lens[0] = written;
    pos += written;
    // additive shares: clerks 0..n-2 draw uniformly; the last share makes
    // the column sums telescope to the masked secret (additive.rs:32-52)
    std::vector<int64_t> acc((size_t)dim, 0);
    std::vector<int64_t> share((size_t)dim);
    for (int32_t c = 0; c < share_count; ++c) {
        payload.clear();
        if (c + 1 < share_count) {
            uniform_fill(s, m, share.data(), dim);
            for (int64_t i = 0; i < dim; ++i) {
                uint64_t a = (uint64_t)acc[(size_t)i]
                           + (uint64_t)share[(size_t)i];
                if (a >= m) a -= m;
                acc[(size_t)i] = (int64_t)a;
            }
        } else {
            for (int64_t i = 0; i < dim; ++i) {
                int64_t v = masked[(size_t)i] - acc[(size_t)i];
                if (v < 0) v += modulus;
                share[(size_t)i] = v;
            }
        }
        for (int64_t i = 0; i < dim; ++i)
            varint_append(payload, share[(size_t)i]);
        int rc = seal_blob(s, payload, clerk_pks + (size_t)c * 32,
                           out + pos, out_cap - pos, &written);
        if (rc) return rc;
        out_lens[1 + c] = written;
        pos += written;
    }
    return 0;
}

// Packed-Shamir variant: the share MATRIX is computed host-side (the
// NTT/Vandermonde number theory stays in fields/numtheory.py) and passed
// in as canonical residues; the core batches the masked vector into
// ceil(dim/k) columns of k secrets (batched.rs:18-53 semantics: values
// vector per batch = [0, secrets_k, randomness_t]), evaluates shares as
// [n, m2] @ [m2] modmuls with 128-bit accumulation, and streams clerk i's
// per-batch share into its sealed payload. modulus < 2^62.
//
//   share_modulus the sharing prime p: shares/partial sums live mod p
//   mask_modulus  the masking ring (<= p): the CLI/protocol policy draws
//                 masks mod the AGGREGATION modulus while Shamir shares
//                 ride a larger NTT prime with participant-sum headroom
//                 (masked values < mask_modulus <= p are shared verbatim;
//                 pass mask_modulus == share_modulus when they coincide)
//   m_host        n_shares x m2 canonical residues, row-major
//   m2            1 + secret_count + privacy_threshold
//   out_lens      int64[1 + n_shares], as in sda_embed_participate
int sda_embed_participate_shamir(
    const int64_t* secret, int64_t dim, int64_t share_modulus,
    int64_t mask_modulus,
    const int64_t* m_host, int32_t n_shares, int32_t m2, int32_t k,
    int32_t masking_kind, int32_t seed_bits,
    const uint8_t* recipient_pk, const uint8_t* clerk_pks,
    uint8_t* out, int64_t out_cap, int64_t* out_lens) {
    if (dim < 0 || share_modulus <= 0 || n_shares < 1) return 3;
    if (mask_modulus <= 0 || mask_modulus > share_modulus) return 3;
    if (k < 1 || m2 < k + 1) return 3;
    if (share_modulus >= (int64_t)1 << 62) return 3;  // u128 accum bound
    if (masking_kind < 0 || masking_kind > 3) return 3;
    Sodium& s = sodium();
    if (!s.ok) return 1;
    const uint64_t m = (uint64_t)share_modulus;
    std::vector<int64_t> masked;
    int64_t pos = 0, written = 0;
    int rc0 = mask_phase(s, secret, dim, mask_modulus, masking_kind,
                         seed_bits, recipient_pk, out, out_cap, masked,
                         &written);
    if (rc0) return rc0;
    out_lens[0] = written;
    pos += written;
    const int32_t t = m2 - 1 - k;
    const int64_t B = (dim + k - 1) / k;
    std::vector<std::vector<uint8_t>> clerk_payloads((size_t)n_shares);
    for (auto& p : clerk_payloads) p.reserve((size_t)B * 5);
    std::vector<int64_t> rands((size_t)(B * t));
    if (t > 0) uniform_fill(s, m, rands.data(), B * t);
    std::vector<uint64_t> vals((size_t)m2);
    for (int64_t b = 0; b < B; ++b) {
        vals[0] = 0;  // the share matrix's fixed zero column
        for (int32_t j = 0; j < k; ++j) {
            int64_t idx = b * k + j;  // zero-padded final batch
            vals[(size_t)(1 + j)] =
                idx < dim ? (uint64_t)masked[(size_t)idx] : 0;
        }
        for (int32_t j = 0; j < t; ++j)
            vals[(size_t)(1 + k + j)] = (uint64_t)rands[(size_t)(b * t + j)];
        for (int32_t i = 0; i < n_shares; ++i) {
            varint_append(
                clerk_payloads[(size_t)i],
                (int64_t)moddot_row(m_host + (size_t)i * m2, vals.data(),
                                    m2, m));
        }
    }
    for (int32_t i = 0; i < n_shares; ++i) {
        int rc = seal_blob(s, clerk_payloads[(size_t)i],
                           clerk_pks + (size_t)i * 32,
                           out + pos, out_cap - pos, &written);
        if (rc) return rc;
        out_lens[1 + i] = written;
        pos += written;
    }
    return 0;
}

int sda_native_abi_version() { return 5; }

}  // extern "C"
