// sda-tpu native host kernels.
//
// The reference's native-performance surface is libsodium (C) and the
// threshold-secret-sharing Rust crate; sda-tpu binds libsodium directly via
// ctypes and re-owns the field math here: an exact C++ oracle for the modular
// matmul kernels (independent of numpy/XLA, used for bit-exactness audits)
// plus a fast ChaCha20 mask PRG implementing CHACHA_PRG_V1
// (sda_tpu/fields/chacha.py) for the recipient's seed re-expansion hot loop
// (reference: client/src/receive.rs:102-118, masking/chacha.rs:57-77).
//
// Build: g++ -O3 -shared -fPIC (see build.py). ABI: plain C, int64/uint32
// buffers owned by the caller.

#include <cstdint>
#include <cstring>

extern "C" {

// (a[m,k] @ b[k,n]) mod p with exact 128-bit accumulation.
// Entries must be canonical residues in [0, p); p < 2^62.
// Returns 0 on success, nonzero on bad arguments.
int sda_modmatmul_i64(const int64_t* a, const int64_t* b, int64_t* out,
                      int64_t m, int64_t k, int64_t n, int64_t p) {
    if (p <= 0 || m < 0 || k < 0 || n < 0) return 1;
    for (int64_t i = 0; i < m; ++i) {
        for (int64_t j = 0; j < n; ++j) {
            unsigned __int128 acc = 0;
            for (int64_t t = 0; t < k; ++t) {
                acc += (unsigned __int128)(uint64_t)a[i * k + t] *
                       (uint64_t)b[t * n + j];
                // lazy reduction: fold down before the 128-bit accumulator
                // can overflow (p^2 < 2^124, so at most 8 products fit)
                if ((t & 7) == 7) acc %= (uint64_t)p;
            }
            out[i * n + j] = (int64_t)(acc % (uint64_t)p);
        }
    }
    return 0;
}

// Elementwise sum mod m over the leading axis: x[rows, n] -> out[n].
int sda_modsum_axis0(const int64_t* x, int64_t* out, int64_t rows, int64_t n,
                     int64_t m) {
    if (m <= 0 || rows < 0 || n < 0) return 1;
    for (int64_t j = 0; j < n; ++j) out[j] = 0;
    for (int64_t i = 0; i < rows; ++i) {
        const int64_t* row = x + i * n;
        for (int64_t j = 0; j < n; ++j) {
            int64_t v = out[j] + row[j] % m;
            out[j] = v >= m ? v - m : v;
        }
    }
    return 0;
}

// ---------------------------------------------------------------------------
// ChaCha20 (CHACHA_PRG_V1): RFC-7539 constants, key = seed words 0..7
// (zero-padded), block counter in word 12, words 13..15 zero, 20 rounds.

static inline uint32_t rotl32(uint32_t x, int n) {
    return (x << n) | (x >> (32 - n));
}

#define SDA_QR(a, b, c, d)                                                   \
    a += b; d ^= a; d = rotl32(d, 16);                                       \
    c += d; b ^= c; b = rotl32(b, 12);                                       \
    a += b; d ^= a; d = rotl32(d, 8);                                        \
    c += d; b ^= c; b = rotl32(b, 7);

static void chacha_block(const uint32_t key[8], uint32_t counter,
                         uint32_t out[16]) {
    uint32_t s[16] = {0x61707865u, 0x3320646Eu, 0x79622D32u, 0x6B206574u,
                      key[0], key[1], key[2], key[3],
                      key[4], key[5], key[6], key[7],
                      counter, 0u, 0u, 0u};
    uint32_t x[16];
    std::memcpy(x, s, sizeof(x));
    for (int i = 0; i < 10; ++i) {
        SDA_QR(x[0], x[4], x[8], x[12]);
        SDA_QR(x[1], x[5], x[9], x[13]);
        SDA_QR(x[2], x[6], x[10], x[14]);
        SDA_QR(x[3], x[7], x[11], x[15]);
        SDA_QR(x[0], x[5], x[10], x[15]);
        SDA_QR(x[1], x[6], x[11], x[12]);
        SDA_QR(x[2], x[7], x[8], x[13]);
        SDA_QR(x[3], x[4], x[9], x[14]);
    }
    for (int i = 0; i < 16; ++i) out[i] = x[i] + s[i];
}

// Expand a seed into `dim` uniform draws in [0, modulus) by rejection
// sampling over u64 lanes (two keystream words each, low word first) —
// bit-identical to sda_tpu.fields.chacha.expand_mask.
int sda_chacha_expand_mask(const uint32_t* seed, int64_t seed_words,
                           int64_t dim, int64_t modulus, int64_t* out) {
    if (modulus <= 0 || dim < 0 || seed_words < 0 || seed_words > 8) return 1;
    uint32_t key[8] = {0, 0, 0, 0, 0, 0, 0, 0};
    for (int64_t i = 0; i < seed_words; ++i) key[i] = seed[i];
    const uint64_t m = (uint64_t)modulus;
    // accept v <= zone where zone+1 is the largest multiple of m <= 2^64
    const uint64_t zone =
        (uint64_t)(((((unsigned __int128)1) << 64) / m) * m - 1);
    uint32_t counter = 0;
    int64_t filled = 0;
    uint32_t words[16];
    while (filled < dim) {
        chacha_block(key, counter++, words);
        for (int lane = 0; lane < 8 && filled < dim; ++lane) {
            uint64_t lo = words[2 * lane];
            uint64_t hi = words[2 * lane + 1];
            uint64_t v = (hi << 32) | lo;
            if (v <= zone) out[filled++] = (int64_t)(v % m);
        }
    }
    return 0;
}

// Sum of many expanded masks mod m — the recipient hot loop in one call:
// seeds[n_seeds, seed_words] (as i64 per wire convention) -> out[dim].
int sda_chacha_combine_masks(const int64_t* seeds, int64_t n_seeds,
                             int64_t seed_words, int64_t dim, int64_t modulus,
                             int64_t* scratch, int64_t* out) {
    if (modulus <= 0) return 1;
    for (int64_t j = 0; j < dim; ++j) out[j] = 0;
    uint32_t seed32[8];
    for (int64_t s = 0; s < n_seeds; ++s) {
        if (seed_words > 8) return 1;
        for (int64_t w = 0; w < seed_words; ++w)
            seed32[w] = (uint32_t)(uint64_t)seeds[s * seed_words + w];
        int rc = sda_chacha_expand_mask(seed32, seed_words, dim, modulus, scratch);
        if (rc) return rc;
        for (int64_t j = 0; j < dim; ++j) {
            int64_t v = out[j] + scratch[j];
            out[j] = v >= modulus ? v - modulus : v;
        }
    }
    return 0;
}

int sda_native_abi_version() { return 1; }

}  // extern "C"
