"""``application/x-sda-bin`` — the binary wire codec for hot-path resources.

JSON frames every ciphertext as base64 text (+33% bytes) inside a parsed
object tree; at production dimension the serialization tax dominates a
participation upload. This codec frames the three hot-path resources —
``Participation`` uploads, ``ClerkingJob`` payloads, ``ClerkingResult``
uploads — as tight binary:

    header   := MAGIC "SDAB" | version u8 | resource tag u8
    uuid     := 16 raw bytes (RFC 4122 byte order)
    varlen   := LEB128 (the exact framing ``crypto/encryption.py`` uses
                inside PackedPaillier payloads — one framing, two layers)
    array    := dtype tag u8 | varlen(byte length) | raw little-endian
                bytes (``np.ndarray.tobytes`` / ``np.frombuffer``)
    bytes    := array with dtype tag ``u1``
    string   := varlen | utf-8 bytes
    option   := presence u8 (0/1) | value
    list     := varlen(count) | items
    encryption := variant u8 (0=Sodium, 1=PackedPaillier) | bytes

Integer vectors (share payloads, seeds) ride the ``array`` primitive —
dtype-tagged little-endian buffers that decode with one ``frombuffer``
call instead of a Python-int-per-element JSON parse.

Content negotiation lives in ``http/``: the server advertises
``X-SDA-Codecs: bin`` on every response and accepts both content types on
the hot POST routes; the client upgrades after seeing the advert (or is
pinned with ``codec="json"|"bin"``). Old JSON-only peers interoperate
transparently in both directions. See ``docs/performance.md``.

Malformed input raises ``ValueError`` — the HTTP layer maps it to 400,
same as malformed JSON.
"""

from __future__ import annotations

import uuid as _uuid
from typing import List, Optional, Tuple

import numpy as np

from ..crypto.encryption import leb128, read_leb128
from .crypto import Encryption
from .helpers import Binary
from .resources import (
    AgentId,
    AggregationId,
    ClerkingJob,
    ClerkingJobId,
    ClerkingResult,
    Participation,
    ParticipationId,
    SnapshotId,
)

#: The negotiated content type (and the server's advert token, "bin").
CONTENT_TYPE = "application/x-sda-bin"
CODECS_HEADER = "X-SDA-Codecs"

MAGIC = b"SDAB"
VERSION = 1

TAG_PARTICIPATION = 1
TAG_CLERKING_JOB = 2
TAG_CLERKING_RESULT = 3

#: Wire order is the codec contract: appending a variant is
#: backward-compatible, reordering is not.
_ENC_VARIANTS = ("Sodium", "PackedPaillier")

#: dtype tag -> numpy dtype. Little-endian on the wire regardless of host
#: byte order; ``u1`` doubles as the raw-bytes frame.
_DTYPES = ("u1", "<i8", "<u8", "<i4", "<u4")
_DTYPE_TAG = {np.dtype(d): tag for tag, d in enumerate(_DTYPES)}


# ---------------------------------------------------------------------------
# Primitives

def _need(raw: bytes, pos: int, n: int) -> None:
    if pos + n > len(raw):
        raise ValueError("truncated x-sda-bin payload")


def write_array(out: List[bytes], arr: np.ndarray) -> None:
    """Dtype-tagged little-endian array frame (1-D)."""
    arr = np.ascontiguousarray(arr)
    dtype = np.dtype(arr.dtype.str.replace(">", "<"))
    tag = _DTYPE_TAG.get(dtype)
    if tag is None:
        raise ValueError(f"unsupported array dtype {arr.dtype}")
    payload = arr.astype(dtype, copy=False).tobytes()
    out.append(bytes([tag]) + leb128(len(payload)))
    out.append(payload)


def read_array(raw: bytes, pos: int) -> Tuple[np.ndarray, int]:
    _need(raw, pos, 1)
    tag = raw[pos]
    if tag >= len(_DTYPES):
        raise ValueError(f"unknown array dtype tag {tag}")
    nbytes, pos = read_leb128(raw, pos + 1)
    _need(raw, pos, nbytes)
    dtype = np.dtype(_DTYPES[tag])
    if nbytes % dtype.itemsize:
        raise ValueError("array byte length not a multiple of its itemsize")
    arr = np.frombuffer(raw[pos:pos + nbytes], dtype=dtype)
    return arr, pos + nbytes


def _w_bytes(out: List[bytes], data: bytes) -> None:
    out.append(bytes([0]) + leb128(len(data)))  # dtype tag 0 == u1
    out.append(data)


def _r_bytes(raw: bytes, pos: int) -> Tuple[bytes, int]:
    arr, pos = read_array(raw, pos)
    if arr.dtype != np.uint8:
        raise ValueError("expected a u1 byte frame")
    return arr.tobytes(), pos


def _w_uuid(out: List[bytes], rid) -> None:
    out.append(rid.uuid.bytes)


def _r_uuid(raw: bytes, pos: int, cls):
    _need(raw, pos, 16)
    return cls(_uuid.UUID(bytes=raw[pos:pos + 16])), pos + 16


def _w_encryption(out: List[bytes], enc: Encryption) -> None:
    try:
        variant = _ENC_VARIANTS.index(enc.variant)
    except ValueError:
        raise ValueError(f"unsupported encryption variant {enc.variant}")
    out.append(bytes([variant]))
    _w_bytes(out, enc.value.data)


def _r_encryption(raw: bytes, pos: int) -> Tuple[Encryption, int]:
    _need(raw, pos, 1)
    variant = raw[pos]
    if variant >= len(_ENC_VARIANTS):
        raise ValueError(f"unknown encryption variant tag {variant}")
    data, pos = _r_bytes(raw, pos + 1)
    return Encryption(_ENC_VARIANTS[variant], Binary(data)), pos


# ---------------------------------------------------------------------------
# Resource codecs

def _header(tag: int) -> bytes:
    return MAGIC + bytes([VERSION, tag])


def _check_header(raw: bytes, want_tag: Optional[int] = None) -> int:
    if len(raw) < 6 or raw[:4] != MAGIC:
        raise ValueError("not an x-sda-bin payload (bad magic)")
    if raw[4] != VERSION:
        raise ValueError(f"unsupported x-sda-bin version {raw[4]}")
    tag = raw[5]
    if want_tag is not None and tag != want_tag:
        raise ValueError(f"unexpected resource tag {tag} (want {want_tag})")
    return tag


def encode_participation(p: Participation) -> bytes:
    if p.forwarded_masks is not None:
        # tree-relay participations carry forwarded mask ciphertexts the
        # v1 frame has no slot for; encoding would silently DROP them and
        # corrupt the root's unmask. The HTTP client falls back to JSON
        # for these (rare: one per leaf group per round).
        raise ValueError(
            "x-sda-bin v1 cannot frame forwarded_masks; use JSON")
    out: List[bytes] = [_header(TAG_PARTICIPATION)]
    _w_uuid(out, p.id)
    _w_uuid(out, p.participant)
    _w_uuid(out, p.aggregation)
    if p.recipient_encryption is None:
        out.append(b"\x00")
    else:
        out.append(b"\x01")
        _w_encryption(out, p.recipient_encryption)
    out.append(leb128(len(p.clerk_encryptions)))
    for clerk_id, enc in p.clerk_encryptions:
        _w_uuid(out, clerk_id)
        _w_encryption(out, enc)
    return b"".join(out)


def decode_participation(raw: bytes) -> Participation:
    _check_header(raw, TAG_PARTICIPATION)
    pos = 6
    pid, pos = _r_uuid(raw, pos, ParticipationId)
    participant, pos = _r_uuid(raw, pos, AgentId)
    aggregation, pos = _r_uuid(raw, pos, AggregationId)
    _need(raw, pos, 1)
    recipient_encryption = None
    if raw[pos] not in (0, 1):
        raise ValueError("malformed option byte")
    present, pos = raw[pos], pos + 1
    if present:
        recipient_encryption, pos = _r_encryption(raw, pos)
    count, pos = read_leb128(raw, pos)
    clerk_encryptions = []
    for _ in range(count):
        clerk_id, pos = _r_uuid(raw, pos, AgentId)
        enc, pos = _r_encryption(raw, pos)
        clerk_encryptions.append((clerk_id, enc))
    if pos != len(raw):
        raise ValueError("trailing bytes after participation payload")
    return Participation(
        id=pid, participant=participant, aggregation=aggregation,
        recipient_encryption=recipient_encryption,
        clerk_encryptions=clerk_encryptions,
    )


def encode_clerking_job(job: ClerkingJob) -> bytes:
    out: List[bytes] = [_header(TAG_CLERKING_JOB)]
    _w_uuid(out, job.id)
    _w_uuid(out, job.clerk)
    _w_uuid(out, job.aggregation)
    _w_uuid(out, job.snapshot)
    out.append(leb128(len(job.encryptions)))
    for enc in job.encryptions:
        _w_encryption(out, enc)
    return b"".join(out)


def decode_clerking_job(raw: bytes) -> ClerkingJob:
    _check_header(raw, TAG_CLERKING_JOB)
    pos = 6
    jid, pos = _r_uuid(raw, pos, ClerkingJobId)
    clerk, pos = _r_uuid(raw, pos, AgentId)
    aggregation, pos = _r_uuid(raw, pos, AggregationId)
    snapshot, pos = _r_uuid(raw, pos, SnapshotId)
    count, pos = read_leb128(raw, pos)
    encryptions = []
    for _ in range(count):
        enc, pos = _r_encryption(raw, pos)
        encryptions.append(enc)
    if pos != len(raw):
        raise ValueError("trailing bytes after clerking-job payload")
    return ClerkingJob(id=jid, clerk=clerk, aggregation=aggregation,
                       snapshot=snapshot, encryptions=encryptions)


def encode_clerking_result(result: ClerkingResult) -> bytes:
    out: List[bytes] = [_header(TAG_CLERKING_RESULT)]
    _w_uuid(out, result.job)
    _w_uuid(out, result.clerk)
    _w_encryption(out, result.encryption)
    return b"".join(out)


def decode_clerking_result(raw: bytes) -> ClerkingResult:
    _check_header(raw, TAG_CLERKING_RESULT)
    pos = 6
    job, pos = _r_uuid(raw, pos, ClerkingJobId)
    clerk, pos = _r_uuid(raw, pos, AgentId)
    encryption, pos = _r_encryption(raw, pos)
    if pos != len(raw):
        raise ValueError("trailing bytes after clerking-result payload")
    return ClerkingResult(job=job, clerk=clerk, encryption=encryption)


_ENCODERS = {
    Participation: encode_participation,
    ClerkingJob: encode_clerking_job,
    ClerkingResult: encode_clerking_result,
}
_DECODERS = {
    TAG_PARTICIPATION: decode_participation,
    TAG_CLERKING_JOB: decode_clerking_job,
    TAG_CLERKING_RESULT: decode_clerking_result,
}


def encode(resource) -> bytes:
    """Resource -> framed binary (dispatch on type)."""
    encoder = _ENCODERS.get(type(resource))
    if encoder is None:
        raise ValueError(f"no binary codec for {type(resource).__name__}")
    return encoder(resource)


def decode(raw: bytes):
    """Framed binary -> resource (dispatch on the header tag)."""
    tag = _check_header(raw)
    decoder = _DECODERS.get(tag)
    if decoder is None:
        raise ValueError(f"unknown resource tag {tag}")
    return decoder(raw)


# ---------------------------------------------------------------------------
# Incremental (feed-based) decode — the streaming wire path
#
# The one-shot decoders above need the whole body in memory before the
# first field parses; at production dimension that doubles a request's
# peak memory (raw bytes + decoded arrays side by side) and, worse, forces
# the HTTP planes to buffer entire dim-1e8 uploads per connection. The
# FeedDecoder consumes the same wire format chunk by chunk: completed
# fields (ids, one encryption blob at a time) move straight into the
# resource under construction and their raw bytes are released, so the
# transient buffer is bounded by the largest SINGLE field frame plus one
# network chunk — O(frame), not O(body) — regardless of how many clerk
# encryptions the upload carries.
#
# The parsers are generators speaking a tiny pull protocol: ``yield n``
# returns exactly n bytes once the driver has them. They mirror the
# one-shot decoders field for field; tests pin chunked == one-shot on
# golden payloads at every chunk size.

def _g_leb():
    n = shift = 0
    while True:
        b = (yield 1)[0]
        if shift > 63:
            raise ValueError("oversized varint in x-sda-bin payload")
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n
        shift += 7


def _g_array():
    tag = (yield 1)[0]
    if tag >= len(_DTYPES):
        raise ValueError(f"unknown array dtype tag {tag}")
    nbytes = yield from _g_leb()
    payload = yield nbytes
    dtype = np.dtype(_DTYPES[tag])
    if nbytes % dtype.itemsize:
        raise ValueError("array byte length not a multiple of its itemsize")
    return np.frombuffer(payload, dtype=dtype)


def _g_bytes():
    arr = yield from _g_array()
    if arr.dtype != np.uint8:
        raise ValueError("expected a u1 byte frame")
    return arr.tobytes()


def _g_uuid(cls):
    raw = yield 16
    return cls(_uuid.UUID(bytes=bytes(raw)))


def _g_encryption():
    variant = (yield 1)[0]
    if variant >= len(_ENC_VARIANTS):
        raise ValueError(f"unknown encryption variant tag {variant}")
    data = yield from _g_bytes()
    return Encryption(_ENC_VARIANTS[variant], Binary(data))


def _g_header(want_tag):
    head = yield 6
    if bytes(head[:4]) != MAGIC:
        raise ValueError("not an x-sda-bin payload (bad magic)")
    if head[4] != VERSION:
        raise ValueError(f"unsupported x-sda-bin version {head[4]}")
    tag = head[5]
    if want_tag is not None and tag != want_tag:
        raise ValueError(f"unexpected resource tag {tag} (want {want_tag})")
    return tag


def _g_participation():
    pid = yield from _g_uuid(ParticipationId)
    participant = yield from _g_uuid(AgentId)
    aggregation = yield from _g_uuid(AggregationId)
    present = (yield 1)[0]
    if present not in (0, 1):
        raise ValueError("malformed option byte")
    recipient_encryption = None
    if present:
        recipient_encryption = yield from _g_encryption()
    count = yield from _g_leb()
    clerk_encryptions = []
    for _ in range(count):
        clerk_id = yield from _g_uuid(AgentId)
        enc = yield from _g_encryption()
        clerk_encryptions.append((clerk_id, enc))
    return Participation(
        id=pid, participant=participant, aggregation=aggregation,
        recipient_encryption=recipient_encryption,
        clerk_encryptions=clerk_encryptions,
    )


def _g_clerking_job():
    jid = yield from _g_uuid(ClerkingJobId)
    clerk = yield from _g_uuid(AgentId)
    aggregation = yield from _g_uuid(AggregationId)
    snapshot = yield from _g_uuid(SnapshotId)
    count = yield from _g_leb()
    encryptions = []
    for _ in range(count):
        enc = yield from _g_encryption()
        encryptions.append(enc)
    return ClerkingJob(id=jid, clerk=clerk, aggregation=aggregation,
                       snapshot=snapshot, encryptions=encryptions)


def _g_clerking_result():
    job = yield from _g_uuid(ClerkingJobId)
    clerk = yield from _g_uuid(AgentId)
    encryption = yield from _g_encryption()
    return ClerkingResult(job=job, clerk=clerk, encryption=encryption)


_G_PARSERS = {
    TAG_PARTICIPATION: _g_participation,
    TAG_CLERKING_JOB: _g_clerking_job,
    TAG_CLERKING_RESULT: _g_clerking_result,
}


def _g_resource(want_tag):
    tag = yield from _g_header(want_tag)
    parser = _G_PARSERS.get(tag)
    if parser is None:
        raise ValueError(f"unknown resource tag {tag}")
    result = yield from parser()
    return result


class FeedDecoder:
    """Incremental ``x-sda-bin`` decoder: ``feed()`` body chunks as they
    arrive, ``finish()`` once the body is done.

    Malformed input raises ``ValueError`` from the offending ``feed`` (or
    from ``finish`` for truncation/trailing bytes) — the same error
    contract as the one-shot decoders, so the HTTP layer's 400 mapping
    is unchanged. ``expect_tag`` pins the resource kind the route expects
    (a participation POST must not decode as a clerking result)."""

    __slots__ = ("_buf", "_gen", "_want", "_result", "_done", "fed_bytes")

    def __init__(self, expect_tag: Optional[int] = None):
        self._buf = bytearray()
        self._gen = _g_resource(expect_tag)
        self._want = self._gen.send(None)
        self._result = None
        self._done = False
        #: total body bytes consumed (request accounting/logging)
        self.fed_bytes = 0

    def feed(self, chunk: bytes) -> None:
        if not chunk:
            return
        self.fed_bytes += len(chunk)
        if self._done:
            raise ValueError("trailing bytes after x-sda-bin payload")
        self._buf += chunk
        while not self._done and len(self._buf) >= self._want:
            piece = bytes(self._buf[:self._want])
            del self._buf[:self._want]
            try:
                self._want = self._gen.send(piece)
            except StopIteration as stop:
                self._result = stop.value
                self._done = True
        if self._done and self._buf:
            raise ValueError("trailing bytes after x-sda-bin payload")

    @property
    def done(self) -> bool:
        return self._done

    def finish(self):
        """The decoded resource; raises if the stream ended early."""
        if not self._done:
            raise ValueError("truncated x-sda-bin payload")
        return self._result


def decode_stream(chunks, expect_tag: Optional[int] = None):
    """Decode an iterable of body chunks incrementally (the threaded HTTP
    plane's streaming read path); returns the resource."""
    decoder = FeedDecoder(expect_tag)
    for chunk in chunks:
        decoder.feed(chunk)
    return decoder.finish()
